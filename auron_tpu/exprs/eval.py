"""Expression evaluator: IR trees -> jnp columnar programs.

Evaluates ``exprs.ir`` trees over a ``Batch``, producing per-expression
``ColumnVal`` (values + validity + dtype + optional dictionary). Device math
is pure jnp; dictionary-encoded strings are handled by transforming the
*dictionary* host-side (small) and gathering by code on device — so string
equality/ordering/LIKE/casts stay on the TPU data path with only O(|dict|)
host work (analog of how the reference hashes/compares dictionary arrays,
spark_hash.rs:228-249).

Common subexpressions are evaluated once per batch via a structural memo —
the analog of the reference's CachedExprsEvaluator
(datafusion-ext-plans/src/common/cached_exprs_evaluator.rs). SQL
three-valued logic: AND/OR use Kleene semantics, arithmetic propagates
NULLs, division/modulo by zero produce NULL (Spark non-ANSI), decimal
overflow produces NULL via the checked kernels in decimal_math.py.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from auron_tpu import types as T
from auron_tpu.columnar.batch import Batch
from auron_tpu.exprs import cast as C
from auron_tpu.exprs import decimal_math as D
from auron_tpu.exprs import ir


@dataclass
class ColumnVal:
    values: jnp.ndarray
    validity: jnp.ndarray
    dtype: T.DataType
    dict: pa.Array | None = None  # set iff dtype.is_dict_encoded


class Evaluator:
    def __init__(
        self,
        schema: T.Schema,
        partition_id: int | None = None,
        row_offset: int = 0,
        resources: dict | None = None,
    ):
        self.schema = schema
        if partition_id is None or resources is None:
            # default to the executing task's context (exec/base.py) so
            # partition-context expressions work at every evaluation site
            from auron_tpu.exec.base import current_context

            # cross-thread callers (the sort-spill run path) pass
            # partition_id + resources explicitly, so this thread-local
            # fallback only ever runs on the operator's own pump thread
            ctx = current_context()  # auronlint: disable=R7 -- guarded fallback: spill-reachable callers (sort_exec._sort_run) thread ctx explicitly
            if partition_id is None:
                partition_id = ctx.partition_id if ctx is not None else 0
            if resources is None and ctx is not None:
                resources = ctx.resources
        self.partition_id = partition_id
        self.row_offset = row_offset  # live rows already emitted upstream
        self.resources = resources or {}

    # ---- public ----

    def evaluate(self, batch: Batch, exprs: list[ir.Expr]) -> list[ColumnVal]:
        memo: dict = {}
        return [self._eval(e, batch, memo) for e in exprs]

    # ---- core dispatch ----

    def _eval(self, e: ir.Expr, b: Batch, memo: dict) -> ColumnVal:
        key = e
        try:
            if key in memo:
                return memo[key]
        except TypeError:  # unhashable (shouldn't happen, all nodes frozen)
            key = None
        out = self._eval_uncached(e, b, memo)
        if key is not None:
            memo[key] = out
        return out

    def _eval_uncached(self, e: ir.Expr, b: Batch, memo: dict) -> ColumnVal:
        if isinstance(e, ir.Column):
            f = self.schema[e.index]
            return ColumnVal(
                b.col_values(e.index), b.col_validity(e.index), f.dtype, b.dicts[e.index]
            )
        if isinstance(e, ir.Literal):
            return self._literal(e, b.capacity)
        if isinstance(e, ir.Cast):
            return self._cast(self._eval(e.child, b, memo), e.to)
        if isinstance(e, ir.BinaryOp):
            return self._binary(e, b, memo)
        if isinstance(e, ir.Not):
            c = self._eval(e.child, b, memo)
            return ColumnVal(~c.values.astype(bool), c.validity, T.BOOL)
        if isinstance(e, ir.IsNull):
            c = self._eval(e.child, b, memo)
            return ColumnVal(~c.validity, jnp.ones_like(c.validity), T.BOOL)
        if isinstance(e, ir.IsNotNull):
            c = self._eval(e.child, b, memo)
            return ColumnVal(c.validity, jnp.ones_like(c.validity), T.BOOL)
        if isinstance(e, ir.If):
            return self._case([(e.cond, e.then)], e.orelse, b, memo)
        if isinstance(e, ir.Case):
            return self._case(list(e.branches), e.orelse, b, memo)
        if isinstance(e, ir.Coalesce):
            return self._coalesce([self._eval(a, b, memo) for a in e.args])
        if isinstance(e, ir.In):
            return self._in(e, b, memo)
        if isinstance(e, ir.Like):
            return self._like(e, b, memo)
        if isinstance(e, ir.ScalarFunc):
            from auron_tpu.functions import registry

            args = [self._eval(a, b, memo) for a in e.args]
            return registry.dispatch(e.name, args, b.capacity)
        if isinstance(e, ir.HostUDF):
            return self._host_udf(e, b, memo)
        if isinstance(e, ir.SparkPartitionId):
            return ColumnVal(
                jnp.full(b.capacity, jnp.int32(self.partition_id)),
                jnp.ones(b.capacity, bool), T.INT32,
            )
        if isinstance(e, ir.MonotonicId):
            pos = jnp.cumsum(b.device.sel.astype(jnp.int64)) - 1
            base = jnp.int64(self.partition_id) << jnp.int64(33)
            return ColumnVal(
                base + self.row_offset + jnp.maximum(pos, 0),
                jnp.ones(b.capacity, bool), T.INT64,
            )
        if isinstance(e, ir.RowNum):
            pos = jnp.cumsum(b.device.sel.astype(jnp.int64))
            return ColumnVal(
                self.row_offset + pos, jnp.ones(b.capacity, bool), T.INT64
            )
        if isinstance(e, ir.ScalarSubquery):
            if e.resource_id not in self.resources:
                raise KeyError(
                    f"scalar subquery value '{e.resource_id}' not in the task "
                    "resource map (host engine must ship it before the task runs)"
                )
            value = self.resources[e.resource_id]
            return self._literal(ir.Literal(value, e.dtype), b.capacity)
        raise TypeError(f"unsupported expression {type(e).__name__}")

    def _host_udf(self, e: ir.HostUDF, b: Batch, memo: dict) -> ColumnVal:
        """Materialize args to Arrow, call the bridge callback, re-ingest."""
        from auron_tpu.bridge.udf import lookup_udf
        from auron_tpu.columnar.batch import _arrow_to_device, host_arrow_cols

        args = [self._eval(a, b, memo) for a in e.args]
        cap = b.capacity
        # host UDF evaluates on host by contract; host_arrow_cols makes the
        # one batched transfer for all args
        host_args = host_arrow_cols(args)
        result = lookup_udf(e.name)(host_args, cap)
        assert len(result) == cap, "host UDF must return one value per slot"
        v, m, d = _arrow_to_device(result, e.out_dtype, cap)
        return ColumnVal(v, m, e.out_dtype, d)

    # ---- literals ----

    def _literal(self, e: ir.Literal, cap: int) -> ColumnVal:
        dt = e.dtype
        if e.value is None or dt.kind == T.TypeKind.NULL:
            phys = dt.physical_dtype() if dt.kind != T.TypeKind.NULL else jnp.int8
            return ColumnVal(
                jnp.zeros(cap, phys), jnp.zeros(cap, bool), dt,
                _single_dict(dt, None) if dt.is_dict_encoded else None,
            )
        if dt.is_dict_encoded:
            return ColumnVal(
                jnp.zeros(cap, jnp.int32), jnp.ones(cap, bool), dt,
                _single_dict(dt, e.value),
            )
        if dt.kind == T.TypeKind.DECIMAL:
            import decimal as pd

            u = int(pd.Decimal(str(e.value)).scaleb(dt.scale).quantize(pd.Decimal(1)))
            v = jnp.full(cap, jnp.int64(u))
        elif dt.kind == T.TypeKind.BOOL:
            v = jnp.full(cap, bool(e.value))
        else:
            v = jnp.full(cap, e.value, dtype=dt.physical_dtype())
        return ColumnVal(v, jnp.ones(cap, bool), dt)

    # ---- casts ----

    def _cast(self, c: ColumnVal, to: T.DataType) -> ColumnVal:
        if c.dtype == to:
            return c
        if c.dtype.is_dict_encoded and to.is_dict_encoded:
            return self._cast_dict_to_dict(c, to)
        if c.dtype.is_dict_encoded and not to.is_dict_encoded:
            if to.is_string_like:
                return ColumnVal(c.values, c.validity, to, c.dict)
            dvals, dok = C.cast_string_dict(c.dict, to)
            codes = jnp.clip(c.values, 0, len(dvals) - 1)
            vals = jnp.asarray(dvals)[codes]
            ok = jnp.asarray(dok)[codes]
            return ColumnVal(vals, c.validity & ok, to)
        if to.is_dict_encoded:
            return self._cast_plain_to_dict(c, to)
        v, m = C.cast_values(c.values, c.validity, c.dtype, to)
        return ColumnVal(v, m, to)

    def _cast_dict_to_dict(self, c: ColumnVal, to: T.DataType) -> ColumnVal:
        """dict-encoded -> dict-encoded: transform the dictionary host-side
        (it is small), keep the device codes."""
        if c.dtype.is_string_like and to.is_string_like:
            return ColumnVal(c.values, c.validity, to, c.dict)
        entries = c.dict.to_pylist()
        out, ok = [], np.ones(len(entries), dtype=bool)
        for i, v in enumerate(entries):
            r = C.cast_scalar(v, c.dtype, to) if v is not None else None
            if v is not None and r is None:
                ok[i] = False  # invalid entry -> NULL rows (non-ANSI)
            out.append(r)
        new_dict = pa.array(out, type=to.to_arrow())
        codes = jnp.clip(c.values, 0, max(len(entries) - 1, 0))
        okv = jnp.asarray(ok)[codes] if len(entries) else jnp.zeros_like(c.validity)
        return ColumnVal(c.values, c.validity & okv, to, new_dict)

    def _cast_plain_to_dict(self, c: ColumnVal, to: T.DataType) -> ColumnVal:
        """fixed-width -> string/binary/wide-decimal: the one cast that must
        BUILD a dictionary from data. One host sync; unique-codes the values
        so the dictionary stays |distinct|-sized."""
        vals = np.asarray(c.values)
        valid = np.asarray(c.validity)
        if vals.dtype.kind == "f":
            # dedup on the BIT pattern: np.unique would collapse -0.0 == 0.0
            # (they display differently) and merge NaN payloads
            bits = vals.view(np.int32 if vals.dtype == np.float32 else np.int64)
            uniq_bits, inv = np.unique(bits, return_inverse=True)
            uniq = uniq_bits.view(vals.dtype)
        else:
            uniq, inv = np.unique(vals, return_inverse=True)
        ents = [C.cast_scalar(u.item(), c.dtype, to) for u in uniq]
        new_dict = pa.array(ents, type=to.to_arrow())
        ok = np.array([e is not None for e in ents], dtype=bool)[inv]
        return ColumnVal(
            jnp.asarray(inv.astype(np.int32)),
            c.validity & jnp.asarray(ok & valid),
            to,
            new_dict,
        )

    # ---- binary ops ----

    def _binary(self, e: ir.BinaryOp, b: Batch, memo: dict) -> ColumnVal:
        l = self._eval(e.left, b, memo)
        r = self._eval(e.right, b, memo)
        op = e.op
        if op in ("and", "or"):
            return self._logic(op, l, r)
        if op in ir._CMP_OPS:
            return self._compare(op, l, r)
        return self._arith(op, l, r)

    def _logic(self, op: str, l: ColumnVal, r: ColumnVal) -> ColumnVal:
        lv = l.values.astype(bool)
        rv = r.values.astype(bool)
        if op == "and":
            known = (l.validity & ~lv) | (r.validity & ~rv)  # a known False
            value = jnp.where(known, False, lv & rv)
            valid = (l.validity & r.validity) | known
        else:
            known = (l.validity & lv) | (r.validity & rv)  # a known True
            value = jnp.where(known, True, lv | rv)
            valid = (l.validity & r.validity) | known
        return ColumnVal(value, valid, T.BOOL)

    def _compare(self, op: str, l: ColumnVal, r: ColumnVal) -> ColumnVal:
        if l.dtype.is_string_like or r.dtype.is_string_like:
            return self._compare_strings(op, l, r)
        valid = l.validity & r.validity
        if l.dtype.is_wide_decimal or r.dtype.is_wide_decimal:
            return self._compare_wide_decimal(op, l, r)
        if l.dtype.kind == T.TypeKind.DECIMAL or r.dtype.kind == T.TypeKind.DECIMAL:
            lv, rv, fallback = self._align_decimals(l, r)
            res = _cmp_apply(op, lv, rv)
            if fallback is not None:
                res = jnp.where(fallback[0], _cmp_apply(op, fallback[1], fallback[2]), res)
            return ColumnVal(res, valid, T.BOOL)
        common = ir.numeric_common_type(l.dtype, r.dtype) if l.dtype != r.dtype else l.dtype
        lc = self._cast(l, common)
        rc = self._cast(r, common)
        return ColumnVal(_cmp_apply(op, lc.values, rc.values), valid, T.BOOL)

    def _align_decimals(self, l: ColumnVal, r: ColumnVal):
        ld = l if l.dtype.kind == T.TypeKind.DECIMAL else self._cast(l, ir._as_decimal(l.dtype))
        rd = r if r.dtype.kind == T.TypeKind.DECIMAL else self._cast(r, ir._as_decimal(r.dtype))
        s = max(ld.dtype.scale, rd.dtype.scale)
        lv, lok = D.rescale(ld.values, ld.dtype.scale, s)
        rv, rok = D.rescale(rd.values, rd.dtype.scale, s)
        bad = ~(lok & rok)
        # if aligning overflowed int64 (enormous values), compare as float64
        lf = ld.values.astype(jnp.float64) * (10.0 ** (-ld.dtype.scale))
        rf = rd.values.astype(jnp.float64) * (10.0 ** (-rd.dtype.scale))
        return lv, rv, (bad, lf, rf)

    # 13-digit words: 5 of them cover any wide unscaled value after scale
    # alignment (<= 38 + 18 shift digits), each word int64-safe
    _DEC_WORD_BASE = 10**13
    _DEC_WORDS = 5

    def _compare_wide_decimal(self, op: str, l: ColumnVal, r: ColumnVal) -> ColumnVal:
        """Exact comparison when either operand is a wide (dict-encoded)
        decimal: both sides decompose into base-1e13 words of the unscaled
        value at the common scale (wide via host tables, narrow via exact
        device div/mod), compared lexicographically. Floats compare via a
        float64 view of the dictionary."""
        valid = l.validity & r.validity
        if l.dtype.is_float or r.dtype.is_float:
            lf = self._wide_as_float(l)
            rf = self._wide_as_float(r)
            return ColumnVal(_cmp_apply(op, lf, rf), valid, T.BOOL)
        ls = l.dtype.scale if l.dtype.kind == T.TypeKind.DECIMAL else 0
        rs = r.dtype.scale if r.dtype.kind == T.TypeKind.DECIMAL else 0
        s = max(ls, rs)
        # word count from the ACTUAL scale spread: 38 digits + up-shift
        # (decimal(38,0) vs decimal(38,38) aligns to 76 digits — a fixed
        # 5-word budget would overflow the top word, ADVICE r2 #3)
        need_digits = 38 + max(s - ls, s - rs)
        n_words = max(self._DEC_WORDS, -(-need_digits // 13) + 1)
        lw = self._decimal_words(l, s, n_words)
        rw = self._decimal_words(r, s, n_words)
        lt = jnp.zeros(l.values.shape, bool)
        eq = jnp.ones(l.values.shape, bool)
        for j in reversed(range(n_words)):  # big-endian compare
            lt = lt | (eq & (lw[j] < rw[j]))
            eq = eq & (lw[j] == rw[j])
        res = {
            "eq": eq, "neq": ~eq, "lt": lt, "lteq": lt | eq,
            "gt": ~lt & ~eq, "gteq": ~lt,
        }[op]
        return ColumnVal(res, valid, T.BOOL)

    def _wide_literal_arith(
        self, op: str, l: ColumnVal, r: ColumnVal
    ) -> ColumnVal | None:
        """Exact wide-decimal arithmetic when one operand is a broadcast
        constant (a one-entry dictionary or a scalar-valued narrow side):
        the op evaluates once per DICTIONARY ENTRY with python Decimals —
        the dictionary-transform pattern string functions use. Returns
        None when neither side is constant (column-pair arithmetic)."""
        import decimal as pydec

        def const_of(cv: ColumnVal):
            if cv.dtype.is_wide_decimal:
                if cv.dict is not None and len(cv.dict) == 1:
                    return cv.dict.to_pylist()[0]
                return None
            if cv.dtype.kind not in (
                T.TypeKind.DECIMAL, T.TypeKind.INT8, T.TypeKind.INT16,
                T.TypeKind.INT32, T.TypeKind.INT64,
            ):
                return None
            import jax

            # auronlint: disable=R9 -- constant probe memoized per plan node: re-evaluations hit the cached literal, not this read
            host = np.asarray(jax.device_get(cv.values))  # auronlint: sync-point(2/task) -- scalar-subquery constant probe, once per plan
            if host.size == 0 or not (host == host.flat[0]).all():
                return None
            v = int(host.flat[0])
            if cv.dtype.kind == T.TypeKind.DECIMAL:
                return T.decimal_from_unscaled(v, cv.dtype.scale)
            return pydec.Decimal(v)

        wide, other, wide_is_left = (
            (l, r, True) if l.dtype.is_wide_decimal else (r, l, False)
        )
        const = const_of(other)
        if const is None or wide.dict is None:
            return None
        out_t = ir.arith_result_type(op, l.dtype, r.dtype)
        assert out_t.kind == T.TypeKind.DECIMAL
        q = pydec.Decimal(1).scaleb(-out_t.scale)
        bound = pydec.Decimal(10) ** (out_t.precision - out_t.scale)
        new_entries: list = []
        ok_tab = np.zeros(max(len(wide.dict), 1), dtype=bool)
        with pydec.localcontext() as hp:
            hp.prec = 100
            for i, e in enumerate(wide.dict.to_pylist()):
                if e is None:
                    new_entries.append(pydec.Decimal(0))
                    continue
                a, b = (e, const) if wide_is_left else (const, e)
                v = _decimal_binop_exact(op, a, b, q, bound)
                if v is None:
                    new_entries.append(pydec.Decimal(0))
                    continue
                new_entries.append(v)
                ok_tab[i] = True
        return _materialize_decimal_entries(
            new_entries, ok_tab, wide.values, l.validity & r.validity, out_t
        )

    def _wide_pair_arith(self, op: str, l: ColumnVal, r: ColumnVal) -> ColumnVal:
        """Exact arithmetic over PAIRS of wide-decimal (or wide x narrow)
        COLUMNS — the last wide-decimal gap (VERDICT r2 #9).

        Wide values are dictionary codes, so the result is a function of the
        (left code, right value) pair: pull both columns once, np.unique the
        pairs, evaluate each distinct pair exactly with python Decimals, and
        regather by the pair index. One host sync + O(distinct pairs) exact
        ops — the documented host-exact path (a device limb multiply would
        still need a cross-limb HALF_UP rescale that has no exact int64
        formulation for div/mod)."""
        import decimal as pydec

        import jax

        def host_side(cv: ColumnVal):
            vals = np.asarray(jax.device_get(cv.values)).astype(np.int64)  # auronlint: sync-point(1/batch) -- documented host-exact decimal path (one sync, O(distinct pairs))
            if cv.dtype.is_wide_decimal:
                entries = cv.dict.to_pylist()
                vals = np.clip(vals, 0, max(len(entries) - 1, 0))
                return vals, lambda c: entries[int(c)]
            if cv.dtype.kind == T.TypeKind.DECIMAL:
                sc = cv.dtype.scale
                return vals, lambda v: T.decimal_from_unscaled(int(v), sc)
            return vals, lambda v: pydec.Decimal(int(v))

        lv, lfn = host_side(l)
        rv, rfn = host_side(r)
        pairs = np.stack([lv, rv], axis=1)
        uniq, inv = np.unique(pairs, axis=0, return_inverse=True)
        out_t = ir.arith_result_type(op, l.dtype, r.dtype)
        assert out_t.kind == T.TypeKind.DECIMAL
        q = pydec.Decimal(1).scaleb(-out_t.scale)
        bound = pydec.Decimal(10) ** (out_t.precision - out_t.scale)
        entries: list = []
        ok_tab = np.zeros(max(len(uniq), 1), dtype=bool)
        with pydec.localcontext() as hp:
            hp.prec = 100
            for i, (a_raw, b_raw) in enumerate(uniq):
                a = lfn(a_raw)
                b = rfn(b_raw)
                if a is None or b is None:
                    entries.append(pydec.Decimal(0))
                    continue
                v = _decimal_binop_exact(op, a, b, q, bound)
                if v is None:
                    entries.append(pydec.Decimal(0))
                    continue
                entries.append(v)
                ok_tab[i] = True
        return _materialize_decimal_entries(
            entries, ok_tab, jnp.asarray(inv.astype(np.int32)),
            l.validity & r.validity, out_t,
        )

    def _wide_as_float(self, cv: ColumnVal) -> jnp.ndarray:
        if not cv.dtype.is_wide_decimal:
            if cv.dtype.kind == T.TypeKind.DECIMAL:
                return cv.values.astype(jnp.float64) * (10.0 ** -cv.dtype.scale)
            return cv.values.astype(jnp.float64)
        tab = np.zeros(max(len(cv.dict), 1), dtype=np.float64)
        for i, e in enumerate(cv.dict.to_pylist()):
            if e is not None:
                tab[i] = float(e)
        return jnp.asarray(tab)[jnp.clip(cv.values, 0, len(tab) - 1)]

    def _decimal_words(
        self, cv: ColumnVal, s: int, n_words: int | None = None
    ) -> list[jnp.ndarray]:
        """Base-1e13 little-endian words of the unscaled value at scale s
        (floored decomposition: lower words in [0, 1e13), top word signed)."""
        W, BASE = n_words or self._DEC_WORDS, self._DEC_WORD_BASE
        if cv.dtype.is_wide_decimal:
            entries = cv.dict.to_pylist()
            n = max(len(entries), 1)
            tabs = np.zeros((W, n), dtype=np.int64)
            shift = 10 ** (s - cv.dtype.scale)
            for i, e in enumerate(entries):
                if e is None:
                    continue
                u = T.unscaled_int(e, cv.dtype.scale) * shift
                for j in range(W - 1):
                    u, rem = divmod(u, BASE)
                    tabs[j, i] = rem
                tabs[W - 1, i] = u
            idx = jnp.clip(cv.values, 0, n - 1)
            return [jnp.asarray(tabs[j])[idx] for j in range(W)]
        # narrow side: scaled int64 at its own scale, shifted up by
        # k = s - ns digits. word j = floor(v * 10^(k-13j)) mod 1e13,
        # computed without overflow via exact div/mod identities.
        # Integers enter directly at scale 0 (never cast: _as_decimal of
        # INT64 is decimal(20,0), itself wide)
        if cv.dtype.kind == T.TypeKind.DECIMAL:
            v = cv.values.astype(jnp.int64)
            ns = cv.dtype.scale
        else:
            v = cv.values.astype(jnp.int64)
            ns = 0
        k = s - ns
        words = []
        sign_lo = jnp.where(v < 0, jnp.int64(BASE - 1), jnp.int64(0))
        sign_top = jnp.where(v < 0, jnp.int64(-1), jnp.int64(0))
        for j in range(W):
            e = k - 13 * j
            if -e > 18:
                # shift beyond int64's 10^18 range: the word is pure
                # floored sign extension
                words.append(sign_top if j == W - 1 else sign_lo)
            elif j == W - 1:
                # top word carries the sign: pure floored division
                words.append(
                    jnp.floor_divide(v, jnp.int64(10 ** (-e)))
                    if e < 0 else v * jnp.int64(10**e)
                )
            elif e >= 13:
                words.append(jnp.zeros_like(v))
            elif e >= 0:
                words.append(jnp.mod(v, jnp.int64(10 ** (13 - e))) * jnp.int64(10**e))
            else:
                words.append(
                    jnp.mod(jnp.floor_divide(v, jnp.int64(10 ** (-e))), jnp.int64(BASE))
                )
        return words

    def _compare_strings(self, op: str, l: ColumnVal, r: ColumnVal) -> ColumnVal:
        assert l.dtype.is_string_like and r.dtype.is_string_like, (l.dtype, r.dtype)
        lmap, rmap, rank = _unify_two_dicts(l.dict, r.dict)
        lu = jnp.asarray(lmap)[jnp.clip(l.values, 0, len(lmap) - 1)]
        ru = jnp.asarray(rmap)[jnp.clip(r.values, 0, len(rmap) - 1)]
        valid = l.validity & r.validity
        if op in ("eq", "neq"):
            res = lu == ru if op == "eq" else lu != ru
            return ColumnVal(res, valid, T.BOOL)
        rk = jnp.asarray(rank)
        return ColumnVal(_cmp_apply(op, rk[lu], rk[ru]), valid, T.BOOL)

    def _arith(self, op: str, l: ColumnVal, r: ColumnVal) -> ColumnVal:
        if l.dtype.is_wide_decimal or r.dtype.is_wide_decimal:
            if l.dtype.is_float or r.dtype.is_float:
                # Spark: decimal (op) double computes in double
                lf = self._wide_as_float(l)
                rf = self._wide_as_float(r)
                valid = l.validity & r.validity
                fv, fok = _float_arith(op, lf, rf)
                return ColumnVal(fv, valid & fok, T.FLOAT64)
            out = self._wide_literal_arith(op, l, r)
            if out is not None:
                return out
            return self._wide_pair_arith(op, l, r)
        out = ir.arith_result_type(op, l.dtype, r.dtype)
        valid = l.validity & r.validity
        if out.kind == T.TypeKind.DECIMAL:
            ld = l if l.dtype.kind == T.TypeKind.DECIMAL else self._cast(l, ir._as_decimal(l.dtype))
            rd = r if r.dtype.kind == T.TypeKind.DECIMAL else self._cast(r, ir._as_decimal(r.dtype))
            fn = {"add": D.add, "sub": D.sub, "mul": D.mul, "div": D.div, "mod": D.mod}[op]
            v, ok = fn(
                ld.values, ld.dtype.scale, rd.values, rd.dtype.scale,
                out.precision, out.scale,
            )
            return ColumnVal(v, valid & ld.validity & rd.validity & ok, out)
        lc = self._cast(l, out)
        rc = self._cast(r, out)
        lv, rv = lc.values, rc.values
        if op == "add":
            v = lv + rv
        elif op == "sub":
            v = lv - rv
        elif op == "mul":
            v = lv * rv
        elif op == "div":
            zero = rv == 0
            if out.is_float:
                v = lv / jnp.where(zero, 1, rv)
            else:
                from jax import lax

                v = lax.div(lv, jnp.where(zero, 1, rv))
            valid = valid & ~zero
        elif op == "mod":
            from jax import lax

            zero = rv == 0
            safe = jnp.where(zero, 1, rv)
            if out.is_float:
                # Java % keeps the dividend's sign
                v = lv - jnp.trunc(lv / safe) * safe
            else:
                v = lax.rem(lv, safe)
            valid = valid & ~zero
        else:
            raise ValueError(op)
        return ColumnVal(v, valid, out)

    # ---- conditionals ----

    def _case(
        self, branches: list[tuple[ir.Expr, ir.Expr]], orelse: ir.Expr | None,
        b: Batch, memo: dict,
    ) -> ColumnVal:
        conds = [self._eval(c, b, memo) for c, _ in branches]
        vals = [self._eval(v, b, memo) for _, v in branches]
        if orelse is not None:
            els = self._eval(orelse, b, memo)
        else:
            els = _null_like(vals[0], b.capacity)
        vals = _unify_vals(vals + [els])
        els = vals[-1]
        vals = vals[:-1]
        # NULL condition counts as false; first true branch wins
        taken = jnp.zeros(b.capacity, bool)
        out_v = els.values
        out_m = els.validity
        for c, v in zip(conds, vals):
            fire = c.validity & c.values.astype(bool) & ~taken
            out_v = jnp.where(fire, v.values, out_v)
            out_m = jnp.where(fire, v.validity, out_m)
            taken = taken | fire
        return ColumnVal(out_v, out_m, vals[0].dtype, vals[0].dict)

    def _coalesce(self, args: list[ColumnVal]) -> ColumnVal:
        args = _unify_vals(args)
        out_v = args[0].values
        out_m = args[0].validity
        for a in args[1:]:
            take = ~out_m & a.validity
            out_v = jnp.where(take, a.values, out_v)
            out_m = out_m | a.validity
        return ColumnVal(out_v, out_m, args[0].dtype, args[0].dict)

    # ---- membership / pattern ----

    def _in(self, e: ir.In, b: Batch, memo: dict) -> ColumnVal:
        c = self._eval(e.child, b, memo)
        has_null_item = any(i is None for i in e.items)
        if c.dtype.is_string_like:
            entries = c.dict.to_pylist()
            member = np.array(
                [s in set(i for i in e.items if i is not None) for s in entries],
                dtype=bool,
            )
            hit = jnp.asarray(member)[jnp.clip(c.values, 0, len(member) - 1)]
        else:
            hit = jnp.zeros(b.capacity, bool)
            for item in e.items:
                if item is None:
                    continue
                lv = self._literal(ir.lit(item) if not isinstance(item, ir.Literal) else item, b.capacity)
                hit = hit | jnp.asarray(
                    self._compare("eq", c, lv).values
                )
        if e.negated:
            value = ~hit
        else:
            value = hit
        # Spark: x IN (...) is NULL if x is NULL, or no match and list has NULL
        valid = c.validity & ~(jnp.asarray(~hit) & has_null_item)
        return ColumnVal(value, valid, T.BOOL)

    def _like(self, e: ir.Like, b: Batch, memo: dict) -> ColumnVal:
        c = self._eval(e.child, b, memo)
        assert c.dtype.is_string_like, "LIKE requires a string input"
        rx = _like_to_regex(e.pattern, e.escape)
        entries = c.dict.to_pylist()
        match = np.array(
            [bool(rx.fullmatch(s)) if s is not None else False for s in entries],
            dtype=bool,
        )
        hit = jnp.asarray(match)[jnp.clip(c.values, 0, len(match) - 1)]
        return ColumnVal(~hit if e.negated else hit, c.validity, T.BOOL)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def eval_exprs(batch: Batch, exprs: list[ir.Expr]) -> list[ColumnVal]:
    return Evaluator(batch.schema).evaluate(batch, exprs)


def _materialize_decimal_entries(entries, ok_tab, codes, valid, out_t) -> ColumnVal:
    """Decimal entry table + per-entry ok mask + device codes -> ColumnVal:
    wide results keep codes against a fresh dictionary, narrow results
    gather scaled int64 values (the one place this encoding is defined)."""
    idx = jnp.clip(codes, 0, max(len(ok_tab) - 1, 0))
    valid = valid & jnp.asarray(ok_tab)[idx]
    if out_t.is_wide_decimal:
        return ColumnVal(codes, valid, out_t, pa.array(entries, type=out_t.to_arrow()))
    tab = np.zeros(max(len(entries), 1), dtype=np.int64)
    for i, v in enumerate(entries):
        tab[i] = T.unscaled_int(v, out_t.scale)
    return ColumnVal(jnp.asarray(tab)[idx], valid, out_t)


def _decimal_binop_exact(op: str, a, b, q, bound):
    """One exact Spark-decimal op on python Decimals: HALF_UP quantize to
    the result scale, overflow/zero-division -> None (non-ANSI NULL).
    Decimal % keeps the dividend's sign, matching Spark."""
    import decimal as pydec

    try:
        if op == "add":
            v = a + b
        elif op == "sub":
            v = a - b
        elif op == "mul":
            v = a * b
        elif op == "div":
            if b == 0:
                return None
            v = a / b
        elif op == "mod":
            if b == 0:
                return None
            v = a % b
        else:
            raise ValueError(op)
        v = v.quantize(q, rounding=pydec.ROUND_HALF_UP)
    except (pydec.InvalidOperation, ZeroDivisionError):
        return None
    if abs(v) >= bound:
        return None
    return v


def _float_arith(op: str, lf: jnp.ndarray, rf: jnp.ndarray):
    """float64 arithmetic with Spark semantics; returns (values, ok)."""
    ok = jnp.ones(lf.shape, bool)
    if op == "add":
        return lf + rf, ok
    if op == "sub":
        return lf - rf, ok
    if op == "mul":
        return lf * rf, ok
    zero = rf == 0
    safe = jnp.where(zero, 1.0, rf)
    if op == "div":
        return lf / safe, ok & ~zero
    if op == "mod":
        return lf - jnp.trunc(lf / safe) * safe, ok & ~zero
    raise ValueError(op)


def _cmp_apply(op: str, l: jnp.ndarray, r: jnp.ndarray) -> jnp.ndarray:
    if op == "eq":
        return l == r
    if op == "neq":
        return l != r
    if op == "lt":
        return l < r
    if op == "lteq":
        return l <= r
    if op == "gt":
        return l > r
    if op == "gteq":
        return l >= r
    raise ValueError(op)


#: literal dictionaries memoized by (kind, value): a string literal's
#: single-entry vocabulary must be the SAME pa.Array object every batch,
#: so the identity-keyed _unify_two_dicts memo hits on batch 2+ of a
#: column-vs-literal comparison (q43-class day-name CASE chains evaluate
#: 7 of these per batch) instead of re-unifying per batch. Bounded; one
#: lock (concurrent queries share literals, R8).
_SINGLE_DICT_MEMO: dict = {}
_SINGLE_DICT_LOCK = threading.Lock()


def _single_dict(dtype: T.DataType, value) -> pa.Array:
    key = (dtype.kind, dtype.to_arrow() if dtype.kind == T.TypeKind.DECIMAL
           else None, value)
    try:
        with _SINGLE_DICT_LOCK:
            arr = _SINGLE_DICT_MEMO.get(key)
    except TypeError:            # unhashable value: build uncached
        arr, key = None, None
    if arr is not None:
        return arr
    if dtype.kind == T.TypeKind.BINARY:
        arr = pa.array([value if value is not None else b""],
                       type=pa.binary())
    elif dtype.kind == T.TypeKind.DECIMAL:
        import decimal as pydec

        arr = pa.array(
            [value if value is not None else pydec.Decimal(0)],
            type=dtype.to_arrow(),
        )
    else:
        arr = pa.array([value if value is not None else ""],
                       type=pa.string())
    if key is not None:
        with _SINGLE_DICT_LOCK:
            if len(_SINGLE_DICT_MEMO) >= 512:
                _SINGLE_DICT_MEMO.pop(next(iter(_SINGLE_DICT_MEMO)))
            _SINGLE_DICT_MEMO[key] = arr
    return arr


def _null_like(proto: ColumnVal, cap: int) -> ColumnVal:
    return ColumnVal(
        jnp.zeros(cap, proto.values.dtype), jnp.zeros(cap, bool), proto.dtype, proto.dict
    )


def _unify_vals(vals: list[ColumnVal]) -> list[ColumnVal]:
    """Make CASE/COALESCE branch values physically mergeable (same dtype, and
    for strings, the same dictionary)."""
    if any(v.dtype.is_dict_encoded for v in vals):
        assert all(
            v.dtype.is_dict_encoded for v in vals
        ), "mixed dict-encoded / plain branches"
        first = vals[0].dtype
        if first.kind == T.TypeKind.DECIMAL:
            import decimal as pydec

            # Spark branch-type widening: max integer digits + max scale,
            # bounded at p38 with scale give-back (adjustPrecisionScale)
            s_max = max(v.dtype.scale for v in vals)
            i_max = max(v.dtype.precision - v.dtype.scale for v in vals)
            first = ir._bounded(i_max + s_max, s_max)
            _q = pydec.Decimal(1).scaleb(-first.scale)
            value_type, filler = first.to_arrow(), [pydec.Decimal(0)]
        elif first.kind == T.TypeKind.BINARY:
            value_type, filler = pa.binary(), [b""]
        else:
            value_type, filler = pa.string(), [""]
        is_dec = first.kind == T.TypeKind.DECIMAL
        vocab: dict = {}
        remaps = []
        for v in vals:
            pl = v.dict.to_pylist()
            r = np.empty(len(pl), dtype=np.int32)
            for i, s in enumerate(pl):
                if is_dec and s is not None:
                    import decimal as pydec

                    # cast-to-branch-type semantics: quantize HALF_UP at
                    # the widened target scale (exact when scale grew)
                    with pydec.localcontext() as _hp:
                        _hp.prec = 100
                        s = s.quantize(_q, rounding=pydec.ROUND_HALF_UP)
                r[i] = vocab.setdefault(s, len(vocab))
            remaps.append(r)
        unified = pa.array(list(vocab.keys()) or filler, type=value_type)
        out = []
        for v, r in zip(vals, remaps):
            codes = jnp.asarray(r)[jnp.clip(v.values, 0, len(r) - 1)]
            out.append(ColumnVal(codes, v.validity, first, unified))
        return out
    target = vals[0].dtype
    for v in vals[1:]:
        if v.dtype != target:
            target = ir.numeric_common_type(target, v.dtype)
    ev = Evaluator(T.Schema())  # casts don't need the schema
    return [ev._cast(v, target) for v in vals]


#: memo for _unify_two_dicts keyed by dictionary ARRAY IDENTITY: batch
#: dictionaries are immutable pa.Arrays reused across batches (and, under
#: the serving layer, across queries — uploaded table views are shared),
#: so the same (left, right) pair recurs for every batch of a string
#: comparison. Entries hold strong refs to both arrays, so an id() can
#: never alias a collected array; bounded LRU; one lock (concurrent
#: queries evaluate string comparisons from many threads, R8).
_UNIFY_MEMO: "dict[tuple[int, int], tuple]" = {}
_UNIFY_MEMO_LOCK = threading.Lock()
_UNIFY_MEMO_CAP = 1024  # pairs are per (batch dict, other dict); a large
# table contributes one dict object per uploaded batch, reused across
# queries — the cap bounds memory, not the working set


def _unify_two_dicts_py(ld: pa.Array, rd: pa.Array):
    """Python fallback (null-bearing vocabularies: arrow encode maps null
    to a null index, the engine's contract maps it to a vocab id)."""
    vocab: dict = {}
    maps = []
    for d in (ld, rd):
        pl = d.to_pylist()
        m = np.empty(len(pl), dtype=np.int32)
        for i, s in enumerate(pl):
            m[i] = vocab.setdefault(s, len(vocab))
        maps.append(m)
    keys = list(vocab.keys())
    order = np.argsort(np.array(keys, dtype=object), kind="stable")
    rank = np.empty(len(keys), dtype=np.int32)
    rank[order] = np.arange(len(keys), dtype=np.int32)
    return maps[0], maps[1], rank


def _unify_two_dicts(ld: pa.Array, rd: pa.Array):
    """Returns (lmap, rmap, rank): per-code unified ids and ordering ranks.

    Vectorized (arrow dictionary_encode over the concatenated vocabularies
    — first-occurrence ids, exactly the old setdefault semantics; UTF-8
    byte order equals code-point order, so the arrow sort ranks match the
    old python-object argsort) and memoized by array identity: the
    per-batch python vocab loop was a top GIL site under concurrent
    serving (models/servegate.py sampling)."""
    key = (id(ld), id(rd))
    with _UNIFY_MEMO_LOCK:
        ent = _UNIFY_MEMO.get(key)
        if ent is not None and ent[0] is ld and ent[1] is rd:
            return ent[2], ent[3], ent[4]
    if ld.null_count or rd.null_count:
        lmap, rmap, rank = _unify_two_dicts_py(ld, rd)
    else:
        import pyarrow.compute as pc

        typ = pa.large_string() if (
            pa.types.is_large_string(ld.type)
            or pa.types.is_large_string(rd.type)
        ) else ld.type
        both = pa.concat_arrays([ld.cast(typ), rd.cast(typ)])
        enc = both.dictionary_encode()
        codes = enc.indices.to_numpy(zero_copy_only=False).astype(np.int32)
        lmap, rmap = codes[: len(ld)], codes[len(ld):]
        order = pc.array_sort_indices(enc.dictionary).to_numpy(
            zero_copy_only=False)
        rank = np.empty(len(enc.dictionary), dtype=np.int32)
        rank[order] = np.arange(len(order), dtype=np.int32)
    with _UNIFY_MEMO_LOCK:
        if len(_UNIFY_MEMO) >= _UNIFY_MEMO_CAP:
            _UNIFY_MEMO.pop(next(iter(_UNIFY_MEMO)))
        _UNIFY_MEMO[key] = (ld, rd, lmap, rmap, rank)
    return lmap, rmap, rank


def _like_to_regex(pattern: str, escape: str) -> "re.Pattern":
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return re.compile("".join(out), re.DOTALL)

"""Spark-exact cast kernels (device-side subset).

The reference spends ~1 kLoC on Spark-exact casting
(datafusion-ext-commons/src/arrow/cast.rs); this is the TPU-native
equivalent, organized by (from_kind, to_kind). Implemented semantics
(Spark non-ANSI unless noted):

- int -> narrower int: two's-complement wrap (Java narrowing);
- float/double -> int types: NaN -> 0, out-of-range saturates (Java
  narrowing from double goes through the double->long/int saturation);
- numeric -> decimal and decimal -> numeric with HALF_UP rescale and
  overflow -> NULL;
- bool <-> numeric, date32 <-> timestamp-us;
- string -> numeric/bool/date: evaluated over the *dictionary* host-side
  (strings live as codes; the dictionary is small), then gathered by code —
  invalid strings become NULL like Spark's non-ANSI cast.

Long-tail semantics (cast.rs parity, VERDICT r2 #8):

- string -> date/timestamp uses Spark's LENIENT parser
  (`DateTimeUtils.stringToDate` / `stringToTimestamp`): partial dates
  ("2021", "2021-3"), 1-2 digit month/day/time segments, ' ' or 'T'
  separators, 1..9 fraction digits (truncated to micros), trailing zone ids
  (Z, +h[h][:mm[:ss]], +hhmm, UTC/GMT[+off], region ids via zoneinfo);
- X -> string follows Java formatting: Float/Double.toString shortest-digit
  with the 1e-3..1e7 plain/scientific switch, BigDecimal.toString notation
  rules, timestamp fraction trimming;
- nested casts: list/map/struct -> same shape with element-wise inner casts
  (Spark `canCast` element rules, invalid element -> NULL element when the
  target is nullable), nested -> string in Spark's `[..]` / `{k -> v}` /
  `{f1, f2}` display format. Nested values are dictionary-encoded, so these
  run host-side over the (small) dictionary and regather by code.

X -> string over non-dict columns is the one cast that must *build* a
dictionary from data; the evaluator does that with one host sync
(`eval.py:_cast`), using `format_scalar` here for per-value text.
"""

from __future__ import annotations

import datetime as _dt
import decimal as _pydec

import numpy as np
import jax.numpy as jnp
import pyarrow as pa

from auron_tpu import types as T
from auron_tpu.exprs import decimal_math as D

_INT_BOUNDS = {
    T.TypeKind.INT8: (-128, 127),
    T.TypeKind.INT16: (-(2**15), 2**15 - 1),
    T.TypeKind.INT32: (-(2**31), 2**31 - 1),
    T.TypeKind.INT64: (-(2**63), 2**63 - 1),
}


def cast_values(
    values: jnp.ndarray,
    validity: jnp.ndarray,
    src: T.DataType,
    dst: T.DataType,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Device cast; returns (values, validity). Strings handled separately."""
    if src == dst:
        return values, validity
    sk, dk = src.kind, dst.kind

    if sk == T.TypeKind.NULL:
        return jnp.zeros(values.shape, dst.physical_dtype()), jnp.zeros_like(validity)

    # bool source
    if sk == T.TypeKind.BOOL:
        iv = values.astype(jnp.int64)
        return cast_values(iv, validity, T.INT64, dst)

    # to bool
    if dk == T.TypeKind.BOOL:
        if src.kind == T.TypeKind.DECIMAL:
            return values != 0, validity
        return values != 0, validity

    # date/timestamp
    if sk == T.TypeKind.DATE32 and dk == T.TypeKind.TIMESTAMP:
        return values.astype(jnp.int64) * jnp.int64(86_400_000_000), validity
    if sk == T.TypeKind.TIMESTAMP and dk == T.TypeKind.DATE32:
        return jnp.floor_divide(values, jnp.int64(86_400_000_000)).astype(jnp.int32), validity
    if sk == T.TypeKind.DATE32 and dst.is_numeric:
        return cast_values(values.astype(jnp.int32), validity, T.INT32, dst)
    if sk == T.TypeKind.TIMESTAMP and dst.is_numeric:
        # Spark: timestamp -> long is seconds
        secs = jnp.floor_divide(values, jnp.int64(1_000_000))
        return cast_values(secs, validity, T.INT64, dst)
    if src.is_integer and dk == T.TypeKind.DATE32:
        return values.astype(jnp.int32), validity
    if src.is_integer and dk == T.TypeKind.TIMESTAMP:
        return values.astype(jnp.int64) * jnp.int64(1_000_000), validity

    # decimal source
    if sk == T.TypeKind.DECIMAL:
        if dk == T.TypeKind.DECIMAL:
            v, ok = D.rescale(values, src.scale, dst.scale)
            ok = ok & D.precision_ok(v, dst.precision)
            return v, validity & ok
        if dst.is_integer:
            # Spark decimal -> int truncates toward zero, out of range -> NULL
            from jax import lax

            p = jnp.int64(D.pow10(min(src.scale, 18)))
            trunc = lax.div(values, p) if src.scale > 0 else values
            lo, hi = _INT_BOUNDS[dk]
            ok = (trunc >= lo) & (trunc <= hi)
            return trunc.astype(dst.physical_dtype()), validity & ok
        if dst.is_float:
            f = values.astype(jnp.float64) * (10.0 ** (-src.scale))
            return f.astype(dst.physical_dtype()), validity

    # to decimal
    if dk == T.TypeKind.DECIMAL:
        if src.is_integer:
            v, ok = D.checked_mul_pow10(values.astype(jnp.int64), dst.scale)
            ok = ok & D.precision_ok(v, dst.precision)
            return v, validity & ok
        if src.is_float:
            scaled = values.astype(jnp.float64) * (10.0**dst.scale)
            rounded = jnp.where(scaled >= 0, jnp.floor(scaled + 0.5), jnp.ceil(scaled - 0.5))
            ok = jnp.isfinite(scaled) & (jnp.abs(rounded) < 2.0**63)
            v = rounded.astype(jnp.int64)
            ok = ok & D.precision_ok(v, dst.precision)
            return jnp.where(ok, v, 0), validity & ok

    # float -> int: NaN -> 0, saturate (Java double narrowing)
    if src.is_float and dst.is_integer:
        lo, hi = _INT_BOUNDS[dk]
        f = values.astype(jnp.float64)
        t = jnp.trunc(f)
        if dk == T.TypeKind.INT64:
            # largest double below 2^63 is 2^63 - 1024; everything >= 2^63
            # saturates to Long.MAX exactly like Java
            maxf = float(2**63 - 1024)
            iv = jnp.clip(t, -(2.0**63), maxf).astype(jnp.int64)
            iv = jnp.where(t >= 2.0**63, jnp.int64(hi), iv)
        else:
            iv = jnp.clip(t, float(lo), float(hi)).astype(jnp.int64)
        iv = jnp.where(jnp.isnan(f), jnp.int64(0), iv)
        return iv.astype(dst.physical_dtype()), validity

    # int -> int: wrap; int -> float
    if src.is_integer and dst.is_integer:
        return values.astype(dst.physical_dtype()), validity
    if src.is_integer and dst.is_float:
        return values.astype(dst.physical_dtype()), validity
    if src.is_float and dst.is_float:
        return values.astype(dst.physical_dtype()), validity

    raise TypeError(f"unsupported device cast {src} -> {dst}")


# ---------------------------------------------------------------------------
# string source: cast the dictionary host-side, gather by code
# ---------------------------------------------------------------------------


def cast_string_dict(d: pa.Array, dst: T.DataType) -> tuple[np.ndarray, np.ndarray]:
    """Cast dictionary entries to dst; returns (values np, ok np) per code.

    Spark trims whitespace for numeric casts and accepts e.g. "123", "1.5",
    scientific notation; invalid -> NULL (non-ANSI).
    """
    entries = d.to_pylist()
    n = len(entries)
    phys = np.dtype(dst.physical_dtype().name)
    vals = np.zeros(n, dtype=phys)
    ok = np.zeros(n, dtype=bool)
    for i, s in enumerate(entries):
        if s is None:
            continue
        t = s.strip() if isinstance(s, str) else s
        try:
            if dst.kind == T.TypeKind.BOOL:
                tl = t.lower()
                if tl in ("true", "t", "yes", "y", "1"):
                    vals[i], ok[i] = True, True
                elif tl in ("false", "f", "no", "n", "0"):
                    vals[i], ok[i] = False, True
            elif dst.is_integer:
                # Spark accepts fractional strings for int casts, truncating
                # toward zero ("1.5" -> 1), and range-checks to NULL
                import decimal as pd

                iv = int(pd.Decimal(t).to_integral_value(rounding=pd.ROUND_DOWN))
                lo, hi = _INT_BOUNDS[dst.kind]
                if lo <= iv <= hi:
                    vals[i], ok[i] = iv, True
            elif dst.is_float:
                vals[i], ok[i] = float(t), True
            elif dst.kind == T.TypeKind.DECIMAL:
                import decimal as pd

                with pd.localcontext() as _hp:
                    _hp.prec = 100  # scaleb rounds at context precision
                    u = int(pd.Decimal(t).scaleb(dst.scale).quantize(
                        pd.Decimal(1), rounding=pd.ROUND_HALF_UP))
                if -(2**63) <= u < 2**63 and (dst.precision >= 19 or abs(u) < 10**dst.precision):
                    vals[i], ok[i] = u, True
            elif dst.kind == T.TypeKind.DATE32:
                days = spark_string_to_date(t)
                if days is not None:
                    vals[i], ok[i] = days, True
            elif dst.kind == T.TypeKind.TIMESTAMP:
                us = spark_string_to_timestamp(t)
                if us is not None:
                    vals[i], ok[i] = us, True
            else:
                raise TypeError(f"cast string -> {dst}")
        except (ValueError, ArithmeticError, OverflowError):
            pass
    return vals, ok


# ---------------------------------------------------------------------------
# Spark's lenient string -> date/timestamp parser
# (reference: datafusion-ext-commons/src/spark_hash + cast.rs delegate to the
#  semantics of Spark DateTimeUtils.stringToDate / stringToTimestamp)
# ---------------------------------------------------------------------------

_EPOCH = _dt.date(1970, 1, 1)


def _seg_ok(pos: int, ndig: int) -> bool:
    """Digit-count rule: year takes 4..7 digits, every other segment 1..2."""
    return (4 <= ndig <= 7) if pos == 0 else (1 <= ndig <= 2)


def _is_leap(y: int) -> bool:
    return y % 4 == 0 and (y % 100 != 0 or y % 400 == 0)


_MONTH_DAYS = (31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31)


def _valid_ymd(y: int, m: int, d: int) -> bool:
    """Proleptic-Gregorian calendar check valid for ANY year (python's
    datetime.date caps at 9999 but Spark's LocalDate does not)."""
    if not 1 <= m <= 12 or d < 1:
        return False
    limit = _MONTH_DAYS[m - 1] + (1 if m == 2 and _is_leap(y) else 0)
    return d <= limit


def _days_from_civil(y: int, m: int, d: int) -> int:
    """Days since 1970-01-01 for a proleptic-Gregorian date, any year
    (Howard Hinnant's civil-days algorithm)."""
    y -= m <= 2
    era = (y if y >= 0 else y - 399) // 400
    yoe = y - era * 400
    doy = (153 * (m + (-3 if m > 2 else 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _civil_from_days(z: int) -> tuple[int, int, int]:
    """Inverse of _days_from_civil: days-since-epoch -> (y, m, d), any year
    (python's datetime.date caps at 9999; formatting must not crash on
    values the lenient parser deliberately accepts)."""
    z += 719468
    era = (z if z >= 0 else z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + (3 if mp < 10 else -9)
    return y + (1 if m <= 2 else 0), m, d


def _date_str(days: int) -> str:
    y, m, d = _civil_from_days(int(days))
    if y < 0:
        return f"-{-y:04d}-{m:02d}-{d:02d}"
    return f"{y:04d}-{m:02d}-{d:02d}"


def _parse_date_segments(s: str) -> tuple[int, int, int] | None:
    """Parse `[+-]yyyy[-[m]m[-[d]d]]`; returns (y, m, d) or None."""
    sign = 1
    if s and s[0] in "+-":
        sign = -1 if s[0] == "-" else 1
        s = s[1:]
    parts = s.split("-")
    if not 1 <= len(parts) <= 3:
        return None
    out = [1, 1, 1]  # missing month/day default to 1
    for i, p in enumerate(parts):
        if not p.isdigit() or not _seg_ok(i, len(p)):
            return None
        out[i] = int(p)
    y, m, d = out
    y *= sign
    if not _valid_ymd(y, m, d):
        return None
    return y, m, d


def spark_string_to_date(s: str) -> int | None:
    """Spark `stringToDate`: days since epoch, or None (-> NULL).

    Accepts yyyy / yyyy-[m]m / yyyy-[m]m-[d]d with anything after a ' ' or
    'T' following the day segment ignored.
    """
    t = s.strip()
    if not t:
        return None
    # chop at the FIRST ' ' or 'T' separator (searching 'T' globally would
    # trip on zone names like UTC/EST after a space-separated time)
    for i, ch in enumerate(t):
        if ch in "T " and i > 0:
            t = t[:i]
            break
    ymd = _parse_date_segments(t)
    if ymd is None:
        return None
    return _days_from_civil(*ymd)


_TZ_ALIASES = {"UTC": 0, "GMT": 0, "Z": 0, "UT": 0}


def _parse_zone_offset(z: str) -> int | None:
    """Zone id -> offset seconds, or None if unparseable.

    Handles Z, ±h[h], ±h[h]:mm, ±h[h]:mm:ss, ±hhmm, UTC/GMT[±...], and IANA
    region ids via zoneinfo (resolved at the parsed instant? Spark resolves
    at the instant; for fixed-offset zones this is identical — region zones
    fall back to their current rules via zoneinfo in _apply_region_zone).
    """
    z = z.strip()
    if z.upper() in _TZ_ALIASES:
        return 0
    if z and z[0] in "+-":
        sign = -1 if z[0] == "-" else 1
        body = z[1:]
        if ":" in body:
            parts = body.split(":")
            if not 2 <= len(parts) <= 3 or not all(p.isdigit() for p in parts):
                return None
            if len(parts[0]) > 2 or any(len(p) != 2 for p in parts[1:]):
                return None
            h, mnt = int(parts[0]), int(parts[1])
            sec = int(parts[2]) if len(parts) == 3 else 0
        elif body.isdigit():
            if len(body) <= 2:
                h, mnt, sec = int(body), 0, 0
            elif len(body) == 4:
                h, mnt, sec = int(body[:2]), int(body[2:]), 0
            elif len(body) == 6:
                h, mnt, sec = int(body[:2]), int(body[2:4]), int(body[4:])
            else:
                return None
        else:
            return None
        if h > 18 or mnt > 59 or sec > 59:
            return None
        return sign * (h * 3600 + mnt * 60 + sec)
    up = z.upper()
    for pref in ("UTC", "GMT", "UT"):
        if up.startswith(pref) and len(z) > len(pref):
            return _parse_zone_offset(z[len(pref):])
    return None


def _region_zone(z: str):
    try:
        from zoneinfo import ZoneInfo

        return ZoneInfo(z)
    except Exception:
        return None


def spark_string_to_timestamp(s: str, default_date: _dt.date | None = None) -> int | None:
    """Spark `stringToTimestamp`: microseconds since epoch UTC, or None.

    Grammar: `[+-]yyyy[-[m]m[-[d]d]][[T ][h]h[:[m]m[:[s]s[.f{1,9}]]][zone]]`
    plus a bare-time form `[h]h:[m]m:...` that borrows `default_date`
    (session "today"; defaults to the current UTC date like Spark's session
    time zone default).
    """
    t = s.strip()
    if not t:
        return None

    # split date / time.  A bare time form starts with a segment containing
    # ':' before any '-' that could begin a date (careful: '-' also signs
    # the year and appears in zone offsets).
    date_part, time_part = t, ""
    for i, ch in enumerate(t):
        if ch == "T" and i == 0:
            # Spark's bare-time form with explicit separator ("T12:34:56"):
            # empty date part, everything after the T is time. A bare "T"
            # or "T<zone>" has no time body and stays invalid.
            if len(t) > 1 and t[1].isdigit():
                date_part, time_part = "", t[1:]
            break
        if ch in "T " and i > 0:
            date_part, time_part = t[:i], t[i + 1 :]
            break
        if ch == ":":  # bare time, no date segment
            date_part, time_part = "", t
            break

    if date_part:
        ymd = _parse_date_segments(date_part)
        if ymd is None:
            return None
        y, m, d = ymd
    else:
        today = default_date or _dt.datetime.now(_dt.timezone.utc).date()
        y, m, d = today.year, today.month, today.day

    hour = minute = sec = micros = 0
    tz_off_sec: int | None = 0
    region = None
    if time_part:
        # peel the zone id: first char after the time body that is not a
        # digit, ':' or '.' starts the zone (also a '+'/'-' always does)
        body, zone = time_part, ""
        for i, ch in enumerate(time_part):
            if ch in "+-":
                body, zone = time_part[:i], time_part[i:]
                break
            if not (ch.isdigit() or ch in ":."):
                body, zone = time_part[:i], time_part[i:].strip()
                break
        body = body.strip()
        if body:
            frac = ""
            if "." in body:
                body, _, frac = body.partition(".")
                if not (frac.isdigit() and 1 <= len(frac) <= 9):
                    return None
            segs = body.split(":")
            if not 1 <= len(segs) <= 3:
                return None
            for i, p in enumerate(segs):
                if not p.isdigit() or not 1 <= len(p) <= 2:
                    return None
            hour = int(segs[0])
            minute = int(segs[1]) if len(segs) > 1 else 0
            sec = int(segs[2]) if len(segs) > 2 else 0
            if frac and len(segs) < 3:
                return None  # fraction requires seconds
            micros = int(frac[:6].ljust(6, "0")) if frac else 0
            if hour > 23 or minute > 59 or sec > 59:
                return None
        if zone:
            tz_off_sec = _parse_zone_offset(zone)
            if tz_off_sec is None:
                region = _region_zone(zone)
                if region is None:
                    return None

    if region is not None:
        try:
            naive = _dt.datetime(y, m, d, hour, minute, sec)
        except ValueError:
            return None  # region-zone resolution needs a python datetime
        epoch_s = naive.replace(tzinfo=region).timestamp()
        return int(round(epoch_s)) * 1_000_000 + micros
    # fixed offsets: pure integer arithmetic, valid for any proleptic year
    epoch_s = (
        _days_from_civil(y, m, d) * 86400
        + hour * 3600
        + minute * 60
        + sec
        - (tz_off_sec or 0)
    )
    return epoch_s * 1_000_000 + micros


# ---------------------------------------------------------------------------
# X -> string: Java/Spark display formatting
# ---------------------------------------------------------------------------


def _java_fp_str(x: float, single: bool) -> str:
    """Java Float/Double.toString: shortest round-trip digits, plain decimal
    in [1e-3, 1e7), otherwise `d.dddE±x` scientific (no '+' on exponents)."""
    if np.isnan(x):
        return "NaN"
    if np.isinf(x):
        return "Infinity" if x > 0 else "-Infinity"
    if x == 0.0:
        return "-0.0" if np.signbit(x) else "0.0"
    neg = x < 0
    m = -x if neg else x
    # shortest round-trip digits for the width (numpy dragon4, unique=True)
    s = np.format_float_scientific(np.float32(m) if single else np.float64(m), trim="-")
    mant, _, e = s.partition("e")
    exp = int(e)
    digits = mant.replace(".", "").rstrip("0") or "0"
    out: str
    if -3 <= exp < 7:
        if exp >= 0:
            ip = digits[: exp + 1].ljust(exp + 1, "0")
            fp = digits[exp + 1 :] or "0"
            out = f"{ip}.{fp}"
        else:
            out = "0." + "0" * (-exp - 1) + digits
    else:
        fp = digits[1:] or "0"
        out = f"{digits[0]}.{fp}E{exp}"
    return ("-" + out) if neg else out


def _java_bigdecimal_str(unscaled: int, scale: int) -> str:
    """Java BigDecimal.toString: plain notation unless scale < 0 or the
    adjusted exponent < -6, then scientific."""
    neg = unscaled < 0
    digs = str(-unscaled if neg else unscaled)
    adjusted = (len(digs) - 1) - scale
    if scale >= 0 and adjusted >= -6:
        if scale == 0:
            out = digs
        elif len(digs) > scale:
            out = f"{digs[:-scale]}.{digs[-scale:]}"
        else:
            out = "0." + digs.rjust(scale, "0")
    else:
        if len(digs) == 1:
            out = f"{digs}E{'+' if adjusted > 0 else ''}{adjusted}"
        else:
            out = f"{digs[0]}.{digs[1:]}E{'+' if adjusted > 0 else ''}{adjusted}"
    return ("-" + out) if neg else out


def _timestamp_str(us: int) -> str:
    """Spark timestampToString: 'yyyy-MM-dd HH:mm:ss[.f]' with the fraction's
    trailing zeros trimmed and no trailing dot."""
    sec, frac = divmod(int(us), 1_000_000)  # divmod floors: frac >= 0
    days, sod = divmod(sec, 86400)
    h, rem = divmod(sod, 3600)
    mi, s = divmod(rem, 60)
    base = f"{_date_str(days)} {h:02d}:{mi:02d}:{s:02d}"
    if frac:
        base += ("." + f"{frac:06d}").rstrip("0")
    return base


def _to_physical(v, dtype: T.DataType):
    """Normalize a host-object scalar (what pa.Array.to_pylist yields inside
    nested dictionary entries: datetime.date/datetime, Decimal) to this
    engine's physical scalar (int days / int micros / unscaled int)."""
    k = dtype.kind
    if k == T.TypeKind.DATE32 and isinstance(v, _dt.date) and not isinstance(v, _dt.datetime):
        return (v - _EPOCH).days
    if k == T.TypeKind.TIMESTAMP and isinstance(v, _dt.datetime):
        if v.tzinfo is not None:
            v = v.astimezone(_dt.timezone.utc).replace(tzinfo=None)
        days = _days_from_civil(v.year, v.month, v.day)
        return (days * 86400 + v.hour * 3600 + v.minute * 60 + v.second) * 1_000_000 + v.microsecond
    if (
        k == T.TypeKind.DECIMAL
        and not dtype.is_wide_decimal
        and isinstance(v, _pydec.Decimal)
    ):
        return T.unscaled_int(v, dtype.scale)
    return v


def _from_physical(v, dtype: T.DataType):
    """Physical scalar -> arrow-compatible value for pa.array embedding.
    Decimals must become Decimal objects (pa would read a raw int as the
    WHOLE value, not the unscaled integer); date32/timestamp stay as raw
    ints — pa.array accepts them directly, and this sidesteps python
    datetime's year 1..9999 cap for values the lenient parser accepts."""
    if v is None:
        return None
    if dtype.kind == T.TypeKind.DECIMAL and isinstance(v, (int, np.integer)):
        return T.decimal_from_unscaled(int(v), dtype.scale)
    return v


def format_scalar(v, dtype: T.DataType) -> str | None:
    """Spark CAST(x AS STRING) display text for one non-NULL python scalar."""
    if v is None:
        return None
    v = _to_physical(v, dtype)
    k = dtype.kind
    if k == T.TypeKind.BOOL:
        return "true" if v else "false"
    if dtype.is_integer:
        return str(int(v))
    if k == T.TypeKind.FLOAT32:
        return _java_fp_str(float(v), single=True)
    if k == T.TypeKind.FLOAT64:
        return _java_fp_str(float(v), single=False)
    if k == T.TypeKind.DECIMAL:
        if isinstance(v, _pydec.Decimal):  # wide decimal: dictionary value
            return _java_bigdecimal_str(T.unscaled_int(v, dtype.scale), dtype.scale)
        return _java_bigdecimal_str(int(v), dtype.scale)
    if k == T.TypeKind.DATE32:
        return _date_str(int(v))
    if k == T.TypeKind.TIMESTAMP:
        return _timestamp_str(int(v))
    if k in (T.TypeKind.STRING, T.TypeKind.BINARY):
        return v if isinstance(v, str) else bytes(v).decode("utf-8", "replace")
    if k == T.TypeKind.LIST:
        el = dtype.inner[0]
        items = ["null" if e is None else format_scalar(e, el) for e in v]
        return "[" + ", ".join(items) + "]"
    if k == T.TypeKind.MAP:
        kt, vt = dtype.inner
        pairs = v.items() if isinstance(v, dict) else v
        parts = [
            f"{'null' if a is None else format_scalar(a, kt)} ->"
            f" {'null' if b is None else format_scalar(b, vt)}"
            for a, b in pairs
        ]
        return "{" + ", ".join(parts) + "}"
    if k == T.TypeKind.STRUCT:
        vals = [v.get(n) for n in dtype.struct_names] if isinstance(v, dict) else list(v)
        parts = [
            "null" if e is None else format_scalar(e, t)
            for e, t in zip(vals, dtype.inner)
        ]
        return "{" + ", ".join(parts) + "}"
    raise TypeError(f"format_scalar: {dtype}")


# ---------------------------------------------------------------------------
# host-side scalar cast (nested dictionaries); mirrors device semantics
# ---------------------------------------------------------------------------


def cast_scalar(v, src: T.DataType, dst: T.DataType):
    """Spark-cast one python scalar; returns the converted value or None
    (invalid -> NULL, matching the non-ANSI device kernels)."""
    if v is None or src.kind == T.TypeKind.NULL:
        return None
    if src == dst:
        return v
    v = _to_physical(v, src)
    sk, dk = src.kind, dst.kind
    if dk == T.TypeKind.BINARY:
        # Spark: only string and integral sources; int -> big-endian bytes
        if sk == T.TypeKind.STRING:
            return v.encode() if isinstance(v, str) else bytes(v)
        if src.is_integer:
            width = {
                T.TypeKind.INT8: 1,
                T.TypeKind.INT16: 2,
                T.TypeKind.INT32: 4,
                T.TypeKind.INT64: 8,
            }[sk]
            return int(v).to_bytes(width, "big", signed=True)
        return None
    if dk == T.TypeKind.STRING:
        return format_scalar(v, src)

    # nested -> nested (same shape)
    if sk == T.TypeKind.LIST and dk == T.TypeKind.LIST:
        return [cast_scalar(e, src.inner[0], dst.inner[0]) for e in v]
    if sk == T.TypeKind.MAP and dk == T.TypeKind.MAP:
        pairs = v.items() if isinstance(v, dict) else v
        out = []
        for a, b in pairs:
            ck = cast_scalar(a, src.inner[0], dst.inner[0])
            if ck is None:
                return None  # map keys cannot be NULL
            out.append((ck, cast_scalar(b, src.inner[1], dst.inner[1])))
        return out
    if sk == T.TypeKind.STRUCT and dk == T.TypeKind.STRUCT:
        vals = [v.get(n) for n in src.struct_names] if isinstance(v, dict) else list(v)
        if len(vals) != len(dst.inner):
            return None
        return {
            n: cast_scalar(e, st, dt_)
            for n, e, st, dt_ in zip(dst.struct_names, vals, src.inner, dst.inner)
        }

    # primitive mirrors: run the string/dict kernels on a 1-element batch
    if sk in (T.TypeKind.STRING, T.TypeKind.BINARY):
        s = v if isinstance(v, str) else v.decode("utf-8", "replace")
        if dst.is_wide_decimal:
            # parse exactly (the dict kernel's int64 bound doesn't apply)
            try:
                with _pydec.localcontext() as hp:
                    hp.prec = 100
                    u = int(
                        _pydec.Decimal(s.strip())
                        .scaleb(dst.scale)
                        .quantize(_pydec.Decimal(1), rounding=_pydec.ROUND_HALF_UP)
                    )
            except (ValueError, ArithmeticError):
                return None
            if not _fits_precision(u, dst.precision):
                return None
            return T.decimal_from_unscaled(u, dst.scale)
        vals, ok = cast_string_dict(pa.array([s]), dst)
        if not ok[0]:
            return None
        out = vals[0]
        return _from_physical(out.item() if hasattr(out, "item") else out, dst)
    # numeric/date/bool scalars: reuse the device kernel on a length-1 array
    if src.is_wide_decimal:
        u = T.unscaled_int(v, src.scale) if isinstance(v, _pydec.Decimal) else int(v)
        if dst.kind == T.TypeKind.DECIMAL:
            scaled = _rescale_int(u, src.scale, dst.scale)
            if scaled is None or not _fits_precision(scaled, dst.precision):
                return None
            if dst.is_wide_decimal:
                return T.decimal_from_unscaled(scaled, dst.scale)
            if not -(2**63) <= scaled < 2**63:
                return None
            return T.decimal_from_unscaled(scaled, dst.scale)
        if dst.is_integer:
            q = u // (10**src.scale) if src.scale else u
            if u < 0 and src.scale and u % (10**src.scale):
                q += 1  # truncate toward zero
            lo, hi = _INT_BOUNDS[dk]
            return q if lo <= q <= hi else None
        if dst.is_float:
            return float(T.decimal_from_unscaled(u, src.scale))
        if dk == T.TypeKind.BOOL:
            return u != 0
        return None
    if dst.is_wide_decimal:
        # compute the unscaled target integer EXACTLY per source kind (a
        # decimal64 funnel would cap magnitude at precision 18 and lose the
        # scaled/unscaled distinction)
        if src.kind == T.TypeKind.BOOL:
            u = (1 if v else 0) * 10**dst.scale
        elif src.is_integer:
            u = int(v) * 10**dst.scale
        elif src.is_float:
            try:
                with _pydec.localcontext() as hp:
                    hp.prec = 60
                    u = int(
                        _pydec.Decimal(repr(float(v)))
                        .scaleb(dst.scale)
                        .quantize(_pydec.Decimal(1), rounding=_pydec.ROUND_HALF_UP)
                    )
            except (ValueError, ArithmeticError):
                return None  # NaN / Infinity
        elif src.kind == T.TypeKind.DECIMAL:  # narrow: v is the unscaled int
            u = _rescale_int(int(v), src.scale, dst.scale)
        elif src.kind == T.TypeKind.TIMESTAMP:  # Spark: seconds
            u = (int(v) // 1_000_000) * 10**dst.scale
        else:
            return None
        if u is None or not _fits_precision(u, dst.precision):
            return None
        return T.decimal_from_unscaled(u, dst.scale)
    va = jnp.asarray(np.array([v], dtype=np.dtype(src.physical_dtype().name)))
    out_v, out_ok = cast_values(va, jnp.ones(1, bool), src, dst)
    if not bool(out_ok[0]):
        return None
    o = np.asarray(out_v)[0]
    return bool(o) if dk == T.TypeKind.BOOL else _from_physical(o.item(), dst)


def _rescale_int(u: int, s_from: int, s_to: int) -> int | None:
    if s_to >= s_from:
        return u * 10 ** (s_to - s_from)
    q, r = divmod(abs(u), 10 ** (s_from - s_to))
    if 2 * r >= 10 ** (s_from - s_to):
        q += 1  # HALF_UP
    return -q if u < 0 else q


def _fits_precision(u: int, precision: int) -> bool:
    return abs(u) < 10**precision


def can_cast(src: T.DataType, dst: T.DataType) -> bool:
    """Static Spark `Cast.canCast` subset for the types this engine carries."""
    if src == dst or src.kind == T.TypeKind.NULL:
        return True
    if dst.kind == T.TypeKind.STRING:
        return True
    if dst.kind == T.TypeKind.BINARY:
        # Spark Cast.canCast: only string and integral sources
        return src.is_string_like or src.is_integer
    sk, dk = src.kind, dst.kind
    if sk == T.TypeKind.LIST and dk == T.TypeKind.LIST:
        return can_cast(src.inner[0], dst.inner[0])
    if sk == T.TypeKind.MAP and dk == T.TypeKind.MAP:
        return can_cast(src.inner[0], dst.inner[0]) and can_cast(src.inner[1], dst.inner[1])
    if sk == T.TypeKind.STRUCT and dk == T.TypeKind.STRUCT:
        return len(src.inner) == len(dst.inner) and all(
            can_cast(a, b) for a, b in zip(src.inner, dst.inner)
        )
    if sk in (T.TypeKind.LIST, T.TypeKind.MAP, T.TypeKind.STRUCT) or dk in (
        T.TypeKind.LIST,
        T.TypeKind.MAP,
        T.TypeKind.STRUCT,
    ):
        return False
    return True  # primitive lattice: everything else is castable in Spark

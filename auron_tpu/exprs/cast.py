"""Spark-exact cast kernels (device-side subset).

The reference spends ~1 kLoC on Spark-exact casting
(datafusion-ext-commons/src/arrow/cast.rs); this is the TPU-native
equivalent, organized by (from_kind, to_kind). Implemented semantics
(Spark non-ANSI unless noted):

- int -> narrower int: two's-complement wrap (Java narrowing);
- float/double -> int types: NaN -> 0, out-of-range saturates (Java
  narrowing from double goes through the double->long/int saturation);
- numeric -> decimal and decimal -> numeric with HALF_UP rescale and
  overflow -> NULL;
- bool <-> numeric, date32 <-> timestamp-us;
- string -> numeric/bool/date: evaluated over the *dictionary* host-side
  (strings live as codes; the dictionary is small), then gathered by code —
  invalid strings become NULL like Spark's non-ANSI cast.

numeric -> string requires building a dictionary from data (host sync) and
is handled by the evaluator's host-fallback path, not here.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pyarrow as pa

from auron_tpu import types as T
from auron_tpu.exprs import decimal_math as D

_INT_BOUNDS = {
    T.TypeKind.INT8: (-128, 127),
    T.TypeKind.INT16: (-(2**15), 2**15 - 1),
    T.TypeKind.INT32: (-(2**31), 2**31 - 1),
    T.TypeKind.INT64: (-(2**63), 2**63 - 1),
}


def cast_values(
    values: jnp.ndarray,
    validity: jnp.ndarray,
    src: T.DataType,
    dst: T.DataType,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Device cast; returns (values, validity). Strings handled separately."""
    if src == dst:
        return values, validity
    sk, dk = src.kind, dst.kind

    if sk == T.TypeKind.NULL:
        return jnp.zeros(values.shape, dst.physical_dtype()), jnp.zeros_like(validity)

    # bool source
    if sk == T.TypeKind.BOOL:
        iv = values.astype(jnp.int64)
        return cast_values(iv, validity, T.INT64, dst)

    # to bool
    if dk == T.TypeKind.BOOL:
        if src.kind == T.TypeKind.DECIMAL:
            return values != 0, validity
        return values != 0, validity

    # date/timestamp
    if sk == T.TypeKind.DATE32 and dk == T.TypeKind.TIMESTAMP:
        return values.astype(jnp.int64) * jnp.int64(86_400_000_000), validity
    if sk == T.TypeKind.TIMESTAMP and dk == T.TypeKind.DATE32:
        return jnp.floor_divide(values, jnp.int64(86_400_000_000)).astype(jnp.int32), validity
    if sk == T.TypeKind.DATE32 and dst.is_numeric:
        return cast_values(values.astype(jnp.int32), validity, T.INT32, dst)
    if sk == T.TypeKind.TIMESTAMP and dst.is_numeric:
        # Spark: timestamp -> long is seconds
        secs = jnp.floor_divide(values, jnp.int64(1_000_000))
        return cast_values(secs, validity, T.INT64, dst)
    if src.is_integer and dk == T.TypeKind.DATE32:
        return values.astype(jnp.int32), validity
    if src.is_integer and dk == T.TypeKind.TIMESTAMP:
        return values.astype(jnp.int64) * jnp.int64(1_000_000), validity

    # decimal source
    if sk == T.TypeKind.DECIMAL:
        if dk == T.TypeKind.DECIMAL:
            v, ok = D.rescale(values, src.scale, dst.scale)
            ok = ok & D.precision_ok(v, dst.precision)
            return v, validity & ok
        if dst.is_integer:
            # Spark decimal -> int truncates toward zero, out of range -> NULL
            from jax import lax

            p = jnp.int64(D.pow10(min(src.scale, 18)))
            trunc = lax.div(values, p) if src.scale > 0 else values
            lo, hi = _INT_BOUNDS[dk]
            ok = (trunc >= lo) & (trunc <= hi)
            return trunc.astype(dst.physical_dtype()), validity & ok
        if dst.is_float:
            f = values.astype(jnp.float64) * (10.0 ** (-src.scale))
            return f.astype(dst.physical_dtype()), validity

    # to decimal
    if dk == T.TypeKind.DECIMAL:
        if src.is_integer:
            v, ok = D.checked_mul_pow10(values.astype(jnp.int64), dst.scale)
            ok = ok & D.precision_ok(v, dst.precision)
            return v, validity & ok
        if src.is_float:
            scaled = values.astype(jnp.float64) * (10.0**dst.scale)
            rounded = jnp.where(scaled >= 0, jnp.floor(scaled + 0.5), jnp.ceil(scaled - 0.5))
            ok = jnp.isfinite(scaled) & (jnp.abs(rounded) < 2.0**63)
            v = rounded.astype(jnp.int64)
            ok = ok & D.precision_ok(v, dst.precision)
            return jnp.where(ok, v, 0), validity & ok

    # float -> int: NaN -> 0, saturate (Java double narrowing)
    if src.is_float and dst.is_integer:
        lo, hi = _INT_BOUNDS[dk]
        f = values.astype(jnp.float64)
        t = jnp.trunc(f)
        if dk == T.TypeKind.INT64:
            # largest double below 2^63 is 2^63 - 1024; everything >= 2^63
            # saturates to Long.MAX exactly like Java
            maxf = float(2**63 - 1024)
            iv = jnp.clip(t, -(2.0**63), maxf).astype(jnp.int64)
            iv = jnp.where(t >= 2.0**63, jnp.int64(hi), iv)
        else:
            iv = jnp.clip(t, float(lo), float(hi)).astype(jnp.int64)
        iv = jnp.where(jnp.isnan(f), jnp.int64(0), iv)
        return iv.astype(dst.physical_dtype()), validity

    # int -> int: wrap; int -> float
    if src.is_integer and dst.is_integer:
        return values.astype(dst.physical_dtype()), validity
    if src.is_integer and dst.is_float:
        return values.astype(dst.physical_dtype()), validity
    if src.is_float and dst.is_float:
        return values.astype(dst.physical_dtype()), validity

    raise TypeError(f"unsupported device cast {src} -> {dst}")


# ---------------------------------------------------------------------------
# string source: cast the dictionary host-side, gather by code
# ---------------------------------------------------------------------------


def cast_string_dict(d: pa.Array, dst: T.DataType) -> tuple[np.ndarray, np.ndarray]:
    """Cast dictionary entries to dst; returns (values np, ok np) per code.

    Spark trims whitespace for numeric casts and accepts e.g. "123", "1.5",
    scientific notation; invalid -> NULL (non-ANSI).
    """
    entries = d.to_pylist()
    n = len(entries)
    phys = np.dtype(dst.physical_dtype().name)
    vals = np.zeros(n, dtype=phys)
    ok = np.zeros(n, dtype=bool)
    for i, s in enumerate(entries):
        if s is None:
            continue
        t = s.strip() if isinstance(s, str) else s
        try:
            if dst.kind == T.TypeKind.BOOL:
                tl = t.lower()
                if tl in ("true", "t", "yes", "y", "1"):
                    vals[i], ok[i] = True, True
                elif tl in ("false", "f", "no", "n", "0"):
                    vals[i], ok[i] = False, True
            elif dst.is_integer:
                # Spark accepts fractional strings for int casts, truncating
                # toward zero ("1.5" -> 1), and range-checks to NULL
                import decimal as pd

                iv = int(pd.Decimal(t).to_integral_value(rounding=pd.ROUND_DOWN))
                lo, hi = _INT_BOUNDS[dst.kind]
                if lo <= iv <= hi:
                    vals[i], ok[i] = iv, True
            elif dst.is_float:
                vals[i], ok[i] = float(t), True
            elif dst.kind == T.TypeKind.DECIMAL:
                import decimal as pd

                with pd.localcontext() as _hp:
                    _hp.prec = 100  # scaleb rounds at context precision
                    u = int(pd.Decimal(t).scaleb(dst.scale).quantize(
                        pd.Decimal(1), rounding=pd.ROUND_HALF_UP))
                if -(2**63) <= u < 2**63 and (dst.precision >= 19 or abs(u) < 10**dst.precision):
                    vals[i], ok[i] = u, True
            elif dst.kind == T.TypeKind.DATE32:
                import datetime as dt

                y = dt.date.fromisoformat(t[:10])
                vals[i], ok[i] = (y - dt.date(1970, 1, 1)).days, True
            elif dst.kind == T.TypeKind.TIMESTAMP:
                import datetime as dt

                ts = dt.datetime.fromisoformat(t)
                if ts.tzinfo is None:
                    # session timezone is UTC (naive strings must not pick
                    # up the host machine's local zone)
                    ts = ts.replace(tzinfo=dt.timezone.utc)
                vals[i], ok[i] = int(ts.timestamp() * 1e6), True
            else:
                raise TypeError(f"cast string -> {dst}")
        except (ValueError, ArithmeticError, OverflowError):
            pass
    return vals, ok

from auron_tpu.exprs.ir import (  # noqa: F401
    BinaryOp,
    Case,
    Cast,
    Coalesce,
    Column,
    If,
    In,
    IsNotNull,
    IsNull,
    Like,
    Literal,
    Not,
    ScalarFunc,
)
from auron_tpu.exprs.eval import ColumnVal, Evaluator, eval_exprs  # noqa: F401

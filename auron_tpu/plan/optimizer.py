"""Plan-level optimizer passes over the protobuf IR.

``prune_columns`` is the column-pruning pass (analog of the reference's
common/column_pruning.rs and DataFusion join projections): a top-down
required-column analysis that shrinks join outputs to exactly the columns
consumed downstream. On TPU this matters more than on CPU — the dominant
join cost is pair-gather bytes through HBM, which scales linearly with the
emitted column count, so pruning a 12-column join to 3 columns cuts the
expansion roofline by 4x.

The pass returns a REWRITTEN plan; column references in every affected
node are remapped. Nodes the pass doesn't understand act as pruning
barriers (they require all their children's columns) but the recursion
still descends so joins below a barrier are pruned too.
"""

from __future__ import annotations

from auron_tpu.proto import plan_pb2 as pb

# nodes whose output schema is exactly their (single) child's schema;
# rename_columns is NOT here — its names list is sized to the unpruned
# child, so it acts as a pruning barrier
_PASSTHROUGH = ("limit", "coalesce_batches", "debug")


def prune_columns(plan: pb.PhysicalPlanNode) -> pb.PhysicalPlanNode:
    new, _ = _prune(plan, None)
    return new


# ---------------------------------------------------------------------------
# proto-expression helpers
# ---------------------------------------------------------------------------


def _walk_columns(msg, fn) -> None:
    """Apply fn to every ColumnExpr reachable from msg (any proto message)."""
    if isinstance(msg, pb.PhysicalExprNode) and msg.WhichOneof("expr") == "column":
        fn(msg.column)
        return
    for fd, val in msg.ListFields():
        if fd.type != fd.TYPE_MESSAGE:
            continue
        if fd.label == fd.LABEL_REPEATED:
            for v in val:
                _walk_columns(v, fn)
        else:
            _walk_columns(val, fn)


def _collect_cols(*msgs) -> set[int]:
    out: set[int] = set()
    for m in msgs:
        _walk_columns(m, lambda c: out.add(c.index))
    return out


def _remap_exprs(mapping: dict[int, int] | None, *msgs) -> None:
    if mapping is None:
        return

    def rewrite(c):
        c.index = mapping[c.index]

    for m in msgs:
        _walk_columns(m, rewrite)


def _out_width(node: pb.PhysicalPlanNode) -> int:
    """Output column count of a plan subtree, computed structurally where
    the node type makes it cheap; falls back to instantiating the planner's
    exec tree only for width-opaque nodes (agg intermediates etc.)."""
    which = node.WhichOneof("plan")
    inner = getattr(node, which)
    if which in ("memory_scan", "ipc_reader", "ffi_reader", "parquet_scan",
                 "orc_scan", "empty_partitions"):
        return len(inner.schema.fields)
    if which == "project":
        return len(inner.exprs)
    if which in ("filter", "sort", "limit", "coalesce_batches", "debug",
                 "shuffle_writer", "rss_shuffle_writer", "mesh_exchange"):
        return _out_width(inner.child)
    if which == "rename_columns":
        return len(inner.names)
    if which in ("hash_join", "sort_merge_join"):
        if inner.has_projection:
            return len(inner.projection)
        jt = inner.join_type
        if jt in (pb.JOIN_LEFT_SEMI, pb.JOIN_LEFT_ANTI):
            return _out_width(inner.left)
        if jt == pb.JOIN_EXISTENCE:
            return _out_width(inner.left) + 1
        return _out_width(inner.left) + _out_width(inner.right)
    if which == "union":
        return _out_width(inner.children[0])
    if which == "hash_agg" and inner.mode == pb.AGG_FINAL:
        return len(inner.groupings) + len(inner.aggs)
    if which == "kafka_scan":
        return len(inner.schema.fields)
    # fallback instantiates the exec subtree; never valid across a
    # mesh_exchange (driver-resolved), so width-opaque nodes above one
    # must be covered structurally above
    from auron_tpu.plan.planner import plan_from_proto

    return len(plan_from_proto(node).schema)


def _req_or_all(required: list[int] | None, width: int) -> list[int]:
    return list(range(width)) if required is None else required


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------


def _prune(
    node: pb.PhysicalPlanNode, required: list[int] | None
) -> tuple[pb.PhysicalPlanNode, dict[int, int] | None]:
    """Returns (rewritten node, old->new output index mapping or None=id)."""
    which = node.WhichOneof("plan")
    handler = _HANDLERS.get(which)
    if handler is not None:
        return handler(node, required)
    # barrier: keep the node, but descend into any plan children with
    # "all required" so deeper joins still get pruned
    new = pb.PhysicalPlanNode()
    new.CopyFrom(node)
    inner = getattr(new, which)
    if which == "union":
        for c in inner.children:
            c.CopyFrom(_prune(c, None)[0])
        return new, None
    for f in ("child", "left", "right"):
        try:
            present = inner.HasField(f)
        except ValueError:
            continue
        if present:
            sub, cmap = _prune(getattr(inner, f), None)
            assert cmap is None, f"barrier child of {which} must not remap"
            getattr(inner, f).CopyFrom(sub)
    return new, None


def _prune_project(node, required):
    n = node.project
    keep = sorted(set(_req_or_all(required, len(n.exprs))))
    child_req = sorted(_collect_cols(*(n.exprs[i].expr for i in keep)))
    new_child, cmap = _prune(n.child, child_req)
    new = pb.PhysicalPlanNode()
    p = new.project
    p.child.CopyFrom(new_child)
    for i in keep:
        ne = p.exprs.add()
        ne.CopyFrom(n.exprs[i])
        _remap_exprs(cmap, ne.expr)
    if len(keep) == len(n.exprs):
        return new, None
    return new, {old: i for i, old in enumerate(keep)}


def _prune_filter(node, required):
    n = node.filter
    pred_cols = _collect_cols(*n.predicates)
    child_req = (
        None if required is None else sorted(set(required) | pred_cols)
    )
    new_child, cmap = _prune(n.child, child_req)
    new = pb.PhysicalPlanNode()
    f = new.filter
    f.child.CopyFrom(new_child)
    for p in n.predicates:
        np_ = f.predicates.add()
        np_.CopyFrom(p)
        _remap_exprs(cmap, np_)
    return new, cmap


def _prune_sort(node, required):
    n = node.sort
    sort_cols = _collect_cols(*(f.expr for f in n.fields))
    child_req = (
        None if required is None else sorted(set(required) | sort_cols)
    )
    new_child, cmap = _prune(n.child, child_req)
    new = pb.PhysicalPlanNode()
    new.CopyFrom(node)
    s = new.sort
    s.child.CopyFrom(new_child)
    for f in s.fields:
        _remap_exprs(cmap, f.expr)
    return new, cmap


def _prune_passthrough(node, required):
    which = node.WhichOneof("plan")
    new = pb.PhysicalPlanNode()
    new.CopyFrom(node)
    inner = getattr(new, which)
    new_child, cmap = _prune(inner.child, required)
    inner.child.CopyFrom(new_child)
    return new, cmap


def _prune_hash_agg(node, required):
    n = node.hash_agg
    new = pb.PhysicalPlanNode()
    new.CopyFrom(node)
    a = new.hash_agg
    if n.mode == pb.AGG_PARTIAL:
        child_req = sorted(
            _collect_cols(
                *(g.expr for g in n.groupings),
                *(sp.expr for sp in n.aggs if sp.has_expr),
            )
        )
        new_child, cmap = _prune(n.child, child_req)
        a.child.CopyFrom(new_child)
        for g in a.groupings:
            _remap_exprs(cmap, g.expr)
        for sp in a.aggs:
            if sp.has_expr:
                _remap_exprs(cmap, sp.expr)
    else:
        # merge/final consume positional intermediate columns: all required
        new_child, cmap = _prune(n.child, None)
        assert cmap is None
        a.child.CopyFrom(new_child)
    return new, None  # agg output layout unchanged


def _prune_exchange_like(node, required):
    """shuffle/mesh-exchange writers emit every child column; the
    partitioning expressions address child coordinates directly."""
    which = node.WhichOneof("plan")
    new = pb.PhysicalPlanNode()
    new.CopyFrom(node)
    inner = getattr(new, which)
    new_child, cmap = _prune(inner.child, None)
    assert cmap is None
    inner.child.CopyFrom(new_child)
    return new, None


def _prune_join(node, required):
    which = node.WhichOneof("plan")
    n = getattr(node, which)
    if n.has_projection:  # already projected (pass ran twice): barrier
        return node, None
    jt = n.join_type
    nl = _out_width(n.left)
    nr = _out_width(n.right)
    semi_like = jt in (pb.JOIN_LEFT_SEMI, pb.JOIN_LEFT_ANTI)
    existence = jt == pb.JOIN_EXISTENCE
    out_width = nl if semi_like else (nl + 1 if existence else nl + nr)
    R = _req_or_all(required, out_width)

    lkeys = _collect_cols(*n.left_keys)
    rkeys = _collect_cols(*n.right_keys)
    cond_cols = _collect_cols(n.condition) if n.has_condition else set()
    cond_l = {c for c in cond_cols if c < nl}
    cond_r = {c - nl for c in cond_cols if c >= nl}

    left_need = {c for c in R if c < nl}
    right_need = (
        set() if (semi_like or existence) else {c - nl for c in R if c >= nl}
    )
    child_req_l = sorted(left_need | lkeys | cond_l)
    child_req_r = sorted(right_need | rkeys | cond_r)

    new_left, lmap = _prune(n.left, child_req_l if len(child_req_l) < nl else None)
    new_right, rmap = _prune(
        n.right, child_req_r if len(child_req_r) < nr else None
    )
    lmap = lmap or {i: i for i in range(nl)}
    rmap = rmap or {i: i for i in range(nr)}
    new_nl = _out_width(new_left)
    new_nr = _out_width(new_right)

    new = pb.PhysicalPlanNode()
    new.CopyFrom(node)
    j = getattr(new, which)
    j.left.CopyFrom(new_left)
    j.right.CopyFrom(new_right)
    for k in j.left_keys:
        _remap_exprs(lmap, k)
    for k in j.right_keys:
        _remap_exprs(rmap, k)
    if n.has_condition:
        comb = {c: lmap[c] for c in cond_l}
        comb.update({c + nl: new_nl + rmap[c] for c in cond_r})
        _remap_exprs(comb, j.condition)

    # projection over the PRUNED combined/left coordinates, in R's order
    if semi_like:
        proj = [lmap[c] for c in R]
        new_width = new_nl
    elif existence:
        proj = [(lmap[c] if c < nl else new_nl) for c in R]
        new_width = new_nl + 1
    else:
        proj = [(lmap[c] if c < nl else new_nl + rmap[c - nl]) for c in R]
        new_width = new_nl + new_nr
    if proj != list(range(new_width)):
        j.projection.extend(proj)
        j.has_projection = True
    mapping = None if required is None else {c: i for i, c in enumerate(R)}
    return new, mapping


_HANDLERS = {
    "project": _prune_project,
    "filter": _prune_filter,
    "sort": _prune_sort,
    "hash_agg": _prune_hash_agg,
    "hash_join": _prune_join,
    "sort_merge_join": _prune_join,
    "shuffle_writer": _prune_exchange_like,
    "rss_shuffle_writer": _prune_exchange_like,
    "mesh_exchange": _prune_exchange_like,
    "parquet_sink": _prune_exchange_like,
    "orc_sink": _prune_exchange_like,
    "ipc_writer": _prune_exchange_like,
}
for _p in _PASSTHROUGH:
    _HANDLERS[_p] = _prune_passthrough


# ---------------------------------------------------------------------------
# Sort elision under sort-merge join
# ---------------------------------------------------------------------------

# the only operator in the IR whose OUTPUT depends on its input's row order
# (head-N). Sort/TakeOrdered/Window establish their own order internally.
_ORDER_SENSITIVE = ("limit",)


def elide_smj_input_sorts(
    plan: "pb.PhysicalPlanNode", mode: str = "build"
) -> "pb.PhysicalPlanNode":
    """Drop SortExec children feeding a sort-merge join.

    The host engine plans Sort->SMJ because ITS merge-join streams two
    ordered cursors; this engine's SMJ clusters the build side itself
    (joins/core.prepare_build) and probes with order-independent binary
    searches, so explicit input sorts are pure overhead — at perf-gate
    scale each one is a full materialized lexsort of a fact partition.

    ``mode`` controls how aggressive the rewrite is:

    - "build" (default): elide only the BUILD-side (right) sort. The join's
      output order follows the probe side, so this NEVER changes the output
      ordering — safe even when the host relied on the SMJ's output
      ordering to satisfy a downstream requirement invisible in this task
      plan (Spark's EnsureRequirements plants no sort above a join whose
      outputOrdering already satisfies the parent).
    - "full": elide both sides. Only the host can know no ancestor outside
      the converted section needs the order; it asserts that by setting
      ``auron.smj.elide.sorts=full`` in the task conf.
    - "off": no rewrite.

    Either way a fetch-carrying sort (TakeOrdered — changes the row SET) is
    never touched, and the rewrite is skipped under an order-sensitive
    ancestor inside the plan (head-N limit).
    """
    if mode == "off":
        return plan
    new = pb.PhysicalPlanNode()
    new.CopyFrom(plan)
    _elide(new, order_sensitive=False, full=(mode == "full"))
    return new


def _elide(node: "pb.PhysicalPlanNode", order_sensitive: bool, full: bool) -> None:
    from auron_tpu.plan.protowalk import child_nodes

    which = node.WhichOneof("plan")
    sensitive = order_sensitive or which in _ORDER_SENSITIVE
    if which == "sort_merge_join" and not sensitive:
        j = node.sort_merge_join
        sides = ("left", "right") if full else ("right",)
        for side in sides:
            child = getattr(j, side)
            if (
                child.WhichOneof("plan") == "sort"
                and not child.sort.has_fetch
            ):
                grand = pb.PhysicalPlanNode()
                grand.CopyFrom(child.sort.child)
                getattr(j, side).CopyFrom(grand)
    for c in child_nodes(node):
        _elide(c, sensitive, full)

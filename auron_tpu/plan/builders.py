"""Host-side plan builders: python expression IR / operator specs -> protobuf.

This is the in-process stand-in for the host engine's plan conversion layer
(the role the reference's Spark extension plays: AuronConverters building
PhysicalPlanNode protos per operator, AuronConverters.scala:212-305 and
NativeConverters.convertExpr, NativeConverters.scala:329). The TPC-DS
harness and tests build plans through these, ship serialized
TaskDefinitions, and the planner (plan/planner.py) reconstructs exec trees —
exercising the same wire contract a real engine front-end would.
"""

from __future__ import annotations

import decimal as pydec
from typing import Any

from auron_tpu import types as T
from auron_tpu.exprs import ir
from auron_tpu.ops.sortkeys import SortSpec
from auron_tpu.plan.planner import dtype_to_proto, schema_to_proto
from auron_tpu.proto import plan_pb2 as pb

# ---------------------------------------------------------------------------
# expressions: ir -> proto
# ---------------------------------------------------------------------------


def literal_to_proto(value: Any, dtype: T.DataType) -> pb.LiteralExpr:
    p = pb.LiteralExpr(dtype=dtype_to_proto(dtype))
    if value is None:
        p.is_null = True
        return p
    k = dtype.kind
    if k == T.TypeKind.BOOL:
        p.bool_value = bool(value)
    elif dtype.is_integer or k in (T.TypeKind.DATE32, T.TypeKind.TIMESTAMP):
        p.int_value = int(value)
    elif dtype.is_float:
        p.float_value = float(value)
    elif k == T.TypeKind.STRING:
        p.string_value = str(value)
    elif k == T.TypeKind.BINARY:
        p.bytes_value = bytes(value)
    elif k == T.TypeKind.DECIMAL:
        u = int(pydec.Decimal(str(value)).scaleb(dtype.scale).quantize(pydec.Decimal(1)))
        p.decimal_unscaled = u
    else:
        raise TypeError(f"literal of type {dtype}")
    return p


def expr_to_proto(e: ir.Expr) -> pb.PhysicalExprNode:
    n = pb.PhysicalExprNode()
    if isinstance(e, ir.Column):
        n.column.index = e.index
        n.column.name = e.name
    elif isinstance(e, ir.Literal):
        n.literal.CopyFrom(literal_to_proto(e.value, e.dtype))
    elif isinstance(e, ir.Cast):
        n.cast.child.CopyFrom(expr_to_proto(e.child))
        n.cast.to.CopyFrom(dtype_to_proto(e.to))
        n.cast.try_cast = e.try_
    elif isinstance(e, ir.BinaryOp):
        n.binary.op = e.op
        n.binary.left.CopyFrom(expr_to_proto(e.left))
        n.binary.right.CopyFrom(expr_to_proto(e.right))
    elif isinstance(e, ir.IsNull):
        n.is_null.child.CopyFrom(expr_to_proto(e.child))
    elif isinstance(e, ir.IsNotNull):
        n.is_not_null.child.CopyFrom(expr_to_proto(e.child))
    elif isinstance(e, ir.Not):
        getattr(n, "not").child.CopyFrom(expr_to_proto(e.child))
    elif isinstance(e, ir.If):
        n.if_expr.cond.CopyFrom(expr_to_proto(e.cond))
        n.if_expr.then.CopyFrom(expr_to_proto(e.then))
        n.if_expr.orelse.CopyFrom(expr_to_proto(e.orelse))
    elif isinstance(e, ir.Case):
        for c, v in e.branches:
            b = n.case_expr.branches.add()
            b.when.CopyFrom(expr_to_proto(c))
            b.then.CopyFrom(expr_to_proto(v))
        if e.orelse is not None:
            n.case_expr.orelse.CopyFrom(expr_to_proto(e.orelse))
    elif isinstance(e, ir.In):
        n.in_list.child.CopyFrom(expr_to_proto(e.child))
        n.in_list.negated = e.negated
        for item in e.items:
            lit = ir.lit(item) if not isinstance(item, ir.Literal) else item
            n.in_list.items.add().CopyFrom(literal_to_proto(lit.value, lit.dtype))
    elif isinstance(e, ir.Coalesce):
        for a in e.args:
            n.coalesce.args.add().CopyFrom(expr_to_proto(a))
    elif isinstance(e, ir.Like):
        n.like.child.CopyFrom(expr_to_proto(e.child))
        n.like.pattern = e.pattern
        n.like.negated = e.negated
        n.like.escape = e.escape
    elif isinstance(e, ir.ScalarFunc):
        n.scalar_func.name = e.name
        for a in e.args:
            n.scalar_func.args.add().CopyFrom(expr_to_proto(a))
        if e.out_dtype is not None:
            n.scalar_func.out_dtype.CopyFrom(dtype_to_proto(e.out_dtype))
            n.scalar_func.has_out_dtype = True
    elif isinstance(e, ir.HostUDF):
        n.host_udf.name = e.name
        for a in e.args:
            n.host_udf.args.add().CopyFrom(expr_to_proto(a))
        n.host_udf.out_dtype.CopyFrom(dtype_to_proto(e.out_dtype))
    elif isinstance(e, ir.SparkPartitionId):
        n.spark_partition_id.SetInParent()
    elif isinstance(e, ir.MonotonicId):
        n.monotonic_id.SetInParent()
    elif isinstance(e, ir.RowNum):
        n.row_num.SetInParent()
    elif isinstance(e, ir.ScalarSubquery):
        n.scalar_subquery.resource_id = e.resource_id
        n.scalar_subquery.dtype.CopyFrom(dtype_to_proto(e.dtype))
    else:
        raise TypeError(f"cannot serialize {type(e).__name__}")
    return n


def sort_field(e: ir.Expr, spec: SortSpec) -> pb.SortField:
    f = pb.SortField(asc=spec.asc, nulls_first=spec.nulls_first)
    f.expr.CopyFrom(expr_to_proto(e))
    return f


# ---------------------------------------------------------------------------
# plan nodes
# ---------------------------------------------------------------------------


def _wrap(**kwargs) -> pb.PhysicalPlanNode:
    return pb.PhysicalPlanNode(**kwargs)


def memory_scan(schema: T.Schema, resource_id: str) -> pb.PhysicalPlanNode:
    return _wrap(memory_scan=pb.MemoryScanNode(
        schema=schema_to_proto(schema), resource_id=resource_id))


def ffi_reader(schema: T.Schema, resource_id: str) -> pb.PhysicalPlanNode:
    return _wrap(ffi_reader=pb.FfiReaderNode(
        schema=schema_to_proto(schema), resource_id=resource_id))


def parquet_scan(schema: T.Schema, files: list[str],
                 pruning: list[ir.Expr] = (), fs_resource_id: str = "") -> pb.PhysicalPlanNode:
    n = pb.ParquetScanNode(schema=schema_to_proto(schema), file_paths=list(files),
                           fs_resource_id=fs_resource_id)
    for p in pruning:
        n.pruning_predicates.add().CopyFrom(expr_to_proto(p))
    return _wrap(parquet_scan=n)


def project(child: pb.PhysicalPlanNode, exprs: list[tuple[ir.Expr, str]]) -> pb.PhysicalPlanNode:
    n = pb.ProjectNode(child=child)
    for e, name in exprs:
        ne = n.exprs.add()
        ne.expr.CopyFrom(expr_to_proto(e))
        ne.name = name
    return _wrap(project=n)


def filter_(child: pb.PhysicalPlanNode, predicates: list[ir.Expr]) -> pb.PhysicalPlanNode:
    n = pb.FilterNode(child=child)
    for p in predicates:
        n.predicates.add().CopyFrom(expr_to_proto(p))
    return _wrap(filter=n)


def limit(child: pb.PhysicalPlanNode, k: int) -> pb.PhysicalPlanNode:
    return _wrap(limit=pb.LimitNode(child=child, limit=k))


def union(children: list[pb.PhysicalPlanNode]) -> pb.PhysicalPlanNode:
    return _wrap(union=pb.UnionNode(children=children))


def rename_columns(child: pb.PhysicalPlanNode, names: list[str]) -> pb.PhysicalPlanNode:
    return _wrap(rename_columns=pb.RenameColumnsNode(child=child, names=list(names)))


def empty_partitions(schema: T.Schema, num_partitions: int) -> pb.PhysicalPlanNode:
    return _wrap(empty_partitions=pb.EmptyPartitionsNode(
        schema=schema_to_proto(schema), num_partitions=num_partitions))


def coalesce_batches(child: pb.PhysicalPlanNode, target_rows: int = 0) -> pb.PhysicalPlanNode:
    return _wrap(coalesce_batches=pb.CoalesceBatchesNode(
        child=child, target_rows=target_rows))


def debug(child: pb.PhysicalPlanNode, tag: str = "debug") -> pb.PhysicalPlanNode:
    return _wrap(debug=pb.DebugNode(child=child, tag=tag))


def expand(child, projections: list[list[ir.Expr]], names: list[str]) -> pb.PhysicalPlanNode:
    """ROLLUP/CUBE lowering: one output batch per projection per input."""
    n = pb.ExpandNode(child=child, names=names)
    for proj in projections:
        p = n.projections.add()
        for e in proj:
            p.exprs.append(expr_to_proto(e))
    return _wrap(expand=n)


def hash_agg(child: pb.PhysicalPlanNode, groupings: list[tuple[ir.Expr, str]],
             aggs: list[tuple], mode: str) -> pb.PhysicalPlanNode:
    """aggs: (func, expr, name) or (func, expr, name, udaf_name) tuples."""
    m = {"partial": pb.AGG_PARTIAL, "partial_merge": pb.AGG_PARTIAL_MERGE,
         "final": pb.AGG_FINAL}[mode]
    fmap = {"sum": pb.AGG_SUM, "count": pb.AGG_COUNT, "count_star": pb.AGG_COUNT_STAR,
            "avg": pb.AGG_AVG, "min": pb.AGG_MIN, "max": pb.AGG_MAX,
            "first": pb.AGG_FIRST, "first_ignores_null": pb.AGG_FIRST_IGNORES_NULL,
            "collect_list": pb.AGG_COLLECT_LIST, "collect_set": pb.AGG_COLLECT_SET,
            "host_udaf": pb.AGG_HOST_UDAF}
    n = pb.HashAggNode(child=child, mode=m)
    for e, name in groupings:
        g = n.groupings.add()
        g.expr.CopyFrom(expr_to_proto(e))
        g.name = name
    for spec in aggs:
        func, e, name = spec[0], spec[1], spec[2]
        a = n.aggs.add()
        a.func = fmap[func]
        a.name = name
        if len(spec) > 3 and spec[3]:
            a.udaf = spec[3]
        if e is not None:
            a.expr.CopyFrom(expr_to_proto(e))
            a.has_expr = True
    return _wrap(hash_agg=n)


def sort(child: pb.PhysicalPlanNode, fields: list[tuple[ir.Expr, SortSpec]],
         fetch: int | None = None) -> pb.PhysicalPlanNode:
    n = pb.SortNode(child=child)
    for e, s in fields:
        n.fields.add().CopyFrom(sort_field(e, s))
    if fetch is not None:
        n.fetch = fetch
        n.has_fetch = True
    return _wrap(sort=n)


_JT = {"inner": pb.JOIN_INNER, "left": pb.JOIN_LEFT, "right": pb.JOIN_RIGHT,
       "full": pb.JOIN_FULL, "left_semi": pb.JOIN_LEFT_SEMI,
       "left_anti": pb.JOIN_LEFT_ANTI, "existence": pb.JOIN_EXISTENCE}


def sort_merge_join(left, right, left_keys, right_keys, join_type,
                    condition=None) -> pb.PhysicalPlanNode:
    n = pb.SortMergeJoinNode(left=left, right=right, join_type=_JT[join_type])
    for e in left_keys:
        n.left_keys.add().CopyFrom(expr_to_proto(e))
    for e in right_keys:
        n.right_keys.add().CopyFrom(expr_to_proto(e))
    if condition is not None:
        n.condition.CopyFrom(expr_to_proto(condition))
        n.has_condition = True
    return _wrap(sort_merge_join=n)


def hash_join(left, right, left_keys, right_keys, join_type,
              build_side="right", condition=None,
              cached_build_id: str = "") -> pb.PhysicalPlanNode:
    n = pb.HashJoinNode(
        left=left, right=right, join_type=_JT[join_type],
        build_side=pb.BUILD_LEFT if build_side == "left" else pb.BUILD_RIGHT,
        cached_build_id=cached_build_id,
    )
    for e in left_keys:
        n.left_keys.add().CopyFrom(expr_to_proto(e))
    for e in right_keys:
        n.right_keys.add().CopyFrom(expr_to_proto(e))
    if condition is not None:
        n.condition.CopyFrom(expr_to_proto(condition))
        n.has_condition = True
    return _wrap(hash_join=n)


def hash_partitioning(exprs: list[ir.Expr], n: int) -> pb.Partitioning:
    p = pb.Partitioning(kind=pb.Partitioning.HASH, num_partitions=n)
    for e in exprs:
        p.hash_exprs.add().CopyFrom(expr_to_proto(e))
    return p


def shuffle_writer(child, partitioning: pb.Partitioning,
                   data_file: str, index_file: str) -> pb.PhysicalPlanNode:
    return _wrap(shuffle_writer=pb.ShuffleWriterNode(
        child=child, partitioning=partitioning,
        output_data_file=data_file, output_index_file=index_file))


def mesh_exchange(child, partitioning: pb.Partitioning,
                  exchange_id: str = "") -> pb.PhysicalPlanNode:
    """Device-resident repartition boundary (ICI all_to_all or file
    fallback, decided by the mesh driver per exchange.mode/statistics)."""
    return _wrap(mesh_exchange=pb.MeshExchangeNode(
        child=child, partitioning=partitioning, exchange_id=exchange_id))


def rss_shuffle_writer(child, partitioning: pb.Partitioning,
                       rss_resource_id: str) -> pb.PhysicalPlanNode:
    return _wrap(rss_shuffle_writer=pb.RssShuffleWriterNode(
        child=child, partitioning=partitioning,
        rss_resource_id=rss_resource_id))


def ipc_reader(schema: T.Schema, resource_id: str) -> pb.PhysicalPlanNode:
    return _wrap(ipc_reader=pb.IpcReaderNode(
        schema=schema_to_proto(schema), resource_id=resource_id))


def window(child, partition_by: list[ir.Expr],
           order_by: list[tuple[ir.Expr, SortSpec]],
           funcs: list[tuple]) -> pb.PhysicalPlanNode:
    """funcs: (kind, agg, expr, offset, frame_whole, name) tuples."""
    n = pb.WindowNode(child=child)
    for e in partition_by:
        n.partition_by.add().CopyFrom(expr_to_proto(e))
    for e, s in order_by:
        n.order_by.add().CopyFrom(sort_field(e, s))
    for kind, agg, e, offset, whole, name in funcs:
        f = n.funcs.add()
        f.kind = kind
        f.agg = agg or ""
        if e is not None:
            f.expr.CopyFrom(expr_to_proto(e))
            f.has_expr = True
        f.offset = offset
        f.frame_whole = whole
        f.name = name
    return _wrap(window=n)


def generate(child, generator: str, gen_expr: ir.Expr, required_cols: list[int],
             outer=False, json_fields=(), elem_name="col", pos_name="pos") -> pb.PhysicalPlanNode:
    n = pb.GenerateNode(child=child, generator=generator,
                        required_cols=list(required_cols), outer=outer,
                        json_fields=list(json_fields),
                        elem_name=elem_name, pos_name=pos_name)
    n.gen_expr.CopyFrom(expr_to_proto(gen_expr))
    return _wrap(generate=n)


def parquet_sink(child, output_path: str, props: dict | None = None,
                 partition_by: list[str] | None = None) -> pb.PhysicalPlanNode:
    return _wrap(parquet_sink=pb.ParquetSinkNode(
        child=child, output_path=output_path, props=props or {},
        partition_by=list(partition_by or [])))


def ipc_writer(child, resource_id: str) -> pb.PhysicalPlanNode:
    return _wrap(ipc_writer=pb.IpcWriterNode(child=child, resource_id=resource_id))


def kafka_scan(schema: T.Schema, topic: str, source_resource_id: str,
               startup_mode: str = "earliest", start_offsets: dict | None = None,
               data_format: str = "json", on_error: str = "skip",
               pb_field_ids: list[int] | None = None,
               max_batch_records: int = 0,
               zigzag_cols: list[int] | None = None) -> pb.PhysicalPlanNode:
    n = pb.KafkaScanNode(
        schema=schema_to_proto(schema), topic=topic,
        startup_mode=startup_mode, format=data_format, on_error=on_error,
        source_resource_id=source_resource_id,
        max_batch_records=max_batch_records,
    )
    # sorted: proto emission must be byte-stable regardless of the
    # caller's dict build order (the serialized plan feeds digests)
    for k, v in sorted((start_offsets or {}).items(), key=lambda kv: int(kv[0])):
        n.start_offsets[int(k)] = int(v)
    if pb_field_ids:
        n.pb_field_ids.extend(pb_field_ids)
    if zigzag_cols:
        n.zigzag_cols.extend(zigzag_cols)
    return _wrap(kafka_scan=n)


def task(plan: pb.PhysicalPlanNode, stage_id=0, partition_id=0,
         conf: dict | None = None) -> pb.TaskDefinition:
    t = pb.TaskDefinition(plan=plan, stage_id=stage_id, partition_id=partition_id)
    # sorted: task protos diff byte-for-byte across processes
    for k, v in sorted((conf or {}).items()):
        t.conf[k] = str(v)
    return t

"""Plan explain rendering + stability checking.

Analog of the reference's golden-plan gate (dev/auron-it
PlanStabilityChecker.scala:30-110): render a normalized text form of the
executable plan tree and diff it against checked-in goldens, so native-
coverage regressions (an operator silently falling back or changing shape)
fail tests instead of shipping.
"""

from __future__ import annotations

import re

from auron_tpu.exec.base import ExecOperator
from auron_tpu.exprs import ir


def expr_str(e: ir.Expr) -> str:
    if isinstance(e, ir.Column):
        return f"#{e.index}" + (f"({e.name})" if e.name else "")
    if isinstance(e, ir.Literal):
        return repr(e.value)
    if isinstance(e, ir.BinaryOp):
        return f"({expr_str(e.left)} {e.op} {expr_str(e.right)})"
    if isinstance(e, ir.Cast):
        return f"cast({expr_str(e.child)} as {e.to})"
    if isinstance(e, ir.IsNull):
        return f"isnull({expr_str(e.child)})"
    if isinstance(e, ir.IsNotNull):
        return f"isnotnull({expr_str(e.child)})"
    if isinstance(e, ir.Not):
        return f"not({expr_str(e.child)})"
    if isinstance(e, ir.ScalarFunc):
        return f"{e.name}({', '.join(expr_str(a) for a in e.args)})"
    if isinstance(e, ir.HostUDF):
        return f"host_udf:{e.name}({', '.join(expr_str(a) for a in e.args)})"
    if isinstance(e, ir.In):
        return f"{expr_str(e.child)} in {list(e.items)!r}"
    if isinstance(e, ir.Like):
        return f"{expr_str(e.child)} like {e.pattern!r}"
    if isinstance(e, ir.Case):
        return "case(...)"
    if isinstance(e, ir.If):
        return f"if({expr_str(e.cond)}, {expr_str(e.then)}, {expr_str(e.orelse)})"
    if isinstance(e, ir.Coalesce):
        return f"coalesce({', '.join(expr_str(a) for a in e.args)})"
    return type(e).__name__


def _node_detail(op: ExecOperator) -> str:
    d = []
    for attr in ("exprs", "predicates", "sort_exprs", "left_keys", "right_keys",
                 "partition_by", "gen_expr"):
        v = getattr(op, attr, None)
        if v is None:
            continue
        if isinstance(v, list):
            d.append(f"{attr}=[{', '.join(expr_str(e) for e in v)}]")
        else:
            d.append(f"{attr}={expr_str(v)}")
    for attr in ("limit", "fetch", "mode", "generator", "outer", "build_side"):
        v = getattr(op, attr, None)
        if v is not None and v is not False:
            d.append(f"{attr}={v}")
    drv = getattr(op, "driver", None)
    if drv is not None:
        d.append(f"join_type={drv.join_type}")
    part = getattr(op, "partitioning", None)
    if part is not None:
        d.append(f"partitioning={type(part).__name__}({part.num_partitions})")
    groupings = getattr(op, "groupings", None)
    if groupings:
        d.append(f"groups=[{', '.join(expr_str(e) for e, _ in groupings)}]")
    aggs = getattr(op, "aggs", None)
    if aggs:
        d.append(
            "aggs=["
            + ", ".join(
                f"{a.func}({expr_str(a.expr) if a.expr is not None else '*'}) as {n}"
                for a, n in aggs
            )
            + "]"
        )
    return " " + " ".join(d) if d else ""


def explain(op: ExecOperator, indent: int = 0) -> str:
    lines = ["  " * indent + op.name + _node_detail(op)]
    for c in op.children:
        lines.append(explain(c, indent + 1))
    return "\n".join(lines)


#: Per-variant detail attributes rendered by ``explain_proto``. EVERY plan
#: oneof variant in proto/plan.proto MUST have an entry here — auronlint
#: R4 cross-checks this registry against the proto, so a new operator
#: cannot ship without deciding what its explain line shows. Structural
#: nodes with nothing to say carry an explicit empty tuple.
PLAN_DETAILS: dict[str, tuple[str, ...]] = {
    "memory_scan": ("resource_id",),
    "ffi_reader": ("resource_id",),
    "parquet_scan": ("fs_resource_id",),
    "project": (),
    "filter": (),
    "limit": ("limit",),
    "union": (),
    "expand": (),
    "rename_columns": (),
    "empty_partitions": ("num_partitions",),
    "coalesce_batches": ("target_rows",),
    "hash_agg": (),          # mode rendered as a special case below
    "sort": ("fetch",),
    "sort_merge_join": (),
    "hash_join": ("cached_build_id",),
    "shuffle_writer": (),    # partitioning rendered as a special case
    "ipc_reader": ("resource_id",),
    "window": (),
    "generate": ("generator",),
    "parquet_sink": ("output_path",),
    "ipc_writer": ("resource_id",),
    "debug": ("tag",),
    "orc_scan": ("fs_resource_id",),
    "orc_sink": ("output_path",),
    "rss_shuffle_writer": ("rss_resource_id",),
    "mesh_exchange": ("exchange_id",),
    "kafka_scan": ("topic", "format", "startup_mode", "on_error",
                   "source_resource_id"),
}


def explain_proto(node, indent: int = 0) -> str:
    """Render a protobuf plan tree (works for driver-resolved nodes like
    mesh_exchange / kafka_scan that never become exec operators)."""
    from auron_tpu.proto import plan_pb2 as pb

    which = node.WhichOneof("plan")
    inner = getattr(node, which)
    details = []
    for attr in PLAN_DETAILS.get(which, ()):
        v = getattr(inner, attr, None)
        if v:
            details.append(f"{attr}={v}")
    if getattr(inner, "file_paths", None):
        details.append(f"files={len(inner.file_paths)}")
    part = getattr(inner, "partitioning", None)
    if part is not None and part.ByteSize() >= 0 and (
        part.num_partitions or part.kind
    ):
        kind = pb.Partitioning.Kind.Name(part.kind).lower()
        details.append(f"partitioning={kind}({part.num_partitions})")
    if getattr(inner, "has_projection", False):
        details.append(f"projection={list(inner.projection)}")
    if getattr(inner, "mode", None) is not None and which == "hash_agg":
        details.append(f"mode={pb.AggMode.Name(inner.mode).lower()}")
    line = "  " * indent + which + (" " + " ".join(details) if details else "")
    lines = [line]
    if which == "union":
        for c in inner.children:
            lines.append(explain_proto(c, indent + 1))
    else:
        for f in ("child", "left", "right"):
            try:
                present = inner.HasField(f)
            except ValueError:
                continue
            if present:
                lines.append(explain_proto(getattr(inner, f), indent + 1))
    return "\n".join(lines)


def normalize(plan_text: str) -> str:
    """Strip run-specific detail (paths, resource ids) for golden diffs."""
    t = re.sub(r"/[^\s]*\.(data|index|parquet|orc)", "<path>", plan_text)
    t = re.sub(r"resource_id=\S+", "resource_id=<id>", t)
    return t


def check_stability(op: ExecOperator, golden_path: str, update: bool = False) -> None:
    """Compare the normalized explain output to a golden file."""
    import os

    text = normalize(explain(op)) + "\n"
    if update or not os.path.exists(golden_path):
        os.makedirs(os.path.dirname(golden_path), exist_ok=True)
        with open(golden_path, "w") as f:
            f.write(text)
        return
    with open(golden_path) as f:
        golden = f.read()
    if golden != text:
        raise AssertionError(
            f"plan changed vs golden {golden_path}:\n--- golden ---\n{golden}"
            f"--- current ---\n{text}"
        )

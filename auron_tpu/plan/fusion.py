"""Whole-stage fusion: pipeline segments -> single XLA programs.

PR 3 proved the thesis at operator scope (``exec.filter.fuse``: one jitted
program per predicate chain). This pass generalizes it Flare-style (PAPERS
1703.08219): a **segment finder** walks the instantiated exec tree and
identifies maximal scan->filter->project(->partial-agg-input) pipeline
segments between blocking boundaries (sort, agg state, join build, shuffle,
collect — every operator that is not a stateless row-pipeline stage), a
**stage compiler** traces each segment's per-batch work into ONE jitted XLA
program keyed on ``(schema, segment signature, compaction bucket)``, and a
**cost model** chooses fuse vs. materialize per segment (SystemML-style
selection, PAPERS 1801.00829): operator cost = estimated eager dispatches
(expression DAG nodes + per-operator overhead), substrate-resolved through
``utils.config.resolve_tri`` — accelerators always fuse, XLA:CPU fuses only
segments whose eager cost reaches ``exec.fuse.min.ops`` (the PR-3-measured
CPU exception: fused chains beat eager dispatch there too).

Fusion is an EXEC-TREE rewrite (``task_from_proto`` applies it after column
pruning): the protobuf plan, plan goldens and ``plan/explain`` output are
untouched, and results are bit-identical with the pass off
(``exec.fuse.enable=off`` — the A/B lever the fuzz suite and the perf gate
exercise).

Invariants the fused stage preserves (docs/fusion.md):

- R10 jit-boundary purity: the traced region is the same trace-safe
  expression machinery behind ``exec.filter.fuse`` (``exprs/eval.py``
  evaluated over a dict-less device batch); no conf reads, host transfers
  or captured-state mutation inside the trace (auronlint R10 checks the
  closure, R2 the cache-key discipline).
- Dictionary passthrough: a dict-encoded column may ride THROUGH a fused
  segment only as a bare column reference — its codes flow through the
  program, the host-side dictionary re-attaches on emission. Expressions
  that *transform* dictionaries (string compare/LIKE/casts) stay eager.
- Batch protocol: fused stages refine the selection mask exactly like
  FilterExec (no compaction inside the stage), so downstream compaction
  boundaries — including the selectivity predictor's mispredict repair —
  see the same batches they would without fusion, and emitted batches
  remain prefetchable through the async transfer window.
- Metric attribution: fused-program wall time is split back into
  per-operator MetricNode children (proportional to the cost model's
  per-operator weights), and the SAME split nanos are handed to the obs
  span timeline — ``top_ops`` and the <=5% span/metric cross-check see
  FilterExec/ProjectExec/HashAggExec, never one opaque stage.
"""

from __future__ import annotations

import threading
import time
from functools import partial as _partial

import jax
import jax.numpy as jnp
import numpy as np

from auron_tpu import obs
from auron_tpu import types as T
from auron_tpu.columnar.batch import Batch, DeviceBatch
from auron_tpu.exec.base import ExecOperator, ExecutionContext
from auron_tpu.exprs import Evaluator, ir
from auron_tpu.utils.config import (
    FUSE_AGG_INPUTS,
    FUSE_ENABLE,
    FUSE_MIN_OPS,
    FUSE_PROBE,
    FUSE_SHUFFLE,
    Configuration,
    resolve_tri,
)

# ---------------------------------------------------------------------------
# trace safety
# ---------------------------------------------------------------------------

#: expression nodes whose evaluation is a pure jnp program over dict-free
#: operands — the exec.filter.fuse whitelist plus In (numeric membership is
#: a pure compare/or chain). Everything else (scalar funcs, host UDFs,
#: row-offset context, LIKE, subqueries) stays eager.
_FUSABLE_NODES = (
    ir.Literal, ir.Cast, ir.BinaryOp, ir.Not, ir.IsNull, ir.IsNotNull,
    ir.If, ir.Case, ir.Coalesce, ir.In,
)

_NESTED_KINDS = (T.TypeKind.LIST, T.TypeKind.MAP, T.TypeKind.STRUCT)


def expr_trace_safe(e: ir.Expr, schema: T.Schema, allow_dict_out: bool = False) -> bool:
    """True when evaluating ``e`` inside a jit over a dict-less batch is
    exactly the eager evaluation. ``allow_dict_out`` permits a BARE
    dict-encoded column reference (projection passthrough: codes flow
    through the program, the dictionary re-attaches host-side); computed
    dict-encoded results never fuse — their evaluation transforms host
    dictionaries. IsNull/IsNotNull over a bare column are safe even for
    dict columns (they read only the validity plane)."""
    if isinstance(e, ir.Column):
        dt = e.dtype_of(schema)
        return allow_dict_out or not (dt.is_dict_encoded or dt.kind in _NESTED_KINDS)
    if isinstance(e, (ir.IsNull, ir.IsNotNull)) and isinstance(e.child, ir.Column):
        return True
    if not isinstance(e, _FUSABLE_NODES):
        return False
    dt = e.dtype_of(schema)
    if dt.is_dict_encoded or dt.kind in _NESTED_KINDS:
        return False
    return all(expr_trace_safe(c, schema) for c in e.children())


def _expr_nodes(e: ir.Expr) -> int:
    return 1 + sum(_expr_nodes(c) for c in e.children())


# ---------------------------------------------------------------------------
# the stage program (ONE jit; cache key = static (steps, emit) + shapes)
# ---------------------------------------------------------------------------


def _trace_steps(dev: DeviceBatch, steps: tuple):
    """The shared traced step walk: apply ("filter", schema, predicates) /
    ("project", schema, exprs) stages in order; each step carries the
    ORIGINAL operator's input schema so expression typing is exactly the
    eager path's. Returns (sel, values, validity, final projection's
    ColumnVals or None). The common-subexpression memo is shared across
    consecutive steps over the same input columns and reset at every
    projection (which replaces the column planes)."""
    sel = dev.sel
    values, validity = dev.values, dev.validity
    outs = None
    memo: dict = {}
    for step in steps:
        kind, schema, exprs = step
        b = Batch(schema, DeviceBatch(sel, values, validity),
                  (None,) * len(schema.fields))
        ev = Evaluator(schema, partition_id=0, row_offset=0, resources={})
        if kind == "filter":
            for p in exprs:
                cv = ev._eval(p, b, memo)
                sel = sel & cv.validity & cv.values.astype(bool)
        else:
            outs = [ev._eval(e, b, memo) for e in exprs]
            values = tuple(cv.values for cv in outs)
            validity = tuple(cv.validity for cv in outs)
            memo = {}
    return sel, values, validity, outs


@_partial(jax.jit, static_argnames=("steps", "emit"))
def _stage_program(dev: DeviceBatch, *, steps: tuple, emit: str):
    """The whole segment's per-batch work as ONE compiled program.
    ``emit`` is "sel" (filter-only segment: the caller reuses the input
    columns) or "cols" (the final projection's columns are returned)."""
    sel, values, validity, _ = _trace_steps(dev, steps)
    if emit == "sel":
        return sel
    return sel, values, validity


# 2^62 sentinels for the per-key guard min/max reductions (ignored by the
# consumer unless the key saw a live valid row — the any_ok flag)
_GUARD_HI = (1 << 62)


@_partial(jax.jit, static_argnames=("steps", "prep"))
def _stage_program_prep(dev: DeviceBatch, bases, his, strides, size, *,
                        steps: tuple, prep: tuple):
    """Stage program variant for segments feeding a DENSE partial
    aggregate on the host-scatter substrate: in the SAME compiled program
    as the filter/project work, compute the dense fold's per-batch prep —
    the range-guard statistics, the packed slot index and the per-agg
    masked value planes — so the host keeps only the bincount
    scatter-reduces (the substrate choice PR 3 measured; the ~6 numpy
    passes of guard/index/mask arithmetic move into this one XLA pass).

    ``bases``/``his``/``strides``/``size`` are the anchor geometry owned
    by the aggregate's dense table — ALL device ARGUMENTS, never statics,
    so a re-anchor (even onto a different table size) reuses the compiled
    program; ``prep`` is the static (n_keys, agg plane spec). Every
    computation mirrors _DenseAggState._fold_host_arrays bit-for-bit:
    same masks, same clip arithmetic, same identities."""
    from auron_tpu.ops import segments as S

    sel, values, validity, outs = _trace_steps(dev, steps)
    n_keys, aggs = prep
    idx = jnp.zeros(dev.sel.shape, jnp.int64)
    any_l, mn_l, mx_l = [], [], []
    for i in range(n_keys):
        kv = outs[i]
        v64 = kv.values.astype(jnp.int64)
        ok = sel & kv.validity
        off = jnp.where(
            kv.validity, jnp.clip(v64, bases[i], his[i]) - bases[i] + 1, 0
        )
        idx = idx + off * strides[i]
        any_l.append(jnp.any(ok))
        mn_l.append(jnp.min(jnp.where(ok, v64, jnp.int64(_GUARD_HI))))
        mx_l.append(jnp.max(jnp.where(ok, v64, jnp.int64(-_GUARD_HI))))
    idx = jnp.where(sel, jnp.clip(idx, 0, size - 1), size).astype(jnp.int32)
    ev = Evaluator(T.Schema())  # casts only (mirrors _keys_and_inputs)
    planes: list[tuple] = []
    for spec in aggs:
        func = spec[0]
        if func == "count_star":
            planes.append(())
            continue
        cv = outs[spec[1]]
        if func == "count":
            planes.append((sel & cv.validity,))
            continue
        if func in ("sum", "avg"):
            _, _, sum_dt, kind = spec
            cvv = ev._cast(cv, sum_dt)
            ok = sel & cvv.validity
            if kind == "f":
                vm = jnp.where(ok, cvv.values.astype(jnp.float64), 0.0)
            else:
                vm = jnp.where(ok, cvv.values.astype(jnp.int64), jnp.int64(0))
            planes.append((vm, ok))
        else:  # min / max
            _, _, acc_name = spec
            accdt = np.dtype(acc_name)
            ok = sel & cv.validity
            ident = S._max_identity(accdt) if func == "min" else S._min_identity(accdt)
            vm = jnp.where(ok, cv.values, ident).astype(accdt)
            planes.append((vm, ok))
    guards = (jnp.stack(any_l), jnp.stack(mn_l), jnp.stack(mx_l))
    return sel, values, validity, (idx, guards, tuple(planes))


@_partial(jax.jit, static_argnames=("steps", "emit", "probe"))
def _stage_program_probe(dev, lut, lut_base, bwords, n_live, pack_args,
                         exists_lut, bvals, bmasks, *, steps: tuple,
                         emit: str, probe: tuple):
    """Stage program variant for segments feeding a hash-join probe: in the
    SAME compiled program as the filter/project work, run the probe
    prologue — key evaluation, canonical-word packing, the unique/existence
    hash-map lookup and (per ``take``) the build-row gather or the
    predicted compact-take — mirroring ``exec/joins/driver.py``'s eager
    chain (``_pack_probe_jit`` -> ``_unique_probe_jit`` ->
    ``_gather_build_jit`` / ``_unique_compact_take_pred_jit``) bit-for-bit.

    Build-side state (``lut``/``bwords``/``n_live``/pack ranges/build
    columns) arrives as DEVICE ARGUMENTS published at runtime by the join
    exec (ProbePrepLink), so a fresh build — even a different one — reuses
    the compiled program; ``probe`` is the static half:
    (key_exprs, key_schema, key_kinds, use_lut, probe_outer, bcap, packed,
    pcol_ids, take) with take one of ("probe",) | ("gather",) |
    ("compact", out_cap) | ("exists",)."""
    from auron_tpu.columnar.batch import compaction_index
    from auron_tpu.exec.joins import core as jcore

    sel, values, validity, _ = _trace_steps(dev, steps)
    (key_exprs, key_schema, kinds, use_lut, probe_outer, bcap, packed,
     pcol_ids, take) = probe
    b = Batch(key_schema, DeviceBatch(sel, values, validity),
              (None,) * len(key_schema.fields))
    ev = Evaluator(key_schema, partition_id=0, row_offset=0, resources={})
    memo: dict = {}
    kcvs = [ev._eval(e, b, memo) for e in key_exprs]
    if packed:
        # multi-key packing with the build's ranges (driver: _pack_probe_jit
        # then a single synthetic INT64 key column)
        w0, v0 = jcore._canon_words(kcvs)
        mins, maxs, shifts = pack_args
        pw, pv = jcore._pack_probe_words_jit(tuple(w0), v0, mins, maxs, shifts)
        probe_words = [jnp.where(pv, pw, jnp.uint64(0))]
        pvalid = pv
    else:
        probe_words, pvalid = jcore._canon_words_traced(
            tuple(cv.values for cv in kcvs),
            tuple(cv.validity for cv in kcvs), kinds,
        )
    ok_base = sel & (pvalid if pvalid is not None else jnp.ones_like(sel))
    if take[0] == "exists":
        # duplicate-tolerant existence LUT (driver: _probe_exists_jit)
        size = exists_lut.shape[0]
        eidx = probe_words[0].view(jnp.int64) - lut_base
        in_range = (eidx >= 0) & (eidx < size)
        hit = exists_lut[jnp.clip(eidx, 0, size - 1).astype(jnp.int32)]
        out = (ok_base & in_range & hit,)
        if emit == "cols":
            return sel, values, validity, out
        return sel, out
    bi, ok = jcore._probe_unique_ops(
        probe_words, ok_base, lut if use_lut else None, lut_base,
        list(bwords), n_live, bcap,
    )
    sel_out = sel if probe_outer else (sel & ok)
    live = jnp.sum(sel_out.astype(jnp.int32))
    if take[0] == "probe":
        out = (bi, ok, sel_out, live)
    elif take[0] == "gather":
        bv = tuple(v[bi] for v in bvals)
        bm = tuple(m[bi] & ok for m in bmasks)
        out = (bi, ok, sel_out, live, bv, bm)
    else:  # ("compact", out_cap) — the predicted sync-free take
        out_cap = take[1]
        idx, new_sel = compaction_index(sel_out, out_cap)
        c_pvals = tuple(values[c][idx] for c in pcol_ids)
        c_pmasks = tuple(validity[c][idx] & new_sel for c in pcol_ids)
        c_bi = bi[idx]
        c_ok = ok[idx] & new_sel
        out_bvals = tuple(v[c_bi] for v in bvals)
        out_bmasks = tuple(m[c_bi] & c_ok for m in bmasks)
        out = (bi, ok, sel_out, live,
               (c_pvals, c_pmasks, out_bvals, out_bmasks, new_sel))
    if emit == "cols":
        return sel, values, validity, out
    return sel, out


@_partial(jax.jit, static_argnames=("steps", "emit", "shuffle"))
def _stage_program_shuffle(dev, rr_start, *, steps: tuple, emit: str,
                           shuffle: tuple):
    """Stage program variant for segments feeding a shuffle writer: in the
    SAME compiled program as the filter/project work, compute the per-row
    partition ids (partitioning.partition_ids_traced — the eager policy
    minus the pallas fast path, bit-identical ids) and, on the device
    clustering substrate, the pid-clustered gather + per-partition counts
    (writer.cluster_rows — the one clustering policy the host fallback
    shares). ``shuffle`` is the static (spec, schema, n_out, mode) with
    mode "device" (clustered batch + counts ride the payload) or "host"
    (only the pids ride; the writer's numpy path clusters host-side)."""
    from auron_tpu.exec.shuffle.partitioning import partition_ids_traced
    from auron_tpu.exec.shuffle.writer import cluster_rows

    sel, values, validity, _ = _trace_steps(dev, steps)
    spec, schema, n_out, mode = shuffle
    pids = partition_ids_traced(
        spec, schema, n_out, sel, values, validity, rr_start
    )
    if mode == "host":
        extra = (pids,)
    else:
        out_dev, counts = cluster_rows(
            DeviceBatch(sel, values, validity), pids, n_out
        )
        extra = (out_dev, counts)
    if emit == "cols":
        return sel, values, validity, extra
    return sel, extra


class ProbePrepLink:
    """Anchor hand-off from a hash-join exec to the fused stage feeding its
    probe side. The join publishes once its build is prepared (device
    arrays + host ints of the build layout, the per-stream
    UniqueProbePipeline, and the compact-vs-dense choice); the stage then
    runs the probe prologue inside its program and attaches a
    ProbePrepPayload to each emitted batch. Same thread-model as
    DensePrepLink: stage and join share the task pump thread, the lock
    guards foreign observers only. The payload carries the BUILD IT WAS
    COMPUTED UNDER — the driver refuses a payload whose build is not the
    one it is probing (identity check), falling back to the eager
    prologue bit-identically."""

    def __init__(self):
        self._lock = threading.Lock()
        self._anchor: dict | None = None

    def publish(self, **anchor) -> None:
        with self._lock:
            self._anchor = anchor

    def clear(self) -> None:
        with self._lock:
            self._anchor = None

    def snapshot(self) -> dict | None:
        with self._lock:
            return self._anchor


class ProbePrepPayload:
    """One probe batch's stage-computed prologue results riding to the join
    driver (attached to the Batch as ``_probe_prep``). ``take`` names the
    eager twin the stage replaced: "probe" (lookup only — the driver's
    blocking seed path finishes), "gather" / "gather_pred" (build columns
    gathered at probe width; non-compact emit vs predicted-dense window
    push), "compact" (the predicted compact-take, ``taken`` =
    _unique_compact_take_pred_jit's output tuple), "exists"
    (existence-LUT probe flags)."""

    __slots__ = ("build", "kind", "take", "pred_cap", "bi", "ok", "sel_out",
                 "live", "bvals", "bmasks", "taken", "probe_matched")

    def __init__(self, build, kind, take, pred_cap=None, bi=None, ok=None,
                 sel_out=None, live=None, bvals=None, bmasks=None,
                 taken=None, probe_matched=None):
        self.build = build
        self.kind = kind
        self.take = take
        self.pred_cap = pred_cap
        self.bi = bi
        self.ok = ok
        self.sel_out = sel_out
        self.live = live
        self.bvals = bvals
        self.bmasks = bmasks
        self.taken = taken
        self.probe_matched = probe_matched


class ShufflePrepPayload:
    """One batch's stage-computed repartition riding to the shuffle writer
    (attached as ``_shuffle_prep``): mode "device" carries the
    pid-clustered DeviceBatch + per-partition counts, mode "host" carries
    the partition ids (the writer's numpy path clusters host-side). The
    writer validates n_out and the substrate policy before consuming —
    a mismatch falls back to the eager repartition bit-identically."""

    __slots__ = ("n_out", "mode", "pids", "clustered_dev", "counts")

    def __init__(self, n_out, mode, pids=None, clustered_dev=None, counts=None):
        self.n_out = n_out
        self.mode = mode
        self.pids = pids
        self.clustered_dev = clustered_dev
        self.counts = counts


class DensePrepLink:
    """Anchor hand-off from a dense partial aggregate to the fused stage
    feeding it. Stage and aggregate run on the SAME task pump thread (the
    stage generator resumes inside the aggregate's pull), so publish /
    snapshot / clear never race; the lock is defense against foreign
    observers (memory-manager polls) only. ``epoch`` increments on every
    re-anchor — a payload prepped under a stale anchor is refused by the
    aggregate at submission and its batch folds through the raw path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._anchor: dict | None = None

    def publish(self, **anchor) -> None:
        with self._lock:
            self._anchor = anchor

    def clear(self) -> None:
        with self._lock:
            self._anchor = None

    def snapshot(self) -> dict | None:
        with self._lock:
            return self._anchor


class DensePrepPayload:
    """One batch's device-resident prep planes riding from the fused stage
    to the dense aggregate (attached to the Batch as ``_dense_prep``).
    Guard comparisons use the ANCHOR THE PLANES WERE COMPUTED UNDER
    (bases/his/dims captured here), never the aggregate's current one."""

    __slots__ = ("epoch", "bases", "his", "dims", "size", "sel", "idx",
                 "guards", "planes")

    def __init__(self, epoch, bases, his, dims, size, sel, idx, guards, planes):
        self.epoch = epoch
        self.bases = bases
        self.his = his
        self.dims = dims
        self.size = size
        self.sel = sel
        self.idx = idx
        self.guards = guards
        self.planes = planes

    def tree(self):
        return (self.sel, self.idx, self.guards, self.planes)


# -- compile accounting: the retrace guard's evidence (tools/perfcheck.py) --

_FUSE_LOCK = threading.Lock()
_SEEN_PROGRAMS: set = set()  # segment signatures
_SEEN_TRACES: set = set()  # (segment signature, capacity bucket)
_SEEN_BUCKETS: set = set()  # capacity buckets observed (any segment)
_STATS = {"segments": 0, "programs": 0, "compiles": 0, "buckets": 0,
          "probe_segments": 0, "writer_segments": 0}


def fusion_stats() -> dict:
    """Snapshot of fused-segment accounting: ``segments`` = FusedStageExec
    instances built (``probe_segments`` / ``writer_segments`` = the subset
    carrying a join-probe / shuffle-repartition extension), ``programs`` =
    distinct segment signatures dispatched, ``buckets`` = distinct
    capacity buckets observed, ``compiles`` = distinct (signature,
    capacity-bucket) traces — the number perfcheck's retrace guard bounds
    by programs x buckets and requires FLAT across a replay."""
    with _FUSE_LOCK:
        return dict(_STATS)


def reset_fusion_stats() -> None:
    with _FUSE_LOCK:
        _SEEN_PROGRAMS.clear()
        _SEEN_TRACES.clear()
        _SEEN_BUCKETS.clear()
        for k in _STATS:
            _STATS[k] = 0


def _note_dispatch(sig, capacity: int) -> bool:
    """Record one program dispatch; True when it is a NEW (signature,
    bucket) trace — i.e. a compile, not a cache hit."""
    with _FUSE_LOCK:
        if sig not in _SEEN_PROGRAMS:
            _SEEN_PROGRAMS.add(sig)
            _STATS["programs"] += 1
        if capacity not in _SEEN_BUCKETS:
            _SEEN_BUCKETS.add(capacity)
            _STATS["buckets"] = len(_SEEN_BUCKETS)
        key = (sig, capacity)
        if key in _SEEN_TRACES:
            return False
        _SEEN_TRACES.add(key)
        _STATS["compiles"] += 1
        return True


# ---------------------------------------------------------------------------
# the fused operator
# ---------------------------------------------------------------------------


# auronlint: thread-owned -- one fused operator per query/stream plan instance; its link/prep memo fields are touched only by the single thread driving that plan's batch stream (task pump, serving handler, or stream pump — never two at once)
class FusedStageExec(ExecOperator):
    """One pipeline segment compiled as a single per-batch XLA program.

    Built only by ``fuse_exec_tree`` — it carries the segment's static
    description precomputed by ``_plan_segment``:

    - ``steps``: the static half of the program cache key;
    - ``out_stamp``: schema to stamp on emitted batches (None = the input
      batch's schema rides through, exactly like FilterExec);
    - ``dict_src``: per-output-column input index for dictionary
      passthrough (None = identity — all input dictionaries ride through);
    - ``op_shares``: (operator name, cost weight) per constituent operator,
      the proportional split of fused-program wall time back into
      per-operator metric/span accounting.
    """

    def __init__(self, child: ExecOperator, steps: tuple, out_stamp,
                 dict_src, op_shares: tuple, schema: T.Schema):
        super().__init__([child], schema)
        self.steps = steps
        self.out_stamp = out_stamp
        self.dict_src = dict_src
        self.op_shares = op_shares
        self.has_project = any(s[0] == "project" for s in steps)
        #: set by _try_prefuse_agg when the consumer is a dense-eligible
        #: partial aggregate: once the aggregate anchors its table, the
        #: stage compiles the dense fold's guard/index/mask prep into the
        #: same program (_stage_program_prep)
        self.dense_link: DensePrepLink | None = None
        self._prep_nkeys = 0
        self._prep_aggs: tuple = ()
        #: set by the probe-side rewrite when the consumer is a hash join:
        #: once the join publishes its prepared build, the stage compiles
        #: the probe prologue into the same program (_stage_program_probe)
        self.probe_link: ProbePrepLink | None = None
        self._probe_keys: tuple = ()
        self._probe_kinds: tuple = ()
        self._probe_outer = False
        self._probe_pcols: tuple = ()
        #: set by the writer-side rewrite: (spec, schema, n_out) — the
        #: repartition rides the stage program (_stage_program_shuffle)
        self.shuffle: tuple | None = None
        with _FUSE_LOCK:
            _STATS["segments"] += 1

    def attach_dense_link(self, link: DensePrepLink, n_keys: int,
                          aggs_spec: tuple) -> None:
        self.dense_link = link
        self._prep_nkeys = n_keys
        self._prep_aggs = aggs_spec
        # the prep arithmetic is per-batch aggregate work: charge its cost
        # share to the aggregate's name in the proportional split
        extra = n_keys * 4 + len(aggs_spec) * 2
        self.op_shares = tuple(
            (nm, w + extra if nm == "HashAggExec" else w)
            for nm, w in self.op_shares
        )

    def attach_probe_link(self, link: ProbePrepLink, key_exprs: tuple,
                          key_kinds: tuple, probe_outer: bool,
                          pcol_ids: tuple, op_name: str, cost: int) -> None:
        """Arm the stage as a join-probe prologue carrier. The probe work's
        cost share is charged to the JOIN's operator name — fused-program
        wall nanos spent on the lookup/gather surface under the join in
        top_ops, exactly where the eager prologue books them."""
        self.probe_link = link
        self._probe_keys = key_exprs
        self._probe_kinds = key_kinds
        self._probe_outer = probe_outer
        self._probe_pcols = pcol_ids
        self.op_shares = tuple(self.op_shares) + ((op_name, cost),)
        with _FUSE_LOCK:
            _STATS["probe_segments"] += 1

    def attach_shuffle(self, spec: tuple, schema, n_out: int,
                       cost: int) -> None:
        """Arm the stage as a shuffle-repartition carrier; the repartition
        cost share is charged to ShuffleWriterExec's name (the eager twin
        books it under the writer's repart_time)."""
        self.shuffle = (spec, schema, n_out)
        self.op_shares = tuple(self.op_shares) + (("ShuffleWriterExec", cost),)
        with _FUSE_LOCK:
            _STATS["writer_segments"] += 1

    def fused_op_names(self) -> list[str]:
        return [nm for nm, _ in self.op_shares]

    def _dispatch_probe(self, b: Batch, anchor: dict, node):
        """One probe-extended program dispatch: resolve the per-batch take
        mode from the pipeline's predictor (the SAME predict call the eager
        driver would make), run _stage_program_probe, and wrap the results
        as a ProbePrepPayload for the join driver."""
        from auron_tpu.columnar.batch import compaction_bucket

        kind = anchor["kind"]
        pred_cap = None
        take_tag = None
        if kind == "exists":
            take_prog = ("exists",)
        elif not anchor["compact"]:
            take_prog, take_tag = ("gather",), "gather"
        else:
            pipe = anchor["pipe"]
            pred = pipe.pred if pipe is not None else None
            pred_cap = pred.predict(b.capacity) if pred is not None else None
            if pred_cap is None:
                # seed/fallback: lookup only — the driver's blocking seed
                # read finishes the batch exactly as the eager path does
                take_prog, take_tag = ("probe",), "probe"
            elif compaction_bucket(pred_cap, b.capacity) is None:
                take_prog, take_tag = ("gather",), "gather_pred"
            else:
                take_prog, take_tag = ("compact", pred_cap), "compact"
        key_schema = self.out_stamp or self.children[0].schema
        cfg = (self._probe_keys, key_schema, self._probe_kinds,
               anchor["use_lut"], self._probe_outer, anchor["bcap"],
               anchor["packed"], self._probe_pcols, take_prog)
        emit = "cols" if self.has_project else "sel"
        if _note_dispatch((self.steps, "probe", cfg), b.capacity):
            node.add("stage_compiles", 1)
        res = _stage_program_probe(
            b.device, anchor["lut"], anchor["lut_base"], anchor["words"],
            anchor["n_live"], anchor["pack_args"], anchor["exists_lut"],
            anchor["bvals"], anchor["bmasks"],
            steps=self.steps, emit=emit, probe=cfg,
        )
        if emit == "cols":
            sel, values, validity, extra = res
            out = (sel, values, validity)
        else:
            sel, extra = res
            out = sel
        build = anchor["build"]
        if kind == "exists":
            payload = ProbePrepPayload(
                build, kind, "exists", probe_matched=extra[0]
            )
        elif take_prog[0] == "probe":
            bi, ok, sel_out, live = extra
            payload = ProbePrepPayload(
                build, kind, take_tag, pred_cap=None,
                bi=bi, ok=ok, sel_out=sel_out, live=live,
            )
        elif take_prog[0] == "gather":
            bi, ok, sel_out, live, bv, bm = extra
            payload = ProbePrepPayload(
                build, kind, take_tag, pred_cap=pred_cap,
                bi=bi, ok=ok, sel_out=sel_out, live=live, bvals=bv, bmasks=bm,
            )
        else:
            # taken mirrors _unique_compact_take_pred_jit's output layout:
            # (c_pvals, c_pmasks, bvals, bmasks, new_sel)
            bi, ok, sel_out, live, taken = extra
            payload = ProbePrepPayload(
                build, kind, take_tag, pred_cap=pred_cap,
                bi=bi, ok=ok, sel_out=sel_out, live=live, taken=taken,
            )
        return out, payload

    def _dispatch_shuffle(self, b: Batch, mode: str, rr_start, node):
        spec, schema, n_out = self.shuffle
        cfg = (spec, schema, n_out, mode)
        emit = "cols" if self.has_project else "sel"
        if _note_dispatch((self.steps, "shuffle", cfg), b.capacity):
            node.add("stage_compiles", 1)
        res = _stage_program_shuffle(
            b.device, rr_start, steps=self.steps, emit=emit, shuffle=cfg
        )
        if emit == "cols":
            sel, values, validity, extra = res
            out = (sel, values, validity)
        else:
            sel, extra = res
            out = sel
        if mode == "host":
            payload = ShufflePrepPayload(n_out, mode, pids=extra[0])
        else:
            payload = ShufflePrepPayload(
                n_out, mode, clustered_dev=extra[0], counts=extra[1]
            )
        return out, payload

    def _execute(self, partition: int, ctx: ExecutionContext):
        node = ctx.metrics
        emit = "cols" if self.has_project else "sel"
        sig = (self.steps, emit)
        shares = [(nm, w) for nm, w in self.op_shares if w > 0]
        total_w = sum(w for _, w in shares) or 1
        # per-constituent-operator metric nodes (index 0 is the child
        # operator's node, claimed by child_stream)
        attr = []
        for k, (nm, _) in enumerate(shares):
            c = node.child(1 + k)
            c.name = nm
            attr.append(c)
        rr_start = None
        shuffle_mode = None
        if self.shuffle is not None:
            from auron_tpu.exec.shuffle.writer import repartition_substrate

            # conf-stable per task: the SAME policy the eager writer
            # resolves, so fused and fallback repartition cannot diverge
            shuffle_mode = repartition_substrate(ctx.conf)
            rr_start = jnp.int32(ctx.partition_id % self.shuffle[2])
        for b in self.child_stream(0, partition, ctx):
            t_all = time.perf_counter_ns()
            anchor = self.dense_link.snapshot() if self.dense_link else None
            probe_anchor = (
                self.probe_link.snapshot() if self.probe_link else None
            )
            payload = None
            probe_payload = None
            shuffle_payload = None
            t0 = time.perf_counter_ns()
            if anchor is not None:
                prep_cfg = (self._prep_nkeys, self._prep_aggs)
                if _note_dispatch((self.steps, "prep", prep_cfg), b.capacity):
                    node.add("stage_compiles", 1)
                sel, values, validity, (idx, guards, planes) = _stage_program_prep(
                    b.device, anchor["bases_dev"], anchor["his_dev"],
                    anchor["strides_dev"], anchor["size_dev"],
                    steps=self.steps, prep=prep_cfg,
                )
                out = (sel, values, validity)
                payload = DensePrepPayload(
                    anchor["epoch"], anchor["bases"], anchor["his"],
                    anchor["dims"], anchor["size"], sel, idx, guards, planes,
                )
            elif probe_anchor is not None:
                out, probe_payload = self._dispatch_probe(b, probe_anchor, node)
            elif self.shuffle is not None:
                out, shuffle_payload = self._dispatch_shuffle(
                    b, shuffle_mode, rr_start, node
                )
            elif not self.steps:
                # bare prologue carrier with nothing published (e.g. the
                # join fell back to a build shape the stage can't serve):
                # pure passthrough, no program dispatch
                yield b
                continue
            else:
                if _note_dispatch(sig, b.capacity):
                    node.add("stage_compiles", 1)
                out = _stage_program(b.device, steps=self.steps, emit=emit)
            dt = time.perf_counter_ns() - t0
            node.add("fused_batches", 1)
            # split the stage's wall nanos back into per-operator timers,
            # handing the SAME split to the span timeline (obs.note_op) so
            # the <=5% span/metric cross-check holds through fusion
            spent = 0
            for i, ((nm, w), cnode) in enumerate(zip(shares, attr)):
                dt_i = dt - spent if i == len(shares) - 1 else dt * w // total_w
                spent += dt_i
                cnode.add("elapsed_compute", dt_i)
                obs.note_op(nm, "elapsed_compute", dt_i)
            if self.has_project:
                sel, values, validity = out
                dicts = tuple(
                    b.dicts[s] if s is not None else None for s in self.dict_src
                )
                nb = Batch(self.out_stamp, DeviceBatch(sel, values, validity), dicts)
            else:
                dev = DeviceBatch(out, b.device.values, b.device.validity)
                nb = Batch(self.out_stamp or b.schema, dev, b.dicts)
            if payload is not None:
                nb._dense_prep = payload
            if probe_payload is not None:
                nb._probe_prep = probe_payload
            if shuffle_payload is not None:
                nb._shuffle_prep = shuffle_payload
            # residual stage overhead (batch re-wrap, anchor snapshot,
            # payload assembly) not covered by the per-constituent split is
            # attributed to the STAGE node — top_ops must conserve nanos
            # (sum of splits + residual == stage wall; test_fusion pins it)
            total = time.perf_counter_ns() - t_all
            residual = max(total - dt, 0)
            node.add("stage_wall", total)
            node.add("elapsed_compute", residual)
            obs.note_op(node.name or "FusedStageExec", "elapsed_compute",
                        residual)
            yield nb


# ---------------------------------------------------------------------------
# segment planning
# ---------------------------------------------------------------------------

# import here (not at top) keeps plan/ free of a hard exec-module cycle
from auron_tpu.exec.basic import (  # noqa: E402
    FilterExec,
    ProjectExec,
    RenameColumnsExec,
)
from auron_tpu.exec.joins.core import key_kind as core_key_kind  # noqa: E402

_CHAIN_OPS = (FilterExec, ProjectExec, RenameColumnsExec)


def _op_safe(op: ExecOperator) -> bool:
    schema = op.children[0].schema
    if isinstance(op, FilterExec):
        return all(expr_trace_safe(p, schema) for p in op.predicates)
    if isinstance(op, ProjectExec):
        return all(
            expr_trace_safe(e, schema, allow_dict_out=True) for e in op.exprs
        )
    return isinstance(op, RenameColumnsExec)


def _collect_chain(op: ExecOperator):
    """Maximal stateless pipeline chain from ``op`` downward. Returns
    (ops top-down, source below the chain). Everything that is not a
    filter/project/rename is a blocking boundary: sorts, aggregations,
    join builds, shuffle writers/readers, unions, limits, generators —
    segments NEVER cross them."""
    ops = []
    cur = op
    while isinstance(cur, _CHAIN_OPS):
        ops.append(cur)
        cur = cur.children[0]
    return ops, cur


def _mirror_project_schema(exprs, names, schema: T.Schema) -> T.Schema:
    """The schema ProjectExec's batch_from_columns stamps on emitted
    batches (NULL-kind values surface as INT32 fields) — mirrored exactly
    so fused and eager streams are indistinguishable downstream."""
    fields = []
    for e, n in zip(exprs, names):
        dt = e.dtype_of(schema)
        fields.append(T.Field(n, dt if dt.kind != T.TypeKind.NULL else T.INT32, True))
    return T.Schema(tuple(fields))


# auronlint: thread-owned -- segments are built and mutated only inside one fuse_exec_tree call on the thread lowering that plan
class _Segment:
    """Static description of one fusable run, built bottom-up."""

    def __init__(self):
        self.steps: list = []
        self.op_shares: list = []
        self.stamp: T.Schema | None = None
        self.src: list | None = None  # None = identity passthrough
        self.n_ops = 0

    def add_filter(self, schema: T.Schema, preds: tuple) -> None:
        self.steps.append(("filter", schema, preds))
        self.op_shares.append(("FilterExec", sum(_expr_nodes(p) for p in preds)))
        self.n_ops += 1

    def add_project(self, schema: T.Schema, exprs: tuple, names,
                    op_name: str = "ProjectExec") -> None:
        self.steps.append(("project", schema, exprs))
        self.op_shares.append((op_name, sum(_expr_nodes(e) for e in exprs)))
        self.stamp = _mirror_project_schema(exprs, names, schema)
        prev = self.src
        self.src = [
            (e.index if prev is None else prev[e.index])
            if isinstance(e, ir.Column) else None
            for e in exprs
        ]
        self.n_ops += 1

    def add_rename(self, schema: T.Schema) -> None:
        # renames are pure schema bookkeeping: no step, no device work
        self.stamp = schema
        self.n_ops += 1

    def cost(self) -> int:
        """Estimated eager per-batch dispatches the fused program replaces:
        one per expression DAG node plus one per constituent operator
        (batch re-wrap + dispatch overhead)."""
        return sum(w for _, w in self.op_shares) + self.n_ops

    def build(self, child: ExecOperator, schema: T.Schema) -> FusedStageExec:
        return FusedStageExec(
            child,
            tuple(self.steps),
            self.stamp,
            None if self.src is None else tuple(self.src),
            tuple(self.op_shares),
            schema,
        )


def _plan_segment(ops_top_down: list) -> _Segment:
    seg = _Segment()
    for o in reversed(ops_top_down):
        schema = o.children[0].schema
        if isinstance(o, FilterExec):
            seg.add_filter(schema, tuple(o.predicates))
        elif isinstance(o, ProjectExec):
            seg.add_project(schema, tuple(o.exprs), o.names)
        else:
            seg.add_rename(o.schema)
    return seg


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------


def _should_fuse(cost: int, conf: Configuration, knob=FUSE_ENABLE) -> bool:
    """The fuse-vs-materialize decision (docs/fusion.md): explicit on/off
    win; auto fuses on accelerators always (dispatch round-trips dominate)
    and on XLA:CPU only when the eager path's estimated dispatch count
    reaches exec.fuse.min.ops — the substrate-dependent selection PR 3
    measured for the operator-scope knobs. ``knob`` selects the tri-state
    governing a stage extension (exec.fuse.probe / exec.fuse.shuffle)."""
    accel = jax.default_backend() != "cpu"
    return resolve_tri(
        conf.get(knob), accel or cost >= conf.get(FUSE_MIN_OPS)
    )


def _safe_runs(ops: list) -> list:
    """Partition a chain (top-down) into maximal runs tagged fusable or
    not: a single host-evaluated expression splits the segment around it
    rather than killing the whole chain."""
    runs: list[tuple[bool, list]] = []
    for o in ops:
        ok = _op_safe(o)
        if runs and runs[-1][0] == ok:
            runs[-1][1].append(o)
        else:
            runs.append((ok, [o]))
    return runs


def _rebuild_chain(runs: list, bottom: ExecOperator, conf: Configuration) -> ExecOperator:
    """Reassemble a chain over ``bottom``, fusing each fusable run that
    passes the cost model and keeping the others' original operators."""
    cur = bottom
    for ok, run in reversed(runs):
        seg = _plan_segment(run) if ok else None
        if seg is not None and seg.steps and _should_fuse(seg.cost(), conf):
            cur = seg.build(cur, run[0].schema)
        else:
            for o in reversed(run):
                o.children[0] = cur
                cur = o
    return cur


def _try_prefuse_agg(agg, conf: Configuration):
    """Extend the segment THROUGH a partial-mode HashAggExec: compile the
    chain below it plus the agg's grouping/argument expressions into one
    stage program and rewrite the aggregate over bare column refs. Returns
    the rebuilt aggregate, or None when the shape doesn't qualify (the
    normal chain pass then runs below the untouched aggregate)."""
    from auron_tpu.exec.agg_exec import AggExpr, HashAggExec

    in_schema = agg.children[0].schema
    exprs = [g for g, _ in agg.groupings] + [
        a.expr for a, _ in agg.aggs if a.expr is not None
    ]
    if not exprs:
        return None
    if not all(expr_trace_safe(e, in_schema, allow_dict_out=True) for e in exprs):
        return None
    ops, source = _collect_chain(agg.children[0])
    runs = _safe_runs(ops)
    top_run = runs[0][1] if runs and runs[0][0] else []
    rest = runs[1:] if top_run else runs
    names = [n for _, n in agg.groupings] + [
        n for a, n in agg.aggs if a.expr is not None
    ]
    seg = _plan_segment(top_run)
    seg.add_project(in_schema, tuple(exprs), names, op_name="HashAggExec")
    if not _should_fuse(seg.cost(), conf):
        return None

    new_groupings = [
        (ir.Column(i, n), n) for i, (_, n) in enumerate(agg.groupings)
    ]
    k = len(agg.groupings)
    new_aggs = []
    for a, n in agg.aggs:
        if a.expr is None:
            new_aggs.append((AggExpr(a.func, None, udaf=a.udaf), n))
        else:
            new_aggs.append((AggExpr(a.func, ir.Column(k, n), udaf=a.udaf), n))
            k += 1
    # validate the rewrite BEFORE any side effects (segment accounting,
    # chain rewiring): probe the rebuilt aggregate's typing against a
    # schema-only carrier of the stage's emitted layout
    from auron_tpu.exec.basic import EmptyPartitionsExec

    probe = HashAggExec(
        EmptyPartitionsExec(seg.stamp, 1), new_groupings, new_aggs, agg.mode
    )
    if probe.schema != agg.schema or probe.inter_schema != agg.inter_schema:
        # typing drift (e.g. a NULL-kind grouping literal surfacing as
        # INT32 through the stage): materialize instead of fusing wrong
        return None
    below = _rebuild_chain(rest, _visit(source, conf), conf)
    fused = seg.build(below, seg.stamp)
    new_agg = HashAggExec(fused, new_groupings, new_aggs, agg.mode)
    spec = _dense_prep_spec(new_agg)
    if spec is not None:
        link = DensePrepLink()
        fused.attach_dense_link(link, new_agg.n_keys, spec)
        new_agg._dense_prep_link = link
    return new_agg


def _dense_prep_spec(agg) -> tuple | None:
    """Static per-agg plane spec for _stage_program_prep, or None when the
    aggregate can't run its dense fold off stage-prepped planes. Column
    indices address the stage's OUTPUT layout (keys first, then aggregate
    arguments in declaration order). Publication stays runtime-gated: the
    aggregate only publishes an anchor when its dense table is live AND
    the host-scatter substrate is chosen, so attaching a link to a plan
    that ends up on the device-scatter path costs nothing."""
    from auron_tpu.exec.agg_exec import is_wide_sum, sum_type

    if not agg._dense_eligible():
        return None
    spec = []
    col = agg.n_keys
    for (a, _), in_t in zip(agg.aggs, agg._agg_input_types):
        if a.func == "count_star":
            spec.append(("count_star",))
            continue
        if a.func == "count":
            spec.append(("count", col))
        elif a.func in ("sum", "avg"):
            if is_wide_sum(in_t):
                return None  # _dense_eligible already excludes; stay safe
            st = sum_type(in_t)
            kind = "f" if st.is_float else "i"
            spec.append((a.func, col, st, kind))
        elif a.func in ("min", "max"):
            spec.append((a.func, col, np.dtype(in_t.physical_dtype().name).name))
        else:
            return None
        col += 1
    return tuple(spec)


def _fallback_chain(child: ExecOperator, conf: Configuration) -> ExecOperator:
    """The ordinary chain-fusion pass over a prologue-stage candidate that
    didn't qualify — the SAME step `_visit` takes for a bare chain, kept
    in one place so the probe/writer fallbacks can't diverge from it."""
    if isinstance(child, _CHAIN_OPS):
        ops, source = _collect_chain(child)
        return _rebuild_chain(_safe_runs(ops), _visit(source, conf), conf)
    return _visit(child, conf)


def _chain_segment_below(child: ExecOperator, conf: Configuration):
    """Shared prologue-stage planning: split the chain under ``child`` into
    (segment for the TOP fusable run, remaining runs, source below) — the
    same top-run carve-out _try_prefuse_agg performs. The top segment may
    be EMPTY (child is not a chain op, or its top run is unsafe): the
    extension then rides a bare carrier stage with steps=()."""
    ops, source = _collect_chain(child)
    runs = _safe_runs(ops)
    top_run = runs[0][1] if runs and runs[0][0] else []
    rest = runs[1:] if top_run else runs
    seg = _plan_segment(top_run)
    out_schema = top_run[0].schema if top_run else child.schema
    return seg, rest, source, out_schema


def _probe_side_rewrite(join, child: ExecOperator,
                        conf: Configuration) -> ExecOperator:
    """Extend the fused stage feeding ``join``'s probe side through the
    probe prologue (docs/fusion.md): the stage carries a ProbePrepLink the
    join publishes its prepared build into at run time; until (or unless)
    a publishable build exists the stage is a plain segment (or a zero-
    cost passthrough). Falls back to the ordinary chain pass when the
    join's shape can't run off stage-prepped probes."""
    from auron_tpu.exec.joins.bhj import BroadcastHashJoinExec

    def fallback():
        return _fallback_chain(child, conf)

    d = join.driver
    # a probe child that is itself a BHJ is (potentially) a fused-chain
    # stack member (exec/joins/chain.py): never wedge a stage between
    # stacked joins — the chain's own fused probe already covers them
    if isinstance(child, BroadcastHashJoinExec):
        return fallback()
    if d.condition is not None:
        return fallback()  # residual conditions assemble pair batches
    probe_keys = d.left_keys if d.probe_is_left else d.right_keys
    seg, rest, source, out_schema = _chain_segment_below(child, conf)
    # keys must evaluate inside the program over the stage's emitted
    # layout: trace-safe, no dict-encoded or nested operands
    if not probe_keys or not all(
        expr_trace_safe(k, out_schema) for k in probe_keys
    ):
        return fallback()
    proj, pcol_ids, bcol_ids = d._unique_probe_cfg()
    probe_cost = (
        sum(_expr_nodes(k) for k in probe_keys) + 6 + len(bcol_ids)
    )
    if not _should_fuse(seg.cost() + probe_cost, conf, knob=FUSE_PROBE):
        return fallback()
    below = _rebuild_chain(rest, _visit(source, conf), conf)
    fused = seg.build(below, out_schema)
    link = ProbePrepLink()
    kinds = tuple(
        core_key_kind(k.dtype_of(out_schema)) for k in probe_keys
    )
    fused.attach_probe_link(
        link, tuple(probe_keys), kinds, d.probe_outer, tuple(pcol_ids),
        type(join).__name__, probe_cost,
    )
    join._probe_prep_link = link
    return fused


def _writer_side_rewrite(writer, child: ExecOperator,
                         conf: Configuration) -> ExecOperator:
    """Extend the fused stage feeding a shuffle writer through the
    repartition prologue: partition-id hashing (and device pid-clustering)
    ride the stage program; the writer consumes the ShufflePrepPayload
    instead of re-deriving both (docs/fusion.md)."""

    def fallback():
        return _fallback_chain(child, conf)

    spec = writer.partitioning.fuse_spec(child.schema)
    if spec is None:
        return fallback()
    seg, rest, source, out_schema = _chain_segment_below(child, conf)
    key_exprs = spec[1] if spec[0] == "hash" else ()
    if not all(expr_trace_safe(e, out_schema) for e in key_exprs):
        return fallback()
    n_out = writer.partitioning.num_partitions
    shuffle_cost = sum(_expr_nodes(e) for e in key_exprs) + 4 + len(out_schema)
    if not _should_fuse(seg.cost() + shuffle_cost, conf, knob=FUSE_SHUFFLE):
        return fallback()
    below = _rebuild_chain(rest, _visit(source, conf), conf)
    fused = seg.build(below, out_schema)
    fused.attach_shuffle(spec, out_schema, n_out, shuffle_cost)
    return fused


def _visit(op: ExecOperator, conf: Configuration) -> ExecOperator:
    from auron_tpu.exec.agg_exec import HashAggExec
    from auron_tpu.exec.joins.bhj import BroadcastHashJoinExec
    from auron_tpu.exec.shuffle.writer import (
        RssShuffleWriterExec,
        ShuffleWriterExec,
    )

    if (
        isinstance(op, HashAggExec)
        and op.mode == "partial"
        and conf.get(FUSE_AGG_INPUTS)
    ):
        new = _try_prefuse_agg(op, conf)
        if new is not None:
            return new
    if isinstance(op, BroadcastHashJoinExec):
        pc = 1 if op.build_side == "left" else 0
        op.children[1 - pc] = _visit(op.children[1 - pc], conf)
        op.children[pc] = _probe_side_rewrite(op, op.children[pc], conf)
        return op
    if isinstance(op, (ShuffleWriterExec, RssShuffleWriterExec)):
        op.children[0] = _writer_side_rewrite(op, op.children[0], conf)
        return op
    if isinstance(op, _CHAIN_OPS):
        ops, source = _collect_chain(op)
        return _rebuild_chain(_safe_runs(ops), _visit(source, conf), conf)
    for i, c in enumerate(op.children):
        op.children[i] = _visit(c, conf)
    return op


def fuse_exec_tree(plan: ExecOperator, conf: Configuration) -> ExecOperator:
    """Apply whole-stage fusion to an instantiated exec tree. A no-op when
    ``exec.fuse.enable`` resolves off for every segment; bit-identical
    results either way (tests/test_fusion.py fuzzes the equivalence)."""
    if not resolve_tri(conf.get(FUSE_ENABLE), True):
        return plan
    return _visit(plan, conf)

"""Whole-stage fusion: pipeline segments -> single XLA programs.

PR 3 proved the thesis at operator scope (``exec.filter.fuse``: one jitted
program per predicate chain). This pass generalizes it Flare-style (PAPERS
1703.08219): a **segment finder** walks the instantiated exec tree and
identifies maximal scan->filter->project(->partial-agg-input) pipeline
segments between blocking boundaries (sort, agg state, join build, shuffle,
collect — every operator that is not a stateless row-pipeline stage), a
**stage compiler** traces each segment's per-batch work into ONE jitted XLA
program keyed on ``(schema, segment signature, compaction bucket)``, and a
**cost model** chooses fuse vs. materialize per segment (SystemML-style
selection, PAPERS 1801.00829): operator cost = estimated eager dispatches
(expression DAG nodes + per-operator overhead), substrate-resolved through
``utils.config.resolve_tri`` — accelerators always fuse, XLA:CPU fuses only
segments whose eager cost reaches ``exec.fuse.min.ops`` (the PR-3-measured
CPU exception: fused chains beat eager dispatch there too).

Fusion is an EXEC-TREE rewrite (``task_from_proto`` applies it after column
pruning): the protobuf plan, plan goldens and ``plan/explain`` output are
untouched, and results are bit-identical with the pass off
(``exec.fuse.enable=off`` — the A/B lever the fuzz suite and the perf gate
exercise).

Invariants the fused stage preserves (docs/fusion.md):

- R10 jit-boundary purity: the traced region is the same trace-safe
  expression machinery behind ``exec.filter.fuse`` (``exprs/eval.py``
  evaluated over a dict-less device batch); no conf reads, host transfers
  or captured-state mutation inside the trace (auronlint R10 checks the
  closure, R2 the cache-key discipline).
- Dictionary passthrough: a dict-encoded column may ride THROUGH a fused
  segment only as a bare column reference — its codes flow through the
  program, the host-side dictionary re-attaches on emission. Expressions
  that *transform* dictionaries (string compare/LIKE/casts) stay eager.
- Batch protocol: fused stages refine the selection mask exactly like
  FilterExec (no compaction inside the stage), so downstream compaction
  boundaries — including the selectivity predictor's mispredict repair —
  see the same batches they would without fusion, and emitted batches
  remain prefetchable through the async transfer window.
- Metric attribution: fused-program wall time is split back into
  per-operator MetricNode children (proportional to the cost model's
  per-operator weights), and the SAME split nanos are handed to the obs
  span timeline — ``top_ops`` and the <=5% span/metric cross-check see
  FilterExec/ProjectExec/HashAggExec, never one opaque stage.
"""

from __future__ import annotations

import threading
import time
from functools import partial as _partial

import jax
import jax.numpy as jnp
import numpy as np

from auron_tpu import obs
from auron_tpu import types as T
from auron_tpu.columnar.batch import Batch, DeviceBatch
from auron_tpu.exec.base import ExecOperator, ExecutionContext
from auron_tpu.exprs import Evaluator, ir
from auron_tpu.utils.config import (
    FUSE_AGG_INPUTS,
    FUSE_ENABLE,
    FUSE_MIN_OPS,
    Configuration,
    resolve_tri,
)

# ---------------------------------------------------------------------------
# trace safety
# ---------------------------------------------------------------------------

#: expression nodes whose evaluation is a pure jnp program over dict-free
#: operands — the exec.filter.fuse whitelist plus In (numeric membership is
#: a pure compare/or chain). Everything else (scalar funcs, host UDFs,
#: row-offset context, LIKE, subqueries) stays eager.
_FUSABLE_NODES = (
    ir.Literal, ir.Cast, ir.BinaryOp, ir.Not, ir.IsNull, ir.IsNotNull,
    ir.If, ir.Case, ir.Coalesce, ir.In,
)

_NESTED_KINDS = (T.TypeKind.LIST, T.TypeKind.MAP, T.TypeKind.STRUCT)


def expr_trace_safe(e: ir.Expr, schema: T.Schema, allow_dict_out: bool = False) -> bool:
    """True when evaluating ``e`` inside a jit over a dict-less batch is
    exactly the eager evaluation. ``allow_dict_out`` permits a BARE
    dict-encoded column reference (projection passthrough: codes flow
    through the program, the dictionary re-attaches host-side); computed
    dict-encoded results never fuse — their evaluation transforms host
    dictionaries. IsNull/IsNotNull over a bare column are safe even for
    dict columns (they read only the validity plane)."""
    if isinstance(e, ir.Column):
        dt = e.dtype_of(schema)
        return allow_dict_out or not (dt.is_dict_encoded or dt.kind in _NESTED_KINDS)
    if isinstance(e, (ir.IsNull, ir.IsNotNull)) and isinstance(e.child, ir.Column):
        return True
    if not isinstance(e, _FUSABLE_NODES):
        return False
    dt = e.dtype_of(schema)
    if dt.is_dict_encoded or dt.kind in _NESTED_KINDS:
        return False
    return all(expr_trace_safe(c, schema) for c in e.children())


def _expr_nodes(e: ir.Expr) -> int:
    return 1 + sum(_expr_nodes(c) for c in e.children())


# ---------------------------------------------------------------------------
# the stage program (ONE jit; cache key = static (steps, emit) + shapes)
# ---------------------------------------------------------------------------


def _trace_steps(dev: DeviceBatch, steps: tuple):
    """The shared traced step walk: apply ("filter", schema, predicates) /
    ("project", schema, exprs) stages in order; each step carries the
    ORIGINAL operator's input schema so expression typing is exactly the
    eager path's. Returns (sel, values, validity, final projection's
    ColumnVals or None). The common-subexpression memo is shared across
    consecutive steps over the same input columns and reset at every
    projection (which replaces the column planes)."""
    sel = dev.sel
    values, validity = dev.values, dev.validity
    outs = None
    memo: dict = {}
    for step in steps:
        kind, schema, exprs = step
        b = Batch(schema, DeviceBatch(sel, values, validity),
                  (None,) * len(schema.fields))
        ev = Evaluator(schema, partition_id=0, row_offset=0, resources={})
        if kind == "filter":
            for p in exprs:
                cv = ev._eval(p, b, memo)
                sel = sel & cv.validity & cv.values.astype(bool)
        else:
            outs = [ev._eval(e, b, memo) for e in exprs]
            values = tuple(cv.values for cv in outs)
            validity = tuple(cv.validity for cv in outs)
            memo = {}
    return sel, values, validity, outs


@_partial(jax.jit, static_argnames=("steps", "emit"))
def _stage_program(dev: DeviceBatch, *, steps: tuple, emit: str):
    """The whole segment's per-batch work as ONE compiled program.
    ``emit`` is "sel" (filter-only segment: the caller reuses the input
    columns) or "cols" (the final projection's columns are returned)."""
    sel, values, validity, _ = _trace_steps(dev, steps)
    if emit == "sel":
        return sel
    return sel, values, validity


# 2^62 sentinels for the per-key guard min/max reductions (ignored by the
# consumer unless the key saw a live valid row — the any_ok flag)
_GUARD_HI = (1 << 62)


@_partial(jax.jit, static_argnames=("steps", "prep"))
def _stage_program_prep(dev: DeviceBatch, bases, his, strides, size, *,
                        steps: tuple, prep: tuple):
    """Stage program variant for segments feeding a DENSE partial
    aggregate on the host-scatter substrate: in the SAME compiled program
    as the filter/project work, compute the dense fold's per-batch prep —
    the range-guard statistics, the packed slot index and the per-agg
    masked value planes — so the host keeps only the bincount
    scatter-reduces (the substrate choice PR 3 measured; the ~6 numpy
    passes of guard/index/mask arithmetic move into this one XLA pass).

    ``bases``/``his``/``strides``/``size`` are the anchor geometry owned
    by the aggregate's dense table — ALL device ARGUMENTS, never statics,
    so a re-anchor (even onto a different table size) reuses the compiled
    program; ``prep`` is the static (n_keys, agg plane spec). Every
    computation mirrors _DenseAggState._fold_host_arrays bit-for-bit:
    same masks, same clip arithmetic, same identities."""
    from auron_tpu.ops import segments as S

    sel, values, validity, outs = _trace_steps(dev, steps)
    n_keys, aggs = prep
    idx = jnp.zeros(dev.sel.shape, jnp.int64)
    any_l, mn_l, mx_l = [], [], []
    for i in range(n_keys):
        kv = outs[i]
        v64 = kv.values.astype(jnp.int64)
        ok = sel & kv.validity
        off = jnp.where(
            kv.validity, jnp.clip(v64, bases[i], his[i]) - bases[i] + 1, 0
        )
        idx = idx + off * strides[i]
        any_l.append(jnp.any(ok))
        mn_l.append(jnp.min(jnp.where(ok, v64, jnp.int64(_GUARD_HI))))
        mx_l.append(jnp.max(jnp.where(ok, v64, jnp.int64(-_GUARD_HI))))
    idx = jnp.where(sel, jnp.clip(idx, 0, size - 1), size).astype(jnp.int32)
    ev = Evaluator(T.Schema())  # casts only (mirrors _keys_and_inputs)
    planes: list[tuple] = []
    for spec in aggs:
        func = spec[0]
        if func == "count_star":
            planes.append(())
            continue
        cv = outs[spec[1]]
        if func == "count":
            planes.append((sel & cv.validity,))
            continue
        if func in ("sum", "avg"):
            _, _, sum_dt, kind = spec
            cvv = ev._cast(cv, sum_dt)
            ok = sel & cvv.validity
            if kind == "f":
                vm = jnp.where(ok, cvv.values.astype(jnp.float64), 0.0)
            else:
                vm = jnp.where(ok, cvv.values.astype(jnp.int64), jnp.int64(0))
            planes.append((vm, ok))
        else:  # min / max
            _, _, acc_name = spec
            accdt = np.dtype(acc_name)
            ok = sel & cv.validity
            ident = S._max_identity(accdt) if func == "min" else S._min_identity(accdt)
            vm = jnp.where(ok, cv.values, ident).astype(accdt)
            planes.append((vm, ok))
    guards = (jnp.stack(any_l), jnp.stack(mn_l), jnp.stack(mx_l))
    return sel, values, validity, (idx, guards, tuple(planes))


class DensePrepLink:
    """Anchor hand-off from a dense partial aggregate to the fused stage
    feeding it. Stage and aggregate run on the SAME task pump thread (the
    stage generator resumes inside the aggregate's pull), so publish /
    snapshot / clear never race; the lock is defense against foreign
    observers (memory-manager polls) only. ``epoch`` increments on every
    re-anchor — a payload prepped under a stale anchor is refused by the
    aggregate at submission and its batch folds through the raw path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._anchor: dict | None = None

    def publish(self, **anchor) -> None:
        with self._lock:
            self._anchor = anchor

    def clear(self) -> None:
        with self._lock:
            self._anchor = None

    def snapshot(self) -> dict | None:
        with self._lock:
            return self._anchor


class DensePrepPayload:
    """One batch's device-resident prep planes riding from the fused stage
    to the dense aggregate (attached to the Batch as ``_dense_prep``).
    Guard comparisons use the ANCHOR THE PLANES WERE COMPUTED UNDER
    (bases/his/dims captured here), never the aggregate's current one."""

    __slots__ = ("epoch", "bases", "his", "dims", "size", "sel", "idx",
                 "guards", "planes")

    def __init__(self, epoch, bases, his, dims, size, sel, idx, guards, planes):
        self.epoch = epoch
        self.bases = bases
        self.his = his
        self.dims = dims
        self.size = size
        self.sel = sel
        self.idx = idx
        self.guards = guards
        self.planes = planes

    def tree(self):
        return (self.sel, self.idx, self.guards, self.planes)


# -- compile accounting: the retrace guard's evidence (tools/perfcheck.py) --

_FUSE_LOCK = threading.Lock()
_SEEN_PROGRAMS: set = set()  # segment signatures
_SEEN_TRACES: set = set()  # (segment signature, capacity bucket)
_SEEN_BUCKETS: set = set()  # capacity buckets observed (any segment)
_STATS = {"segments": 0, "programs": 0, "compiles": 0, "buckets": 0}


def fusion_stats() -> dict:
    """Snapshot of fused-segment accounting: ``segments`` = FusedStageExec
    instances built, ``programs`` = distinct segment signatures dispatched,
    ``buckets`` = distinct capacity buckets observed, ``compiles`` =
    distinct (signature, capacity-bucket) traces — the number perfcheck's
    retrace guard bounds by programs x buckets and requires FLAT across a
    replay."""
    with _FUSE_LOCK:
        return dict(_STATS)


def reset_fusion_stats() -> None:
    with _FUSE_LOCK:
        _SEEN_PROGRAMS.clear()
        _SEEN_TRACES.clear()
        _SEEN_BUCKETS.clear()
        for k in _STATS:
            _STATS[k] = 0


def _note_dispatch(sig, capacity: int) -> bool:
    """Record one program dispatch; True when it is a NEW (signature,
    bucket) trace — i.e. a compile, not a cache hit."""
    with _FUSE_LOCK:
        if sig not in _SEEN_PROGRAMS:
            _SEEN_PROGRAMS.add(sig)
            _STATS["programs"] += 1
        if capacity not in _SEEN_BUCKETS:
            _SEEN_BUCKETS.add(capacity)
            _STATS["buckets"] = len(_SEEN_BUCKETS)
        key = (sig, capacity)
        if key in _SEEN_TRACES:
            return False
        _SEEN_TRACES.add(key)
        _STATS["compiles"] += 1
        return True


# ---------------------------------------------------------------------------
# the fused operator
# ---------------------------------------------------------------------------


class FusedStageExec(ExecOperator):
    """One pipeline segment compiled as a single per-batch XLA program.

    Built only by ``fuse_exec_tree`` — it carries the segment's static
    description precomputed by ``_plan_segment``:

    - ``steps``: the static half of the program cache key;
    - ``out_stamp``: schema to stamp on emitted batches (None = the input
      batch's schema rides through, exactly like FilterExec);
    - ``dict_src``: per-output-column input index for dictionary
      passthrough (None = identity — all input dictionaries ride through);
    - ``op_shares``: (operator name, cost weight) per constituent operator,
      the proportional split of fused-program wall time back into
      per-operator metric/span accounting.
    """

    def __init__(self, child: ExecOperator, steps: tuple, out_stamp,
                 dict_src, op_shares: tuple, schema: T.Schema):
        super().__init__([child], schema)
        self.steps = steps
        self.out_stamp = out_stamp
        self.dict_src = dict_src
        self.op_shares = op_shares
        self.has_project = any(s[0] == "project" for s in steps)
        #: set by _try_prefuse_agg when the consumer is a dense-eligible
        #: partial aggregate: once the aggregate anchors its table, the
        #: stage compiles the dense fold's guard/index/mask prep into the
        #: same program (_stage_program_prep)
        self.dense_link: DensePrepLink | None = None
        self._prep_nkeys = 0
        self._prep_aggs: tuple = ()
        with _FUSE_LOCK:
            _STATS["segments"] += 1

    def attach_dense_link(self, link: DensePrepLink, n_keys: int,
                          aggs_spec: tuple) -> None:
        self.dense_link = link
        self._prep_nkeys = n_keys
        self._prep_aggs = aggs_spec
        # the prep arithmetic is per-batch aggregate work: charge its cost
        # share to the aggregate's name in the proportional split
        extra = n_keys * 4 + len(aggs_spec) * 2
        self.op_shares = tuple(
            (nm, w + extra if nm == "HashAggExec" else w)
            for nm, w in self.op_shares
        )

    def fused_op_names(self) -> list[str]:
        return [nm for nm, _ in self.op_shares]

    def _execute(self, partition: int, ctx: ExecutionContext):
        node = ctx.metrics
        emit = "cols" if self.has_project else "sel"
        sig = (self.steps, emit)
        shares = [(nm, w) for nm, w in self.op_shares if w > 0]
        total_w = sum(w for _, w in shares) or 1
        # per-constituent-operator metric nodes (index 0 is the child
        # operator's node, claimed by child_stream)
        attr = []
        for k, (nm, _) in enumerate(shares):
            c = node.child(1 + k)
            c.name = nm
            attr.append(c)
        for b in self.child_stream(0, partition, ctx):
            anchor = self.dense_link.snapshot() if self.dense_link else None
            payload = None
            t0 = time.perf_counter_ns()
            if anchor is not None:
                prep_cfg = (self._prep_nkeys, self._prep_aggs)
                if _note_dispatch((self.steps, "prep", prep_cfg), b.capacity):
                    node.add("stage_compiles", 1)
                sel, values, validity, (idx, guards, planes) = _stage_program_prep(
                    b.device, anchor["bases_dev"], anchor["his_dev"],
                    anchor["strides_dev"], anchor["size_dev"],
                    steps=self.steps, prep=prep_cfg,
                )
                out = (sel, values, validity)
                payload = DensePrepPayload(
                    anchor["epoch"], anchor["bases"], anchor["his"],
                    anchor["dims"], anchor["size"], sel, idx, guards, planes,
                )
            else:
                if _note_dispatch(sig, b.capacity):
                    node.add("stage_compiles", 1)
                out = _stage_program(b.device, steps=self.steps, emit=emit)
            dt = time.perf_counter_ns() - t0
            node.add("fused_batches", 1)
            # split the stage's wall nanos back into per-operator timers,
            # handing the SAME split to the span timeline (obs.note_op) so
            # the <=5% span/metric cross-check holds through fusion
            spent = 0
            for i, ((nm, w), cnode) in enumerate(zip(shares, attr)):
                dt_i = dt - spent if i == len(shares) - 1 else dt * w // total_w
                spent += dt_i
                cnode.add("elapsed_compute", dt_i)
                obs.note_op(nm, "elapsed_compute", dt_i)
            if self.has_project:
                sel, values, validity = out
                dicts = tuple(
                    b.dicts[s] if s is not None else None for s in self.dict_src
                )
                nb = Batch(self.out_stamp, DeviceBatch(sel, values, validity), dicts)
                if payload is not None:
                    nb._dense_prep = payload
                yield nb
            else:
                dev = DeviceBatch(out, b.device.values, b.device.validity)
                yield Batch(self.out_stamp or b.schema, dev, b.dicts)


# ---------------------------------------------------------------------------
# segment planning
# ---------------------------------------------------------------------------

# import here (not at top) keeps plan/ free of a hard exec-module cycle
from auron_tpu.exec.basic import (  # noqa: E402
    FilterExec,
    ProjectExec,
    RenameColumnsExec,
)

_CHAIN_OPS = (FilterExec, ProjectExec, RenameColumnsExec)


def _op_safe(op: ExecOperator) -> bool:
    schema = op.children[0].schema
    if isinstance(op, FilterExec):
        return all(expr_trace_safe(p, schema) for p in op.predicates)
    if isinstance(op, ProjectExec):
        return all(
            expr_trace_safe(e, schema, allow_dict_out=True) for e in op.exprs
        )
    return isinstance(op, RenameColumnsExec)


def _collect_chain(op: ExecOperator):
    """Maximal stateless pipeline chain from ``op`` downward. Returns
    (ops top-down, source below the chain). Everything that is not a
    filter/project/rename is a blocking boundary: sorts, aggregations,
    join builds, shuffle writers/readers, unions, limits, generators —
    segments NEVER cross them."""
    ops = []
    cur = op
    while isinstance(cur, _CHAIN_OPS):
        ops.append(cur)
        cur = cur.children[0]
    return ops, cur


def _mirror_project_schema(exprs, names, schema: T.Schema) -> T.Schema:
    """The schema ProjectExec's batch_from_columns stamps on emitted
    batches (NULL-kind values surface as INT32 fields) — mirrored exactly
    so fused and eager streams are indistinguishable downstream."""
    fields = []
    for e, n in zip(exprs, names):
        dt = e.dtype_of(schema)
        fields.append(T.Field(n, dt if dt.kind != T.TypeKind.NULL else T.INT32, True))
    return T.Schema(tuple(fields))


class _Segment:
    """Static description of one fusable run, built bottom-up."""

    def __init__(self):
        self.steps: list = []
        self.op_shares: list = []
        self.stamp: T.Schema | None = None
        self.src: list | None = None  # None = identity passthrough
        self.n_ops = 0

    def add_filter(self, schema: T.Schema, preds: tuple) -> None:
        self.steps.append(("filter", schema, preds))
        self.op_shares.append(("FilterExec", sum(_expr_nodes(p) for p in preds)))
        self.n_ops += 1

    def add_project(self, schema: T.Schema, exprs: tuple, names,
                    op_name: str = "ProjectExec") -> None:
        self.steps.append(("project", schema, exprs))
        self.op_shares.append((op_name, sum(_expr_nodes(e) for e in exprs)))
        self.stamp = _mirror_project_schema(exprs, names, schema)
        prev = self.src
        self.src = [
            (e.index if prev is None else prev[e.index])
            if isinstance(e, ir.Column) else None
            for e in exprs
        ]
        self.n_ops += 1

    def add_rename(self, schema: T.Schema) -> None:
        # renames are pure schema bookkeeping: no step, no device work
        self.stamp = schema
        self.n_ops += 1

    def cost(self) -> int:
        """Estimated eager per-batch dispatches the fused program replaces:
        one per expression DAG node plus one per constituent operator
        (batch re-wrap + dispatch overhead)."""
        return sum(w for _, w in self.op_shares) + self.n_ops

    def build(self, child: ExecOperator, schema: T.Schema) -> FusedStageExec:
        return FusedStageExec(
            child,
            tuple(self.steps),
            self.stamp,
            None if self.src is None else tuple(self.src),
            tuple(self.op_shares),
            schema,
        )


def _plan_segment(ops_top_down: list) -> _Segment:
    seg = _Segment()
    for o in reversed(ops_top_down):
        schema = o.children[0].schema
        if isinstance(o, FilterExec):
            seg.add_filter(schema, tuple(o.predicates))
        elif isinstance(o, ProjectExec):
            seg.add_project(schema, tuple(o.exprs), o.names)
        else:
            seg.add_rename(o.schema)
    return seg


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------


def _should_fuse(cost: int, conf: Configuration) -> bool:
    """The fuse-vs-materialize decision (docs/fusion.md): explicit on/off
    win; auto fuses on accelerators always (dispatch round-trips dominate)
    and on XLA:CPU only when the eager path's estimated dispatch count
    reaches exec.fuse.min.ops — the substrate-dependent selection PR 3
    measured for the operator-scope knobs."""
    accel = jax.default_backend() != "cpu"
    return resolve_tri(
        conf.get(FUSE_ENABLE), accel or cost >= conf.get(FUSE_MIN_OPS)
    )


def _safe_runs(ops: list) -> list:
    """Partition a chain (top-down) into maximal runs tagged fusable or
    not: a single host-evaluated expression splits the segment around it
    rather than killing the whole chain."""
    runs: list[tuple[bool, list]] = []
    for o in ops:
        ok = _op_safe(o)
        if runs and runs[-1][0] == ok:
            runs[-1][1].append(o)
        else:
            runs.append((ok, [o]))
    return runs


def _rebuild_chain(runs: list, bottom: ExecOperator, conf: Configuration) -> ExecOperator:
    """Reassemble a chain over ``bottom``, fusing each fusable run that
    passes the cost model and keeping the others' original operators."""
    cur = bottom
    for ok, run in reversed(runs):
        seg = _plan_segment(run) if ok else None
        if seg is not None and seg.steps and _should_fuse(seg.cost(), conf):
            cur = seg.build(cur, run[0].schema)
        else:
            for o in reversed(run):
                o.children[0] = cur
                cur = o
    return cur


def _try_prefuse_agg(agg, conf: Configuration):
    """Extend the segment THROUGH a partial-mode HashAggExec: compile the
    chain below it plus the agg's grouping/argument expressions into one
    stage program and rewrite the aggregate over bare column refs. Returns
    the rebuilt aggregate, or None when the shape doesn't qualify (the
    normal chain pass then runs below the untouched aggregate)."""
    from auron_tpu.exec.agg_exec import AggExpr, HashAggExec

    in_schema = agg.children[0].schema
    exprs = [g for g, _ in agg.groupings] + [
        a.expr for a, _ in agg.aggs if a.expr is not None
    ]
    if not exprs:
        return None
    if not all(expr_trace_safe(e, in_schema, allow_dict_out=True) for e in exprs):
        return None
    ops, source = _collect_chain(agg.children[0])
    runs = _safe_runs(ops)
    top_run = runs[0][1] if runs and runs[0][0] else []
    rest = runs[1:] if top_run else runs
    names = [n for _, n in agg.groupings] + [
        n for a, n in agg.aggs if a.expr is not None
    ]
    seg = _plan_segment(top_run)
    seg.add_project(in_schema, tuple(exprs), names, op_name="HashAggExec")
    if not _should_fuse(seg.cost(), conf):
        return None

    new_groupings = [
        (ir.Column(i, n), n) for i, (_, n) in enumerate(agg.groupings)
    ]
    k = len(agg.groupings)
    new_aggs = []
    for a, n in agg.aggs:
        if a.expr is None:
            new_aggs.append((AggExpr(a.func, None, udaf=a.udaf), n))
        else:
            new_aggs.append((AggExpr(a.func, ir.Column(k, n), udaf=a.udaf), n))
            k += 1
    # validate the rewrite BEFORE any side effects (segment accounting,
    # chain rewiring): probe the rebuilt aggregate's typing against a
    # schema-only carrier of the stage's emitted layout
    from auron_tpu.exec.basic import EmptyPartitionsExec

    probe = HashAggExec(
        EmptyPartitionsExec(seg.stamp, 1), new_groupings, new_aggs, agg.mode
    )
    if probe.schema != agg.schema or probe.inter_schema != agg.inter_schema:
        # typing drift (e.g. a NULL-kind grouping literal surfacing as
        # INT32 through the stage): materialize instead of fusing wrong
        return None
    below = _rebuild_chain(rest, _visit(source, conf), conf)
    fused = seg.build(below, seg.stamp)
    new_agg = HashAggExec(fused, new_groupings, new_aggs, agg.mode)
    spec = _dense_prep_spec(new_agg)
    if spec is not None:
        link = DensePrepLink()
        fused.attach_dense_link(link, new_agg.n_keys, spec)
        new_agg._dense_prep_link = link
    return new_agg


def _dense_prep_spec(agg) -> tuple | None:
    """Static per-agg plane spec for _stage_program_prep, or None when the
    aggregate can't run its dense fold off stage-prepped planes. Column
    indices address the stage's OUTPUT layout (keys first, then aggregate
    arguments in declaration order). Publication stays runtime-gated: the
    aggregate only publishes an anchor when its dense table is live AND
    the host-scatter substrate is chosen, so attaching a link to a plan
    that ends up on the device-scatter path costs nothing."""
    from auron_tpu.exec.agg_exec import is_wide_sum, sum_type

    if not agg._dense_eligible():
        return None
    spec = []
    col = agg.n_keys
    for (a, _), in_t in zip(agg.aggs, agg._agg_input_types):
        if a.func == "count_star":
            spec.append(("count_star",))
            continue
        if a.func == "count":
            spec.append(("count", col))
        elif a.func in ("sum", "avg"):
            if is_wide_sum(in_t):
                return None  # _dense_eligible already excludes; stay safe
            st = sum_type(in_t)
            kind = "f" if st.is_float else "i"
            spec.append((a.func, col, st, kind))
        elif a.func in ("min", "max"):
            spec.append((a.func, col, np.dtype(in_t.physical_dtype().name).name))
        else:
            return None
        col += 1
    return tuple(spec)


def _visit(op: ExecOperator, conf: Configuration) -> ExecOperator:
    from auron_tpu.exec.agg_exec import HashAggExec

    if (
        isinstance(op, HashAggExec)
        and op.mode == "partial"
        and conf.get(FUSE_AGG_INPUTS)
    ):
        new = _try_prefuse_agg(op, conf)
        if new is not None:
            return new
    if isinstance(op, _CHAIN_OPS):
        ops, source = _collect_chain(op)
        return _rebuild_chain(_safe_runs(ops), _visit(source, conf), conf)
    for i, c in enumerate(op.children):
        op.children[i] = _visit(c, conf)
    return op


def fuse_exec_tree(plan: ExecOperator, conf: Configuration) -> ExecOperator:
    """Apply whole-stage fusion to an instantiated exec tree. A no-op when
    ``exec.fuse.enable`` resolves off for every segment; bit-identical
    results either way (tests/test_fusion.py fuzzes the equivalence)."""
    if conf.get(FUSE_ENABLE) == "off":
        return plan
    return _visit(plan, conf)

"""Shared traversal over PhysicalPlanNode child links.

Every plan operator reaches its inputs through one of: ``child``,
``left``/``right``, or the repeated ``children`` of union. Walkers across
the codebase (optimizer, explain, mesh driver, stage split) must agree on
this shape — this module is the single definition.
"""

from __future__ import annotations

from typing import Callable, Iterator

from auron_tpu.proto import plan_pb2 as pb


def child_nodes(node: pb.PhysicalPlanNode) -> Iterator[pb.PhysicalPlanNode]:
    """Yield the direct child plan nodes (mutable references)."""
    inner = getattr(node, node.WhichOneof("plan"))
    if hasattr(inner, "children"):
        yield from inner.children
        return
    for f in ("child", "left", "right"):
        try:
            present = inner.HasField(f)
        except ValueError:
            continue
        if present:
            yield getattr(inner, f)


def rewrite_children(
    node: pb.PhysicalPlanNode,
    fn: Callable[[pb.PhysicalPlanNode], pb.PhysicalPlanNode],
) -> pb.PhysicalPlanNode:
    """Copy ``node`` with every direct child replaced by ``fn(child)``."""
    new = pb.PhysicalPlanNode()
    new.CopyFrom(node)
    inner = getattr(new, new.WhichOneof("plan"))
    if hasattr(inner, "children"):
        for c in inner.children:
            c.CopyFrom(fn(c))
        return new
    for f in ("child", "left", "right"):
        try:
            present = inner.HasField(f)
        except ValueError:
            continue
        if present:
            getattr(inner, f).CopyFrom(fn(getattr(inner, f)))
    return new

from auron_tpu.plan.planner import expr_from_proto, plan_from_proto, task_from_proto  # noqa: F401

"""Physical planner: protobuf plan IR -> executable operator tree.

Analog of the reference's PhysicalPlanner::create_plan recursive match
(native-engine/auron-planner/src/planner.rs:122-740): every
``PhysicalPlanNode`` variant maps to one exec operator, every
``PhysicalExprNode`` variant to one exprs.ir node. The TaskDefinition
carries (stage, partition, conf) — the runtime installs the conf scope and
drives the root operator (runtime/task.py).
"""

from __future__ import annotations

from auron_tpu import types as T
from auron_tpu.exec.base import ExecOperator, ExecutionContext
from auron_tpu.exprs import ir
from auron_tpu.ops.sortkeys import SortSpec
from auron_tpu.proto import plan_pb2 as pb
from auron_tpu.utils.config import Configuration


class ResourceScanExec(ExecOperator):
    """memory_scan proto node: batches provided via the task resource map
    (how the host engine hands pre-imported data to a task — analog of the
    JniBridge resource map feeding readers, JniBridge.java:65-70)."""

    def __init__(self, schema: T.Schema, resource_id: str):
        super().__init__([], schema)
        self.resource_id = resource_id

    def _execute(self, partition: int, ctx: ExecutionContext):
        # per-partition form first ("rid.pid" — what a per-task host
        # executor registers; the payload IS this partition's stream),
        # then the shared per-partition-indexed source
        parts = ctx.resources.get(f"{self.resource_id}.{partition}")
        if parts is None:
            source = ctx.resources[self.resource_id]
            import pyarrow as _pa

            if callable(source):
                parts = source(partition)
            elif isinstance(source, dict):
                # partition-keyed mapping (SPMD drivers expose only the
                # locally-addressable partitions this way)
                parts = source[partition]
            elif source and isinstance(source[0], _pa.RecordBatch):
                # flat RecordBatch list — the unambiguous C-ABI host form
                # (put_resource decodes one IPC payload per task); every
                # other shape keeps the per-partition indexing semantics
                parts = source
            else:
                parts = source[partition]
        from auron_tpu.columnar.batch import Batch as _B

        for b in parts:
            if isinstance(b, _B):
                yield b
            elif b.num_rows:
                yield _B.from_arrow(b)

# ---------------------------------------------------------------------------
# types
# ---------------------------------------------------------------------------

_KIND_TO_T = {
    pb.DataType.NULL: T.TypeKind.NULL,
    pb.DataType.BOOL: T.TypeKind.BOOL,
    pb.DataType.INT8: T.TypeKind.INT8,
    pb.DataType.INT16: T.TypeKind.INT16,
    pb.DataType.INT32: T.TypeKind.INT32,
    pb.DataType.INT64: T.TypeKind.INT64,
    pb.DataType.FLOAT32: T.TypeKind.FLOAT32,
    pb.DataType.FLOAT64: T.TypeKind.FLOAT64,
    pb.DataType.DECIMAL: T.TypeKind.DECIMAL,
    pb.DataType.DATE32: T.TypeKind.DATE32,
    pb.DataType.TIMESTAMP: T.TypeKind.TIMESTAMP,
    pb.DataType.STRING: T.TypeKind.STRING,
    pb.DataType.BINARY: T.TypeKind.BINARY,
    pb.DataType.LIST: T.TypeKind.LIST,
    pb.DataType.MAP: T.TypeKind.MAP,
    pb.DataType.STRUCT: T.TypeKind.STRUCT,
}
_T_TO_KIND = {v: k for k, v in _KIND_TO_T.items()}


def dtype_from_proto(p: pb.DataType) -> T.DataType:
    kind = _KIND_TO_T[p.kind]
    if kind == T.TypeKind.LIST:
        return T.DataType(kind, inner=(dtype_from_proto(p.inner),))
    if kind in (T.TypeKind.MAP, T.TypeKind.STRUCT):
        return T.DataType(
            kind,
            inner=tuple(dtype_from_proto(i) for i in p.inners),
            struct_names=tuple(p.struct_names),
        )
    return T.DataType(kind, p.precision, p.scale)


def dtype_to_proto(t: T.DataType) -> pb.DataType:
    p = pb.DataType(kind=_T_TO_KIND[t.kind], precision=t.precision, scale=t.scale)
    if t.kind == T.TypeKind.LIST:
        p.inner.CopyFrom(dtype_to_proto(t.inner[0]))
    elif t.kind in (T.TypeKind.MAP, T.TypeKind.STRUCT):
        p.inners.extend(dtype_to_proto(i) for i in t.inner)
        if t.struct_names:
            p.struct_names.extend(t.struct_names)
    return p


def schema_from_proto(p: pb.Schema) -> T.Schema:
    return T.Schema(
        tuple(T.Field(f.name, dtype_from_proto(f.dtype), f.nullable) for f in p.fields)
    )


def schema_to_proto(s: T.Schema) -> pb.Schema:
    return pb.Schema(
        fields=[
            pb.Field(name=f.name, dtype=dtype_to_proto(f.dtype), nullable=f.nullable)
            for f in s.fields
        ]
    )


# ---------------------------------------------------------------------------
# expressions
# ---------------------------------------------------------------------------


def _literal_from_proto(p: pb.LiteralExpr) -> ir.Literal:
    dt = dtype_from_proto(p.dtype)
    if p.is_null:
        return ir.Literal(None, dt)
    which = p.WhichOneof("value")
    if which == "bool_value":
        return ir.Literal(p.bool_value, dt)
    if which == "int_value":
        return ir.Literal(p.int_value, dt)
    if which == "float_value":
        return ir.Literal(p.float_value, dt)
    if which == "string_value":
        return ir.Literal(p.string_value, dt)
    if which == "bytes_value":
        return ir.Literal(p.bytes_value, dt)
    if which == "decimal_unscaled":
        import decimal as pd

        return ir.Literal(
            pd.Decimal(p.decimal_unscaled).scaleb(-dt.scale), dt
        )
    return ir.Literal(None, dt)


def expr_from_proto(p: pb.PhysicalExprNode) -> ir.Expr:
    which = p.WhichOneof("expr")
    if which == "column":
        return ir.Column(p.column.index, p.column.name)
    if which == "literal":
        return _literal_from_proto(p.literal)
    if which == "cast":
        return ir.Cast(expr_from_proto(p.cast.child), dtype_from_proto(p.cast.to), p.cast.try_cast)
    if which == "binary":
        return ir.BinaryOp(
            p.binary.op, expr_from_proto(p.binary.left), expr_from_proto(p.binary.right)
        )
    if which == "is_null":
        return ir.IsNull(expr_from_proto(p.is_null.child))
    if which == "is_not_null":
        return ir.IsNotNull(expr_from_proto(p.is_not_null.child))
    if which == "not":
        return ir.Not(expr_from_proto(getattr(p, "not").child))
    if which == "if_expr":
        return ir.If(
            expr_from_proto(p.if_expr.cond),
            expr_from_proto(p.if_expr.then),
            expr_from_proto(p.if_expr.orelse),
        )
    if which == "case_expr":
        return ir.Case(
            tuple(
                (expr_from_proto(b.when), expr_from_proto(b.then))
                for b in p.case_expr.branches
            ),
            expr_from_proto(p.case_expr.orelse)
            if p.case_expr.HasField("orelse")
            else None,
        )
    if which == "in_list":
        return ir.In(
            expr_from_proto(p.in_list.child),
            tuple(_literal_from_proto(i).value for i in p.in_list.items),
            p.in_list.negated,
        )
    if which == "coalesce":
        return ir.Coalesce(tuple(expr_from_proto(a) for a in p.coalesce.args))
    if which == "like":
        return ir.Like(
            expr_from_proto(p.like.child), p.like.pattern, p.like.negated,
            p.like.escape or "\\",
        )
    if which == "scalar_func":
        return ir.ScalarFunc(
            p.scalar_func.name,
            tuple(expr_from_proto(a) for a in p.scalar_func.args),
            dtype_from_proto(p.scalar_func.out_dtype)
            if p.scalar_func.has_out_dtype
            else None,
        )
    if which == "host_udf":
        return ir.HostUDF(
            p.host_udf.name,
            tuple(expr_from_proto(a) for a in p.host_udf.args),
            dtype_from_proto(p.host_udf.out_dtype),
        )
    if which == "spark_partition_id":
        return ir.SparkPartitionId()
    if which == "monotonic_id":
        return ir.MonotonicId()
    if which == "row_num":
        return ir.RowNum()
    if which == "scalar_subquery":
        return ir.ScalarSubquery(
            p.scalar_subquery.resource_id, dtype_from_proto(p.scalar_subquery.dtype)
        )
    raise ValueError(f"unknown expr variant {which}")


def _sort_fields(fields) -> tuple[list[ir.Expr], list[SortSpec]]:
    exprs = [expr_from_proto(f.expr) for f in fields]
    specs = [SortSpec(asc=f.asc, nulls_first=f.nulls_first) for f in fields]
    return exprs, specs


# ---------------------------------------------------------------------------
# plans
# ---------------------------------------------------------------------------

_JOIN_TYPE = {
    pb.JOIN_INNER: "inner",
    pb.JOIN_LEFT: "left",
    pb.JOIN_RIGHT: "right",
    pb.JOIN_FULL: "full",
    pb.JOIN_LEFT_SEMI: "left_semi",
    pb.JOIN_LEFT_ANTI: "left_anti",
    pb.JOIN_EXISTENCE: "existence",
}

_AGG_FUNC = {
    pb.AGG_SUM: "sum",
    pb.AGG_COUNT: "count",
    pb.AGG_COUNT_STAR: "count_star",
    pb.AGG_AVG: "avg",
    pb.AGG_MIN: "min",
    pb.AGG_MAX: "max",
    pb.AGG_FIRST: "first",
    pb.AGG_FIRST_IGNORES_NULL: "first_ignores_null",
    pb.AGG_COLLECT_LIST: "collect_list",
    pb.AGG_COLLECT_SET: "collect_set",
    pb.AGG_HOST_UDAF: "host_udaf",
}

_AGG_MODE = {
    pb.AGG_PARTIAL: "partial",
    pb.AGG_PARTIAL_MERGE: "partial_merge",
    pb.AGG_FINAL: "final",
}


def partitioning_from_proto(p: pb.Partitioning):
    from auron_tpu.exec.shuffle import (
        HashPartitioning,
        RangePartitioning,
        RoundRobinPartitioning,
        SinglePartitioning,
    )

    if p.kind == pb.Partitioning.SINGLE:
        return SinglePartitioning()
    if p.kind == pb.Partitioning.HASH:
        return HashPartitioning(
            [expr_from_proto(e) for e in p.hash_exprs], p.num_partitions
        )
    if p.kind == pb.Partitioning.ROUND_ROBIN:
        return RoundRobinPartitioning(p.num_partitions)
    if p.kind == pb.Partitioning.RANGE:
        import numpy as np

        exprs, specs = _sort_fields(p.range_fields)
        w = p.range_words_per_bound
        arr = np.array(list(p.range_bound_words), dtype=np.uint64)
        bounds = arr.reshape(-1, w) if w else np.zeros((0, 1), np.uint64)
        return RangePartitioning(exprs, specs, p.num_partitions, bounds)
    raise ValueError(p.kind)


def plan_from_proto(p: pb.PhysicalPlanNode):
    from auron_tpu.exec import basic
    from auron_tpu.exec.agg_exec import AggExpr, HashAggExec
    from auron_tpu.exec.generate_exec import GenerateExec
    from auron_tpu.exec.joins import (
        BroadcastHashJoinExec,
        SortMergeJoinExec,
    )
    from auron_tpu.exec.shuffle import IpcReaderExec, ShuffleWriterExec
    from auron_tpu.exec.sort_exec import SortExec
    from auron_tpu.exec.window_exec import WindowExec, WindowFunc

    which = p.WhichOneof("plan")
    if which == "memory_scan":
        return ResourceScanExec(schema_from_proto(p.memory_scan.schema), p.memory_scan.resource_id)
    if which == "ffi_reader":
        from auron_tpu.exec.scan import FFIReaderExec

        return FFIReaderExec(schema_from_proto(p.ffi_reader.schema), p.ffi_reader.resource_id)
    if which == "parquet_scan":
        from auron_tpu.exec.scan import ParquetScanExec

        return ParquetScanExec(
            schema_from_proto(p.parquet_scan.schema),
            list(p.parquet_scan.file_paths),
            [expr_from_proto(e) for e in p.parquet_scan.pruning_predicates],
            p.parquet_scan.fs_resource_id or None,
            partitions=[list(fp.paths) for fp in p.parquet_scan.partitions] or None,
        )
    if which == "project":
        return basic.ProjectExec(
            plan_from_proto(p.project.child),
            [expr_from_proto(e.expr) for e in p.project.exprs],
            [e.name for e in p.project.exprs],
        )
    if which == "filter":
        return basic.FilterExec(
            plan_from_proto(p.filter.child),
            [expr_from_proto(e) for e in p.filter.predicates],
        )
    if which == "limit":
        return basic.LimitExec(plan_from_proto(p.limit.child), p.limit.limit)
    if which == "union":
        return basic.UnionExec([plan_from_proto(c) for c in p.union.children])
    if which == "expand":
        return basic.ExpandExec(
            plan_from_proto(p.expand.child),
            [[expr_from_proto(e) for e in proj.exprs] for proj in p.expand.projections],
            list(p.expand.names),
        )
    if which == "rename_columns":
        return basic.RenameColumnsExec(
            plan_from_proto(p.rename_columns.child), list(p.rename_columns.names)
        )
    if which == "empty_partitions":
        return basic.EmptyPartitionsExec(
            schema_from_proto(p.empty_partitions.schema), p.empty_partitions.num_partitions
        )
    if which == "coalesce_batches":
        return basic.CoalesceBatchesExec(
            plan_from_proto(p.coalesce_batches.child),
            p.coalesce_batches.target_rows or None,
        )
    if which == "hash_agg":
        n = p.hash_agg
        return HashAggExec(
            plan_from_proto(n.child),
            [(expr_from_proto(g.expr), g.name) for g in n.groupings],
            [
                (
                    AggExpr(
                        _AGG_FUNC[a.func],
                        expr_from_proto(a.expr) if a.has_expr else None,
                        udaf=a.udaf or None,
                    ),
                    a.name,
                )
                for a in n.aggs
            ],
            _AGG_MODE[n.mode],
        )
    if which == "sort":
        n = p.sort
        exprs, specs = _sort_fields(n.fields)
        return SortExec(
            plan_from_proto(n.child), exprs, specs,
            fetch=n.fetch if n.has_fetch else None,
        )
    if which == "sort_merge_join":
        n = p.sort_merge_join
        return SortMergeJoinExec(
            plan_from_proto(n.left),
            plan_from_proto(n.right),
            [expr_from_proto(e) for e in n.left_keys],
            [expr_from_proto(e) for e in n.right_keys],
            _JOIN_TYPE[n.join_type],
            condition=expr_from_proto(n.condition) if n.has_condition else None,
            exists_col=n.exists_col or "exists",
            projection=list(n.projection) if n.has_projection else None,
        )
    if which == "hash_join":
        n = p.hash_join
        return BroadcastHashJoinExec(
            plan_from_proto(n.left),
            plan_from_proto(n.right),
            [expr_from_proto(e) for e in n.left_keys],
            [expr_from_proto(e) for e in n.right_keys],
            _JOIN_TYPE[n.join_type],
            build_side="left" if n.build_side == pb.BUILD_LEFT else "right",
            condition=expr_from_proto(n.condition) if n.has_condition else None,
            cached_build_id=n.cached_build_id or None,
            exists_col=n.exists_col or "exists",
            projection=list(n.projection) if n.has_projection else None,
        )
    if which == "shuffle_writer":
        n = p.shuffle_writer
        return ShuffleWriterExec(
            plan_from_proto(n.child),
            partitioning_from_proto(n.partitioning),
            n.output_data_file,
            n.output_index_file,
        )
    if which == "rss_shuffle_writer":
        from auron_tpu.exec.shuffle.writer import RssShuffleWriterExec

        n = p.rss_shuffle_writer
        return RssShuffleWriterExec(
            plan_from_proto(n.child),
            partitioning_from_proto(n.partitioning),
            n.rss_resource_id,
        )
    if which == "ipc_reader":
        return IpcReaderExec(schema_from_proto(p.ipc_reader.schema), p.ipc_reader.resource_id)
    if which == "window":
        n = p.window
        order_exprs, order_specs = _sort_fields(n.order_by)
        return WindowExec(
            plan_from_proto(n.child),
            [expr_from_proto(e) for e in n.partition_by],
            list(zip(order_exprs, order_specs)),
            [
                (
                    WindowFunc(
                        f.kind,
                        agg=f.agg or None,
                        expr=expr_from_proto(f.expr) if f.has_expr else None,
                        offset=f.offset or 1,
                        frame_whole=f.frame_whole,
                    ),
                    f.name,
                )
                for f in n.funcs
            ],
        )
    if which == "generate":
        n = p.generate
        return GenerateExec(
            plan_from_proto(n.child),
            n.generator,
            expr_from_proto(n.gen_expr),
            list(n.required_cols),
            outer=n.outer,
            json_fields=list(n.json_fields),
            elem_name=n.elem_name or "col",
            pos_name=n.pos_name or "pos",
            udtf=n.udtf or None,
        )
    if which == "orc_scan":
        from auron_tpu.exec.scan import OrcScanExec

        return OrcScanExec(
            schema_from_proto(p.orc_scan.schema),
            list(p.orc_scan.file_paths),
            [expr_from_proto(e) for e in p.orc_scan.pruning_predicates],
            p.orc_scan.fs_resource_id or None,
            partitions=[list(fp.paths) for fp in p.orc_scan.partitions] or None,
        )
    if which == "orc_sink":
        from auron_tpu.exec.sink import OrcSinkExec

        return OrcSinkExec(
            plan_from_proto(p.orc_sink.child),
            p.orc_sink.output_path,
            dict(p.orc_sink.props),
        )
    if which == "parquet_sink":
        from auron_tpu.exec.sink import ParquetSinkExec

        return ParquetSinkExec(
            plan_from_proto(p.parquet_sink.child),
            p.parquet_sink.output_path,
            dict(p.parquet_sink.props),
            partition_by=list(p.parquet_sink.partition_by) or None,
        )
    if which == "ipc_writer":
        from auron_tpu.exec.sink import IpcWriterExec

        return IpcWriterExec(plan_from_proto(p.ipc_writer.child), p.ipc_writer.resource_id)
    if which == "debug":
        return basic.DebugExec(plan_from_proto(p.debug.child), p.debug.tag)
    if which == "kafka_scan":
        from auron_tpu.exec.streaming import KafkaScanExec

        n = p.kafka_scan
        return KafkaScanExec(
            schema_from_proto(n.schema),
            n.topic,
            n.source_resource_id,
            startup_mode=n.startup_mode or "earliest",
            start_offsets={int(k): int(v) for k, v in n.start_offsets.items()},
            data_format=n.format or "json",
            on_error=n.on_error or "skip",
            pb_field_ids=list(n.pb_field_ids) or None,
            max_batch_records=n.max_batch_records or 8192,
            zigzag_cols=set(n.zigzag_cols) or None,
        )
    if which == "mesh_exchange":
        raise ValueError(
            "mesh_exchange is a stage boundary resolved by "
            "parallel.mesh_driver.MeshQueryDriver, not a streaming operator; "
            "run the plan through the driver"
        )
    raise ValueError(f"unknown plan variant {which}")


def task_from_proto(task: pb.TaskDefinition):
    """Returns (root exec, stage_id, partition_id, Configuration)."""
    from auron_tpu.plan.fusion import fuse_exec_tree
    from auron_tpu.plan.optimizer import elide_smj_input_sorts, prune_columns

    _resolve_shuffle_templates(task)
    conf = Configuration(dict(task.conf))
    mode = dict(task.conf).get("auron.smj.elide.sorts", "build")
    # column pruning runs on EVERY task (idempotent): join pair-gather
    # bytes scale with emitted column count, the dominant join cost
    proto = prune_columns(elide_smj_input_sorts(task.plan, mode=mode))
    # whole-stage fusion rewrites the EXEC tree (protos/goldens untouched):
    # pipeline segments between blocking boundaries compile into single
    # XLA programs where the cost model says fusion wins (plan/fusion.py)
    plan = fuse_exec_tree(plan_from_proto(proto), conf)
    return plan, task.stage_id, task.partition_id, conf


def _resolve_shuffle_templates(task: pb.TaskDefinition) -> None:
    """Fill {work_dir}/{partition} placeholders in shuffle-writer paths from
    the task conf + partition id. Lets a host assemble stage tasks from the
    conversion service's per-stage plan template with byte-level surgery
    only (TaskDefs appends partition_id + conf; it never edits nested plan
    strings) — the host computes the same paths from the stage's
    output_*_template fields to commit/fetch map outputs."""
    from auron_tpu.plan.protowalk import child_nodes

    work_dir = task.conf.get("auron.work_dir", "")

    def rec(node: pb.PhysicalPlanNode) -> None:
        if node.WhichOneof("plan") == "shuffle_writer":
            w = node.shuffle_writer
            for attr in ("output_data_file", "output_index_file"):
                v = getattr(w, attr)
                if "{work_dir}" in v or "{partition}" in v:
                    if "{work_dir}" in v and not work_dir:
                        raise ValueError(
                            "shuffle path template needs task conf auron.work_dir"
                        )
                    setattr(
                        w, attr,
                        v.replace("{work_dir}", work_dir)
                        .replace("{partition}", str(task.partition_id)),
                    )
        for c in child_nodes(node):
            rec(c)

    rec(task.plan)

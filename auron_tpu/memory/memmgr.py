"""HBM budget manager with spillable consumers.

Analog of the reference's memory manager (native-engine/auron-memmgr/src/
lib.rs): a global budget (total = overhead * memory_fraction, set at session
init — exec.rs:80-88), consumers register and report usage
(MemConsumer trait, lib.rs:46,202), per-consumer fair share drives who
spills (mem_used_percent, lib.rs:213-225), and spills cascade until the
budget is met (lib.rs:393-410). The reference spills to JVM-heap blocks or
local files (spill.rs:90-101); the TPU-native tiers are:

    HBM (device arrays) -> host RAM (numpy, this module's HostSpill)
                        -> local disk (zstd-compressed Arrow IPC files)

Stateful operators (sort runs, agg states, shuffle staging, join builds)
register as consumers; when an ``acquire`` would exceed the budget the
manager asks the largest-usage consumers to spill first (the requester
last), exactly the ordering policy the reference uses.
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Protocol

from auron_tpu.utils.config import HBM_BUDGET_BYTES, MEMORY_FRACTION, active_conf


class MemConsumer(Protocol):
    name: str

    def mem_used(self) -> int: ...

    def spill(self) -> int:
        """Release memory; returns bytes freed."""
        ...


class MemManager:
    _instance: "MemManager | None" = None

    def __init__(self, budget_bytes: int | None = None):
        conf = active_conf()
        total = budget_bytes if budget_bytes is not None else conf.get(HBM_BUDGET_BYTES)
        self.budget = int(total * conf.get(MEMORY_FRACTION))
        self._lock = threading.RLock()
        self._consumers: list[MemConsumer] = []
        self.num_spills = 0

    # ---- lifecycle ----

    @classmethod
    def init(cls, budget_bytes: int | None = None) -> "MemManager":
        cls._instance = MemManager(budget_bytes)
        return cls._instance

    @classmethod
    def get(cls) -> "MemManager":
        if cls._instance is None:
            cls._instance = MemManager()
        return cls._instance

    # ---- consumer API ----

    def register(self, consumer: MemConsumer) -> None:
        with self._lock:
            self._consumers.append(consumer)

    def unregister(self, consumer: MemConsumer) -> None:
        with self._lock:
            if consumer in self._consumers:
                self._consumers.remove(consumer)

    def total_used(self) -> int:
        with self._lock:
            return sum(c.mem_used() for c in self._consumers)

    def mem_used_percent(self, consumer: MemConsumer) -> float:
        """Consumer's share of the budget (fair-share signal)."""
        return consumer.mem_used() / max(self.budget, 1)

    def acquire(self, consumer: MemConsumer, additional: int) -> None:
        """Declare intent to grow; triggers spills if over budget.

        Spill order: largest other consumers first, the requester last —
        so small consumers can grow at the expense of dominant ones.
        """
        with self._lock:
            needed = self.total_used() + additional - self.budget
            if needed <= 0:
                return
            others = sorted(
                (c for c in self._consumers if c is not consumer),
                key=lambda c: c.mem_used(),
                reverse=True,
            )
            for c in others + [consumer]:
                if needed <= 0:
                    break
                if c.mem_used() == 0:
                    continue
                freed = c.spill()
                self.num_spills += 1
                needed -= freed


# ---------------------------------------------------------------------------
# spill containers (host-RAM and disk tiers)
# ---------------------------------------------------------------------------


class DiskSpill:
    """Disk tier: zstd-compressed Arrow IPC blocks in a temp file (analog of
    the reference's compressed file spills, spill.rs:40-56)."""

    def __init__(self, spill_dir: str | None = None):
        fd, self.path = tempfile.mkstemp(
            suffix=".spill", dir=spill_dir or tempfile.gettempdir()
        )
        os.close(fd)
        self._offsets: list[int] = [0]

    def write_table(self, tbl) -> None:
        from auron_tpu.exec.shuffle.format import encode_block

        blk = encode_block(tbl)
        with open(self.path, "ab") as f:
            f.write(blk)
        self._offsets.append(self._offsets[-1] + len(blk))

    def read_tables(self):
        from auron_tpu.exec.shuffle.format import decode_blocks

        with open(self.path, "rb") as f:
            data = f.read()
        yield from decode_blocks(data)

    def release(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass

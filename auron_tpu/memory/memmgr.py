"""HBM budget manager with spillable consumers.

Analog of the reference's memory manager (native-engine/auron-memmgr/src/
lib.rs): a global budget (total = overhead * memory_fraction, set at session
init — exec.rs:80-88), consumers register and report usage
(MemConsumer trait, lib.rs:46,202), per-consumer fair share drives who
spills (mem_used_percent, lib.rs:213-225), and growth beyond the managed
pool either self-spills or WAITS for siblings to release memory
(Operation::Spill/Wait, lib.rs:330-410). The reference spills to JVM-heap
blocks or local files (spill.rs:90-101); the TPU-native tiers are:

    HBM (device arrays) -> host RAM (``HostSpill``: compressed blocks in
                           RAM, demoted when the host ledger fills)
                        -> local disk (``DiskSpill``: zstd-compressed
                           Arrow IPC files)

Stateful operators (sort runs, agg states, shuffle staging, join builds)
register as consumers. Unspillable consumers (e.g. a hash-join build that
must stay resident for probing) still register so their usage shrinks the
managed pool others fair-share — the reference's mem_unspillable
accounting (lib.rs:355-364).

Two growth protocols coexist:

- ``update_mem_used(consumer, new_used)`` — the reference's protocol:
  fair-share limits (consumer_mem_max = managed/num_spillables, min =
  max/8), self-spill when over, condition-variable wait (with timeout →
  forced spill) when under min share.
- ``acquire(consumer, additional)`` — cascade protocol used by streaming
  operators: spill the largest *other* spillable consumers first, the
  requester last, so small consumers can grow at dominant ones' expense.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Protocol

from auron_tpu import obs
from auron_tpu.utils.config import (
    HBM_BUDGET_BYTES,
    HOST_SPILL_BUDGET_BYTES,
    MEM_WAIT_TIMEOUT_S,
    MEMORY_FRACTION,
    active_conf,
)

# growth below this never triggers spill/wait (reference MIN_TRIGGER_SIZE)
_MIN_TRIGGER_BYTES = 1 << 20


def _auto_budget() -> int:
    """Hardware-shaped default (conf 0 = auto): accelerators get an
    HBM-sized 8GB; on the CPU backend device arrays live in host RAM, so
    half the physical memory is the faithful analog of the reference's
    executor-memory-derived budget."""
    try:
        import jax

        if jax.default_backend() != "cpu":
            return 8 << 30
    except Exception:  # noqa: BLE001  # auronlint: disable=R12 -- backend probe: an unprobeable jax means the CPU sizing below, which IS the documented fallback
        pass
    try:
        phys = os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
        return phys // 2  # the documented behavior, no floor: a small
        # host must spill, not OOM
    except (ValueError, OSError):
        return 8 << 30


class MemConsumer(Protocol):
    name: str

    def mem_used(self) -> int: ...

    def spill(self) -> int:
        """Release memory; returns bytes freed."""
        ...


class MemManager:
    _instance: "MemManager | None" = None

    def __init__(self, budget_bytes: int | None = None):
        # the process-wide singleton is DELIBERATELY built from the
        # ambient conf: init() runs at session setup under the session's
        # scope, and a lazy get() from a service thread sees the global —
        # both are the intended process-level budget source
        conf = active_conf()  # auronlint: disable=R7 -- process singleton: session-setup scope or the global conf IS the budget source
        # 0 = auto applies to the CONF default only; an explicit
        # budget_bytes=0 is an intentional always-spill manager
        total = (
            budget_bytes
            if budget_bytes is not None
            else (conf.get(HBM_BUDGET_BYTES) or _auto_budget())
        )
        self.budget = int(total * conf.get(MEMORY_FRACTION))
        self._lock = threading.RLock()
        self._released = threading.Condition(self._lock)
        self._consumers: list[MemConsumer] = []
        self._spillable: dict[int, bool] = {}
        # owning span captured at register(): registration happens on the
        # owning task's thread, so a spill dispatched LATER by a foreign
        # thread still attributes to the owner's trace (obs/span.py)
        self._owner_spans: dict[int, object] = {}
        self.num_spills = 0
        self.num_waits = 0
        self._wait_timeout = float(conf.get(MEM_WAIT_TIMEOUT_S))

    # ---- lifecycle ----

    @classmethod
    def init(cls, budget_bytes: int | None = None) -> "MemManager":
        cls._instance = MemManager(budget_bytes)
        return cls._instance

    @classmethod
    def get(cls) -> "MemManager":
        if cls._instance is None:
            cls._instance = MemManager()
        return cls._instance

    # ---- consumer API ----

    def register(self, consumer: MemConsumer, spillable: bool = True) -> None:
        with self._lock:
            self._consumers.append(consumer)
            self._spillable[id(consumer)] = spillable
            self._owner_spans[id(consumer)] = obs.current_span()

    def unregister(self, consumer: MemConsumer) -> None:
        with self._lock:
            if consumer in self._consumers:
                self._consumers.remove(consumer)
            self._spillable.pop(id(consumer), None)
            self._owner_spans.pop(id(consumer), None)
            # freed capacity: wake waiters blocked on the managed pool
            self._released.notify_all()

    def notify_released(self) -> None:
        """Consumers call this after shrinking (spill, drain, finish) so
        waiters blocked in update_mem_used can re-check the pool."""
        with self._lock:
            self._released.notify_all()

    def total_used(self) -> int:
        with self._lock:
            return sum(c.mem_used() for c in self._consumers)

    def mem_snapshot(self) -> dict:
        """THE manager snapshot both observability surfaces render
        (httpsvc /metrics JSON and /metrics.prom): budget, spill count,
        per-consumer usage — taken under the lock, one definition so a
        new field can't land on one endpoint and silently miss the
        other."""
        with self._lock:
            return {
                "budget_bytes": self.budget,
                "num_spills": self.num_spills,
                "consumers": [
                    {"name": c.name, "mem_used": c.mem_used()}
                    for c in self._consumers
                ],
            }

    def _pool_state(self) -> tuple[int, int, int]:
        """(total_used, managed_pool, num_spillables) — managed pool =
        budget minus unspillable usage (lib.rs:355-364)."""
        total_used = 0
        unspillable = 0
        n_spillables = 0
        for c in self._consumers:
            u = c.mem_used()
            total_used += u
            if self._spillable.get(id(c), True):
                n_spillables += 1
            else:
                unspillable += u
        return total_used, max(self.budget - unspillable, 0), max(n_spillables, 1)

    def mem_used_percent(self, consumer: MemConsumer) -> float:
        """Consumer's share of its fair-share maximum (lib.rs:213-225)."""
        with self._lock:
            _, managed, n = self._pool_state()
            return consumer.mem_used() / max(managed / n, 1)

    def _dispatch_spill(self, consumer: MemConsumer) -> int:
        """Run ``consumer.spill()`` under the OWNING task's span (captured
        at register()): spill enter/exit land on the owner's trace
        timeline even when the memory manager dispatches the spill from a
        foreign task's thread. Owner-less consumers record untraced —
        NEVER against the executing thread's ambient span."""
        if obs.core._mode == obs.MODE_OFF:  # keep the no-obs path bare
            return consumer.spill()
        sp = self._owner_spans.get(id(consumer))
        t0 = time.perf_counter_ns()
        with obs.use_span(sp):
            freed = consumer.spill()
        if freed:
            # freed==0 attempts are not spills: num_spills skips them,
            # and the two exported counts must agree (/metrics.prom vs
            # /queries)
            obs.note_spill(consumer.name, "spill",
                           time.perf_counter_ns() - t0, freed, sp=sp)
        return freed

    def update_mem_used(self, consumer: MemConsumer, old_used: int, new_used: int) -> None:
        """Reference growth protocol (lib.rs:330-410): growing past the
        managed pool or the consumer's fair share triggers a self-spill;
        consumers under min share (fair/8) wait for siblings to release
        before spilling tiny states, with a timeout escape."""
        if new_used <= old_used or new_used < _MIN_TRIGGER_BYTES:
            if new_used < old_used:
                self.notify_released()
            return
        with self._lock:
            spillable = self._spillable.get(id(consumer), True)
            total_used, managed, n = self._pool_state()
            consumer_max = managed // n
            consumer_min = consumer_max // 8
            over = total_used > managed or new_used > consumer_max
            if not over:
                return
            if spillable and new_used > consumer_min:
                pass  # self-spill below (outside the wait path)
            else:
                # below min share (or unspillable): wait for the pool
                self.num_waits += 1
                ok = self._released.wait_for(
                    lambda: self._pool_state()[0] <= self._pool_state()[1],
                    timeout=self._wait_timeout,
                )
                if ok or not spillable:
                    return
        # self-spill without holding the manager lock (consumer locks are
        # ordered manager -> consumer; spill takes the consumer lock)
        freed = self._dispatch_spill(consumer)
        if freed:
            with self._lock:
                # R8: concurrent growers from different task threads race
                # on this counter (the acquire() path already locks it)
                self.num_spills += 1
            self.notify_released()

    def acquire(self, consumer: MemConsumer, additional: int) -> None:
        """Cascade protocol: declare intent to grow; spills largest other
        spillable consumers first, the requester last.

        Lock order invariant: the manager lock is NEVER held across a
        consumer's spill() (consumer locks wrap device compute that can
        take seconds — and on the CPU backend a blocked chain through a
        callback-bearing computation can wedge outright). Victims are
        chosen under the lock, spilled outside it, and the shortfall
        re-checked per victim."""
        with self._lock:
            needed = self.total_used() + additional - self.budget
            if needed <= 0:
                return
            others = sorted(
                (
                    c
                    for c in self._consumers
                    if c is not consumer and self._spillable.get(id(c), True)
                ),
                key=lambda c: c.mem_used(),
                reverse=True,
            )
            victims = others + (
                [consumer] if self._spillable.get(id(consumer), True) else []
            )
        for c in victims:
            with self._lock:
                # re-check live pool state per victim: concurrent spills/
                # releases may have already covered the shortfall — and
                # membership: a victim that finished and unregistered in
                # the meantime must not be spilled (its spill would write
                # a temp file nothing ever unlinks, ADVICE r4)
                needed = self.total_used() + additional - self.budget
                gone = c is not consumer and c not in self._consumers
            if needed <= 0:
                break
            if gone or c.mem_used() == 0:
                continue
            if self._dispatch_spill(c):
                with self._lock:
                    self.num_spills += 1
        self.notify_released()


# ---------------------------------------------------------------------------
# spill containers (host-RAM and disk tiers)
# ---------------------------------------------------------------------------


def _conf_trace_id(conf) -> int:
    """Owning trace id carried by a spill container's conf (obs.trace.id,
    threaded exactly like the compression codec: the executing thread may
    be a foreign task's, its ambient context is NOT the owner's)."""
    if conf is None:
        return 0
    try:
        return int(conf.get(obs.OBS_TRACE_ID))
    except Exception:
        return 0


class DiskSpill:
    """Disk tier: zstd-compressed Arrow IPC blocks in a temp file (analog of
    the reference's compressed file spills, spill.rs:40-56).

    ``conf``: the owning task's Configuration — spills run on whichever
    thread the memory manager dispatches, so the compression codec must
    be threaded, not read from the spilling thread's active_conf() (R7)."""

    def __init__(self, spill_dir: str | None = None, *, conf):
        fd, self.path = tempfile.mkstemp(
            suffix=".spill", dir=spill_dir or tempfile.gettempdir()
        )
        os.close(fd)
        self._offsets: list[int] = [0]
        self._conf = conf

    def write_table(self, tbl) -> None:
        from auron_tpu.exec.shuffle.format import encode_block

        obs_on = obs.core._mode != obs.MODE_OFF
        t0 = time.perf_counter_ns() if obs_on else 0
        blk = encode_block(tbl, conf=self._conf)
        with open(self.path, "ab") as f:
            f.write(blk)
        self._offsets.append(self._offsets[-1] + len(blk))
        if obs_on:
            obs.note_spill("DiskSpill", "write", time.perf_counter_ns() - t0,
                           len(blk), trace_id=_conf_trace_id(self._conf))

    def read_tables(self):
        from auron_tpu.exec.shuffle.format import decode_blocks

        with open(self.path, "rb") as f:
            data = f.read()
        yield from decode_blocks(data)

    def release(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


class _HostLedger:
    """Process-wide accounting of host-RAM spill bytes. When the ledger
    would exceed the configured host budget, the OLDEST resident HostSpills
    demote to disk first (they are the coldest; the reference's analog is
    the JVM on-heap spill manager handing blocks to the block manager when
    heap runs short, SparkOnHeapSpillManager.scala:37-199)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._resident: list["HostSpill"] = []
        self._bytes = 0

    def admit(self, spill: "HostSpill", nbytes: int, conf=None) -> list["HostSpill"]:
        """Record bytes; returns the demotion victims WITHOUT demoting —
        the caller runs them after releasing its own spill lock (admission
        happens under the admitting spill's lock so it can never interleave
        with a concurrent demotion of that same spill, ADVICE r4).

        ``conf``: threaded from the admitting spill — admissions happen on
        spill-dispatch threads where active_conf() is a foreign task's."""
        budget = int(
            (conf if conf is not None else active_conf()).get(HOST_SPILL_BUDGET_BYTES)
        )
        to_demote: list[HostSpill] = []
        with self._lock:
            self._bytes += nbytes
            if spill not in self._resident:
                self._resident.append(spill)
            # pick only enough victims to clear the shortfall: their bytes
            # leave the ledger later (each victim's forget), so track a
            # running remainder here instead of re-reading self._bytes —
            # otherwise ONE pressure event demotes every resident spill
            remaining = self._bytes
            while remaining > budget and self._resident:
                victim = self._resident.pop(0)
                to_demote.append(victim)
                remaining -= victim._admitted
        return to_demote

    def forget(self, spill: "HostSpill", nbytes: int) -> None:
        with self._lock:
            self._bytes -= nbytes
            if spill in self._resident:
                self._resident.remove(spill)

    def resident_bytes(self) -> int:
        with self._lock:
            return self._bytes


_host_ledger = _HostLedger()


class HostSpill:
    """Host-RAM tier: compressed blocks kept in RAM (device -> host is one
    transfer; re-reading skips the disk round trip). Demotes itself to a
    DiskSpill when the process host ledger fills. Interface-compatible
    with DiskSpill (write_table / read_tables / release)."""

    def __init__(self, spill_dir: str | None = None, *, conf):
        self._blocks: list[bytes] | None = []
        self._nbytes = 0
        self._admitted = 0  # bytes this spill currently holds in the ledger
        self._disk: DiskSpill | None = None
        self._spill_dir = spill_dir
        self._conf = conf  # owning task's conf (codec + ledger budget, R7)
        self._lock = threading.Lock()

    def write_table(self, tbl) -> None:
        from auron_tpu.exec.shuffle.format import encode_block

        obs_on = obs.core._mode != obs.MODE_OFF
        t0 = time.perf_counter_ns() if obs_on else 0
        blk = encode_block(tbl, conf=self._conf)
        with self._lock:
            if self._disk is not None:
                with open(self._disk.path, "ab") as f:
                    f.write(blk)
                return
            self._blocks.append(blk)
            self._nbytes += len(blk)
            self._admitted += len(blk)
            # admission under OUR lock: a concurrent demotion of this spill
            # must take this lock first, so it always sees these bytes and
            # forgets exactly _admitted — the ledger can't drift (ADVICE r4:
            # the post-release admit re-added bytes a demotion had already
            # forgotten and re-inserted a demoted spill as resident)
            victims = _host_ledger.admit(self, len(blk), conf=self._conf)
        if obs_on:
            obs.note_spill("HostSpill", "write", time.perf_counter_ns() - t0,
                           len(blk), trace_id=_conf_trace_id(self._conf))
        for v in victims:  # demote OUTSIDE our lock (lock order spill->ledger)
            v._demote()

    def _demote(self) -> None:  # auronlint: thread-root(foreign) -- ledger pressure demotes victims on whichever thread admitted the last block
        """Move resident blocks to disk (ledger pressure)."""
        obs_on = obs.core._mode != obs.MODE_OFF
        t0 = time.perf_counter_ns() if obs_on else 0
        with self._lock:
            if self._disk is not None or self._blocks is None:
                return
            disk = DiskSpill(self._spill_dir, conf=self._conf)
            try:
                with open(disk.path, "ab") as f:
                    for blk in self._blocks:
                        f.write(blk)
            except BaseException:
                # a failed demotion write (disk full) must not leak the
                # temp file; the blocks stay resident in RAM (R11)
                disk.release()
                raise
            freed = self._admitted
            self._blocks, self._nbytes, self._admitted = [], 0, 0
            self._disk = disk
        _host_ledger.forget(self, freed)
        if obs_on:
            obs.note_spill("HostSpill", "demote", time.perf_counter_ns() - t0,
                           freed, trace_id=_conf_trace_id(self._conf))

    @property
    def demoted(self) -> bool:
        with self._lock:
            return self._disk is not None

    def read_tables(self):
        from auron_tpu.exec.shuffle.format import decode_blocks

        with self._lock:
            disk, blocks = self._disk, list(self._blocks or ())
        if disk is not None:
            yield from disk.read_tables()
            return
        yield from decode_blocks(b"".join(blocks))

    def release(self) -> None:
        with self._lock:
            disk, freed = self._disk, self._admitted
            self._blocks, self._nbytes, self._disk = None, 0, None
            self._admitted = 0
        if disk is not None:
            disk.release()
        if freed:
            _host_ledger.forget(self, freed)


def make_spill(spill_dir: str | None = None, *, conf):
    """Spill container for operator state: host-RAM tier first, demoting
    to disk under ledger pressure (the promised HBM -> host RAM -> disk
    cascade). ``conf``: REQUIRED — the OWNING task's Configuration.
    Spill writes and ledger demotions run on memory-manager dispatch
    threads, where the ambient active_conf() is a FOREIGN task's;
    keyword-only with no default so a forgotten conf is a TypeError at
    construction, not a silent cross-thread codec/budget leak (R7).
    Pass None deliberately only for conf-independent scratch (tests)."""
    return HostSpill(spill_dir, conf=conf)

from auron_tpu.memory.memmgr import MemConsumer, MemManager  # noqa: F401

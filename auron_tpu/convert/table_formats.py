"""Table-format scan conversion (Iceberg/Hudi/Paimon plugin analog).

The reference's table-format plugins (thirdparty/auron-iceberg/.../
NativeIcebergTableScanExec.scala and the hudi/paimon twins) do one thing:
resolve the format's metadata (snapshot -> manifests -> data files with
per-file partition values and stats) into a native file scan, pruning
whole files with the query predicates before any I/O. The engine then
scans plain parquet.

Here the host shim ships that metadata as a neutral descriptor:

    {"op": "IcebergScanExec",          # or HudiScanExec / PaimonScanExec
     "schema": [...],
     "args": {"files": [{"path": ..., "partition": {col: value, ...},
                         "record_count": N}, ...],
              "filters": [<expr>, ...],     # engine expression dicts
              "format": "parquet"},
     "children": []}

and this provider lowers it to a ParquetScanNode over the files whose
partition values can satisfy the filters (file-level pruning), with the
residual predicates pushed into the scan's row-group pruning.
"""

from __future__ import annotations

import operator

from auron_tpu.convert.exprs import convert_expr
from auron_tpu.convert.hostplan import HostNode
from auron_tpu.exprs import ir
from auron_tpu.plan import builders as B
from auron_tpu.proto import plan_pb2 as pb
from auron_tpu.utils.config import Configuration

_TABLE_SCAN_OPS = ("IcebergScanExec", "HudiScanExec", "PaimonScanExec")

_CMP = {
    "eq": operator.eq, "lt": operator.lt, "lteq": operator.le,
    "gt": operator.gt, "gteq": operator.ge, "neq": operator.ne,
}


def _file_may_match(e: ir.Expr, schema, partition: dict) -> bool:
    """Can any row of a file with these partition values satisfy e?
    Conservative: unknown shapes / non-partition columns -> True."""
    if isinstance(e, ir.BinaryOp):
        if e.op == "and":
            return _file_may_match(e.left, schema, partition) and _file_may_match(
                e.right, schema, partition
            )
        if e.op == "or":
            return _file_may_match(e.left, schema, partition) or _file_may_match(
                e.right, schema, partition
            )
        if (
            e.op in _CMP
            and isinstance(e.left, ir.Column)
            and isinstance(e.right, ir.Literal)
        ):
            name = schema[e.left.index].name
            if name not in partition:
                return True  # not a partition column: cannot prune
            v = partition[name]
            lit_v = e.right.value
            if v is None or lit_v is None:
                return False  # NULL never satisfies a comparison
            if not _comparable(v, lit_v):
                return True  # cross-type metadata (e.g. '2023' vs 2023)
            try:
                return bool(_CMP[e.op](v, lit_v))
            except TypeError:
                return True
    if isinstance(e, ir.In) and isinstance(e.child, ir.Column) and not e.negated:
        name = schema[e.child.index].name
        if name not in partition:
            return True
        v = partition[name]
        if not all(_comparable(v, i) for i in e.items if i is not None):
            return True
        return v in set(e.items)
    return True


def _comparable(a, b) -> bool:
    """Same-type (or numeric/numeric) values can be pruned on; anything
    else — notably string-typed partition metadata vs int literals — must
    stay conservative or matching files silently vanish."""
    num = (int, float)
    if isinstance(a, num) and isinstance(b, num):
        return True
    return type(a) is type(b)


class TableFormatScanProvider:
    """One provider covers all three formats: the descriptor shape is the
    format-neutral output of their metadata resolution."""

    def is_supported(self, node: HostNode) -> bool:
        return node.op in _TABLE_SCAN_OPS and "files" in node.args

    def is_enabled(self, node: HostNode, conf: Configuration) -> bool:
        from auron_tpu.convert.providers import TABLE_FORMATS_ENABLE

        return conf.get(TABLE_FORMATS_ENABLE)

    def convert(self, node: HostNode, children, conf: Configuration):
        assert not children
        filters = [
            convert_expr(f, conf) for f in node.args.get("filters", [])
        ]
        kept: list[str] = []
        pruned = 0
        for f in node.args["files"]:
            part = f.get("partition") or {}
            if all(_file_may_match(e, node.schema, part) for e in filters):
                kept.append(f["path"])
            else:
                pruned += 1
        fmt = node.args.get("format", "parquet")
        if fmt != "parquet":
            raise ValueError(f"table-format data files must be parquet, got {fmt}")
        scan = B.parquet_scan(
            node.schema, kept, filters,
            node.args.get("fs_resource_id", ""),
        )
        if pruned:
            from auron_tpu.utils.logging import get_logger

            get_logger().info(
                "%s: pruned %d/%d data files by partition values",
                node.op, pruned, pruned + len(kept),
            )
        return scan

"""Iceberg table metadata -> table-format scan descriptor.

VERDICT r3 (item 16) called the table-format support "descriptors only —
no shim producing descriptors from real metadata". This closes it for
Iceberg: resolve a REAL table directory (metadata/v*.metadata.json,
current snapshot, Avro manifest list, Avro manifests — utils/avro.py)
into the neutral descriptor TableFormatScanProvider already lowers to a
pruned native parquet scan. Reference analog:
thirdparty/auron-iceberg/.../NativeIcebergTableScanExec.scala (which
leans on Iceberg's own library for this resolution; the image has none,
so the resolution lives here against the public Iceberg spec v1/v2).

Hudi/Paimon keep the descriptor-only path (their hosts resolve metadata
with the formats' own libraries and ship the same descriptor).
"""

from __future__ import annotations

import json
import os

from auron_tpu.utils.avro import read_container

#: iceberg primitive -> engine hostplan type name
_TYPES = {
    "boolean": "boolean",
    "int": "int",
    "long": "long",
    "float": "float",
    "double": "double",
    "date": "date",
    "timestamp": "timestamp",
    "timestamptz": "timestamp",
    "string": "string",
    "binary": "binary",
}


def _engine_type(t) -> str:
    if isinstance(t, str):
        if t in _TYPES:
            return _TYPES[t]
        if t.startswith("decimal("):
            return t  # "decimal(p, s)" parses engine-side
    # nested (struct/list/map) and unknown types ship as an unparseable
    # tag: hostplan's schema parse marks the NODE degraded with a reason
    # instead of this resolver raising — one nested column must not block
    # resolution outright
    return f"iceberg:{json.dumps(t)}"


def _latest_metadata(table_path: str) -> str:
    meta_dir = os.path.join(table_path, "metadata")
    hint = os.path.join(meta_dir, "version-hint.text")
    if os.path.exists(hint):
        with open(hint) as f:
            v = f.read().strip()
        path = os.path.join(meta_dir, f"v{v}.metadata.json")
        if os.path.exists(path):
            return path
    def version_of(f: str) -> int:
        # "v3.metadata.json" (hadoop tables) or "00003-<uuid>.metadata.json"
        # (catalog tables): the leading integer is the version either way
        stem = f.split(".")[0].lstrip("v").split("-")[0]
        return int(stem) if stem.isdigit() else -1

    candidates = sorted(
        (f for f in os.listdir(meta_dir) if f.endswith(".metadata.json")),
        key=version_of,
    )
    if not candidates:
        raise FileNotFoundError(f"{meta_dir}: no metadata.json")
    return os.path.join(meta_dir, candidates[-1])


def _local_path(p: str, table_path: str) -> str:
    """Iceberg paths may be absolute URIs; strip file: schemes and remap
    the table location prefix (tables move; their metadata keeps the
    original absolute locations)."""
    if p.startswith("file://"):
        p = p[len("file://"):]
    if not os.path.isabs(p):
        return os.path.join(table_path, p)
    if not os.path.exists(p):
        # remap <orig-location>/... -> <table_path>/... by the marker dirs
        # (LAST occurrence: the original location may itself contain
        # /data/ or /metadata/ segments)
        for marker in ("/data/", "/metadata/"):
            i = p.rfind(marker)
            if i >= 0:
                cand = os.path.join(table_path, p[i + 1 :])
                if os.path.exists(cand):
                    return cand
    return p


def resolve_iceberg_scan(
    table_path: str, snapshot_id: int | None = None
) -> dict:
    """Resolve a real Iceberg table directory into the IcebergScanExec
    descriptor (hostplan node dict, filters empty — the converter merges
    the query's predicates)."""
    with open(_latest_metadata(table_path)) as f:
        meta = json.load(f)

    # schema: v2 "schemas"+"current-schema-id", v1 "schema"
    if "schemas" in meta:
        cur = meta.get("current-schema-id", 0)
        schema_json = next(s for s in meta["schemas"] if s.get("schema-id", 0) == cur)
    else:
        schema_json = meta["schema"]
    fields = schema_json["fields"]
    schema = [
        [f["name"], _engine_type(f["type"]), not f.get("required", False)]
        for f in fields
    ]
    field_names = {f["id"]: f["name"] for f in fields}

    # partition spec: source field ids -> names (identity transforms prune;
    # non-identity partition values are opaque to the pruner and pass)
    specs = {
        s.get("spec-id", 0): s["fields"]
        for s in meta.get("partition-specs", [{"spec-id": 0, "fields": meta.get("partition-spec", [])}])
    }

    snap_id = snapshot_id if snapshot_id is not None else meta.get("current-snapshot-id")
    snap = next(
        (s for s in meta.get("snapshots", []) if s["snapshot-id"] == snap_id), None
    )
    if snap is None:
        return {"op": "IcebergScanExec", "schema": schema,
                "args": {"files": [], "filters": [], "format": "parquet"}}

    files: list[dict] = []
    if "manifest-list" in snap:
        _, manifest_entries = read_container(
            _local_path(snap["manifest-list"], table_path)
        )
    else:
        # spec v1 alternative: inline manifest path array
        manifest_entries = [
            {"manifest_path": p, "partition_spec_id": 0}
            for p in snap.get("manifests", [])
        ]
    for m in manifest_entries:
        manifest_path = _local_path(m["manifest_path"], table_path)
        spec_fields = specs.get(m.get("partition_spec_id", 0), [])
        _, entries = read_container(manifest_path)
        for e in entries:
            if e.get("status") == 2:  # DELETED
                continue
            df = e["data_file"]
            if df.get("content", 0) != 0:  # only DATA files (no deletes)
                continue
            fmt = str(df.get("file_format", "PARQUET")).lower()
            if fmt != "parquet":
                # the provider lowers to a parquet scan; reading ORC/Avro
                # data files as parquet would crash or return garbage
                raise ValueError(
                    f"iceberg data file {df['file_path']}: format {fmt!r} "
                    "is not supported (parquet only)"
                )
            partition = {}
            pvals = df.get("partition") or {}
            for sf in spec_fields:
                if sf.get("transform", "identity") != "identity":
                    continue  # non-identity values can't prune literally
                col = field_names.get(sf["source-id"])
                if col is not None and sf["name"] in pvals:
                    partition[col] = pvals[sf["name"]]
            files.append({
                "path": _local_path(df["file_path"], table_path),
                "partition": partition,
                "record_count": int(df.get("record_count", 0)),
                "format": fmt,
            })
    return {
        "op": "IcebergScanExec",
        "schema": schema,
        "args": {"files": files, "filters": [], "format": "parquet"},
    }

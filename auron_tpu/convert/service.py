"""Engine-side conversion service for out-of-process hosts.

The JVM shim ships its serialized physical plan (hostplan JSON) through the
C ABI (``auron_convert_plan``) and receives a *segmentation response* it
can splice mechanically — the counterpart of the reference's JVM-side
AuronConverters, moved engine-side so the shim stays Spark-version-stable.

Response JSON:

    {"converted": <bool — any native segment produced>,
     "root": <node>,
     "tags": [[op, ok, reason|null], ...]}           # walk_down order

    node := {"kind": "segment",
             "path": [child indexes RELATIVE to the parent response node],
             "plan_b64": <TaskDefinition-ready plan proto, base64>,
             "stages": [ {"plan_b64": ..., "exchange_id": ...,
                          "num_output_partitions": ...,
                          "input_exchange_ids": [...]} ... ],
             "task_partitions": <int|null — task count pinned by the
                                 segment's scan file placement>,
             "schema": [[name, type, nullable], ...],
             "inputs": [{"resource_id": ..., "child": <node>} ...]}
          |  {"kind": "host", "path": [...], "op": ...,
             "children": [<node> ...]}

``path`` is RELATIVE to the parent response node (the plan root for the
root node), so a splicer can navigate its own plan tree compositionally —
it never needs absolute coordinates. ``stages`` is the host-schedulable
split of the segment (convert/stages.py) — a segment with no exchanges has
exactly one final stage. ``task_partitions`` is non-null when the segment
contains a file scan with host-decided per-task file groups: the host MUST
run exactly that many tasks or file groups would be dropped.
"""

from __future__ import annotations

import base64
import json

from auron_tpu.convert.converters import (
    ConversionResult,
    HostOp,
    NativeSegment,
    convert_plan,
)
from auron_tpu.convert.hostplan import HostNode
from auron_tpu.convert.stages import ffi_reader_ids, split_stages

# Conversion counter + pid salt: namespaces stage exchange ids so queries
# converted concurrently (same engine process) or by different driver
# processes feeding one executor can never collide on reduce-side shuffle
# resource keys.
_conversion_seq = __import__("itertools").count()


def _namespace() -> str:
    import os

    return f"c{os.getpid()}_{next(_conversion_seq)}_"


def convert_host_plan_json(payload: bytes | str) -> bytes:
    try:
        res = convert_plan(payload if isinstance(payload, str) else payload.decode())
        return json.dumps(_response(res)).encode()
    except Exception as e:  # noqa: BLE001 — the shim must never crash a query
        return json.dumps(
            {"converted": False, "error": f"{type(e).__name__}: {e}"}
        ).encode()


def _response(res: ConversionResult) -> dict:
    paths: dict[int, list[int]] = {}

    def index(node: HostNode, path: list[int]) -> None:
        paths[id(node)] = path
        for i, c in enumerate(node.children):
            index(c, path + [i])

    index(res.host_root, [])

    any_native = [False]

    def host_of(n) -> HostNode:
        return n.host if isinstance(n, NativeSegment) else n.node

    def rel_path(n, parent_abs: list[int]) -> list[int]:
        abs_p = paths.get(id(host_of(n)), [])
        return abs_p[len(parent_abs):]

    def emit(n, parent_abs: list[int]) -> dict:
        my_abs = paths.get(id(host_of(n)), [])
        if isinstance(n, NativeSegment):
            any_native[0] = True
            segment_rids = {rid for rid, _ in n.inputs}
            namespace = _namespace()
            stages = [
                {
                    "plan_b64": base64.b64encode(s.plan.SerializeToString()).decode(),
                    "exchange_id": s.exchange_id,
                    "num_output_partitions": s.num_output_partitions,
                    "input_exchange_ids": s.input_exchange_ids,
                    # {work_dir}/{partition} placeholders: the host derives
                    # task shuffle-file paths (and the reduce manifest) by
                    # string substitution only — it never parses plan protos
                    "output_data_template": s.data_template,
                    "output_index_template": s.index_template,
                    # which of the segment's FFI inputs feed THIS stage: the
                    # host must run the stage's tasks over those children's
                    # partitions and register "rid.pid" batch resources
                    "ffi_input_ids": [
                        r for r in ffi_reader_ids(s.plan) if r in segment_rids
                    ],
                    # per-stage scan pinning: a stage whose plan carries
                    # host-decided file groups must run exactly that many
                    # tasks (segment-level task_partitions is the max, kept
                    # for single-stage splicers)
                    "task_partitions": _pinned_task_partitions(s.plan),
                }
                for s in split_stages(n.plan, namespace=namespace)
            ]
            return {
                "kind": "segment",
                "path": rel_path(n, parent_abs),
                "plan_b64": base64.b64encode(n.plan.SerializeToString()).decode(),
                "stages": stages,
                "task_partitions": _pinned_task_partitions(n.plan),
                "schema": [
                    [f.name, _type_name(f.dtype), f.nullable] for f in n.schema
                ],
                "inputs": [
                    {"resource_id": rid, "child": emit(c, my_abs)}
                    for rid, c in n.inputs
                ],
            }
        assert isinstance(n, HostOp)
        return {
            "kind": "host",
            "path": rel_path(n, parent_abs),
            "op": n.node.op,
            "children": [emit(c, my_abs) for c in n.children],
        }

    root = emit(res.root, [])
    return {
        "converted": any_native[0],
        "root": root,
        "tags": [
            [op, ok, why]
            for op, ok, why in res.tags.summary(res.host_root)
        ],
    }


def _pinned_task_partitions(plan) -> int | None:
    """When a segment's file scan carries host-decided per-task file groups,
    the task count is pinned to the group count (running fewer tasks would
    silently drop file groups — exec/scan.py raises on out-of-range)."""
    from auron_tpu.plan.protowalk import child_nodes

    pinned: list[int] = []

    def rec(node):
        which = node.WhichOneof("plan")
        if which in ("parquet_scan", "orc_scan"):
            inner = getattr(node, which)
            if len(inner.partitions):
                pinned.append(len(inner.partitions))
        for c in child_nodes(node):
            rec(c)

    rec(plan)
    return max(pinned) if pinned else None


def _type_name(dtype) -> str:
    from auron_tpu import types as T

    k = dtype.kind
    simple = {
        T.TypeKind.BOOL: "boolean", T.TypeKind.INT8: "byte",
        T.TypeKind.INT16: "short", T.TypeKind.INT32: "int",
        T.TypeKind.INT64: "long", T.TypeKind.FLOAT32: "float",
        T.TypeKind.FLOAT64: "double", T.TypeKind.STRING: "string",
        T.TypeKind.BINARY: "binary", T.TypeKind.DATE32: "date",
        T.TypeKind.TIMESTAMP: "timestamp", T.TypeKind.NULL: "null",
    }
    if k in simple:
        return simple[k]
    if k == T.TypeKind.DECIMAL:
        return f"decimal({dtype.precision},{dtype.scale})"
    if k == T.TypeKind.LIST:
        return f"array<{_type_name(dtype.inner[0])}>"
    if k == T.TypeKind.MAP:
        return f"map<{_type_name(dtype.inner[0])},{_type_name(dtype.inner[1])}>"
    if k == T.TypeKind.STRUCT:
        inner = ",".join(
            f"{n}:{_type_name(t)}" for n, t in zip(dtype.struct_names, dtype.inner)
        )
        return f"struct<{inner}>"
    return str(k.value)

"""Hudi COW table metadata -> table-format scan descriptor.

VERDICT r4 missing #5: only Iceberg resolved real table metadata; Hudi
stayed descriptor-lowering only. This closes the Hudi half: resolve a
real Copy-on-Write table directory (``.hoodie/`` commit timeline +
``hoodie.properties``) into the same neutral descriptor
TableFormatScanProvider lowers to a pruned native parquet scan.
Reference analog: thirdparty/auron-hudi/ (which leans on Hudi's own
library; the image has none, so the resolution lives here against the
public Hudi table layout).

COW read semantics implemented:
- completed instants only: ``.hoodie/<ts>.commit`` (and
  ``<ts>.replacecommit``) files, ordered by instant time; inflight /
  requested instants are invisible;
- the LATEST FILE SLICE per file group wins: every commit's
  ``partitionToWriteStats`` names (fileId, path); a later commit's write
  for the same fileId replaces the earlier file (compaction/update),
  and replacecommits drop the file groups they replace;
- schema comes from the latest commit's ``extraMetadata.schema`` (an
  Avro record schema, written by Hudi writers);
- partition columns come from ``hoodie.properties``
  (``hoodie.table.partitionfields``) matched against the hive-style
  partition path segments.
"""

from __future__ import annotations

import json
import os

#: avro primitive -> engine hostplan type name
_AVRO_TYPES = {
    "boolean": "boolean",
    "int": "int",
    "long": "long",
    "float": "float",
    "double": "double",
    "string": "string",
    "bytes": "binary",
}


def _engine_type(t) -> str:
    """Engine type name for an Avro schema node (unions unwrap null)."""
    if isinstance(t, list):  # union, e.g. ["null", "long"]
        non_null = [x for x in t if x != "null"]
        return _engine_type(non_null[0]) if non_null else "string"
    if isinstance(t, dict):
        lt = t.get("logicalType")
        if lt == "date":
            return "date"
        if lt in ("timestamp-millis", "timestamp-micros"):
            return "timestamp"
        if lt == "decimal":  # both Avro encodings: fixed- AND bytes-backed
            return f"decimal({t.get('precision', 38)},{t.get('scale', 18)})"
        return _engine_type(t.get("type", "string"))
    if t in _AVRO_TYPES:
        return _AVRO_TYPES[t]
    raise ValueError(f"unsupported hudi/avro type {t!r}")


def _read_properties(path: str) -> dict:
    props = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#") or "=" not in line:
                    continue
                k, v = line.split("=", 1)
                props[k.strip()] = v.strip()
    except OSError:
        pass
    return props


def _partition_values(rel_path: str, partition_fields: list[str]) -> dict:
    """Partition values from a relative file path: hive-style ``k=v``
    segments by name, else positional against partition_fields."""
    segs = rel_path.split("/")[:-1]
    out = {}
    hive = {}
    for s in segs:
        if "=" in s:
            k, v = s.split("=", 1)
            hive[k] = v
    for i, f in enumerate(partition_fields):
        if f in hive:
            out[f] = hive[f]
        elif i < len(segs) and "=" not in segs[i]:
            out[f] = segs[i]
    return out


def resolve_hudi_scan(table_path: str) -> dict:
    """Resolve a real Hudi COW table directory into the HudiScanExec
    descriptor (hostplan node dict, filters empty — the converter merges
    the query's predicates)."""
    hoodie = os.path.join(table_path, ".hoodie")
    props = _read_properties(os.path.join(hoodie, "hoodie.properties"))
    table_type = props.get("hoodie.table.type", "COPY_ON_WRITE")
    if table_type != "COPY_ON_WRITE":
        raise ValueError(
            f"hudi table type {table_type!r} not supported (COW only; MOR "
            "log-file merging needs the format's own reader)"
        )
    part_fields = [
        p for p in props.get("hoodie.table.partitionfields", "").split(",") if p
    ]

    # completed commit timeline, instant-time order
    instants = []
    for fn in os.listdir(hoodie) if os.path.isdir(hoodie) else []:
        base = fn.split(".")
        if len(base) == 2 and base[1] in ("commit", "replacecommit"):
            instants.append((base[0], base[1], os.path.join(hoodie, fn)))
    instants.sort()

    # latest slice per file group (fileId); replaced groups drop
    slices: dict[str, tuple[str, str, int]] = {}  # fileId -> (ts, path, rows)
    schema_avro = None
    for ts, kind, path in instants:
        with open(path) as f:
            commit = json.load(f)
        meta_schema = (commit.get("extraMetadata") or {}).get("schema")
        if meta_schema:
            schema_avro = json.loads(meta_schema)
        for pstats in (commit.get("partitionToWriteStats") or {}).values():
            for st in pstats:
                fid = st.get("fileId")
                rel = st.get("path")
                if not fid or not rel:
                    continue
                slices[fid] = (ts, rel, int(st.get("numWrites", 0)))
        if kind == "replacecommit":
            for gids in (commit.get("partitionToReplaceFileIds") or {}).values():
                for fid in gids:
                    slices.pop(fid, None)

    if schema_avro is None:
        raise ValueError(f"no completed commit with a schema under {hoodie}")
    schema = [
        [f["name"], _engine_type(f["type"]),
         isinstance(f["type"], list) and "null" in f["type"]]
        for f in schema_avro["fields"]
        if not f["name"].startswith("_hoodie_")  # writer meta columns
    ]

    files = []
    for fid, (ts, rel, rows) in sorted(slices.items()):
        files.append({
            "path": os.path.join(table_path, rel),
            "partition": _partition_values(rel, part_fields),
            "record_count": rows,
            "format": "parquet",
        })
    return {
        "op": "HudiScanExec",
        "schema": schema,
        "args": {"files": files, "filters": [], "format": "parquet"},
    }

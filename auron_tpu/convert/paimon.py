"""Paimon table metadata -> table-format scan descriptor.

VERDICT r4 missing #5, second half: Iceberg and Hudi resolve real table
metadata; this closes Paimon. An append-only Paimon table directory
(``schema/schema-N`` JSON + ``snapshot/snapshot-N`` JSON + Avro manifest
lists/manifests + bucketed data files) resolves into the same neutral
descriptor TableFormatScanProvider lowers to a pruned native parquet
scan. Reference analog: thirdparty/auron-paimon/ (which leans on
Paimon's own reader stack; the image has none, so the resolution lives
here against the PUBLIC Paimon file layout).

Read semantics implemented:
- latest snapshot wins: ``snapshot/LATEST`` hint (or max snapshot-N);
  its ``schemaId`` picks the TableSchema from ``schema/schema-<id>``;
- live files = ADD entries minus DELETE entries applied in order over
  the snapshot's BASE manifest list then its DELTA manifest list (both
  Avro containers naming Avro manifest files);
- typed partition values decode from each entry's serialized BinaryRow
  ``_PARTITION`` key (null values map to the table's
  ``partition.default-name`` path segment);
- primary-key tables are refused: their LSM levels require merge-on-read
  (the format's own reader), same honest refusal as Hudi MOR.
"""

from __future__ import annotations

import json
import os
import re

from auron_tpu.utils.avro import read_container

#: Paimon SQL-style type string -> engine hostplan type name
_SIMPLE_TYPES = {
    "BOOLEAN": "boolean",
    "TINYINT": "int",
    "SMALLINT": "int",
    "INT": "int",
    "INTEGER": "int",
    "BIGINT": "long",
    "FLOAT": "float",
    "DOUBLE": "double",
    "STRING": "string",
    "BYTES": "binary",
    "BINARY": "binary",
    "VARBINARY": "binary",
    "DATE": "date",
}


def _engine_type(t: str) -> tuple[str, bool]:
    """(engine type name, nullable) for a Paimon type string like
    ``"BIGINT NOT NULL"`` / ``"DECIMAL(10, 2)"`` / ``"VARCHAR(32)"``."""
    s = t.strip()
    nullable = True
    up = s.upper()
    if up.endswith(" NOT NULL"):
        nullable = False
        up = up[: -len(" NOT NULL")].strip()
    base = up.split("(", 1)[0].strip()
    if base in _SIMPLE_TYPES:
        return _SIMPLE_TYPES[base], nullable
    if base in ("VARCHAR", "CHAR"):
        return "string", nullable
    if base == "DECIMAL":
        m = re.match(r"DECIMAL\s*\(\s*(\d+)\s*,\s*(\d+)\s*\)", up)
        p, sc = (m.group(1), m.group(2)) if m else ("38", "18")
        return f"decimal({p},{sc})", nullable
    if base in ("TIMESTAMP", "TIMESTAMP_LTZ"):
        return "timestamp", nullable
    # nested (ARRAY/MAP/ROW) and unknown types ship as an unparseable tag:
    # hostplan's schema parse marks the NODE degraded with a reason instead
    # of this resolver raising — one nested column must not block
    # resolution outright (same contract as the Iceberg resolver)
    return f"paimon:{s}", nullable


def _decode_binary_row(data: bytes, types: list[str]) -> list:
    """Decode a Paimon BinaryRow (the Flink BinaryRowData layout): an
    8-bit header + null bitset, then one 8-byte little-endian slot per
    field; var-length values live past the fixed part, small strings
    inline in the slot with the high bit of the last byte set."""
    arity = len(types)
    null_bits = ((arity + 8 + 63) // 64) * 8
    out = []
    for i, t in enumerate(types):
        bit = 8 + i
        if data[bit >> 3] & (1 << (bit & 7)):
            out.append(None)
            continue
        slot = data[null_bits + 8 * i : null_bits + 8 * i + 8]
        base = t.split("(", 1)[0].split()[0].upper()
        if base in ("INT", "INTEGER", "DATE", "TINYINT", "SMALLINT"):
            out.append(int.from_bytes(slot[:4], "little", signed=True))
        elif base == "BIGINT":
            out.append(int.from_bytes(slot, "little", signed=True))
        elif base == "BOOLEAN":
            out.append(bool(slot[0]))
        elif base in ("STRING", "VARCHAR", "CHAR"):
            if slot[7] & 0x80:  # compact: <=7 bytes inline
                ln = slot[7] & 0x7F
                out.append(slot[:ln].decode("utf-8"))
            else:
                v = int.from_bytes(slot, "little", signed=False)
                off, size = v >> 32, v & 0xFFFFFFFF
                out.append(data[off : off + size].decode("utf-8"))
        else:
            raise ValueError(f"unsupported paimon partition type {t!r}")
    return out


def _latest_snapshot_id(snap_dir: str) -> int:
    hint = os.path.join(snap_dir, "LATEST")
    if os.path.exists(hint):
        with open(hint) as f:
            sid = int(f.read().strip())
        # hints are best-effort in the layout: a stale/corrupt hint must
        # fall back to listing, not crash on a missing snapshot file
        if os.path.exists(os.path.join(snap_dir, f"snapshot-{sid}")):
            return sid
    ids = [
        int(fn.split("-", 1)[1])
        for fn in os.listdir(snap_dir)
        if fn.startswith("snapshot-") and fn.split("-", 1)[1].isdigit()
    ]
    if not ids:
        raise ValueError(f"no snapshots under {snap_dir}")
    return max(ids)


def _partition_rel(partition: dict) -> str:
    """Hive-style relative dir for a partition-values dict (layout order
    is the table's partitionKeys order, which the caller preserves)."""
    return "/".join(f"{k}={v}" for k, v in partition.items())


def _manifest_entries(table_path: str, manifest_list: str) -> list[dict]:
    """Flatten a manifest list (Avro) into its manifests' entries, in
    list order (base before delta is the CALLER's contract)."""
    mdir = os.path.join(table_path, "manifest")
    entries: list[dict] = []
    _, lists = read_container(os.path.join(mdir, manifest_list))
    for rec in lists:
        name = rec.get("_FILE_NAME")
        if not name:
            continue
        _, recs = read_container(os.path.join(mdir, name))
        entries.extend(recs)
    return entries


def resolve_paimon_scan(table_path: str) -> dict:
    """Resolve a real append-only Paimon table directory into the
    PaimonScanExec descriptor (hostplan node dict, filters empty — the
    converter merges the query's predicates)."""
    snap_dir = os.path.join(table_path, "snapshot")
    sid = _latest_snapshot_id(snap_dir)
    with open(os.path.join(snap_dir, f"snapshot-{sid}")) as f:
        snapshot = json.load(f)

    with open(
        os.path.join(table_path, "schema", f"schema-{snapshot['schemaId']}")
    ) as f:
        table_schema = json.load(f)
    if table_schema.get("primaryKeys"):
        raise ValueError(
            "paimon primary-key table not supported (LSM merge-on-read "
            "needs the format's own reader); append-only tables resolve"
        )
    part_keys = table_schema.get("partitionKeys") or []
    schema = []
    for fld in table_schema["fields"]:
        t, nullable = _engine_type(fld["type"])
        schema.append([fld["name"], t, nullable])
    part_types = [
        next(f["type"] for f in table_schema["fields"] if f["name"] == k)
        for k in part_keys
    ]

    opts = table_schema.get("options") or {}
    file_format = opts.get("file.format", "orc")
    default_part = opts.get("partition.default-name", "__DEFAULT_PARTITION__")

    # live files: ADDs minus DELETEs, base list first, then delta
    live: dict[tuple, dict] = {}
    for part in ("baseManifestList", "deltaManifestList"):
        name = snapshot.get(part)
        if not name:
            continue
        for e in _manifest_entries(table_path, name):
            fmeta = e.get("_FILE") or {}
            fname = fmeta.get("_FILE_NAME")
            if not fname:
                continue
            bucket = int(e.get("_BUCKET", 0))
            praw = e.get("_PARTITION") or b""
            pvals = (
                _decode_binary_row(praw, part_types) if part_keys else []
            )
            partition = dict(zip(part_keys, pvals))
            # null partition values live under the default partition name
            path_parts = {
                k: (default_part if v is None else v)
                for k, v in partition.items()
            }
            key = (tuple(str(v) for v in partition.values()), bucket, fname)
            if int(e.get("_KIND", 0)) == 0:  # ADD
                rel = os.path.join(
                    _partition_rel(path_parts), f"bucket-{bucket}", fname
                ) if partition else os.path.join(f"bucket-{bucket}", fname)
                ffmt = ("parquet" if fname.endswith(".parquet")
                        else "orc" if fname.endswith(".orc")
                        else file_format)
                if ffmt != "parquet":
                    # the provider lowers to a parquet scan; reading
                    # ORC/Avro data files as parquet would crash or
                    # return garbage (same refusal as Iceberg)
                    raise ValueError(
                        f"paimon data file {fname}: format {ffmt!r} is "
                        "not supported (parquet only)"
                    )
                live[key] = {
                    "path": os.path.join(table_path, rel),
                    "partition": partition,
                    "record_count": int(fmeta.get("_ROW_COUNT", 0)),
                    "format": ffmt,
                }
            else:  # DELETE (compaction dropped this file)
                live.pop(key, None)

    files = [live[k] for k in sorted(live)]
    return {
        "op": "PaimonScanExec",
        "schema": schema,
        "args": {"files": files, "filters": [], "format": file_format},
    }

"""Host expression -> engine IR conversion with UDF-fallback wrapping.

Analog of NativeConverters.convertExpr (NativeConverters.scala:329-1200):
every host expression either translates to a native ir.Expr, or — when
``udf.fallback.enable`` is on — is wrapped as a HostUDF evaluated through
the bridge callback (SparkUDFWrapper analog). If fallback is off, the
failure propagates and marks the owning operator unconvertible.
"""

from __future__ import annotations

from auron_tpu import types as T
from auron_tpu.convert.hostplan import parse_type
from auron_tpu.exprs import cast as cast_kernels
from auron_tpu.exprs import ir
from auron_tpu.functions import registry  # loads the full function registry
from auron_tpu.utils.config import UDF_FALLBACK_ENABLE, Configuration


class UnsupportedExpr(Exception):
    pass


_BINOPS = {
    "add": "add", "subtract": "sub", "multiply": "mul", "divide": "div",
    "remainder": "mod", "pmod": "mod",
    "equalto": "eq", "lessthan": "lt", "lessthanorequal": "lteq",
    "greaterthan": "gt", "greaterthanorequal": "gteq",
    "and": "and", "or": "or",
}

# host expression names -> engine scalar function names (identity unless
# listed); anything the function registry knows converts directly
_FN_RENAME = {
    "stringtrim": "trim",
    "stringtrimleft": "ltrim",
    "stringtrimright": "rtrim",
    "lower": "lower",
    "upper": "upper",
    "dateadd": "date_add",
    "datesub": "date_sub",
    "dayofmonth": "day",
    "createarray": "make_array",
    "makearray": "make_array",
    "createnamedstruct": "named_struct",
}


def convert_expr(e: dict, conf: Configuration, udf_registry: dict | None = None) -> ir.Expr:
    """Convert one host expression dict; raises UnsupportedExpr on failure
    (the caller decides whole-node fallback vs HostUDF wrapping).
    Malformed payloads (missing keys) degrade to UnsupportedExpr so the
    owning operator falls back instead of crashing conversion."""
    try:
        return _convert_expr(e, conf, udf_registry)
    except UnsupportedExpr:
        raise
    except (KeyError, TypeError, ValueError) as err:
        raise UnsupportedExpr(f"malformed host expression {e!r}: {err}") from err


def _convert_expr(e: dict, conf: Configuration, udf_registry: dict | None = None) -> ir.Expr:
    kind = e.get("kind")
    if kind == "attr":
        idx = int(e["index"])
        if idx < 0:
            raise UnsupportedExpr(
                "unbound attribute (host serializer could not resolve it)"
            )
        return ir.Column(idx, e.get("name", ""))
    if kind == "lit":
        dt = parse_type(e.get("type", "null"))
        v = e.get("value")
        if dt.kind == T.TypeKind.BINARY and isinstance(v, str):
            import base64

            v = base64.b64decode(v)  # serializer ships bytes as base64
        return ir.Literal(v, dt)
    if kind != "call":
        raise UnsupportedExpr(f"unknown expression kind {kind!r}")

    name = e["name"].lower()
    kids = e.get("children", [])

    def sub(i):
        return convert_expr(kids[i], conf, udf_registry)

    def subs():
        return [convert_expr(k, conf, udf_registry) for k in kids]

    if name in _BINOPS:
        return ir.BinaryOp(_BINOPS[name], sub(0), sub(1))
    if name == "not":
        return ir.Not(sub(0))
    if name == "isnull":
        return ir.IsNull(sub(0))
    if name == "isnotnull":
        return ir.IsNotNull(sub(0))
    if name == "cast":
        child = sub(0)
        to = parse_type(e["to"])
        # the serializer ships the source type ("from"); without it the only
        # statically-known source is a literal child
        src = parse_type(e["from"]) if "from" in e else getattr(child, "dtype", None)
        if src is not None and not cast_kernels.can_cast(src, to):
            raise UnsupportedExpr(f"cast {src} -> {to} is not castable")
        return ir.Cast(child, to, bool(e.get("try", False)))
    if name == "if":
        return ir.If(sub(0), sub(1), sub(2))
    if name == "casewhen":
        # "branches" is REQUIRED: a generic name+children serialization of
        # CaseWhen would otherwise become a silent all-NULL expression
        branches = tuple(
            (convert_expr(w, conf, udf_registry), convert_expr(t, conf, udf_registry))
            for w, t in e["branches"]
        )
        orelse = (
            convert_expr(e["else"], conf, udf_registry) if e.get("else") else None
        )
        return ir.Case(branches, orelse)
    if name == "in":
        # "values" is REQUIRED (a missing key would silently become an
        # empty IN list matching nothing). "value_type" (the serializer's
        # literal type tag) coerces items to typed scalars — string-encoded
        # decimals/dates become exact values instead of raw strings
        # (ADVICE r2: intCol IN (1,2,3) must not compare as strings).
        items = tuple(e["values"])
        vt = e.get("value_type")
        if vt:
            items = tuple(
                None if v is None else _coerce_literal(v, parse_type(vt))
                for v in items
            )
        return ir.In(sub(0), items, bool(e.get("negated")))
    if name == "coalesce":
        return ir.Coalesce(tuple(subs()))
    if name == "like":
        return ir.Like(sub(0), e["pattern"], bool(e.get("negated")),
                       e.get("escape", "\\"))
    if name == "sparkpartitionid":
        return ir.SparkPartitionId()
    if name == "monotonicallyincreasingid":
        return ir.MonotonicId()
    if name == "scalarsubquery":
        return ir.ScalarSubquery(e["resource_id"], parse_type(e["type"]))

    if name == "__hive_udf__":
        # Hive UDF (HiveUdfGlue.scala): the host serializer embedded the
        # serialized function (base64) in the plan, so ANY executor can
        # evaluate it through the C-ABI callback (bridge/udf.py
        # hive_blob_udf). Gated by the same udf fallback flag as
        # registered host UDFs.
        if not conf.get(UDF_FALLBACK_ENABLE):
            raise UnsupportedExpr("hive UDF with udf.fallback.enable off")
        out_t = parse_type(e.get("type", "string"))
        return ir.HostUDF(f"__hive:{e['udf_blob']}", tuple(subs()), out_t)

    fn = _FN_RENAME.get(name, name)
    if registry.lookup(fn) is not None:
        return ir.ScalarFunc(fn, tuple(subs()))

    # ---- host-UDF fallback (SparkUDFWrapper analog) ----
    if udf_registry is not None and name in udf_registry and conf.get(UDF_FALLBACK_ENABLE):
        out_t = parse_type(e.get("type", "string"))
        return ir.HostUDF(name, tuple(subs()), out_t)
    raise UnsupportedExpr(f"expression {e['name']!r} is not supported")


def _coerce_literal(v, dt):
    """Coerce a JSON-decoded IN-list item to the serializer's declared
    literal type. String-like types stay plain python strings (the string
    IN path compares dictionary entries); numeric/temporal/decimal items
    become typed ir.Literals so comparisons run in value space."""
    from auron_tpu import types as T

    k = dt.kind
    if k == T.TypeKind.STRING:
        return v
    if k == T.TypeKind.BINARY:
        # serializer ships binary values as base64 strings
        import base64

        return base64.b64decode(v) if isinstance(v, str) else v
    if k == T.TypeKind.BOOL:
        return ir.Literal(bool(v), dt)
    if k == T.TypeKind.DECIMAL:
        import decimal as pydec

        return ir.Literal(pydec.Decimal(str(v)), dt)
    if dt.is_integer or k in (T.TypeKind.DATE32, T.TypeKind.TIMESTAMP):
        return ir.Literal(int(v), dt)
    if k in (T.TypeKind.FLOAT32, T.TypeKind.FLOAT64):
        return ir.Literal(float(v), dt)
    return v


def convert_sort_fields(fields: list[dict], conf, udf_registry=None):
    from auron_tpu.ops.sortkeys import SortSpec

    out = []
    for f in fields:
        out.append(
            (
                convert_expr(f["expr"], conf, udf_registry),
                SortSpec(
                    asc=bool(f.get("asc", True)),
                    nulls_first=bool(f.get("nulls_first", f.get("asc", True))),
                ),
            )
        )
    return out

"""Host-engine plan conversion layer (L2).

The reference's reason to exist is intercepting a host engine's physical
plan and rewriting maximal convertible subtrees into native plans
(AuronConvertStrategy.scala:49-283, AuronConverters.scala:189-305,
NativeConverters.scala:329-1200). This package is that layer for the TPU
engine, driven by a *serialized host-plan description* (JSON) instead of
live JVM objects — a thin JVM/engine shim only needs to dump its physical
plan in this format and ship the resulting TaskDefinitions.

- hostplan:   the neutral host-plan tree format
- exprs:      host expression -> engine IR, with host-UDF fallback wrapping
- strategy:   bottom-up convertibility tagging + per-operator enable flags
              + removeInefficientConverts fixpoint
- converters: per-operator proto builders + maximal-subtree segmentation
"""

from auron_tpu.convert.converters import ConversionResult, convert_plan
from auron_tpu.convert.hostplan import HostNode
from auron_tpu.convert.strategy import ConvertTags

__all__ = ["HostNode", "ConversionResult", "ConvertTags", "convert_plan"]

"""Host-schedulable stage splitting + shuffle-manager contract.

The engine's own ``MeshQueryDriver`` resolves ``mesh_exchange`` nodes
internally (ICI all_to_all or file shuffle) — but a host engine like Spark
schedules stages ITSELF: the reference integrates by making stage N's plan
end in a native shuffle writer whose map output is committed to the host's
shuffle tracker, and stage N+1 start with a reader fed by the host's
shuffle fetch (AuronShuffleManager.scala:14-37,
NativeShuffleExchangeBase.scala:124-296, Shims.scala:249 MapStatus commit).

``split_stages`` performs the same decomposition on a converted plan:

    stage k   = subtree below a mesh_exchange, wrapped in shuffle_writer
                (one task per map partition; .data/.index file paths are
                filled per task by the host via ``stage_task``)
    stage k+1 = the consumer, with the exchange spliced into an ipc_reader
                whose resource id is the exchange id

``ShuffleManager`` is the host-side contract: map tasks register their
(map_partition -> data/index) outputs per exchange (the MapStatus commit
analog); reduce tasks fetch a block provider that serves exactly those
files. A JSON *manifest* form of the registration crosses the C ABI for
out-of-process hosts (see ``manifest``/``provider_from_manifest`` and
bridge/api.put_resource_shuffle).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from auron_tpu.plan.protowalk import child_nodes, rewrite_children
from auron_tpu.proto import plan_pb2 as pb

DATA_TEMPLATE = "{work_dir}/{exchange_id}_map{partition}.data"
INDEX_TEMPLATE = "{work_dir}/{exchange_id}_map{partition}.index"


@dataclass
class StageSpec:
    """One host-schedulable stage of a split plan."""

    stage_id: int
    plan: pb.PhysicalPlanNode  # shuffle_writer root for producer stages
    exchange_id: str | None  # exchange this stage PRODUCES (None = final)
    num_output_partitions: int | None  # reduce width of the produced exchange
    input_exchange_ids: list[str] = field(default_factory=list)

    @property
    def is_final(self) -> bool:
        return self.exchange_id is None

    @property
    def data_template(self) -> str | None:
        """Shuffle data-file path template with {work_dir}/{partition}
        placeholders — the host computes task file paths by plain string
        substitution, never touching the plan proto (TaskDefs contract)."""
        if self.exchange_id is None:
            return None
        return DATA_TEMPLATE.replace("{exchange_id}", self.exchange_id)

    @property
    def index_template(self) -> str | None:
        if self.exchange_id is None:
            return None
        return INDEX_TEMPLATE.replace("{exchange_id}", self.exchange_id)


def ffi_reader_ids(plan: pb.PhysicalPlanNode) -> list[str]:
    """Resource ids of every ffi_reader in a plan subtree (dedup, in
    tree order) — tells a host which segment inputs feed which stage."""
    out: list[str] = []

    def rec(node: pb.PhysicalPlanNode) -> None:
        if node.WhichOneof("plan") == "ffi_reader":
            rid = node.ffi_reader.resource_id
            if rid not in out:
                out.append(rid)
        for c in child_nodes(node):
            rec(c)

    rec(plan)
    return out


def split_stages(
    plan: pb.PhysicalPlanNode, namespace: str = ""
) -> list[StageSpec]:
    """Decompose a plan with mesh_exchange nodes into host-schedulable
    stages, producers before consumers (post-order). ``namespace``
    prefixes every exchange id (writer paths AND reader resource ids) so
    concurrent conversions in one engine process can't collide on
    executor-side resource keys."""
    stages: list[StageSpec] = []
    counter = [0]

    def rewrite(node: pb.PhysicalPlanNode, inputs: list[str]) -> pb.PhysicalPlanNode:
        which = node.WhichOneof("plan")
        if which == "mesh_exchange":
            ex = node.mesh_exchange
            child_inputs: list[str] = []
            child = rewrite(ex.child, child_inputs)
            ex_id = namespace + (
                ex.exchange_id or f"__stage_exchange_{counter[0]}"
            )
            counter[0] += 1
            writer = pb.PhysicalPlanNode(
                shuffle_writer=pb.ShuffleWriterNode(
                    child=child,
                    partitioning=ex.partitioning,
                    output_data_file=DATA_TEMPLATE.replace(
                        "{exchange_id}", ex_id
                    ),
                    output_index_file=INDEX_TEMPLATE.replace(
                        "{exchange_id}", ex_id
                    ),
                )
            )
            stages.append(
                StageSpec(
                    stage_id=len(stages),
                    plan=writer,
                    exchange_id=ex_id,
                    num_output_partitions=int(ex.partitioning.num_partitions),
                    input_exchange_ids=child_inputs,
                )
            )
            inputs.append(ex_id)
            schema = _plan_schema(child)
            return pb.PhysicalPlanNode(
                ipc_reader=pb.IpcReaderNode(schema=schema, resource_id=ex_id)
            )
        return rewrite_children(node, lambda c: rewrite(c, inputs))

    final_inputs: list[str] = []
    final = rewrite(plan, final_inputs)
    stages.append(
        StageSpec(
            stage_id=len(stages),
            plan=final,
            exchange_id=None,
            num_output_partitions=None,
            input_exchange_ids=final_inputs,
        )
    )
    return stages


def _plan_schema(node: pb.PhysicalPlanNode) -> pb.Schema:
    """Output schema of a plan subtree (instantiates operators, no exec)."""
    from auron_tpu.plan.planner import plan_from_proto, schema_to_proto

    return schema_to_proto(plan_from_proto(node).schema)


def stage_task(
    spec: StageSpec,
    partition: int,
    work_dir: str,
    conf: dict | None = None,
) -> pb.TaskDefinition:
    """Instantiate one task of a stage: clone the stage plan, fill this
    task's shuffle output file paths (the host owns file placement, like
    Spark's shuffle block resolver), stamp stage/partition ids."""
    plan = pb.PhysicalPlanNode()
    plan.CopyFrom(spec.plan)
    _fill_paths(plan, partition, work_dir)
    t = pb.TaskDefinition(
        plan=plan, stage_id=spec.stage_id, partition_id=partition
    )
    for k, v in (conf or {}).items():
        t.conf[k] = str(v)
    return t


def _fill_paths(node: pb.PhysicalPlanNode, partition: int, work_dir: str) -> None:
    which = node.WhichOneof("plan")
    if which == "shuffle_writer":
        inner = node.shuffle_writer
        inner.output_data_file = inner.output_data_file.format(
            work_dir=work_dir, partition=partition
        )
        inner.output_index_file = inner.output_index_file.format(
            work_dir=work_dir, partition=partition
        )
    for c in child_nodes(node):
        _fill_paths(c, partition, work_dir)


# ---------------------------------------------------------------------------
# shuffle-manager contract (AuronShuffleManager / MapStatus analog)
# ---------------------------------------------------------------------------


class ShuffleManager:
    """Tracks committed map outputs per exchange and serves block providers
    to reduce tasks. In-process hosts use the object directly; out-of-process
    hosts ship the JSON manifest over the C ABI."""

    def __init__(self):
        self._outputs: dict[str, dict[int, tuple[str, str]]] = {}

    def register_map_output(
        self, exchange_id: str, map_partition: int, data_file: str, index_file: str
    ) -> None:
        """MapStatus commit: a map task's shuffle files become visible."""
        self._outputs.setdefault(exchange_id, {})[map_partition] = (
            data_file, index_file,
        )

    def map_outputs(self, exchange_id: str) -> list[tuple[str, str]]:
        by_part = self._outputs.get(exchange_id, {})
        return [by_part[p] for p in sorted(by_part)]

    def block_provider(self, exchange_id: str):
        from auron_tpu.exec.shuffle.reader import MultiMapBlockProvider

        return MultiMapBlockProvider(self.map_outputs(exchange_id))

    def manifest(self, exchange_id: str) -> bytes:
        """JSON manifest of an exchange's map outputs — the cross-process
        form of ``block_provider`` (shipped through put_resource_shuffle)."""
        return json.dumps(
            [
                {"data": d, "index": i}
                for d, i in self.map_outputs(exchange_id)
            ]
        ).encode()


def provider_from_manifest(payload: bytes | str):
    """Rebuild a reduce-side block provider from a JSON manifest."""
    from auron_tpu.exec.shuffle.reader import MultiMapBlockProvider

    entries = json.loads(payload)
    pairs = [(e["data"], e["index"]) for e in entries]
    for d, i in pairs:
        if not (os.path.exists(d) and os.path.exists(i)):
            raise FileNotFoundError(f"missing shuffle files {d} / {i}")
    return MultiMapBlockProvider(pairs)

"""Neutral serialized host-plan format.

A host-engine shim (Spark/Flink) serializes its fully-optimized physical
plan into this JSON-able tree; the conversion layer consumes it. Shape:

    {"op": "ProjectExec",
     "schema": [["name", "long", true], ...],       # output schema
     "args": {"projections": [<expr>, ...], ...},   # op-specific payload
     "children": [<node>, ...]}

Expressions are dicts: {"kind": "attr", "index": i} bound references,
{"kind": "lit", "value": v, "type": t}, and {"kind": "call",
"name": <spark-expression-name>, "children": [...], ...} — the same
bound-reference + expression-class model NativeConverters translates
(NativeConverters.scala:329).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from auron_tpu import types as T


def parse_type(s: str) -> T.DataType:
    raw = s.strip()  # struct field names are case-sensitive
    s = raw.lower()
    simple = {
        "boolean": T.BOOL,
        "byte": T.INT8,
        "tinyint": T.INT8,
        "short": T.INT16,
        "smallint": T.INT16,
        "int": T.INT32,
        "integer": T.INT32,
        "long": T.INT64,
        "bigint": T.INT64,
        "float": T.FLOAT32,
        "double": T.FLOAT64,
        "string": T.STRING,
        "binary": T.BINARY,
        "date": T.DATE32,
        "timestamp": T.TIMESTAMP,
        "null": T.NULL,
    }
    if s in simple:
        return simple[s]
    if s.startswith("decimal"):
        if "(" in s:
            p, sc = s[s.index("(") + 1 : s.index(")")].split(",")
            return T.decimal(int(p), int(sc))
        return T.decimal(10, 0)
    if s.startswith("array<") and s.endswith(">"):
        return T.DataType(T.TypeKind.LIST, inner=(parse_type(raw[6:-1]),))
    if s.startswith("map<") and s.endswith(">"):
        parts = _split_top(raw[4:-1])
        if len(parts) != 2:
            raise ValueError(f"unsupported host type {s!r}")
        k, v = parts
        return T.DataType(T.TypeKind.MAP, inner=(parse_type(k), parse_type(v)))
    if s.startswith("struct<") and s.endswith(">"):
        names, inners = [], []
        for part in _split_top(raw[7:-1]):
            name, _, t = part.partition(":")
            names.append(name.strip())
            inners.append(parse_type(t))
        return T.DataType(
            T.TypeKind.STRUCT, inner=tuple(inners), struct_names=tuple(names)
        )
    raise ValueError(f"unsupported host type {s!r}")


def _split_top(s: str) -> list[str]:
    """Split on commas at bracket/paren depth 0
    (struct<a:decimal(10,2),b:map<int,int>>)."""
    out, depth, start = [], 0, 0
    for i, ch in enumerate(s):
        if ch in "<(":
            depth += 1
        elif ch in ">)":
            depth -= 1
        elif ch == "," and depth == 0:
            out.append(s[start:i])
            start = i + 1
    out.append(s[start:])
    return [p.strip() for p in out]


@dataclass
class HostNode:
    """One operator of the host engine's physical plan."""

    op: str  # host exec class name, e.g. "ProjectExec"
    schema: T.Schema  # output schema
    args: dict = field(default_factory=dict)
    children: list["HostNode"] = field(default_factory=list)
    # non-None when this node's declared schema contains a type the engine
    # can't represent: the node itself becomes NeverConvert (the reference
    # tags only the owning operator, AuronConvertStrategy.scala), while
    # sibling subtrees stay convertible
    schema_error: str | None = None

    @staticmethod
    def from_json(data: dict | str) -> "HostNode":
        if isinstance(data, str):
            data = json.loads(data)
        fields = []
        schema_error = None
        for name, t, nullable in data.get("schema", []):
            try:
                dtype = parse_type(t)
            except ValueError as e:
                # UNSUPPORTED placeholder: the owning node degrades, and any
                # parent binding this column fails its own trial conversion
                # (physical_dtype / proto lowering raise on this kind)
                dtype = T.DataType(T.TypeKind.UNSUPPORTED)
                if schema_error is None:
                    schema_error = str(e)
            fields.append(T.Field(name, dtype, bool(nullable)))
        return HostNode(
            op=data["op"],
            schema=T.Schema(tuple(fields)),
            args=data.get("args", {}),
            children=[HostNode.from_json(c) for c in data.get("children", [])],
            schema_error=schema_error,
        )

    def walk_up(self):
        """Post-order (children first) — the tagging order of
        AuronConvertStrategy.apply's foreachUp."""
        for c in self.children:
            yield from c.walk_up()
        yield self

    def walk_down(self):
        yield self
        for c in self.children:
            yield from c.walk_down()

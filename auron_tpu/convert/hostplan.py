"""Neutral serialized host-plan format.

A host-engine shim (Spark/Flink) serializes its fully-optimized physical
plan into this JSON-able tree; the conversion layer consumes it. Shape:

    {"op": "ProjectExec",
     "schema": [["name", "long", true], ...],       # output schema
     "args": {"projections": [<expr>, ...], ...},   # op-specific payload
     "children": [<node>, ...]}

Expressions are dicts: {"kind": "attr", "index": i} bound references,
{"kind": "lit", "value": v, "type": t}, and {"kind": "call",
"name": <spark-expression-name>, "children": [...], ...} — the same
bound-reference + expression-class model NativeConverters translates
(NativeConverters.scala:329).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from auron_tpu import types as T


def parse_type(s: str) -> T.DataType:
    s = s.strip().lower()
    simple = {
        "boolean": T.BOOL,
        "byte": T.INT8,
        "tinyint": T.INT8,
        "short": T.INT16,
        "smallint": T.INT16,
        "int": T.INT32,
        "integer": T.INT32,
        "long": T.INT64,
        "bigint": T.INT64,
        "float": T.FLOAT32,
        "double": T.FLOAT64,
        "string": T.STRING,
        "binary": T.BINARY,
        "date": T.DATE32,
        "timestamp": T.TIMESTAMP,
        "null": T.NULL,
    }
    if s in simple:
        return simple[s]
    if s.startswith("decimal"):
        if "(" in s:
            p, sc = s[s.index("(") + 1 : s.index(")")].split(",")
            return T.decimal(int(p), int(sc))
        return T.decimal(10, 0)
    if s.startswith("array<") and s.endswith(">"):
        return T.DataType(T.TypeKind.LIST, inner=(parse_type(s[6:-1]),))
    raise ValueError(f"unsupported host type {s!r}")


@dataclass
class HostNode:
    """One operator of the host engine's physical plan."""

    op: str  # host exec class name, e.g. "ProjectExec"
    schema: T.Schema  # output schema
    args: dict = field(default_factory=dict)
    children: list["HostNode"] = field(default_factory=list)

    @staticmethod
    def from_json(data: dict | str) -> "HostNode":
        if isinstance(data, str):
            data = json.loads(data)
        fields = tuple(
            T.Field(name, parse_type(t), bool(nullable))
            for name, t, nullable in data.get("schema", [])
        )
        return HostNode(
            op=data["op"],
            schema=T.Schema(fields),
            args=data.get("args", {}),
            children=[HostNode.from_json(c) for c in data.get("children", [])],
        )

    def walk_up(self):
        """Post-order (children first) — the tagging order of
        AuronConvertStrategy.apply's foreachUp."""
        for c in self.children:
            yield from c.walk_up()
        yield self

    def walk_down(self):
        yield self
        for c in self.children:
            yield from c.walk_down()

"""Bottom-up convertibility tagging + inefficient-convert removal.

Analog of AuronConvertStrategy (AuronConvertStrategy.scala:49-283):

1. every node is trial-converted bottom-up; failures tag NeverConvert with
   a reason (per-operator enable flags gate conversion exactly like the
   reference's SparkAuronConfiguration.ENABLE_* keys,
   AuronConverters.scala:98-128);
2. a fixpoint pass reverts conversions that would force expensive
   row<->columnar boundaries for little native benefit — the same rule set
   as removeInefficientConverts (AuronConvertStrategy.scala:205-283):
   filter/agg over a non-native child, shuffle over a non-native agg,
   native expand/scan/sort feeding a non-native parent.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from auron_tpu.convert.hostplan import HostNode
from auron_tpu.utils.config import Configuration, bool_conf

# per-operator enable flags (reference: ENABLE_* keys); registered once
_OP_KEYS = [
    "scan", "project", "filter", "sort", "union", "smj", "shj", "bhj",
    "local_limit", "global_limit", "take_ordered_and_project", "aggr",
    "expand", "window", "window_group_limit", "generate",
    "local_table_scan", "data_writing", "broadcast_exchange",
    "shuffle_exchange", "kafka_scan",
]
ENABLE_FLAGS = {
    k: bool_conf(f"convert.enable.{k}", True, "convert",
                 f"convert host {k} operators to native plans")
    for k in _OP_KEYS
}

# host exec class -> enable-flag key
OP_FLAG = {
    "FileSourceScanExec": "scan",
    "OrcScanExec": "scan",
    "LocalTableScanExec": "local_table_scan",
    "ProjectExec": "project",
    "FilterExec": "filter",
    "SortExec": "sort",
    "UnionExec": "union",
    "SortMergeJoinExec": "smj",
    "ShuffledHashJoinExec": "shj",
    "BroadcastHashJoinExec": "bhj",
    "LocalLimitExec": "local_limit",
    "GlobalLimitExec": "global_limit",
    "TakeOrderedAndProjectExec": "take_ordered_and_project",
    "HashAggregateExec": "aggr",
    "ObjectHashAggregateExec": "aggr",
    "SortAggregateExec": "aggr",
    "ExpandExec": "expand",
    "WindowExec": "window",
    "WindowGroupLimitExec": "window_group_limit",
    "GenerateExec": "generate",
    "DataWritingCommandExec": "data_writing",
    "BroadcastExchangeExec": "broadcast_exchange",
    "ShuffleExchangeExec": "shuffle_exchange",
    # streaming front-end (Flink table source; jvm/flink-extension)
    "KafkaSourceExec": "kafka_scan",
}

_AGG_OPS = {"HashAggregateExec", "ObjectHashAggregateExec", "SortAggregateExec"}


@dataclass
class ConvertTags:
    """Per-node conversion verdicts, keyed by node identity."""

    convertible: dict[int, bool] = field(default_factory=dict)
    reason: dict[int, str] = field(default_factory=dict)

    def ok(self, node: HostNode) -> bool:
        return self.convertible.get(id(node), False)

    def never(self, node: HostNode, reason: str) -> None:
        self.convertible[id(node)] = False
        self.reason.setdefault(id(node), reason)

    def why(self, node: HostNode) -> str | None:
        return self.reason.get(id(node))

    def summary(self, root: HostNode) -> list[tuple[str, bool, str | None]]:
        return [
            (n.op, self.ok(n), self.why(n)) for n in root.walk_down()
        ]


def tag_plan(root: HostNode, conf: Configuration, try_convert) -> ConvertTags:
    """Bottom-up trial conversion (AuronConvertStrategy.apply).

    ``try_convert(node, tags)`` must raise with a reason when the node (with
    its children assumed converted where tagged) cannot convert."""
    from auron_tpu.convert.providers import find_provider

    tags = ConvertTags()
    for node in root.walk_up():
        if node.schema_error is not None:
            # unsupported column type: only the owning node degrades
            tags.never(node, f"{node.op}: {node.schema_error}")
            continue
        flag_key = OP_FLAG.get(node.op)
        if flag_key is None:
            # extension point: table-format / third-party providers
            # (AuronConvertProvider SPI analog)
            if find_provider(node, conf) is not None:
                try:
                    try_convert(node, tags)
                    tags.convertible[id(node)] = True
                except Exception as e:  # noqa: BLE001
                    tags.never(node, f"{node.op}: {e}")
                continue
            tags.never(node, f"{node.op} is not supported yet.")
            continue
        if not conf.get(ENABLE_FLAGS[flag_key]):
            tags.never(node, f"{node.op} disabled by convert.enable.{flag_key}")
            continue
        try:
            try_convert(node, tags)
            tags.convertible[id(node)] = True
        except Exception as e:  # noqa: BLE001 — reason captured like the reference
            tags.never(node, f"{node.op}: {e}")
    _remove_inefficient_converts(root, tags)
    return tags


def _remove_inefficient_converts(root: HostNode, tags: ConvertTags) -> None:
    """Fixpoint rule set of AuronConvertStrategy.removeInefficientConverts."""
    parent_of: dict[int, HostNode | None] = {id(root): None}
    for n in root.walk_down():
        for c in n.children:
            parent_of[id(c)] = n

    finished = False
    while not finished:
        finished = True

        def dont_convert(node: HostNode, cond: bool, reason: str):
            nonlocal finished
            if cond and tags.ok(node):
                tags.never(node, reason)
                finished = False

        def induced_boundary(e: HostNode) -> bool:
            """True when converting e would CREATE a row->columnar
            boundary. A FlinkStreamInput child is a DECLARED stream
            boundary (jvm/flink-extension Calc shadow) — the conversion
            cost exists either way, so the rule must not demote."""
            return (
                bool(e.children)
                and not tags.ok(e.children[0])
                and e.children[0].op != "FlinkStreamInput"
            )

        for e in root.walk_down():
            # NonNative -> NativeFilter / NativeAgg: converting would force
            # a row->columnar conversion of a large input
            if tags.ok(e) and e.op == "FilterExec":
                dont_convert(
                    e, induced_boundary(e), f"{e.op}, children is not native.",
                )
            if tags.ok(e) and e.op in _AGG_OPS:
                dont_convert(
                    e, induced_boundary(e), f"{e.op}, children is not native.",
                )
            # Agg -> NativeShuffle: next stage likely reads non-natively
            if tags.ok(e) and e.op == "ShuffleExchangeExec":
                c = e.children[0] if e.children else None
                dont_convert(
                    e, c is not None and c.op in _AGG_OPS and not tags.ok(c),
                    f"{e.op}, children is not native and children is agg.",
                )
            # native Expand/Scan feeding a non-native parent forces C2R of
            # a large output
            if not tags.ok(e):
                for c in e.children:
                    if c.op == "ExpandExec":
                        dont_convert(
                            c, tags.ok(c), f"{e.op}, children is nativeExpand."
                        )
                    if c.op in ("FileSourceScanExec", "OrcScanExec"):
                        dont_convert(
                            c, tags.ok(c), f"{e.op}, children is nativeParquetScan."
                        )
                    # NonNative -> NativeSort -> NonNative sandwich
                    if c.op == "SortExec":
                        dont_convert(
                            c,
                            tags.ok(c)
                            and c.children
                            and not tags.ok(c.children[0]),
                            f"{e.op}, children and parent both are not native.",
                        )

"""Pluggable conversion providers (AuronConvertProvider SPI analog).

The reference extends its conversion layer through a ServiceLoader SPI
(spark-extension/.../AuronConvertProvider.scala: isEnabled / isSupported /
convert) — the mechanism behind the Iceberg/Hudi/Paimon table-format
plugins (thirdparty/auron-{iceberg,hudi,paimon}). Here providers register
with the conversion layer and are consulted for host operators the
built-in converter table doesn't know.
"""

from __future__ import annotations

from typing import Protocol

from auron_tpu.convert.hostplan import HostNode
from auron_tpu.proto import plan_pb2 as pb
from auron_tpu.utils.config import Configuration, bool_conf

TABLE_FORMATS_ENABLE = bool_conf(
    "convert.enable.table_formats", True, "convert",
    "convert table-format scans (iceberg/hudi/paimon descriptors) to "
    "native file scans",
)


class ConvertProvider(Protocol):
    def is_enabled(self, node: HostNode, conf: Configuration) -> bool: ...

    def is_supported(self, node: HostNode) -> bool: ...

    def convert(
        self, node: HostNode, children: list[pb.PhysicalPlanNode],
        conf: Configuration,
    ) -> pb.PhysicalPlanNode: ...


_PROVIDERS: list[ConvertProvider] = []


def register_provider(p: ConvertProvider) -> None:
    _PROVIDERS.append(p)


def find_provider(node: HostNode, conf: Configuration) -> ConvertProvider | None:
    for p in _PROVIDERS:
        if p.is_supported(node) and p.is_enabled(node, conf):
            return p
    return None


def _install_builtin_providers() -> None:
    from auron_tpu.convert.table_formats import TableFormatScanProvider

    register_provider(TableFormatScanProvider())


_install_builtin_providers()

"""Per-operator host-plan -> proto conversion + maximal-subtree segmentation.

Analog of AuronConverters.convertSparkPlanRecursively/convertSparkPlan
(AuronConverters.scala:189-305): after tagging, every maximal convertible
subtree is lowered into ONE native plan (a ``NativeSegment``); an
unconvertible child below it becomes an ``ffi_reader`` boundary node (the
ConvertToNative analog, ConvertToNativeBase.scala:49-86) whose rows the
host feeds through the resource map at run time.

Spark shuffle exchanges convert to ``mesh_exchange`` nodes, so a converted
multi-stage plan runs directly under MeshQueryDriver with the ICI-vs-file
transport decision applied per exchange.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from auron_tpu import types as T
from auron_tpu.convert.exprs import convert_expr, convert_sort_fields
from auron_tpu.convert.hostplan import HostNode
from auron_tpu.convert.strategy import ConvertTags, tag_plan
from auron_tpu.plan import builders as B
from auron_tpu.proto import plan_pb2 as pb
from auron_tpu.utils.config import Configuration


@dataclass
class NativeSegment:
    """A maximal convertible subtree lowered to one native plan."""

    plan: pb.PhysicalPlanNode
    schema: T.Schema
    inputs: list[tuple[str, "ConvertedNode"]] = field(default_factory=list)
    host: HostNode | None = None  # the subtree root this segment covers

    @property
    def is_native(self) -> bool:
        return True


@dataclass
class HostOp:
    """An operator left on the host engine."""

    node: HostNode
    children: list["ConvertedNode"] = field(default_factory=list)

    @property
    def is_native(self) -> bool:
        return False


ConvertedNode = NativeSegment | HostOp


@dataclass
class ConversionResult:
    root: ConvertedNode
    tags: ConvertTags
    host_root: HostNode

    def explain(self) -> str:
        lines: list[str] = []

        def rec(n: ConvertedNode, depth: int):
            pad = "  " * depth
            if isinstance(n, NativeSegment):
                lines.append(f"{pad}NativeSegment[{n.plan.WhichOneof('plan')}]")
                for rid, child in n.inputs:
                    lines.append(f"{pad}  <- ffi:{rid}")
                    rec(child, depth + 2)
            else:
                why = self.tags.why(n.node)
                lines.append(f"{pad}Host[{n.node.op}]" + (f"  # {why}" if why else ""))
                for c in n.children:
                    rec(c, depth + 1)

        rec(self.root, 0)
        return "\n".join(lines)


def convert_plan(
    root: HostNode | dict | str,
    conf: Configuration | None = None,
    udf_registry: dict | None = None,
) -> ConversionResult:
    """Tag + segment a serialized host plan (the whole L2 pipeline)."""
    if not isinstance(root, HostNode):
        root = HostNode.from_json(root)
    conf = conf or Configuration()
    conv = _Converter(conf, udf_registry)

    def try_convert(node: HostNode, tags: ConvertTags) -> None:
        # trial conversion with child boundaries stubbed as ffi readers
        stubs = [B.ffi_reader(c.schema, "__stub") for c in node.children]
        conv.to_proto(node, stubs)

    tags = tag_plan(root, conf, try_convert)
    seq = [0]

    def build(node: HostNode) -> ConvertedNode:
        if tags.ok(node):
            inputs: list[tuple[str, ConvertedNode]] = []
            proto = lower(node, inputs)
            return NativeSegment(proto, node.schema, inputs, host=node)
        return HostOp(node, [build(c) for c in node.children])

    def lower(node: HostNode, inputs) -> pb.PhysicalPlanNode:
        child_protos = []
        for c in node.children:
            if tags.ok(c):
                child_protos.append(lower(c, inputs))
            else:
                rid = f"__convert_input_{seq[0]}"
                seq[0] += 1
                inputs.append((rid, build(c)))
                child_protos.append(B.ffi_reader(c.schema, rid))
        return conv.to_proto(node, child_protos)

    return ConversionResult(build(root), tags, root)


def _range_partitioning_proto(fields, num: int, bound_rows: list) -> pb.Partitioning:
    """RANGE partitioning proto from host-sampled bound rows.

    ``bound_rows``: one row per boundary, each a list of typed literal dicts
    ({"value": v, "type": t}) for the sort keys. Dict-encoded key types
    (strings) are rejected — their orderable words are per-dictionary ranks,
    not comparable against data batches — so the owning exchange degrades to
    host execution instead of mis-routing."""
    import numpy as np

    from auron_tpu.columnar.batch import Batch
    from auron_tpu.convert.hostplan import parse_type
    from auron_tpu.exprs.eval import ColumnVal
    from auron_tpu.ops.sortkeys import sort_operands
    from auron_tpu.plan.builders import sort_field

    specs = [s for _, s in fields]
    part = pb.Partitioning(kind=pb.Partitioning.RANGE, num_partitions=num)
    for e, s in fields:
        part.range_fields.add().CopyFrom(sort_field(e, s))
    if not bound_rows:
        if num > 1:
            # without host-sampled bounds every row routes to partition 0 —
            # degrade to host execution instead of silently mis-scattering
            raise ValueError("range partitioning requires host-sampled bounds")
        part.range_words_per_bound = 2 * len(fields)
        return part
    n_keys = len(bound_rows[0])
    import pyarrow as pa

    cols = []
    for k in range(n_keys):
        dt = parse_type(bound_rows[0][k]["type"])
        if dt.is_dict_encoded:
            raise ValueError("range bounds over dictionary-encoded keys")
        arr = pa.array([r[k]["value"] for r in bound_rows], type=dt.to_arrow())
        cols.append((arr, dt))
    rb = pa.record_batch([a for a, _ in cols],
                         names=[f"b{k}" for k in range(n_keys)])
    sample = Batch.from_arrow(rb)
    keys = [
        ColumnVal(sample.col_values(k), sample.col_validity(k), dt, sample.dicts[k])
        for k, (_, dt) in enumerate(cols)
    ]
    import jax

    # auronlint: sync-point(call) -- range-bound sampling at plan time (driver side, once per query); one batched transfer
    words_d, sel_d = jax.device_get((tuple(sort_operands(keys, specs)),
                                     sample.device.sel))
    words = [np.asarray(w) for w in words_d]
    sel = np.asarray(sel_d)
    live = np.nonzero(sel)[0]
    mat = np.stack([w[live] for w in words], axis=1).astype(np.uint64)
    part.range_words_per_bound = mat.shape[1]
    part.range_bound_words.extend(int(x) for x in mat.reshape(-1))
    return part


# ---------------------------------------------------------------------------
# per-operator converters (AuronConverters.scala:212-305 case set)
# ---------------------------------------------------------------------------


class _Converter:
    def __init__(self, conf: Configuration, udf_registry: dict | None):
        self.conf = conf
        self.udfs = udf_registry

    def expr(self, e: dict):
        return convert_expr(e, self.conf, self.udfs)

    def to_proto(self, node: HostNode, children: list[pb.PhysicalPlanNode]):
        fn = getattr(self, "_c_" + node.op, None)
        if fn is None:
            from auron_tpu.convert.providers import find_provider

            provider = find_provider(node, self.conf)
            if provider is not None:
                return provider.convert(node, children, self.conf)
            raise ValueError(f"{node.op} has no converter")
        return fn(node, children)

    # ---- scans ----

    def _c_LocalTableScanExec(self, n, ch):
        return B.memory_scan(n.schema, n.args["resource_id"])

    def _c_FileSourceScanExec(self, n, ch):
        fmt = n.args.get("format", "parquet")
        pruning = [self.expr(e) for e in n.args.get("filters", [])]
        # host-decided task placement: "partitions" (per-task file groups)
        # beats the flat "files" list — a real Spark scan must not read the
        # whole table in every task (ADVICE r2)
        partitions = n.args.get("partitions")
        if fmt == "orc":
            from auron_tpu.plan.builders import _wrap

            node = pb.OrcScanNode(
                schema=B.schema_to_proto(n.schema),
                file_paths=list(n.args["files"]),
                fs_resource_id=n.args.get("fs_resource_id", ""),
            )
            for p in pruning:
                node.pruning_predicates.add().CopyFrom(B.expr_to_proto(p))
            for group in partitions or []:
                node.partitions.add().paths.extend(group)
            return _wrap(orc_scan=node)
        node = B.parquet_scan(
            n.schema, n.args["files"], pruning,
            n.args.get("fs_resource_id", ""),
        )
        for group in partitions or []:
            node.parquet_scan.partitions.add().paths.extend(group)
        return node

    _c_OrcScanExec = _c_FileSourceScanExec

    # ---- stateless ----

    def _c_ProjectExec(self, n, ch):
        exprs = [self.expr(e) for e in n.args["projections"]]
        return B.project(ch[0], list(zip(exprs, n.schema.names)))

    def _c_FilterExec(self, n, ch):
        return B.filter_(ch[0], [self.expr(e) for e in n.args["predicates"]])

    def _c_LocalLimitExec(self, n, ch):
        return B.limit(ch[0], int(n.args["limit"]))

    _c_GlobalLimitExec = _c_LocalLimitExec

    def _c_UnionExec(self, n, ch):
        return B.union(list(ch))

    def _c_ExpandExec(self, n, ch):
        projections = [
            [self.expr(e) for e in proj] for proj in n.args["projections"]
        ]
        from auron_tpu.plan.builders import _wrap

        node = pb.ExpandNode(child=ch[0], names=list(n.schema.names))
        for proj in projections:
            p = node.projections.add()
            for e in proj:
                p.exprs.add().CopyFrom(B.expr_to_proto(e))
        return _wrap(expand=node)

    # ---- sort / limit+sort ----

    def _c_SortExec(self, n, ch):
        fields = convert_sort_fields(n.args["order"], self.conf, self.udfs)
        return B.sort(ch[0], fields)

    def _c_TakeOrderedAndProjectExec(self, n, ch):
        fields = convert_sort_fields(n.args["order"], self.conf, self.udfs)
        sorted_ = B.sort(ch[0], fields, fetch=int(n.args["limit"]))
        exprs = [self.expr(e) for e in n.args.get("projections", [])]
        if not exprs:
            return sorted_
        return B.project(sorted_, list(zip(exprs, n.schema.names)))

    # ---- aggregation ----

    def _c_HashAggregateExec(self, n, ch):
        mode = n.args.get("mode", "partial")
        groupings = [
            (self.expr(g["expr"]), g["name"]) for g in n.args.get("groupings", [])
        ]
        aggs = []
        for a in n.args.get("aggs", []):
            fn = a["fn"].lower()
            e = self.expr(a["expr"]) if a.get("expr") is not None else None
            aggs.append((fn, e, a["name"]) + ((a["udaf"],) if a.get("udaf") else ()))
        return B.hash_agg(ch[0], groupings, aggs, mode)

    _c_ObjectHashAggregateExec = _c_HashAggregateExec
    _c_SortAggregateExec = _c_HashAggregateExec

    # ---- joins ----

    def _c_SortMergeJoinExec(self, n, ch):
        cond = self.expr(n.args["condition"]) if n.args.get("condition") else None
        return B.sort_merge_join(
            ch[0], ch[1],
            [self.expr(e) for e in n.args["left_keys"]],
            [self.expr(e) for e in n.args["right_keys"]],
            n.args.get("join_type", "inner"),
            condition=cond,
        )

    def _c_BroadcastHashJoinExec(self, n, ch):
        cond = self.expr(n.args["condition"]) if n.args.get("condition") else None
        return B.hash_join(
            ch[0], ch[1],
            [self.expr(e) for e in n.args["left_keys"]],
            [self.expr(e) for e in n.args["right_keys"]],
            n.args.get("join_type", "inner"),
            build_side=n.args.get("build_side", "right"),
            condition=cond,
            cached_build_id=n.args.get("cached_build_id", ""),
        )

    _c_ShuffledHashJoinExec = _c_BroadcastHashJoinExec

    # ---- window / generate ----

    def _c_WindowExec(self, n, ch):
        order = convert_sort_fields(n.args.get("order", []), self.conf, self.udfs)
        funcs = []
        for f in n.args["funcs"]:
            e = self.expr(f["expr"]) if f.get("expr") is not None else None
            if f["kind"] in ("lead", "lag", "nth_value", "ntile"):
                # offset REQUIRED and static: a missing/null offset (non-
                # literal in the host plan) must fail the trial conversion,
                # never silently default (int(None) raises)
                offset = int(f["offset"])
            else:
                offset = int(f.get("offset", 1))
            funcs.append(
                (f["kind"], f.get("agg"), e, offset,
                 bool(f.get("frame_whole", False)), f["name"])
            )
        return B.window(
            ch[0],
            [self.expr(e) for e in n.args.get("partition_by", [])],
            order, funcs,
        )

    def _c_WindowGroupLimitExec(self, n, ch):
        # planned as a rank-family window + filter in this engine; the host
        # shim ships it as a WindowExec with a limit arg instead
        raise ValueError("ship WindowGroupLimitExec as WindowExec + limit")

    def _c_GenerateExec(self, n, ch):
        return B.generate(
            ch[0],
            n.args["generator"],
            self.expr(n.args["gen_expr"]),
            list(n.args.get("required_cols", [])),
            outer=bool(n.args.get("outer", False)),
            json_fields=n.args.get("json_fields", ()),
        )

    # ---- exchanges / sinks ----

    def _c_ShuffleExchangeExec(self, n, ch):
        p = n.args["partitioning"]
        kind = p.get("kind", "hash")
        num = int(p.get("num_partitions", 1))
        if kind == "hash":
            part = B.hash_partitioning([self.expr(e) for e in p["exprs"]], num)
        elif kind == "single":
            part = pb.Partitioning(kind=pb.Partitioning.SINGLE, num_partitions=1)
        elif kind == "round_robin":
            part = pb.Partitioning(
                kind=pb.Partitioning.ROUND_ROBIN, num_partitions=num
            )
        elif kind == "range":
            # bounds are sampled host-side (the reference samples on the JVM,
            # NativeShuffleExchangeBase.scala:312) and ship as typed literal
            # rows; the engine turns them into orderable words
            fields = convert_sort_fields(p["order"], self.conf, self.udfs)
            part = _range_partitioning_proto(fields, num, p.get("bounds", []))
        else:
            raise ValueError(f"unsupported partitioning {kind}")
        return B.mesh_exchange(ch[0], part, n.args.get("exchange_id", ""))

    def _c_BroadcastExchangeExec(self, n, ch):
        # broadcast materialization is host-driven (NativeBroadcastExchange
        # collects IPC bytes); in-segment it is the identity on its child —
        # build reuse comes from hash_join.cached_build_id
        return ch[0]

    def _c_KafkaSourceExec(self, n, ch):
        """Streaming table source (Flink front-end; jvm/flink-extension
        AuronTpuKafkaTableFactory serializes this node). The source
        resource is a JSON client config the task runtime materializes
        into a real KafkaWireSource (exec/streaming.py)."""
        return B.kafka_scan(
            n.schema,
            n.args["topic"],
            n.args["source_resource_id"],
            startup_mode=n.args.get("startup_mode", "earliest"),
            start_offsets={
                int(k): int(v)
                for k, v in (n.args.get("start_offsets") or {}).items()
            },
            data_format=n.args.get("format", "json"),
            on_error=n.args.get("on_error", "skip"),
            max_batch_records=int(n.args.get("max_batch_records", 0)),
            pb_field_ids=[int(x) for x in n.args.get("pb_field_ids") or []] or None,
            zigzag_cols=[int(x) for x in n.args.get("zigzag_cols") or []] or None,
        )

    def _c_DataWritingCommandExec(self, n, ch):
        fmt = n.args.get("format", "parquet")
        partition_by = n.args.get("partition_by") or []
        if fmt == "parquet":
            return B.parquet_sink(ch[0], n.args["path"], n.args.get("props"),
                                  partition_by=partition_by)
        if partition_by:
            raise ValueError("dynamic partitioning is parquet-only for now")
        from auron_tpu.plan.builders import _wrap

        return _wrap(orc_sink=pb.OrcSinkNode(
            child=ch[0], output_path=n.args["path"],
            props=n.args.get("props") or {},
        ))

"""Cluster-sort microbench: lax.sort vs the bitonic network (ops/bitonic.py).

The q3-class agg shape: one group-key word + the null-bits word + the
dead-rows-first key + an int32 payload, at agg batch capacities. This is
the engine's dominant device primitive (VERDICT r3 weak #5); the bitonic
network is the Pallas answer, and its jitted-jnp twin is the measurable
proxy on whatever backend is live (identical algorithm, XLA-scheduled).

Prints one JSON line per (impl, cap): {"impl", "cap", "n_words", "ms",
"backend", "vs_lax"}. Run on TPU to get the kernel-vs-lax.sort verdict;
on CPU the jnp row is the documented proxy (plus hostsort as the CPU
reference point).
"""

import json
import time

import numpy as np


def _time(fn, *args, reps=None):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    if reps is None:
        # one warm rep is enough at multi-million-row caps (CPU proxy)
        reps = 5 if args[0].shape[0] <= (1 << 18) else 1
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps * 1000.0


def main(quiet: bool = False):
    import jax
    import jax.numpy as jnp
    from jax import lax

    import auron_tpu  # noqa: F401  (x64)
    from auron_tpu.ops import bitonic

    backend = jax.default_backend()
    rng = np.random.default_rng(3)
    results = []
    import os

    # default sizes cover agg-batch caps AND a multi-million-row cap
    # (2^21) that forces the tiled multi-block network (VERDICT r4 #4:
    # q95-class reduce sorts run millions of rows); larger caps via
    # BENCH_SORT_CAPS on TPU, where the kernel case actually holds
    caps = tuple(
        int(c) for c in os.environ.get(
            "BENCH_SORT_CAPS", "16384,65536,131072,2097152"
        ).split(",")
    )
    for cap in caps:
        n_groups = max(cap // 64, 1)
        sel = jnp.asarray(rng.random(cap) > 0.2)
        dead = jnp.where(sel, jnp.uint64(0), jnp.uint64(1))
        word = jnp.asarray(rng.integers(0, n_groups, cap).astype(np.uint64))
        nulls = jnp.zeros(cap, jnp.uint64)
        iota = jnp.arange(cap, dtype=jnp.int32)
        ops = (dead, word, nulls, iota)

        lax_fn = jax.jit(lambda *o: lax.sort(o, num_keys=len(o) - 1))
        ms_lax = _time(lax_fn, *ops)
        rows = [("lax", ms_lax)]
        rows.append(("jnp", _time(lambda *o: bitonic.bitonic_sort(o, impl="jnp"), *ops)))
        if backend in ("tpu", "axon"):
            rows.append(
                ("pallas", _time(lambda *o: bitonic.bitonic_sort(o, impl="pallas"), *ops))
            )
        for impl, ms in rows:
            rec = {
                "impl": impl,
                "cap": cap,
                "n_words": 2,
                "ms": round(ms, 3),
                "backend": backend,
                "vs_lax": round(ms_lax / ms, 2) if ms else None,
            }
            results.append(rec)
            if not quiet:
                print(json.dumps(rec), flush=True)
    return results


if __name__ == "__main__":
    main()

"""Planner tests: serialized TaskDefinition -> exec tree -> results.

Exercises the full wire contract (build proto -> SerializeToString ->
ParseFromString -> plan_from_proto -> execute), the way a host engine ships
plans to the runtime.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exec.base import ExecutionContext
from auron_tpu.exprs.ir import BinaryOp, Case, ScalarFunc, col, lit
from auron_tpu.ops.sortkeys import SortSpec
from auron_tpu.plan import builders as B
from auron_tpu.plan.planner import plan_from_proto, task_from_proto
from auron_tpu.proto import plan_pb2 as pb


def _roundtrip(plan: pb.PhysicalPlanNode) -> pb.PhysicalPlanNode:
    t = B.task(plan, stage_id=3, partition_id=0, conf={"batch.size": "4096"})
    raw = t.SerializeToString()
    t2 = pb.TaskDefinition()
    t2.ParseFromString(raw)
    op, stage, part, conf = task_from_proto(t2)
    assert stage == 3
    from auron_tpu.utils.config import BATCH_SIZE

    assert conf.get(BATCH_SIZE) == 4096
    return op


def _run(plan, resources=None):
    op = _roundtrip(plan)
    ctx = ExecutionContext(resources=resources or {})
    from auron_tpu.columnar.batch import concat_batches

    out = list(op.execute(0, ctx))
    if not out:
        return None
    return concat_batches(out).to_pandas()


def _mem(data: dict, schema=None) -> tuple[pb.PhysicalPlanNode, dict]:
    b = Batch.from_pydict(data, schema=schema)
    node = B.memory_scan(b.schema, "src")
    return node, {"src": [[b]]}


def test_scan_filter_project_pipeline():
    scan, res = _mem({"x": [1, 2, 3, 4], "s": ["a", "b", "c", "d"]})
    plan = B.project(
        B.filter_(scan, [BinaryOp("gt", col(0), lit(1))]),
        [(BinaryOp("mul", col(0), lit(10)), "x10"),
         (ScalarFunc("upper", (col(1),)), "u")],
    )
    got = _run(plan, res)
    assert got["x10"].tolist() == [20, 30, 40]
    assert got["u"].tolist() == ["B", "C", "D"]


def test_agg_sort_limit_plan():
    scan, res = _mem({"k": [1, 2, 1, 3, 2, 1], "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]})
    partial = B.hash_agg(scan, [(col(0), "k")], [("sum", col(1), "s")], "partial")
    final = B.hash_agg(partial, [(col(0), "k")], [("sum", col(1), "s")], "final")
    sorted_ = B.sort(final, [(col(1), SortSpec(asc=False))], fetch=2)
    got = _run(sorted_, res)
    assert got["k"].tolist() == [1, 2]
    assert got["s"].tolist() == [10.0, 7.0]


def test_join_plan():
    b1 = Batch.from_pydict({"k": [1, 2, 3], "a": ["x", "y", "z"]})
    b2 = Batch.from_pydict({"k2": [2, 3, 4], "b": [20.0, 30.0, 40.0]})
    left = B.memory_scan(b1.schema, "l")
    right = B.memory_scan(b2.schema, "r")
    plan = B.hash_join(left, right, [col(0)], [col(0)], "inner", build_side="right")
    got = _run(plan, {"l": [[b1]], "r": [[b2]]})
    got = got.sort_values("k").reset_index(drop=True)
    assert got["k"].tolist() == [2, 3]
    assert got["b"].tolist() == [20.0, 30.0]


def test_window_generate_plan():
    b = Batch.from_arrow(pa.record_batch({
        "g": pa.array([1, 1, 2]),
        "o": pa.array([2, 1, 5]),
        "l": pa.array([[1, 2], [3], []], type=pa.list_(pa.int64())),
    }))
    scan = B.memory_scan(b.schema, "src")
    w = B.window(scan, [col(0)], [(col(1), SortSpec())],
                 [("row_number", None, None, 1, False, "rn")])
    got = _run(w, {"src": [[b]]})
    assert got.sort_values(["g", "o"])["rn"].tolist() == [1, 2, 1]
    g = B.generate(scan, "explode", col(2), [0])
    got2 = _run(g, {"src": [[b]]})
    assert got2["g"].tolist() == [1, 1, 1]
    assert got2["col"].tolist() == [1, 2, 3]


def test_shuffle_plan_roundtrip(tmp_path):
    scan, res = _mem({"k": list(range(20)), "v": [float(i) for i in range(20)]})
    data, index = str(tmp_path / "s.data"), str(tmp_path / "s.index")
    part = B.hash_partitioning([col(0)], 4)
    w = B.shuffle_writer(scan, part, data, index)
    assert _run(w, res) is None  # writer yields nothing
    from auron_tpu.exec.shuffle.reader import LocalFileBlockProvider

    schema = T.Schema.of(T.Field("k", T.INT64), T.Field("v", T.FLOAT64))
    total = 0
    for p in range(4):
        r = B.ipc_reader(schema, "blocks")
        op = _roundtrip(r)
        ctx = ExecutionContext(resources={"blocks": LocalFileBlockProvider(data, index)})
        for b in op.execute(p, ctx):
            total += b.num_rows()
    assert total == 20


def test_parquet_scan_sink_plan(tmp_path):
    df = pd.DataFrame({"a": np.arange(100), "b": np.arange(100) * 0.5})
    src = str(tmp_path / "in.parquet")
    import pyarrow.parquet as pq

    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), src)
    schema = T.Schema.of(T.Field("a", T.INT64), T.Field("b", T.FLOAT64))
    scan = B.parquet_scan(schema, [src], pruning=[BinaryOp("lt", col(0), lit(10))])
    sink = B.parquet_sink(scan, str(tmp_path / "out"))
    assert _run(sink) is None
    back = pq.read_table(str(tmp_path / "out" / "part-00000.parquet")).to_pandas()
    assert back["a"].tolist() == list(range(10))


def test_ipc_writer_collect_path():
    scan, res = _mem({"x": [1, 2, 3]})
    w = B.ipc_writer(scan, "chan")
    chan: list = []
    res["chan"] = chan
    assert _run(w, res) is None
    from auron_tpu.exec.shuffle.format import decode_blocks

    rows = sum(rb.num_rows for blk in chan for rb in decode_blocks(blk))
    assert rows == 3


def test_orc_scan_sink_plan(tmp_path):
    import pyarrow.orc as orc

    df = pd.DataFrame({"a": np.arange(50), "s": [f"v{i%5}" for i in range(50)]})
    src = str(tmp_path / "in.orc")
    orc.write_table(pa.Table.from_pandas(df, preserve_index=False), src)
    schema = T.Schema.of(T.Field("a", T.INT64), T.Field("s", T.STRING))
    node = pb.PhysicalPlanNode(orc_scan=pb.OrcScanNode(
        schema=__import__("auron_tpu.plan.planner", fromlist=["schema_to_proto"]).schema_to_proto(schema),
        file_paths=[src]))
    sink = pb.PhysicalPlanNode(orc_sink=pb.OrcSinkNode(child=node, output_path=str(tmp_path / "out")))
    assert _run(sink) is None
    back = orc.ORCFile(str(tmp_path / "out" / "part-00000.orc")).read().to_pandas()
    assert back["a"].tolist() == list(range(50))
    assert back["s"].tolist() == df["s"].tolist()


def test_partition_context_exprs():
    from auron_tpu.exprs.ir import MonotonicId, RowNum, ScalarSubquery, SparkPartitionId

    b = Batch.from_pydict({"x": [10, 20, 30]})
    scan = B.memory_scan(b.schema, "src")
    res = {"src": (lambda p: [b])}
    plan = B.project(scan, [
        (SparkPartitionId(), "pid"),
        (MonotonicId(), "mid"),
        (RowNum(), "rn"),
        (ScalarSubquery("subq_val", T.INT64), "sq"),
    ])
    t = B.task(plan, partition_id=2)
    raw = t.SerializeToString()
    t2 = pb.TaskDefinition(); t2.ParseFromString(raw)
    op, _, part, conf = task_from_proto(t2)
    res["subq_val"] = 99
    ctx = ExecutionContext(partition_id=part, resources=res)
    from auron_tpu.columnar.batch import concat_batches
    got = concat_batches(list(op.execute(part, ctx))).to_pandas()
    assert got["pid"].tolist() == [2, 2, 2]
    assert got["mid"].tolist() == [(2 << 33), (2 << 33) + 1, (2 << 33) + 2]
    assert got["rn"].tolist() == [1, 2, 3]
    assert got["sq"].tolist() == [99, 99, 99]


def test_context_exprs_in_filter():
    """Partition-context expressions must work at every evaluation site,
    not just projections."""
    from auron_tpu.exprs.ir import ScalarSubquery, SparkPartitionId

    b = Batch.from_pydict({"x": [1, 2, 3, 4]})
    scan = B.memory_scan(b.schema, "src")
    plan = B.filter_(scan, [BinaryOp("gt", col(0), ScalarSubquery("threshold", T.INT64))])
    op = _roundtrip(plan)
    ctx = ExecutionContext(resources={"src": [[b]], "threshold": 2})
    from auron_tpu.columnar.batch import concat_batches
    got = concat_batches(list(op.execute(0, ctx))).to_pydict()
    assert got["x"] == [3, 4]
    # missing subquery value raises instead of silently dropping rows
    ctx2 = ExecutionContext(resources={"src": [[b]]})
    op2 = _roundtrip(plan)
    with pytest.raises(Exception):
        list(op2.execute(0, ctx2))


def test_collect_and_udaf_over_wire():
    from auron_tpu.bridge.udf import register_udaf

    register_udaf(
        "p90",
        lambda vs: float(np.percentile([v for v in vs if v is not None], 90)) if vs else None,
        T.FLOAT64,
    )
    b = Batch.from_pydict({"k": [1, 1, 1, 2], "v": [1.0, 9.0, 5.0, 2.0]})
    scan = B.memory_scan(b.schema, "src")
    p1 = B.hash_agg(scan, [(col(0), "k")],
                    [("host_udaf", col(1), "p", "p90"),
                     ("collect_list", col(1), "cl")], "partial")
    f1 = B.hash_agg(p1, [(col(0), "k")],
                    [("host_udaf", col(1), "p", "p90"),
                     ("collect_list", col(1), "cl")], "final")
    got = _run(f1, {"src": [[b]]}).sort_values("k").reset_index(drop=True)
    assert got["p"][0] == pytest.approx(np.percentile([1.0, 9.0, 5.0], 90))
    assert sorted(got["cl"][0]) == [1.0, 5.0, 9.0]
    assert list(got["cl"][1]) == [2.0]


def test_builder_proto_emission_is_insertion_order_stable():
    """Serialized plan/task protos feed digests and goldens, so builder
    emission must be byte-stable regardless of the caller's dict build
    order (R16's contract, pinned dynamically): kafka_scan offsets and
    task conf maps serialize identically from reversed insertion
    orders."""
    schema = T.Schema([T.Field("v", T.INT64)])

    fwd = {0: 7, 1: 11, 2: 13, 10: 17}
    rev = dict(reversed(list(fwd.items())))
    a = B.kafka_scan(schema, "t", "res", start_offsets=fwd)
    b = B.kafka_scan(schema, "t", "res", start_offsets=rev)
    assert a.SerializeToString(deterministic=True) == \
        b.SerializeToString(deterministic=True)

    plan = B.memory_scan(schema, "rid")
    conf_fwd = {"spark.a": "1", "spark.b": "2", "spark.c": "3"}
    conf_rev = dict(reversed(list(conf_fwd.items())))
    ta = B.task(plan, conf=conf_fwd)
    tb = B.task(plan, conf=conf_rev)
    assert ta.SerializeToString(deterministic=True) == \
        tb.SerializeToString(deterministic=True)

"""Serving continuous queries: StreamServer + POST /stream.

The serving contract: register a CREATE STREAMING VIEW against a
registered topic and it runs as a background stream under admission
control (hard cap, 429 — streams never finish on their own, so there is
nothing to queue behind); inspect reads live watermark/emission
progress; cancel stops the pump and returns the final status. The HTTP
tests go through a real socket so the handler routing, error→status
mapping, and keep-alive framing are all exercised.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from auron_tpu import types as T
from auron_tpu.exec.streaming import MockKafkaSource
from auron_tpu.serve.streams import StreamBusy, StreamError, StreamServer
from auron_tpu.utils import httpsvc
from auron_tpu.utils.config import (
    STREAM_CHECKPOINT_INTERVAL,
    STREAM_POLL_MAX_RECORDS,
    STREAM_SERVE_MAX_STREAMS,
    active_conf,
)

SCHEMA = T.Schema.of(T.Field("k", T.STRING), T.Field("v", T.FLOAT64),
                     T.Field("ts", T.INT64))

VIEW = """
CREATE STREAMING VIEW orders_1s
  WATERMARK FOR ts AS ts - INTERVAL '1' SECOND
AS SELECT k, window_start, SUM(v) AS total, COUNT(*) AS n
   FROM orders
   GROUP BY k, TUMBLE(ts, INTERVAL '1' SECOND)
"""


def _records(n=600, seed=11):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        recs.append(json.dumps({
            "k": "kab"[int(rng.integers(0, 3))],
            "v": round(float(rng.random()) * 10, 3),
            "ts": int(i * 13),
        }).encode())
    return [recs[: n // 2], recs[n // 2:]]


def _factory(parts):
    return lambda mode, offsets: MockKafkaSource(
        parts, startup_mode=mode, start_offsets=offsets)


class _IdleSource:
    """Never-ending, never-producing source: keeps a stream alive for
    admission-cap tests without burning CPU on real data."""

    def poll(self, max_records):
        time.sleep(0.002)
        return []

    def offsets(self):
        return {}

    def close(self):
        pass


def _conf(**overrides):
    c = active_conf().copy()
    c.set(STREAM_POLL_MAX_RECORDS, 64)
    c.set(STREAM_CHECKPOINT_INTERVAL, 2)
    for opt, v in overrides.items():
        c.set(globals()[opt], v)
    return c


def _wait(cond, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


@pytest.fixture()
def server():
    srv = StreamServer(conf=_conf())
    srv.register_topic("orders", SCHEMA, _factory(_records()))
    yield srv
    srv.shutdown()


# ---------------------------------------------------------------------------
# in-process server contract
# ---------------------------------------------------------------------------


def test_register_inspect_cancel(server):
    out = server.register(VIEW)
    assert out == {"stream": "orders_1s", "status": "running"}
    # the mock topic is finite: the pump drains it and parks as exhausted
    assert _wait(lambda: server.inspect("orders_1s")["exhausted"])
    st = server.inspect("orders_1s")
    assert st["steps"] > 0 and st["error"] is None
    assert st["watermark_ms"] is not None and st["emit_seq"] > 0
    assert st["emissions"] == st["emit_seq"] and len(st["tail"]) == 3
    final = server.cancel("orders_1s", drain=True)
    assert final["status"] == "cancelled"
    # drain force-closed the windows still inside the watermark delay
    assert final["final"]["open_groups"] == 0
    assert final["final"]["emit_seq"] > st["emit_seq"]
    with pytest.raises(StreamError, match="no stream"):
        server.inspect("orders_1s")


def test_duplicate_name_refused(server):
    server.register(VIEW)
    with pytest.raises(StreamError, match="already running"):
        server.register(VIEW)


def test_unknown_topic_is_a_request_error(server):
    with pytest.raises(StreamError, match="unknown source topic"):
        server.register(VIEW.replace("FROM orders", "FROM nope"))


def test_sql_diagnostics_surface_as_request_errors(server):
    with pytest.raises(StreamError, match="TUMBLE"):
        server.register(
            "CREATE STREAMING VIEW x AS SELECT k, COUNT(*) AS n "
            "FROM orders GROUP BY k")
    with pytest.raises(StreamError, match='"sql"'):
        server.execute_json({"action": "register"})
    with pytest.raises(StreamError, match="unknown action"):
        server.execute_json({"action": "explode"})


def test_session_conf_denial(server):
    for bad in ("serve.plan.cache.capacity", "obs.mode",
                "stream.serve.max.streams"):
        with pytest.raises(StreamError, match="not stream-settable"):
            server.register(VIEW, conf={bad: "1"})
    with pytest.raises(StreamError, match="unknown conf key"):
        server.register(VIEW, conf={"no.such.knob": "1"})
    # stream runtime knobs ARE session-settable
    server.register(VIEW, conf={"stream.poll.max.records": "32"})
    assert server.inspect("orders_1s")["name"] == "orders_1s"


def test_admission_cap_refuses_not_queues():
    srv = StreamServer(conf=_conf(STREAM_SERVE_MAX_STREAMS=1))
    srv.register_topic("orders", SCHEMA, lambda mode, off: _IdleSource())
    try:
        srv.register(VIEW)
        with pytest.raises(StreamBusy, match="stream.serve.max.streams=1"):
            srv.register(VIEW.replace("orders_1s", "orders_1s_b"))
        # cancelling the live stream frees the slot
        srv.cancel("orders_1s")
        out = srv.register(VIEW.replace("orders_1s", "orders_1s_b"))
        assert out["status"] == "running"
    finally:
        srv.shutdown()


def test_checkpoint_resume_through_serving(server, tmp_path):
    ck = str(tmp_path / "ck")
    server.register(VIEW, checkpoint_dir=ck)
    assert _wait(lambda: server.inspect("orders_1s")["exhausted"])
    first = server.cancel("orders_1s")["final"]
    assert first["checkpoints"] > 0
    # a new registration against the same dir resumes, not replays:
    # the restored pipeline starts at the checkpointed sequence
    server.register(VIEW, checkpoint_dir=ck)
    assert _wait(lambda: server.inspect("orders_1s")["exhausted"])
    st = server.inspect("orders_1s")
    assert st["steps"] <= first["steps"]
    assert st["emit_seq"] >= first["emit_seq"]
    server.cancel("orders_1s")
    # drifting the micro-batch size against the checkpoint is refused
    with pytest.raises(StreamError, match="poll.max.records"):
        server.register(VIEW, conf={"stream.poll.max.records": "16"},
                        checkpoint_dir=ck)


# ---------------------------------------------------------------------------
# POST /stream over a real socket
# ---------------------------------------------------------------------------


def _post(port, body, path="/stream"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        raw = e.read().decode()
        try:
            return e.code, json.loads(raw)
        except ValueError:
            return e.code, {"error": raw}


@pytest.fixture()
def http_stream(server):
    port = httpsvc.start(0)
    httpsvc.install_stream_server(server)
    yield port
    httpsvc.stop()


def test_http_stream_lifecycle(http_stream, server):
    port = http_stream
    code, out = _post(port, {"action": "register", "sql": VIEW})
    assert code == 200 and out["status"] == "running"
    assert _wait(lambda: server.inspect("orders_1s")["exhausted"])
    code, st = _post(port, {"action": "inspect", "stream": "orders_1s"})
    assert code == 200 and st["emit_seq"] > 0
    code, ls = _post(port, {"action": "list"})
    assert code == 200 and [s["stream"] for s in ls["streams"]] == [
        "orders_1s"]
    code, fin = _post(port, {"action": "cancel", "stream": "orders_1s",
                             "drain": True})
    assert code == 200 and fin["status"] == "cancelled"
    code, ls = _post(port, {"action": "list"})
    assert code == 200 and ls == {"streams": []}


def test_http_stream_error_codes(http_stream):
    port = http_stream
    code, out = _post(port, {"action": "inspect", "stream": "ghost"})
    assert code == 400 and "no stream" in out["error"]
    code, out = _post(port, {"action": "register", "sql": "SELECT 1"})
    assert code == 400 and "error" in out
    # no server installed -> 404, not 500
    httpsvc.install_stream_server(None)
    code, out = _post(port, {"action": "list"})
    assert code == 404


def test_http_stream_429_when_full():
    srv = StreamServer(conf=_conf(STREAM_SERVE_MAX_STREAMS=1))
    srv.register_topic("orders", SCHEMA, lambda mode, off: _IdleSource())
    port = httpsvc.start(0)
    httpsvc.install_stream_server(srv)
    try:
        code, _ = _post(port, {"action": "register", "sql": VIEW})
        assert code == 200
        code, out = _post(port, {
            "action": "register",
            "sql": VIEW.replace("orders_1s", "orders_1s_b")})
        assert code == 429 and "max.streams" in out["error"]
    finally:
        httpsvc.stop()
        srv.shutdown()

"""Flink front-end wire contract, driven from the engine side.

jvm/flink-extension serializes the SAME hostplan JSON the Spark shim
does; these tests replay byte-identical payloads to what the Java code
builds (FlinkCalcConverter / AuronTpuKafkaSourceFunction.buildTask) and
run them through the real conversion service + C-ABI-shaped task flow —
the contract test a JDK-less image can run.
"""

import base64
import json

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.bridge import api
from auron_tpu.columnar import Batch
from auron_tpu.convert.service import convert_host_plan_json


def _flink_calc_json():
    """What FlinkCalcConverter.convert builds for
    SELECT id, price * 2 FROM src WHERE price > 10 AND tag IS NOT NULL."""
    schema_in = '[["id","long",false],["price","double",true],["tag","string",true]]'
    schema_out = '[["id","long",false],["p2","double",true]]'
    inp = ('{"op":"FlinkStreamInput","schema":' + schema_in
           + ',"args":{},"children":[]}')
    cond = ('{"kind":"call","name":"and","children":['
            '{"kind":"call","name":"greaterthan","children":['
            '{"kind":"attr","index":1},'
            '{"kind":"lit","type":"double","value":10}]},'
            '{"kind":"call","name":"isnotnull","children":['
            '{"kind":"attr","index":2}]}]}')
    filt = ('{"op":"FilterExec","schema":' + schema_in
            + ',"args":{"predicates":[' + cond + ']},"children":[' + inp + ']}')
    projs = ('{"kind":"attr","index":0},'
             '{"kind":"call","name":"multiply","children":['
             '{"kind":"attr","index":1},'
             '{"kind":"lit","type":"double","value":2}]}')
    return ('{"op":"ProjectExec","schema":' + schema_out
            + ',"args":{"projections":[' + projs + ']},"children":[' + filt + ']}')


def test_flink_calc_fragment_converts_and_runs():
    resp = json.loads(convert_host_plan_json(_flink_calc_json()))
    assert resp["converted"] is True
    seg = resp["root"]
    assert seg["kind"] == "segment"
    # the unknown FlinkStreamInput became the FFI boundary
    assert len(seg["inputs"]) == 1
    rid = seg["inputs"][0]["resource_id"]
    plan = base64.b64decode(seg["plan_b64"])

    # feed a micro-batch exactly like AuronTpuCalcOperator.flush: resource
    # "<rid>.<subtask>", then run the stamped task through the bridge
    df = pd.DataFrame({
        "id": np.arange(20, dtype=np.int64),
        "price": np.arange(20, dtype=np.float64),
        "tag": [None if i % 5 == 0 else f"t{i}" for i in range(20)],
    })
    rb = pa.RecordBatch.from_pandas(df, preserve_index=False)
    subtask = 3
    api.put_resource(f"{rid}.{subtask}", [rb])
    try:
        from auron_tpu.proto import plan_pb2 as pb

        node = pb.PhysicalPlanNode()
        node.ParseFromString(plan)
        task = pb.TaskDefinition(plan=node, partition_id=subtask)
        h = api.call_native(task.SerializeToString())
        frames = []
        while (out := api.next_batch(h)) is not None:
            frames.append(out.to_pandas())
        api.finalize_native(h)
        got = pd.concat(frames).reset_index(drop=True)
    finally:
        api.remove_resource(f"{rid}.{subtask}")

    want = df[(df.price > 10) & df.tag.notna()]
    want = pd.DataFrame({"id": want.id, "p2": want.price * 2}).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def test_kafka_source_node_converts_and_consumes_real_broker():
    """The KafkaSourceExec hostplan node (what buildTask serializes) runs
    the engine's wire client from a bytes config resource; resume offsets
    ride the finalize metric tree (kafka_offset_p<N>)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tkw", "tests/test_kafka_wire.py")
    tkw = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tkw)

    broker = tkw.MiniKafkaBroker("flinktopic", n_partitions=2)
    try:
        rows = [{"k": i, "v": f"r{i}"} for i in range(40)]
        broker.produce(0, [json.dumps(r).encode() for r in rows[:25]])
        broker.produce(1, [json.dumps(r).encode() for r in rows[25:]])

        rid = "flink_kafka_flinktopic_0"
        host = json.dumps({
            "op": "KafkaSourceExec",
            "schema": [["k", "long", False], ["v", "string", True]],
            "args": {
                "topic": "flinktopic",
                "source_resource_id": rid,
                "startup_mode": "earliest",
                "start_offsets": {},
                "format": "json",
                "on_error": "skip",
            },
            "children": [],
        })
        resp = json.loads(convert_host_plan_json(host))
        assert resp["converted"] is True, resp.get("error")
        plan = base64.b64decode(resp["root"]["plan_b64"])

        # what auron_put_resource_bytes registers: the raw config payload
        api.put_resource(
            rid, json.dumps({"bootstrap": f"127.0.0.1:{broker.port}"}).encode())
        try:
            from auron_tpu.proto import plan_pb2 as pb

            node = pb.PhysicalPlanNode()
            node.ParseFromString(plan)
            task = pb.TaskDefinition(plan=node, partition_id=0)
            h = api.call_native(task.SerializeToString())
            got = []
            while (out := api.next_batch(h)) is not None:
                got += out.to_pandas()["k"].tolist()
            metrics = api.finalize_native(h)
        finally:
            api.remove_resource(rid)

        assert sorted(got) == list(range(40))
        from auron_tpu.exec.metrics import MetricNode

        flat = MetricNode.flat_totals(metrics)
        # offsets surfaced for the host's checkpoint (union of partitions)
        assert flat.get("kafka_offset_p0") == 25
        assert flat.get("kafka_offset_p1") == 15
    finally:
        broker.close()


def test_cached_client_continues_and_mod_assignment():
    """Micro-batch cycles reuse the engine-cached client (position
    persists; no reconnect); assign_mod splits partitions per subtask;
    config start_offsets override the plan for restores; the cache entry
    dies (and the client closes) with remove_resource."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tkw", "tests/test_kafka_wire.py")
    tkw = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tkw)

    broker = tkw.MiniKafkaBroker("mb", n_partitions=2)
    try:
        broker.produce(0, [json.dumps({"k": i}).encode() for i in range(0, 10)])
        broker.produce(1, [json.dumps({"k": i}).encode() for i in range(10, 20)])

        host = json.dumps({
            "op": "KafkaSourceExec",
            "schema": [["k", "long", False]],
            "args": {"topic": "mb", "source_resource_id": "mb_src",
                     "startup_mode": "earliest", "format": "json"},
            "children": [],
        })
        resp = json.loads(convert_host_plan_json(host))
        plan = base64.b64decode(resp["root"]["plan_b64"])
        from auron_tpu.proto import plan_pb2 as pb

        node = pb.PhysicalPlanNode()
        node.ParseFromString(plan)

        # subtask 0 of 2: mod assignment -> partition 0 only
        api.put_resource("mb_src", json.dumps(
            {"bootstrap": f"127.0.0.1:{broker.port}",
             "assign_mod": [0, 2]}).encode())

        def run_cycle():
            task = pb.TaskDefinition(plan=node, partition_id=0)
            h = api.call_native(task.SerializeToString())
            got = []
            while (out := api.next_batch(h)) is not None:
                got += out.to_pandas()["k"].tolist()
            api.finalize_native(h)
            return got

        assert sorted(run_cycle()) == list(range(0, 10))  # partition 0 only
        client = api.get_resource("mb_src.client")
        assert client is not None

        broker.produce(0, [json.dumps({"k": 100}).encode()])
        # second cycle: SAME cached client continues (no re-read of 0-9)
        assert run_cycle() == [100]
        assert api.get_resource("mb_src.client") is client

        api.remove_resource("mb_src")
        assert api.get_resource("mb_src.client") is None
        assert not client._conns  # closed with the resource

        # restore path: config start_offsets override the plan's startup
        api.put_resource("mb_src", json.dumps(
            {"bootstrap": f"127.0.0.1:{broker.port}",
             "assign_mod": [0, 2],
             "start_offsets": {"0": 9}}).encode())
        assert run_cycle() == [9, 100]
        api.remove_resource("mb_src")
    finally:
        broker.close()


def test_zero_split_assignment_drains_immediately():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tkw", "tests/test_kafka_wire.py")
    tkw = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tkw)
    from auron_tpu.exec import kafka_wire as KW

    broker = tkw.MiniKafkaBroker("zs", n_partitions=1)
    try:
        broker.produce(0, [b"x"])
        # parallelism 3, subtask 2: no partition satisfies pid % 3 == 2
        src = KW.KafkaWireSource(f"127.0.0.1:{broker.port}", "zs",
                                 "earliest", assign_mod=(2, 3))
        assert src.poll(10) is None
        assert src.offsets() == {}
        src.close()
        # explicit empty assignment behaves the same
        src2 = KW.KafkaWireSource(f"127.0.0.1:{broker.port}", "zs",
                                  "earliest", partitions=[])
        assert src2.poll(10) is None
        src2.close()
    finally:
        broker.close()

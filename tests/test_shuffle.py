"""Shuffle write/read round-trip tests."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exec.base import ExecutionContext
from auron_tpu.exec.basic import MemoryScanExec
from auron_tpu.exec.shuffle import (
    HashPartitioning,
    IpcReaderExec,
    RangePartitioning,
    RoundRobinPartitioning,
    ShuffleWriterExec,
    SinglePartitioning,
)
from auron_tpu.exec.shuffle.partitioning import make_range_bounds
from auron_tpu.exec.shuffle.reader import LocalFileBlockProvider, MultiMapBlockProvider
from auron_tpu.exprs.ir import col
from auron_tpu.ops.sortkeys import SortSpec


def _write(tmp_path, batches, partitioning, map_id=0):
    scan = MemoryScanExec.single(batches)
    data = str(tmp_path / f"map{map_id}.data")
    index = str(tmp_path / f"map{map_id}.index")
    w = ShuffleWriterExec(scan, partitioning, data, index)
    ctx = ExecutionContext(partition_id=map_id)
    assert list(w.execute(0, ctx)) == []
    return data, index


def _read_all(schema, provider, n_partitions):
    out = {}
    for p in range(n_partitions):
        r = IpcReaderExec(schema, "blocks")
        ctx = ExecutionContext()
        ctx.resources["blocks"] = provider
        parts = [b.to_pandas() for b in r.execute(p, ctx)]
        out[p] = pd.concat(parts).reset_index(drop=True) if parts else pd.DataFrame()
    return out


def test_hash_partitioning_roundtrip(tmp_path):
    df = pd.DataFrame({"k": np.arange(1000) % 37, "v": np.arange(1000.0)})
    b = Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))
    part = HashPartitioning([col(0)], 4)
    data, index = _write(tmp_path, [b], part)
    out = _read_all(b.schema, LocalFileBlockProvider(data, index), 4)
    # all rows preserved
    total = pd.concat(out.values())
    assert len(total) == 1000
    assert sorted(total["v"].tolist()) == sorted(df["v"].tolist())
    # co-location: every key appears in exactly one partition
    seen = {}
    for p, d in out.items():
        for k in set(d["k"].tolist()):
            assert k not in seen, f"key {k} in partitions {seen[k]} and {p}"
            seen[k] = p
    # bit-exactness: partition of k must equal pmod(murmur3(k))
    from auron_tpu.ops.hash_dispatch import hash_batch
    from auron_tpu.ops.hashing import pmod

    kb = Batch.from_pydict({"k": list(seen.keys())},
                           schema=T.Schema.of(T.Field("k", T.INT64)))
    expected_pids = np.asarray(pmod(hash_batch(kb, [0], "murmur3"), 4))[: len(seen)]
    for (k, p), ep in zip(seen.items(), expected_pids):
        assert p == ep


def test_round_robin_and_single(tmp_path):
    df = pd.DataFrame({"x": np.arange(10)})
    b = Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))
    data, index = _write(tmp_path, [b], RoundRobinPartitioning(3))
    out = _read_all(b.schema, LocalFileBlockProvider(data, index), 3)
    sizes = sorted(len(d) for d in out.values())
    assert sum(sizes) == 10 and max(sizes) - min(sizes) <= 1
    data2, index2 = _write(tmp_path, [b], SinglePartitioning(), map_id=1)
    out2 = _read_all(b.schema, LocalFileBlockProvider(data2, index2), 1)
    assert len(out2[0]) == 10


def test_range_partitioning(tmp_path):
    rng = np.random.default_rng(5)
    df = pd.DataFrame({"x": rng.integers(0, 1000, 500)})
    b = Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))
    specs = [SortSpec()]
    bounds = make_range_bounds(b, [col(0)], specs, 4)
    part = RangePartitioning([col(0)], specs, 4, bounds)
    data, index = _write(tmp_path, [b], part)
    out = _read_all(b.schema, LocalFileBlockProvider(data, index), 4)
    total = pd.concat(out.values())
    assert len(total) == 500
    # ranges are disjoint and ordered
    for p in range(3):
        if len(out[p]) and len(out[p + 1]):
            assert out[p]["x"].max() <= out[p + 1]["x"].min()


def test_multi_map_exchange_with_strings(tmp_path):
    dfs = [
        pd.DataFrame({"k": ["a", "b", "c", "a"], "v": [1, 2, 3, 4]}),
        pd.DataFrame({"k": ["b", "c", "d"], "v": [5, 6, 7]}),
    ]
    pairs = []
    part = HashPartitioning([col(0)], 3)
    schema = None
    for mid, df in enumerate(dfs):
        b = Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))
        schema = b.schema
        pairs.append(_write(tmp_path, [b], part, map_id=mid))
    out = _read_all(schema, MultiMapBlockProvider(pairs), 3)
    total = pd.concat(out.values())
    assert len(total) == 7
    assert sorted(total["v"].tolist()) == [1, 2, 3, 4, 5, 6, 7]
    # same key from different maps lands in the same partition
    where = {}
    for p, d in out.items():
        if len(d) == 0:
            continue
        for k in set(d["k"]):
            where.setdefault(k, set()).add(p)
    assert all(len(v) == 1 for v in where.values())


def test_empty_partition_regions(tmp_path):
    df = pd.DataFrame({"k": [5, 5, 5], "v": [1.0, 2.0, 3.0]})
    b = Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))
    data, index = _write(tmp_path, [b], HashPartitioning([col(0)], 8))
    out = _read_all(b.schema, LocalFileBlockProvider(data, index), 8)
    nonempty = [p for p, d in out.items() if len(d)]
    assert len(nonempty) == 1
    assert len(out[nonempty[0]]) == 3


def test_rss_push_writer():
    """RSS-style push shuffle: blocks pushed per partition to a registered
    writer callable; reading them back reproduces the dataset."""
    from auron_tpu.exec.shuffle.format import decode_blocks
    from auron_tpu.exec.shuffle.writer import RssShuffleWriterExec

    df = pd.DataFrame({"k": np.arange(200) % 7, "v": np.arange(200.0)})
    b = Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))
    scan = MemoryScanExec.single([b])

    pushed: dict[int, list[bytes]] = {}
    flushed = []

    class FakeRssClient:
        def write(self, pid, blk):
            pushed.setdefault(pid, []).append(blk)

        def flush(self):
            flushed.append(True)

    w = RssShuffleWriterExec(scan, HashPartitioning([col(0)], 5), "rss")
    ctx = ExecutionContext(resources={"rss": FakeRssClient()})
    assert list(w.execute(0, ctx)) == []
    assert flushed == [True]
    rows = 0
    for pid, blocks in pushed.items():
        for blk in blocks:
            for rb in decode_blocks(blk):
                rows += rb.num_rows
                ks = set(rb.column("k").to_pylist())
                from auron_tpu.ops.hash_dispatch import hash_batch
                from auron_tpu.ops.hashing import pmod
                kb = Batch.from_pydict({"k": sorted(ks)},
                                       schema=T.Schema.of(T.Field("k", T.INT64)))
                pids = np.asarray(pmod(hash_batch(kb, [0], "murmur3"), 5))[: len(ks)]
                assert (pids == pid).all()
    assert rows == 200


def test_corrupted_file_tolerance(tmp_path):
    import pyarrow.parquet as pq

    from auron_tpu.exec.scan import ParquetScanExec
    from auron_tpu.utils.config import Configuration, IGNORE_CORRUPTED_FILES

    good = str(tmp_path / "good.parquet")
    bad = str(tmp_path / "bad.parquet")
    pq.write_table(pa.table({"x": [1, 2, 3]}), good)
    with open(bad, "wb") as f:
        f.write(b"not a parquet file")
    schema = T.Schema.of(T.Field("x", T.INT64))
    scan = ParquetScanExec(schema, [bad, good])
    # default: corrupted file raises
    with pytest.raises(Exception):
        scan.collect()
    # tolerant mode: skipped, good file still read
    ctx = ExecutionContext(conf=Configuration().set(IGNORE_CORRUPTED_FILES, True))
    out = [b.to_pydict()["x"] for b in scan.execute(0, ctx)]
    assert out == [[1, 2, 3]]
    assert ctx.metrics.total("corrupted_files_skipped") == 1


# ---------------------------------------------------------------------------
# RSS service/client analog (thirdparty/auron-celeborn / auron-uniffle)
# ---------------------------------------------------------------------------


def test_rss_end_to_end_matches_file_shuffle(tmp_path):
    import pandas as pd

    from auron_tpu.bridge import api
    from auron_tpu.exec.shuffle.rss import (
        LocalRssService, RssBlockProvider, RssPartitionWriterClient,
    )
    from auron_tpu.plan import builders as B
    from auron_tpu.exprs.ir import col

    rng = np.random.default_rng(3)
    df = pd.DataFrame({"k": rng.integers(0, 50, 3000).astype(np.int64),
                       "v": rng.integers(0, 100, 3000).astype(np.int64)})
    schema = T.Schema.of(T.Field("k", T.INT64), T.Field("v", T.INT64))
    n_map, n_reduce = 3, 4
    per = 1000
    parts = [[Batch.from_pydict(
        {"k": df.k[p * per:(p + 1) * per].tolist(),
         "v": df.v[p * per:(p + 1) * per].tolist()}, schema=schema)]
        for p in range(n_map)]

    svc = LocalRssService(num_replicas=2)
    api.put_resource("rss_src", parts)
    try:
        part = B.hash_partitioning([col(0)], n_reduce)
        for m in range(n_map):
            api.put_resource("rss_w", RssPartitionWriterClient(svc, "shuf1", m))
            w = B.rss_shuffle_writer(
                B.memory_scan(schema, "rss_src"), part, "rss_w"
            )
            h = api.call_native(B.task(w, partition_id=m).SerializeToString())
            while api.next_batch(h) is not None:
                pass
            api.finalize_native(h)

        # reduce through the normal IPC reader over the RSS fetch path
        api.put_resource("rss_blocks", RssBlockProvider(svc, "shuf1"))
        got_rows = []
        for p in range(n_reduce):
            h = api.call_native(
                B.task(B.ipc_reader(schema, "rss_blocks"),
                       partition_id=p).SerializeToString())
            while (rb := api.next_batch(h)) is not None:
                got_rows += rb.to_pylist()
            api.finalize_native(h)
        got = sorted((r["k"], r["v"]) for r in got_rows)
        assert got == sorted(zip(df.k.tolist(), df.v.tolist()))
        # replica 1 serves the same data (replication fan-out)
        rep1 = RssBlockProvider(svc, "shuf1", replica=1)
        assert sum(rb.num_rows for p in range(n_reduce) for rb in rep1(p)) == 3000
    finally:
        for k in ("rss_src", "rss_w", "rss_blocks"):
            api.remove_resource(k)


def test_rss_commit_and_retry_semantics():
    from auron_tpu.exec.shuffle.format import encode_block
    from auron_tpu.exec.shuffle.rss import LocalRssService, RssPartitionWriterClient

    svc = LocalRssService()
    blk = encode_block(pa.table({"x": pa.array([1, 2, 3], pa.int64())}))

    w = RssPartitionWriterClient(svc, "s", map_id=0)
    w.write(0, blk)
    assert svc.fetch("s", 0) == []  # uncommitted: invisible to readers

    # task retry: a fresh writer for the same map drops stale pushes
    w2 = RssPartitionWriterClient(svc, "s", map_id=0)
    w2.write(0, blk)
    w2.flush()
    assert len(svc.fetch("s", 0)) == 1  # exactly one committed copy


def test_rss_speculative_attempt_cannot_destroy_committed():
    from auron_tpu.exec.shuffle.format import encode_block
    from auron_tpu.exec.shuffle.rss import LocalRssService, RssPartitionWriterClient

    svc = LocalRssService()
    blk = encode_block(pa.table({"x": pa.array([1], pa.int64())}))
    w = RssPartitionWriterClient(svc, "s2", map_id=0)
    w.write(0, blk)
    w.flush()
    assert len(svc.fetch("s2", 0)) == 1

    # speculative duplicate attempt: pushes + commits, but first wins
    spec = RssPartitionWriterClient(svc, "s2", map_id=0)
    assert len(svc.fetch("s2", 0)) == 1  # construction didn't wipe anything
    spec.write(0, blk)
    spec.write(0, blk)
    spec.flush()
    assert len(svc.fetch("s2", 0)) == 1  # still exactly one committed copy


def test_align_dict_batches_mixed_schema():
    """Dictionary-preserving and materialized blocks for the same column
    must merge (the preserve decision is per-batch dict size, so one
    stream can produce both)."""
    import pyarrow as pa

    from auron_tpu.exec.shuffle.format import align_dict_batches

    d = pa.RecordBatch.from_arrays(
        [pa.array(["a", "b", "a"]).dictionary_encode()], names=["s"])
    m = pa.RecordBatch.from_arrays([pa.array(["c", "a"])], names=["s"])
    tbl = pa.Table.from_batches(align_dict_batches([d, m]))
    assert tbl.column("s").to_pylist() == ["a", "b", "a", "c", "a"]


def test_cluster_rows_device_host_bit_identity():
    """ONE clustering policy (writer.cluster_rows / cluster_rows_host):
    the device lax.sort path and the host numpy-argsort fallback produce
    the same per-partition counts AND the same row order (stable sort by
    pid, dead rows last) — the fused repartition can never diverge from
    the host fallback."""
    import jax
    import jax.numpy as jnp

    from auron_tpu.exec.shuffle.writer import (
        _cluster_by_pid, cluster_rows_host,
    )

    rng = np.random.default_rng(23)
    for trial in range(5):
        cap = int(rng.integers(64, 1024))
        n_out = int(rng.integers(1, 9))
        sel = rng.random(cap) < 0.8
        pids = rng.integers(0, n_out, cap).astype(np.int32)
        vals = rng.integers(0, 1 << 40, cap).astype(np.int64)
        from auron_tpu.columnar.batch import DeviceBatch

        dev = DeviceBatch(
            jnp.asarray(sel), (jnp.asarray(vals),),
            (jnp.ones(cap, bool),),
        )
        out_dev, counts_dev = _cluster_by_pid(dev, jnp.asarray(pids), n_out)
        counts_np = np.asarray(jax.device_get(counts_dev))[:n_out]
        order_host, counts_host = cluster_rows_host(pids, sel, n_out)
        assert counts_np.tolist() == counts_host.tolist(), trial
        live = int(counts_host.sum())
        dev_vals = np.asarray(jax.device_get(out_dev.values[0]))[:live]
        host_vals = vals[order_host]
        assert dev_vals.tolist() == host_vals.tolist(), trial


def test_op_sync_attribution_follows_the_waiting_operator():
    """profiling.EngineCounters.op_sync books a blocking sync under the
    operator actually waiting (innermost LIVE ExecOperator frame) — a
    producer suspended at yield inside an open timer can no longer absorb
    a consumer's stall (the q93 probe_time misattribution)."""
    from auron_tpu.exec.agg_exec import AggExpr, HashAggExec
    from auron_tpu.utils.config import AGG_PARTIAL_DEFER, active_conf
    from auron_tpu.utils.profiling import EngineCounters

    counters = EngineCounters.install()
    conf = active_conf()
    saved = conf.get(AGG_PARTIAL_DEFER)
    saved_all = counters.record_all_sites
    counters.record_all_sites = True
    try:
        conf.set(AGG_PARTIAL_DEFER, "off")  # force the blocking 1/batch read
        rng = np.random.default_rng(3)
        frames = [
            Batch.from_pydict({
                "k": (rng.integers(0, 50, 800) * 1_000_003).tolist(),
                "v": [1.0] * 800,
            })
            for _ in range(6)
        ]
        agg = HashAggExec(
            MemoryScanExec.single(frames), [(col(0), "k")],
            [(AggExpr("count_star", None), "c")], "partial")
        counters.reset()
        agg.collect()
        snap = counters.snapshot()
        assert "HashAggExec" in snap["op_sync"], snap["op_sync"]
        assert snap["op_sync"]["HashAggExec"][0] > 0
    finally:
        counters.record_all_sites = saved_all
        conf.set(AGG_PARTIAL_DEFER, saved)


def test_rss_fetch_rides_iter_payloads_raw_bytes(tmp_path):
    """ISSUE-12 satellite: the RSS fetch provider exposes iter_payloads,
    so format-v2 blocks cross into the reader as RAW BYTES (bucketed
    decode) instead of round-tripping through the RecordBatch view —
    and both paths emit identical rows."""
    import pandas as pd

    from auron_tpu.bridge import api
    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.exec.shuffle.format import is_v2_payload
    from auron_tpu.exec.shuffle.reader import IpcReaderExec
    from auron_tpu.exec.shuffle.rss import (
        LocalRssService, RssBlockProvider, RssPartitionWriterClient,
    )
    from auron_tpu.exprs.ir import col
    from auron_tpu.plan import builders as B
    from auron_tpu.utils.config import SHUFFLE_ENCODING, Configuration

    rng = np.random.default_rng(7)
    schema = T.Schema.of(T.Field("k", T.INT64), T.Field("v", T.INT64))
    batch = Batch.from_pydict(
        {"k": rng.integers(0, 40, 2000).astype(np.int64).tolist(),
         "v": rng.integers(0, 9, 2000).astype(np.int64).tolist()},
        schema=schema)
    n_reduce = 3
    svc = LocalRssService()
    api.put_resource("rssp_src", [[batch]])
    try:
        api.put_resource("rssp_w", RssPartitionWriterClient(svc, "shufp", 0))
        w = B.rss_shuffle_writer(
            B.memory_scan(schema, "rssp_src"),
            B.hash_partitioning([col(0)], n_reduce), "rssp_w")
        h = api.call_native(B.task(w, partition_id=0).SerializeToString())
        while api.next_batch(h) is not None:
            pass
        api.finalize_native(h)
    finally:
        api.remove_resource("rssp_src")
        api.remove_resource("rssp_w")

    prov = RssBlockProvider(svc, "shufp")
    # vacuity: the fetch path actually yields v2 payloads as raw bytes
    payloads = [p for part in range(n_reduce)
                for p in prov.iter_payloads(part)]
    assert payloads and any(is_v2_payload(p) for p in payloads)

    def read_all(encoding: str):
        rows = []
        for p in range(n_reduce):
            ctx = ExecutionContext(
                partition_id=p,
                conf=Configuration().set(SHUFFLE_ENCODING, encoding))
            ctx.resources["rssp_blocks"] = prov
            r = IpcReaderExec(schema, "rssp_blocks")
            for out in r.execute(p, ctx):
                rows.extend(out.to_arrow().to_pylist())
        return sorted((r["k"], r["v"]) for r in rows)

    bucketed = read_all("on")    # iter_payloads -> bucketed decode
    legacy = read_all("off")     # RecordBatch view path
    assert bucketed == legacy and len(bucketed) == 2000


def test_rss_push_rides_iter_payloads_raw_bytes(tmp_path):
    """ISSUE-20 satellite: the PUSH half of the raw-bytes pair — a
    finished local map output migrates into the RSS service via
    push_payloads as raw block payloads, never through the RecordBatch
    view, and the pushed bytes are byte-identical to the source file's
    payloads (no decode -> re-encode)."""
    from auron_tpu.exec.shuffle.format import is_v2_payload
    from auron_tpu.exec.shuffle.rss import (
        LocalRssService, RssBlockProvider, RssPartitionWriterClient,
        push_payloads,
    )

    rng = np.random.default_rng(13)
    df = pd.DataFrame({"k": rng.integers(0, 40, 2500).astype(np.int64),
                       "v": np.round(rng.random(2500) * 100, 2)})
    b = Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))
    n_reduce = 4
    data, index = _write(tmp_path, [b], HashPartitioning([col(0)], n_reduce))

    class NoDecodeProvider(LocalFileBlockProvider):
        """The relay must never materialize the RecordBatch view."""

        def __call__(self, partition):
            raise AssertionError("push relay touched the RecordBatch view")

    src = NoDecodeProvider(data, index)
    src_payloads = [p for part in range(n_reduce)
                    for p in src.iter_payloads(part)]
    # vacuity: the source actually holds v2 payloads to relay
    assert src_payloads and any(is_v2_payload(p) for p in src_payloads)

    svc = LocalRssService()
    w = RssPartitionWriterClient(svc, "mig", 0)
    pushed = push_payloads(src, w, n_reduce)
    assert pushed == len(src_payloads)

    # byte identity: what the service serves back IS the source payloads
    dst = RssBlockProvider(svc, "mig")
    dst_payloads = [p for part in range(n_reduce)
                    for p in dst.iter_payloads(part)]
    assert dst_payloads == src_payloads

    # and the migrated output reads back as the original rows
    out = _read_all(b.schema, dst, n_reduce)
    total = pd.concat(out.values())
    assert sorted(total["v"].tolist()) == sorted(df["v"].tolist())


def test_rss_push_relay_aborts_on_failure():
    """A failing relay aborts the attempt (service drops staged blocks)."""
    from auron_tpu.exec.shuffle.rss import push_payloads

    class ExplodingProvider:
        def iter_payloads(self, partition):
            yield b"AUB2xxxx"
            raise RuntimeError("fetch died")

    events = []

    class Writer:
        def write(self, pid, blk):
            events.append(("write", pid))

        def abort(self):
            events.append(("abort",))

        def flush(self):
            events.append(("flush",))

    with pytest.raises(RuntimeError, match="fetch died"):
        push_payloads(ExplodingProvider(), Writer(), 2)
    assert ("abort",) in events and ("flush",) not in events

"""Task runtime + bridge ABI + memory manager tests."""

import time

import pyarrow as pa
import pytest


from auron_tpu import types as T
from auron_tpu.bridge import api
from auron_tpu.columnar import Batch
from auron_tpu.exprs.ir import BinaryOp, col, lit
from auron_tpu.memory.memmgr import DiskSpill, MemManager
from auron_tpu.plan import builders as B
from auron_tpu.runtime.task import TaskRuntime


def _task_bytes(plan, **kw):
    return B.task(plan, **kw).SerializeToString()


def test_runtime_pump_and_metrics():
    b = Batch.from_pydict({"x": list(range(100))})
    plan = B.filter_(B.memory_scan(b.schema, "src"), [BinaryOp("lt", col(0), lit(10))])
    rt = TaskRuntime(_task_bytes(plan), resources={"src": [[b]]})
    out = [rb for rb in iter(rt.next_arrow, None)]
    assert sum(r.num_rows for r in out) == 10
    snap = rt.finalize()
    assert snap["values"]["output_rows"] == 10
    assert snap["children"][0]["values"]["output_rows"] == 100


def test_runtime_error_relay():
    b = Batch.from_pydict({"x": [1, 0]})
    # division by a string function that doesn't exist -> error in pump
    from auron_tpu.exprs.ir import ScalarFunc

    plan = B.project(B.memory_scan(b.schema, "src"), [(ScalarFunc("nope", (col(0),)), "y")])
    rt = TaskRuntime(_task_bytes(plan), resources={"src": [[b]]})
    with pytest.raises(RuntimeError, match="failed"):
        while rt.next_batch() is not None:
            pass


def test_runtime_cancellation():
    b = Batch.from_pydict({"x": list(range(10))})
    plan = B.memory_scan(b.schema, "src")
    rt = TaskRuntime(
        _task_bytes(plan), resources={"src": [[b] * 200]}
    )
    assert rt.next_batch() is not None
    rt.finalize()  # cancels mid-stream without hanging


def test_bridge_abi_roundtrip():
    b = Batch.from_pydict({"k": [1, 2, 1], "v": [1.0, 2.0, 3.0]})
    api.put_resource("bridge_src", [[b]])
    partial = B.hash_agg(B.memory_scan(b.schema, "bridge_src"),
                         [(col(0), "k")], [("sum", col(1), "s")], "partial")
    final = B.hash_agg(partial, [(col(0), "k")], [("sum", col(1), "s")], "final")
    h = api.call_native(_task_bytes(final))
    rows = []
    while (ipc := api.next_batch_ipc(h)) is not None:
        with pa.ipc.open_stream(ipc) as r:
            for rb in r:
                rows += rb.to_pylist()
    metrics = api.finalize_native(h)
    api.remove_resource("bridge_src")
    got = sorted((r["k"], r["s"]) for r in rows)
    assert got == [(1, 4.0), (2, 2.0)]
    assert metrics["values"]["output_rows"] == 2


class _FakeConsumer:
    def __init__(self, name, used):
        self.name = name
        self._used = used
        self.spilled = 0

    def mem_used(self):
        return self._used

    def spill(self):
        freed = self._used
        self._used = 0
        self.spilled += 1
        return freed


def test_memmgr_spill_ordering():
    mm = MemManager.init(budget_bytes=1000)
    assert mm.budget == 600  # x fraction 0.6
    big = _FakeConsumer("big", 400)
    small = _FakeConsumer("small", 150)
    mm.register(big)
    mm.register(small)
    # small asks for more -> big (largest other) spills first
    mm.acquire(small, 200)
    assert big.spilled == 1 and small.spilled == 0
    assert mm.total_used() == 150
    # requester spills only if others can't cover
    big2 = _FakeConsumer("big2", 550)
    mm.register(big2)
    mm.acquire(big2, 500)
    assert small.spilled == 1 and big2.spilled == 1


def test_disk_spill_roundtrip(tmp_path):
    ds = DiskSpill(str(tmp_path), conf=None)  # deliberate: conf-independent scratch
    t1 = pa.table({"x": [1, 2]})
    t2 = pa.table({"x": [3]})
    ds.write_table(t1)
    ds.write_table(t2)
    got = [rb.to_pydict() for rb in ds.read_tables()]
    assert got == [{"x": [1, 2]}, {"x": [3]}]
    ds.release()


def test_agg_spill_under_pressure():
    import numpy as np
    import pandas as pd

    from auron_tpu.exec.agg_exec import FINAL, PARTIAL, AggExpr, HashAggExec
    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.exec.basic import MemoryScanExec

    MemManager.init(budget_bytes=200_000)  # tiny budget forces agg spills
    try:
        rng = np.random.default_rng(31)
        n = 20_000
        # wide key range keeps the dense direct-address agg (no spills
        # needed) ineligible; this test exercises the generic spill path
        df = pd.DataFrame({"k": rng.integers(0, 3000, n) * 1_000_003,
                           "v": rng.normal(size=n)})
        batches = [
            Batch.from_arrow(
                pa.RecordBatch.from_pandas(df.iloc[i : i + 2000], preserve_index=False)
            )
            for i in range(0, n, 2000)
        ]
        scan = MemoryScanExec.single(batches)
        partial = HashAggExec(scan, [(col(0), "k")], [(AggExpr("sum", col(1)), "s")], PARTIAL)
        ctx = ExecutionContext()
        partial_out = list(partial.execute(0, ctx))
        spilled = ctx.metrics.total("spilled_aggs")
        final = HashAggExec(
            MemoryScanExec.single(partial_out), [(col(0), "k")],
            [(AggExpr("sum", col(1)), "s")], FINAL,
        )
        got = final.collect().to_pandas().sort_values("k").reset_index(drop=True)
        want = df.groupby("k").agg(s=("v", "sum")).reset_index()
        assert got["k"].tolist() == want["k"].tolist()
        for g, w in zip(got["s"], want["s"]):
            assert g == pytest.approx(w, rel=1e-9)
        assert spilled > 0
    finally:
        MemManager.init()  # restore default budget


def test_sort_spill_under_pressure():
    import numpy as np
    import pandas as pd

    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.exec.basic import MemoryScanExec
    from auron_tpu.exec.sort_exec import SortExec
    from auron_tpu.ops.sortkeys import SortSpec

    MemManager.init(budget_bytes=150_000)
    try:
        rng = np.random.default_rng(32)
        n = 30_000
        df = pd.DataFrame({"x": rng.permutation(n)})
        batches = [
            Batch.from_arrow(
                pa.RecordBatch.from_pandas(df.iloc[i : i + 3000], preserve_index=False)
            )
            for i in range(0, n, 3000)
        ]
        s = SortExec(MemoryScanExec.single(batches), [col(0)], [SortSpec()])
        ctx = ExecutionContext()
        out = []
        for b in s.execute(0, ctx):
            out += b.to_pydict()["x"]
        assert out == list(range(n))
        assert ctx.metrics.total("spilled_runs") > 0
    finally:
        MemManager.init()


def test_metric_render():
    b = Batch.from_pydict({"x": [1, 2, 3]})
    plan = B.filter_(B.memory_scan(b.schema, "src"), [BinaryOp("lt", col(0), lit(3))])
    rt = TaskRuntime(_task_bytes(plan), resources={"src": [[b]]})
    while rt.next_batch() is not None:
        pass
    rt.finalize()
    text = rt.ctx.metrics.render()
    assert "FilterExec" in text and "output_rows=2" in text
    assert "ResourceScanExec" in text


@pytest.fixture(autouse=True)
def _row_metrics_on(enable_row_metrics):
    # these suites assert per-operator output_rows metrics
    pass


def test_hive_partitioned_parquet_sink(tmp_path):
    """parquet sink with partition_by writes hive-style directories with
    partition columns dropped from the files (parquet_sink_exec.rs +
    NativeParquetSinkUtils dynamic partitioning analog)."""
    import os

    import pyarrow.parquet as pq

    from auron_tpu import types as T
    from auron_tpu.bridge import api
    from auron_tpu.columnar import Batch
    from auron_tpu.exprs.ir import col
    from auron_tpu.plan import builders as B

    b = Batch.from_pydict(
        {"year": [2023, 2023, 2024, 2024, 2024],
         "cat": ["a", "b", "a", "a", None],
         "v": [1, 2, 3, 4, 5]},
        schema=T.Schema.of(T.Field("year", T.INT32), T.Field("cat", T.STRING),
                           T.Field("v", T.INT64)),
    )
    api.put_resource("sink_rows", [[b]])
    try:
        out = str(tmp_path / "table")
        plan = B.parquet_sink(B.memory_scan(b.schema, "sink_rows"), out,
                              partition_by=["year", "cat"])
        h = api.call_native(B.task(plan).SerializeToString())
        while api.next_batch(h) is not None:
            pass
        m = api.finalize_native(h)
        dirs = sorted(
            os.path.relpath(os.path.join(r, f), out)
            for r, _, fs in os.walk(out) for f in fs
        )
        assert "year=2023/cat=a/part-00000.parquet" in dirs
        assert "year=2024/cat=__HIVE_DEFAULT_PARTITION__/part-00000.parquet" in dirs
        tbl = pq.read_table(os.path.join(out, "year=2024", "cat=a"))
        assert tbl.column_names == ["v"]  # partition cols dropped
        assert sorted(tbl.column("v").to_pylist()) == [3, 4]
        # hive-read round trip reconstructs the partition columns
        import pyarrow.dataset as ds

        full = ds.dataset(out, partitioning="hive").to_table()
        assert full.num_rows == 5
    finally:
        api.remove_resource("sink_rows")


def test_concurrent_hostsort_tasks_no_wedge():
    """Regression: two task pumps whose programs carried hostsort
    pure_callbacks wedged XLA:CPU (each in-flight computation parked an
    intra-op thread waiting for a callback continuation that itself
    needed a pool thread). The fix: host-sort orders compute EAGERLY and
    enter the jitted programs as data (ops/segments.py host_order) — no
    compiled program launched from a pump may carry a callback. This
    must finish, not hang."""
    import threading

    import numpy as np
    import pandas as pd
    import pyarrow as pa

    from auron_tpu.bridge import api
    from auron_tpu.columnar import Batch
    from auron_tpu.exprs.ir import col
    from auron_tpu.plan import builders as B

    rng = np.random.default_rng(17)
    df = pd.DataFrame({
        "k": rng.integers(0, 500, 40_000).astype(np.int64),
        "v": rng.integers(-10, 10, 40_000).astype(np.int64),
    })
    parts = [
        [Batch.from_arrow(pa.RecordBatch.from_pandas(
            df.iloc[i::4].reset_index(drop=True), preserve_index=False))
         for i in range(2)]
        for _ in range(2)
    ]
    b0 = parts[0][0]
    api.put_resource("wedge_fact", parts)
    try:
        agg_p = B.hash_agg(B.memory_scan(b0.schema, "wedge_fact"),
                           [(col(0), "k")], [("sum", col(1), "s")], "partial")
        agg = B.hash_agg(agg_p, [(col(0), "k")], [("sum", col(1), "s")], "final")
        # two concurrent pumps, each a host-sorted aggregation
        handles = [
            api.call_native(B.task(agg, stage_id=9, partition_id=p).SerializeToString())
            for p in range(2)
        ]
        totals = []

        def drain(h, out):
            rows = 0
            while (rb := api.next_batch(h)) is not None:
                rows += rb.num_rows
            api.finalize_native(h)
            out.append(rows)

        ts = [threading.Thread(target=drain, args=(h, totals), daemon=True)
              for h in handles]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in ts), "concurrent tasks wedged"
        assert sum(totals) > 0
    finally:
        api.remove_resource("wedge_fact")

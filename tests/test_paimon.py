"""Real-metadata Paimon resolution: table dir -> descriptor -> native scan.

The table on disk is built to the PUBLIC Paimon append-only layout
(schema/schema-N JSON, snapshot/snapshot-N JSON + LATEST hint, Avro
manifest lists -> Avro manifests with BinaryRow-encoded partitions,
bucketed parquet data files) — the test_iceberg/test_hudi analog for the
third table format. The resolver must honor the latest snapshot, apply
base-then-delta manifests with ADD/DELETE kinds, decode BinaryRow
partition values for pruning, and refuse primary-key (merge-on-read)
tables.
"""

import json
import os
import struct

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from auron_tpu.convert.paimon import resolve_paimon_scan
from auron_tpu.utils.avro import write_container

FIELDS = [
    {"id": 0, "name": "id", "type": "BIGINT NOT NULL"},
    {"id": 1, "name": "amount", "type": "DOUBLE"},
    {"id": 2, "name": "year", "type": "BIGINT"},
]

MANIFEST_SCHEMA = {
    "type": "record", "name": "manifest_entry",
    "fields": [
        {"name": "_KIND", "type": "int"},
        {"name": "_PARTITION", "type": "bytes"},
        {"name": "_BUCKET", "type": "int"},
        {"name": "_FILE", "type": {
            "type": "record", "name": "data_file", "fields": [
                {"name": "_FILE_NAME", "type": "string"},
                {"name": "_FILE_SIZE", "type": "long"},
                {"name": "_ROW_COUNT", "type": "long"},
            ]}},
    ],
}

MANIFEST_LIST_SCHEMA = {
    "type": "record", "name": "manifest_file",
    "fields": [
        {"name": "_FILE_NAME", "type": "string"},
        {"name": "_FILE_SIZE", "type": "long"},
        {"name": "_NUM_ADDED_FILES", "type": "long"},
    ],
}


def _binary_row_bigint(*values) -> bytes:
    """Encode fixed-width BIGINT fields in the BinaryRow layout the
    resolver decodes: 8-byte null bitset (header bit 0-7) + 8-byte LE
    slots."""
    arity = len(values)
    null_bits = ((arity + 8 + 63) // 64) * 8
    buf = bytearray(null_bits + 8 * arity)
    for i, v in enumerate(values):
        if v is None:
            bit = 8 + i
            buf[bit >> 3] |= 1 << (bit & 7)
        else:
            buf[null_bits + 8 * i : null_bits + 8 * i + 8] = struct.pack(
                "<q", v)
    return bytes(buf)


def _write_parquet(root, rel, df):
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), path)
    return os.path.getsize(path)


def _manifest(root, name, entries):
    mdir = os.path.join(root, "manifest")
    os.makedirs(mdir, exist_ok=True)
    write_container(os.path.join(mdir, name), MANIFEST_SCHEMA, entries)


def _manifest_list(root, name, manifest_names):
    mdir = os.path.join(root, "manifest")
    os.makedirs(mdir, exist_ok=True)
    write_container(
        os.path.join(mdir, name), MANIFEST_LIST_SCHEMA,
        [{"_FILE_NAME": n, "_FILE_SIZE": 0, "_NUM_ADDED_FILES": 1}
         for n in manifest_names],
    )


def _snapshot(root, sid, schema_id, base_list, delta_list):
    sdir = os.path.join(root, "snapshot")
    os.makedirs(sdir, exist_ok=True)
    with open(os.path.join(sdir, f"snapshot-{sid}"), "w") as f:
        json.dump({
            "version": 3, "id": sid, "schemaId": schema_id,
            "baseManifestList": base_list, "deltaManifestList": delta_list,
            "commitKind": "APPEND",
        }, f)
    with open(os.path.join(sdir, "LATEST"), "w") as f:
        f.write(str(sid))


def _build_table(root):
    """Partitioned by year (BIGINT). Snapshot 1 adds f1 (2023) and f2
    (2024); snapshot 2's delta DELETEs f2 and adds its compaction f3 —
    the latest snapshot must see exactly {f1, f3}."""
    os.makedirs(os.path.join(root, "schema"), exist_ok=True)
    with open(os.path.join(root, "schema", "schema-0"), "w") as f:
        json.dump({
            "id": 0, "fields": FIELDS, "highestFieldId": 2,
            "partitionKeys": ["year"], "primaryKeys": [],
            "options": {"file.format": "parquet"},
        }, f)

    rng = np.random.default_rng(9)

    def mk(year, n, seed):
        return pd.DataFrame({
            "id": np.arange(n, dtype=np.int64) + seed,
            "amount": np.round(rng.random(n) * 100, 2),
            "year": np.full(n, year, dtype=np.int64),
        })

    f1, f2 = mk(2023, 300, 0), mk(2024, 200, 1000)
    f3 = mk(2024, 250, 2000)  # compaction rewrite of f2's bucket
    s1 = _write_parquet(root, "year=2023/bucket-0/f1.parquet", f1)
    s2 = _write_parquet(root, "year=2024/bucket-0/f2.parquet", f2)
    s3 = _write_parquet(root, "year=2024/bucket-0/f3.parquet", f3)

    def entry(kind, year, bucket, name, size, rows):
        return {"_KIND": kind, "_PARTITION": _binary_row_bigint(year),
                "_BUCKET": bucket,
                "_FILE": {"_FILE_NAME": name, "_FILE_SIZE": size,
                          "_ROW_COUNT": rows}}

    _manifest(root, "manifest-1", [
        entry(0, 2023, 0, "f1.parquet", s1, 300),
        entry(0, 2024, 0, "f2.parquet", s2, 200),
    ])
    _manifest_list(root, "manifest-list-1-base", [])
    _manifest_list(root, "manifest-list-1-delta", ["manifest-1"])
    _snapshot(root, 1, 0, "manifest-list-1-base", "manifest-list-1-delta")

    _manifest(root, "manifest-2", [
        entry(1, 2024, 0, "f2.parquet", s2, 200),   # DELETE
        entry(0, 2024, 0, "f3.parquet", s3, 250),   # compaction ADD
    ])
    _manifest_list(root, "manifest-list-2-base", ["manifest-1"])
    _manifest_list(root, "manifest-list-2-delta", ["manifest-2"])
    _snapshot(root, 2, 0, "manifest-list-2-base", "manifest-list-2-delta")
    return {"f1": f1, "f3": f3}


def test_resolve_latest_snapshot(tmp_path):
    frames = _build_table(str(tmp_path))
    desc = resolve_paimon_scan(str(tmp_path))
    assert desc["op"] == "PaimonScanExec"
    assert [s[0] for s in desc["schema"]] == ["id", "amount", "year"]
    assert desc["schema"][0][2] is False  # BIGINT NOT NULL
    files = {os.path.basename(f["path"]): f for f in desc["args"]["files"]}
    assert set(files) == {"f1.parquet", "f3.parquet"}
    # typed partition values decoded from the BinaryRow bytes
    assert files["f1.parquet"]["partition"] == {"year": 2023}
    assert files["f3.parquet"]["partition"] == {"year": 2024}
    assert files["f3.parquet"]["record_count"] == 250


def test_descriptor_to_native_scan_with_pruning(tmp_path):
    frames = _build_table(str(tmp_path))
    desc = resolve_paimon_scan(str(tmp_path))

    import base64

    from auron_tpu.bridge import api
    from auron_tpu.convert.service import convert_host_plan_json
    from auron_tpu.proto import plan_pb2 as pb

    # year = 2024 must prune f1 away entirely (typed int comparison)
    host = dict(desc)
    host["args"] = dict(host["args"])
    host["args"]["filters"] = [
        {"kind": "call", "name": "equalto", "children": [
            {"kind": "attr", "index": 2, "name": "year"},
            {"kind": "lit", "type": "long", "value": 2024}]},
    ]
    host["children"] = []
    resp = json.loads(convert_host_plan_json(json.dumps(host)))
    assert resp["converted"] is True, resp.get("error")
    node = pb.PhysicalPlanNode()
    node.ParseFromString(base64.b64decode(resp["root"]["plan_b64"]))
    h = api.call_native(pb.TaskDefinition(plan=node).SerializeToString())
    got = []
    while (rb := api.next_batch(h)) is not None:
        got.append(rb.to_pandas())
    api.finalize_native(h)
    out = pd.concat(got).reset_index(drop=True)
    want = frames["f3"]
    assert len(out) == len(want)
    assert out["amount"].sum() == pytest.approx(want["amount"].sum())


def test_primary_key_table_rejected(tmp_path):
    root = str(tmp_path)
    os.makedirs(os.path.join(root, "schema"))
    with open(os.path.join(root, "schema", "schema-0"), "w") as f:
        json.dump({"id": 0, "fields": FIELDS, "partitionKeys": [],
                   "primaryKeys": ["id"], "options": {}}, f)
    _snapshot(root, 1, 0, "x", "y")
    with pytest.raises(ValueError, match="primary-key"):
        resolve_paimon_scan(root)


def test_no_snapshots_is_loud(tmp_path):
    os.makedirs(os.path.join(str(tmp_path), "snapshot"))
    with pytest.raises(ValueError, match="no snapshots"):
        resolve_paimon_scan(str(tmp_path))


def test_inline_string_partition_decodes():
    """Compact (<=7 byte) inline strings in BinaryRow slots."""
    from auron_tpu.convert.paimon import _decode_binary_row

    arity = 1
    null_bits = ((arity + 8 + 63) // 64) * 8
    buf = bytearray(null_bits + 8)
    buf[null_bits : null_bits + 2] = b"us"
    buf[null_bits + 7] = 0x80 | 2
    assert _decode_binary_row(bytes(buf), ["STRING"]) == ["us"]

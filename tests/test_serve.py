"""Concurrent multi-tenant serving (auron_tpu/serve, docs/serving.md).

Covers the ISSUE-12 satellite contract for the program cache — hit/miss
accounting, bounded-size eviction, invalidation when a session conf
changes a plan-affecting knob, replay-adds-no-compiles across fresh
server sessions — plus admission control (queueing, timeouts, memory
backpressure), the POST /sql front door, and a toy-scale run of the
concurrency differential gate (bit-identity + zero-compile legs; the
throughput floor is `make servegate`'s job at real scale).
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

from auron_tpu.models import sqlgate, tpcds
from auron_tpu.serve import (
    AdmissionController,
    AdmissionTimeout,
    PlanCache,
    QueryError,
    SqlServer,
)
from auron_tpu.serve.cache import plan_cache_key
from auron_tpu.sql.catalog import build_tables
from auron_tpu.utils.config import (
    SERVE_MAX_CONCURRENT,
    SERVE_QUEUE_TIMEOUT_S,
    SQL_SHUFFLE_PARTITIONS,
    Configuration,
)

TOY_SF = 0.02


@pytest.fixture(scope="module", autouse=True)
def _suite_leak_canary(leak_canary):
    """Tier-1 leak canary (conftest): runtimes/resource-map/obs rings
    must return to their pre-suite baselines after this module."""
    yield


@pytest.fixture(scope="module")
def frames():
    data = tpcds.generate(sf=TOY_SF, seed=42)
    return build_tables(data, seed=42)


@pytest.fixture(scope="module")
def server(frames):
    srv = SqlServer(sqlgate.gate_catalog(), frames, n_parts=2)
    yield srv
    # in-flight upload events: every entry that is still resident must
    # have released its waiters (a cleared event after the builder
    # returned = the PR-12 stuck-waiter shape)
    with srv._res_lock:
        stuck = [k for k, ent in srv._res_cache.items()
                 if not ent["done"].is_set() or ent["val"] is None]
    assert not stuck, f"resource-map entries with unreleased waiters: {stuck}"


def _sql(name):
    return sqlgate.case_by_name(name).sql


# ---------------------------------------------------------------------------
# plan digests
# ---------------------------------------------------------------------------


def test_digest_normalizes_whitespace_comments_case():
    from auron_tpu.sql.digest import plan_digest

    a = plan_digest("select d_year from date_dim where d_moy = 11")
    b = plan_digest(
        "SELECT  d_year\n FROM date_dim -- comment\n WHERE D_MOY = 11")
    c = plan_digest("select d_year from date_dim where d_moy = 12")
    assert a == b        # whitespace/comments/identifier case fold away
    assert a != c        # literals are part of the plan


def test_digest_distinguishes_string_literals_from_bare_tokens():
    """Token kinds survive canonicalization: the lexer strips quotes, so
    a bare rendering would collide ``'1'`` with ``1`` and ``'NAME'``
    with an identifier — two different plans on one cache key (review
    finding, reproduced)."""
    from auron_tpu.sql.digest import plan_digest

    assert plan_digest("select '1' from t") != plan_digest("select 1 from t")
    assert (plan_digest("select a from t where s = 'NAME'")
            != plan_digest("select a from t where s = NAME"))
    # '' escaping round-trips into ONE canonical form
    assert (plan_digest("select 'o''k' from t")
            == plan_digest("select  'o''k'  from t"))


def test_json_rows_serializes_datetimes_and_nulls():
    import json as _json

    import numpy as np
    import pandas as pd

    from auron_tpu.serve.server import _json_rows

    df = pd.DataFrame({
        "d": pd.to_datetime(["2020-01-01", None]),
        "x": [np.int64(7), np.int64(8)],
        "f": [1.5, float("nan")],
    })
    rows = _json_rows(df)
    _json.dumps(rows)  # must be JSON-safe (Timestamp 500'd POST /sql)
    assert rows[0][0].startswith("2020-01-01") and rows[1][0] is None
    assert rows[0][1] == 7 and rows[1][2] is None


def test_digest_distinguishes_quoted_identifiers():
    """Quoted identifiers re-quote in the canonical form: rendered bare,
    ``"a b"`` (one column) collides with ``a b`` (implicit alias) — two
    different plans on one cache key (review finding)."""
    from auron_tpu.sql.digest import plan_digest

    assert (plan_digest('select "a b" from t')
            != plan_digest("select a b from t"))
    assert (plan_digest('select "from" from t')
            != plan_digest("select from from t"))
    # quoting is canonical regardless of surrounding whitespace
    assert (plan_digest('select  "a b"  from t')
            == plan_digest('select "a b" from t'))


def test_failing_query_does_not_leak_task_runtimes(server, monkeypatch):
    """A query whose collect-stage drain fails must still finalize its
    TaskRuntime: a persistent server leaking one handle + pump thread
    per failing request grows without bound (review finding)."""
    from auron_tpu.bridge import api

    before = set(api._runtimes)

    def boom(h):
        raise RuntimeError("injected drain failure")

    monkeypatch.setattr(api, "next_batch", boom)
    with pytest.raises(RuntimeError, match="injected"):
        server.submit(_sql("q3"), tenant="leak")  # q3 has a collect stage
    monkeypatch.undo()
    assert set(api._runtimes) == before
    from auron_tpu.sql.digest import plan_digest

    a = plan_digest("select X from t", fold_ident_case=False)
    b = plan_digest("select x from t", fold_ident_case=False)
    assert a != b


def test_plan_cache_key_includes_plan_knobs():
    conf2 = Configuration().set(SQL_SHUFFLE_PARTITIONS, 2)
    conf4 = Configuration().set(SQL_SHUFFLE_PARTITIONS, 4)
    sql = _sql("q96")
    assert plan_cache_key(sql, conf2) != plan_cache_key(sql, conf4)
    assert plan_cache_key(sql, conf2) == plan_cache_key(sql, conf2)


def test_plan_cache_key_splits_on_fusion_and_host_sort_knobs():
    """The cache-split bugs auronlint R14 found in this tree: the fuse
    family and exec.host.sort are read during lowering/fusion, so two
    sessions differing on them must land on DIFFERENT cache keys —
    before PLAN_KNOBS covered them, both tenants shared one compiled
    plan and the second silently ran under the first's settings."""
    from auron_tpu.utils.config import (
        FUSE_AGG_INPUTS,
        FUSE_ENABLE,
        FUSE_MIN_OPS,
        FUSE_PROBE,
        FUSE_SHUFFLE,
        HOST_SORT_MODE,
    )

    sql = _sql("q96")
    for knob, a, b in (
        (FUSE_ENABLE, "on", "off"),
        (FUSE_PROBE, "on", "off"),
        (FUSE_SHUFFLE, "on", "off"),
        (FUSE_MIN_OPS, 2, 9),
        (FUSE_AGG_INPUTS, True, False),
        (HOST_SORT_MODE, "on", "off"),
    ):
        ka = plan_cache_key(sql, Configuration().set(knob, a))
        kb = plan_cache_key(sql, Configuration().set(knob, b))
        assert ka != kb, f"{knob.key} does not split the plan cache"
    # defaults are stable: two fresh sessions share the compiled plan
    assert plan_cache_key(sql, Configuration()) == plan_cache_key(
        sql, Configuration())


def test_plan_knobs_single_source_of_truth():
    """PLAN_KNOBS lives in sql/digest.py (next to the digest it keys);
    serve/cache.py re-exports the SAME tuple — two copies would drift."""
    from auron_tpu.serve import cache
    from auron_tpu.sql import digest

    assert cache.PLAN_KNOBS is digest.PLAN_KNOBS
    assert {k.key for k in digest.PLAN_KNOBS} >= {
        "sql.shuffle.partitions",
        "exec.fuse.enable",
        "exec.host.sort",
    }


# ---------------------------------------------------------------------------
# program cache: accounting, eviction, invalidation, zero-compile replay
# ---------------------------------------------------------------------------


def test_plan_cache_hit_miss_accounting():
    c = PlanCache(capacity=8)
    assert c.lookup("k1") is None
    c.insert("k1", "plan1")
    assert c.lookup("k1") == "plan1"
    s = c.stats()
    assert (s["hits"], s["misses"], s["entries"]) == (1, 1, 1)


def test_plan_cache_eviction_is_lru_and_bounded():
    c = PlanCache(capacity=2)
    c.insert("a", 1)
    c.insert("b", 2)
    assert c.lookup("a") == 1       # touch a: b is now least-recent
    c.insert("c", 3)                # evicts b
    assert c.lookup("b") is None
    assert c.lookup("a") == 1 and c.lookup("c") == 3
    s = c.stats()
    assert s["evictions"] == 1 and s["entries"] == 2


def test_server_cache_hit_and_knob_invalidation(server):
    sql = _sql("q96")
    df1, r1 = server.submit(sql, tenant="a")
    df2, r2 = server.submit(sql, tenant="b")
    assert not r1["cache_hit"] and r2["cache_hit"]
    assert r1["digest"] == r2["digest"]
    assert df1.equals(df2)
    # a session conf changing a plan-affecting knob lands on a DIFFERENT
    # cache entry (invalidation by keying) and still computes the same
    # rows at the new mesh width
    df3, r3 = server.submit(sql, session={"sql.shuffle.partitions": 4},
                            tenant="c")
    assert not r3["cache_hit"]
    assert r3["digest"] != r1["digest"]
    assert df3.equals(df1)
    # and back on the default width: the original entry still hits
    _, r4 = server.submit(sql, tenant="d")
    assert r4["cache_hit"]


def test_replay_adds_no_compiles_across_fresh_server_sessions(frames):
    from auron_tpu.utils.profiling import EngineCounters

    counters = EngineCounters.install()
    sql = _sql("q3")
    warm = SqlServer(sqlgate.gate_catalog(), frames, n_parts=2)
    df1, _ = warm.submit(sql)            # compiles (first touch this test)
    before = counters.compiles
    fresh = SqlServer(sqlgate.gate_catalog(), frames, n_parts=2)
    df2, rec = fresh.submit(sql)         # fresh session: its OWN plan
    assert not rec["cache_hit"]          # cache is empty -> re-lowered...
    assert counters.compiles == before   # ...but ZERO new XLA compiles
    assert df1.equals(df2)


# ---------------------------------------------------------------------------
# session confs
# ---------------------------------------------------------------------------


def test_session_conf_rejects_unknown_and_process_global_keys(server):
    with pytest.raises(QueryError):
        server.session_conf({"no.such.key": "1"})
    for denied in ("obs.mode", "http.service.enable",
                   "serve.admission.max.concurrent"):
        with pytest.raises(QueryError):
            server.session_conf({denied: "1"})
    # a legitimate engine knob is accepted and resolves
    conf = server.session_conf({"batch.size": 4096})
    from auron_tpu.utils.config import BATCH_SIZE

    assert conf.get(BATCH_SIZE) == 4096


def test_sql_diagnostics_surface_as_query_errors(server):
    err0 = server.stats()["queries_err"]
    with pytest.raises(QueryError):
        server.execute_json({"sql": "select definitely from"})
    with pytest.raises(QueryError):
        server.execute_json({"nope": 1})
    with pytest.raises(QueryError):
        server.submit(_sql("q96"), session={"obs.mode": "off"})
    with pytest.raises(QueryError):
        server.submit(_sql("q96"),
                      session={"sql.shuffle.partitions": 4096})
    # refused requests COUNT on /serve (review finding: conf refusals
    # and admission timeouts were raised before the stats try block).
    # The malformed-body refusal raises before submit, so 3 of the 4.
    assert server.stats()["queries_err"] >= err0 + 3


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


def _adm(max_concurrent=1, timeout_s=0.3, mem_fraction=0.9):
    conf = (Configuration()
            .set(SERVE_MAX_CONCURRENT, max_concurrent)
            .set(SERVE_QUEUE_TIMEOUT_S, timeout_s))
    from auron_tpu.utils.config import SERVE_ADMIT_MEM_FRACTION

    conf = conf.set(SERVE_ADMIT_MEM_FRACTION, mem_fraction)
    return AdmissionController(conf)


def test_admission_queues_beyond_the_slot_bound():
    adm = _adm(max_concurrent=1, timeout_s=5.0)
    order = []
    gate = threading.Event()

    def worker(i):
        with adm.admit():
            order.append(i)
            if i == 0:
                gate.wait(2.0)

    t0 = threading.Thread(target=worker, args=(0,))
    t0.start()
    while not order:            # first worker holds the only slot
        pass
    t1 = threading.Thread(target=worker, args=(1,))
    t1.start()
    t1.join(0.2)
    assert t1.is_alive()        # queued behind the held slot
    gate.set()
    t0.join(3.0)
    t1.join(3.0)
    st = adm.stats()
    assert st["peak_running"] == 1 and st["queued"] >= 1
    assert order == [0, 1]


def test_admission_timeout_answers_instead_of_hanging():
    adm = _adm(max_concurrent=1, timeout_s=0.15)
    with adm.admit():
        with pytest.raises(AdmissionTimeout):
            with adm.admit():
                pass
    assert adm.stats()["timeouts"] == 1
    with adm.admit():           # slot released: admits again
        pass


def test_admission_memory_backpressure_queues_then_admits():
    """A consumer holding more than the admission fraction of the budget
    makes new queries WAIT; releasing it unblocks them (queue-don't-die)."""
    from auron_tpu.memory.memmgr import MemManager

    mgr = MemManager.get()

    class Hog:
        name = "test_admission_hog"

        def __init__(self, nbytes):
            self.nbytes = nbytes

        def mem_used(self):
            return self.nbytes

        def spill(self):
            return 0

    hog = Hog(int(mgr.budget * 1.5) + (1 << 20))
    adm = _adm(max_concurrent=4, timeout_s=0.2)
    mgr.register(hog, spillable=False)
    try:
        with pytest.raises(AdmissionTimeout):
            with adm.admit():
                pass
    finally:
        mgr.unregister(hog)
    with adm.admit():           # pressure gone: admits
        pass
    st = adm.stats()
    assert st["timeouts"] == 1 and st["admitted"] == 1


# ---------------------------------------------------------------------------
# POST /sql front door
# ---------------------------------------------------------------------------


def _post(port, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/sql", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        try:
            return e.code, json.loads(body)
        except ValueError:
            return e.code, {"error": body.decode(errors="replace")}


def test_post_sql_endpoint(server):
    from auron_tpu.utils import httpsvc

    port = httpsvc.start(0)
    httpsvc.install_sql_server(server)
    try:
        code, resp = _post(port, {"sql": _sql("q1a"), "tenant": "http"})
        assert code == 200
        assert resp["columns"] == ["cnt", "total", "mean"]
        assert len(resp["rows"]) == 1 and resp["rows"][0][0] > 0
        assert resp["digest"] and "trace_id" in resp
        code, resp = _post(port, {"sql": "select broken from"})
        assert code == 400 and "error" in resp
        code, resp = _post(port, {"sql": _sql("q1a"),
                                  "conf": {"obs.mode": "off"}})
        assert code == 400
        # /serve reflects the traffic
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/serve", timeout=30
        ) as r:
            stats = json.loads(r.read())
        assert stats["plan_cache"]["misses"] >= 1
        assert stats["queries_err"] >= 2
    finally:
        httpsvc.stop()


def test_post_sql_404_without_server():
    from auron_tpu.utils import httpsvc

    port = httpsvc.start(0)
    try:
        code, _ = _post(port, {"sql": "select 1"})
        assert code == 404
    finally:
        httpsvc.stop()


# ---------------------------------------------------------------------------
# the concurrency differential gate, toy scale
# ---------------------------------------------------------------------------


def test_servegate_toy_bit_identity_and_zero_compiles(frames, monkeypatch):
    from auron_tpu.models import servegate

    monkeypatch.setenv("SERVEGATE_RATCHET", "0")
    rec = servegate.run_gate(sf=TOY_SF, clients=3, frames=frames,
                             names=["q3", "q96", "q5a"], min_speedup=0.0)
    assert rec["ok"], rec["failures"]
    assert rec["replay_compiles"] == 0
    assert rec["concurrent_compiles"] == 0
    assert rec["concurrent"]["p50_ms"] is not None


def test_servegate_detects_divergence(frames, monkeypatch):
    """Teeth: a server returning wrong rows must FAIL the gate."""
    from auron_tpu.models import servegate

    monkeypatch.setenv("SERVEGATE_RATCHET", "0")
    srv = SqlServer(sqlgate.gate_catalog(), frames, n_parts=2)
    real_submit = srv.submit
    calls = {"n": 0}

    def flaky(sql, session=None, tenant=None):
        df, rec = real_submit(sql, session=session, tenant=tenant)
        calls["n"] += 1
        if tenant == "client0" and len(df):
            df = df.iloc[::-1].reset_index(drop=True)  # reordered rows
        return df, rec

    srv.submit = flaky
    rec = servegate.run_gate(sf=TOY_SF, clients=2, names=["q3"],
                             min_speedup=0.0, server=srv)
    assert not rec["ok"]
    assert any("diverged" in f for f in rec["failures"])

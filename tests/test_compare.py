"""Unit tests for the shared differential comparator (models/compare.py).

Every tolerance rule the gates rely on has a direct test here: NULL-only-
matches-NULL, the epsilon-OR-ULP float rule, decimal exactness (no float
round trip), sorted-row canonicalization with NULLs-first ordering, and
the delegation from models/tpcds._cmp_frames so the class gate and the
SQL gate cannot diverge.
"""

import decimal

import numpy as np
import pandas as pd
import pytest

from auron_tpu.models import tpcds
from auron_tpu.models.compare import (
    canonical_sort,
    compare_frames,
    float_close,
    is_null_scalar,
)


def _df(**cols):
    return pd.DataFrame(dict(cols))


# ---------------------------------------------------------------------------
# NULL rule
# ---------------------------------------------------------------------------


def test_null_scalar_forms():
    assert is_null_scalar(None)
    assert is_null_scalar(float("nan"))
    assert is_null_scalar(pd.NA)
    assert is_null_scalar(pd.NaT)
    assert not is_null_scalar(0)
    assert not is_null_scalar("")
    assert not is_null_scalar([1, 2])  # containers are values, not NULLs
    assert not is_null_scalar(np.array([1]))


def test_null_matches_only_null():
    assert compare_frames(_df(a=[None, 1.0]), _df(a=[np.nan, 1.0])) is None
    err = compare_frames(_df(a=[0.0]), _df(a=[np.nan]))
    assert err is not None and "a[0]" in err
    err = compare_frames(_df(a=[None]), _df(a=[0.0]))
    assert err is not None and "a[0]" in err


# ---------------------------------------------------------------------------
# float rule: relative epsilon OR ULP distance
# ---------------------------------------------------------------------------


def test_float_rel_epsilon():
    assert float_close(1.0000001, 1.0, rel=1e-6)
    assert not float_close(1.001, 1.0, rel=1e-6)
    # tiny magnitudes: epsilon scales with max(1, |b|), keeping absolute
    # 1e-6 room near zero
    assert float_close(1e-9, 2e-9, rel=1e-6)


def test_float_ulp_keeps_huge_magnitudes_honest():
    b = 1e300
    one_ulp = np.nextafter(b, np.inf)
    assert float_close(float(one_ulp), b, rel=0.0)  # 1 ULP <= 4
    # ~1e6 ULPs away but still within 1e-6 relative — the epsilon term
    # accepts; with rel=0 the ULP term alone must reject
    far = b * (1 + 1e-9)
    assert float_close(far, b, rel=1e-6)
    assert not float_close(far, b, rel=1e-12)


def test_float_nonfinite_never_close():
    assert float_close(float("inf"), float("inf"))  # == catches equals
    assert not float_close(float("inf"), 1e308)
    assert not float_close(float("nan"), float("nan"))  # NULLs handled upstream


def test_float_sign_straddle_ulp():
    # the int64 bit trick must stay monotone across the sign boundary
    a = np.nextafter(0.0, -1.0)
    assert float_close(float(a), float(np.nextafter(0.0, 1.0)), rel=0.0)


def test_frame_float_tolerance_applied():
    assert compare_frames(
        _df(x=[1.0000001]), _df(x=[1.0]), float_tol=1e-6) is None
    err = compare_frames(_df(x=[1.01]), _df(x=[1.0]), float_tol=1e-6)
    assert err is not None


# ---------------------------------------------------------------------------
# decimal rule: exact numeric equality, never through a float round trip
# ---------------------------------------------------------------------------


def test_decimal_exactness():
    d = decimal.Decimal
    assert compare_frames(
        _df(x=[d("1.10")]), _df(x=[d("1.1")])) is None  # numeric equality
    # differs only past float53 precision: a float round trip would pass,
    # the decimal rule must fail
    a = d("0.10000000000000000001")
    b = d("0.1")
    assert float(a) == float(b)
    err = compare_frames(_df(x=[a]), _df(x=[b]))
    assert err is not None and "decimal exact" in err
    # mixed: engine returns a string/float against a decimal oracle —
    # still compared as decimals
    assert compare_frames(_df(x=["1.50"]), _df(x=[d("1.5")])) is None
    err = compare_frames(_df(x=["not-a-number"]), _df(x=[d("1.5")]))
    assert err is not None


# ---------------------------------------------------------------------------
# structure rules
# ---------------------------------------------------------------------------


def test_row_count_and_missing_column():
    assert "row count" in compare_frames(_df(a=[1]), _df(a=[1, 2]))
    assert "missing column" in compare_frames(_df(a=[1]), _df(b=[1]))


def test_exact_rule_for_other_types():
    assert compare_frames(_df(s=["x"], i=[3]), _df(s=["x"], i=[3])) is None
    assert compare_frames(_df(s=["x"]), _df(s=["y"])) is not None


# ---------------------------------------------------------------------------
# sorted-row canonicalization (the SQL gate's mode)
# ---------------------------------------------------------------------------


def test_sorted_rows_order_independent():
    got = _df(k=[2, 1, 3], v=[2.0, 1.0, 3.0])
    want = _df(k=[1, 2, 3], v=[1.0, 2.0, 3.0])
    assert compare_frames(got, want) is not None  # unsorted mode: mismatch
    assert compare_frames(got, want, sorted_rows=True) is None


def test_sorted_rows_nulls_first_total_order():
    df = _df(k=[3.0, None, 1.0])
    out = canonical_sort(df)
    assert is_null_scalar(out["k"][0])
    assert out["k"].tolist()[1:] == [1.0, 3.0]


def test_sorted_rows_extra_engine_columns_ignored():
    got = _df(b=[2, 1], a=[20, 10], extra=[0, 0])
    want = _df(a=[10, 20], b=[1, 2])
    # sorted mode projects to the oracle's columns before canonicalizing
    assert compare_frames(got, want, sorted_rows=True) is None


def test_sorted_rows_value_mismatch_still_caught():
    got = _df(k=[1, 2], v=[1.0, 99.0])
    want = _df(k=[2, 1], v=[2.0, 1.0])
    assert compare_frames(got, want, sorted_rows=True) is not None


# ---------------------------------------------------------------------------
# gate unification: tpcds._cmp_frames is the same comparator
# ---------------------------------------------------------------------------


def test_tpcds_cmp_frames_delegates():
    d = decimal.Decimal
    # decimal exactness now applies through the class-gate entry point too
    err = tpcds._cmp_frames(
        _df(x=[d("0.10000000000000000001")]), _df(x=[d("0.1")]))
    assert err is not None and "decimal exact" in err
    assert tpcds._cmp_frames(_df(x=[1.0 + 1e-9]), _df(x=[1.0])) is None

"""Column-pruning optimizer pass: pruned plans must be result-identical
and actually shrink join outputs (reference: common/column_pruning.rs)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exprs.ir import BinaryOp, col, lit
from auron_tpu.plan import builders as B
from auron_tpu.plan.optimizer import prune_columns
from auron_tpu.plan.planner import plan_from_proto


def _mk_batch(df):
    return [Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))]


def _schema(df):
    return T.Schema.from_arrow(
        pa.RecordBatch.from_pandas(df.iloc[:1], preserve_index=False).schema
    )


@pytest.fixture()
def star():
    rng = np.random.default_rng(11)
    fact = pd.DataFrame(
        {
            "f_date": rng.integers(0, 50, 3000).astype(np.int64),
            "f_item": rng.integers(0, 40, 3000).astype(np.int64),
            "f_junk1": rng.normal(size=3000),
            "f_junk2": rng.integers(0, 9, 3000).astype(np.int64),
            "f_price": rng.integers(1, 500, 3000).astype(np.int64),
        }
    )
    dim = pd.DataFrame(
        {
            "d_sk": np.arange(50, dtype=np.int64),
            "d_year": (1998 + np.arange(50) % 5).astype(np.int64),
            "d_junk": rng.normal(size=50),
        }
    )
    return fact, dim


def _q(fact_schema, dim_schema):
    """scan(fact) JOIN dim ON f_date=d_sk -> project(year, price)
    -> partial agg sum(price) by year."""
    scan = B.memory_scan(fact_schema, "opt_fact")
    dscan = B.memory_scan(dim_schema, "opt_dim")
    j = B.hash_join(scan, dscan, [col(0)], [col(0)], "inner", build_side="right")
    proj = B.project(j, [(col(6), "year"), (col(4), "price")])
    return B.hash_agg(proj, [(col(0), "year")], [("sum", col(1), "s")], "partial")


def _run_plan(plan, fact, dim):
    from auron_tpu.exec.base import ExecutionContext

    ctx = ExecutionContext(
        resources={"opt_fact": [_mk_batch(fact)], "opt_dim": [_mk_batch(dim)]}
    )
    op = plan_from_proto(plan)
    return op.collect(ctx=ctx).to_pandas().sort_values("year").reset_index(drop=True)


def test_prune_shrinks_join_and_preserves_results(star):
    fact, dim = star
    plan = _q(_schema(fact), _schema(dim))
    pruned = prune_columns(plan)

    # the join now carries a projection and the project references remapped
    j = pruned.hash_agg.child.project.child.hash_join
    assert j.has_projection
    assert len(j.projection) < 8  # 5 fact + 3 dim columns before pruning
    op = plan_from_proto(pruned)
    join_op = op.children[0].children[0]
    assert len(join_op.schema) == len(j.projection)

    got_orig = _run_plan(plan, fact, dim)
    got_pruned = _run_plan(pruned, fact, dim)
    pd.testing.assert_frame_equal(got_orig, got_pruned)

    want = (
        fact.merge(dim, left_on="f_date", right_on="d_sk")
        .groupby("d_year").agg(s=("f_price", "sum")).reset_index()
        .rename(columns={"d_year": "year"})
        .sort_values("year").reset_index(drop=True)
    )
    assert got_pruned["year"].tolist() == want["year"].tolist()
    assert got_pruned["s#sum"].astype(np.int64).tolist() == want["s"].tolist()


@pytest.mark.parametrize("join_type", ["left", "right", "full", "left_semi",
                                       "left_anti", "existence"])
def test_prune_all_join_types_result_identical(star, join_type):
    fact, dim = star
    fs, ds = _schema(fact), _schema(dim)
    scan = B.memory_scan(fs, "opt_fact")
    dscan = B.memory_scan(ds, "opt_dim")
    j = B.hash_join(scan, dscan, [col(0)], [col(0)], join_type, build_side="right")
    if join_type in ("left_semi", "left_anti"):
        proj = B.project(j, [(col(0), "k"), (col(4), "price")])
    elif join_type == "existence":
        proj = B.project(j, [(col(0), "k"), (col(5), "ex")])
    else:
        proj = B.project(j, [(col(0), "k"), (col(6), "year")])
    pruned = prune_columns(proj)
    got_orig = _run_plan_nosort(proj, fact, dim)
    got_pruned = _run_plan_nosort(pruned, fact, dim)
    pd.testing.assert_frame_equal(got_orig, got_pruned)


def _run_plan_nosort(plan, fact, dim):
    from auron_tpu.exec.base import ExecutionContext

    ctx = ExecutionContext(
        resources={"opt_fact": [_mk_batch(fact)], "opt_dim": [_mk_batch(dim)]}
    )
    op = plan_from_proto(plan)
    df = op.collect(ctx=ctx).to_pandas()
    return df.sort_values(list(df.columns)).reset_index(drop=True)

"""Query-scoped structured tracing (auron_tpu/obs, docs/observability.md).

The acceptance teeth live in test_gate_class_trace_is_complete_and_agrees:
a gate-class replay under full tracing must export a Perfetto-loadable
trace whose per-operator span totals agree with MetricNode.op_seconds
within 5%, and whose event stream carries compile, host-sync, spill and
async-harvest events — with a FORCED spill and a FORCED sync performed
by foreign threads still attributed to the owning task's trace.
"""

import json
import threading

import jax
import jax.numpy as jnp
import pytest

from auron_tpu import obs
from auron_tpu import types as T
from auron_tpu.bridge import api
from auron_tpu.columnar import Batch
from auron_tpu.exec.metrics import MetricNode
from auron_tpu.exprs.ir import col
from auron_tpu.obs import core, export
from auron_tpu.plan import builders as B
from auron_tpu.utils.profiling import EngineCounters


@pytest.fixture(autouse=True)
def _restore_mode():
    prev = obs.mode()
    yield
    obs.set_mode(prev)


def _events(trace_id=None, kind=None):
    out = []
    for _ring, evs in core.snapshot_events(trace_id=trace_id):
        for ev in evs:
            if kind is None or ev[2] == kind:
                out.append(ev)
    return out


# ---------------------------------------------------------------------------
# span model
# ---------------------------------------------------------------------------


def test_mode_off_short_circuits_everything():
    obs.set_mode("off")
    with obs.query_trace("off_query") as qt:
        assert qt.trace is None
        with obs.span("x") as sp:
            assert sp is None
        obs.note_op("Op", "elapsed_compute", 123)
    assert qt.summary is None


def test_span_nesting_and_contextvar():
    obs.set_mode("trace")
    with obs.query_trace("nest") as qt:
        root = obs.current_span()
        assert root is not None and root.trace is qt.trace
        with obs.span("child") as c:
            assert c.parent_id == root.span_id
            assert obs.current_span() is c
        assert obs.current_span() is root
    assert obs.current_span() is None
    evs = _events(trace_id=qt.trace.id, kind="span")
    assert {e[3] for e in evs} >= {"child", "nest"}


def test_use_span_hands_off_across_threads_and_none_clears():
    obs.set_mode("trace")
    seen = {}
    with obs.query_trace("hop") as qt:
        sp = obs.current_span()

        def foreign():
            with obs.use_span(sp):
                seen["inside"] = obs.current_span()
                obs.note_op("ForeignOp", "elapsed_compute", 1000)
            seen["after"] = obs.current_span()

        t = threading.Thread(target=foreign)
        t.start()
        t.join()
    assert seen["inside"] is sp and seen["after"] is None
    assert qt.trace.span_op_seconds().get("ForeignOp") == pytest.approx(1e-6)
    # use_span(None) CLEARS: an untraced producer must not inherit the
    # executing thread's foreign span
    with obs.span("ambient"):
        with obs.use_span(None):
            assert obs.current_span() is None


def test_ring_is_bounded_and_wraps():
    obs.set_mode("recorder")
    core.set_ring_capacity(256)
    try:
        done = []

        def burst():
            for i in range(1000):
                core.record("t", f"e{i}", 0, 0, 0, 0, None)
            r = core._tls.ring
            done.append((r.idx, r.cap, sum(1 for x in r.buf if x)))

        t = threading.Thread(target=burst)  # fresh thread -> fresh ring
        t.start()
        t.join()
        idx, cap, filled = done[0]
        assert cap == 256 and idx == 1000 and filled == 256
    finally:
        core.set_ring_capacity(32768)


def test_recorder_mode_rings_only_no_per_event_lock():
    """recorder vs trace distinction: recorder records ring events and
    publishes per-task summaries, but never takes the per-event Trace
    lock (span_op_ns / sync counters stay empty); trace accumulates."""
    obs.set_mode("recorder")
    with obs.query_trace("rec_mode") as qt:
        obs.note_op("SomeExec", "elapsed_compute", 5_000_000)
        obs.note_sync(100_000, False)
    assert _events(trace_id=qt.trace.id, kind="op")      # rings: yes
    assert qt.trace.span_op_ns == {}                     # accumulators: no
    assert qt.summary["host_syncs"] == 0
    assert qt.summary["trace_id"] == qt.trace.id         # /queries: yes
    obs.set_mode("trace")
    with obs.query_trace("trace_mode") as qt2:
        obs.note_op("SomeExec", "elapsed_compute", 5_000_000)
    assert qt2.trace.span_op_seconds()["SomeExec"] == pytest.approx(0.005)


def test_apply_conf_ignores_env_only_mode(monkeypatch):
    """An env-set obs.mode must not be re-asserted per task: it already
    took effect at import, and re-applying would clobber a later
    programmatic set_mode (bench --trace-out under env off)."""
    from auron_tpu.utils.config import Configuration

    monkeypatch.setenv("AURON_TPU_OBS_MODE", "off")
    obs.set_mode("trace")
    obs.apply_conf(Configuration())          # env-only: no-op
    assert obs.mode() == obs.MODE_TRACE
    obs.apply_conf(Configuration().set(obs.OBS_MODE, "recorder"))
    assert obs.mode() == obs.MODE_RECORDER   # session-set: applies


def test_query_trace_summary_lands_in_recent_ring():
    obs.set_mode("trace")
    with obs.query_trace("ringed") as qt:
        obs.note_op("AggExec", "elapsed_compute", 2_000_000)
        obs.note_sync(500_000, False)
    recent = obs.recent_queries()
    assert recent and recent[0]["trace_id"] == qt.trace.id
    assert recent[0]["host_syncs"] == 1
    assert recent[0]["name"] == "ringed"


def test_sql_compile_emits_parse_bind_lower_spans():
    from auron_tpu.sql import compile_text

    obs.set_mode("trace")
    with obs.query_trace("sqlspans") as qt:
        compile_text(
            "select ss_item_sk, sum(ss_ext_sales_price) s from store_sales "
            "group by ss_item_sk"
        )
    names = {e[3] for e in _events(trace_id=qt.trace.id, kind="span")}
    assert {"sql.parse", "sql.bind", "sql.lower"} <= names


# ---------------------------------------------------------------------------
# the acceptance teeth
# ---------------------------------------------------------------------------


def test_gate_class_trace_is_complete_and_agrees(tmp_path):
    from auron_tpu.memory.memmgr import MemManager
    from auron_tpu.models import tpcds
    from auron_tpu.runtime.transfer import TransferWindow

    EngineCounters.install()
    obs.set_mode("trace")
    spilled = threading.Event()

    class _Consumer:
        name = "teeth_consumer"

        def mem_used(self):
            return 0 if spilled.is_set() else (4 << 20)

        def spill(self):
            spilled.set()
            return 4 << 20

    data = tpcds.generate(sf=0.1, seed=3)
    with obs.query_trace("gate.q3") as qt:
        # --- the gate-class replay itself
        tpcds.run_q3_class(data, n_map=2, n_reduce=2,
                           work_dir=str(tmp_path / "q3"))
        # --- forced spill: consumer registered under the OWNING trace,
        # spill dispatched by a FOREIGN thread with no span installed
        mm = MemManager(budget_bytes=0)
        mm.register(_Consumer())
        t = threading.Thread(
            target=lambda: mm.acquire(_Consumer(), 1 << 20)
        )
        t.start()
        t.join()
        assert spilled.is_set()
        # --- forced sync on a foreign thread, span threaded explicitly
        # (the R7 hand-off recipe, docs/observability.md)
        sp = obs.current_span()
        arr = jnp.arange(1 << 16)

        def foreign_sync():
            with obs.use_span(sp):
                jax.device_get(arr + 1)

        t = threading.Thread(target=foreign_sync)
        t.start()
        t.join()
        # --- a compile inside the trace (fresh persistent-cache dir so
        # the compile can't be served from the box's warm XLA cache)
        prev_cache = jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir",
                          str(tmp_path / "xlacache"))
        try:
            jax.jit(lambda x: x * 3 + 1)(jnp.arange(12347))
        finally:
            jax.config.update("jax_compilation_cache_dir", prev_cache)
        # --- an async-transfer harvest inside the trace
        w = TransferWindow(1)
        for i in range(3):
            w.push((jnp.asarray([i]),), i)
        list(w.drain())

    out = str(tmp_path / "trace.json")
    export.write_chrome_trace(out, trace_id=qt.trace.id)
    with open(out) as f:
        ct = json.load(f)

    # Perfetto-loadable shape: X events with name/ts/dur/pid/tid
    xs = [e for e in ct["traceEvents"] if e["ph"] == "X"]
    assert xs
    for e in xs:
        assert {"name", "cat", "ts", "dur", "pid", "tid"} <= set(e)
        assert e["pid"] == qt.trace.id  # every event attributed

    # the full event stream is present, attributed to THIS trace even for
    # the foreign-thread spill and sync
    kinds = {e["cat"] for e in xs}
    assert {"op", "span", "sync", "compile", "spill", "transfer"} <= kinds
    spill_evs = [e for e in xs if e["cat"] == "spill"
                 and e["args"].get("consumer") == "teeth_consumer"]
    assert spill_evs, "forced foreign-thread spill missing from the trace"
    assert any(e["name"] == "harvest" for e in xs if e["cat"] == "transfer")
    assert any(e["name"] == "host_sync" for e in xs if e["cat"] == "sync")

    # per-operator span totals FROM THE EXPORTED FILE agree with the
    # MetricNode.op_seconds rollup within 5%
    from_file: dict[str, float] = {}
    for e in xs:
        if e["cat"] != "op":
            continue
        metric = e["args"]["metric"]
        if metric in MetricNode.NESTED_TIMERS:
            continue
        op = e["args"]["op"]
        from_file[op] = from_file.get(op, 0.0) + e["dur"] / 1e6
    metric_ops = qt.trace.metric_op_seconds()
    assert metric_ops, "no finalize-time metric rollup reached the trace"
    for op, secs in metric_ops.items():
        if secs < 0.01:
            continue  # sub-10ms ops: rounding noise dominates percentages
        assert from_file.get(op, 0.0) == pytest.approx(secs, rel=0.05), (
            op, from_file.get(op), secs
        )
    # and the Trace's own accumulator agrees too (what perf_gate emits)
    assert qt.trace.op_seconds_skew()["ok"]


def test_spill_container_attributes_via_conf_trace_id():
    """HostSpill carries the owning conf; a write on a foreign thread
    attributes through obs.trace.id with NO live span anywhere."""
    import pyarrow as pa

    from auron_tpu.memory.memmgr import make_spill
    from auron_tpu.utils.config import Configuration

    obs.set_mode("trace")
    with obs.query_trace("conf_attr") as qt:
        from auron_tpu.utils.config import active_conf

        conf = active_conf().copy()  # carries obs.trace.id
    # trace CLOSED; write from a plain thread with no span: the ring event
    # must still carry the owning trace id
    spill = make_spill(conf=conf)
    tbl = pa.table({"v": list(range(100))})

    def foreign_write():
        spill.write_table(tbl)

    t = threading.Thread(target=foreign_write)
    t.start()
    t.join()
    evs = _events(trace_id=qt.trace.id, kind="spill")
    assert any(e[3] == "write" for e in evs)
    spill.release()


def test_chrome_trace_last_window_filters_old_events():
    import time as _t

    obs.set_mode("recorder")
    core.record("t", "old_event_marker", 0, 0, 0, 0, None)
    _t.sleep(0.05)
    core.record("t", "new_event_marker", 0, 0, 0, 0, None)
    ct = export.chrome_trace(last_s=0.03)
    names = {e["name"] for e in ct["traceEvents"] if e["ph"] == "X"}
    assert "new_event_marker" in names and "old_event_marker" not in names

"""Native (C++) helper tests: parity with device kernels / numpy."""

import numpy as np
import pytest

from auron_tpu import native


def test_native_available():
    # the library builds in this environment; if this fails the fallbacks
    # still keep the engine correct, but we want CI to notice
    assert native.available()


def test_murmur3_i64_matches_device():
    import jax.numpy as jnp

    from auron_tpu.ops.hashing import murmur3_i64

    v = np.array([1, 0, -1, 2**63 - 1, -(2**63), 123456789], dtype=np.int64)
    got = native.murmur3_i64_host(v)
    want = np.asarray(murmur3_i64(jnp.asarray(v), jnp.uint32(42)).view(jnp.int32))
    assert (got == want).all()


def test_murmur3_bytes_matches_spark_vectors():
    strings = ["hello", "bar", "", "😁", "天地"]
    bufs = [s.encode() for s in strings]
    data = b"".join(bufs)
    offsets = np.zeros(len(bufs) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in bufs], out=offsets[1:])
    got = native.murmur3_bytes_host(data, offsets).tolist()
    want = [v - (1 << 32) if v >= (1 << 31) else v
            for v in [3286402344, 2486176763, 142593372, 885025535, 2395000894]]
    assert got == want


def test_radix_partition():
    rng = np.random.default_rng(17)
    pids = rng.integers(0, 7, 10_000).astype(np.int32)
    counts, order = native.radix_partition_host(pids, 7)
    assert counts.sum() == 10_000
    assert (counts == np.bincount(pids, minlength=7)).all()
    clustered = pids[order]
    assert (np.diff(clustered) >= 0).all()
    # stability: within each partition, original order preserved
    for p in range(7):
        rows = order[clustered == p]
        assert (np.diff(rows) > 0).all()


def test_loser_tree_merge_matches_lexsort():
    rng = np.random.default_rng(18)
    runs = []
    for _ in range(5):
        n = rng.integers(1, 500)
        w1 = np.sort(rng.integers(0, 50, n).astype(np.uint64))
        # secondary word sorted within w1 groups
        w2 = rng.integers(0, 50, n).astype(np.uint64)
        order = np.lexsort((w2, w1))
        runs.append([w1[order], w2[order]])
    out_run, out_idx = native.loser_tree_merge_host(runs)
    merged_w1 = np.array([runs[r][0][i] for r, i in zip(out_run, out_idx)])
    merged_w2 = np.array([runs[r][1][i] for r, i in zip(out_run, out_idx)])
    packed = merged_w1 * 10_000 + merged_w2
    assert (np.diff(packed.astype(np.int64)) >= 0).all()
    assert len(out_run) == sum(len(r[0]) for r in runs)


def test_pallas_partition_ids_interpret():
    """Pallas murmur3+pmod kernel matches the jnp reference (interpret mode
    on CPU; the same kernel compiles for TPU)."""
    import jax.numpy as jnp

    from auron_tpu.ops import hashing as H
    from auron_tpu.ops.pallas_kernels import partition_ids_pallas

    rng = np.random.default_rng(41)
    v = jnp.asarray(rng.integers(-(2**62), 2**62, 1000))
    try:
        got = np.asarray(partition_ids_pallas(v, 16, interpret=True))
    except NotImplementedError as e:
        pytest.skip(f"pallas unavailable on this jaxlib build: {e}")
    want = np.asarray(H.pmod(H.murmur3_i64(v, jnp.uint32(42)).view(jnp.int32), 16))
    assert (got == want).all()

"""Native (C++) helper tests: parity with device kernels / numpy."""

import os

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu import native


def test_native_available():
    # the library builds in this environment; if this fails the fallbacks
    # still keep the engine correct, but we want CI to notice
    assert native.available()


def test_murmur3_i64_matches_device():
    import jax.numpy as jnp

    from auron_tpu.ops.hashing import murmur3_i64

    v = np.array([1, 0, -1, 2**63 - 1, -(2**63), 123456789], dtype=np.int64)
    got = native.murmur3_i64_host(v)
    want = np.asarray(murmur3_i64(jnp.asarray(v), jnp.uint32(42)).view(jnp.int32))
    assert (got == want).all()


def test_murmur3_bytes_matches_spark_vectors():
    strings = ["hello", "bar", "", "😁", "天地"]
    bufs = [s.encode() for s in strings]
    data = b"".join(bufs)
    offsets = np.zeros(len(bufs) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in bufs], out=offsets[1:])
    got = native.murmur3_bytes_host(data, offsets).tolist()
    want = [v - (1 << 32) if v >= (1 << 31) else v
            for v in [3286402344, 2486176763, 142593372, 885025535, 2395000894]]
    assert got == want


def test_radix_partition():
    rng = np.random.default_rng(17)
    pids = rng.integers(0, 7, 10_000).astype(np.int32)
    counts, order = native.radix_partition_host(pids, 7)
    assert counts.sum() == 10_000
    assert (counts == np.bincount(pids, minlength=7)).all()
    clustered = pids[order]
    assert (np.diff(clustered) >= 0).all()
    # stability: within each partition, original order preserved
    for p in range(7):
        rows = order[clustered == p]
        assert (np.diff(rows) > 0).all()


def test_loser_tree_merge_matches_lexsort():
    rng = np.random.default_rng(18)
    runs = []
    for _ in range(5):
        n = rng.integers(1, 500)
        w1 = np.sort(rng.integers(0, 50, n).astype(np.uint64))
        # secondary word sorted within w1 groups
        w2 = rng.integers(0, 50, n).astype(np.uint64)
        order = np.lexsort((w2, w1))
        runs.append([w1[order], w2[order]])
    out_run, out_idx = native.loser_tree_merge_host(runs)
    merged_w1 = np.array([runs[r][0][i] for r, i in zip(out_run, out_idx)])
    merged_w2 = np.array([runs[r][1][i] for r, i in zip(out_run, out_idx)])
    packed = merged_w1 * 10_000 + merged_w2
    assert (np.diff(packed.astype(np.int64)) >= 0).all()
    assert len(out_run) == sum(len(r[0]) for r in runs)


def test_pallas_partition_ids_interpret():
    """Pallas murmur3+pmod kernel matches the jnp reference (interpret mode
    on CPU; the same kernel compiles for TPU)."""
    import jax.numpy as jnp

    from auron_tpu.ops import hashing as H
    from auron_tpu.ops.pallas_kernels import partition_ids_pallas

    rng = np.random.default_rng(41)
    v = jnp.asarray(rng.integers(-(2**62), 2**62, 1000))
    try:
        got = np.asarray(partition_ids_pallas(v, 16, interpret=True))
    except NotImplementedError as e:
        pytest.skip(f"pallas unavailable on this jaxlib build: {e}")
    want = np.asarray(H.pmod(H.murmur3_i64(v, jnp.uint32(42)).view(jnp.int32), 16))
    assert (got == want).all()


# ---------------------------------------------------------------------------
# C ABI bridge (native/auron_bridge.cpp): a C host engine drives a
# TaskDefinition end-to-end through the exported symbols — the analog of
# JniBridge.java:49-80 + exec.rs:42-122
# ---------------------------------------------------------------------------


def _build_bridge():
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    native = os.path.join(root, "native")
    import shutil

    if shutil.which("make") is None:
        pytest.skip("no make in this environment")
    r = subprocess.run(
        ["make", "-C", native, "libauron_bridge.so", "bridge_harness"],
        capture_output=True, text=True,
    )
    # toolchain exists: a broken build is a FAILURE, not a skip
    assert r.returncode == 0, f"bridge build failed: {r.stderr[-800:]}"
    return os.path.join(native, "bridge_harness")


def _harness_env():
    import sysconfig

    env = dict(os.environ)
    env["PYTHONPATH"] = sysconfig.get_paths()["purelib"]
    env["JAX_PLATFORMS"] = "cpu"
    env["AURON_TPU_ROOT"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return env


def _ipc_bytes(rb):
    import io

    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as w:
        w.write_batch(rb)
    return sink.getvalue()


def _decode_framed(path):
    import io
    import struct

    data = open(path, "rb").read()
    pos, rows = 0, []
    while pos < len(data):
        (n,) = struct.unpack_from("<Q", data, pos)
        pos += 8
        with pa.ipc.open_stream(io.BytesIO(data[pos : pos + n])) as r:
            for rb in r:
                rows += rb.to_pylist()
        pos += n
    return rows


def test_c_abi_filter_project_roundtrip(tmp_path):
    import json
    import subprocess

    from auron_tpu import types as T
    from auron_tpu.exprs.ir import BinaryOp, col, lit
    from auron_tpu.plan import builders as B

    harness = _build_bridge()
    schema = T.Schema.of(T.Field("k", T.INT64), T.Field("v", T.INT64))
    plan = B.project(
        B.filter_(B.ffi_reader(schema, "input"), [BinaryOp("gt", col(1), lit(10))]),
        [(col(0), "k"), (BinaryOp("mul", col(1), lit(2)), "v2")],
    )
    task_f = tmp_path / "task.bin"
    task_f.write_bytes(B.task(plan).SerializeToString())
    rb = pa.record_batch(
        {"k": np.arange(6, dtype=np.int64),
         "v": np.array([5, 11, 7, 20, 30, 9], dtype=np.int64)}
    )
    in_f = tmp_path / "input.bin"
    in_f.write_bytes(_ipc_bytes(rb))
    out_f = tmp_path / "out.bin"

    r = subprocess.run(
        [harness, str(task_f), str(out_f), "input", str(in_f)],
        env=_harness_env(), capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr
    rows = _decode_framed(out_f)
    assert rows == [{"k": 1, "v2": 22}, {"k": 3, "v2": 40}, {"k": 4, "v2": 60}]
    metrics = json.loads(r.stdout)
    # whole-stage fusion compiles the filter->project chain into one
    # FusedStageExec whose metric children keep the per-operator split
    # (docs/fusion.md) — the harvested tree must still name both operators
    assert metrics["name"] == "FusedStageExec"
    child_names = {c["name"] for c in metrics["children"]}
    assert {"FilterExec", "ProjectExec"} <= child_names


def test_c_abi_aggregate_through_so(tmp_path):
    import subprocess

    from auron_tpu import types as T
    from auron_tpu.exprs.ir import col
    from auron_tpu.plan import builders as B

    harness = _build_bridge()
    schema = T.Schema.of(T.Field("k", T.INT64), T.Field("v", T.INT64))
    agg_p = B.hash_agg(B.ffi_reader(schema, "rows"),
                       [(col(0), "k")], [("sum", col(1), "s")], "partial")
    agg_f = B.hash_agg(agg_p, [(col(0), "k")], [("sum", col(1), "s")], "final")
    task_f = tmp_path / "task.bin"
    task_f.write_bytes(B.task(agg_f).SerializeToString())

    rng = np.random.default_rng(5)
    k = rng.integers(0, 7, 500).astype(np.int64)
    v = rng.integers(-100, 100, 500).astype(np.int64)
    in_f = tmp_path / "rows.bin"
    in_f.write_bytes(_ipc_bytes(pa.record_batch({"k": k, "v": v})))
    out_f = tmp_path / "out.bin"

    r = subprocess.run(
        [harness, str(task_f), str(out_f), "rows", str(in_f)],
        env=_harness_env(), capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr
    got = sorted((row["k"], row["s"]) for row in _decode_framed(out_f))
    import pandas as pd

    want = sorted(
        pd.DataFrame({"k": k, "v": v}).groupby("k")["v"].sum().items()
    )
    assert got == want


def test_c_abi_error_relay(tmp_path):
    import subprocess

    harness = _build_bridge()
    task_f = tmp_path / "bad.bin"
    task_f.write_bytes(b"\x00not a protobuf")
    out_f = tmp_path / "out.bin"
    r = subprocess.run(
        [harness, str(task_f), str(out_f)],
        env=_harness_env(), capture_output=True, text=True, timeout=300,
    )
    assert r.returncode != 0
    assert "failed" in r.stderr


def test_pallas_partition_histogram_interpret():
    import jax.numpy as jnp

    from auron_tpu.ops.pallas_kernels import partition_histogram_pallas

    rng = np.random.default_rng(9)
    pids = rng.integers(0, 7, 5000).astype(np.int32)
    try:
        got = np.asarray(partition_histogram_pallas(jnp.asarray(pids), 7, interpret=True))
    except NotImplementedError as e:
        pytest.skip(f"pallas unavailable: {e}")
    want = np.bincount(pids, minlength=7)
    assert (got == want).all()


def test_pallas_pid_path_matches_generic(monkeypatch):
    """Force the gated pallas pid path (interpret mode) through the real
    HashPartitioning entry and compare with the generic jnp path."""
    import jax.numpy as jnp

    import auron_tpu.exec.shuffle.partitioning as P
    import auron_tpu.ops.pallas_kernels as PK
    from auron_tpu import types as T
    from auron_tpu.columnar import Batch
    from auron_tpu.exprs.ir import col

    rng = np.random.default_rng(10)
    b = Batch.from_pydict(
        {"k": rng.integers(-(2**60), 2**60, 2000).tolist()},
        schema=T.Schema.of(T.Field("k", T.INT64)),
    )
    hp = P.HashPartitioning([col(0)], 16)
    want = np.asarray(hp.partition_ids(b, None))

    monkeypatch.setattr(PK, "use_pallas", lambda: True)
    orig = PK.partition_ids_pallas
    monkeypatch.setattr(
        PK, "partition_ids_pallas",
        lambda v, n, seed=42: orig(v, n, seed=seed, interpret=True),
    )
    try:
        got = np.asarray(hp.partition_ids(b, None))
    except NotImplementedError as e:
        pytest.skip(f"pallas unavailable: {e}")
    assert (got == want).all()

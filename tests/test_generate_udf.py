"""Generate (explode/pos_explode/json_tuple) and host-UDF fallback tests."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exec.basic import MemoryScanExec, ProjectExec
from auron_tpu.exec.generate_exec import GenerateExec
from auron_tpu.exprs.ir import HostUDF, col
from auron_tpu.bridge.udf import register_udf


def _scan(rb):
    return MemoryScanExec.single([Batch.from_arrow(rb)])


def test_list_column_roundtrip():
    rb = pa.record_batch({"l": pa.array([[1, 2], None, [], [3]], type=pa.list_(pa.int64()))})
    b = Batch.from_arrow(rb)
    assert b.schema[0].dtype.kind == T.TypeKind.LIST
    assert b.to_arrow().column("l").to_pylist() == [[1, 2], None, [], [3]]


def test_explode():
    rb = pa.record_batch(
        {
            "id": pa.array([1, 2, 3, 4]),
            "l": pa.array([[10, 20], None, [], [30]], type=pa.list_(pa.int64())),
        }
    )
    g = GenerateExec(_scan(rb), "explode", col(1), required_cols=[0])
    out = g.collect_pydict()
    assert out == {"id": [1, 1, 4], "col": [10, 20, 30]}


def test_explode_outer_and_pos():
    rb = pa.record_batch(
        {
            "id": pa.array([1, 2, 3]),
            "l": pa.array([["a", "b"], None, []], type=pa.list_(pa.string())),
        }
    )
    g = GenerateExec(_scan(rb), "pos_explode", col(1), required_cols=[0], outer=True)
    out = g.collect_pydict()
    assert out["id"] == [1, 1, 2, 3]
    assert out["col"] == ["a", "b", None, None]
    assert out["pos"][:2] == [0, 1]


def test_json_tuple():
    rb = pa.record_batch(
        {
            "id": pa.array([1, 2, 3]),
            "j": pa.array(
                ['{"a": "x", "b": 2}', '{"a": null}', "not json"]
            ),
        }
    )
    g = GenerateExec(
        _scan(rb), "json_tuple", col(1), required_cols=[0], json_fields=["a", "b"]
    )
    out = g.collect_pydict()
    assert out == {"id": [1, 2, 3], "a": ["x", None, None], "b": ["2", None, None]}


def test_host_udf_fallback():
    def my_udf(args, n):
        a = args[0].to_pylist()
        return pa.array(
            [(s.upper() + "!" if s is not None else None) for s in a],
            type=pa.string(),
        )

    register_udf("exclaim", my_udf)
    rb = pa.record_batch({"s": pa.array(["hi", None, "yo"])})
    p = ProjectExec(
        _scan(rb), [HostUDF("exclaim", (col(0),), T.STRING)], ["e"]
    )
    assert p.collect_pydict() == {"e": ["HI!", None, "YO!"]}


def test_host_udf_numeric():
    def add_mod(args, n):
        import pyarrow.compute as pc

        return pc.add(args[0], args[1])

    register_udf("add2", add_mod)
    rb = pa.record_batch({"x": pa.array([1, 2]), "y": pa.array([10, None])})
    p = ProjectExec(_scan(rb), [HostUDF("add2", (col(0), col(1)), T.INT64)], ["z"])
    assert p.collect_pydict() == {"z": [11, None]}


def test_host_udtf():
    from auron_tpu.bridge.udf import register_udtf

    register_udtf(
        "ngrams",
        lambda s: [(s[i : i + 2], i) for i in range(len(s) - 1)] if s else [],
        T.Schema.of(T.Field("gram", T.STRING), T.Field("ofs", T.INT32)),
    )
    rb = pa.record_batch({"id": pa.array([1, 2, 3]),
                          "s": pa.array(["abc", "x", None])})
    g = GenerateExec(_scan(rb), "host_udtf", col(1), required_cols=[0], udtf="ngrams")
    out = g.collect_pydict()
    assert out == {"id": [1, 1], "gram": ["ab", "bc"], "ofs": [0, 1]}
    # outer mode emits a null row for non-generating inputs
    g2 = GenerateExec(_scan(rb), "host_udtf", col(1), required_cols=[0],
                      udtf="ngrams", outer=True)
    out2 = g2.collect_pydict()
    assert out2["id"] == [1, 1, 2, 3]
    assert out2["gram"] == ["ab", "bc", None, None]

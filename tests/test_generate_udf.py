"""Generate (explode/pos_explode/json_tuple) and host-UDF fallback tests."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exec.basic import MemoryScanExec, ProjectExec
from auron_tpu.exec.generate_exec import GenerateExec
from auron_tpu.exprs.ir import HostUDF, col
from auron_tpu.bridge.udf import register_udf


def _scan(rb):
    return MemoryScanExec.single([Batch.from_arrow(rb)])


def test_list_column_roundtrip():
    rb = pa.record_batch({"l": pa.array([[1, 2], None, [], [3]], type=pa.list_(pa.int64()))})
    b = Batch.from_arrow(rb)
    assert b.schema[0].dtype.kind == T.TypeKind.LIST
    assert b.to_arrow().column("l").to_pylist() == [[1, 2], None, [], [3]]


def test_explode():
    rb = pa.record_batch(
        {
            "id": pa.array([1, 2, 3, 4]),
            "l": pa.array([[10, 20], None, [], [30]], type=pa.list_(pa.int64())),
        }
    )
    g = GenerateExec(_scan(rb), "explode", col(1), required_cols=[0])
    out = g.collect_pydict()
    assert out == {"id": [1, 1, 4], "col": [10, 20, 30]}


def test_explode_outer_and_pos():
    rb = pa.record_batch(
        {
            "id": pa.array([1, 2, 3]),
            "l": pa.array([["a", "b"], None, []], type=pa.list_(pa.string())),
        }
    )
    g = GenerateExec(_scan(rb), "pos_explode", col(1), required_cols=[0], outer=True)
    out = g.collect_pydict()
    assert out["id"] == [1, 1, 2, 3]
    assert out["col"] == ["a", "b", None, None]
    assert out["pos"][:2] == [0, 1]


def test_json_tuple():
    rb = pa.record_batch(
        {
            "id": pa.array([1, 2, 3]),
            "j": pa.array(
                ['{"a": "x", "b": 2}', '{"a": null}', "not json"]
            ),
        }
    )
    g = GenerateExec(
        _scan(rb), "json_tuple", col(1), required_cols=[0], json_fields=["a", "b"]
    )
    out = g.collect_pydict()
    assert out == {"id": [1, 2, 3], "a": ["x", None, None], "b": ["2", None, None]}


def test_host_udf_fallback():
    def my_udf(args, n):
        a = args[0].to_pylist()
        return pa.array(
            [(s.upper() + "!" if s is not None else None) for s in a],
            type=pa.string(),
        )

    register_udf("exclaim", my_udf)
    rb = pa.record_batch({"s": pa.array(["hi", None, "yo"])})
    p = ProjectExec(
        _scan(rb), [HostUDF("exclaim", (col(0),), T.STRING)], ["e"]
    )
    assert p.collect_pydict() == {"e": ["HI!", None, "YO!"]}


def test_host_udf_numeric():
    def add_mod(args, n):
        import pyarrow.compute as pc

        return pc.add(args[0], args[1])

    register_udf("add2", add_mod)
    rb = pa.record_batch({"x": pa.array([1, 2]), "y": pa.array([10, None])})
    p = ProjectExec(_scan(rb), [HostUDF("add2", (col(0), col(1)), T.INT64)], ["z"])
    assert p.collect_pydict() == {"z": [11, None]}


def test_host_udtf():
    from auron_tpu.bridge.udf import register_udtf

    register_udtf(
        "ngrams",
        lambda s: [(s[i : i + 2], i) for i in range(len(s) - 1)] if s else [],
        T.Schema.of(T.Field("gram", T.STRING), T.Field("ofs", T.INT32)),
    )
    rb = pa.record_batch({"id": pa.array([1, 2, 3]),
                          "s": pa.array(["abc", "x", None])})
    g = GenerateExec(_scan(rb), "host_udtf", col(1), required_cols=[0], udtf="ngrams")
    out = g.collect_pydict()
    assert out == {"id": [1, 1], "gram": ["ab", "bc"], "ofs": [0, 1]}
    # outer mode emits a null row for non-generating inputs
    g2 = GenerateExec(_scan(rb), "host_udtf", col(1), required_cols=[0],
                      udtf="ngrams", outer=True)
    out2 = g2.collect_pydict()
    assert out2["id"] == [1, 1, 2, 3]
    assert out2["gram"] == ["ab", "bc", None, None]


# ---------------------------------------------------------------------------
# Hive UDF glue: C-ABI callback channel (auron_register_udf_callback)
# ---------------------------------------------------------------------------


def test_hive_udf_token_roundtrip_through_c_abi():
    """A __hive_udf__ expression (what HostPlanSerializer emits for
    HiveSimpleUDF/HiveGenericUDF) evaluates through the registered C
    callback: argument columns travel as Arrow IPC, the host returns one
    result column. The callback here is a ctypes CFUNCTYPE with the EXACT
    auron_udf_eval_fn signature — the same marshalling the JVM upcall
    (HiveUdfUpcall.java) goes through."""
    import base64
    import ctypes
    import io
    import json

    import numpy as np
    import pandas as pd
    import pyarrow as pa

    from auron_tpu.bridge import api, udf
    from auron_tpu.columnar import Batch
    from auron_tpu.convert.service import convert_host_plan_json

    state = {"calls": 0, "buf": None}  # buf pinned like the host contract

    @ctypes.CFUNCTYPE(
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)), ctypes.POINTER(ctypes.c_size_t),
    )
    def host_eval(blob_ptr, blob_len, args_ptr, args_len, out_ptr, out_len):
        # the "JVM": deserialize the plan-embedded function (here the blob
        # IS the tag) and evaluate hive_upper(a0) + tag
        tag = ctypes.string_at(blob_ptr, blob_len).decode()
        data = ctypes.string_at(args_ptr, args_len)
        with pa.ipc.open_stream(io.BytesIO(data)) as r:
            tbl = r.read_all()
        col = tbl.column(0).to_pylist()
        # padding rows reach callbacks (engine keeps the selection mask):
        # anything non-string maps to null, like a real UDF's null path
        result = pa.table({"r": pa.array(
            [f"{v.upper()}#{tag}" if isinstance(v, str) else None
             for v in col], pa.string())})
        sink = io.BytesIO()
        with pa.ipc.new_stream(sink, result.schema) as w:
            w.write_table(result)
        payload = sink.getvalue()
        state["buf"] = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        out_ptr[0] = ctypes.cast(state["buf"], ctypes.POINTER(ctypes.c_uint8))
        out_len[0] = len(payload)
        state["calls"] += 1
        return 0

    fn_ptr = ctypes.cast(host_eval, ctypes.c_void_p).value
    api.install_udf_callback(fn_ptr)
    try:
        host = json.dumps({
            "op": "ProjectExec",
            "schema": [["s", "string", True], ["u", "string", True]],
            "args": {"projections": [
                {"kind": "attr", "index": 0},
                {"kind": "call", "name": "__hive_udf__",
                 "udf_blob": base64.b64encode(b"7").decode(),
                 "type": "string",
                 "children": [{"kind": "attr", "index": 0}]},
            ]},
            "children": [{"op": "FlinkStreamInput",
                          "schema": [["s", "string", True]],
                          "args": {}, "children": []}],
        })
        resp = json.loads(convert_host_plan_json(host))
        assert resp["converted"] is True, resp.get("error")
        from auron_tpu.proto import plan_pb2 as pb

        rid = resp["root"]["inputs"][0]["resource_id"]
        node = pb.PhysicalPlanNode()
        node.ParseFromString(base64.b64decode(resp["root"]["plan_b64"]))

        df = pd.DataFrame({"s": ["ab", None, "cd", "efg"] * 25})
        api.put_resource(f"{rid}.0", [pa.RecordBatch.from_pandas(
            df, preserve_index=False)])
        try:
            h = api.call_native(pb.TaskDefinition(
                plan=node, partition_id=0).SerializeToString())
            frames = []
            while (rb := api.next_batch(h)) is not None:
                frames.append(rb.to_pandas())
            api.finalize_native(h)
        finally:
            api.remove_resource(f"{rid}.0")
        got = pd.concat(frames).reset_index(drop=True)
        want = df.assign(u=df.s.map(
            lambda v: f"{v.upper()}#7" if isinstance(v, str) else None))
        # the blob round-tripped verbatim through plan + callback
        assert state["calls"] >= 1
        pd.testing.assert_frame_equal(got, want, check_dtype=False)
        assert state["calls"] >= 1
    finally:
        udf._C_EVAL = None  # uninstall for test isolation

"""Randomized differential testing of the expression engine.

Generates random expression trees over typed columns with NULLs and checks
the engine against an independent numpy (values, mask) oracle implementing
SQL semantics — the fuzzing analog of the reference's forked-Spark
expression suites (SURVEY.md §4.3).
"""

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exprs import eval_exprs
from auron_tpu.exprs.ir import BinaryOp, Coalesce, Column, If, IsNull, Not, lit

N = 200


def _make_batch(rng):
    cols = {
        "a": (rng.integers(-1000, 1000, N).astype(np.int64), rng.random(N) < 0.15),
        "b": (rng.integers(-50, 50, N).astype(np.int64), rng.random(N) < 0.15),
        "x": (np.round(rng.normal(size=N) * 10, 3), rng.random(N) < 0.15),
        "p": (rng.random(N) < 0.5, rng.random(N) < 0.15),
    }
    arrays = {
        name: pa.array(v, mask=null) for name, (v, null) in cols.items()
    }
    batch = Batch.from_arrow(pa.record_batch(arrays))
    oracle = {
        i: (v.copy(), ~null) for i, (name, (v, null)) in enumerate(cols.items())
    }
    return batch, oracle


# ---------------------------------------------------------------------------
# oracle: (values, valid-mask) numpy interpreter with SQL semantics
# ---------------------------------------------------------------------------


def _o_eval(e, oracle):
    if isinstance(e, Column):
        return oracle[e.index]
    if hasattr(e, "value") and hasattr(e, "dtype"):  # Literal
        v = np.full(N, e.value if e.value is not None else 0)
        return v, np.full(N, e.value is not None)
    if isinstance(e, IsNull):
        v, m = _o_eval(e.child, oracle)
        return ~m, np.ones(N, bool)
    if isinstance(e, Not):
        v, m = _o_eval(e.child, oracle)
        return ~v.astype(bool), m
    if isinstance(e, Coalesce):
        vals = [_o_eval(a, oracle) for a in e.args]
        out_v, out_m = vals[0][0].copy(), vals[0][1].copy()
        for v, m in vals[1:]:
            take = ~out_m & m
            out_v = np.where(take, v, out_v)
            out_m = out_m | m
        return out_v, out_m
    if isinstance(e, If):
        cv, cm = _o_eval(e.cond, oracle)
        tv, tm = _o_eval(e.then, oracle)
        ev, em = _o_eval(e.orelse, oracle)
        fire = cm & cv.astype(bool)
        return np.where(fire, tv, ev), np.where(fire, tm, em)
    assert isinstance(e, BinaryOp)
    lv, lm = _o_eval(e.left, oracle)
    rv, rm = _o_eval(e.right, oracle)
    op = e.op
    if op == "and":
        known_false = (lm & ~lv.astype(bool)) | (rm & ~rv.astype(bool))
        return (
            np.where(known_false, False, lv.astype(bool) & rv.astype(bool)),
            (lm & rm) | known_false,
        )
    if op == "or":
        known_true = (lm & lv.astype(bool)) | (rm & rv.astype(bool))
        return (
            np.where(known_true, True, lv.astype(bool) | rv.astype(bool)),
            (lm & rm) | known_true,
        )
    both = lm & rm
    lf, rf = np.asarray(lv), np.asarray(rv)
    if lf.dtype != rf.dtype and (lf.dtype.kind == "f" or rf.dtype.kind == "f"):
        lf = lf.astype(np.float64)
        rf = rf.astype(np.float64)
    if op == "add":
        return lf + rf, both
    if op == "sub":
        return lf - rf, both
    if op == "mul":
        return lf * rf, both
    if op == "div":
        z = rf == 0
        safe = np.where(z, 1, rf)
        return lf.astype(np.float64) / safe, both & ~z
    if op in ("eq", "neq", "lt", "lteq", "gt", "gteq"):
        import operator as _op

        f = {"eq": _op.eq, "neq": _op.ne, "lt": _op.lt,
             "lteq": _op.le, "gt": _op.gt, "gteq": _op.ge}[op]
        return f(lf, rf), both
    raise ValueError(op)


# ---------------------------------------------------------------------------
# generator
# ---------------------------------------------------------------------------


def _gen_numeric(rng, depth):
    if depth == 0 or rng.random() < 0.3:
        choice = rng.random()
        if choice < 0.4:
            return Column(int(rng.integers(0, 2)))  # a or b (int)
        if choice < 0.7:
            return Column(2)  # x (float)
        return lit(int(rng.integers(-20, 20)))
    op = rng.choice(["add", "sub", "mul", "div"])
    return BinaryOp(str(op), _gen_numeric(rng, depth - 1), _gen_numeric(rng, depth - 1))


def _gen_bool(rng, depth):
    if depth == 0 or rng.random() < 0.3:
        if rng.random() < 0.5:
            return Column(3)
        return BinaryOp(
            str(rng.choice(["lt", "gteq", "eq", "neq"])),
            _gen_numeric(rng, 1), _gen_numeric(rng, 1),
        )
    r = rng.random()
    if r < 0.35:
        return BinaryOp("and", _gen_bool(rng, depth - 1), _gen_bool(rng, depth - 1))
    if r < 0.7:
        return BinaryOp("or", _gen_bool(rng, depth - 1), _gen_bool(rng, depth - 1))
    if r < 0.85:
        return Not(_gen_bool(rng, depth - 1))
    return IsNull(_gen_numeric(rng, 1))


@pytest.mark.parametrize("seed", range(20))
def test_fuzz_expressions(seed):
    rng = np.random.default_rng(seed)
    batch, oracle = _make_batch(rng)
    exprs = [
        _gen_numeric(rng, 3),
        _gen_bool(rng, 3),
        If(_gen_bool(rng, 2), _gen_numeric(rng, 2), _gen_numeric(rng, 2)),
        Coalesce((_gen_numeric(rng, 2), _gen_numeric(rng, 2), lit(0))),
    ]
    got = eval_exprs(batch, exprs)
    for e, cv in zip(exprs, got):
        want_v, want_m = _o_eval(e, oracle)
        gv = np.asarray(cv.values)[:N]
        gm = np.asarray(cv.validity)[:N]
        assert (gm == want_m).all(), f"validity mismatch for {e}"
        live = gm
        if gv.dtype.kind == "f" or np.asarray(want_v).dtype.kind == "f":
            a = gv[live].astype(np.float64)
            b = np.asarray(want_v)[live].astype(np.float64)
            ok = np.isclose(a, b, rtol=1e-12, atol=1e-12, equal_nan=True)
            # div-by-near-zero can produce inf on both sides differently
            ok |= np.isinf(a) & np.isinf(b) & (np.sign(a) == np.sign(b))
            assert ok.all(), f"value mismatch for {e}"
        else:
            assert (gv[live] == np.asarray(want_v)[live]).all(), f"value mismatch for {e}"

"""Extended scalar function tests."""

import datetime as dt
import hashlib

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exec.basic import MemoryScanExec, ProjectExec
from auron_tpu.exprs.ir import ScalarFunc, col, lit


def _run(data, exprs, names, schema=None):
    b = Batch.from_pydict(data, schema=schema)
    p = ProjectExec(MemoryScanExec.single([b]), exprs, names)
    return p.collect_pydict()


def test_bround_half_even():
    out = _run({"x": [2.5, 3.5, -2.5, 2.4]},
               [ScalarFunc("bround", (col(0),))], ["r"])
    assert out["r"] == [2.0, 4.0, -2.0, 2.0]


def test_timestamp_fields():
    ts = np.datetime64("2024-03-05T17:45:30.123456", "us")
    out = _run({"t": pa.array([ts])},
               [ScalarFunc("hour", (col(0),)), ScalarFunc("minute", (col(0),)),
                ScalarFunc("second", (col(0),))],
               ["h", "m", "s"])
    assert (out["h"], out["m"], out["s"]) == ([17], [45], [30])


def test_weekofyear_vs_python():
    dates = [dt.date(2024, 1, 1), dt.date(2023, 1, 1), dt.date(2020, 12, 31),
             dt.date(2021, 1, 4), dt.date(1999, 6, 15)]
    days = [(d - dt.date(1970, 1, 1)).days for d in dates]
    out = _run({"d": pa.array(days, type=pa.int32()).cast(pa.date32())},
               [ScalarFunc("weekofyear", (col(0),))], ["w"])
    assert out["w"] == [d.isocalendar()[1] for d in dates]


def test_months_between():
    d1 = (dt.date(2024, 3, 31) - dt.date(1970, 1, 1)).days
    d2 = (dt.date(2024, 1, 31) - dt.date(1970, 1, 1)).days
    d3 = (dt.date(2024, 2, 14) - dt.date(1970, 1, 1)).days
    arr = pa.array([d1, d1], type=pa.int32()).cast(pa.date32())
    arr2 = pa.array([d2, d3], type=pa.int32()).cast(pa.date32())
    out = _run({"a": arr, "b": arr2},
               [ScalarFunc("months_between", (col(0), col(1)))], ["mb"])
    assert out["mb"][0] == 2.0  # both last day of month -> integral
    assert out["mb"][1] == pytest.approx(1.0 + 17 / 31.0, abs=1e-8)


def test_string_crypto_and_json():
    out = _run({"s": ["hello world", None]},
               [ScalarFunc("md5", (col(0),)), ScalarFunc("sha256", (col(0),)),
                ScalarFunc("initcap", (col(0),))],
               ["m", "h", "i"])
    assert out["m"][0] == hashlib.md5(b"hello world").hexdigest()
    assert out["h"][0] == hashlib.sha256(b"hello world").hexdigest()
    assert out["i"] == ["Hello World", None]
    j = _run({"j": ['{"a": {"b": [1, 2]}}', '{"a": 1}', "bad"]},
             [ScalarFunc("get_json_object", (col(0), lit("$.a.b[1]")))], ["g"])
    assert j["g"] == ["2", None, None]


def test_replace_translate_concat():
    out = _run({"s": ["banana", "abc"]},
               [ScalarFunc("replace", (col(0), lit("an"), lit("AN")))], ["r"])
    assert out["r"] == ["bANANa", "abc"]
    out2 = _run({"s": ["abcd"]},
                [ScalarFunc("translate", (col(0), lit("abc"), lit("xy")))], ["t"])
    assert out2["t"] == ["xyd"]
    out3 = _run({"a": ["x", None], "b": ["y", "z"]},
                [ScalarFunc("concat", (col(0), col(1))),
                 ScalarFunc("concat_ws", (lit("-"), col(0), col(1)))],
                ["c", "cw"])
    assert out3["c"] == ["xy", None]
    assert out3["cw"] == ["x-y", "z"]


def test_split_and_array_ops():
    out = _run({"s": ["a,b,c", "x"]},
               [ScalarFunc("split", (col(0), lit(",")))], ["l"])
    assert out["l"] == [["a", "b", "c"], ["x"]]
    rb = pa.record_batch({"l": pa.array([[3, 1], [7]], type=pa.list_(pa.int64()))})
    b = Batch.from_arrow(rb)
    p = ProjectExec(MemoryScanExec.single([b]),
                    [ScalarFunc("array_reverse", (col(0),))], ["r"])
    assert p.collect_pydict()["r"] == [[1, 3], [7]]


def test_decimal_plumbing():
    import decimal as d

    data = {"x": pa.array([d.Decimal("12.34")], type=pa.decimal128(10, 2))}
    out = _run(data, [ScalarFunc("unscaled_value", (col(0),))], ["u"])
    assert out["u"] == [1234]
    out2 = _run({"n": pa.array([1234], type=pa.int64())},
                [ScalarFunc("make_decimal", (col(0), lit(10), lit(2)))], ["m"])
    assert out2["m"] == [d.Decimal("12.34")]


def test_date_arithmetic():
    base = dt.date(2024, 1, 31)
    days = (base - dt.date(1970, 1, 1)).days
    arr = pa.array([days, days], type=pa.int32()).cast(pa.date32())
    out = _run({"d": arr, "n": pa.array([1, 13], type=pa.int32())},
               [ScalarFunc("add_months", (col(0), col(1)))], ["am"])
    assert out["am"] == [dt.date(2024, 2, 29), dt.date(2025, 2, 28)]
    out2 = _run({"d": arr},
                [ScalarFunc("trunc_date", (col(0), lit("month"))),
                 ScalarFunc("trunc_date", (col(0), lit("year"))),
                 ScalarFunc("next_day", (col(0), lit("Mon")))],
                ["tm", "ty", "nd"])
    assert out2["tm"][0] == dt.date(2024, 1, 1)
    assert out2["ty"][0] == dt.date(2024, 1, 1)
    assert out2["nd"][0] == dt.date(2024, 2, 5)  # next Monday after Wed Jan 31


def test_least_greatest_skip_nulls():
    out = _run({"a": pa.array([1, None, 5], type=pa.int64()),
                "b": pa.array([3, 2, None], type=pa.int64())},
               [ScalarFunc("least", (col(0), col(1))),
                ScalarFunc("greatest", (col(0), col(1)))],
               ["l", "g"])
    assert out["l"] == [1, 2, 5]
    assert out["g"] == [3, 2, 5]


def test_unix_timestamp_roundtrip():
    ts = np.datetime64("2024-03-05T17:45:30", "us")
    out = _run({"t": pa.array([ts])},
               [ScalarFunc("unix_timestamp", (col(0),))], ["u"])
    import calendar
    want = calendar.timegm(dt.datetime(2024, 3, 5, 17, 45, 30).timetuple())
    assert out["u"] == [want]


def test_date_format():
    days = (dt.date(2024, 3, 5) - dt.date(1970, 1, 1)).days
    arr = pa.array([days], type=pa.int32()).cast(pa.date32())
    out = _run({"d": arr},
               [ScalarFunc("date_format", (col(0), lit("yyyy-MM-dd")))], ["f"])
    assert out["f"] == ["2024-03-05"]


def test_map_functions():
    mt = pa.map_(pa.string(), pa.int64())
    rb = pa.record_batch({
        "m": pa.array([[("a", 1), ("b", 2)], [("x", 9)], None], type=mt),
    })
    b = Batch.from_arrow(rb)
    p = ProjectExec(
        MemoryScanExec.single([b]),
        [ScalarFunc("map_keys", (col(0),)),
         ScalarFunc("map_values", (col(0),)),
         ScalarFunc("get_map_value", (col(0), lit("b"))),
         ScalarFunc("element_at", (col(0), lit("x")))],
        ["ks", "vs", "gb", "ex"],
    )
    out = p.collect_pydict()
    assert out["ks"] == [["a", "b"], ["x"], None]
    assert out["vs"] == [[1, 2], [9], None]
    assert out["gb"] == [2, None, None]
    assert out["ex"] == [None, 9, None]


def test_str_to_map_and_concat():
    out = _run({"s": ["a:1,b:2", "k:v"]},
               [ScalarFunc("str_to_map", (col(0),))], ["m"])
    assert out["m"] == [[("a", "1"), ("b", "2")], [("k", "v")]]
    mt = pa.map_(pa.string(), pa.int64())
    rb = pa.record_batch({
        "m1": pa.array([[("a", 1)]], type=mt),
        "m2": pa.array([[("a", 7), ("b", 2)]], type=mt),
    })
    b = Batch.from_arrow(rb)
    p = ProjectExec(MemoryScanExec.single([b]),
                    [ScalarFunc("map_concat", (col(0), col(1)))], ["mc"])
    assert p.collect_pydict()["mc"] == [[("a", 7), ("b", 2)]]


def test_element_at_list():
    rb = pa.record_batch({"l": pa.array([[10, 20, 30], [5]], type=pa.list_(pa.int64()))})
    b = Batch.from_arrow(rb)
    p = ProjectExec(MemoryScanExec.single([b]),
                    [ScalarFunc("element_at", (col(0), lit(2))),
                     ScalarFunc("element_at", (col(0), lit(-1))),
                     ScalarFunc("array_size", (col(0),))],
                    ["e2", "em1", "sz"])
    out = p.collect_pydict()
    assert out["e2"] == [20, None]
    assert out["em1"] == [30, 5]
    assert out["sz"] == [3, 1]


def test_struct_roundtrip_and_access():
    st = pa.struct([pa.field("a", pa.int64()), pa.field("s", pa.string())])
    rb = pa.record_batch({"r": pa.array([{"a": 1, "s": "x"}, {"a": 2, "s": None}, None],
                                        type=st)})
    b = Batch.from_arrow(rb)
    assert b.to_arrow().column("r").to_pylist() == [
        {"a": 1, "s": "x"}, {"a": 2, "s": None}, None]
    p = ProjectExec(MemoryScanExec.single([b]),
                    [ScalarFunc("get_struct_field", (col(0), lit("a"))),
                     ScalarFunc("get_struct_field", (col(0), lit("s")))],
                    ["a", "s"])
    out = p.collect_pydict()
    assert out["a"] == [1, 2, None]
    assert out["s"] == ["x", None, None]


def test_named_struct():
    out = _run({"x": [1, 2], "y": ["p", "q"]},
               [ScalarFunc("named_struct", (lit("n"), col(0), lit("t"), col(1)))],
               ["st"])
    assert out["st"] == [{"n": 1, "t": "p"}, {"n": 2, "t": "q"}]


def test_array_utilities():
    rb = pa.record_batch({"l": pa.array([[3, 1, 3, None], [7], []],
                                        type=pa.list_(pa.int64()))})
    b = Batch.from_arrow(rb)
    p = ProjectExec(
        MemoryScanExec.single([b]),
        [ScalarFunc("array_contains", (col(0), lit(3))),
         ScalarFunc("array_join", (col(0), lit(","))),
         ScalarFunc("array_distinct", (col(0),)),
         ScalarFunc("sort_array", (col(0),)),
         ScalarFunc("array_min", (col(0),)),
         ScalarFunc("array_max", (col(0),))],
        ["has3", "j", "d", "s", "mn", "mx"],
    )
    out = p.collect_pydict()
    assert out["has3"] == [True, False, False]
    assert out["j"] == ["3,1,3", "7", ""]
    assert out["d"] == [[3, 1, None], [7], []]
    assert out["s"] == [[None, 1, 3, 3], [7], []]  # Spark: nulls first asc
    assert out["mn"] == [1, 7, None]
    assert out["mx"] == [3, 7, None]


def test_least_greatest_strings_lexicographic():
    out = _run(
        {"a": ["zebra", "mango", None], "b": ["apple", "pear", "kiwi"]},
        [ScalarFunc("least", (col(0), col(1))),
         ScalarFunc("greatest", (col(0), col(1)))],
        ["l", "g"],
    )
    assert out["l"] == ["apple", "mango", "kiwi"]
    assert out["g"] == ["zebra", "pear", "kiwi"]


def test_least_greatest_nan_ordering():
    # Spark: NaN is greater than any non-NaN value
    out = _run(
        {"a": [1.0, float("nan"), float("nan")], "b": [float("nan"), 2.0, None]},
        [ScalarFunc("least", (col(0), col(1))),
         ScalarFunc("greatest", (col(0), col(1)))],
        ["l", "g"],
    )
    assert out["l"][0] == 1.0 and out["l"][1] == 2.0
    assert np.isnan(out["g"][0]) and np.isnan(out["g"][1])
    assert np.isnan(out["l"][2]) and np.isnan(out["g"][2])


def test_concat_ws_null_separator():
    out = _run(
        {"sep": [",", None], "x": ["a", "a"], "y": ["b", "b"]},
        [ScalarFunc("concat_ws", (col(0), col(1), col(2)))],
        ["r"],
    )
    assert out["r"] == ["a,b", None]


def test_sort_array_null_placement():
    arrs = pa.array([[3, None, 1, 2]], type=pa.list_(pa.int64()))
    lt = T.DataType(T.TypeKind.LIST, inner=(T.INT64,))
    out = _run({"a": arrs},
               [ScalarFunc("sort_array", (col(0),))], ["asc"],
               schema=T.Schema.of(T.Field("a", lt)))
    assert out["asc"] == [[None, 1, 2, 3]]
    out = _run({"a": arrs},
               [ScalarFunc("sort_array", (col(0), lit(False)))], ["dsc"],
               schema=T.Schema.of(T.Field("a", lt)))
    assert out["dsc"] == [[3, 2, 1, None]]


# ---------------------------------------------------------------------------
# long-tail wave (VERDICT r1 item 7): regexp, hex/base64, conv, hash fns
# ---------------------------------------------------------------------------


def test_rlike_and_regexp_extract():
    out = _run(
        {"s": ["foo123bar", "nope", None, "abc999"]},
        [ScalarFunc("rlike", (col(0), lit("[0-9]+"))),
         ScalarFunc("regexp_extract", (col(0), lit("([a-z]+)([0-9]+)"), lit(2))),
         ScalarFunc("regexp_extract", (col(0), lit("zzz(9+)"), lit(1)))],
        ["m", "g2", "none"],
    )
    assert out["m"] == [True, False, None, True]
    assert out["g2"] == ["123", "", None, "999"]
    assert out["none"] == ["", "", None, ""]  # pattern absent -> empty


def test_regexp_replace_java_dollar_groups():
    out = _run(
        {"s": ["a1b2", "xy", None]},
        [ScalarFunc("regexp_replace", (col(0), lit("([a-z])([0-9])"), lit("$2$1")))],
        ["r"],
    )
    assert out["r"] == ["1a2b", "xy", None]


def test_hex_unhex_roundtrip():
    out = _run(
        {"n": [255, 0, 16, None], "s": ["ABC", "", "ABC", "ABC"]},
        [ScalarFunc("hex", (col(0),)), ScalarFunc("hex", (col(1),)),
         ScalarFunc("unhex", (ScalarFunc("hex", (col(1),)),))],
        ["hn", "hs", "rt"],
    )
    assert out["hn"] == ["FF", "0", "10", None]  # Spark: uppercase, no pad
    assert out["hs"] == ["414243", "", "414243", "414243"]
    assert out["rt"] == [b"ABC", b"", b"ABC", b"ABC"]
    # odd-length input gets a leading zero (Spark semantics)
    out2 = _run({"s": ["F", "zz"]}, [ScalarFunc("unhex", (col(0),))], ["u"])
    assert out2["u"] == [b"\x0f", None]


def test_hex_negative_two_complement():
    out = _run({"n": [-1, -16]}, [ScalarFunc("hex", (col(0),))], ["h"])
    assert out["h"] == ["FFFFFFFFFFFFFFFF", "FFFFFFFFFFFFFFF0"]


def test_base64_unbase64():
    out = _run(
        {"s": ["hello", "", None]},
        [ScalarFunc("base64", (col(0),)),
         ScalarFunc("unbase64", (ScalarFunc("base64", (col(0),)),))],
        ["b", "rt"],
    )
    assert out["b"] == ["aGVsbG8=", "", None]
    assert out["rt"] == [b"hello", b"", None]


def test_conv_hive_semantics():
    out = _run(
        {"s": ["100", "-10", "1z", "zz", "", None]},
        [ScalarFunc("conv", (col(0), lit(2), lit(10))),
         ScalarFunc("conv", (col(0), lit(16), lit(2)))],
        ["b2d", "h2b"],
    )
    # '100' base2 = 4; '-10' base2 = -2 -> unsigned 2^64-2
    assert out["b2d"][0] == "4"
    assert out["b2d"][1] == "18446744073709551614"
    assert out["b2d"][2] == "1"    # leading valid digit only
    assert out["b2d"][3] == "0"    # no valid digits but non-empty
    assert out["b2d"][4] is None   # empty -> NULL
    assert out["b2d"][5] is None
    assert out["h2b"][0] == "100000000"  # 0x100 = 256
    # negative to_base: signed output
    out2 = _run({"s": ["-15"]},
                [ScalarFunc("conv", (col(0), lit(10), lit(-16)))], ["r"])
    assert out2["r"] == ["-F"]


def test_hash_functions_spark_exact():
    # the same Spark-generated vectors tests/test_hashing.py verifies the
    # kernels against (Murmur3Hash / XxHash64, seed 42)
    out = _run({"n": pa.array([1, 2, 3, 4], type=pa.int32())},
               [ScalarFunc("hash", (col(0),))], ["h"])
    assert out["h"] == [-559580957, 1765031574, -1823081949, -397064898]
    out2 = _run({"n": pa.array([1, 0, -1], type=pa.int64()),
                 "s": ["hello", "bar", ""]},
                [ScalarFunc("xxhash64", (col(0),)),
                 ScalarFunc("xxhash64", (col(1),))],
                ["x", "xs"])
    assert out2["x"] == [-7001672635703045582, -5252525462095825812,
                         3858142552250413010]
    assert out2["xs"] == [-4367754540140381902, -1798770879548125814,
                          -7444071767201028348]


def test_parse_json_and_get_parsed():
    out = _run(
        {"j": ['{"a":  1, "b": {"c": "x"}}', "not json"]},
        [ScalarFunc("parse_json", (col(0),)),
         ScalarFunc("get_parsed_json_object",
                    (ScalarFunc("parse_json", (col(0),)), lit("$.b.c")))],
        ["p", "g"],
    )
    assert out["p"] == ['{"a":1,"b":{"c":"x"}}', None]
    assert out["g"] == ["x", None]


def test_map_from_entries():
    entries = pa.array([[(1, "a"), (2, "b")], []],
                       type=pa.list_(pa.struct([("key", pa.int64()),
                                                ("value", pa.string())])))
    lt = T.DataType(T.TypeKind.LIST,
                    inner=(T.DataType(T.TypeKind.STRUCT,
                                      inner=(T.INT64, T.STRING),
                                      struct_names=("key", "value")),))
    out = _run({"e": entries},
               [ScalarFunc("map_from_entries", (col(0),))], ["m"],
               schema=T.Schema.of(T.Field("e", lt)))
    assert out["m"] == [[(1, "a"), (2, "b")], []]


def test_regexp_replace_dollar_zero_and_escapes():
    out = _run(
        {"s": ["ab12"]},
        [ScalarFunc("regexp_replace", (col(0), lit("[0-9]+"), lit("<$0>"))),
         ScalarFunc("regexp_replace", (col(0), lit("[0-9]+"), lit(r"\$1")))],
        ["whole", "lit_dollar"],
    )
    assert out["whole"] == ["ab<12>"]      # $0 = whole match, not octal NUL
    assert out["lit_dollar"] == ["ab$1"]   # java \$ escapes the dollar


def test_conv_overflow_clamps_to_unsigned_max():
    out = _run({"s": ["10000000000000000FF"]},
               [ScalarFunc("conv", (col(0), lit(16), lit(10)))], ["r"])
    assert out["r"] == ["18446744073709551615"]  # Hive clamp, no wraparound


def test_conv_negative_to_base_signed_view():
    out = _run({"s": ["18446744073709551615", "9223372036854775808"]},
               [ScalarFunc("conv", (col(0), lit(10), lit(-10)))], ["r"])
    assert out["r"] == ["-1", "-9223372036854775808"]  # signed 64-bit view


def test_regexp_replace_longest_valid_group():
    out = _run({"s": ["ab"]},
               [ScalarFunc("regexp_replace", (col(0), lit("(a)"), lit("$12")))],
               ["r"])
    assert out["r"] == ["a2b"]  # java: group 1 + literal '2'

"""Real-metadata Hudi COW resolution: table dir -> descriptor -> native scan.

The table on disk is built to the PUBLIC Hudi COW layout (.hoodie commit
timeline JSON + hoodie.properties + hive-partitioned parquet base files)
— the test_iceberg.py analog demanded by VERDICT r4 #9. The resolver
must walk completed instants in order, keep only the LATEST file slice
per file group, honor replacecommits, read the schema from commit
metadata, and map hive partition paths to partition values the existing
provider prunes on.
"""

import json
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from auron_tpu.convert.hudi import resolve_hudi_scan

SCHEMA_AVRO = {
    "type": "record", "name": "rec",
    "fields": [
        {"name": "_hoodie_commit_time", "type": ["null", "string"]},
        {"name": "id", "type": "long"},
        {"name": "amount", "type": ["null", "double"]},
        {"name": "year", "type": ["null", "long"]},
    ],
}


def _write_parquet(root, rel, df):
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), path)
    return path


def _commit(root, ts, stats_by_partition, kind="commit", replace=None):
    hoodie = os.path.join(root, ".hoodie")
    os.makedirs(hoodie, exist_ok=True)
    body = {
        "partitionToWriteStats": stats_by_partition,
        "extraMetadata": {"schema": json.dumps(SCHEMA_AVRO)},
    }
    if replace:
        body["partitionToReplaceFileIds"] = replace
    with open(os.path.join(hoodie, f"{ts}.{kind}"), "w") as f:
        json.dump(body, f)


def _build_table(root):
    """Two hive partitions; file group f1 written at t1 then UPDATED at t3
    (the t3 slice must win); f2 written at t1; f3 written at t2 then
    dropped by a t4 replacecommit; an inflight t5 is invisible."""
    frames = {}
    os.makedirs(os.path.join(root, ".hoodie"), exist_ok=True)
    with open(os.path.join(root, ".hoodie", "hoodie.properties"), "w") as f:
        f.write("hoodie.table.name=t\n")
        f.write("hoodie.table.type=COPY_ON_WRITE\n")
        f.write("hoodie.table.partitionfields=year\n")

    rng = np.random.default_rng(5)

    def mk(year, n, seed):
        return pd.DataFrame({
            "id": np.arange(n, dtype=np.int64) + seed,
            "amount": np.round(rng.random(n) * 100, 2),
            "year": np.full(n, year, dtype=np.int64),
        })

    old_f1 = mk(2023, 300, 0)
    frames["f1"] = mk(2023, 400, 1000)  # the t3 update (replaces old_f1)
    frames["f2"] = mk(2024, 500, 2000)
    f3 = mk(2024, 100, 3000)

    _write_parquet(root, "year=2023/f1_0-0-0_t1.parquet", old_f1)
    _write_parquet(root, "year=2024/f2_0-0-0_t1.parquet", frames["f2"])
    _commit(root, "t1", {
        "year=2023": [{"fileId": "f1", "path": "year=2023/f1_0-0-0_t1.parquet",
                       "numWrites": 300}],
        "year=2024": [{"fileId": "f2", "path": "year=2024/f2_0-0-0_t1.parquet",
                       "numWrites": 500}],
    })
    _write_parquet(root, "year=2024/f3_0-0-0_t2.parquet", f3)
    _commit(root, "t2", {
        "year=2024": [{"fileId": "f3", "path": "year=2024/f3_0-0-0_t2.parquet",
                       "numWrites": 100}],
    })
    _write_parquet(root, "year=2023/f1_0-0-0_t3.parquet", frames["f1"])
    _commit(root, "t3", {
        "year=2023": [{"fileId": "f1", "path": "year=2023/f1_0-0-0_t3.parquet",
                       "numWrites": 400}],
    })
    _commit(root, "t4", {}, kind="replacecommit",
            replace={"year=2024": ["f3"]})
    # inflight instant: a writer crashed mid-commit; must be invisible
    with open(os.path.join(root, ".hoodie", "t5.commit.inflight"), "w") as f:
        f.write("{}")
    return frames


def test_resolve_latest_slices(tmp_path):
    frames = _build_table(str(tmp_path))
    desc = resolve_hudi_scan(str(tmp_path))
    assert desc["op"] == "HudiScanExec"
    # writer meta columns stripped
    assert [s[0] for s in desc["schema"]] == ["id", "amount", "year"]
    files = {os.path.basename(f["path"]): f for f in desc["args"]["files"]}
    # f1's t3 slice won, f2 survives, f3 was replaced away
    assert set(files) == {"f1_0-0-0_t3.parquet", "f2_0-0-0_t1.parquet"}
    assert files["f1_0-0-0_t3.parquet"]["partition"] == {"year": "2023"}
    assert files["f1_0-0-0_t3.parquet"]["record_count"] == 400


def test_descriptor_to_native_scan(tmp_path):
    frames = _build_table(str(tmp_path))
    desc = resolve_hudi_scan(str(tmp_path))

    import base64

    from auron_tpu.bridge import api
    from auron_tpu.convert.service import convert_host_plan_json
    from auron_tpu.proto import plan_pb2 as pb

    host = dict(desc)
    host["children"] = []
    resp = json.loads(convert_host_plan_json(json.dumps(host)))
    assert resp["converted"] is True, resp.get("error")
    node = pb.PhysicalPlanNode()
    node.ParseFromString(base64.b64decode(resp["root"]["plan_b64"]))
    h = api.call_native(pb.TaskDefinition(plan=node).SerializeToString())
    got = []
    while (rb := api.next_batch(h)) is not None:
        got.append(rb.to_pandas())
    api.finalize_native(h)
    out = pd.concat(got).reset_index(drop=True)
    want = pd.concat([frames["f1"], frames["f2"]]).reset_index(drop=True)
    assert len(out) == len(want)
    assert out["amount"].sum() == pytest.approx(want["amount"].sum())


def test_mor_table_rejected(tmp_path):
    os.makedirs(os.path.join(str(tmp_path), ".hoodie"))
    with open(os.path.join(str(tmp_path), ".hoodie", "hoodie.properties"), "w") as f:
        f.write("hoodie.table.type=MERGE_ON_READ\n")
    with pytest.raises(ValueError, match="COW only"):
        resolve_hudi_scan(str(tmp_path))


def test_no_commits_is_loud(tmp_path):
    os.makedirs(os.path.join(str(tmp_path), ".hoodie"))
    with open(os.path.join(str(tmp_path), ".hoodie", "hoodie.properties"), "w") as f:
        f.write("hoodie.table.type=COPY_ON_WRITE\n")
    with pytest.raises(ValueError, match="no completed commit"):
        resolve_hudi_scan(str(tmp_path))

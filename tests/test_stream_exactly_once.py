"""Exactly-once crash-resume for continuous streaming queries.

The core proof: a pipeline killed at ANY instrumented seam (mid-batch,
mid-window-fold, mid-emission, mid-barrier) and resumed from its newest
committed checkpoint produces emission-for-emission bit-identical
output vs the never-killed run — fuzzed over every fault point × many
occurrence indices (> 20 kill points per query shape).
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from auron_tpu import types as T
from auron_tpu.exec.streaming import JsonRowDeserializer, MockKafkaSource
from auron_tpu.stream import (
    CollectSink,
    JsonlFileSink,
    StreamKilled,
    StreamPipeline,
    lower_streaming_view,
)
from auron_tpu.stream.pipeline import FAULT_POINTS
from auron_tpu.utils.config import (
    STREAM_CHECKPOINT_INTERVAL,
    STREAM_POLL_MAX_RECORDS,
    active_conf,
)

SCHEMA = T.Schema.of(T.Field("k", T.STRING), T.Field("v", T.FLOAT64),
                     T.Field("ts", T.INT64))

TUMBLE_VIEW = """
CREATE STREAMING VIEW orders_1s
  WATERMARK FOR ts AS ts - INTERVAL '2' SECOND
AS SELECT k, window_start, window_end, SUM(v) AS total, COUNT(*) AS n,
          AVG(v) AS mean, MIN(v) AS lo, MAX(v) AS hi
   FROM orders
   WHERE v >= 0
   GROUP BY k, TUMBLE(ts, INTERVAL '1' SECOND)
"""

HOP_VIEW = """
CREATE STREAMING VIEW orders_hop
  WATERMARK FOR ts AS ts - INTERVAL '1' SECOND
AS SELECT k, window_start, SUM(v) AS total, COUNT(*) AS n
   FROM orders
   GROUP BY k, HOP(ts, INTERVAL '1' SECOND, INTERVAL '3' SECOND)
"""


def _records(n=1200, seed=5, null_every=0):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        row = {"k": "kab"[int(rng.integers(0, 3))],
               "v": round(float(rng.random()) * 10 - 0.5, 3),
               "ts": int(i * 11 + int(rng.integers(0, 5)))}
        if null_every and i % null_every == 0:
            row["ts"] = None
        recs.append(json.dumps(row).encode())
    return [recs[: n // 2], recs[n // 2:]]


def _conf(poll=64, interval=2):
    c = active_conf().copy()
    c.set(STREAM_POLL_MAX_RECORDS, poll)
    c.set(STREAM_CHECKPOINT_INTERVAL, interval)
    return c


def _factory(parts):
    return lambda mode, offsets: MockKafkaSource(
        parts, startup_mode=mode, start_offsets=offsets)


def _baseline(view, parts, tmp_path, sub="base", **kw):
    plan = lower_streaming_view(view, SCHEMA)
    sink = CollectSink()
    p = StreamPipeline(plan, _factory(parts)("earliest", {}),
                       JsonRowDeserializer(SCHEMA), sink,
                       conf=_conf(**kw), checkpoint_dir=str(tmp_path / sub))
    p.run(drain=True)
    p.close()
    return plan, [e.to_json() for e in sink.emissions]


class _KillAt:
    """Raise StreamKilled at the n-th occurrence of one fault point."""

    def __init__(self, point, n):
        self.point, self.n, self.count = point, n, 0
        self.fired = False

    def __call__(self, pt):
        if pt == self.point:
            self.count += 1
            if self.count == self.n:
                self.fired = True
                raise StreamKilled(f"{pt}#{self.n}")


def _run_killed_then_resume(plan, parts, sink, ckdir, kill, conf):
    """One crash-resume cycle: run until the injected kill (or clean
    end), then resume from the checkpoint dir and run to completion."""
    factory = _factory(parts)
    p = StreamPipeline(plan, factory("earliest", {}),
                       JsonRowDeserializer(SCHEMA), sink, conf=conf,
                       checkpoint_dir=ckdir, fault=kill)
    try:
        p.run(drain=True)
        killed = False
    except StreamKilled:
        killed = True
    if killed:
        p2 = StreamPipeline.restore(plan, factory, JsonRowDeserializer(SCHEMA),
                                    sink, ckdir, conf=conf)
        p2.run(drain=True)
        p2.close()
    else:
        p.close()
    return killed


@pytest.mark.parametrize("point", FAULT_POINTS)
@pytest.mark.parametrize("occurrence", [1, 2, 3])
def test_kill_resume_bit_identical_tumble(tmp_path, point, occurrence):
    """9 fault points x 3 occurrence indexes = 27 kill points; each
    killed+resumed run must match the baseline emission-for-emission."""
    parts = _records()
    plan, want = _baseline(TUMBLE_VIEW, parts, tmp_path)
    sink = CollectSink()
    kill = _KillAt(point, occurrence)
    killed = _run_killed_then_resume(
        plan, parts, sink, str(tmp_path / f"{point}-{occurrence}"),
        kill, _conf())
    got = [e.to_json() for e in sink.emissions]
    assert got == want, (
        f"kill at {point}#{occurrence} (fired={kill.fired}, "
        f"killed={killed}) diverged from the baseline")


def test_kill_points_actually_fire(tmp_path):
    """Vacuity guard: every fault point is reachable in the fuzz shape
    (a point that never fires proves nothing)."""
    parts = _records()
    plan, _ = _baseline(TUMBLE_VIEW, parts, tmp_path)
    for point in FAULT_POINTS:
        kill = _KillAt(point, 1)
        _run_killed_then_resume(
            plan, parts, CollectSink(), str(tmp_path / f"v-{point}"),
            kill, _conf())
        assert kill.fired, f"fault point {point} never fired"


@pytest.mark.parametrize("occurrence", [1, 2, 4, 6])
def test_kill_resume_hop_windows(tmp_path, occurrence):
    """Sliding windows: rows live in 3 overlapping windows; the fold /
    emission / checkpoint cycle must still replay bit-identically."""
    parts = _records(seed=9)
    plan, want = _baseline(HOP_VIEW, parts, tmp_path)
    sink = CollectSink()
    _run_killed_then_resume(
        plan, parts, sink, str(tmp_path / f"hop-{occurrence}"),
        _KillAt("post-fold", occurrence), _conf())
    assert [e.to_json() for e in sink.emissions] == want


def test_double_kill_resume(tmp_path):
    """Two crashes in one logical stream (kill, resume, kill again,
    resume again) still converge to the baseline."""
    parts = _records()
    plan, want = _baseline(TUMBLE_VIEW, parts, tmp_path)
    conf = _conf()
    factory = _factory(parts)
    sink = CollectSink()
    ckdir = str(tmp_path / "double")
    p = StreamPipeline(plan, factory("earliest", {}),
                       JsonRowDeserializer(SCHEMA), sink, conf=conf,
                       checkpoint_dir=ckdir, fault=_KillAt("post-emit", 1))
    with pytest.raises(StreamKilled):
        p.run(drain=True)
    p2 = StreamPipeline.restore(plan, factory, JsonRowDeserializer(SCHEMA),
                                sink, ckdir, conf=conf,
                                fault=_KillAt("mid-barrier", 1))
    with pytest.raises(StreamKilled):
        p2.run(drain=True)
    p3 = StreamPipeline.restore(plan, factory, JsonRowDeserializer(SCHEMA),
                                sink, ckdir, conf=conf)
    p3.run(drain=True)
    p3.close()
    assert [e.to_json() for e in sink.emissions] == want


def test_mock_source_offset_resume_regression(tmp_path):
    """The aborted-stream offset-resume regression: a killed pipeline's
    checkpointed offsets seek the replacement MockKafkaSource to the
    exact record positions — no record is lost or re-folded."""
    parts = _records(n=400)
    plan = lower_streaming_view(TUMBLE_VIEW, SCHEMA)
    conf = _conf(poll=32, interval=1)
    sink = CollectSink()
    ckdir = str(tmp_path / "offsets")
    p = StreamPipeline(plan, _factory(parts)("earliest", {}),
                       JsonRowDeserializer(SCHEMA), sink, conf=conf,
                       checkpoint_dir=ckdir, fault=_KillAt("poll", 5))
    with pytest.raises(StreamKilled):
        p.run(drain=True)
    ckpt_offsets = p.source.offsets()
    p2 = StreamPipeline.restore(plan, _factory(parts),
                                JsonRowDeserializer(SCHEMA), sink, ckdir,
                                conf=conf)
    # the resumed source starts at the checkpointed positions, which at
    # a poll-boundary kill equal the crashed source's positions
    assert p2.source.offsets() == ckpt_offsets
    before = p2.metrics["events_in"]
    p2.run(drain=True)
    p2.close()
    total = sum(len(part) for part in parts)
    consumed_after_resume = p2.metrics["events_in"] - before
    already = sum(ckpt_offsets.values())
    assert consumed_after_resume == total - already


def test_restore_refuses_poll_size_drift(tmp_path):
    """stream.poll.max.records is part of the checkpoint manifest:
    changing it shifts micro-batch boundaries, so restore refuses."""
    parts = _records(n=300)
    plan = lower_streaming_view(TUMBLE_VIEW, SCHEMA)
    ckdir = str(tmp_path / "drift")
    p = StreamPipeline(plan, _factory(parts)("earliest", {}),
                       JsonRowDeserializer(SCHEMA), CollectSink(),
                       conf=_conf(poll=32, interval=1), checkpoint_dir=ckdir)
    p.run(max_steps=3)
    p.close()
    with pytest.raises(ValueError, match="poll.max.records"):
        StreamPipeline.restore(plan, _factory(parts),
                               JsonRowDeserializer(SCHEMA), CollectSink(),
                               ckdir, conf=_conf(poll=16, interval=1))


def test_checkpoint_partial_write_invisible(tmp_path):
    """A kill mid-write (temp file exists, replace never ran) must be
    invisible to latest() — resume sees the previous barrier."""
    from auron_tpu.stream.checkpoint import CheckpointCoordinator, snapshot_tmp

    coord = CheckpointCoordinator(str(tmp_path / "ck"), keep=3)
    coord.write(0, {"meta": b"a"})
    # simulate the crashed attempt: bytes in the temp path only
    with open(snapshot_tmp(coord.path_of(1)), "wb") as f:
        f.write(b"garbage-partial")
    seq, sections = coord.latest()
    assert seq == 0 and sections == {"meta": b"a"}


def test_checkpoint_prune_keeps_newest(tmp_path):
    from auron_tpu.stream.checkpoint import CheckpointCoordinator

    coord = CheckpointCoordinator(str(tmp_path / "ck"), keep=2)
    for i in range(5):
        coord.write(i, {"meta": str(i).encode()})
    seqs = [s for s, _ in coord._committed()]
    assert seqs == [3, 4]
    assert coord.latest()[0] == 4


def test_jsonl_sink_truncate_atomic(tmp_path):
    """The durable sink's truncate drops exactly the uncommitted
    suffix and survives being applied twice."""
    path = str(tmp_path / "out.jsonl")
    sink = JsonlFileSink(path)
    from auron_tpu.stream.sink import Emission
    for i in range(5):
        sink.emit(Emission(i, i * 1000, (i + 1) * 1000, ("n",), ((i,),)))
    sink.truncate(3)
    sink.truncate(3)
    with open(path) as f:
        seqs = [json.loads(ln)["seq"] for ln in f]
    assert seqs == [0, 1, 2]


def test_null_event_time_rows_dropped(tmp_path):
    """NULL event time has no window: dropped, counted, and the drop is
    stable across kill/resume."""
    parts = _records(n=600, null_every=7)
    plan, want = _baseline(TUMBLE_VIEW, parts, tmp_path, sub="nullbase")
    sink = CollectSink()
    _run_killed_then_resume(
        plan, parts, sink, str(tmp_path / "nullkill"),
        _KillAt("post-fold", 2), _conf())
    assert [e.to_json() for e in sink.emissions] == want


def test_emission_order_deterministic(tmp_path):
    """Windows emit ascending; rows within a window sort by key — the
    property the bit-identity replay rests on."""
    parts = _records()
    _, want = _baseline(TUMBLE_VIEW, parts, tmp_path, sub="order")
    docs = [json.loads(e) for e in want]
    starts = [d["window_start"] for d in docs]
    assert starts == sorted(starts)
    for d in docs:
        ks = [r[0] for r in d["rows"]]
        assert ks == sorted(ks)
    assert [d["seq"] for d in docs] == list(range(len(docs)))

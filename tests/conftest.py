"""Test harness: force the CPU backend with 8 virtual devices.

Multi-chip behavior (shuffle exchange over a Mesh, sharded aggregation) is
tested on a virtual 8-device CPU mesh — mirroring how the reference tests
"multi-node" behavior on a single JVM with local task scheduling
(reference: BaseAuronSQLSuite.scala:38-50). Real-TPU runs happen in
bench.py / __graft_entry__.py, not in unit tests.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from auron_tpu.jaxenv import force_cpu_backend  # noqa: E402

force_cpu_backend(8)

import auron_tpu  # noqa: F401,E402  (enables x64)


import pytest  # noqa: E402


@pytest.fixture()
def enable_row_metrics(monkeypatch):
    """Turn on per-operator output_rows metrics (conf-gated, default off)."""
    from auron_tpu.utils.config import METRICS_ROW_COUNTS

    env_key = "AURON_TPU_" + METRICS_ROW_COUNTS.key.upper().replace(".", "_")
    monkeypatch.setenv(env_key, "true")


@pytest.fixture(scope="module")
def leak_canary():
    """Tier-1 leak canary (R11's dynamic twin): a suite that drives whole
    queries must leave the process registries as it found them —
    ``api._runtimes`` (a failing request leaked one per query before
    PR 12), the global resource map, and the obs ring registry (a ring
    owned by a suite-spawned thread that never exited = a stuck waiter).
    Autoused by the serving and sqlgate suites; teardown asserts the
    baselines restored."""
    import threading
    import time

    from auron_tpu.bridge import api
    from auron_tpu.obs import core as obs_core

    with api._lock:
        base_rt = set(api._runtimes)
        base_res = set(api._resources)
    base_threads = {t.ident for t in threading.enumerate()}

    yield

    with api._lock:
        leaked_rt = {h: type(rt).__name__ for h, rt in api._runtimes.items()
                     if h not in base_rt}
        leaked_res = sorted(set(api._resources) - base_res)
    assert not leaked_rt, (
        f"suite leaked task runtimes {leaked_rt} — every call_native "
        "needs its finalize_native on every path (R11)")
    assert not leaked_res, (
        f"suite leaked resource-map entries {leaked_res} — every "
        "put_resource needs its remove_resource")

    # obs rings: suite-spawned threads must have exited (their rings go
    # dead and prune); a STILL-LIVE post-baseline thread owning a ring is
    # the stuck-waiter shape. Short grace: handler/pump threads may be
    # mid-exit when the last test returns.
    deadline = time.monotonic() + 5.0
    while True:
        live_now = {t.ident for t in threading.enumerate()}
        with obs_core._reg_lock:
            stuck = [r.tname for r in obs_core._rings
                     if r.ident in live_now and r.ident not in base_threads]
        if not stuck or time.monotonic() > deadline:
            break
        time.sleep(0.05)
    assert not stuck, (
        f"suite-spawned threads still alive with obs rings: {stuck} — "
        "a waiter was never released (R11 inflight-event shape)")
    # and the registry prunes dead rings once retention lapses — the
    # eviction path the /trace endpoint's memory bound rests on
    with obs_core._reg_lock:
        obs_core._prune_locked(
            time.perf_counter_ns() + obs_core._RETENTION_NS)
        live_now = {t.ident for t in threading.enumerate()}
        undead = [r.tname for r in obs_core._rings
                  if r.ident not in live_now]
    assert not undead, f"dead-thread rings survived a forced prune: {undead}"

"""Test harness: force the CPU backend with 8 virtual devices.

Multi-chip behavior (shuffle exchange over a Mesh, sharded aggregation) is
tested on a virtual 8-device CPU mesh — mirroring how the reference tests
"multi-node" behavior on a single JVM with local task scheduling
(reference: BaseAuronSQLSuite.scala:38-50). Real-TPU runs happen in
bench.py / __graft_entry__.py, not in unit tests.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from auron_tpu.jaxenv import force_cpu_backend  # noqa: E402

force_cpu_backend(8)

import auron_tpu  # noqa: F401,E402  (enables x64)


import pytest  # noqa: E402


@pytest.fixture()
def enable_row_metrics(monkeypatch):
    """Turn on per-operator output_rows metrics (conf-gated, default off)."""
    from auron_tpu.utils.config import METRICS_ROW_COUNTS

    env_key = "AURON_TPU_" + METRICS_ROW_COUNTS.key.upper().replace(".", "_")
    monkeypatch.setenv(env_key, "true")

"""Planned mesh exchange: proto-built two-stage queries over an 8-device mesh.

VERDICT r1 item 2: the ICI exchange must be reachable from the plan IR.
These tests build q3-class plans (partial agg -> mesh_exchange -> final agg)
through the protobuf builders, run them with MeshQueryDriver, and check

- mesh and file transports produce identical results (bit-for-bit on
  integer sums/counts — routing and grouping are spark-exact in both);
- results match a pandas oracle;
- the auto transport rule switches on the statistics/conf;
- dict-encoded (string) keys route correctly (murmur3 over bytes, not codes);
- full skew (every row to one reducer) sizes slots without overflow.

Reference analog: NativeShuffleExchangeBase.scala:187-296 + shuffle/mod.rs.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exprs.ir import col
from auron_tpu.parallel.mesh import make_mesh
from auron_tpu.parallel.mesh_driver import MeshQueryDriver
from auron_tpu.plan import builders as B
from auron_tpu.utils.config import (
    EXCHANGE_MESH_MAX_BYTES,
    EXCHANGE_MODE,
    Configuration,
)

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N_DEV)


def _fact(n=4000, seed=0, str_keys=False, skew=False):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame(
        {
            "k": np.zeros(n, np.int64) if skew else rng.integers(0, 97, n),
            "g2": rng.integers(0, 7, n).astype(np.int64),
            "v": rng.integers(-1000, 1000, n).astype(np.int64),
        }
    )
    if str_keys:
        df["k"] = df["k"].map(lambda x: f"key_{x}")
    return df


def _partitioned(df: pd.DataFrame, n_parts: int) -> list[list[Batch]]:
    per = (len(df) + n_parts - 1) // n_parts
    return [
        [
            Batch.from_arrow(
                pa.RecordBatch.from_pandas(
                    df.iloc[p * per : (p + 1) * per], preserve_index=False
                )
            )
        ]
        for p in range(n_parts)
    ]


def _two_stage_plan(schema: T.Schema, res_id: str):
    """SELECT k, g2, sum(v) s FROM fact GROUP BY k, g2 with a planned
    exchange between partial and final aggregation."""
    scan = B.memory_scan(schema, res_id)
    partial = B.hash_agg(
        scan, [(col(0), "k"), (col(1), "g2")], [("sum", col(2), "s")], "partial"
    )
    ex = B.mesh_exchange(
        partial, B.hash_partitioning([col(0), col(1)], N_DEV), "ex0"
    )
    return B.hash_agg(
        ex, [(col(0), "k"), (col(1), "g2")], [("sum", col(2), "s")], "final"
    )


def _oracle(df: pd.DataFrame) -> pd.DataFrame:
    return (
        df.groupby(["k", "g2"]).agg(s=("v", "sum")).reset_index()
        .sort_values(["k", "g2"]).reset_index(drop=True)
    )


def _run(mesh, df, mode: str, **conf_extra):
    schema = T.Schema.from_arrow(
        pa.RecordBatch.from_pandas(df.iloc[:1], preserve_index=False).schema
    )
    conf = Configuration().set(EXCHANGE_MODE, mode)
    for k, v in conf_extra.items():
        conf.set(k, v)
    driver = MeshQueryDriver(mesh, conf=conf)
    resources = {"fact": _partitioned(df, N_DEV)}
    out = driver.collect(_two_stage_plan(schema, "fact"), resources)
    out = out.sort_values(["k", "g2"]).reset_index(drop=True)
    return out, driver


def test_mesh_matches_file_bit_for_bit(mesh):
    df = _fact()
    got_mesh, d_mesh = _run(mesh, df, "mesh")
    got_file, d_file = _run(mesh, df, "file")
    assert d_mesh.stats[0].mode == "mesh"
    assert d_file.stats[0].mode == "file"
    pd.testing.assert_frame_equal(got_mesh, got_file)  # int sums: exact
    pd.testing.assert_frame_equal(
        got_mesh.astype({"k": np.int64, "g2": np.int64, "s": np.int64}),
        _oracle(df).astype({"k": np.int64, "g2": np.int64, "s": np.int64}),
    )
    # routing statistics recorded for AQE
    assert d_mesh.stats[0].rows.sum() > 0
    assert d_mesh.stats[0].rows.shape == (N_DEV, N_DEV)


def test_string_keys_route_by_bytes(mesh):
    df = _fact(n=2000, seed=3, str_keys=True)
    got_mesh, _ = _run(mesh, df, "mesh")
    got_file, _ = _run(mesh, df, "file")
    pd.testing.assert_frame_equal(got_mesh, got_file)
    want = _oracle(df)
    assert got_mesh["k"].tolist() == want["k"].tolist()
    assert got_mesh["s"].astype(np.int64).tolist() == want["s"].astype(np.int64).tolist()


def test_skewed_exchange_no_overflow(mesh):
    # single-key grouping with one hot key: every partial-agg row lands on
    # the same reducer, exercising the slot-capacity sizing under full skew.
    # NO partial aggregation benefit here — partial yields 1 group per shard,
    # so the exchange itself is tiny; route the RAW rows instead to stress it.
    df = _fact(n=3000, seed=5, skew=True)
    schema = T.Schema.from_arrow(
        pa.RecordBatch.from_pandas(df.iloc[:1], preserve_index=False).schema
    )
    scan = B.memory_scan(schema, "fact")
    ex = B.mesh_exchange(scan, B.hash_partitioning([col(0)], N_DEV), "ex_skew")
    final = B.hash_agg(
        ex, [(col(0), "k")], [("sum", col(2), "s"), ("count_star", None, "c")],
        "partial",
    )
    driver = MeshQueryDriver(mesh, conf=Configuration().set(EXCHANGE_MODE, "mesh"))
    resources = {"fact": _partitioned(df, N_DEV)}
    out = driver.collect(final, resources)
    assert int(out["c#count"].sum()) == len(df)
    assert int(out["s#sum"].sum()) == int(df["v"].sum())
    # all raw rows routed to a single reducer
    sizes = driver.stats[0].partition_sizes()
    assert (sizes > 0).sum() == 1 and sizes.sum() == len(df)


def test_auto_mode_statistics_rule(mesh):
    df = _fact(n=1000, seed=7)
    _, d_small = _run(mesh, df, "auto")
    assert d_small.stats[0].mode == "mesh"  # tiny payload rides ICI
    _, d_forced = _run(mesh, df, "auto", **{EXCHANGE_MESH_MAX_BYTES.key: 1})
    assert d_forced.stats[0].mode == "file"  # over budget -> durable path


def test_aqe_coalesces_small_reduce_partitions(mesh):
    """file-mode exchange consumes map-output statistics: 8 tiny reduce
    partitions coalesce into fewer reduce tasks, results unchanged
    (the stats are no longer write-only — VERDICT r1 weak #8)."""
    df = _fact(n=400, seed=11)
    got, driver = _run(mesh, df, "file",
                       **{"exchange.coalesce.target.bytes": 1 << 20})
    st = driver.stats[0]
    assert st.coalesced_groups is not None
    assert 1 <= len(st.coalesced_groups) < N_DEV
    assert sorted(p for g in st.coalesced_groups for p in g) == list(range(N_DEV))
    want = _oracle(df)
    assert got["s"].astype(np.int64).tolist() == want["s"].astype(np.int64).tolist()

    # disabled -> one reduce task per partition again
    got2, d2 = _run(mesh, df, "file", **{"exchange.coalesce.enable": False})
    assert d2.stats[0].coalesced_groups is None
    pd.testing.assert_frame_equal(got, got2)


def test_aqe_skipped_when_other_sources_feed_reduce_stage(mesh):
    """coalescing must not shrink a stage with additional per-partition
    inputs (their partitions would be dropped/misaligned)."""
    df = _fact(n=400, seed=13)
    schema = T.Schema.from_arrow(
        pa.RecordBatch.from_pandas(df.iloc[:1], preserve_index=False).schema
    )
    dim = pd.DataFrame({"k2": np.arange(97, dtype=np.int64),
                        "tag": np.arange(97, dtype=np.int64) * 10})
    dim_schema = T.Schema.from_arrow(
        pa.RecordBatch.from_pandas(dim.iloc[:1], preserve_index=False).schema
    )
    scan = B.memory_scan(schema, "fact")
    partial = B.hash_agg(scan, [(col(0), "k")], [("sum", col(2), "s")], "partial")
    ex = B.mesh_exchange(partial, B.hash_partitioning([col(0)], N_DEV), "exj")
    final = B.hash_agg(ex, [(col(0), "k")], [("sum", col(1), "s")], "final")
    j = B.hash_join(final, B.memory_scan(dim_schema, "dim"),
                    [col(0)], [col(0)], "inner", build_side="right")
    conf = Configuration().set(EXCHANGE_MODE, "file").set(
        "exchange.coalesce.target.bytes", 1 << 20)
    driver = MeshQueryDriver(mesh, conf=conf)
    dim_b = Batch.from_arrow(pa.RecordBatch.from_pandas(dim, preserve_index=False))
    out = driver.collect(j, {"fact": _partitioned(df, N_DEV),
                             "dim": [[dim_b]] * N_DEV})
    # the join stage has a second input -> no coalescing applied
    assert driver.stats[0].coalesced_groups is None
    want = (df.groupby("k").agg(s=("v", "sum")).reset_index()
            .merge(dim, left_on="k", right_on="k2"))
    out = out.sort_values("k").reset_index(drop=True)
    assert out["s"].astype(np.int64).tolist() == want["s"].tolist()
    assert out["tag"].astype(np.int64).tolist() == want["tag"].tolist()


def test_range_partitioned_exchange_orders_partitions(mesh):
    """RANGE partitioning through the planned exchange: reduce partition i
    holds keys strictly below partition i+1's (Spark RangePartitioner)."""
    from auron_tpu.exec.shuffle.partitioning import make_range_bounds
    from auron_tpu.ops.sortkeys import SortSpec
    from auron_tpu.plan.builders import sort_field
    from auron_tpu.proto import plan_pb2 as pb

    df = _fact(n=2000, seed=21)
    schema = T.Schema.from_arrow(
        pa.RecordBatch.from_pandas(df.iloc[:1], preserve_index=False).schema
    )
    sample = Batch.from_arrow(
        pa.RecordBatch.from_pandas(df.sample(256, random_state=0),
                                   preserve_index=False)
    )
    bounds = make_range_bounds(sample, [col(0)], [SortSpec()], N_DEV)
    part = pb.Partitioning(kind=pb.Partitioning.RANGE, num_partitions=N_DEV,
                           range_words_per_bound=bounds.shape[1])
    part.range_fields.add().CopyFrom(sort_field(col(0), SortSpec()))
    part.range_bound_words.extend(int(x) for x in bounds.reshape(-1))

    scan = B.memory_scan(schema, "fact")
    ex = B.mesh_exchange(scan, part, "ex_range")
    driver = MeshQueryDriver(mesh, conf=Configuration().set(EXCHANGE_MODE, "mesh"))
    outs = driver.run(B.filter_(ex, []), {"fact": _partitioned(df, N_DEV)})
    per_part_keys = []
    for p, batches in enumerate(outs):
        ks = []
        for b in batches:
            ks += b.to_arrow().to_pydict()["k"]
        per_part_keys.append(ks)
    assert sum(len(k) for k in per_part_keys) == len(df)
    # ranges are ordered: max(part i) <= min(part i+1)
    prev_max = None
    for ks in per_part_keys:
        if not ks:
            continue
        if prev_max is not None:
            assert prev_max <= min(ks)
        prev_max = max(ks)


def test_aqe_coalesces_multi_exchange_join_stage(mesh):
    """Two shuffles feed ONE join stage: AQE applies the SAME partition
    grouping to both exchanges (hash co-partitioning preserved), the stage
    shrinks, and the join result matches pandas (VERDICT r2 weak #6 —
    coalescing beyond the single-exchange case)."""
    rng = np.random.default_rng(21)
    left = pd.DataFrame({
        "k": rng.integers(0, 60, 700), "v": rng.integers(0, 100, 700).astype(np.int64)
    })
    right = pd.DataFrame({
        "rk": np.arange(60), "w": (np.arange(60) * 3).astype(np.int64)
    })
    ls = T.Schema.from_arrow(pa.RecordBatch.from_pandas(left.iloc[:1], preserve_index=False).schema)
    rs = T.Schema.from_arrow(pa.RecordBatch.from_pandas(right.iloc[:1], preserve_index=False).schema)
    exl = B.mesh_exchange(B.memory_scan(ls, "L"), B.hash_partitioning([col(0)], N_DEV), "exL")
    exr = B.mesh_exchange(B.memory_scan(rs, "R"), B.hash_partitioning([col(0)], N_DEV), "exR")
    join = B.hash_join(exl, exr, [col(0)], [col(0)], "inner", build_side="right")
    conf = (Configuration().set(EXCHANGE_MODE, "file")
            .set("exchange.coalesce.target.bytes", 1 << 20))
    driver = MeshQueryDriver(mesh, conf=conf)
    resources = {"L": _partitioned(left, N_DEV), "R": _partitioned(right, N_DEV)}
    out = driver.collect(join, resources)

    st = {s.exchange_id: s for s in driver.stats}
    assert st["exL"].coalesced_groups is not None
    assert st["exR"].coalesced_groups is not None
    assert st["exL"].coalesced_groups == st["exR"].coalesced_groups  # same groups!
    want = left.merge(right, left_on="k", right_on="rk", how="inner")
    got = out.sort_values(list(out.columns)).reset_index(drop=True)
    want.columns = got.columns
    want = want.sort_values(list(want.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def test_aqe_coalesces_intermediate_stage(mesh):
    """An exchange whose consumer is ANOTHER exchange's map stage coalesces
    too — per-stage re-planning, not just the residual stage."""
    df = _fact(n=500, seed=23)
    schema = T.Schema.from_arrow(
        pa.RecordBatch.from_pandas(df.iloc[:1], preserve_index=False).schema
    )
    scan = B.memory_scan(schema, "fact")
    partial = B.hash_agg(
        scan, [(col(0), "k"), (col(1), "g2")], [("sum", col(2), "s")], "partial"
    )
    ex0 = B.mesh_exchange(partial, B.hash_partitioning([col(0), col(1)], N_DEV), "ex0")
    mid = B.hash_agg(
        ex0, [(col(0), "k"), (col(1), "g2")], [("sum", col(2), "s")], "final"
    )
    # second shuffle: regroup by k only
    p2 = B.hash_agg(mid, [(col(0), "k")], [("sum", col(2), "s2")], "partial")
    ex1 = B.mesh_exchange(p2, B.hash_partitioning([col(0)], N_DEV), "ex1")
    final = B.hash_agg(ex1, [(col(0), "k")], [("sum", col(2), "s2")], "final")

    conf = (Configuration().set(EXCHANGE_MODE, "file")
            .set("exchange.coalesce.target.bytes", 1 << 20))
    driver = MeshQueryDriver(mesh, conf=conf)
    out = driver.collect(final, {"fact": _partitioned(df, N_DEV)})

    st = {s.exchange_id: s for s in driver.stats}
    assert st["ex0"].coalesced_groups is not None  # intermediate stage shrank
    assert st["ex1"].coalesced_groups is not None  # residual stage shrank
    want = df.groupby("k").agg(s2=("v", "sum")).reset_index()
    got = out.sort_values("k").reset_index(drop=True)
    assert got["s2"].astype(np.int64).tolist() == want["s2"].astype(np.int64).tolist()


# ---------------------------------------------------------------------------
# AQE skew-join splitting (Spark OptimizeSkewedJoin analog)
# ---------------------------------------------------------------------------


def _skew_join_plan(l_schema, r_schema):
    """fact JOIN dim over two planned exchanges + sorts (q72 shape)."""
    from auron_tpu.ops.sortkeys import SortSpec

    lex = B.mesh_exchange(
        B.memory_scan(l_schema, "skew_l"), B.hash_partitioning([col(0)], N_DEV),
        "skew_ex_l")
    rex = B.mesh_exchange(
        B.memory_scan(r_schema, "skew_r"), B.hash_partitioning([col(0)], N_DEV),
        "skew_ex_r")
    lsort = B.sort(lex, [(col(0), SortSpec())])
    rsort = B.sort(rex, [(col(0), SortSpec())])
    j = B.sort_merge_join(lsort, rsort, [col(0)], [col(0)], "inner")
    p = B.hash_agg(j, [(col(0), "k")],
                   [("count_star", None, "c"), ("sum", col(3), "w")], "partial")
    # the regrouping agg sits BEYOND another exchange: the join stage is
    # skew-splittable, the final agg keeps one group per partition
    ex2 = B.mesh_exchange(p, B.hash_partitioning([col(0)], N_DEV), "skew_ex2")
    return B.hash_agg(ex2, [(col(0), "k")],
                      [("count_star", None, "c"), ("sum", col(1), "w")], "final")


def _skew_data(hot_frac=0.7, n=30000):
    rng = np.random.default_rng(12)
    keys = rng.integers(0, 60, n)
    keys[: int(n * hot_frac)] = 7  # one hot key -> one hot partition
    fact = pd.DataFrame({
        "k": keys.astype(np.int64),
        "v": rng.integers(0, 5, n).astype(np.int64),
    })
    dim = pd.DataFrame({
        "k2": np.arange(60, dtype=np.int64),
        "w": rng.integers(1, 10, 60).astype(np.int64),
    })
    return fact, dim


def _run_skew(mesh, fact, dim, extra=None):
    from auron_tpu.utils.config import (
        EXCHANGE_COALESCE_TARGET_BYTES,
        EXCHANGE_SKEW_FACTOR,
        EXCHANGE_SKEW_MIN_BYTES,
    )

    l_schema = T.Schema.from_arrow(
        pa.RecordBatch.from_pandas(fact.iloc[:1], preserve_index=False).schema)
    r_schema = T.Schema.from_arrow(
        pa.RecordBatch.from_pandas(dim.iloc[:1], preserve_index=False).schema)
    conf = (Configuration().set(EXCHANGE_MODE, "file")
            .set(EXCHANGE_COALESCE_TARGET_BYTES, 1)  # keep full width
            .set(EXCHANGE_SKEW_FACTOR, 2.0)
            .set(EXCHANGE_SKEW_MIN_BYTES, 1))
    for k, v in (extra or {}).items():
        conf.set(k, v)
    driver = MeshQueryDriver(mesh, conf=conf)
    resources = {"skew_l": _partitioned(fact, N_DEV),
                 "skew_r": _partitioned(dim, N_DEV)}
    out = driver.collect(_skew_join_plan(l_schema, r_schema), resources)
    return out.sort_values("k").reset_index(drop=True), driver


def test_skew_join_splits_hot_partition(mesh):
    fact, dim = _skew_data()
    got, driver = _run_skew(mesh, fact, dim)
    # the JOIN stage widened: the downstream exchange saw more map tasks
    # than mesh partitions, and the split sides recorded their task maps
    ex2 = next(s for s in driver.stats if s.exchange_id == "skew_ex2")
    assert ex2.rows.shape[0] > N_DEV
    exl = next(s for s in driver.stats if s.exchange_id == "skew_ex_l")
    assert exl.skew_tasks is not None and len(exl.skew_tasks) > N_DEV
    want = (fact.merge(dim, left_on="k", right_on="k2")
            .groupby("k").agg(c=("v", "size"), w=("w", "sum")).reset_index()
            .sort_values("k").reset_index(drop=True))
    got = got.astype({"k": np.int64, "c": np.int64, "w": np.int64})
    pd.testing.assert_frame_equal(
        got, want.astype({"k": np.int64, "c": np.int64, "w": np.int64}))


def test_skew_join_disabled_keeps_width(mesh):
    from auron_tpu.utils.config import EXCHANGE_SKEW_ENABLE

    fact, dim = _skew_data()
    got, driver = _run_skew(mesh, fact, dim, extra={EXCHANGE_SKEW_ENABLE: False})
    ex2 = next(s for s in driver.stats if s.exchange_id == "skew_ex2")
    assert ex2.rows.shape[0] == N_DEV  # untouched width
    want = (fact.merge(dim, left_on="k", right_on="k2")
            .groupby("k").agg(c=("v", "size"), w=("w", "sum")).reset_index()
            .sort_values("k").reset_index(drop=True))
    got = got.astype({"k": np.int64, "c": np.int64, "w": np.int64})
    pd.testing.assert_frame_equal(
        got, want.astype({"k": np.int64, "c": np.int64, "w": np.int64}))

"""Plan explain + golden stability tests (PlanStabilityChecker analog)."""

import os

import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exec.basic import FilterExec, MemoryScanExec, ProjectExec
from auron_tpu.exec.agg_exec import PARTIAL, AggExpr, HashAggExec
from auron_tpu.exec.joins import BroadcastHashJoinExec
from auron_tpu.exprs.ir import BinaryOp, col, lit
from auron_tpu.plan.explain import check_stability, explain, normalize

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "goldens")


def _plan():
    b1 = Batch.from_pydict({"k": [1], "v": [1.0]})
    b2 = Batch.from_pydict({"k2": [1], "w": [2.0]})
    scan = MemoryScanExec.single([b1])
    scan2 = MemoryScanExec.single([b2])
    f = FilterExec(scan, [BinaryOp("gt", col(1, "v"), lit(0.5))])
    j = BroadcastHashJoinExec(f, scan2, [col(0)], [col(0)], "inner",
                              build_side="right")
    p = ProjectExec(j, [col(0, "k"), BinaryOp("mul", col(1), col(3))], ["k", "vw"])
    return HashAggExec(p, [(col(0), "k")], [(AggExpr("sum", col(1)), "s")], PARTIAL)


def test_explain_renders_tree():
    text = explain(_plan())
    assert "HashAggExec" in text and "BroadcastHashJoinExec" in text
    assert "groups=[#0]" in text
    assert "aggs=[sum(#1) as s]" in text
    assert "join_type=inner" in text
    assert text.count("\n") >= 4  # nested tree


def test_plan_stability_golden():
    golden = os.path.join(GOLDEN_DIR, "agg_join_plan.txt")
    check_stability(_plan(), golden)  # creates on first run, diffs after
    check_stability(_plan(), golden)  # must match itself


def test_plan_stability_detects_change(tmp_path):
    golden = str(tmp_path / "g.txt")
    check_stability(_plan(), golden)
    b1 = Batch.from_pydict({"k": [1], "v": [1.0]})
    other = FilterExec(MemoryScanExec.single([b1]), [BinaryOp("lt", col(0), lit(9))])
    with pytest.raises(AssertionError, match="plan changed"):
        check_stability(other, golden)


def test_explain_proto_renders_driver_nodes():
    """proto-level explain covers nodes that never become exec operators
    (mesh_exchange, kafka_scan)."""
    from auron_tpu import types as T
    from auron_tpu.exprs.ir import col
    from auron_tpu.plan import builders as B
    from auron_tpu.plan.explain import explain_proto

    schema = T.Schema.of(T.Field("k", T.INT64), T.Field("v", T.INT64))
    plan = B.hash_agg(
        B.mesh_exchange(
            B.hash_agg(B.kafka_scan(schema, "orders", "src",
                                    data_format="protobuf"),
                       [(col(0), "k")], [("sum", col(1), "s")], "partial"),
            B.hash_partitioning([col(0)], 8), "e1"),
        [(col(0), "k")], [("sum", col(1), "s")], "final")
    text = explain_proto(plan)
    assert "mesh_exchange" in text and "exchange_id=e1" in text
    assert "kafka_scan" in text and "topic=orders" in text
    assert "partitioning=hash(8)" in text
    assert "mode=agg_partial" in text and "mode=agg_final" in text
    assert text.count("\n") == 3  # nested 4-level tree

"""Conversion layer (L2) tests: tagging, boundaries, fallbacks, fixpoint.

VERDICT r1 item 4: feed a mixed plan (convertible + unconvertible nodes)
and assert correct boundaries and fallbacks. Reference behavior:
AuronConvertStrategy.scala:49-283, AuronConverters.scala:189-305,
NativeConverters.scala:329-1200.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.convert import HostNode, convert_plan
from auron_tpu.convert.converters import HostOp, NativeSegment
from auron_tpu.utils.config import UDF_FALLBACK_ENABLE, Configuration


def _attr(i, name=""):
    return {"kind": "attr", "index": i, "name": name}


def _lit(v, t):
    return {"kind": "lit", "value": v, "type": t}


def _call(name, *children, **extra):
    return {"kind": "call", "name": name, "children": list(children), **extra}


def _scan(schema, rid="t"):
    return {"op": "LocalTableScanExec", "schema": schema,
            "args": {"resource_id": rid}, "children": []}


SCHEMA = [["k", "long", True], ["v", "long", True], ["s", "string", True]]


def test_mixed_plan_boundaries_and_reasons():
    """project -> filter -> <python op> -> scan: the python op is
    unconvertible; the filter above it gets reverted by the
    removeInefficientConverts rule (filter over non-native child); the
    project remains native with an FFI boundary."""
    plan = {
        "op": "ProjectExec",
        "schema": [["k", "long", True]],
        "args": {"projections": [_attr(0)]},
        "children": [{
            "op": "FilterExec", "schema": SCHEMA,
            "args": {"predicates": [_call("greaterthan", _attr(1), _lit(0, "long"))]},
            "children": [{
                "op": "PythonMapExec", "schema": SCHEMA, "args": {},
                "children": [_scan(SCHEMA)],
            }],
        }],
    }
    res = convert_plan(plan)
    root = res.root
    assert isinstance(root, NativeSegment)
    assert root.plan.WhichOneof("plan") == "project"
    assert len(root.inputs) == 1  # one FFI boundary below the project
    rid, host_filter = root.inputs[0]
    assert root.plan.project.child.ffi_reader.resource_id == rid
    assert isinstance(host_filter, HostOp) and host_filter.node.op == "FilterExec"
    assert "children is not native" in res.tags.why(host_filter.node)
    py = host_filter.children[0]
    assert isinstance(py, HostOp) and py.node.op == "PythonMapExec"
    assert "not supported yet" in res.tags.why(py.node)
    # the scan below the python op is still a native segment
    assert isinstance(py.children[0], NativeSegment)
    assert py.children[0].plan.WhichOneof("plan") == "memory_scan"


def test_per_operator_enable_flag():
    plan = {
        "op": "ProjectExec", "schema": [["k", "long", True]],
        "args": {"projections": [_attr(0)]},
        "children": [_scan(SCHEMA)],
    }
    res = convert_plan(plan)
    assert isinstance(res.root, NativeSegment)

    conf = Configuration().set("convert.enable.project", False)
    res2 = convert_plan(plan, conf=conf)
    assert isinstance(res2.root, HostOp)
    assert "convert.enable.project" in res2.tags.why(res2.root.node)
    # the child scan is still converted below the host project
    assert isinstance(res2.root.children[0], NativeSegment)


def test_udf_fallback_wrapping():
    plan = {
        "op": "ProjectExec", "schema": [["r", "long", True]],
        "args": {"projections": [_call("my_weird_fn", _attr(1), type="long")]},
        "children": [_scan(SCHEMA)],
    }
    # unknown function, no registry -> whole node falls back with a reason
    res = convert_plan(plan)
    assert isinstance(res.root, HostOp)
    assert "my_weird_fn" in res.tags.why(res.root.node)

    # registered host UDF + fallback enabled -> wrapped as HostUDF, native
    res2 = convert_plan(plan, udf_registry={"my_weird_fn": lambda v: v * 2})
    assert isinstance(res2.root, NativeSegment)
    proj_expr = res2.root.plan.project.exprs[0].expr
    assert proj_expr.WhichOneof("expr") == "host_udf"
    assert proj_expr.host_udf.name == "my_weird_fn"

    # fallback disabled -> unconvertible again
    conf = Configuration().set(UDF_FALLBACK_ENABLE, False)
    res3 = convert_plan(plan, conf=conf, udf_registry={"my_weird_fn": lambda v: v})
    assert isinstance(res3.root, HostOp)


def test_inefficient_convert_fixpoint_rules():
    # agg over a non-native child is reverted
    agg_over_py = {
        "op": "HashAggregateExec", "schema": [["k", "long", True], ["c#count", "long", False]],
        "args": {"mode": "partial", "groupings": [{"expr": _attr(0), "name": "k"}],
                 "aggs": [{"fn": "count_star", "expr": None, "name": "c"}]},
        "children": [{
            "op": "PythonMapExec", "schema": SCHEMA, "args": {},
            "children": [_scan(SCHEMA)],
        }],
    }
    res = convert_plan(agg_over_py)
    assert isinstance(res.root, HostOp)
    assert "children is not native" in res.tags.why(res.root.node)

    # non-native -> native sort -> non-native sandwich is reverted
    sandwich = {
        "op": "PythonMapExec", "schema": SCHEMA, "args": {},
        "children": [{
            "op": "SortExec", "schema": SCHEMA,
            "args": {"order": [{"expr": _attr(0), "asc": True}]},
            "children": [{
                "op": "PythonMapExec", "schema": SCHEMA, "args": {},
                "children": [_scan(SCHEMA)],
            }],
        }],
    }
    res2 = convert_plan(sandwich)
    sort_host = res2.root.children[0]
    assert isinstance(sort_host, HostOp) and sort_host.node.op == "SortExec"
    assert "both are not native" in res2.tags.why(sort_host.node)


def test_scan_reverted_under_nonnative_parent():
    plan = {
        "op": "PythonMapExec", "schema": SCHEMA, "args": {},
        "children": [{
            "op": "FileSourceScanExec", "schema": SCHEMA,
            "args": {"files": ["/tmp/x.parquet"]}, "children": [],
        }],
    }
    res = convert_plan(plan)
    scan = res.root.children[0]
    assert isinstance(scan, HostOp)
    assert "nativeParquetScan" in res.tags.why(scan.node)


def test_converted_two_stage_runs_on_mesh():
    """Fully-convertible host plan (scan -> partial agg -> shuffle ->
    final agg) converts to ONE native segment with a mesh_exchange inside
    and runs under MeshQueryDriver, matching pandas."""
    from auron_tpu.parallel.mesh import make_mesh
    from auron_tpu.parallel.mesh_driver import MeshQueryDriver

    n_dev = 8
    inter = [["k", "long", True], ["s#sum", "long", True]]
    plan = {
        "op": "HashAggregateExec", "schema": inter,
        "args": {"mode": "final", "groupings": [{"expr": _attr(0), "name": "k"}],
                 "aggs": [{"fn": "sum", "expr": _attr(1), "name": "s"}]},
        "children": [{
            "op": "ShuffleExchangeExec", "schema": inter,
            "args": {"partitioning": {"kind": "hash", "exprs": [_attr(0)],
                                      "num_partitions": n_dev}},
            "children": [{
                "op": "HashAggregateExec", "schema": inter,
                "args": {"mode": "partial",
                         "groupings": [{"expr": _attr(0), "name": "k"}],
                         "aggs": [{"fn": "sum", "expr": _attr(1), "name": "s"}]},
                "children": [_scan([["k", "long", True], ["v", "long", True]],
                                   rid="conv_fact")],
            }],
        }],
    }
    res = convert_plan(plan)
    assert isinstance(res.root, NativeSegment) and not res.root.inputs

    rng = np.random.default_rng(3)
    df = pd.DataFrame({"k": rng.integers(0, 23, 2000), "v": rng.integers(-50, 50, 2000)})
    per = (len(df) + n_dev - 1) // n_dev
    parts = [
        [Batch.from_arrow(pa.RecordBatch.from_pandas(
            df.iloc[p * per : (p + 1) * per].astype(np.int64), preserve_index=False))]
        for p in range(n_dev)
    ]
    driver = MeshQueryDriver(make_mesh(n_dev))
    out = driver.collect(res.root.plan, {"conv_fact": parts})
    out = out.sort_values("k").reset_index(drop=True)
    want = df.groupby("k").agg(s=("v", "sum")).reset_index()
    assert out["k"].astype(np.int64).tolist() == want["k"].tolist()
    assert out["s"].astype(np.int64).tolist() == want["s"].tolist()
    assert driver.stats and driver.stats[0].rows.shape == (n_dev, n_dev)


def test_ffi_boundary_executes():
    """A native segment fed by a host-computed child through the FFI
    boundary produces correct results (ConvertToNative analog)."""
    from auron_tpu.plan.planner import plan_from_proto
    from auron_tpu.exec.base import ExecutionContext

    plan = {
        "op": "ProjectExec", "schema": [["doubled", "long", True]],
        "args": {"projections": [_call("multiply", _attr(1), _lit(2, "long"))]},
        "children": [{
            "op": "PythonMapExec", "schema": [["k", "long", True], ["v", "long", True]],
            "args": {}, "children": [],
        }],
    }
    res = convert_plan(plan)
    root = res.root
    assert isinstance(root, NativeSegment) and len(root.inputs) == 1
    rid, _host = root.inputs[0]

    # the "host engine" evaluates its subtree and exports arrow batches
    host_df = pd.DataFrame({"k": [1, 2, 3], "v": [10, 20, 30]})
    rb = pa.RecordBatch.from_pandas(host_df.astype(np.int64), preserve_index=False)
    ctx = ExecutionContext(resources={rid: [rb]})
    op = plan_from_proto(root.plan)
    got = op.collect(ctx=ctx).to_pydict()
    assert got["doubled"] == [20, 40, 60]


def test_table_format_provider_prunes_files(tmp_path):
    """Iceberg/Hudi/Paimon analog (AuronConvertProvider SPI): a table-scan
    descriptor lowers to a parquet scan over only the partition-matching
    data files, and executes correctly."""
    import pyarrow.parquet as pa_pq

    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.plan.planner import plan_from_proto

    files = []
    for year in (2022, 2023, 2024):
        path = str(tmp_path / f"y{year}.parquet")
        pa_pq.write_table(
            pa.table({"year": pa.array([year] * 10, pa.int32()),
                      "v": pa.array(range(10), pa.int64())}),
            path,
        )
        files.append({"path": path, "partition": {"year": year},
                      "record_count": 10})

    plan = {
        "op": "IcebergScanExec",
        "schema": [["year", "int", True], ["v", "long", True]],
        "args": {"files": files,
                 "filters": [_call("greaterthanorequal", _attr(0), _lit(2023, "int"))]},
        "children": [],
    }
    res = convert_plan(plan)
    assert isinstance(res.root, NativeSegment)
    scan = res.root.plan.parquet_scan
    assert len(scan.file_paths) == 2  # 2022 file pruned by partition value
    assert all("2022" not in p for p in scan.file_paths)

    op = plan_from_proto(res.root.plan)
    rows = op.collect(ctx=ExecutionContext()).to_arrow().to_pylist()
    assert len(rows) == 20 and {r["year"] for r in rows} == {2023, 2024}

    # the per-op conf gate turns the provider off
    conf = Configuration().set("convert.enable.table_formats", False)
    res2 = convert_plan(plan, conf=conf)
    assert isinstance(res2.root, HostOp)


def test_table_format_provider_composes_with_pipeline():
    """A table-format scan participates in a larger convertible subtree."""
    plan = {
        "op": "HashAggregateExec",
        "schema": [["year", "int", True], ["c#count", "long", False]],
        "args": {"mode": "partial",
                 "groupings": [{"expr": _attr(0), "name": "year"}],
                 "aggs": [{"fn": "count_star", "expr": None, "name": "c"}]},
        "children": [{
            "op": "PaimonScanExec",
            "schema": [["year", "int", True], ["v", "long", True]],
            "args": {"files": [], "filters": []},
            "children": [],
        }],
    }
    res = convert_plan(plan)
    assert isinstance(res.root, NativeSegment)
    assert res.root.plan.WhichOneof("plan") == "hash_agg"
    assert res.root.plan.hash_agg.child.WhichOneof("plan") == "parquet_scan"


def test_malformed_host_exprs_fall_back_not_crash():
    """missing keys / unbound attrs degrade to unconvertible-with-reason."""
    for bad_expr in (
        _call("in", _attr(0)),                # no "values"
        _call("like", _attr(0)),              # no "pattern"
        {"kind": "attr", "index": -1},        # unbound reference
        _call("scalarsubquery"),              # no resource_id
    ):
        plan = {
            "op": "FilterExec", "schema": SCHEMA,
            "args": {"predicates": [bad_expr]},
            "children": [_scan(SCHEMA)],
        }
        res = convert_plan(plan)
        assert isinstance(res.root, HostOp), bad_expr
        assert res.tags.why(res.root.node), bad_expr


def test_unsupported_column_type_degrades_only_owner():
    """ADVICE r2 (medium): an unsupported column type anywhere in the host
    plan must tag only the OWNING node NeverConvert — sibling subtrees keep
    converting (the reference tags per-node, never aborts the whole query)."""
    bad_schema = [["m", "interval day to second", True]]
    plan = {
        "op": "UnionExec",
        "schema": SCHEMA,
        "args": {},
        "children": [
            _scan(SCHEMA, rid="a"),
            {"op": "ProjectExec", "schema": bad_schema,
             "args": {"projections": [_attr(0)]},
             "children": [_scan(bad_schema, rid="b")]},
        ],
    }
    res = convert_plan(plan)  # must not raise
    # the union binds the bad-typed child column, so it degrades as well
    # (native union over mistyped FFI data would corrupt); the GOOD sibling
    # subtree still converts — the failure never aborts the whole plan
    root = res.root
    assert isinstance(root, HostOp) and root.node.op == "UnionExec"
    good, bad = root.children
    assert isinstance(good, NativeSegment)
    assert isinstance(bad, HostOp) and bad.node.op == "ProjectExec"
    assert "unsupported host type" in (res.tags.why(bad.node) or "")


def test_map_struct_types_parse():
    """map<>/struct<> host types lower to engine MAP/STRUCT columns."""
    schema = [["m", "map<string,int>", True],
              ["st", "struct<a:int,b:array<long>>", True]]
    plan = {"op": "ProjectExec", "schema": schema,
            "args": {"projections": [_attr(0), _attr(1)]},
            "children": [_scan(schema, rid="ms")]}
    res = convert_plan(plan)
    assert isinstance(res.root, NativeSegment)


# ---------------------------------------------------------------------------
# serializer-shaped coverage: JSON in the exact shape HostPlanSerializer
# emits, for every operator class the engine converts (VERDICT r2 item 4)
# ---------------------------------------------------------------------------


def _sort_field(e, asc=True, nf=True):
    return {"expr": e, "asc": asc, "nulls_first": nf}


def test_serializer_shaped_full_operator_coverage():
    from auron_tpu.convert import convert_plan as cp

    scan = _scan(SCHEMA, rid="t")
    win = {
        "op": "WindowExec",
        "schema": SCHEMA + [["rn", "long", True]],
        "args": {
            "partition_by": [_attr(0)],
            "order": [_sort_field(_attr(1))],
            "funcs": [{"kind": "row_number", "name": "rn"},
                      {"kind": "agg", "agg": "sum", "expr": _attr(1),
                       "frame_whole": True, "name": "s"}],
        },
        "children": [scan],
    }
    expand = {
        "op": "ExpandExec",
        "schema": [["k", "long", True], ["v", "long", True]],
        "args": {"projections": [[_attr(0), _attr(1)],
                                 [_attr(0), _lit(None, "long")]]},
        "children": [scan],
    }
    union = {"op": "UnionExec",
             "schema": [["k", "long", True], ["v", "long", True]],
             "args": {}, "children": [expand, expand]}
    topk = {
        "op": "TakeOrderedAndProjectExec",
        "schema": [["k", "long", True]],
        "args": {"limit": 5, "order": [_sort_field(_attr(1), asc=False)],
                 "projections": [_attr(0)]},
        "children": [union],
    }
    res = cp(topk)
    assert isinstance(res.root, NativeSegment), res.explain()

    gen = {
        "op": "GenerateExec",
        "schema": [["k", "long", True], ["x", "long", True]],
        "args": {"generator": "explode",
                 "gen_expr": _call("makearray", _attr(0), _attr(1)),
                 "required_cols": [0], "outer": False, "json_fields": []},
        "children": [scan],
    }
    res = cp(gen)
    assert isinstance(res.root, NativeSegment), res.explain()

    write = {
        "op": "DataWritingCommandExec",
        "schema": [],
        "args": {"format": "parquet", "path": "/tmp/out_w",
                 "partition_by": [], "props": {}},
        "children": [scan],
    }
    res = cp(write)
    assert isinstance(res.root, NativeSegment), res.explain()


def test_serializer_shaped_range_exchange_with_bounds():
    from auron_tpu.convert import convert_plan as cp

    plan = {
        "op": "ShuffleExchangeExec",
        "schema": SCHEMA,
        "args": {"partitioning": {
            "kind": "range", "num_partitions": 4,
            "order": [_sort_field(_attr(0))],
            "bounds": [[{"value": 10, "type": "long"}],
                       [{"value": 20, "type": "long"}],
                       [{"value": 30, "type": "long"}]],
        }},
        "children": [_scan(SCHEMA)],
    }
    res = cp(plan)
    assert isinstance(res.root, NativeSegment), res.explain()
    ex = res.root.plan.mesh_exchange
    from auron_tpu.proto import plan_pb2 as pb

    assert ex.partitioning.kind == pb.Partitioning.RANGE
    assert ex.partitioning.num_partitions == 4
    assert len(ex.partitioning.range_bound_words) == 3 * 2  # 2 words per key

    # without bounds, a multi-partition range exchange DEGRADES (never
    # mis-scatters)
    plan["args"]["partitioning"]["bounds"] = []
    res = cp(plan)
    assert isinstance(res.root, HostOp)
    assert "bounds" in (res.tags.why(res.root.node) or "")


def test_serializer_shaped_in_list_typed_values():
    """ADVICE r2: intCol IN (1,2,3) must compare as ints even when values
    ride as JSON with a type tag (decimal strings become exact decimals)."""
    import pandas as pd

    from auron_tpu.bridge import api
    from auron_tpu.convert import convert_plan as cp

    plan = {
        "op": "FilterExec", "schema": [["k", "long", True]],
        "args": {"predicates": [
            {"kind": "call", "name": "in", "children": [_attr(0)],
             "values": [1, 3, 5], "value_type": "long"}]},
        "children": [_scan([["k", "long", True]], rid="inlist")],
    }
    res = cp(plan)
    assert isinstance(res.root, NativeSegment)
    from auron_tpu.columnar import Batch

    api.put_resource("inlist", [[Batch.from_pydict({"k": [1, 2, 3, 4, 5, 6]})]])
    try:
        from auron_tpu.plan import builders as B

        h = api.call_native(B.task(res.root.plan).SerializeToString())
        rows = []
        while (rb := api.next_batch(h)) is not None:
            rows += rb.to_pylist()
        api.finalize_native(h)
        assert sorted(r["k"] for r in rows) == [1, 3, 5]
    finally:
        api.remove_resource("inlist")


def test_conversion_service_response_shape():
    from auron_tpu.convert.service import convert_host_plan_json
    import base64
    import json as _json

    plan = {
        "op": "ProjectExec", "schema": [["k", "long", True]],
        "args": {"projections": [_attr(0)]},
        "children": [{
            "op": "PythonMapExec", "schema": SCHEMA, "args": {},
            "children": [_scan(SCHEMA)],
        }],
    }
    resp = _json.loads(convert_host_plan_json(_json.dumps(plan)))
    assert resp["converted"] is True
    root = resp["root"]
    assert root["kind"] == "segment" and root["path"] == []
    assert root["schema"] == [["k", "long", True]]
    assert len(root["stages"]) == 1 and root["stages"][0]["exchange_id"] is None
    # the boundary input: python op at path [0], its scan child a segment
    (inp,) = root["inputs"]
    child = inp["child"]
    assert child["kind"] == "host" and child["op"] == "PythonMapExec"
    assert child["path"] == [0]  # relative to the segment root
    assert child["children"][0]["kind"] == "segment"
    assert child["children"][0]["path"] == [0]  # relative to the python op
    assert root["task_partitions"] is None
    # plan proto decodes
    from auron_tpu.proto import plan_pb2 as pb

    node = pb.PhysicalPlanNode()
    node.ParseFromString(base64.b64decode(root["plan_b64"]))
    assert node.WhichOneof("plan") == "project"
    # tags are (op, ok, reason) rows in walk order
    assert [t[0] for t in resp["tags"]] == [
        "ProjectExec", "PythonMapExec", "LocalTableScanExec"
    ]

"""Memory manager parity tests (VERDICT r3 #6).

Reference semantics under test (auron-memmgr/src/lib.rs):

- unspillable consumers (join builds) register so their footprint shrinks
  the managed pool others fair-share (mem_unspillable, lib.rs:355-364);
- below-fair-share consumers WAIT for siblings to release before being
  forced to spill (Operation::Wait + condvar, lib.rs:393-410);
- the spill cascade stays exact under a tiny budget with a join build
  pinned resident (the VERDICT done-criterion);
- the host-RAM spill tier (HostSpill) demotes to disk under ledger
  pressure (HBM -> host RAM -> disk).
"""

import threading
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu.columnar import Batch
from auron_tpu.memory import memmgr as M
from auron_tpu.utils.config import (
    HOST_SPILL_BUDGET_BYTES,
    MEM_WAIT_TIMEOUT_S,
    Configuration,
    conf_scope,
)


class _FakeConsumer:
    def __init__(self, name, used=0, spillable=True):
        self.name = name
        self._used = used
        self.spill_calls = 0

    def mem_used(self):
        return self._used

    def spill(self):
        self.spill_calls += 1
        freed, self._used = self._used, 0
        return freed


@pytest.fixture(autouse=True)
def _restore_manager():
    yield
    M.MemManager.init()


def test_unspillable_shrinks_managed_pool():
    mm = M.MemManager.init(budget_bytes=1000)
    mm.budget = 1000  # ignore memory.fraction for arithmetic clarity
    build = _FakeConsumer("build", used=600)
    a = _FakeConsumer("a", used=100)
    mm.register(build, spillable=False)
    mm.register(a)
    # managed pool = 1000 - 600 = 400; one spillable -> fair max 400
    assert mm.mem_used_percent(a) == pytest.approx(100 / 400)
    # cascade must never pick the unspillable consumer as a victim
    mm.acquire(a, 350)  # 100+600+350 > 1000 -> needs 50
    assert build.spill_calls == 0
    assert a.spill_calls == 1


def test_update_mem_used_waits_for_release_then_proceeds():
    conf = Configuration().set(MEM_WAIT_TIMEOUT_S, 5.0)
    with conf_scope(conf):
        mm = M.MemManager.init(budget_bytes=64 << 20)
    mm.budget = 64 << 20
    hog = _FakeConsumer("hog", used=63 << 20)
    small = _FakeConsumer("small", used=0)
    mm.register(hog)
    mm.register(small)

    done = threading.Event()

    def grow():
        # pool is over (63MB + 2MB > 64MB) but small sits under min share
        # (fair max = 32MB, min = 4MB) -> waits instead of spilling itself
        small._used = 2 << 20
        mm.update_mem_used(small, 0, 2 << 20)
        done.set()

    t = threading.Thread(target=grow)
    t.start()
    time.sleep(0.3)
    assert not done.is_set()
    assert mm.num_waits == 1
    hog._used = 0  # sibling releases
    mm.notify_released()
    t.join(timeout=5)
    assert done.is_set()
    assert small.spill_calls == 0  # waited, never spilled


def test_update_mem_used_timeout_forces_spill():
    conf = Configuration().set(MEM_WAIT_TIMEOUT_S, 0.2)
    with conf_scope(conf):
        mm = M.MemManager.init(budget_bytes=64 << 20)
    mm.budget = 64 << 20
    hog = _FakeConsumer("hog", used=63 << 20)
    small = _FakeConsumer("small", used=0)
    mm.register(hog)
    mm.register(small)
    small._used = 2 << 20
    t0 = time.time()
    mm.update_mem_used(small, 0, 2 << 20)
    assert time.time() - t0 >= 0.2
    assert small.spill_calls == 1  # forced after the wait timed out


def test_self_spill_when_over_fair_share():
    mm = M.MemManager.init(budget_bytes=10 << 20)
    mm.budget = 10 << 20
    a = _FakeConsumer("a", used=0)
    b = _FakeConsumer("b", used=0)
    mm.register(a)
    mm.register(b)
    # a grows past its fair share (5MB) -> self-spill, b untouched
    a._used = 6 << 20
    mm.update_mem_used(a, 0, 6 << 20)
    assert a.spill_calls == 1 and b.spill_calls == 0


def test_join_build_under_tiny_budget_stays_exact():
    """VERDICT r3 #6 done-criterion: a join build under a tiny budget forces
    the agg/sort consumers to spill around the resident (unspillable) build
    and the query result stays exact."""
    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.exec.basic import MemoryScanExec
    from auron_tpu.exec.joins import BroadcastHashJoinExec
    from auron_tpu.exec.agg_exec import AggExpr, HashAggExec
    from auron_tpu.exprs.ir import col

    rng = np.random.default_rng(3)
    # keys spread over a huge range: keeps the dense direct-address agg
    # (which needs no spills) ineligible — the GENERIC spill machinery
    # under pressure is what this test exercises
    fact = pd.DataFrame({
        "k": (rng.integers(0, 50, 5000) * 1_000_003).astype(np.int64),
        "v": rng.integers(-100, 100, 5000).astype(np.int64),
    })
    dim = pd.DataFrame({
        "k2": (np.arange(50) * 1_000_003).astype(np.int64),
        "g": ((np.arange(50) % 7) * 1_000_003).astype(np.int64),
    })

    def mk(df, chunk):
        return MemoryScanExec.single([
            Batch.from_arrow(pa.RecordBatch.from_pandas(
                df.iloc[i : i + chunk], preserve_index=False))
            for i in range(0, len(df), chunk)
        ])

    M.MemManager.init(budget_bytes=4096)  # tiny: every agg state spills
    join = BroadcastHashJoinExec(
        mk(fact, 500), mk(dim, 50), [col(0)], [col(0)], "inner",
        build_side="right",
    )
    partial = HashAggExec(
        join, [(col(3), "g")], [(AggExpr("sum", col(1)), "s")], "partial",
    )
    agg = HashAggExec(
        partial, [(col(0), "g")], [(AggExpr("sum", col(1)), "s")], "final",
    )
    got = (
        agg.collect(0, ExecutionContext()).to_pandas()
        .sort_values("g").reset_index(drop=True)
    )
    want = (
        fact.merge(dim, left_on="k", right_on="k2")
        .groupby("g").agg(s=("v", "sum")).reset_index()
        .sort_values("g").reset_index(drop=True)
    )
    pd.testing.assert_frame_equal(got, want, check_dtype=False)
    assert M.MemManager.get().num_spills > 0


def test_host_spill_ledger_demotes_to_disk():
    df = pd.DataFrame({"x": np.arange(20000, dtype=np.int64)})
    tbl = pa.Table.from_pandas(df, preserve_index=False)
    conf = Configuration().set(HOST_SPILL_BUDGET_BYTES, 1)  # everything demotes
    with conf_scope(conf):
        hs = M.HostSpill(conf=None)  # deliberate: conf-independent scratch
        hs.write_table(tbl)
        assert hs.demoted  # ledger pressure pushed it to disk
        back = list(hs.read_tables())
        assert sum(t.num_rows for t in back) == 20000
        hs.release()

    # roomy ledger: stays in RAM
    conf2 = Configuration().set(HOST_SPILL_BUDGET_BYTES, 1 << 30)
    with conf_scope(conf2):
        hs2 = M.HostSpill(conf=None)  # deliberate: conf-independent scratch
        hs2.write_table(tbl)
        assert not hs2.demoted
        back2 = list(hs2.read_tables())
        assert sum(t.num_rows for t in back2) == 20000
        hs2.release()
        assert M._host_ledger.resident_bytes() >= 0


def test_shuffle_staging_spills_and_reads_back(tmp_path):
    """Shuffle staging registers as a consumer: a tiny budget forces runs
    to park on disk mid-write, and the merged .data/.index output still
    decodes exactly (sort_repartitioner.rs spill-merge analog)."""
    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.exec.basic import MemoryScanExec
    from auron_tpu.exec.shuffle.partitioning import HashPartitioning
    from auron_tpu.exec.shuffle.reader import MultiMapBlockProvider
    from auron_tpu.exec.shuffle.writer import ShuffleWriterExec
    from auron_tpu.exprs.ir import col

    rng = np.random.default_rng(5)
    df = pd.DataFrame({
        "k": rng.integers(0, 1000, 20000).astype(np.int64),
        "v": rng.integers(0, 10, 20000).astype(np.int64),
    })
    scan = MemoryScanExec.single([
        Batch.from_arrow(pa.RecordBatch.from_pandas(
            df.iloc[i : i + 2000], preserve_index=False))
        for i in range(0, len(df), 2000)
    ])
    M.MemManager.init(budget_bytes=4096)
    n_red = 4
    data_f = str(tmp_path / "out.data")
    index_f = str(tmp_path / "out.index")
    w = ShuffleWriterExec(scan, HashPartitioning([col(0)], n_red), data_f, index_f)
    assert list(w.execute(0, ExecutionContext())) == []
    assert M.MemManager.get().num_spills > 0

    provider = MultiMapBlockProvider([(data_f, index_f)])
    rows = 0
    seen_keys = set()
    for pid in range(n_red):
        for rb in provider(pid):
            t = rb.to_pandas() if hasattr(rb, "to_pandas") else rb
            rows += len(t)
            seen_keys.update(t["k"].tolist())
    assert rows == len(df)
    assert seen_keys == set(df["k"].unique())

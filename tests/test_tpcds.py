"""TPC-DS-class differential integration tests (the in-process analog of the
reference's TPC-DS result-check gate, QueryResultComparator.scala:39-110)."""

import tempfile

import pandas as pd
import pytest

from auron_tpu.models import tpcds


@pytest.fixture(scope="module")
def data():
    return tpcds.generate(sf=0.003, seed=7)


def test_q1_class_matches_oracle(data):
    got = tpcds.run_q1_class(data, n_partitions=3, year=2000)
    want = tpcds.q1_class_oracle(data, year=2000)
    assert len(got) == 1
    assert got["cnt"][0] == want["cnt"][0]
    assert got["total"][0] == pytest.approx(want["total"][0], rel=1e-9)
    assert got["mean"][0] == pytest.approx(want["mean"][0], rel=1e-9)


def test_q3_class_matches_oracle(data, tmp_path):
    got = tpcds.run_q3_class(data, n_map=3, n_reduce=2, work_dir=str(tmp_path))
    want = tpcds.q3_class_oracle(data)
    assert len(got) == len(want)
    assert got["d_year"].tolist() == want["d_year"].tolist()
    assert got["i_brand_id"].tolist() == want["i_brand_id"].tolist()
    for g, w in zip(got["s"], want["s"]):
        assert g == pytest.approx(w, rel=1e-9)


def test_q72_class_matches_oracle(data, tmp_path):
    got, sr = tpcds.run_q72_class(data, n_map=2, n_reduce=3, work_dir=str(tmp_path))
    want = tpcds.q72_class_oracle(data, sr)
    assert len(got) == len(want)
    assert got["item"].tolist() == want["item"].tolist()
    assert got["cnt"].tolist() == want["cnt"].tolist()
    assert got["qty"].tolist() == want["qty"].tolist()
    for g, w in zip(got["p_avg"], want["p_avg"]):
        assert g == pytest.approx(w, rel=1e-9)


def test_q95_class_matches_oracle(data, tmp_path):
    got = tpcds.run_q95_class(data, n_map=2, n_reduce=2, work_dir=str(tmp_path))
    want = tpcds.q95_class_oracle(data)
    assert len(got) == len(want)
    gk = [None if pd.isna(x) else int(x) for x in got["customer"]]
    wk = [None if pd.isna(x) else int(x) for x in want["customer"]]
    assert gk == wk
    assert got["cnt"].tolist() == want["cnt"].tolist()


def test_windowed_query_matches_oracle(data):
    got = tpcds.run_windowed_query(data)
    want = tpcds.windowed_query_oracle(data)
    assert len(got) == len(want)
    assert got["d"].tolist() == want["d"].tolist()
    assert got["item"].tolist() == want["item"].tolist()
    assert got["rk"].tolist() == want["rk"].tolist()
    for g, w in zip(got["rev"], want["rev"]):
        assert g == pytest.approx(w, rel=1e-9)


def test_q3_concurrent_maps_with_spills():
    """Map tasks run concurrently; a tiny memory budget forces cross-thread
    spill cascades through MemManager — results must stay exact (regression
    for the per-consumer locking added in round 2)."""
    from auron_tpu.memory.memmgr import MemManager

    data = tpcds.generate(sf=0.05, seed=9)
    MemManager.init(budget_bytes=4096)  # tiny: every staged inter spills
    orig = tpcds.to_batches
    tpcds.to_batches = lambda df, n, batch_rows=4096, _o=orig: _o(df, n, batch_rows)
    try:
        with tempfile.TemporaryDirectory() as wd:
            got = tpcds.run_q3_class(data, n_map=4, n_reduce=2, work_dir=wd)
        want = tpcds.q3_class_oracle(data)
        assert len(got) == len(want)
        for g, w in zip(got["s"], want["s"]):
            assert abs(float(g) - float(w)) <= 1e-6 * max(1.0, abs(float(w)))
        assert MemManager.get().num_spills > 0
    finally:
        tpcds.to_batches = orig
        MemManager.init()  # restore default budget


def test_q6_class_matches_oracle(data):
    got = tpcds.run_q6_class(data)
    want = tpcds.q6_class_oracle(data)
    assert tpcds._cmp_frames(got, want) is None


def test_q18_class_matches_oracle(data, tmp_path):
    got = tpcds.run_q18_class(data, work_dir=str(tmp_path))
    want = tpcds.q18_class_oracle(data)
    assert tpcds._cmp_frames(got, want) is None


def test_generate_class_matches_oracle(data):
    got = tpcds.run_generate_class(data)
    want = tpcds.generate_class_oracle(data)
    assert tpcds._cmp_frames(got, want) is None


def test_windowed2_class_matches_oracle(data):
    got = tpcds.run_windowed2_class(data)
    want = tpcds.windowed2_class_oracle(data)
    assert tpcds._cmp_frames(got, want) is None


def test_q14b_intersect_except_matches_oracle(data):
    got = tpcds.run_q14b_class(data)
    want = tpcds.q14b_class_oracle(data)
    assert tpcds._cmp_frames(got, want) is None


def test_q67b_cube_matches_oracle(data):
    got = tpcds.run_q67b_class(data)
    want = tpcds.q67b_class_oracle(data)
    assert tpcds._cmp_frames(got, want) is None


def test_q93_null_skew_matches_oracle(data, tmp_path):
    got = tpcds.run_q93_class(data, work_dir=str(tmp_path))
    want = tpcds.q93_class_oracle(data)
    assert tpcds._cmp_frames(got, want) is None
    # the rewrite must actually produce the skew: most keys NULL
    null_row = got[got.k_null]
    assert len(null_row) == 1 and null_row.iloc[0]["rows"] > got["rows"].sum() * 0.7


def test_q9b_decimal_wide_matches_oracle(data):
    got = tpcds.run_q9b_class(data)
    want = tpcds.q9b_class_oracle(data)
    assert tpcds._cmp_frames(got, want) is None
    # the poisoned group's sum overflowed 38 digits -> NULL (non-ANSI)
    assert pd.isna(got[got.g == 7]["s"].iloc[0])
    assert got[got.g != 7]["s"].notna().all()


def test_gate_runs_all_classes():
    """The single-command differential gate (QueryRunner analog): every
    query class executes and matches its oracle."""
    res = tpcds.run_gate(sf=0.02, verbose=False)
    assert len(res) >= 40  # VERDICT r4 #6: the widened differential surface
    failures = [(n, e) for n, ok, e, _ in res if not ok]
    assert not failures, failures


def test_q18_plan_stability_golden(data, tmp_path):
    """Golden explain for the q18 map-stage plan (pruned): native-coverage
    regressions in the agg+join pipeline fail here."""
    import os as _os

    from auron_tpu.plan.explain import check_stability
    from auron_tpu.plan.planner import plan_from_proto
    from auron_tpu.plan.optimizer import prune_columns
    from auron_tpu.plan import builders as B
    from auron_tpu.exprs.ir import col

    fact_schema = tpcds._schema_of(data.store_sales)
    dd_schema = tpcds._schema_of(data.date_dim)
    it_schema = tpcds._schema_of(data.item)
    scan = B.memory_scan(fact_schema, "g_fact")
    j1 = B.hash_join(scan, B.memory_scan(dd_schema, "g_dd"),
                     [col(0)], [col(0)], "inner", build_side="right")
    j2 = B.hash_join(j1, B.memory_scan(it_schema, "g_item"),
                     [col(1)], [col(0)], "inner", build_side="right")
    proj = B.project(j2, [(col(10), "cat"), (col(6), "d_year"),
                          (col(3), "qty"), (col(4), "price")])
    partial = prune_columns(B.hash_agg(
        proj, [(col(0), "cat"), (col(1), "d_year")],
        [("avg", col(2), "q_avg"), ("sum", col(3), "p_sum")], "partial"))
    golden = _os.path.join(_os.path.dirname(__file__), "goldens", "q18_map_plan.txt")
    check_stability(plan_from_proto(partial), golden)


def _golden(name):
    import os as _os

    return _os.path.join(_os.path.dirname(__file__), "goldens", name)


def test_new_classes_match_oracles(data):
    for run, oracle in [
        (tpcds.run_q67_class, tpcds.q67_class_oracle),
        (tpcds.run_q9_class, tpcds.q9_class_oracle),
        (tpcds.run_q88_class, tpcds.q88_class_oracle),
        (tpcds.run_q37_class, tpcds.q37_class_oracle),
        (tpcds.run_q23_class, tpcds.q23_class_oracle),
    ]:
        got, want = run(data), oracle(data)
        assert tpcds._cmp_frames(got, want) is None, run.__name__


def test_q67_rollup_plan_golden(data):
    from auron_tpu.exprs.ir import Literal, col
    from auron_tpu.plan import builders as B
    from auron_tpu.plan.explain import check_stability
    from auron_tpu.plan.optimizer import prune_columns
    from auron_tpu.plan.planner import plan_from_proto
    from auron_tpu import types as T

    fact_schema = tpcds._schema_of(data.store_sales)
    scan = B.memory_scan(fact_schema, "g_fact")
    null_i64 = Literal(None, T.INT64)
    ex = B.expand(scan, [
        [col(0), col(1), col(4), tpcds.lit(0)],
        [col(0), null_i64, col(4), tpcds.lit(1)],
        [null_i64, null_i64, col(4), tpcds.lit(3)],
    ], ["d", "i", "price", "gid"])
    p = prune_columns(B.hash_agg(
        ex, [(col(0), "d"), (col(1), "i"), (col(3), "gid")],
        [("sum", col(2), "s")], "partial"))
    check_stability(plan_from_proto(p), _golden("q67_rollup_plan.txt"))


def test_q23_window_topk_plan_golden(data):
    from auron_tpu.exprs.ir import col
    from auron_tpu.ops.sortkeys import SortSpec
    from auron_tpu.plan import builders as B
    from auron_tpu.plan.explain import check_stability
    from auron_tpu.plan.optimizer import prune_columns
    from auron_tpu.plan.planner import plan_from_proto

    fact_schema = tpcds._schema_of(data.store_sales)
    it_schema = tpcds._schema_of(data.item)
    j = B.hash_join(B.memory_scan(fact_schema, "g_fact"),
                    B.memory_scan(it_schema, "g_item"),
                    [col(1)], [col(0)], "inner", build_side="right")
    proj = B.project(j, [(col(7), "cat"), (col(6), "brand"), (col(4), "price")])
    p = B.hash_agg(proj, [(col(0), "cat"), (col(1), "brand")],
                   [("sum", col(2), "rev")], "partial")
    f = B.hash_agg(p, [(col(0), "cat"), (col(1), "brand")],
                   [("sum", col(2), "rev")], "final")
    w = prune_columns(B.window(
        f, [col(0)], [(col(2), SortSpec(asc=False)), (col(1), SortSpec())],
        [("rank", None, None, 1, False, "rk")]))
    check_stability(plan_from_proto(w), _golden("q23_window_topk_plan.txt"))


def test_q14_stage1_plan_golden(data):
    from auron_tpu.exprs.ir import col
    from auron_tpu.plan import builders as B
    from auron_tpu.plan.explain import check_stability
    from auron_tpu.plan.optimizer import prune_columns
    from auron_tpu.plan.planner import plan_from_proto

    fact_schema = tpcds._schema_of(data.store_sales)
    dd_schema = tpcds._schema_of(data.date_dim)
    scan = B.memory_scan(fact_schema, "g_fact")
    j = B.hash_join(scan, B.memory_scan(dd_schema, "g_dd"),
                    [col(0)], [col(0)], "inner", build_side="right")
    proj = B.project(j, [(col(6), "y"), (col(1), "i")])
    p1 = prune_columns(B.hash_agg(proj, [(col(0), "y"), (col(1), "i")],
                                  [("count_star", None, "c")], "partial"))
    check_stability(plan_from_proto(p1), _golden("q14_stage1_plan.txt"))


def test_q9_scalar_subquery_plan_golden(data):
    from auron_tpu.exprs.ir import BinaryOp, ScalarSubquery, col
    from auron_tpu.plan import builders as B
    from auron_tpu.plan.explain import check_stability
    from auron_tpu.plan.optimizer import prune_columns
    from auron_tpu.plan.planner import plan_from_proto
    from auron_tpu import types as T

    fact_schema = tpcds._schema_of(data.store_sales)
    flt = B.filter_(B.memory_scan(fact_schema, "g_fact"),
                    [BinaryOp("gt", col(4), ScalarSubquery("g_avg", T.FLOAT64))])
    p = prune_columns(B.hash_agg(flt, [], [("count_star", None, "c"),
                                           ("sum", col(4), "s")], "partial"))
    check_stability(plan_from_proto(p), _golden("q9_scalar_plan.txt"))

"""auronlint gate: rule-family fixtures + whole-tree cleanliness.

Each rule family R1-R5 is exercised three ways — firing on a violating
fixture, honoring a suppression comment (with its required reason), and
staying quiet on clean code. The final test runs the real suite over the
real tree and fails on any unsuppressed finding, which is what makes the
engine invariants (host-sync hygiene, bounded compile cache, capacity
bucketing, registry lockstep, vectorization) regressions instead of
style advice.
"""

import json
import os
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.auronlint import ALL_RULES, REPO_ROOT, lint_source, run_tree
from tools.auronlint.report import Finding, Report
from tools.auronlint.rules import (
    HostSyncRule,
    RegistrySyncRule,
    RetraceRule,
    ShapeBucketRule,
    SortPayloadRule,
    VectorizeRule,
)


def _lint(src: str, rule, rel: str = "fixture.py"):
    return lint_source(textwrap.dedent(src), rel, [rule])


def _hits(report: Report, rule_name: str):
    return [f for f in report.findings if f.rule == rule_name and not f.suppressed]


def _suppressed(report: Report, rule_name: str):
    return [f for f in report.findings if f.rule == rule_name and f.suppressed]


# ---------------------------------------------------------------------------
# R1 host-sync hygiene
# ---------------------------------------------------------------------------


def test_r1_fires_on_item_read():
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(xs):
            s = jnp.sum(xs)
            return s.item()
        """,
        HostSyncRule(),
    )
    assert len(_hits(rep, "R1")) == 1
    assert ".item()" in rep.findings[0].message


def test_r1_fires_on_scalar_coercion_and_iteration():
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(xs):
            dev = jnp.cumsum(xs)
            n = int(dev[-1])
            for row in dev:
                pass
            if dev.any():
                n += 1
            return n
        """,
        HostSyncRule(),
    )
    msgs = " | ".join(f.message for f in _hits(rep, "R1"))
    assert len(_hits(rep, "R1")) == 3
    assert "int()" in msgs and "iterating" in msgs and "bool()" in msgs


def test_r1_suppression_honored_and_reason_required():
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(xs):
            s = jnp.sum(xs)
            return s.item()  # auronlint: disable=R1 -- test fixture reason
        """,
        HostSyncRule(),
    )
    assert not _hits(rep, "R1")
    (sup,) = _suppressed(rep, "R1")
    assert sup.reason == "test fixture reason"

    # a reasonless suppression is itself a finding
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(xs):
            s = jnp.sum(xs)
            return s.item()  # auronlint: disable=R1
        """,
        HostSyncRule(),
    )
    assert [f for f in rep.findings if f.rule == "lint.suppression"]


def test_r1_sync_point_declares_allowed_boundary():
    rep = _lint(
        """
        import jax
        import jax.numpy as jnp

        def f(xs):
            total = jax.device_get(jnp.sum(xs))  # auronlint: sync-point -- one count per batch
            return total
        """,
        HostSyncRule(),
    )
    assert not rep.findings  # declared sync points are not findings at all


def test_r1_clean_code_stays_clean():
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(xs):
            n = int(xs.shape[0])     # static metadata, not a sync
            out = jnp.zeros(n)
            cols = [xs, out]
            for c in cols:           # python container, not a device array
                pass
            return out
        """,
        HostSyncRule(),
    )
    assert not rep.findings


def test_r1_allowlisted_paths_are_exempt():
    src = """
    import jax.numpy as jnp

    def f(xs):
        return jnp.sum(xs).item()
    """
    rep = lint_source(textwrap.dedent(src),
                      "auron_tpu/exec/shuffle/writer.py", [HostSyncRule()])
    assert not rep.findings


# ---------------------------------------------------------------------------
# R2 retrace / compile-cache discipline
# ---------------------------------------------------------------------------


def test_r2_fires_on_undeclared_scalar_param():
    rep = _lint(
        """
        import jax

        @jax.jit
        def kernel(x, reverse=False):
            return x
        """,
        RetraceRule(),
    )
    assert len(_hits(rep, "R2")) == 1
    assert "static" in rep.findings[0].message


def test_r2_fires_on_unhashable_default_and_stale_static_name():
    rep = _lint(
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("renamed_flag",))
        def kernel(x, opts=[]):
            return x
        """,
        RetraceRule(),
    )
    msgs = " | ".join(f.message for f in _hits(rep, "R2"))
    assert "unhashable" in msgs and "stale" in msgs


def test_r2_fires_on_device_closure_capture():
    rep = _lint(
        """
        import jax
        import jax.numpy as jnp

        def outer(data):
            big = jnp.asarray(data)

            @jax.jit
            def inner(y):
                return y + big

            return inner
        """,
        RetraceRule(),
    )
    assert any("closes over device array 'big'" in f.message
               for f in _hits(rep, "R2"))


def test_r2_suppression_honored():
    rep = _lint(
        """
        import jax

        @jax.jit  # auronlint: disable=R2 -- traced once at import, fixture
        def kernel(x, reverse=False):
            return x
        """,
        RetraceRule(),
    )
    assert not _hits(rep, "R2") and _suppressed(rep, "R2")


def test_r2_clean_jit_site():
    rep = _lint(
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("reverse",))
        def kernel(x, reverse=False):
            return x
        """,
        RetraceRule(),
    )
    assert not rep.findings


# ---------------------------------------------------------------------------
# R3 shape-bucket discipline
# ---------------------------------------------------------------------------


def test_r3_fires_on_data_derived_shape():
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(xs: jnp.ndarray):
            n = int(jnp.sum(xs))
            return jnp.zeros(n)
        """,
        ShapeBucketRule(),
        rel="auron_tpu/ops/fixture.py",
    )
    assert len(_hits(rep, "R3")) == 1
    assert "data-dependent" in rep.findings[0].message


def test_r3_fires_on_item_shape():
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(counts):
            total = jnp.cumsum(counts)[-1].item()
            return jnp.empty(total)
        """,
        ShapeBucketRule(),
        rel="auron_tpu/ops/fixture.py",
    )
    assert len(_hits(rep, "R3")) == 1


def test_r3_suppression_honored():
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(xs: jnp.ndarray):
            n = int(jnp.sum(xs))
            return jnp.zeros(n)  # auronlint: disable=R3 -- fixture: bounded by test harness
        """,
        ShapeBucketRule(),
        rel="auron_tpu/ops/fixture.py",
    )
    assert not _hits(rep, "R3") and _suppressed(rep, "R3")


def test_r3_clean_capacity_shapes():
    rep = _lint(
        """
        import jax.numpy as jnp

        CAP = 4096

        def f(xs: jnp.ndarray):
            a = jnp.zeros(CAP)
            b = jnp.zeros(xs.shape[0])
            c = jnp.zeros((CAP, 2))
            return a, b, c
        """,
        ShapeBucketRule(),
        rel="auron_tpu/ops/fixture.py",
    )
    assert not rep.findings


# ---------------------------------------------------------------------------
# R4 registry completeness
# ---------------------------------------------------------------------------

_MINI_PROTO = """
syntax = "proto3";
message PhysicalPlanNode {
  oneof plan {
    ScanNode scan = 1;
    FilterNode filter = 2;
  }
}
message PhysicalExprNode {
  oneof expr {
    ColumnExpr column = 1;
  }
}
"""

_MINI_PLANNER_OK = """
def plan_from_proto(p):
    which = p.WhichOneof("plan")
    if which == "scan":
        return 1
    if which == "filter":
        return 2


def expr_from_proto(p):
    which = p.WhichOneof("expr")
    if which == "column":
        return 1
"""

_MINI_PLANNER_DRIFTED = """
def plan_from_proto(p):
    which = p.WhichOneof("plan")
    if which == "scan":
        return 1


def expr_from_proto(p):
    which = p.WhichOneof("expr")
    if which == "column":
        return 1
"""

_MINI_EXPLAIN = """
PLAN_DETAILS = {"scan": (), "filter": ()}
"""

_MINI_BUILDERS = """
def expr_to_proto(e):
    n = X()
    n.column.index = 0
    return n


def scan():
    return W(scan=1)


def filter_():
    return W(filter=1)
"""


def _write_mini_tree(tmp_path, planner_src, explain_src=_MINI_EXPLAIN):
    at = tmp_path / "auron_tpu"
    for d in ("proto", "plan", "convert", "functions"):
        (at / d).mkdir(parents=True, exist_ok=True)
    (at / "proto" / "plan.proto").write_text(_MINI_PROTO)
    (at / "plan" / "planner.py").write_text(planner_src)
    (at / "plan" / "explain.py").write_text(explain_src)
    (at / "plan" / "builders.py").write_text(_MINI_BUILDERS)
    (at / "convert" / "exprs.py").write_text("_FN_RENAME = {}\n")
    return str(tmp_path)


def test_r4_fires_on_registry_drift(tmp_path):
    root = _write_mini_tree(tmp_path, _MINI_PLANNER_DRIFTED)
    findings = list(RegistrySyncRule().check_tree(root))
    msgs = " | ".join(m for _, _, m in findings)
    assert "plan variant 'filter' has no plan_from_proto dispatch" in msgs


def test_r4_fires_on_missing_explain_entry(tmp_path):
    root = _write_mini_tree(
        tmp_path, _MINI_PLANNER_OK, explain_src='PLAN_DETAILS = {"scan": ()}\n'
    )
    findings = list(RegistrySyncRule().check_tree(root))
    msgs = " | ".join(m for _, _, m in findings)
    assert "plan variant 'filter' missing from PLAN_DETAILS" in msgs


def test_r4_clean_mini_tree(tmp_path):
    root = _write_mini_tree(tmp_path, _MINI_PLANNER_OK)
    findings = [
        (rel, line, m)
        for rel, line, m in RegistrySyncRule().check_tree(root)
        if "function registry unimportable" not in m
    ]
    assert findings == []


def test_r4_suppression_honored(tmp_path):
    from tools.auronlint.core import lint_paths

    drifted = _MINI_PLANNER_DRIFTED.replace(
        "def plan_from_proto(p):",
        "def plan_from_proto(p):  # auronlint: disable=R4 -- fixture: drift acknowledged",
    )
    root = _write_mini_tree(tmp_path, drifted)
    rep = lint_paths([os.path.join(root, "auron_tpu")], root,
                     [RegistrySyncRule()])
    r4 = [f for f in rep.findings if f.rule == "R4"
          and "plan_from_proto dispatch" in f.message]
    assert r4 and all(f.suppressed for f in r4)


def test_r4_real_tree_registries_in_lockstep():
    """The real repo's registries must be drift-free right now."""
    findings = [
        (rel, line, m)
        for rel, line, m in RegistrySyncRule().check_tree(REPO_ROOT)
        if "function registry unimportable" not in m
    ]
    assert findings == [], "\n".join(m for _, _, m in findings)


# ---------------------------------------------------------------------------
# R5 vectorization ban
# ---------------------------------------------------------------------------


def test_r5_fires_on_per_row_loop():
    rep = _lint(
        """
        def f(batch):
            out = []
            for i in range(batch.num_rows):
                out.append(i)
            return out
        """,
        VectorizeRule(),
        rel="auron_tpu/exec/fixture.py",
    )
    assert len(_hits(rep, "R5")) == 1


def test_r5_fires_on_capacity_wide_loop_over_device():
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(xs):
            vals = jnp.abs(xs)
            return [vals[i] for i in range(vals.shape[0])]
        """,
        VectorizeRule(),
        rel="auron_tpu/exec/fixture.py",
    )
    assert len(_hits(rep, "R5")) == 1


def test_r5_suppression_honored():
    rep = _lint(
        """
        def f(batch):
            for i in range(batch.num_rows):  # auronlint: disable=R5 -- fixture: per-run loop
                pass
        """,
        VectorizeRule(),
        rel="auron_tpu/exec/fixture.py",
    )
    assert not _hits(rep, "R5") and _suppressed(rep, "R5")


def test_r5_clean_loops_pass():
    rep = _lint(
        """
        def f(batches, cols):
            for b in batches:          # per-batch orchestration
                pass
            for c in cols:             # per-column
                pass
            for i in range(0, 100, 8):  # stepped chunk loop
                pass
        """,
        VectorizeRule(),
        rel="auron_tpu/exec/fixture.py",
    )
    assert not rep.findings


def test_r5_only_scopes_hot_paths():
    src = """
    def f(batch):
        for i in range(batch.num_rows):
            pass
    """
    rep = lint_source(textwrap.dedent(src), "auron_tpu/models/tpcds.py",
                      [VectorizeRule()])
    assert not rep.findings


# ---------------------------------------------------------------------------
# shared report schema
# ---------------------------------------------------------------------------


def test_report_json_schema_shared_with_jvm_lint():
    from tools import jvm_lint

    rep = run_tree(rules=[HostSyncRule()])
    doc = json.loads(rep.to_json())
    assert doc["schema"] == 1 and doc["tool"] == "auronlint"
    assert set(doc["counts"]) == {"total", "unsuppressed", "suppressed"}

    jrep = jvm_lint.run_report()
    jdoc = json.loads(jrep.to_json())
    assert jdoc["schema"] == 1 and jdoc["tool"] == "jvm_lint"
    assert set(jdoc["counts"]) == set(doc["counts"])
    # both serialize the same Finding fields
    f = Finding("t", "r", "p", 1, "m")
    keys = set(f.to_dict())
    for d in doc["findings"] + jdoc["findings"]:
        assert set(d) == keys
    assert Finding.from_dict(f.to_dict()) == f


# ---------------------------------------------------------------------------
# R6 sort-payload discipline
# ---------------------------------------------------------------------------


def test_r6_fires_on_column_scaling_operands():
    rep = _lint(
        """
        from jax import lax
        import jax.numpy as jnp

        def group(words, sel):
            dead = jnp.where(sel, 0, 1)
            iota = jnp.arange(sel.shape[0])
            operands = [dead, *words, iota]
            return lax.sort(tuple(operands), num_keys=len(operands) - 1)
        """,
        SortPayloadRule(),
        rel="auron_tpu/ops/fixture.py",
    )
    assert len(_hits(rep, "R6")) == 1
    assert "fingerprint" in rep.findings[0].message


def test_r6_fires_on_comprehension_and_impl_choice():
    rep = _lint(
        """
        from jax import lax
        from auron_tpu.ops import bitonic

        def group(cols, n_keys, cap):
            impl = bitonic.sort_impl_for(n_keys + 1, cap)
            return lax.sort(tuple(c for c in cols), num_keys=1)
        """,
        SortPayloadRule(),
        rel="auron_tpu/ops/fixture.py",
    )
    assert len(_hits(rep, "R6")) == 2


def test_r6_suppression_honored():
    rep = _lint(
        """
        from jax import lax

        def order_by(operands):
            ops = [*operands]
            return lax.sort(tuple(ops), num_keys=len(ops) - 1)  # auronlint: sort-payload -- ORDER BY sorts every user key by definition
        """,
        SortPayloadRule(),
        rel="auron_tpu/exec/fixture.py",
    )
    assert not _hits(rep, "R6")
    assert _suppressed(rep, "R6")


def test_r6_self_referential_reassignment_no_recursion():
    """`operands = operands + (iota,)` maps the name to an expression
    mentioning itself; the resolver must flag it as scaling (self-append
    grows the list), not recurse forever (regression: RecursionError
    aborted the whole lint run)."""
    rep = _lint(
        """
        from jax import lax

        def group(operands, n):
            operands = operands + (n,)
            return lax.sort(operands, num_keys=1)
        """,
        SortPayloadRule(),
        rel="auron_tpu/ops/fixture.py",
    )
    assert len(_hits(rep, "R6")) == 1


def test_r6_fixed_arity_sorts_pass():
    rep = _lint(
        """
        from jax import lax
        import jax.numpy as jnp

        def cluster(fp, sel):
            dead = jnp.where(sel, 0, 1)
            iota = jnp.arange(sel.shape[0])
            return lax.sort((dead, fp, iota), num_keys=3)
        """,
        SortPayloadRule(),
        rel="auron_tpu/ops/fixture.py",
    )
    assert not rep.findings


# ---------------------------------------------------------------------------
# the gate: whole tree, zero unsuppressed findings
# ---------------------------------------------------------------------------


def test_whole_tree_zero_unsuppressed_findings():
    rep = run_tree(rules=ALL_RULES)
    bad = rep.unsuppressed
    assert not bad, "\n" + "\n".join(f.render() for f in bad)
    # every suppression in the tree carries a reason
    assert all(f.reason for f in rep.suppressed)


# ---------------------------------------------------------------------------
# sync-point multiplicity budgets (syncbudget.py + perfcheck contract)
# ---------------------------------------------------------------------------


def test_r1_sync_point_budget_declares_boundary():
    rep = _lint(
        """
        import jax
        import jax.numpy as jnp

        def f(xs):
            total = jax.device_get(jnp.sum(xs))  # auronlint: sync-point(1/batch) -- one count per batch
            seed = jax.device_get(xs)  # auronlint: sync-point(2/task) -- stream seed read
            ext = jax.device_get(xs)  # auronlint: sync-point(call) -- external API contract
            return total, seed, ext
        """,
        HostSyncRule(),
    )
    assert not rep.findings  # budgeted sync points are clean declarations


def test_malformed_sync_point_budget_is_a_finding():
    rep = _lint(
        """
        import jax
        import jax.numpy as jnp

        def f(xs):
            a = jax.device_get(xs)  # auronlint: sync-point(weekly) -- nonsense unit
            b = jax.device_get(xs)  # auronlint: disable(1/batch)=R1 -- budget on a disable
            return a, b
        """,
        HostSyncRule(),
    )
    assert len([f for f in rep.findings if f.rule == "lint.suppression"]) == 2


def test_parse_sync_budget_grammar():
    from tools.auronlint.core import parse_sync_budget

    assert parse_sync_budget("1/batch") == (1, "batch")
    assert parse_sync_budget(" 8 / task ") == (8, "task")
    assert parse_sync_budget("call") == (0, "call")
    assert parse_sync_budget("1/flush") is None
    assert parse_sync_budget("batch") is None
    assert parse_sync_budget("") is None


def test_syncbudget_collects_engine_declarations():
    """Every sync-point in the live tree parses to a budget, and the known
    hot-path sites resolve through the runtime-site matcher."""
    from tools.auronlint.syncbudget import (
        budget_for_site, collect_sync_points, site_allowlisted,
    )

    points = collect_sync_points(REPO_ROOT)
    assert len(points) > 20
    assert all(p.unit in ("batch", "task", "call") for p in points)
    # the chain seed read (exec/joins/chain.py) must be task-budgeted now —
    # a per-batch budget there would mask the whole tentpole regressing
    chain_pts = [p for p in points if p.rel.endswith("joins/chain.py")]
    assert chain_pts and all(p.unit == "task" for p in chain_pts)
    hit = budget_for_site(f"{chain_pts[0].rel.split('auron_tpu/')[1]}:{chain_pts[0].line}", points)
    assert hit is not None and hit.unit == "task"
    assert site_allowlisted("exec/shuffle/writer.py:330")
    assert not site_allowlisted("exec/joins/chain.py:1")


# ---------------------------------------------------------------------------
# interprocedural substrate (callgraph + summaries)
# ---------------------------------------------------------------------------


def _graph(sources: dict):
    from tools.auronlint.callgraph import build_graph_from_sources

    return build_graph_from_sources(
        {rel: textwrap.dedent(src) for rel, src in sources.items()}
    )


def test_callgraph_cycle_and_recursion_guard():
    """Recursion, mutual recursion and a base-class cycle must not hang
    any traversal (the R6 resolver-cycle lesson, applied to the graph)."""
    g = _graph({
        "pkg/a.py": """
        class A(object):
            def ping(self):
                self.pong()

            def pong(self):
                self.ping()

        def rec(n):  # auronlint: thread-root(foreign) -- test fixture
            from auron_tpu.utils.config import active_conf
            rec(n - 1)
            return active_conf()
        """,
        "pkg/b.py": """
        from pkg.a import A

        class B(A):
            pass

        class C(B):
            def ping(self):
                super().ping()
        """,
    })
    # every analysis terminates and the recursive root sees itself
    states = g.foreign_conf_states()
    assert any(q.endswith("::rec") for q in states)
    g.roots_reaching()
    g.batch_depths()
    g.jit_reachable()


def test_summaries_batch_loop_and_iter_attribution():
    """`for b in child_stream(...)`: the stream-constructing call sits at
    the surrounding depth, the body runs per batch."""
    from tools.auronlint.core import SourceModule
    from tools.auronlint.summaries import summarize_module

    src = textwrap.dedent("""
    def run(self, ctx):
        prelude()
        for b in self.child_stream(0, 0, ctx):
            body(b)
        for x in range(10):
            bounded(x)
    """)
    ms = summarize_module(SourceModule("m.py", "m.py", src))
    fs = ms.functions["m.py::run"]
    depths = {c.name: c.batch_depth for c in fs.calls}
    assert depths["prelude"] == 0
    assert depths["child_stream"] == 0      # iter position: evaluated once
    assert depths["body"] == 1              # per pumped batch
    assert depths["bounded"] == 0           # plain bounded loop


# ---------------------------------------------------------------------------
# R7 thread-context escape
# ---------------------------------------------------------------------------


def _r7(sources: dict):
    from tools.auronlint.rules.threadctx import analyze

    return list(analyze(_graph(sources)))


def test_r7_fires_on_bare_active_conf_from_foreign_root():
    hits = _r7({
        "pkg/spill.py": """
        from pkg.conf import codec

        class Staging:
            def spill(self):  # auronlint: thread-root(foreign) -- test fixture
                return codec()
        """,
        "pkg/conf.py": """
        from auron_tpu.utils.config import active_conf

        def codec():
            return active_conf().get("spill.codec")
        """,
    })
    assert len(hits) == 1
    rel, line, msg = hits[0]
    assert rel == "pkg/conf.py" and "Staging.spill" in msg


def test_r7_quiet_when_conf_threaded_and_guarded():
    hits = _r7({
        "pkg/spill.py": """
        from pkg.conf import codec

        class Staging:
            def __init__(self, ctx):
                self.ctx = ctx

            def spill(self):  # auronlint: thread-root(foreign) -- test fixture
                return codec(conf=self.ctx.conf)
        """,
        "pkg/conf.py": """
        from auron_tpu.utils.config import active_conf

        def codec(conf=None):
            return (conf if conf is not None else active_conf()).get("x")
        """,
    })
    assert hits == []


def test_r7_guarded_fallback_fires_when_a_path_drops_conf():
    hits = _r7({
        "pkg/spill.py": """
        from pkg.conf import codec

        class Staging:
            def spill(self):  # auronlint: thread-root(foreign) -- test fixture
                return codec()
        """,
        "pkg/conf.py": """
        from auron_tpu.utils.config import active_conf

        def codec(conf=None):
            return (conf if conf is not None else active_conf()).get("x")
        """,
    })
    assert len(hits) == 1
    assert "WITHOUT passing conf" in hits[0][2]


def test_r7_conf_scoped_root_is_exempt():
    hits = _r7({
        "pkg/pump.py": """
        from auron_tpu.utils.config import active_conf

        def pump():  # auronlint: thread-root(conf-scoped) -- installs scope
            return active_conf()
        """,
    })
    assert hits == []


def test_r7_conf_scope_block_neutralizes_downstream():
    hits = _r7({
        "pkg/svc.py": """
        from auron_tpu.utils.config import active_conf, conf_scope

        def helper():
            return active_conf()

        def handle(conf):  # auronlint: thread-root(foreign) -- test fixture
            with conf_scope(conf):
                return helper()
        """,
    })
    assert hits == []


# ---------------------------------------------------------------------------
# R8 lock discipline
# ---------------------------------------------------------------------------


def _r8(sources: dict):
    from tools.auronlint.rules.lockguard import analyze

    return list(analyze(_graph(sources)))


_R8_SHARED = """
import threading

class Mgr:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def bump(self):
        {write}

class Consumer:
    def spill(self):  # auronlint: thread-root(foreign) -- test fixture
        shrink()

def shrink():
    m = Mgr()
    m.bump()

def pump():  # auronlint: thread-root(conf-scoped) -- test fixture
    m = Mgr()
    m.bump()

_GLOBAL_MGR = Mgr()
"""


def test_r8_fires_on_unlocked_cross_root_write():
    hits = _r8({"pkg/m.py": _R8_SHARED.format(write="self.n += 1")})
    assert len(hits) == 1
    assert "Mgr.n" in hits[0][2] and "2 thread roots" in hits[0][2]


def test_r8_quiet_under_lock_and_with_guarded_by():
    hits = _r8({"pkg/m.py": _R8_SHARED.format(
        write="with self._lock:\n            self.n += 1"
    )})
    assert hits == []
    # guarded-by declaration: the lock is held by the caller
    hits = _r8({"pkg/m.py": _R8_SHARED.format(
        write="self.n += 1  # auronlint: guarded-by(self._lock) -- callers hold it"
    )})
    assert hits == []


def test_r8_single_root_and_local_objects_are_quiet():
    # single root: per-task state needs no lock
    src = _R8_SHARED.format(write="self.n += 1").replace(
        "def spill(self):  # auronlint: thread-root(foreign) -- test fixture",
        "def spill(self):",
    )
    assert _r8({"pkg/m.py": src}) == []
    # function-local parser objects never escape -> never shared
    hits = _r8({"pkg/p.py": """
    class Cursor:
        def __init__(self, buf):
            self.pos = 0

        def take(self):
            self.pos += 1

    class Consumer:
        def spill(self):  # auronlint: thread-root(foreign) -- test fixture
            c = Cursor(b"x")
            c.take()

    def pump():  # auronlint: thread-root(conf-scoped) -- test fixture
        c = Cursor(b"y")
        c.take()
    """})
    assert hits == []


def test_r8_thread_owned_class_declaration_exempts_writes():
    """A class declared thread-owned (single-thread instance ownership —
    the serving-layer pattern: per-query operator instances reachable
    from both the pump root and the POST /sql handler root) is exempt."""
    src = _R8_SHARED.format(write="self.n += 1").replace(
        "class Mgr:",
        "# auronlint: thread-owned -- fixture: one instance per query, "
        "one driving thread\nclass Mgr:",
    )
    assert _r8({"pkg/m.py": src}) == []


def test_r8_detached_thread_owned_is_a_finding():
    """A thread-owned that anchors to a non-class line is inert — R8
    reports the detached declaration instead of silently dropping it,
    AND still reports the unexempted write."""
    src = _R8_SHARED.format(
        write="self.n += 1  # auronlint: thread-owned -- wrong anchor"
    )
    hits = _r8({"pkg/m.py": src})
    msgs = [h[2] for h in hits]
    assert any("does not anchor to a `class`" in m for m in msgs)
    assert any("Mgr.n" in m for m in msgs)


def test_thread_owned_rides_the_lint_ratchet():
    """thread-owned declarations count as declared debt (LINT_RATCHET)."""
    from tools.auronlint import ratchet

    assert "thread-owned" in ratchet.load(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# R9 static sync-budget verification
# ---------------------------------------------------------------------------


def _r9(sources: dict):
    from tools.auronlint.rules.budgetproof import analyze

    return list(analyze(_graph(sources)))


def test_r9_fires_on_call_budget_inside_batch_loop():
    hits = _r9({"pkg/op.py": """
    import jax

    def read(b):
        return jax.device_get(b)  # auronlint: sync-point(call) -- caller-owned

    class Op:
        def pump(self, ctx):  # auronlint: thread-root(conf-scoped) -- test fixture
            for b in self.child_stream(0, 0, ctx):
                read(b)
    """})
    assert len(hits) == 1
    assert "caller-owned" in hits[0][2]


def test_r9_fires_on_task_budget_in_local_batch_loop():
    hits = _r9({"pkg/op.py": """
    import jax

    class Op:
        def pump(self, ctx):  # auronlint: thread-root(conf-scoped) -- test fixture
            for b in self.child_stream(0, 0, ctx):
                n = jax.device_get(b)  # auronlint: sync-point(2/task) -- wrongly task-budgeted
    """})
    assert len(hits) == 1
    assert "task-bounded" in hits[0][2]


def test_r9_batch_budget_in_batch_loop_is_proven():
    hits = _r9({"pkg/op.py": """
    import jax

    class Op:
        def pump(self, ctx):  # auronlint: thread-root(conf-scoped) -- test fixture
            prep = jax.device_get(0)  # auronlint: sync-point(4/task) -- once per task
            for b in self.child_stream(0, 0, ctx):
                n = jax.device_get(b)  # auronlint: sync-point(1/batch) -- per batch by design
    """})
    assert hits == []


def test_r9_batch_budget_squared_fires():
    hits = _r9({"pkg/op.py": """
    import jax

    class Op:
        def pump(self, ctx):  # auronlint: thread-root(conf-scoped) -- test fixture
            for b in self.child_stream(0, 0, ctx):
                for c in self.child_stream(1, 0, ctx):
                    n = jax.device_get(c)  # auronlint: sync-point(1/batch) -- nested!
    """})
    assert len(hits) == 1
    assert "SQUARED" in hits[0][2]


# ---------------------------------------------------------------------------
# R10 jit-boundary purity
# ---------------------------------------------------------------------------


def _r10(sources: dict):
    from tools.auronlint.rules.jitpurity import analyze

    return list(analyze(_graph(sources)))


def test_r10_fires_on_conf_read_and_transfer_inside_jit():
    hits = _r10({"pkg/k.py": """
    import jax
    from auron_tpu.utils.config import active_conf

    @jax.jit
    def kernel(x):
        mode = active_conf().get("exec.mode")
        n = x.item()
        return x + 1
    """})
    msgs = " | ".join(h[2] for h in hits)
    assert len(hits) == 2
    assert "active_conf" in msgs and ".item()" in msgs


def test_r10_traced_helper_and_captured_mutation():
    hits = _r10({"pkg/k.py": """
    import jax
    from functools import partial

    _CACHE = {}

    def helper(x):
        _CACHE[1] = x
        return x

    @partial(jax.jit, static_argnames=("n",))
    def kernel(x, *, n):
        return helper(x) + n
    """})
    assert len(hits) == 1
    assert "subscript write to captured '_CACHE'" in hits[0][2]
    assert "traced via" in hits[0][2]


def test_r10_fires_on_obs_recorder_call_inside_jit():
    """Span-recording calls are host-side only: inside a jit they fire at
    trace time and never replay — every import shape must be caught."""
    hits = _r10({"pkg/k.py": """
    import jax
    from auron_tpu import obs
    from auron_tpu.obs import note_sync

    @jax.jit
    def kernel(x):
        obs.note_op("FilterExec", "elapsed_compute", 1)
        note_sync(1, False)
        return x + 1

    def helper(y):
        with obs.span("inner"):
            return y

    @jax.jit
    def kernel2(x):
        return helper(x)
    """})
    msgs = [h[2] for h in hits]
    assert len(hits) == 3, msgs
    assert all("host-side only" in m for m in msgs)
    assert any("'note_op'" in m for m in msgs)
    assert any("'note_sync'" in m for m in msgs)
    assert any("'span'" in m and "traced via" in m for m in msgs)


def test_r10_obs_call_outside_jit_quiet():
    hits = _r10({"pkg/k.py": """
    import jax
    from auron_tpu import obs

    @jax.jit
    def kernel(x):
        return x + 1

    def pump(x):
        with obs.span("task"):
            return kernel(x)
    """})
    assert not hits


def test_r10_pure_callback_target_not_traced_and_pure_fn_quiet():
    hits = _r10({"pkg/k.py": """
    import jax
    import numpy as np

    def _host_sort(x):
        out = []
        out.append(1)   # local list: fine
        return np.lexsort(x)

    @jax.jit
    def kernel(x):
        order = jax.pure_callback(_host_sort, x, x)
        return x[order]
    """})
    assert hits == []


# ---------------------------------------------------------------------------
# annotation grammar: thread-root / guarded-by
# ---------------------------------------------------------------------------


def test_thread_root_grammar_validation():
    rep = _lint(
        """
        def ok():  # auronlint: thread-root(foreign) -- net thread
            pass

        def bad_kind():  # auronlint: thread-root(weekly) -- nonsense
            pass

        def no_reason():  # auronlint: thread-root(foreign)
            pass
        """,
        HostSyncRule(),
    )
    sup = [f for f in rep.findings if f.rule == "lint.suppression"]
    # bad kind -> malformed argument; missing reason -> reasonless finding
    assert len(sup) == 2


def test_guarded_by_grammar_requires_lock_and_reason():
    rep = _lint(
        """
        class C:
            def f(self):
                self.n = 1  # auronlint: guarded-by(self._lock) -- caller holds
                self.m = 2  # auronlint: guarded-by -- no lock named
        """,
        HostSyncRule(),
    )
    sup = [f for f in rep.findings if f.rule == "lint.suppression"]
    assert len(sup) == 1  # the lockless guarded-by


def test_standalone_annotations_stack_to_next_code_line():
    """Two standalone declarations above one statement both anchor to the
    statement (the R9-over-sync-point interplay regression)."""
    from tools.auronlint.core import SourceModule

    src = textwrap.dedent("""
    import jax

    def f(xs):
        # auronlint: sync-point(call) -- declared boundary
        # auronlint: disable=R9 -- bounded by spill pressure
        return jax.device_get(xs)
    """)
    mod = SourceModule("m.py", "m.py", src)
    sync = [s for s in mod.suppressions if s.kind == "sync-point"][0]
    assert mod.anchor_line(sync) == 7  # the return line, not the comment
    assert mod.is_sync_point(7)
    assert mod.suppression_for("R9", 7) is not None


# ---------------------------------------------------------------------------
# lint ratchet
# ---------------------------------------------------------------------------


def test_lint_ratchet_seed_improve_regress(tmp_path):
    from tools.auronlint.ratchet import check_and_update, load, save
    from tools.auronlint.report import Finding, Report

    root = str(tmp_path)
    (tmp_path / "auron_tpu").mkdir()

    def report_with(n_suppressed):
        rep = Report(tool="auronlint")
        for i in range(n_suppressed):
            rep.findings.append(Finding(
                "auronlint", "R7", "auron_tpu/x.py", i + 1, "m",
                suppressed=True, reason="r",
            ))
        return rep

    # seed: first sighting records current debt
    assert check_and_update(report_with(3), root) == []
    assert load(root)["R7"] == 3
    # improvement: ratchet tightens automatically
    assert check_and_update(report_with(2), root) == []
    assert load(root)["R7"] == 2
    # regression: fails, file unchanged
    problems = check_and_update(report_with(5), root)
    assert problems and "R7" in problems[0]
    assert load(root)["R7"] == 2
    # explicit conscious raise is honored
    counts = load(root)
    counts["R7"] = 5
    save(root, counts)
    assert check_and_update(report_with(5), root) == []


def test_live_tree_ratchet_matches_current_debt():
    """LINT_RATCHET.json is committed and must match (or exceed) the
    tree's actual suppression counts — `make lint` enforces it."""
    from tools.auronlint.ratchet import current_counts, load
    from tools.auronlint import run_tree

    ratchet = load(REPO_ROOT)
    assert ratchet.get("sync-point", 0) > 20
    rep = run_tree()
    counts = current_counts(rep, REPO_ROOT)
    for key, n in counts.items():
        assert n <= ratchet.get(key, 0), (
            f"{key} debt {n} exceeds LINT_RATCHET.json "
            f"{ratchet.get(key, 0)} — make lint would fail"
        )


# ---------------------------------------------------------------------------
# SARIF emitter (shared by auronlint and jvm_lint)
# ---------------------------------------------------------------------------


def test_sarif_schema_shape():
    from tools.auronlint.report import Finding, Report

    rep = Report(tool="auronlint")
    rep.findings.append(Finding("auronlint", "R7", "a.py", 3, "boom"))
    rep.findings.append(Finding(
        "auronlint", "R9", "b.py", 0, "waived", suppressed=True, reason="why",
    ))
    doc = json.loads(rep.to_sarif())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "auronlint"
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} == {"R7", "R9"}
    res = run["results"]
    assert res[0]["locations"][0]["physicalLocation"]["region"]["startLine"] == 3
    # line 0 (file-level) clamps to 1 for SARIF validity
    assert res[1]["locations"][0]["physicalLocation"]["region"]["startLine"] == 1
    assert res[1]["suppressions"][0]["justification"] == "why"


def test_engine_thread_roots_are_declared():
    """The known thread entry points carry thread-root declarations — the
    interprocedural rules are only as good as their roots."""
    from tools.auronlint.callgraph import build_graph

    g = build_graph(REPO_ROOT)
    roots = {q.split("::", 1)[1]: k for q, k in g.roots.items()}
    assert roots.get("TaskRuntime._pump") == "conf-scoped"
    assert roots.get("_Handler.do_GET") == "foreign"
    assert roots.get("RssNetServer._handle") == "foreign"
    assert roots.get("_ShuffleStaging.spill") == "foreign"
    assert roots.get("_AggTableConsumer.spill") == "foreign"
    assert roots.get("_SorterConsumer.spill") == "foreign"
    assert roots.get("harvest") == "foreign"


def test_thread_root_standalone_above_decorated_def_registers():
    """The anchor of a standalone root above a decorated def is the
    decorator line — the root must still register (a silently-dropped
    root would disable reachability)."""
    hits = _r7({"pkg/svc.py": """
    from auron_tpu.utils.config import active_conf

    def deco(f):
        return f

    # auronlint: thread-root(foreign) -- handler thread
    @deco
    def handler():
        return worker()

    def worker():
        return active_conf()
    """})
    assert len(hits) == 1 and "handler" in hits[0][2]


def test_unanchored_thread_root_is_a_loud_finding():
    hits = _r7({"pkg/svc.py": """
    # auronlint: thread-root(foreign) -- floats above nothing
    X = 1
    """})
    assert len(hits) == 1
    assert "does not anchor to a function definition" in hits[0][2]


def test_lint_ratchet_failing_run_does_not_tighten(tmp_path):
    """A transiently-broken tree (suppressions detached -> unsuppressed
    findings) must not lower the debt ceiling."""
    from tools.auronlint.ratchet import check_and_update, load
    from tools.auronlint.report import Finding, Report

    root = str(tmp_path)
    (tmp_path / "auron_tpu").mkdir()

    def report(n_sup, n_unsup=0):
        rep = Report(tool="auronlint")
        for i in range(n_sup):
            rep.findings.append(Finding(
                "auronlint", "R7", "auron_tpu/x.py", i + 1, "m",
                suppressed=True, reason="r"))
        for i in range(n_unsup):
            rep.findings.append(Finding(
                "auronlint", "R7", "auron_tpu/x.py", 100 + i, "loose"))
        return rep

    check_and_update(report(5), root)
    assert load(root)["R7"] == 5
    # 3 suppressions detach: run FAILS (2 unsuppressed) — ceiling stays
    check_and_update(report(2, n_unsup=3), root)
    assert load(root)["R7"] == 5
    # restoring the suppressions is NOT a regression
    assert check_and_update(report(5), root) == []


def test_changed_mode_rejects_vacuous_and_ambiguous_invocations(capsys):
    from tools.auronlint.__main__ import main

    # tree-only rule selection under --changed would run zero rules
    assert main(["--changed", "--rules", "R7"]) == 2
    assert "vacuous" in capsys.readouterr().err
    # explicit paths would be silently ignored
    assert main(["--changed", "auron_tpu/exec"]) == 2
    assert "picks its own files" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# R2 fused-segment cache-key discipline (whole-stage fusion, docs/fusion.md)
# ---------------------------------------------------------------------------


def test_r2_fires_on_jit_wrapper_built_in_batch_loop():
    """A jit wrapper constructed per batch (or per segment instance inside
    the batch loop) starts an empty compile cache each iteration — the
    fused-segment retrace explosion the stage-program cache key exists to
    prevent."""
    rep = _lint(
        """
        import jax

        def drive(stream, fn):
            for b in stream:
                prog = jax.jit(fn)
                yield prog(b)
        """,
        RetraceRule(),
    )
    hits = _hits(rep, "R2")
    assert len(hits) == 1
    assert "inside a loop" in hits[0].message


def test_r2_fires_on_jit_decorated_def_in_loop():
    rep = _lint(
        """
        import jax

        def build(segments):
            out = []
            for seg in segments:
                @jax.jit
                def prog(dev):
                    return dev
                out.append(prog)
            return out
        """,
        RetraceRule(),
    )
    hits = _hits(rep, "R2")
    assert len(hits) == 1
    assert "defined inside a loop" in hits[0].message


def test_r2_module_level_stage_program_quiet():
    """The sanctioned pattern (plan/fusion.py): ONE module-level jit whose
    cache keys on static (schema, segment signature) args, dispatched from
    the batch loop — a call inside the loop is fine, construction is not."""
    rep = _lint(
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("steps",))
        def _stage_program(dev, *, steps):
            return dev

        def drive(stream, steps):
            for b in stream:
                yield _stage_program(b, steps=steps)
        """,
        RetraceRule(),
    )
    assert not _hits(rep, "R2")


# ---------------------------------------------------------------------------
# R10 teeth for fused-stage closures: the trace-safe machinery the stage
# compiler reuses must keep being checked for conf reads, host transfers
# and captured-state mutation through the whole traced closure
# ---------------------------------------------------------------------------


def test_r10_fused_stage_shaped_closure_conf_read():
    """A helper reachable from a stage-program-shaped jit entry reading
    active_conf(): the resolved knob would be baked into every cached
    (schema, signature, bucket) program."""
    hits = _r10({"pkg/stage.py": """
    import jax
    from functools import partial
    from auron_tpu.utils.config import active_conf

    def _eval_step(dev, steps):
        if active_conf().get("exec.fuse.enable") == "off":
            return dev
        return dev

    @partial(jax.jit, static_argnames=("steps",))
    def stage_program(dev, *, steps):
        return _eval_step(dev, steps)
    """})
    assert len(hits) == 1
    assert "active_conf" in hits[0][2] and "traced via" in hits[0][2]


def test_r10_fused_stage_shaped_closure_host_transfer_and_mutation():
    """Host transfers and compile-counter mutation inside the traced
    closure: both fire once at trace time only — the exact hazards the
    fusion pass keeps OUTSIDE the program (_note_dispatch runs host-side
    before dispatch)."""
    hits = _r10({"pkg/stage.py": """
    import jax
    from functools import partial

    _COMPILES = {}

    def _count_and_read(dev, sig):
        _COMPILES[sig] = _COMPILES.get(sig, 0) + 1
        return int(dev.sum().item())

    @partial(jax.jit, static_argnames=("sig",))
    def stage_program(dev, *, sig):
        n = _count_and_read(dev, sig)
        return dev[:n]
    """})
    msgs = " | ".join(h[2] for h in hits)
    assert len(hits) == 2
    assert ".item()" in msgs and "_COMPILES" in msgs


def test_r2_call_form_decorator_in_loop_reports_once():
    """@partial(jax.jit, ...) decorators are ast.Call nodes too — the
    loop scan must report the site exactly once (decorator branch), not
    double-count it through the bare-call branch."""
    rep = _lint(
        """
        import jax
        from functools import partial

        def build(segments):
            out = []
            for seg in segments:
                @partial(jax.jit, static_argnames=("n",))
                def prog(dev, *, n):
                    return dev
                out.append(prog)
            return out
        """,
        RetraceRule(),
    )
    hits = _hits(rep, "R2")
    assert len(hits) == 1
    assert "defined inside a loop" in hits[0].message


# ---------------------------------------------------------------------------
# the CFG layer (exception edges) — the R11/R12 substrate
# ---------------------------------------------------------------------------


def _cfg_of(src: str):
    import ast as _ast

    from tools.auronlint.cfg import build_cfg

    tree = _ast.parse(textwrap.dedent(src))
    fn = next(n for n in _ast.walk(tree) if isinstance(n, _ast.FunctionDef))
    return fn, build_cfg(fn)


def test_cfg_try_finally_covers_exception_edges():
    """A release in a finally is on EVERY path; without the finally the
    exception edge out of the loop leaks."""
    from tools.auronlint.cfg import leak_paths

    fn, cfg = _cfg_of(
        """
        def f():
            h = acquire()
            try:
                for x in stream():
                    use(h, x)
            finally:
                h.release()
        """
    )
    acq = next(n for n in cfg.stmt_nodes() if n.line == 3)
    rel = {n.idx for n in cfg.stmt_nodes() if n.line == 8}
    assert leak_paths(cfg, acq.idx, rel) == []

    fn, cfg = _cfg_of(
        """
        def f():
            h = acquire()
            for x in stream():
                use(h, x)
            h.release()
        """
    )
    acq = next(n for n in cfg.stmt_nodes() if n.line == 3)
    rel = {n.idx for n in cfg.stmt_nodes() if n.line == 6}
    assert leak_paths(cfg, acq.idx, rel) == ["an exception path"]


def test_cfg_narrow_handler_lets_exceptions_escape():
    """`except ValueError` does not stop a TypeError: the exception edge
    continues outward past narrow handlers, stops at broad ones."""
    from tools.auronlint.cfg import leak_paths

    fn, cfg = _cfg_of(
        """
        def f():
            h = acquire()
            try:
                use(h)
            except ValueError:
                h.release()
            h.release()
        """
    )
    acq = next(n for n in cfg.stmt_nodes() if n.line == 3)
    rel = {n.idx for n in cfg.stmt_nodes() if n.line in (7, 8)}
    assert leak_paths(cfg, acq.idx, rel) == ["an exception path"]

    fn, cfg = _cfg_of(
        """
        def f():
            h = acquire()
            try:
                use(h)
            except Exception:
                h.release()
            else:
                h.release()
        """
    )
    acq = next(n for n in cfg.stmt_nodes() if n.line == 3)
    rel = {n.idx for n in cfg.stmt_nodes() if n.line in (7, 9)}
    assert leak_paths(cfg, acq.idx, rel) == []


def test_cfg_return_through_finally_and_with_exit():
    """A return inside try/finally traverses the finally; a with-exit
    does not invent a path straight to the function exit."""
    from tools.auronlint.cfg import leak_paths

    fn, cfg = _cfg_of(
        """
        def f():
            h = acquire()
            with lock:
                use(h)
            h.release()
            return 1
        """
    )
    acq = next(n for n in cfg.stmt_nodes() if n.line == 3)
    rel = {n.idx for n in cfg.stmt_nodes() if n.line == 6}
    # the with body can raise -> exception leak; but NO normal-path leak
    # through the with-exit (the split-exit-node property)
    assert leak_paths(cfg, acq.idx, rel) == ["an exception path"]


# ---------------------------------------------------------------------------
# R11 resource lifecycle
# ---------------------------------------------------------------------------


def _r11(src: str, rel: str = "fixture.py"):
    from tools.auronlint.rules.lifecycle import ResourceLifecycleRule

    return _lint(src, ResourceLifecycleRule(), rel)


def test_r11_rediscovers_pr12_taskruntime_leak_shape():
    """The exact pre-fix PR-12 collect drain: a failing next_batch leaks
    the runtime (handle + pump thread). R11 must find it."""
    rep = _r11(
        """
        from auron_tpu.bridge import api

        def _execute(task_bytes):
            h = api.call_native(task_bytes)
            dfs = []
            while (rb := api.next_batch(h)) is not None:
                dfs.append(rb.to_pandas())
            api.finalize_native(h)
            return dfs
        """
    )
    hits = _hits(rep, "R11")
    assert len(hits) == 1
    assert "task runtime" in hits[0].message
    assert "an exception path" in hits[0].message


def test_r11_quiet_on_pr12_fixed_shape_and_context_manager():
    """The post-fix shape (finalize in the except unwind) and the
    native_task context manager are both clean."""
    rep = _r11(
        """
        from auron_tpu.bridge import api

        def _execute(task_bytes):
            h = api.call_native(task_bytes)
            dfs = []
            try:
                while (rb := api.next_batch(h)) is not None:
                    dfs.append(rb.to_pandas())
            except BaseException:
                try:
                    api.finalize_native(h)
                except Exception:
                    pass
                raise
            api.finalize_native(h)
            return dfs

        def _execute2(task_bytes):
            out = []
            with api.native_task(task_bytes) as h:
                while (rb := api.next_batch(h)) is not None:
                    out.append(rb)
            return out
        """
    )
    assert not _hits(rep, "R11")


def test_r11_spill_container_fire_and_fixed():
    rep = _r11(
        """
        from auron_tpu.memory.memmgr import make_spill

        def park(self, tbl):
            ds = make_spill(conf=self.conf)
            ds.write_table(tbl)
            self.parked.append(ds)
        """
    )
    hits = _hits(rep, "R11")
    assert len(hits) == 1 and "spill container" in hits[0].message

    rep = _r11(
        """
        from auron_tpu.memory.memmgr import make_spill

        def park(self, tbl):
            ds = make_spill(conf=self.conf)
            try:
                ds.write_table(tbl)
            except BaseException:
                ds.release()
                raise
            self.parked.append(ds)
        """
    )
    assert not _hits(rep, "R11")


def test_r11_mm_registration_fire_and_fixed():
    """register() before the protecting try leaks on a setup failure —
    the agg_exec shape this PR fixed."""
    rep = _r11(
        """
        def _execute(self, ctx):
            mm = get_manager()
            table = TableConsumer(self, ctx)
            mm.register(table)
            win = TransferWindow(ctx.conf)
            try:
                for b in stream():
                    table.add(b)
            finally:
                mm.unregister(table)
        """
    )
    hits = _hits(rep, "R11")
    assert len(hits) == 1 and "register -> unregister" in hits[0].message

    rep = _r11(
        """
        def _execute(self, ctx):
            mm = get_manager()
            table = TableConsumer(self, ctx)
            win = TransferWindow(ctx.conf)
            try:
                mm.register(table)
                for b in stream():
                    table.add(b)
            finally:
                mm.unregister(table)
        """
    )
    assert not _hits(rep, "R11")


def test_r11_conditional_release_idiom_is_quiet():
    """`if guard is not None: mm.unregister(guard)` in the finally is
    the dynamic ownership check — not a leak path around the release."""
    rep = _r11(
        """
        def _execute(self, ctx):
            mm = get_manager()
            guard = None
            try:
                build = self._build(ctx)
                guard = BuildGuard(self, build)
                mm.register(guard, spillable=False)
                for b in stream():
                    probe(build, b)
            finally:
                if guard is not None:
                    mm.unregister(guard)
        """
    )
    assert not _hits(rep, "R11")


def test_r11_inflight_event_stuck_waiter_fire_and_fixed():
    """The PR-12 upload-event class: a builder that fails before set()
    wedges every waiter. Storing the event does NOT transfer ownership;
    waiting on it proves the waiter side."""
    rep = _r11(
        """
        import threading

        def _table_view(self, key):
            with self._res_lock:
                ent = self._res_cache.get(key)
                if ent is None:
                    ent = self._res_cache[key] = {"done": threading.Event(), "val": None}
                    builder = True
                else:
                    builder = False
            if builder:
                ent["val"] = self._build(key)
                ent["done"].set()
                return ent["val"]
            ent["done"].wait()
            return ent["val"]
        """
    )
    hits = _hits(rep, "R11")
    assert len(hits) == 1 and "in-flight event" in hits[0].message

    rep = _r11(
        """
        import threading

        def _table_view(self, key):
            with self._res_lock:
                ent = self._res_cache.get(key)
                if ent is None:
                    ent = self._res_cache[key] = {"done": threading.Event(), "val": None}
                    builder = True
                else:
                    builder = False
            if builder:
                try:
                    ent["val"] = self._build(key)
                finally:
                    ent["done"].set()
                return ent["val"]
            ent["done"].wait()
            return ent["val"]
        """
    )
    assert not _hits(rep, "R11")


def test_r11_owned_by_declaration_suppresses_with_reason():
    rep = _r11(
        """
        from auron_tpu.memory.memmgr import make_spill

        def park(self, tbl):
            ds = make_spill(conf=self.conf)  # auronlint: owned-by(self.parked) -- drained and released by drain()
            ds.write_table(tbl)
            self.parked.append(ds)
        """
    )
    assert not _hits(rep, "R11")
    (sup,) = _suppressed(rep, "R11")
    assert "drained and released" in sup.reason


def test_r11_owned_by_requires_holder_argument():
    rep = _r11(
        """
        from auron_tpu.memory.memmgr import make_spill

        def park(self, tbl):
            ds = make_spill(conf=self.conf)  # auronlint: owned-by -- someone releases it
            ds.write_table(tbl)
        """
    )
    assert [f for f in rep.findings if f.rule == "lint.suppression"]


def test_r11_normal_path_leak_reported():
    """A release only in the except arm misses the normal path."""
    rep = _r11(
        """
        from auron_tpu.memory.memmgr import make_spill

        def park(self, tbl):
            ds = make_spill(conf=self.conf)
            try:
                ds.write_table(tbl)
            except Exception:
                ds.release()
                raise
            return None
        """
    )
    hits = _hits(rep, "R11")
    assert len(hits) == 1 and "a normal path" in hits[0].message


def test_r11_transfers_end_tracking():
    """Returning, yielding, storing and with-managing all hand the
    resource off — no finding."""
    rep = _r11(
        """
        from auron_tpu.memory.memmgr import make_spill
        from auron_tpu import obs

        def make(self):
            ds = make_spill(conf=self.conf)
            return ds

        def stash(self):
            ds = make_spill(conf=self.conf)
            self._spill = ds

        def managed(self):
            sp = obs.span("x")
            with sp:
                work()
        """
    )
    assert not _hits(rep, "R11")


def test_r11_snapshot_temp_fire():
    """A checkpoint temp created but neither published (os.replace) nor
    torn down (os.unlink) on the exception path is a half-written file a
    future restore could mistake for progress."""
    rep = _r11(
        """
        import os
        from auron_tpu.stream.checkpoint import snapshot_tmp

        def write_one(final, data):
            tmp = snapshot_tmp(final)
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, final)
        """
    )
    hits = _hits(rep, "R11")
    assert len(hits) == 1
    assert "checkpoint temp file" in hits[0].message


def test_r11_snapshot_temp_quiet_on_replace_or_unlink_unwind():
    """The shipped shape — publish on success, unlink on the unwind —
    releases the temp on every path."""
    rep = _r11(
        """
        import os
        from auron_tpu.stream.checkpoint import snapshot_tmp

        def write_one(final, data):
            tmp = snapshot_tmp(final)
            try:
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, final)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        """
    )
    assert not _hits(rep, "R11")


# ---------------------------------------------------------------------------
# R12 error-path discipline
# ---------------------------------------------------------------------------


def _r12(sources: dict):
    from tools.auronlint.rules.errorpath import analyze

    return list(analyze(_graph(sources)))


def test_r12_fires_on_swallowed_broad_in_foreign_reachable():
    finds = _r12({
        "pkg/svc.py": """
        class Svc:
            def handle(self):  # auronlint: thread-root(foreign) -- test fixture
                self.work()

            def work(self):
                try:
                    step()
                except Exception:
                    pass
        """,
    })
    assert len([f for f in finds if "swallowed" in f[2]]) == 1


def test_r12_narrow_swallow_and_unreachable_are_quiet():
    finds = _r12({
        "pkg/svc.py": """
        class Svc:
            def handle(self):  # auronlint: thread-root(foreign) -- test fixture
                self.work()

            def work(self):
                try:
                    self.sock.close()
                except OSError:
                    pass

        def unreachable_helper():
            try:
                step()
            except Exception:
                pass
        """,
    })
    assert not [f for f in finds if "swallowed" in f[2]]


def test_r12_thread_target_escape_fire_and_routed():
    finds = _r12({
        "pkg/daemon.py": """
        import threading

        class Daemon:
            def start(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()

            def _loop(self):
                while self.running():
                    self.step()
        """,
    })
    assert len([f for f in finds if "kills its thread" in f[2]]) == 1

    finds = _r12({
        "pkg/daemon.py": """
        import threading

        class Daemon:
            def start(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()

            def _loop(self):
                try:
                    while self.running():
                        self.step()
                except BaseException as e:
                    self._error = e
        """,
    })
    assert not [f for f in finds if "kills its thread" in f[2]]


def test_r12_http_handler_entry_checked():
    finds = _r12({
        "pkg/http.py": """
        from http.server import BaseHTTPRequestHandler

        class H(BaseHTTPRequestHandler):
            def do_GET(self):
                payload = self.render()
                self.wfile.write(payload)
        """,
    })
    assert len([f for f in finds if "handler entry" in f[2]]) == 1


def test_r12_manual_lock_release_skipped_on_raise():
    finds = _r12({
        "pkg/locky.py": """
        class T:
            def handle(self):  # auronlint: thread-root(foreign) -- test fixture
                self.work()

            def work(self):
                self._lock.acquire()
                step()
                self._lock.release()
        """,
    })
    assert len([f for f in finds if "not released" in f[2]]) == 1

    finds = _r12({
        "pkg/locky.py": """
        class T:
            def handle(self):  # auronlint: thread-root(foreign) -- test fixture
                self.work()

            def work(self):
                self._lock.acquire()
                try:
                    step()
                finally:
                    self._lock.release()
        """,
    })
    assert not [f for f in finds if "not released" in f[2]]


def test_r12_annotated_swallow_rides_suppression():
    """A reasoned disable=R12 keeps the deliberate swallow out of the
    failing set (and in the ratchet's suppressed counts)."""
    from tools.auronlint.core import SourceModule, lint_paths
    import os as _os
    import tempfile as _tf

    src = textwrap.dedent("""
        class Svc:
            def handle(self):  # auronlint: thread-root(foreign) -- test fixture
                self.work()

            def work(self):
                try:
                    step()
                except Exception:  # auronlint: disable=R12 -- probe isolation: fallthrough is the contract
                    pass
    """)
    with _tf.TemporaryDirectory() as td:
        pkg = _os.path.join(td, "auron_tpu")
        _os.makedirs(pkg)
        path = _os.path.join(pkg, "svc.py")
        with open(path, "w") as f:
            f.write(src)
        from tools.auronlint.rules.errorpath import ErrorPathRule

        rep = lint_paths([pkg], td, [ErrorPathRule()])
        assert not [f for f in rep.unsuppressed if f.rule == "R12"]
        assert [f for f in rep.suppressed if f.rule == "R12"]


# ---------------------------------------------------------------------------
# R13 retrace stability
# ---------------------------------------------------------------------------


def _r13(sources: dict):
    from tools.auronlint.rules.retracestab import analyze

    return analyze(_graph(sources))


def test_r13_fires_on_lambda_float_rowcount_and_identity_keys():
    finds, stats = _r13({
        "pkg/kern.py": """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("emit", "scale", "n", "cfg"))
        def prog(dev, *, emit, scale, n, cfg):
            return dev
        """,
        "pkg/use.py": """
        from pkg.kern import prog

        class Driver:
            def run(self, b):
                return prog(b.device, emit=lambda x: x, scale=0.5,
                            n=b.num_rows(), cfg=FreshConfig())
        """,
    })
    msgs = " | ".join(m for _, _, m in finds)
    assert "lambda" in msgs
    assert "float literal" in msgs
    assert "row count" in msgs
    assert "per-call object identity" in msgs
    assert stats["proved"] == 0 and stats["covered"] == 1


def test_r13_finite_keys_prove_and_shape_only_entries_count():
    finds, stats = _r13({
        "pkg/kern.py": """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("steps", "bucket", "flags"))
        def prog(dev, *, steps, bucket, flags):
            return dev

        @jax.jit
        def shape_only(dev):
            return dev
        """,
        "pkg/use.py": """
        from pkg.kern import prog

        def run(b, conf):
            steps = tuple(sig for sig in b.schema)
            return prog(b.device, steps=steps,
                        bucket=compaction_bucket(b.capacity),
                        flags=conf.get("exec.knob"))
        """,
    })
    assert not finds
    assert stats["covered"] == 2 and stats["proved"] == 2


def test_r13_closure_over_rebound_module_state_fires():
    finds, stats = _r13({
        "pkg/kern.py": """
        import jax

        _MODE = "a"
        _MODE = "b"

        @jax.jit
        def prog(dev):
            return dev if _MODE == "a" else dev + 1
        """,
    })
    assert len([m for _, _, m in finds if "rebound" in m]) == 1
    assert stats["proved"] == 0


def test_r13_live_tree_coverage_and_floors():
    """Vacuity teeth: the analysis must see every module-level jit entry
    in plan/fusion.py and exec/ that an independent AST scan finds, and
    the proved floor must hold on the live tree."""
    import ast as _ast

    from tools.auronlint.callgraph import build_graph
    from tools.auronlint.rules.retracestab import (
        R13_MIN_COVERED, R13_MIN_PROVED, _JIT_RE, analyze,
    )

    finds, stats = analyze(build_graph(REPO_ROOT))
    assert stats["covered"] >= R13_MIN_COVERED
    assert stats["proved"] >= R13_MIN_PROVED

    # independent discovery: decorated module-level defs + module-level
    # jit-wrapped assigns under plan/fusion.py and exec/
    expected = set()
    for rel in list(stats["entries"]):
        pass
    import os as _os

    for base, _, files in _os.walk(_os.path.join(REPO_ROOT, "auron_tpu")):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = _os.path.join(base, fname)
            rel = _os.path.relpath(path, REPO_ROOT).replace("\\", "/")
            if not (rel == "auron_tpu/plan/fusion.py"
                    or rel.startswith("auron_tpu/exec/")):
                continue
            tree = _ast.parse(open(path).read())
            for node in tree.body:
                if isinstance(node, _ast.FunctionDef) and any(
                    _JIT_RE.search(_ast.unparse(d))
                    for d in node.decorator_list
                ):
                    expected.add(f"{rel}::{node.name}")
                elif isinstance(node, _ast.Assign) and isinstance(
                    node.value, _ast.Call
                ) and _JIT_RE.search(_ast.unparse(node.value.func)) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], _ast.Name):
                    expected.add(f"{rel}::{node.targets[0].id}")
    assert expected, "independent scan found no jit entries — scan broken"
    missing = expected - set(stats["entries"])
    assert not missing, f"R13 lost sight of jit entries: {sorted(missing)}"


def test_r13_vacuity_floor_fails_loudly(monkeypatch):
    from tools.auronlint.rules import retracestab

    rule = retracestab.RetraceStabilityRule()
    monkeypatch.setattr(retracestab, "R13_MIN_COVERED", 10_000)
    finds = list(rule.check_tree(REPO_ROOT))
    assert any("vacuity" in m for _, _, m in finds)


# ---------------------------------------------------------------------------
# incremental parse/summary cache (tools/auronlint/filecache.py)
# ---------------------------------------------------------------------------

_CACHE_FIXTURE = """
import jax.numpy as jnp

def f(xs):
    s = jnp.sum(xs)
    return s.item()
"""


def _fresh_cache(root):
    """A FileCache as a NEW process would see it: drop the in-process
    instance so the next lookup must come from disk."""
    from tools.auronlint import filecache

    filecache._caches.pop(root, None)
    return filecache.file_cache(root)


def test_filecache_warm_run_replays_identical_findings(tmp_path):
    from tools.auronlint import filecache

    root = str(tmp_path)
    pkg = tmp_path / "auron_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(textwrap.dedent(_CACHE_FIXTURE))
    cold = run_tree(root)
    assert _hits(cold, "R1"), "fixture should fire R1"
    assert os.path.exists(os.path.join(root, filecache.CACHE_BASENAME))
    fc = _fresh_cache(root)
    warm = run_tree(root)
    assert fc.hits >= 1 and fc.misses == 0
    assert warm.to_json() == cold.to_json()


def test_filecache_invalidates_on_file_edit(tmp_path):
    root = str(tmp_path)
    pkg = tmp_path / "auron_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(textwrap.dedent(_CACHE_FIXTURE))
    cold = run_tree(root)
    assert len(_hits(cold, "R1")) == 1
    # the edit adds a second violation; a stale cache would still say 1
    (pkg / "mod.py").write_text(textwrap.dedent(_CACHE_FIXTURE) + textwrap.dedent("""
def g(xs):
    return jnp.max(xs).item()
"""))
    _fresh_cache(root)
    warm = run_tree(root)
    assert len(_hits(warm, "R1")) == 2


def test_filecache_invalidates_on_mid_process_rewrite(tmp_path):
    """The in-process memo must re-validate signatures too: a fixture
    tree rewritten between two run_tree calls in ONE process (exactly
    what this test does) must not serve stale summaries."""
    root = str(tmp_path)
    pkg = tmp_path / "auron_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(textwrap.dedent(_CACHE_FIXTURE))
    assert len(_hits(run_tree(root), "R1")) == 1
    (pkg / "mod.py").write_text(
        "def clean():\n    return 1\n")
    assert len(_hits(run_tree(root), "R1")) == 0


def test_filecache_invalidates_on_linter_source_change(tmp_path, monkeypatch):
    from tools.auronlint import filecache

    root = str(tmp_path)
    pkg = tmp_path / "auron_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(textwrap.dedent(_CACHE_FIXTURE))
    run_tree(root)
    # a rule edit changes the package digest: every entry must go cold
    monkeypatch.setattr(filecache, "_tools_digest", lambda: "rule-edited")
    fc = _fresh_cache(root)
    run_tree(root)
    assert fc.hits == 0 and fc.misses >= 1


def test_filecache_corruption_and_disable_are_nonfatal(tmp_path, monkeypatch):
    from tools.auronlint import filecache

    root = str(tmp_path)
    pkg = tmp_path / "auron_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(textwrap.dedent(_CACHE_FIXTURE))
    cache_path = tmp_path / filecache.CACHE_BASENAME
    cache_path.write_bytes(b"\x80garbage, not a pickle")
    _fresh_cache(root)
    rep = run_tree(root)  # advisory: corruption = cold run, not a crash
    assert len(_hits(rep, "R1")) == 1
    # temp + os.replace left no partial files behind
    strays = [p for p in os.listdir(root)
              if p.startswith(filecache.CACHE_BASENAME + ".")]
    assert not strays
    # and the rewritten cache is loadable again
    fc = _fresh_cache(root)
    run_tree(root)
    assert fc.hits >= 1

    other = tmp_path / "disabled"
    (other / "auron_tpu").mkdir(parents=True)
    (other / "auron_tpu" / "mod.py").write_text(
        textwrap.dedent(_CACHE_FIXTURE))
    monkeypatch.setenv("AURONLINT_CACHE", "0")
    rep = run_tree(str(other))
    assert len(_hits(rep, "R1")) == 1
    assert not os.path.exists(other / filecache.CACHE_BASENAME)


def test_sarif_out_artifact_and_time_budget(tmp_path, capsys):
    from tools.auronlint.__main__ import main

    target = os.path.join(REPO_ROOT, "auron_tpu", "utils", "httpsvc.py")
    out = tmp_path / "artifacts" / "lint.sarif"  # dir must be created
    assert main([target, "--sarif-out", str(out)]) == 0
    data = json.loads(out.read_text())
    assert data["version"] == "2.1.0"
    capsys.readouterr()

    # a zero budget always trips: exit 1, loud stderr, artifact STILL
    # written (CI wants the report most when the gate fails)
    out2 = tmp_path / "b.sarif"
    assert main([target, "--sarif-out", str(out2), "--time-budget", "0"]) == 1
    assert json.loads(out2.read_text())["version"] == "2.1.0"
    assert "exceeded the --time-budget" in capsys.readouterr().err
    strays = [p for p in os.listdir(tmp_path) if p.startswith("b.sarif.")]
    assert not strays  # temp + os.replace left nothing behind


# ---------------------------------------------------------------------------
# R14 config-knob contract
# ---------------------------------------------------------------------------


_R14_CONFIG = """
def str_conf(key, default=None, doc=""):
    return (key, default, doc)

def resolve_tri(mode, auto):
    if mode == "on":
        return True
    if mode == "off":
        return False
    return auto

FUSE_MODE = str_conf("exec.fuse.mode", "auto",
                     doc="on | off | auto = on when compacting")
PARTS = str_conf("sql.parts", "8", doc="partition count")
DEAD = str_conf("sql.dead", "x", doc="declared, read by nobody")
"""


def _r14(sources: dict):
    from tools.auronlint.rules.confcontract import analyze

    return analyze(
        _graph(sources),
        anchor_rels=("pkg/lowering.py",),
        digest_rel="pkg/digest.py",
    )


def test_r14_fires_on_raw_get_dead_knob_and_tri_bypass():
    finds, stats = _r14({
        "pkg/config.py": _R14_CONFIG,
        "pkg/digest.py": """
        from pkg.config import PARTS

        PLAN_KNOBS = (PARTS,)
        """,
        "pkg/lowering.py": """
        from pkg.config import FUSE_MODE, PARTS

        def lower(conf):
            legacy = conf.get("sql.raw.key")
            mode = conf.get(FUSE_MODE)
            if mode == "off":
                return None
            return conf.get(PARTS)
        """,
    })
    msgs = " | ".join(m for _, _, m in finds)
    assert "raw-string conf read conf.get('sql.raw.key')" in msgs
    assert "knob DEAD ('sql.dead') is declared but never read" in msgs
    assert "tri-state knob FUSE_MODE read without resolve_tri" in msgs
    # the teeth: FUSE_MODE is read during lowering but not cache-keyed
    assert "plan-affecting knob FUSE_MODE" in msgs
    assert "MISSING from sql/digest.py PLAN_KNOBS" in msgs
    assert stats["declared"] == 3 and stats["tri"] == 1
    assert stats["plan_proved"] == 1  # PARTS is keyed; FUSE_MODE is not


def test_r14_contract_clean_when_keyed_and_resolved():
    finds, stats = _r14({
        "pkg/config.py": _R14_CONFIG.replace(
            'DEAD = str_conf("sql.dead", "x", doc="declared, read by nobody")\n',
            "",
        ),
        "pkg/digest.py": """
        from pkg.config import FUSE_MODE, PARTS

        PLAN_KNOBS = (PARTS, FUSE_MODE)
        """,
        "pkg/lowering.py": """
        from pkg.config import FUSE_MODE, PARTS, resolve_tri

        def lower(conf):
            fuse = resolve_tri(conf.get(FUSE_MODE), True)
            parts = conf.get(PARTS)
            return parts if fuse else None
        """,
    })
    assert finds == []
    assert stats["plan_proved"] == 2


def test_r14_knob_object_passed_to_helper_still_counts_as_plan_read():
    """The knob need not feed conf.get() in the anchor module itself —
    loading the knob OBJECT inside the closure (passing it down to a
    helper that reads it) is the same contract obligation."""
    finds, _stats = _r14({
        "pkg/config.py": _R14_CONFIG.replace(
            'DEAD = str_conf("sql.dead", "x", doc="declared, read by nobody")\n',
            "",
        ),
        "pkg/digest.py": """
        from pkg.config import PARTS

        PLAN_KNOBS = (PARTS,)
        """,
        "pkg/helper.py": """
        def read_knob(conf, knob):
            return conf.get(knob)
        """,
        "pkg/lowering.py": """
        from pkg.config import FUSE_MODE, PARTS, resolve_tri
        from pkg.helper import read_knob

        def lower(conf):
            fuse = resolve_tri(read_knob(conf, FUSE_MODE), True)
            return read_knob(conf, PARTS) if fuse else None
        """,
    })
    assert any("plan-affecting knob FUSE_MODE" in m for _, _, m in finds)


def test_r14_declaration_suppression_honored_in_tree(tmp_path):
    """Reference-parity debt: a reasoned disable=R14 on the declaration
    line keeps a never-read knob out of the gate (and in the ratchet)."""
    from tools.auronlint.core import lint_paths
    from tools.auronlint.rules.confcontract import ConfContractRule

    at = tmp_path / "auron_tpu" / "utils"
    at.mkdir(parents=True)
    (tmp_path / "auron_tpu" / "__init__.py").write_text("")
    (at / "config.py").write_text(textwrap.dedent("""
        def str_conf(key, default=None, doc=""):
            return (key, default, doc)

        PARITY = str_conf("upstream.parity.knob", "x")  # auronlint: disable=R14 -- upstream-parity surface, fixture
        LOUD = str_conf("dead.loud.knob", "y")
    """))
    rep = lint_paths([os.path.join(str(tmp_path), "auron_tpu")],
                     str(tmp_path), [ConfContractRule()])
    dead = [f for f in rep.findings if "declared but never read" in f.message]
    assert {f.suppressed for f in dead} == {True, False}
    sup = next(f for f in dead if f.suppressed)
    assert "PARITY" in sup.message and "upstream-parity" in (sup.reason or "")


def test_r14_vacuity_floors_fail_loudly(monkeypatch):
    from tools.auronlint.rules import confcontract

    rule = confcontract.ConfContractRule()
    monkeypatch.setattr(confcontract, "R14_MIN_DECLARED", 10_000)
    finds = list(rule.check_tree(REPO_ROOT))
    assert any("R14 vacuity check" in m for _, _, m in finds)

    rule2 = confcontract.ConfContractRule()
    monkeypatch.setattr(confcontract, "R14_MIN_DECLARED", 1)
    monkeypatch.setattr(confcontract, "R14_MIN_PLAN_PROVED", 10_000)
    finds2 = list(rule2.check_tree(REPO_ROOT))
    assert any("plan-path knobs proved" in m for _, _, m in finds2)


def test_r14_live_tree_proves_fuse_knobs_into_plan_knobs():
    """The serving-cache teeth on the real tree: the closure from
    lowering/fusion must reach the fuse family and prove every
    plan-affecting knob into PLAN_KNOBS (this PR's live findings — the
    FUSE_*/HOST_SORT_MODE cache-split bugs — stay fixed)."""
    from tools.auronlint.callgraph import build_graph
    from tools.auronlint.rules.confcontract import (
        R14_MIN_DECLARED, R14_MIN_PLAN_PROVED, analyze,
    )

    _finds, stats = analyze(build_graph(REPO_ROOT))
    assert stats["declared"] >= R14_MIN_DECLARED
    assert stats["plan_proved"] >= R14_MIN_PLAN_PROVED
    assert {"FUSE_ENABLE", "HOST_SORT_MODE"} <= set(stats["plan_read"])
    assert set(stats["plan_read"]) <= set(stats["plan_knobs"])


def test_config_doc_drift_gate_detects_stale_doc(monkeypatch, tmp_path):
    """The generated-artifact gate: byte-level doc drift is a finding,
    and the clean regen is drift-free."""
    from tools.auronlint.rules.confcontract import config_doc_drift
    from tools.gen_config_doc import regenerate

    assert list(config_doc_drift(REPO_ROOT)) == []

    doc = os.path.join(REPO_ROOT, "docs", "CONFIG.md")
    with open(doc, encoding="utf-8") as fh:
        original = fh.read()
    try:
        with open(doc, "a", encoding="utf-8") as fh:
            fh.write("| fake.knob | x | drift |\n")
        finds = list(config_doc_drift(REPO_ROOT))
        assert any("stale" in m for _, _, m in finds)
    finally:
        with open(doc, "w", encoding="utf-8") as fh:
            fh.write(original)
    # regenerate() is idempotent on a clean tree
    regenerate()
    with open(doc, encoding="utf-8") as fh:
        assert fh.read() == original


# ---------------------------------------------------------------------------
# R15 FFI/ABI lockstep
# ---------------------------------------------------------------------------


_MINI_NATIVE_CPP = """
#include <cstdint>

extern "C" {

static int32_t private_helper(int32_t a) { return a; }

int32_t add_i32(const int32_t* xs, int64_t n) { return 0; }

void scale_f64(double* xs, int64_t n, double f) { }

uint64_t helper_sym(int32_t a) { return 0; }

}  // extern "C"
"""

_MINI_NATIVE_PY_DRIFTED = """
import ctypes


def _bind(lib):
    lib.add_i32.argtypes = [ctypes.POINTER(ctypes.c_int32)]
    lib.add_i32.restype = ctypes.c_int32
    lib.scale_f64.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64, ctypes.c_double,
    ]
    lib.gone_sym.argtypes = [ctypes.c_int32]
    lib.gone_sym.restype = ctypes.c_int32


def add_i32_host(xs):
    return 0


def scale_f64_host(xs, f):
    return xs
"""

_MINI_NATIVE_PY_OK = """
import ctypes

# auronlint: unbound-native(helper_sym) -- fixture: debug-only export, no engine caller


def _bind(lib):
    lib.add_i32.argtypes = [ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
    lib.add_i32.restype = ctypes.c_int32
    lib.scale_f64.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64, ctypes.c_double,
    ]
    lib.scale_f64.restype = None


def add_i32_host(xs):
    return 0


def scale_f64_host(xs, f):
    return xs
"""


def _write_native_tree(tmp_path, py_src, cpp_src=_MINI_NATIVE_CPP):
    (tmp_path / "native").mkdir()
    (tmp_path / "auron_tpu").mkdir()
    (tmp_path / "native" / "auron_native.cpp").write_text(cpp_src)
    (tmp_path / "auron_tpu" / "native.py").write_text(py_src)
    return str(tmp_path)


def _r15(root):
    from tools.auronlint.rules.ffilockstep import analyze

    return analyze(root)


def test_r15_fires_on_arity_restype_unbound_and_stale(tmp_path):
    finds, stats = _r15(_write_native_tree(tmp_path, _MINI_NATIVE_PY_DRIFTED))
    msgs = " | ".join(m for _, _, m in finds)
    assert "add_i32.argtypes has 1 entries but the C signature" in msgs
    assert "scale_f64 binding has no explicit restype" in msgs
    assert "exported native symbol helper_sym" in msgs
    assert "binds symbol gone_sym" in msgs
    assert "helper_sym has no numpy twin" in msgs
    # static functions are not exports; the parser saw the 3 real ones
    assert stats["exports"] == 3


def test_r15_clean_boundary_with_unbound_declaration(tmp_path):
    finds, stats = _r15(_write_native_tree(tmp_path, _MINI_NATIVE_PY_OK))
    assert finds == []
    assert stats["exports"] == 3 and stats["bound"] == 2
    assert ("add_i32", "add_i32_host") in stats["pairs"]


def test_r15_width_mismatch_and_stale_unbound_fire(tmp_path):
    drifted = _MINI_NATIVE_PY_OK.replace(
        "ctypes.c_int64]", "ctypes.c_int32]"
    ).replace(
        "unbound-native(helper_sym)", "unbound-native(add_i32)"
    )
    finds, _stats = _r15(_write_native_tree(tmp_path, drifted))
    msgs = " | ".join(m for _, _, m in finds)
    assert "add_i32.argtypes[1] is ctypes.c_int32" in msgs
    assert "unbound-native(add_i32) declaration is stale" in msgs
    assert "helper_sym" in msgs  # lost its declaration -> unbound again


def test_r15_vacuity_floor_fails_loudly(monkeypatch):
    from tools.auronlint.rules import ffilockstep

    rule = ffilockstep.FfiLockstepRule()
    monkeypatch.setattr(ffilockstep, "R15_MIN_TWINS", 10_000)
    finds = list(rule.check_tree(REPO_ROOT))
    assert any("R15 vacuity check" in m for _, _, m in finds)


def test_r15_live_tree_bindings_in_lockstep():
    finds, stats = _r15(REPO_ROOT)
    assert finds == [], "\n".join(m for _, _, m in finds)
    from tools.auronlint.rules.ffilockstep import (
        R15_MIN_BOUND, R15_MIN_BRIDGE_DECLS, R15_MIN_EXPORTS, R15_MIN_TWINS,
    )

    assert stats["exports"] >= R15_MIN_EXPORTS
    assert stats["bound"] >= R15_MIN_BOUND
    assert stats["bridge_decls"] >= R15_MIN_BRIDGE_DECLS
    assert stats["twins"] >= R15_MIN_TWINS


# ---------------------------------------------------------------------------
# R16 determinism taint
# ---------------------------------------------------------------------------


def _r16(sources: dict, anchors=("pkg/digest.py",), funcs=None):
    from tools.auronlint.rules.determinism import analyze

    return analyze(_graph(sources), anchor_rels=anchors,
                   anchor_funcs=funcs or {})


def test_r16_fires_on_set_dict_clock_and_id():
    finds, stats = _r16({
        "pkg/digest.py": """
        import time

        def digest(parts, opts):
            tags = {p.name for p in parts}
            body = ",".join(tags)
            for k, v in opts.items():
                body += k
            return body + str(time.time()) + str(id(opts))
        """,
    })
    msgs = " | ".join(m for _, _, m in finds)
    assert "set iterated into a join" in msgs
    assert "unsorted .items() iterated into a for loop" in msgs
    assert "wall-clock read time.time()" in msgs
    assert "id() on a digest-reachable path" in msgs
    assert stats["covered"] == 1


def test_r16_closure_scans_callees_but_not_unreachable_code():
    finds, stats = _r16({
        "pkg/digest.py": """
        from pkg.canon import canon

        def digest(parts, opts):
            tags = sorted({p.name for p in parts})
            body = ",".join(tags)
            for k, v in sorted(opts.items()):
                body += canon(k)
            return body
        """,
        "pkg/canon.py": """
        import time

        def canon(s):
            return s.lower()

        def untainted_elsewhere():
            return time.time()
        """,
    })
    assert finds == []  # sorted() wrappers pass; unreachable clock passes
    assert stats["covered"] == 2  # digest + canon, NOT untainted_elsewhere


def test_r16_entropy_env_and_uuid_fire_through_closure():
    finds, _stats = _r16({
        "pkg/digest.py": """
        from pkg.helper import salt

        def digest(parts):
            return salt() + len(parts)
        """,
        "pkg/helper.py": """
        import os
        import random
        import uuid

        def salt():
            a = random.random()
            b = uuid.uuid4()
            c = os.environ["HOME"]
            d = os.getenv("USER")
            return hash((a, b, c, d))
        """,
    })
    msgs = " | ".join(m for _, _, m in finds)
    assert "entropy read random()" in msgs
    assert "uuid.uuid4()" in msgs
    assert "os.environ read" in msgs
    assert "os.getenv()" in msgs


def test_r16_nondeterministic_declaration_suppresses_in_tree(tmp_path):
    """The dedicated R16 declaration: a reasoned ``nondeterministic``
    annotation keeps a sanctioned site out of the gate; an unannotated
    one still fires."""
    from tools.auronlint.core import lint_paths
    from tools.auronlint.rules.determinism import DeterminismRule

    at = tmp_path / "auron_tpu" / "sql"
    at.mkdir(parents=True)
    (at / "digest.py").write_text(textwrap.dedent("""
        def digest(parts):
            tags = {p for p in parts}
            return ",".join(tags)  # auronlint: nondeterministic -- fixture: caller folds with XOR, order-free

        def digest2(parts):
            tags = {p for p in parts}
            return ";".join(tags)
    """))
    rep = lint_paths([os.path.join(str(tmp_path), "auron_tpu")],
                     str(tmp_path), [DeterminismRule()])
    joins = [f for f in rep.findings if "set iterated" in f.message]
    assert {f.suppressed for f in joins} == {True, False}
    assert next(f for f in joins if f.suppressed).reason


def test_r16_vacuity_floor_fails_loudly(monkeypatch):
    from tools.auronlint.rules import determinism

    rule = determinism.DeterminismRule()
    monkeypatch.setattr(determinism, "R16_MIN_COVERED", 10_000)
    finds = list(rule.check_tree(REPO_ROOT))
    assert any("R16 vacuity check" in m for _, _, m in finds)


def test_r16_live_tree_closure_meets_floor():
    from tools.auronlint.callgraph import build_graph
    from tools.auronlint.rules.determinism import R16_MIN_COVERED, analyze

    _finds, stats = analyze(build_graph(REPO_ROOT))
    assert stats["covered"] >= R16_MIN_COVERED
    assert "auron_tpu/sql/digest.py" in stats["rels"]
    assert "auron_tpu/plan/builders.py" in stats["rels"]


def test_unbound_native_and_nondeterministic_route_to_their_rules():
    """Declaration routing: the dedicated R15/R16 annotations suppress
    ONLY their rule — a disable they are not must not leak across."""
    from tools.auronlint.core import SourceModule

    src = textwrap.dedent("""
        x = 1  # auronlint: unbound-native(foo_sym) -- dormant export
        y = 2  # auronlint: nondeterministic -- order folded away
    """)
    mod = SourceModule("f.py", "f.py", src)
    assert mod.suppression_for("R15", 2) is not None
    assert mod.suppression_for("R16", 2) is None
    assert mod.suppression_for("R16", 3) is not None
    assert mod.suppression_for("R15", 3) is None
    assert mod.suppression_for("R1", 3) is None

"""auronlint gate: rule-family fixtures + whole-tree cleanliness.

Each rule family R1-R5 is exercised three ways — firing on a violating
fixture, honoring a suppression comment (with its required reason), and
staying quiet on clean code. The final test runs the real suite over the
real tree and fails on any unsuppressed finding, which is what makes the
engine invariants (host-sync hygiene, bounded compile cache, capacity
bucketing, registry lockstep, vectorization) regressions instead of
style advice.
"""

import json
import os
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.auronlint import ALL_RULES, REPO_ROOT, lint_source, run_tree
from tools.auronlint.report import Finding, Report
from tools.auronlint.rules import (
    HostSyncRule,
    RegistrySyncRule,
    RetraceRule,
    ShapeBucketRule,
    SortPayloadRule,
    VectorizeRule,
)


def _lint(src: str, rule, rel: str = "fixture.py"):
    return lint_source(textwrap.dedent(src), rel, [rule])


def _hits(report: Report, rule_name: str):
    return [f for f in report.findings if f.rule == rule_name and not f.suppressed]


def _suppressed(report: Report, rule_name: str):
    return [f for f in report.findings if f.rule == rule_name and f.suppressed]


# ---------------------------------------------------------------------------
# R1 host-sync hygiene
# ---------------------------------------------------------------------------


def test_r1_fires_on_item_read():
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(xs):
            s = jnp.sum(xs)
            return s.item()
        """,
        HostSyncRule(),
    )
    assert len(_hits(rep, "R1")) == 1
    assert ".item()" in rep.findings[0].message


def test_r1_fires_on_scalar_coercion_and_iteration():
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(xs):
            dev = jnp.cumsum(xs)
            n = int(dev[-1])
            for row in dev:
                pass
            if dev.any():
                n += 1
            return n
        """,
        HostSyncRule(),
    )
    msgs = " | ".join(f.message for f in _hits(rep, "R1"))
    assert len(_hits(rep, "R1")) == 3
    assert "int()" in msgs and "iterating" in msgs and "bool()" in msgs


def test_r1_suppression_honored_and_reason_required():
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(xs):
            s = jnp.sum(xs)
            return s.item()  # auronlint: disable=R1 -- test fixture reason
        """,
        HostSyncRule(),
    )
    assert not _hits(rep, "R1")
    (sup,) = _suppressed(rep, "R1")
    assert sup.reason == "test fixture reason"

    # a reasonless suppression is itself a finding
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(xs):
            s = jnp.sum(xs)
            return s.item()  # auronlint: disable=R1
        """,
        HostSyncRule(),
    )
    assert [f for f in rep.findings if f.rule == "lint.suppression"]


def test_r1_sync_point_declares_allowed_boundary():
    rep = _lint(
        """
        import jax
        import jax.numpy as jnp

        def f(xs):
            total = jax.device_get(jnp.sum(xs))  # auronlint: sync-point -- one count per batch
            return total
        """,
        HostSyncRule(),
    )
    assert not rep.findings  # declared sync points are not findings at all


def test_r1_clean_code_stays_clean():
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(xs):
            n = int(xs.shape[0])     # static metadata, not a sync
            out = jnp.zeros(n)
            cols = [xs, out]
            for c in cols:           # python container, not a device array
                pass
            return out
        """,
        HostSyncRule(),
    )
    assert not rep.findings


def test_r1_allowlisted_paths_are_exempt():
    src = """
    import jax.numpy as jnp

    def f(xs):
        return jnp.sum(xs).item()
    """
    rep = lint_source(textwrap.dedent(src),
                      "auron_tpu/exec/shuffle/writer.py", [HostSyncRule()])
    assert not rep.findings


# ---------------------------------------------------------------------------
# R2 retrace / compile-cache discipline
# ---------------------------------------------------------------------------


def test_r2_fires_on_undeclared_scalar_param():
    rep = _lint(
        """
        import jax

        @jax.jit
        def kernel(x, reverse=False):
            return x
        """,
        RetraceRule(),
    )
    assert len(_hits(rep, "R2")) == 1
    assert "static" in rep.findings[0].message


def test_r2_fires_on_unhashable_default_and_stale_static_name():
    rep = _lint(
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("renamed_flag",))
        def kernel(x, opts=[]):
            return x
        """,
        RetraceRule(),
    )
    msgs = " | ".join(f.message for f in _hits(rep, "R2"))
    assert "unhashable" in msgs and "stale" in msgs


def test_r2_fires_on_device_closure_capture():
    rep = _lint(
        """
        import jax
        import jax.numpy as jnp

        def outer(data):
            big = jnp.asarray(data)

            @jax.jit
            def inner(y):
                return y + big

            return inner
        """,
        RetraceRule(),
    )
    assert any("closes over device array 'big'" in f.message
               for f in _hits(rep, "R2"))


def test_r2_suppression_honored():
    rep = _lint(
        """
        import jax

        @jax.jit  # auronlint: disable=R2 -- traced once at import, fixture
        def kernel(x, reverse=False):
            return x
        """,
        RetraceRule(),
    )
    assert not _hits(rep, "R2") and _suppressed(rep, "R2")


def test_r2_clean_jit_site():
    rep = _lint(
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("reverse",))
        def kernel(x, reverse=False):
            return x
        """,
        RetraceRule(),
    )
    assert not rep.findings


# ---------------------------------------------------------------------------
# R3 shape-bucket discipline
# ---------------------------------------------------------------------------


def test_r3_fires_on_data_derived_shape():
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(xs: jnp.ndarray):
            n = int(jnp.sum(xs))
            return jnp.zeros(n)
        """,
        ShapeBucketRule(),
        rel="auron_tpu/ops/fixture.py",
    )
    assert len(_hits(rep, "R3")) == 1
    assert "data-dependent" in rep.findings[0].message


def test_r3_fires_on_item_shape():
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(counts):
            total = jnp.cumsum(counts)[-1].item()
            return jnp.empty(total)
        """,
        ShapeBucketRule(),
        rel="auron_tpu/ops/fixture.py",
    )
    assert len(_hits(rep, "R3")) == 1


def test_r3_suppression_honored():
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(xs: jnp.ndarray):
            n = int(jnp.sum(xs))
            return jnp.zeros(n)  # auronlint: disable=R3 -- fixture: bounded by test harness
        """,
        ShapeBucketRule(),
        rel="auron_tpu/ops/fixture.py",
    )
    assert not _hits(rep, "R3") and _suppressed(rep, "R3")


def test_r3_clean_capacity_shapes():
    rep = _lint(
        """
        import jax.numpy as jnp

        CAP = 4096

        def f(xs: jnp.ndarray):
            a = jnp.zeros(CAP)
            b = jnp.zeros(xs.shape[0])
            c = jnp.zeros((CAP, 2))
            return a, b, c
        """,
        ShapeBucketRule(),
        rel="auron_tpu/ops/fixture.py",
    )
    assert not rep.findings


# ---------------------------------------------------------------------------
# R4 registry completeness
# ---------------------------------------------------------------------------

_MINI_PROTO = """
syntax = "proto3";
message PhysicalPlanNode {
  oneof plan {
    ScanNode scan = 1;
    FilterNode filter = 2;
  }
}
message PhysicalExprNode {
  oneof expr {
    ColumnExpr column = 1;
  }
}
"""

_MINI_PLANNER_OK = """
def plan_from_proto(p):
    which = p.WhichOneof("plan")
    if which == "scan":
        return 1
    if which == "filter":
        return 2


def expr_from_proto(p):
    which = p.WhichOneof("expr")
    if which == "column":
        return 1
"""

_MINI_PLANNER_DRIFTED = """
def plan_from_proto(p):
    which = p.WhichOneof("plan")
    if which == "scan":
        return 1


def expr_from_proto(p):
    which = p.WhichOneof("expr")
    if which == "column":
        return 1
"""

_MINI_EXPLAIN = """
PLAN_DETAILS = {"scan": (), "filter": ()}
"""

_MINI_BUILDERS = """
def expr_to_proto(e):
    n = X()
    n.column.index = 0
    return n


def scan():
    return W(scan=1)


def filter_():
    return W(filter=1)
"""


def _write_mini_tree(tmp_path, planner_src, explain_src=_MINI_EXPLAIN):
    at = tmp_path / "auron_tpu"
    for d in ("proto", "plan", "convert", "functions"):
        (at / d).mkdir(parents=True, exist_ok=True)
    (at / "proto" / "plan.proto").write_text(_MINI_PROTO)
    (at / "plan" / "planner.py").write_text(planner_src)
    (at / "plan" / "explain.py").write_text(explain_src)
    (at / "plan" / "builders.py").write_text(_MINI_BUILDERS)
    (at / "convert" / "exprs.py").write_text("_FN_RENAME = {}\n")
    return str(tmp_path)


def test_r4_fires_on_registry_drift(tmp_path):
    root = _write_mini_tree(tmp_path, _MINI_PLANNER_DRIFTED)
    findings = list(RegistrySyncRule().check_tree(root))
    msgs = " | ".join(m for _, _, m in findings)
    assert "plan variant 'filter' has no plan_from_proto dispatch" in msgs


def test_r4_fires_on_missing_explain_entry(tmp_path):
    root = _write_mini_tree(
        tmp_path, _MINI_PLANNER_OK, explain_src='PLAN_DETAILS = {"scan": ()}\n'
    )
    findings = list(RegistrySyncRule().check_tree(root))
    msgs = " | ".join(m for _, _, m in findings)
    assert "plan variant 'filter' missing from PLAN_DETAILS" in msgs


def test_r4_clean_mini_tree(tmp_path):
    root = _write_mini_tree(tmp_path, _MINI_PLANNER_OK)
    findings = [
        (rel, line, m)
        for rel, line, m in RegistrySyncRule().check_tree(root)
        if "function registry unimportable" not in m
    ]
    assert findings == []


def test_r4_suppression_honored(tmp_path):
    from tools.auronlint.core import lint_paths

    drifted = _MINI_PLANNER_DRIFTED.replace(
        "def plan_from_proto(p):",
        "def plan_from_proto(p):  # auronlint: disable=R4 -- fixture: drift acknowledged",
    )
    root = _write_mini_tree(tmp_path, drifted)
    rep = lint_paths([os.path.join(root, "auron_tpu")], root,
                     [RegistrySyncRule()])
    r4 = [f for f in rep.findings if f.rule == "R4"
          and "plan_from_proto dispatch" in f.message]
    assert r4 and all(f.suppressed for f in r4)


def test_r4_real_tree_registries_in_lockstep():
    """The real repo's registries must be drift-free right now."""
    findings = [
        (rel, line, m)
        for rel, line, m in RegistrySyncRule().check_tree(REPO_ROOT)
        if "function registry unimportable" not in m
    ]
    assert findings == [], "\n".join(m for _, _, m in findings)


# ---------------------------------------------------------------------------
# R5 vectorization ban
# ---------------------------------------------------------------------------


def test_r5_fires_on_per_row_loop():
    rep = _lint(
        """
        def f(batch):
            out = []
            for i in range(batch.num_rows):
                out.append(i)
            return out
        """,
        VectorizeRule(),
        rel="auron_tpu/exec/fixture.py",
    )
    assert len(_hits(rep, "R5")) == 1


def test_r5_fires_on_capacity_wide_loop_over_device():
    rep = _lint(
        """
        import jax.numpy as jnp

        def f(xs):
            vals = jnp.abs(xs)
            return [vals[i] for i in range(vals.shape[0])]
        """,
        VectorizeRule(),
        rel="auron_tpu/exec/fixture.py",
    )
    assert len(_hits(rep, "R5")) == 1


def test_r5_suppression_honored():
    rep = _lint(
        """
        def f(batch):
            for i in range(batch.num_rows):  # auronlint: disable=R5 -- fixture: per-run loop
                pass
        """,
        VectorizeRule(),
        rel="auron_tpu/exec/fixture.py",
    )
    assert not _hits(rep, "R5") and _suppressed(rep, "R5")


def test_r5_clean_loops_pass():
    rep = _lint(
        """
        def f(batches, cols):
            for b in batches:          # per-batch orchestration
                pass
            for c in cols:             # per-column
                pass
            for i in range(0, 100, 8):  # stepped chunk loop
                pass
        """,
        VectorizeRule(),
        rel="auron_tpu/exec/fixture.py",
    )
    assert not rep.findings


def test_r5_only_scopes_hot_paths():
    src = """
    def f(batch):
        for i in range(batch.num_rows):
            pass
    """
    rep = lint_source(textwrap.dedent(src), "auron_tpu/models/tpcds.py",
                      [VectorizeRule()])
    assert not rep.findings


# ---------------------------------------------------------------------------
# shared report schema
# ---------------------------------------------------------------------------


def test_report_json_schema_shared_with_jvm_lint():
    from tools import jvm_lint

    rep = run_tree(rules=[HostSyncRule()])
    doc = json.loads(rep.to_json())
    assert doc["schema"] == 1 and doc["tool"] == "auronlint"
    assert set(doc["counts"]) == {"total", "unsuppressed", "suppressed"}

    jrep = jvm_lint.run_report()
    jdoc = json.loads(jrep.to_json())
    assert jdoc["schema"] == 1 and jdoc["tool"] == "jvm_lint"
    assert set(jdoc["counts"]) == set(doc["counts"])
    # both serialize the same Finding fields
    f = Finding("t", "r", "p", 1, "m")
    keys = set(f.to_dict())
    for d in doc["findings"] + jdoc["findings"]:
        assert set(d) == keys
    assert Finding.from_dict(f.to_dict()) == f


# ---------------------------------------------------------------------------
# R6 sort-payload discipline
# ---------------------------------------------------------------------------


def test_r6_fires_on_column_scaling_operands():
    rep = _lint(
        """
        from jax import lax
        import jax.numpy as jnp

        def group(words, sel):
            dead = jnp.where(sel, 0, 1)
            iota = jnp.arange(sel.shape[0])
            operands = [dead, *words, iota]
            return lax.sort(tuple(operands), num_keys=len(operands) - 1)
        """,
        SortPayloadRule(),
        rel="auron_tpu/ops/fixture.py",
    )
    assert len(_hits(rep, "R6")) == 1
    assert "fingerprint" in rep.findings[0].message


def test_r6_fires_on_comprehension_and_impl_choice():
    rep = _lint(
        """
        from jax import lax
        from auron_tpu.ops import bitonic

        def group(cols, n_keys, cap):
            impl = bitonic.sort_impl_for(n_keys + 1, cap)
            return lax.sort(tuple(c for c in cols), num_keys=1)
        """,
        SortPayloadRule(),
        rel="auron_tpu/ops/fixture.py",
    )
    assert len(_hits(rep, "R6")) == 2


def test_r6_suppression_honored():
    rep = _lint(
        """
        from jax import lax

        def order_by(operands):
            ops = [*operands]
            return lax.sort(tuple(ops), num_keys=len(ops) - 1)  # auronlint: sort-payload -- ORDER BY sorts every user key by definition
        """,
        SortPayloadRule(),
        rel="auron_tpu/exec/fixture.py",
    )
    assert not _hits(rep, "R6")
    assert _suppressed(rep, "R6")


def test_r6_self_referential_reassignment_no_recursion():
    """`operands = operands + (iota,)` maps the name to an expression
    mentioning itself; the resolver must flag it as scaling (self-append
    grows the list), not recurse forever (regression: RecursionError
    aborted the whole lint run)."""
    rep = _lint(
        """
        from jax import lax

        def group(operands, n):
            operands = operands + (n,)
            return lax.sort(operands, num_keys=1)
        """,
        SortPayloadRule(),
        rel="auron_tpu/ops/fixture.py",
    )
    assert len(_hits(rep, "R6")) == 1


def test_r6_fixed_arity_sorts_pass():
    rep = _lint(
        """
        from jax import lax
        import jax.numpy as jnp

        def cluster(fp, sel):
            dead = jnp.where(sel, 0, 1)
            iota = jnp.arange(sel.shape[0])
            return lax.sort((dead, fp, iota), num_keys=3)
        """,
        SortPayloadRule(),
        rel="auron_tpu/ops/fixture.py",
    )
    assert not rep.findings


# ---------------------------------------------------------------------------
# the gate: whole tree, zero unsuppressed findings
# ---------------------------------------------------------------------------


def test_whole_tree_zero_unsuppressed_findings():
    rep = run_tree(rules=ALL_RULES)
    bad = rep.unsuppressed
    assert not bad, "\n" + "\n".join(f.render() for f in bad)
    # every suppression in the tree carries a reason
    assert all(f.reason for f in rep.suppressed)


# ---------------------------------------------------------------------------
# sync-point multiplicity budgets (syncbudget.py + perfcheck contract)
# ---------------------------------------------------------------------------


def test_r1_sync_point_budget_declares_boundary():
    rep = _lint(
        """
        import jax
        import jax.numpy as jnp

        def f(xs):
            total = jax.device_get(jnp.sum(xs))  # auronlint: sync-point(1/batch) -- one count per batch
            seed = jax.device_get(xs)  # auronlint: sync-point(2/task) -- stream seed read
            ext = jax.device_get(xs)  # auronlint: sync-point(call) -- external API contract
            return total, seed, ext
        """,
        HostSyncRule(),
    )
    assert not rep.findings  # budgeted sync points are clean declarations


def test_malformed_sync_point_budget_is_a_finding():
    rep = _lint(
        """
        import jax
        import jax.numpy as jnp

        def f(xs):
            a = jax.device_get(xs)  # auronlint: sync-point(weekly) -- nonsense unit
            b = jax.device_get(xs)  # auronlint: disable(1/batch)=R1 -- budget on a disable
            return a, b
        """,
        HostSyncRule(),
    )
    assert len([f for f in rep.findings if f.rule == "lint.suppression"]) == 2


def test_parse_sync_budget_grammar():
    from tools.auronlint.core import parse_sync_budget

    assert parse_sync_budget("1/batch") == (1, "batch")
    assert parse_sync_budget(" 8 / task ") == (8, "task")
    assert parse_sync_budget("call") == (0, "call")
    assert parse_sync_budget("1/flush") is None
    assert parse_sync_budget("batch") is None
    assert parse_sync_budget("") is None


def test_syncbudget_collects_engine_declarations():
    """Every sync-point in the live tree parses to a budget, and the known
    hot-path sites resolve through the runtime-site matcher."""
    from tools.auronlint.syncbudget import (
        budget_for_site, collect_sync_points, site_allowlisted,
    )

    points = collect_sync_points(REPO_ROOT)
    assert len(points) > 20
    assert all(p.unit in ("batch", "task", "call") for p in points)
    # the chain seed read (exec/joins/chain.py) must be task-budgeted now —
    # a per-batch budget there would mask the whole tentpole regressing
    chain_pts = [p for p in points if p.rel.endswith("joins/chain.py")]
    assert chain_pts and all(p.unit == "task" for p in chain_pts)
    hit = budget_for_site(f"{chain_pts[0].rel.split('auron_tpu/')[1]}:{chain_pts[0].line}", points)
    assert hit is not None and hit.unit == "task"
    assert site_allowlisted("exec/shuffle/writer.py:330")
    assert not site_allowlisted("exec/joins/chain.py:1")


# ---------------------------------------------------------------------------
# interprocedural substrate (callgraph + summaries)
# ---------------------------------------------------------------------------


def _graph(sources: dict):
    from tools.auronlint.callgraph import build_graph_from_sources

    return build_graph_from_sources(
        {rel: textwrap.dedent(src) for rel, src in sources.items()}
    )


def test_callgraph_cycle_and_recursion_guard():
    """Recursion, mutual recursion and a base-class cycle must not hang
    any traversal (the R6 resolver-cycle lesson, applied to the graph)."""
    g = _graph({
        "pkg/a.py": """
        class A(object):
            def ping(self):
                self.pong()

            def pong(self):
                self.ping()

        def rec(n):  # auronlint: thread-root(foreign) -- test fixture
            from auron_tpu.utils.config import active_conf
            rec(n - 1)
            return active_conf()
        """,
        "pkg/b.py": """
        from pkg.a import A

        class B(A):
            pass

        class C(B):
            def ping(self):
                super().ping()
        """,
    })
    # every analysis terminates and the recursive root sees itself
    states = g.foreign_conf_states()
    assert any(q.endswith("::rec") for q in states)
    g.roots_reaching()
    g.batch_depths()
    g.jit_reachable()


def test_summaries_batch_loop_and_iter_attribution():
    """`for b in child_stream(...)`: the stream-constructing call sits at
    the surrounding depth, the body runs per batch."""
    from tools.auronlint.core import SourceModule
    from tools.auronlint.summaries import summarize_module

    src = textwrap.dedent("""
    def run(self, ctx):
        prelude()
        for b in self.child_stream(0, 0, ctx):
            body(b)
        for x in range(10):
            bounded(x)
    """)
    ms = summarize_module(SourceModule("m.py", "m.py", src))
    fs = ms.functions["m.py::run"]
    depths = {c.name: c.batch_depth for c in fs.calls}
    assert depths["prelude"] == 0
    assert depths["child_stream"] == 0      # iter position: evaluated once
    assert depths["body"] == 1              # per pumped batch
    assert depths["bounded"] == 0           # plain bounded loop


# ---------------------------------------------------------------------------
# R7 thread-context escape
# ---------------------------------------------------------------------------


def _r7(sources: dict):
    from tools.auronlint.rules.threadctx import analyze

    return list(analyze(_graph(sources)))


def test_r7_fires_on_bare_active_conf_from_foreign_root():
    hits = _r7({
        "pkg/spill.py": """
        from pkg.conf import codec

        class Staging:
            def spill(self):  # auronlint: thread-root(foreign) -- test fixture
                return codec()
        """,
        "pkg/conf.py": """
        from auron_tpu.utils.config import active_conf

        def codec():
            return active_conf().get("spill.codec")
        """,
    })
    assert len(hits) == 1
    rel, line, msg = hits[0]
    assert rel == "pkg/conf.py" and "Staging.spill" in msg


def test_r7_quiet_when_conf_threaded_and_guarded():
    hits = _r7({
        "pkg/spill.py": """
        from pkg.conf import codec

        class Staging:
            def __init__(self, ctx):
                self.ctx = ctx

            def spill(self):  # auronlint: thread-root(foreign) -- test fixture
                return codec(conf=self.ctx.conf)
        """,
        "pkg/conf.py": """
        from auron_tpu.utils.config import active_conf

        def codec(conf=None):
            return (conf if conf is not None else active_conf()).get("x")
        """,
    })
    assert hits == []


def test_r7_guarded_fallback_fires_when_a_path_drops_conf():
    hits = _r7({
        "pkg/spill.py": """
        from pkg.conf import codec

        class Staging:
            def spill(self):  # auronlint: thread-root(foreign) -- test fixture
                return codec()
        """,
        "pkg/conf.py": """
        from auron_tpu.utils.config import active_conf

        def codec(conf=None):
            return (conf if conf is not None else active_conf()).get("x")
        """,
    })
    assert len(hits) == 1
    assert "WITHOUT passing conf" in hits[0][2]


def test_r7_conf_scoped_root_is_exempt():
    hits = _r7({
        "pkg/pump.py": """
        from auron_tpu.utils.config import active_conf

        def pump():  # auronlint: thread-root(conf-scoped) -- installs scope
            return active_conf()
        """,
    })
    assert hits == []


def test_r7_conf_scope_block_neutralizes_downstream():
    hits = _r7({
        "pkg/svc.py": """
        from auron_tpu.utils.config import active_conf, conf_scope

        def helper():
            return active_conf()

        def handle(conf):  # auronlint: thread-root(foreign) -- test fixture
            with conf_scope(conf):
                return helper()
        """,
    })
    assert hits == []


# ---------------------------------------------------------------------------
# R8 lock discipline
# ---------------------------------------------------------------------------


def _r8(sources: dict):
    from tools.auronlint.rules.lockguard import analyze

    return list(analyze(_graph(sources)))


_R8_SHARED = """
import threading

class Mgr:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def bump(self):
        {write}

class Consumer:
    def spill(self):  # auronlint: thread-root(foreign) -- test fixture
        shrink()

def shrink():
    m = Mgr()
    m.bump()

def pump():  # auronlint: thread-root(conf-scoped) -- test fixture
    m = Mgr()
    m.bump()

_GLOBAL_MGR = Mgr()
"""


def test_r8_fires_on_unlocked_cross_root_write():
    hits = _r8({"pkg/m.py": _R8_SHARED.format(write="self.n += 1")})
    assert len(hits) == 1
    assert "Mgr.n" in hits[0][2] and "2 thread roots" in hits[0][2]


def test_r8_quiet_under_lock_and_with_guarded_by():
    hits = _r8({"pkg/m.py": _R8_SHARED.format(
        write="with self._lock:\n            self.n += 1"
    )})
    assert hits == []
    # guarded-by declaration: the lock is held by the caller
    hits = _r8({"pkg/m.py": _R8_SHARED.format(
        write="self.n += 1  # auronlint: guarded-by(self._lock) -- callers hold it"
    )})
    assert hits == []


def test_r8_single_root_and_local_objects_are_quiet():
    # single root: per-task state needs no lock
    src = _R8_SHARED.format(write="self.n += 1").replace(
        "def spill(self):  # auronlint: thread-root(foreign) -- test fixture",
        "def spill(self):",
    )
    assert _r8({"pkg/m.py": src}) == []
    # function-local parser objects never escape -> never shared
    hits = _r8({"pkg/p.py": """
    class Cursor:
        def __init__(self, buf):
            self.pos = 0

        def take(self):
            self.pos += 1

    class Consumer:
        def spill(self):  # auronlint: thread-root(foreign) -- test fixture
            c = Cursor(b"x")
            c.take()

    def pump():  # auronlint: thread-root(conf-scoped) -- test fixture
        c = Cursor(b"y")
        c.take()
    """})
    assert hits == []


def test_r8_thread_owned_class_declaration_exempts_writes():
    """A class declared thread-owned (single-thread instance ownership —
    the serving-layer pattern: per-query operator instances reachable
    from both the pump root and the POST /sql handler root) is exempt."""
    src = _R8_SHARED.format(write="self.n += 1").replace(
        "class Mgr:",
        "# auronlint: thread-owned -- fixture: one instance per query, "
        "one driving thread\nclass Mgr:",
    )
    assert _r8({"pkg/m.py": src}) == []


def test_r8_detached_thread_owned_is_a_finding():
    """A thread-owned that anchors to a non-class line is inert — R8
    reports the detached declaration instead of silently dropping it,
    AND still reports the unexempted write."""
    src = _R8_SHARED.format(
        write="self.n += 1  # auronlint: thread-owned -- wrong anchor"
    )
    hits = _r8({"pkg/m.py": src})
    msgs = [h[2] for h in hits]
    assert any("does not anchor to a `class`" in m for m in msgs)
    assert any("Mgr.n" in m for m in msgs)


def test_thread_owned_rides_the_lint_ratchet():
    """thread-owned declarations count as declared debt (LINT_RATCHET)."""
    from tools.auronlint import ratchet

    assert "thread-owned" in ratchet.load(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# R9 static sync-budget verification
# ---------------------------------------------------------------------------


def _r9(sources: dict):
    from tools.auronlint.rules.budgetproof import analyze

    return list(analyze(_graph(sources)))


def test_r9_fires_on_call_budget_inside_batch_loop():
    hits = _r9({"pkg/op.py": """
    import jax

    def read(b):
        return jax.device_get(b)  # auronlint: sync-point(call) -- caller-owned

    class Op:
        def pump(self, ctx):  # auronlint: thread-root(conf-scoped) -- test fixture
            for b in self.child_stream(0, 0, ctx):
                read(b)
    """})
    assert len(hits) == 1
    assert "caller-owned" in hits[0][2]


def test_r9_fires_on_task_budget_in_local_batch_loop():
    hits = _r9({"pkg/op.py": """
    import jax

    class Op:
        def pump(self, ctx):  # auronlint: thread-root(conf-scoped) -- test fixture
            for b in self.child_stream(0, 0, ctx):
                n = jax.device_get(b)  # auronlint: sync-point(2/task) -- wrongly task-budgeted
    """})
    assert len(hits) == 1
    assert "task-bounded" in hits[0][2]


def test_r9_batch_budget_in_batch_loop_is_proven():
    hits = _r9({"pkg/op.py": """
    import jax

    class Op:
        def pump(self, ctx):  # auronlint: thread-root(conf-scoped) -- test fixture
            prep = jax.device_get(0)  # auronlint: sync-point(4/task) -- once per task
            for b in self.child_stream(0, 0, ctx):
                n = jax.device_get(b)  # auronlint: sync-point(1/batch) -- per batch by design
    """})
    assert hits == []


def test_r9_batch_budget_squared_fires():
    hits = _r9({"pkg/op.py": """
    import jax

    class Op:
        def pump(self, ctx):  # auronlint: thread-root(conf-scoped) -- test fixture
            for b in self.child_stream(0, 0, ctx):
                for c in self.child_stream(1, 0, ctx):
                    n = jax.device_get(c)  # auronlint: sync-point(1/batch) -- nested!
    """})
    assert len(hits) == 1
    assert "SQUARED" in hits[0][2]


# ---------------------------------------------------------------------------
# R10 jit-boundary purity
# ---------------------------------------------------------------------------


def _r10(sources: dict):
    from tools.auronlint.rules.jitpurity import analyze

    return list(analyze(_graph(sources)))


def test_r10_fires_on_conf_read_and_transfer_inside_jit():
    hits = _r10({"pkg/k.py": """
    import jax
    from auron_tpu.utils.config import active_conf

    @jax.jit
    def kernel(x):
        mode = active_conf().get("exec.mode")
        n = x.item()
        return x + 1
    """})
    msgs = " | ".join(h[2] for h in hits)
    assert len(hits) == 2
    assert "active_conf" in msgs and ".item()" in msgs


def test_r10_traced_helper_and_captured_mutation():
    hits = _r10({"pkg/k.py": """
    import jax
    from functools import partial

    _CACHE = {}

    def helper(x):
        _CACHE[1] = x
        return x

    @partial(jax.jit, static_argnames=("n",))
    def kernel(x, *, n):
        return helper(x) + n
    """})
    assert len(hits) == 1
    assert "subscript write to captured '_CACHE'" in hits[0][2]
    assert "traced via" in hits[0][2]


def test_r10_fires_on_obs_recorder_call_inside_jit():
    """Span-recording calls are host-side only: inside a jit they fire at
    trace time and never replay — every import shape must be caught."""
    hits = _r10({"pkg/k.py": """
    import jax
    from auron_tpu import obs
    from auron_tpu.obs import note_sync

    @jax.jit
    def kernel(x):
        obs.note_op("FilterExec", "elapsed_compute", 1)
        note_sync(1, False)
        return x + 1

    def helper(y):
        with obs.span("inner"):
            return y

    @jax.jit
    def kernel2(x):
        return helper(x)
    """})
    msgs = [h[2] for h in hits]
    assert len(hits) == 3, msgs
    assert all("host-side only" in m for m in msgs)
    assert any("'note_op'" in m for m in msgs)
    assert any("'note_sync'" in m for m in msgs)
    assert any("'span'" in m and "traced via" in m for m in msgs)


def test_r10_obs_call_outside_jit_quiet():
    hits = _r10({"pkg/k.py": """
    import jax
    from auron_tpu import obs

    @jax.jit
    def kernel(x):
        return x + 1

    def pump(x):
        with obs.span("task"):
            return kernel(x)
    """})
    assert not hits


def test_r10_pure_callback_target_not_traced_and_pure_fn_quiet():
    hits = _r10({"pkg/k.py": """
    import jax
    import numpy as np

    def _host_sort(x):
        out = []
        out.append(1)   # local list: fine
        return np.lexsort(x)

    @jax.jit
    def kernel(x):
        order = jax.pure_callback(_host_sort, x, x)
        return x[order]
    """})
    assert hits == []


# ---------------------------------------------------------------------------
# annotation grammar: thread-root / guarded-by
# ---------------------------------------------------------------------------


def test_thread_root_grammar_validation():
    rep = _lint(
        """
        def ok():  # auronlint: thread-root(foreign) -- net thread
            pass

        def bad_kind():  # auronlint: thread-root(weekly) -- nonsense
            pass

        def no_reason():  # auronlint: thread-root(foreign)
            pass
        """,
        HostSyncRule(),
    )
    sup = [f for f in rep.findings if f.rule == "lint.suppression"]
    # bad kind -> malformed argument; missing reason -> reasonless finding
    assert len(sup) == 2


def test_guarded_by_grammar_requires_lock_and_reason():
    rep = _lint(
        """
        class C:
            def f(self):
                self.n = 1  # auronlint: guarded-by(self._lock) -- caller holds
                self.m = 2  # auronlint: guarded-by -- no lock named
        """,
        HostSyncRule(),
    )
    sup = [f for f in rep.findings if f.rule == "lint.suppression"]
    assert len(sup) == 1  # the lockless guarded-by


def test_standalone_annotations_stack_to_next_code_line():
    """Two standalone declarations above one statement both anchor to the
    statement (the R9-over-sync-point interplay regression)."""
    from tools.auronlint.core import SourceModule

    src = textwrap.dedent("""
    import jax

    def f(xs):
        # auronlint: sync-point(call) -- declared boundary
        # auronlint: disable=R9 -- bounded by spill pressure
        return jax.device_get(xs)
    """)
    mod = SourceModule("m.py", "m.py", src)
    sync = [s for s in mod.suppressions if s.kind == "sync-point"][0]
    assert mod.anchor_line(sync) == 7  # the return line, not the comment
    assert mod.is_sync_point(7)
    assert mod.suppression_for("R9", 7) is not None


# ---------------------------------------------------------------------------
# lint ratchet
# ---------------------------------------------------------------------------


def test_lint_ratchet_seed_improve_regress(tmp_path):
    from tools.auronlint.ratchet import check_and_update, load, save
    from tools.auronlint.report import Finding, Report

    root = str(tmp_path)
    (tmp_path / "auron_tpu").mkdir()

    def report_with(n_suppressed):
        rep = Report(tool="auronlint")
        for i in range(n_suppressed):
            rep.findings.append(Finding(
                "auronlint", "R7", "auron_tpu/x.py", i + 1, "m",
                suppressed=True, reason="r",
            ))
        return rep

    # seed: first sighting records current debt
    assert check_and_update(report_with(3), root) == []
    assert load(root)["R7"] == 3
    # improvement: ratchet tightens automatically
    assert check_and_update(report_with(2), root) == []
    assert load(root)["R7"] == 2
    # regression: fails, file unchanged
    problems = check_and_update(report_with(5), root)
    assert problems and "R7" in problems[0]
    assert load(root)["R7"] == 2
    # explicit conscious raise is honored
    counts = load(root)
    counts["R7"] = 5
    save(root, counts)
    assert check_and_update(report_with(5), root) == []


def test_live_tree_ratchet_matches_current_debt():
    """LINT_RATCHET.json is committed and must match (or exceed) the
    tree's actual suppression counts — `make lint` enforces it."""
    from tools.auronlint.ratchet import current_counts, load
    from tools.auronlint import run_tree

    ratchet = load(REPO_ROOT)
    assert ratchet.get("sync-point", 0) > 20
    rep = run_tree()
    counts = current_counts(rep, REPO_ROOT)
    for key, n in counts.items():
        assert n <= ratchet.get(key, 0), (
            f"{key} debt {n} exceeds LINT_RATCHET.json "
            f"{ratchet.get(key, 0)} — make lint would fail"
        )


# ---------------------------------------------------------------------------
# SARIF emitter (shared by auronlint and jvm_lint)
# ---------------------------------------------------------------------------


def test_sarif_schema_shape():
    from tools.auronlint.report import Finding, Report

    rep = Report(tool="auronlint")
    rep.findings.append(Finding("auronlint", "R7", "a.py", 3, "boom"))
    rep.findings.append(Finding(
        "auronlint", "R9", "b.py", 0, "waived", suppressed=True, reason="why",
    ))
    doc = json.loads(rep.to_sarif())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "auronlint"
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} == {"R7", "R9"}
    res = run["results"]
    assert res[0]["locations"][0]["physicalLocation"]["region"]["startLine"] == 3
    # line 0 (file-level) clamps to 1 for SARIF validity
    assert res[1]["locations"][0]["physicalLocation"]["region"]["startLine"] == 1
    assert res[1]["suppressions"][0]["justification"] == "why"


def test_engine_thread_roots_are_declared():
    """The known thread entry points carry thread-root declarations — the
    interprocedural rules are only as good as their roots."""
    from tools.auronlint.callgraph import build_graph

    g = build_graph(REPO_ROOT)
    roots = {q.split("::", 1)[1]: k for q, k in g.roots.items()}
    assert roots.get("TaskRuntime._pump") == "conf-scoped"
    assert roots.get("_Handler.do_GET") == "foreign"
    assert roots.get("RssNetServer._handle") == "foreign"
    assert roots.get("_ShuffleStaging.spill") == "foreign"
    assert roots.get("_AggTableConsumer.spill") == "foreign"
    assert roots.get("_SorterConsumer.spill") == "foreign"
    assert roots.get("harvest") == "foreign"


def test_thread_root_standalone_above_decorated_def_registers():
    """The anchor of a standalone root above a decorated def is the
    decorator line — the root must still register (a silently-dropped
    root would disable reachability)."""
    hits = _r7({"pkg/svc.py": """
    from auron_tpu.utils.config import active_conf

    def deco(f):
        return f

    # auronlint: thread-root(foreign) -- handler thread
    @deco
    def handler():
        return worker()

    def worker():
        return active_conf()
    """})
    assert len(hits) == 1 and "handler" in hits[0][2]


def test_unanchored_thread_root_is_a_loud_finding():
    hits = _r7({"pkg/svc.py": """
    # auronlint: thread-root(foreign) -- floats above nothing
    X = 1
    """})
    assert len(hits) == 1
    assert "does not anchor to a function definition" in hits[0][2]


def test_lint_ratchet_failing_run_does_not_tighten(tmp_path):
    """A transiently-broken tree (suppressions detached -> unsuppressed
    findings) must not lower the debt ceiling."""
    from tools.auronlint.ratchet import check_and_update, load
    from tools.auronlint.report import Finding, Report

    root = str(tmp_path)
    (tmp_path / "auron_tpu").mkdir()

    def report(n_sup, n_unsup=0):
        rep = Report(tool="auronlint")
        for i in range(n_sup):
            rep.findings.append(Finding(
                "auronlint", "R7", "auron_tpu/x.py", i + 1, "m",
                suppressed=True, reason="r"))
        for i in range(n_unsup):
            rep.findings.append(Finding(
                "auronlint", "R7", "auron_tpu/x.py", 100 + i, "loose"))
        return rep

    check_and_update(report(5), root)
    assert load(root)["R7"] == 5
    # 3 suppressions detach: run FAILS (2 unsuppressed) — ceiling stays
    check_and_update(report(2, n_unsup=3), root)
    assert load(root)["R7"] == 5
    # restoring the suppressions is NOT a regression
    assert check_and_update(report(5), root) == []


def test_changed_mode_rejects_vacuous_and_ambiguous_invocations(capsys):
    from tools.auronlint.__main__ import main

    # tree-only rule selection under --changed would run zero rules
    assert main(["--changed", "--rules", "R7"]) == 2
    assert "vacuous" in capsys.readouterr().err
    # explicit paths would be silently ignored
    assert main(["--changed", "auron_tpu/exec"]) == 2
    assert "picks its own files" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# R2 fused-segment cache-key discipline (whole-stage fusion, docs/fusion.md)
# ---------------------------------------------------------------------------


def test_r2_fires_on_jit_wrapper_built_in_batch_loop():
    """A jit wrapper constructed per batch (or per segment instance inside
    the batch loop) starts an empty compile cache each iteration — the
    fused-segment retrace explosion the stage-program cache key exists to
    prevent."""
    rep = _lint(
        """
        import jax

        def drive(stream, fn):
            for b in stream:
                prog = jax.jit(fn)
                yield prog(b)
        """,
        RetraceRule(),
    )
    hits = _hits(rep, "R2")
    assert len(hits) == 1
    assert "inside a loop" in hits[0].message


def test_r2_fires_on_jit_decorated_def_in_loop():
    rep = _lint(
        """
        import jax

        def build(segments):
            out = []
            for seg in segments:
                @jax.jit
                def prog(dev):
                    return dev
                out.append(prog)
            return out
        """,
        RetraceRule(),
    )
    hits = _hits(rep, "R2")
    assert len(hits) == 1
    assert "defined inside a loop" in hits[0].message


def test_r2_module_level_stage_program_quiet():
    """The sanctioned pattern (plan/fusion.py): ONE module-level jit whose
    cache keys on static (schema, segment signature) args, dispatched from
    the batch loop — a call inside the loop is fine, construction is not."""
    rep = _lint(
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("steps",))
        def _stage_program(dev, *, steps):
            return dev

        def drive(stream, steps):
            for b in stream:
                yield _stage_program(b, steps=steps)
        """,
        RetraceRule(),
    )
    assert not _hits(rep, "R2")


# ---------------------------------------------------------------------------
# R10 teeth for fused-stage closures: the trace-safe machinery the stage
# compiler reuses must keep being checked for conf reads, host transfers
# and captured-state mutation through the whole traced closure
# ---------------------------------------------------------------------------


def test_r10_fused_stage_shaped_closure_conf_read():
    """A helper reachable from a stage-program-shaped jit entry reading
    active_conf(): the resolved knob would be baked into every cached
    (schema, signature, bucket) program."""
    hits = _r10({"pkg/stage.py": """
    import jax
    from functools import partial
    from auron_tpu.utils.config import active_conf

    def _eval_step(dev, steps):
        if active_conf().get("exec.fuse.enable") == "off":
            return dev
        return dev

    @partial(jax.jit, static_argnames=("steps",))
    def stage_program(dev, *, steps):
        return _eval_step(dev, steps)
    """})
    assert len(hits) == 1
    assert "active_conf" in hits[0][2] and "traced via" in hits[0][2]


def test_r10_fused_stage_shaped_closure_host_transfer_and_mutation():
    """Host transfers and compile-counter mutation inside the traced
    closure: both fire once at trace time only — the exact hazards the
    fusion pass keeps OUTSIDE the program (_note_dispatch runs host-side
    before dispatch)."""
    hits = _r10({"pkg/stage.py": """
    import jax
    from functools import partial

    _COMPILES = {}

    def _count_and_read(dev, sig):
        _COMPILES[sig] = _COMPILES.get(sig, 0) + 1
        return int(dev.sum().item())

    @partial(jax.jit, static_argnames=("sig",))
    def stage_program(dev, *, sig):
        n = _count_and_read(dev, sig)
        return dev[:n]
    """})
    msgs = " | ".join(h[2] for h in hits)
    assert len(hits) == 2
    assert ".item()" in msgs and "_COMPILES" in msgs


def test_r2_call_form_decorator_in_loop_reports_once():
    """@partial(jax.jit, ...) decorators are ast.Call nodes too — the
    loop scan must report the site exactly once (decorator branch), not
    double-count it through the bare-call branch."""
    rep = _lint(
        """
        import jax
        from functools import partial

        def build(segments):
            out = []
            for seg in segments:
                @partial(jax.jit, static_argnames=("n",))
                def prog(dev, *, n):
                    return dev
                out.append(prog)
            return out
        """,
        RetraceRule(),
    )
    hits = _hits(rep, "R2")
    assert len(hits) == 1
    assert "defined inside a loop" in hits[0].message

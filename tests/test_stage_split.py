"""Host-schedulable stage execution (VERDICT r2 item 3).

The converter's mesh_exchange plans assume the engine owns cross-stage
execution (MeshQueryDriver). A real Spark schedules stages itself — the
reference integrates via its shuffle manager: map tasks end in a native
shuffle writer whose output is committed as MapStatus, reduce tasks start
at a reader fed by the shuffle fetch (AuronShuffleManager.scala:14-37,
NativeShuffleExchangeBase.scala:124-296, Shims.scala:249).

These tests prove the same decomposition WITHOUT Spark:

- ``split_stages`` turns a two-stage q3-class plan into per-stage task
  plans (stage 0 ends in shuffle_writer, stage 1 starts at ipc_reader);
- the stages run as SEPARATE task invocations against the ShuffleManager
  contract, in-process first, then through the C ABI harness as separate
  OS processes (the stand-in JVM executor);
- results are identical to MeshQueryDriver resolving the same plan.
"""

import json
import os
import subprocess

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.bridge import api
from auron_tpu.columnar import Batch
from auron_tpu.convert.stages import (
    ShuffleManager,
    split_stages,
    stage_task,
)
from auron_tpu.exprs.ir import BinaryOp, col, lit
from auron_tpu.plan import builders as B

N_MAP = 2
N_REDUCE = 2


def _tables(seed=3, n=6000):
    rng = np.random.default_rng(seed)
    fact = pd.DataFrame(
        {
            "date_sk": rng.integers(0, 365, n).astype(np.int64),
            "item_sk": rng.integers(0, 300, n).astype(np.int64),
            "price": np.round(rng.gamma(2.0, 20.0, n), 2),
        }
    )
    dates = pd.DataFrame(
        {
            "d_date_sk": np.arange(365, dtype=np.int64),
            "d_moy": (np.arange(365) // 31 + 1).astype(np.int64),
            "d_year": (2000 + np.arange(365) % 3).astype(np.int64),
        }
    )
    items = pd.DataFrame(
        {
            "i_item_sk": np.arange(300, dtype=np.int64),
            "i_brand": rng.integers(0, 40, 300).astype(np.int64),
        }
    )
    return fact, dates, items


def _oracle(fact, dates, items):
    m = fact.merge(dates[dates.d_moy == 5], left_on="date_sk", right_on="d_date_sk")
    m = m.merge(items, left_on="item_sk", right_on="i_item_sk")
    return (
        m.groupby(["d_year", "i_brand"])
        .agg(s=("price", "sum"))
        .reset_index()
        .sort_values(["d_year", "i_brand"])
        .reset_index(drop=True)
    )


def _q3_plan(fact_schema, dd_schema, it_schema):
    """scan -> bhj(date, moy=5) -> bhj(item) -> project -> partial agg ->
    mesh_exchange(hash[d_year, i_brand]) -> final agg."""
    scan = B.ffi_reader(fact_schema, "fact")
    dscan = B.filter_(B.ffi_reader(dd_schema, "dd"), [BinaryOp("eq", col(1), lit(5))])
    iscan = B.ffi_reader(it_schema, "it")
    j1 = B.hash_join(scan, dscan, [col(0)], [col(0)], "inner", build_side="right")
    # fact(3) + date(3): d_year at 5; item join on item_sk (1)
    j2 = B.hash_join(j1, iscan, [col(1)], [col(0)], "inner", build_side="right")
    # + item(2): i_brand at 7
    proj = B.project(j2, [(col(5), "d_year"), (col(7), "i_brand"), (col(2), "price")])
    partial = B.hash_agg(
        proj, [(col(0), "d_year"), (col(1), "i_brand")], [("sum", col(2), "s")],
        "partial",
    )
    part = B.hash_partitioning([col(0), col(1)], N_REDUCE)
    ex = B.mesh_exchange(partial, part, "q3ex")
    return B.hash_agg(
        ex, [(col(0), "d_year"), (col(1), "i_brand")], [("sum", col(2), "s")],
        "final",
    )


def _schemas(fact, dates, items):
    def sch(df):
        return T.Schema.from_arrow(
            pa.RecordBatch.from_pandas(df.iloc[:1], preserve_index=False).schema
        )

    return sch(fact), sch(dates), sch(items)


def _fact_chunks(fact):
    per = (len(fact) + N_MAP - 1) // N_MAP
    return [
        pa.RecordBatch.from_pandas(fact.iloc[p * per : (p + 1) * per],
                                   preserve_index=False)
        for p in range(N_MAP)
    ]


def test_split_stages_shapes():
    fact, dates, items = _tables()
    plan = _q3_plan(*_schemas(fact, dates, items))
    stages = split_stages(plan)
    assert len(stages) == 2
    s0, s1 = stages
    assert s0.plan.WhichOneof("plan") == "shuffle_writer"
    assert s0.exchange_id == "q3ex"
    assert s0.num_output_partitions == N_REDUCE
    assert s1.is_final and s1.input_exchange_ids == ["q3ex"]
    assert s1.plan.hash_agg.child.WhichOneof("plan") == "ipc_reader"
    assert s1.plan.hash_agg.child.ipc_reader.resource_id == "q3ex"
    # task instantiation fills per-partition shuffle paths
    t = stage_task(s0, 1, "/tmp/work")
    assert t.plan.shuffle_writer.output_data_file == "/tmp/work/q3ex_map1.data"
    assert t.stage_id == 0 and t.partition_id == 1


def test_nested_exchanges_order():
    """exchange-over-exchange splits into producers-before-consumers."""
    schema = T.Schema.of(T.Field("k", T.INT64), T.Field("v", T.INT64))
    inner = B.mesh_exchange(
        B.ffi_reader(schema, "in"), B.hash_partitioning([col(0)], 2), "exA"
    )
    agg = B.hash_agg(inner, [(col(0), "k")], [("sum", col(1), "s")], "partial")
    outer = B.mesh_exchange(agg, B.hash_partitioning([col(0)], 2), "exB")
    final = B.hash_agg(outer, [(col(0), "k")], [("sum", col(1), "s")], "final")
    stages = split_stages(final)
    assert [s.exchange_id for s in stages] == ["exA", "exB", None]
    assert stages[1].input_exchange_ids == ["exA"]
    assert stages[2].input_exchange_ids == ["exB"]


def _run_stage_inprocess(task_bytes: bytes) -> list[pa.RecordBatch]:
    h = api.call_native(task_bytes)
    out = []
    while (rb := api.next_batch(h)) is not None:
        out.append(rb)
    api.finalize_native(h)
    return out


def test_stage_split_matches_mesh_driver(tmp_path):
    """Drive the two stages as separate task invocations (in-process bridge)
    with the ShuffleManager contract; results match MeshQueryDriver running
    the SAME unsplit plan."""
    from auron_tpu.parallel.mesh import make_mesh
    from auron_tpu.parallel.mesh_driver import MeshQueryDriver

    fact, dates, items = _tables()
    plan = _q3_plan(*_schemas(fact, dates, items))
    chunks = _fact_chunks(fact)
    dd_rb = pa.RecordBatch.from_pandas(dates, preserve_index=False)
    it_rb = pa.RecordBatch.from_pandas(items, preserve_index=False)

    # ---- host-scheduled path
    stages = split_stages(plan)
    mgr = ShuffleManager()
    s0, s1 = stages
    for p in range(N_MAP):
        api.put_resource("fact", [chunks[p]])
        api.put_resource("dd", [dd_rb])
        api.put_resource("it", [it_rb])
        t = stage_task(s0, p, str(tmp_path))
        assert _run_stage_inprocess(t.SerializeToString()) == []
        mgr.register_map_output(
            s0.exchange_id, p,
            t.plan.shuffle_writer.output_data_file,
            t.plan.shuffle_writer.output_index_file,
        )
    frames = []
    api.put_resource(s0.exchange_id, mgr.block_provider(s0.exchange_id))
    for p in range(N_REDUCE):
        t = stage_task(s1, p, str(tmp_path))
        for rb in _run_stage_inprocess(t.SerializeToString()):
            frames.append(rb.to_pandas())
    for k in ("fact", "dd", "it", s0.exchange_id):
        api.remove_resource(k)
    got = (
        pd.concat(frames)
        .sort_values(["d_year", "i_brand"])
        .reset_index(drop=True)
    )

    # ---- engine-scheduled oracle (MeshQueryDriver on the same plan)
    mesh = make_mesh(N_REDUCE)
    driver = MeshQueryDriver(mesh, work_dir=str(tmp_path / "drv"))
    resources = {
        "fact": lambda p: [chunks[p]] if p < N_MAP else [],
        "dd": [dd_rb],
        "it": [it_rb],
    }
    want = (
        driver.collect(plan, resources)
        .sort_values(["d_year", "i_brand"])
        .reset_index(drop=True)
    )

    pd.testing.assert_frame_equal(got, want, check_dtype=False)
    # and both match the pandas oracle
    oracle = _oracle(fact, dates, items)
    assert got["s"].sum() == pytest.approx(oracle["s"].sum(), rel=1e-9)
    assert len(got) == len(oracle)


# ---------------------------------------------------------------------------
# C ABI proof: the same two stages as separate OS processes (VERDICT r2 #3
# done-criterion: per-stage task invocations through the C harness)
# ---------------------------------------------------------------------------


def _build_bridge():
    import shutil

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    native = os.path.join(root, "native")
    if shutil.which("make") is None:
        pytest.skip("no make in this environment")
    r = subprocess.run(
        ["make", "-C", native, "libauron_bridge.so", "bridge_harness"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, f"bridge build failed: {r.stderr[-800:]}"
    return os.path.join(native, "bridge_harness")


def _harness_env():
    import sysconfig

    env = dict(os.environ)
    env["PYTHONPATH"] = sysconfig.get_paths()["purelib"]
    env["JAX_PLATFORMS"] = "cpu"
    env["AURON_TPU_ROOT"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return env


def _ipc_bytes(rb: pa.RecordBatch) -> bytes:
    import io

    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as w:
        w.write_batch(rb)
    return sink.getvalue()


def _decode_framed(path) -> list[dict]:
    import io
    import struct

    data = open(path, "rb").read()
    pos, rows = 0, []
    while pos < len(data):
        (n,) = struct.unpack_from("<Q", data, pos)
        pos += 8
        with pa.ipc.open_stream(io.BytesIO(data[pos : pos + n])) as r:
            for rb in r:
                rows += rb.to_pylist()
        pos += n
    return rows


def test_c_abi_two_stage_execution(tmp_path):
    harness = _build_bridge()
    fact, dates, items = _tables(n=2500)
    plan = _q3_plan(*_schemas(fact, dates, items))
    chunks = _fact_chunks(fact)
    dd_rb = pa.RecordBatch.from_pandas(dates, preserve_index=False)
    it_rb = pa.RecordBatch.from_pandas(items, preserve_index=False)

    stages = split_stages(plan)
    s0, s1 = stages
    work = tmp_path / "shuffle"
    work.mkdir()
    (tmp_path / "dd.bin").write_bytes(_ipc_bytes(dd_rb))
    (tmp_path / "it.bin").write_bytes(_ipc_bytes(it_rb))

    mgr = ShuffleManager()
    # ---- stage 0: one OS process per map task
    for p in range(N_MAP):
        t = stage_task(s0, p, str(work))
        task_f = tmp_path / f"map{p}.task"
        task_f.write_bytes(t.SerializeToString())
        fact_f = tmp_path / f"fact{p}.bin"
        fact_f.write_bytes(_ipc_bytes(chunks[p]))
        out_f = tmp_path / f"map{p}.out"
        r = subprocess.run(
            [harness, str(task_f), str(out_f),
             "fact", str(fact_f), "dd", str(tmp_path / "dd.bin"),
             "it", str(tmp_path / "it.bin")],
            env=_harness_env(), capture_output=True, text=True, timeout=600,
        )
        assert r.returncode == 0, r.stderr[-1500:]
        assert _decode_framed(out_f) == []  # writers emit no rows
        w = t.plan.shuffle_writer
        assert os.path.exists(w.output_data_file)
        mgr.register_map_output(
            s0.exchange_id, p, w.output_data_file, w.output_index_file
        )

    # ---- stage 1: one OS process per reduce task, shuffle fetch via the
    # JSON manifest crossing the C ABI (auron_put_resource_shuffle)
    manifest_f = tmp_path / "manifest.json"
    manifest_f.write_bytes(mgr.manifest(s0.exchange_id))
    rows = []
    for p in range(N_REDUCE):
        t = stage_task(s1, p, str(work))
        task_f = tmp_path / f"red{p}.task"
        task_f.write_bytes(t.SerializeToString())
        out_f = tmp_path / f"red{p}.out"
        r = subprocess.run(
            [harness, str(task_f), str(out_f),
             f"shuffle:{s0.exchange_id}", str(manifest_f)],
            env=_harness_env(), capture_output=True, text=True, timeout=600,
        )
        assert r.returncode == 0, r.stderr[-1500:]
        rows += _decode_framed(out_f)
        metrics = json.loads(r.stdout)
        assert metrics["name"] == "HashAggExec"

    got = (
        pd.DataFrame(rows)
        .sort_values(["d_year", "i_brand"])
        .reset_index(drop=True)
    )
    oracle = _oracle(fact, dates, items)
    assert len(got) == len(oracle)
    assert got["d_year"].tolist() == oracle["d_year"].tolist()
    assert got["i_brand"].tolist() == oracle["i_brand"].tolist()
    for g, w in zip(got["s"], oracle["s"]):
        assert g == pytest.approx(w, rel=1e-9)


def test_c_abi_conversion_service(tmp_path):
    """The conversion service through the C ABI: host-plan JSON ->
    segmentation response, as the JVM shim calls it (auron_convert_plan)."""
    harness = _build_bridge()
    plan = {
        "op": "ProjectExec", "schema": [["k", "long", True]],
        "args": {"projections": [{"kind": "attr", "index": 0, "name": "k"}]},
        "children": [{"op": "LocalTableScanExec",
                      "schema": [["k", "long", True]],
                      "args": {"resource_id": "t"}, "children": []}],
    }
    req = tmp_path / "hostplan.json"
    req.write_text(json.dumps(plan))
    out = tmp_path / "resp.json"
    r = subprocess.run(
        [harness, "--convert", str(req), str(out)],
        env=_harness_env(), capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-1500:]
    resp = json.loads(out.read_text())
    assert resp["converted"] is True
    assert resp["root"]["kind"] == "segment"
    assert resp["root"]["stages"][0]["exchange_id"] is None
    import base64

    from auron_tpu.proto import plan_pb2 as pb

    node = pb.PhysicalPlanNode()
    node.ParseFromString(base64.b64decode(resp["root"]["plan_b64"]))
    assert node.WhichOneof("plan") == "project"

"""Tier-1 wiring of the real-text SQL differential gate (models/sqlgate.py).

Four layers, mirroring the reference's auron-it suites:

- plan-stability DRIFT CHECK: every corpus text compiles in THIS process
  and its rendered plan must equal the checked-in golden
  (tests/goldens/sql/*.txt). pytest runs with a fresh PYTHONHASHSEED each
  invocation, so this doubles as a cross-process determinism gate — any
  dict-order leakage into the plan rendering fails here first;
- toy-scale DIFFERENTIAL run of a representative subset (full corpus is
  @slow — `make sqlgate` runs it at SF=4);
- the UNSUPPORTED corpus: every out-of-subset real text raises a
  positioned SqlUnsupported, never a wrong result;
- TEETH checks: a mutated golden and a wrong oracle must both fail.
"""

import pytest

from auron_tpu.models import sqlgate, tpcds
from auron_tpu.sql import SqlUnsupported, compile_text
from auron_tpu.sql.catalog import build_tables

TOY_SF = 0.02
# diverse shapes: verbatim star-join (q3), GROUP/HAVING basket count
# (q34), CTE + week-over-week self-join (q59), scalar aggregate (q96),
# multi-channel-adapted UNION ALL rollup (q5a) and anti-join (q93a)
SUBSET = ["q3", "q34", "q59", "q96", "q5a", "q93a"]


@pytest.fixture(scope="module", autouse=True)
def _suite_leak_canary(leak_canary):
    """Tier-1 leak canary (conftest): runtimes/resource-map/obs rings
    must return to their pre-suite baselines after this module."""
    yield


@pytest.fixture(scope="module")
def catalog():
    return sqlgate.gate_catalog()


@pytest.fixture(scope="module")
def frames():
    data = tpcds.generate(sf=TOY_SF, seed=42)
    return build_tables(data, seed=42)


# ---------------------------------------------------------------------------
# plan-stability goldens (the drift gate)
# ---------------------------------------------------------------------------


def test_every_case_has_a_golden_and_no_drift(catalog):
    import os

    missing, drifted = [], []
    for case in sqlgate.CASES:
        lq = compile_text(case.sql, catalog)
        path = os.path.join(sqlgate.GOLDEN_DIR, f"{case.name}.txt")
        if not os.path.exists(path):
            missing.append(case.name)
            continue
        if sqlgate.check_golden(case.name, sqlgate.plan_text(lq)):
            drifted.append(case.name)
    assert not missing, f"no golden checked in for {missing}"
    assert not drifted, (
        f"plan drift vs goldens for {drifted} — if the lowering change is "
        "intentional, regenerate with AURON_SQL_UPDATE_GOLDENS=1")


def test_no_stray_goldens():
    """Every golden file corresponds to a live corpus query."""
    import os

    names = {c.name for c in sqlgate.CASES}
    stray = [f for f in os.listdir(sqlgate.GOLDEN_DIR)
             if f.endswith(".txt") and f[:-4] not in names]
    assert not stray


def test_corpus_size_and_verbatim_floor():
    """The acceptance floor: >= 20 real texts, >= 10 unsupported."""
    assert len(sqlgate.CASES) >= 20
    assert sum(c.verbatim for c in sqlgate.CASES) >= 12
    assert len(sqlgate.UNSUPPORTED) >= 10


def test_golden_teeth(tmp_path, monkeypatch, catalog):
    """A corrupted golden must be reported as drift."""
    case = sqlgate.case_by_name("q3")
    lq = compile_text(case.sql, catalog)
    monkeypatch.setattr(sqlgate, "GOLDEN_DIR", str(tmp_path))
    text = sqlgate.plan_text(lq)
    assert sqlgate.check_golden("q3", text) is None  # first write
    (tmp_path / "q3.txt").write_text(text.replace("hash_agg", "smash_agg", 1))
    err = sqlgate.check_golden("q3", text)
    assert err is not None and "drift" in err


# ---------------------------------------------------------------------------
# determinism: two independent parses render identically
# ---------------------------------------------------------------------------


def test_table_uses_match_emitted_scans(catalog):
    """LoweredQuery.tables lists EXACTLY the rids the plans scan: a
    probe-seed derived table is lowered replicated first (schema
    discovery) then re-lowered partitioned, and the discarded phase-1
    plan's replicated rids must not survive — they would upload full
    copies of the fact table nothing scans (q34-family regression)."""
    from auron_tpu.sql.lowering import STAGE_RID, _scan_rids

    for case in sqlgate.CASES:
        lq = compile_text(case.sql, catalog)
        scanned = _scan_rids(lq.distributed)
        if lq.collect is not None:
            scanned |= _scan_rids(lq.collect)
        scanned.discard(STAGE_RID)
        assert {u.rid for u in lq.tables} == scanned, case.name
    # the q34 shape specifically must NOT replicate the fact table
    lq = compile_text(sqlgate.case_by_name("q34").sql, catalog)
    assert "sql:store_sales:all" not in {u.rid for u in lq.tables}


def test_oracle_head_tie_rules():
    """TieError only when the tie class CROSSES the limit boundary."""
    import dataclasses

    import pandas as pd

    base = sqlgate.case_by_name("q3")
    # tie entirely inside the head: deterministic, accepted
    df = pd.DataFrame({"k": [1, 1, 2, 3], "v": [10, 20, 30, 40]})
    c = dataclasses.replace(base, order=("k",), ascending=(True,), limit=3)
    head = sqlgate.oracle_head(df, c)
    assert head["v"].tolist() == [10, 20, 30]
    # non-identical tie across the boundary: refused
    df2 = pd.DataFrame({"k": [1, 2, 2, 2], "v": [10, 20, 30, 40]})
    c2 = dataclasses.replace(base, order=("k",), ascending=(True,), limit=2)
    with pytest.raises(sqlgate.TieError):
        sqlgate.oracle_head(df2, c2)
    # identical rows tying across the boundary: any pick is the same row
    df3 = pd.DataFrame({"k": [1, 2, 2], "v": [10, 20, 20]})
    assert len(sqlgate.oracle_head(df3, c2)) == 2


def test_two_independent_parses_render_identically(catalog):
    for name in ("q59", "q65", "q5a"):  # CTEs, derived tables, UNION ALL
        case = sqlgate.case_by_name(name)
        a = sqlgate.plan_text(compile_text(case.sql, catalog))
        b = sqlgate.plan_text(compile_text(case.sql, catalog))
        assert a == b, name


# ---------------------------------------------------------------------------
# toy-scale differential run
# ---------------------------------------------------------------------------


def test_subset_matches_oracle_at_toy_scale(frames):
    recs = sqlgate.run_gate(sf=TOY_SF, names=SUBSET, frames=frames)
    bad = [r for r in recs if not r["ok"]]
    assert not bad, bad
    assert sum(r["rows"] or 0 for r in recs) > 0


@pytest.mark.slow
def test_full_corpus_matches_oracle(frames):
    recs = sqlgate.run_gate(sf=TOY_SF, frames=frames)
    bad = [r for r in recs if not r["ok"]]
    assert not bad, bad


def test_comparator_teeth(frames):
    """A wrong oracle must FAIL the case — the diff has teeth."""
    case = sqlgate.case_by_name("q96")

    def wrong_oracle(fr):
        df = case.oracle(fr).copy()
        df.iloc[0, 0] = df.iloc[0, 0] + 1
        return df

    import dataclasses

    broken = dataclasses.replace(case, oracle=wrong_oracle)
    from auron_tpu.parallel.mesh import make_mesh

    rec = sqlgate.run_case(
        broken, frames, make_mesh(2), sqlgate.gate_catalog(),
        2, {}, 1e-6)
    assert not rec["ok"] and rec["error"]


# ---------------------------------------------------------------------------
# unsupported corpus: loud, positioned diagnostics
# ---------------------------------------------------------------------------


def test_unsupported_corpus_all_diagnosed(catalog):
    recs = sqlgate.run_unsupported(catalog)
    bad = [r for r in recs if not r["ok"]]
    assert not bad, bad


def test_unsupported_diagnostic_payload(catalog):
    case_sql, construct = sqlgate.UNSUPPORTED["q70"]
    with pytest.raises(SqlUnsupported) as ei:
        compile_text(case_sql, catalog)
    e = ei.value
    assert e.construct == construct
    assert e.pos.line >= 1 and e.pos.col >= 1
    # the rendered message carries the source position and the construct
    assert str(e.pos.line) in str(e) and "window" in str(e)

"""Property tests for the multiword branchless binary search."""

import numpy as np
import jax.numpy as jnp

from auron_tpu.ops.binsearch import lower_bound, upper_bound


def test_single_word_vs_numpy():
    rng = np.random.default_rng(13)
    for n in (0, 1, 2, 5, 40, 1000):
        arr = np.sort(rng.integers(0, 50, n).astype(np.uint64))
        q = rng.integers(-1, 52, 300).astype(np.uint64)
        lo = np.asarray(lower_bound([jnp.asarray(arr)], [jnp.asarray(q)], n))
        hi = np.asarray(upper_bound([jnp.asarray(arr)], [jnp.asarray(q)], n))
        want_lo = np.searchsorted(arr, q, side="left")
        want_hi = np.searchsorted(arr, q, side="right")
        assert (lo == want_lo).all(), n
        assert (hi == want_hi).all(), n


def test_multi_word_lexicographic():
    rng = np.random.default_rng(14)
    n = 500
    w1 = rng.integers(0, 8, n).astype(np.uint64)
    w2 = rng.integers(0, 8, n).astype(np.uint64)
    order = np.lexsort((w2, w1))
    w1, w2 = w1[order], w2[order]
    packed = w1 * 8 + w2
    q1 = rng.integers(0, 8, 200).astype(np.uint64)
    q2 = rng.integers(0, 8, 200).astype(np.uint64)
    qp = q1 * 8 + q2
    lo = np.asarray(lower_bound([jnp.asarray(w1), jnp.asarray(w2)],
                                [jnp.asarray(q1), jnp.asarray(q2)], n))
    hi = np.asarray(upper_bound([jnp.asarray(w1), jnp.asarray(w2)],
                                [jnp.asarray(q1), jnp.asarray(q2)], n))
    assert (lo == np.searchsorted(packed, qp, side="left")).all()
    assert (hi == np.searchsorted(packed, qp, side="right")).all()

"""Randomized plan-composition fuzz vs a pandas oracle.

The reference re-runs ~490 forked Spark SQL suite files per version; the
breadth analog here is generative: seeded random operator pipelines
(filter / project / join / partial+final agg / sort / limit / union)
built through the protobuf plan IR and executed through the real bridge,
each mirrored step-by-step on pandas. Every seed is a new plan shape;
failures reproduce from the printed seed.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.bridge import api
from auron_tpu.columnar import Batch
from auron_tpu.exprs.ir import BinaryOp, Literal, col
from auron_tpu.plan import builders as B

N_SEEDS = 30


def _frame(rng, n):
    df = pd.DataFrame({
        "a": rng.integers(-50, 50, n).astype(np.int64),
        "b": rng.integers(0, 8, n).astype(np.int64),
        "c": rng.standard_normal(n).round(3),
        "d": rng.integers(0, 1000, n).astype(np.int64),
    })
    # inject nulls into one nullable column via arrow (NaN -> null for c)
    df.loc[rng.random(n) < 0.1, "c"] = np.nan
    return df


def _schema_of(df):
    return T.Schema.from_arrow(
        pa.RecordBatch.from_pandas(df.iloc[:1], preserve_index=False).schema
    )


def _run_plan(plan, n_parts=1):
    frames = []
    for p in range(n_parts):
        h = api.call_native(B.task(plan, partition_id=p).SerializeToString())
        while (rb := api.next_batch(h)) is not None:
            frames.append(rb.to_pandas())
        api.finalize_native(h)
    return (pd.concat(frames).reset_index(drop=True)
            if frames else pd.DataFrame())


def _apply_random_op(rng, plan, df, depth):
    """One random (plan node, pandas mirror) transformation; returns
    (plan, df, done). Column layout: keep positional alignment by always
    materializing the mirror's columns in plan output order."""
    cols = list(df.columns)
    op = rng.choice(["filter", "project", "agg", "sort_limit", "union"])
    if op == "filter" and len(df):
        ci = int(rng.integers(0, len(cols)))
        if df[cols[ci]].dtype == np.float64:
            thr = float(np.nan_to_num(df[cols[ci]]).mean())
            pred = BinaryOp("gt", col(ci), Literal(thr, T.FLOAT64))
            keep = df[cols[ci]] > thr  # NaN/null -> False on both sides
        else:
            thr = int(df[cols[ci]].median()) if len(df) else 0
            pred = BinaryOp("lteq", col(ci), Literal(thr, T.INT64))
            keep = df[cols[ci]] <= thr
        return B.filter_(plan, [pred]), df[keep].reset_index(drop=True), False
    if op == "project":
        # keep a random non-empty subset + one arithmetic derivation
        k = int(rng.integers(1, len(cols) + 1))
        idx = sorted(rng.choice(len(cols), size=k, replace=False).tolist())
        exprs = [(col(i), cols[i]) for i in idx]
        out = df[[cols[i] for i in idx]].copy()
        int_cols = [i for i in idx if df[cols[i]].dtype == np.int64]
        if int_cols:
            src = int(rng.choice(int_cols))
            exprs.append((BinaryOp("add", col(src), Literal(1, T.INT64)), "derived"))
            out["derived"] = df[cols[src]] + 1
        return B.project(plan, exprs), out.reset_index(drop=True), False
    if op == "agg":
        int_cols = [i for i, c in enumerate(cols) if df[c].dtype == np.int64]
        if not int_cols:
            return plan, df, False
        gi = int(rng.choice(int_cols))
        vi = int(rng.choice(int_cols))
        p1 = B.hash_agg(plan, [(col(gi), "g")],
                        [("sum", col(vi), "s"), ("count_star", None, "n")],
                        "partial")
        p2 = B.hash_agg(p1, [(col(0), "g")],
                        [("sum", col(1), "s"), ("count", col(2), "n")],
                        "final")
        out = (df.groupby(cols[gi]).agg(s=(cols[vi], "sum"),
                                        n=(cols[vi], "size"))
               .reset_index().rename(columns={cols[gi]: "g"}))
        out["n"] = out["n"].astype(np.int64)
        return p2, out.reset_index(drop=True), "terminal"
    if op == "sort_limit" and len(df.columns):
        from auron_tpu.ops.sortkeys import SortSpec

        ci = int(rng.integers(0, len(cols)))
        asc = bool(rng.integers(0, 2))
        k = int(rng.integers(1, max(len(df), 2)))
        plan = B.sort(plan, [(col(ci), SortSpec(asc=asc))], fetch=k)
        out = df.sort_values(
            cols[ci], ascending=asc, kind="stable", na_position="first"
        ).head(k).reset_index(drop=True)
        return plan, out, "ordered"
    if op == "union":
        return B.union([plan, plan]), pd.concat([df, df]).reset_index(drop=True), False
    return plan, df, False


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_random_pipeline_matches_pandas(seed):
    rng = np.random.default_rng(1000 + seed)
    df = _frame(rng, int(rng.integers(200, 1200)))
    rid = f"fuzz_{seed}"
    api.put_resource(rid, [[Batch.from_arrow(
        pa.RecordBatch.from_pandas(df, preserve_index=False))]])
    try:
        plan = B.memory_scan(_schema_of(df), rid)
        ordered = False
        for _ in range(int(rng.integers(2, 6))):
            plan, df, status = _apply_random_op(rng, plan, df, 0)
            if status == "ordered":
                ordered = True  # top-k output order is part of the contract
                break
            if status == "terminal":
                break  # agg: stable shape, but row order unspecified
        got = _run_plan(plan)
        want = df
        assert len(got) == len(want), (seed, len(got), len(want))
        if not len(want):
            return
        if not ordered:
            got = got.sort_values(list(got.columns)).reset_index(drop=True)
            want = want.sort_values(list(want.columns)).reset_index(drop=True)
        got.columns = want.columns  # names may differ; layout is positional
        pd.testing.assert_frame_equal(got, want, check_dtype=False, atol=1e-9)
    finally:
        api.remove_resource(rid)

"""Bit-exactness tests for Spark hash kernels.

Expected values are Spark-generated vectors (Murmur3Hash / XxHash64 with
seed 42), the same spec vectors the reference engine tests against
(datafusion-ext-commons/src/spark_hash.rs:416-520).
"""

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu.columnar import Batch
from auron_tpu.ops.hash_dispatch import hash_batch


def _m3(data_dict, cols=None, schema=None):
    b = Batch.from_pydict(data_dict, schema=schema)
    cols = cols if cols is not None else list(range(len(b.schema)))
    return np.asarray(hash_batch(b, cols, "murmur3"))[: b.num_rows()].tolist()


def _xx(data_dict, cols=None, schema=None):
    b = Batch.from_pydict(data_dict, schema=schema)
    cols = cols if cols is not None else list(range(len(b.schema)))
    return np.asarray(hash_batch(b, cols, "xxhash64"))[: b.num_rows()].tolist()


def _i32(vals):
    return [v - (1 << 32) if v >= (1 << 31) else v for v in vals]


def test_murmur3_i32():
    got = _m3({"x": pa.array([1, 2, 3, 4], type=pa.int32())})
    assert got == [-559580957, 1765031574, -1823081949, -397064898]


def test_murmur3_i8():
    got = _m3({"x": pa.array([1, 0, -1, 127, -128], type=pa.int8())})
    assert got == _i32([0xDEA578E3, 0x379FAE8F, 0xA0590E3D, 0x43B4D8ED, 0x422A1365])


def test_murmur3_i64():
    got = _m3(
        {"x": pa.array([1, 0, -1, 2**63 - 1, -(2**63)], type=pa.int64())}
    )
    assert got == _i32([0x99F0149D, 0x9C67B85D, 0xC8008529, 0xA05B5D7B, 0xCD1E64FB])


def test_murmur3_str():
    got = _m3({"s": pa.array(["hello", "bar", "", "😁", "天地"])})
    assert got == _i32([3286402344, 2486176763, 142593372, 885025535, 2395000894])


def test_xxhash64_i64():
    got = _xx({"x": pa.array([1, 0, -1, 2**63 - 1, -(2**63)], type=pa.int64())})
    assert got == [
        -7001672635703045582,
        -5252525462095825812,
        3858142552250413010,
        -3246596055638297850,
        -8619748838626508300,
    ]


def test_xxhash64_str():
    got = _xx({"s": pa.array(["hello", "bar", "", "😁", "天地"])})
    assert got == [
        -4367754540140381902,
        -1798770879548125814,
        -7444071767201028348,
        -6337236088984028203,
        -235771157374669727,
    ]


def test_null_skips_and_chaining():
    # NULL leaves the running hash at its seed: hash(null) == seed 42 pattern
    got = _m3({"x": pa.array([None, 1], type=pa.int32())})
    # row0: no column contributes -> result is the initial seed 42
    assert got[0] == 42
    assert got[1] == -559580957
    # chaining: hash((a,b)) must differ from hash(a) and use a's hash as seed
    two = _m3(
        {
            "a": pa.array([1], type=pa.int32()),
            "b": pa.array([2], type=pa.int32()),
        }
    )
    one = _m3({"a": pa.array([1], type=pa.int32())})
    assert two != one
    # null in second column: result equals hash of first column alone
    mixed = _m3(
        {
            "a": pa.array([1], type=pa.int32()),
            "b": pa.array([None], type=pa.int32()),
        }
    )
    assert mixed == one


def test_murmur3_bool_float_decimal():
    import decimal as d

    got_b = _m3({"x": pa.array([True, False])})
    # Spark hashes bool as int 1/0
    assert got_b == _m3({"x": pa.array([1, 0], type=pa.int32())})
    # float hashes its bit pattern as 4 bytes / 8 bytes
    got_f = _m3({"x": pa.array([1.0, -0.0], type=pa.float32())})
    assert len(set(got_f)) == 2
    # decimal64 must hash like a 16-byte unscaled int128
    got_d = _m3({"x": pa.array([d.Decimal("1.23"), d.Decimal("-1.23")], type=pa.decimal128(10, 2))})
    assert len(set(got_d)) == 2


def test_long_string_xxhash64():
    # >= 32 bytes exercises the 4-accumulator streaming path; cross-check a
    # couple of lengths against the pure-python reference implementation below
    def xxh64_py(data: bytes, seed: int = 42) -> int:
        M = (1 << 64) - 1
        P1, P2, P3, P4, P5 = (
            0x9E3779B185EBCA87,
            0xC2B2AE3D27D4EB4F,
            0x165667B19E3779F9,
            0x85EBCA77C2B2AE63,
            0x27D4EB2F165667C5,
        )

        def rotl(x, r):
            return ((x << r) | (x >> (64 - r))) & M

        def rnd(acc, lane):
            return (rotl((acc + lane * P2) & M, 31) * P1) & M

        i, n = 0, len(data)
        if n >= 32:
            v = [(seed + P1 + P2) & M, (seed + P2) & M, seed, (seed - P1) & M]
            while i + 32 <= n:
                for j in range(4):
                    lane = int.from_bytes(data[i : i + 8], "little")
                    v[j] = rnd(v[j], lane)
                    i += 8
            acc = (rotl(v[0], 1) + rotl(v[1], 7) + rotl(v[2], 12) + rotl(v[3], 18)) & M
            for j in range(4):
                acc = ((acc ^ rnd(0, v[j])) * P1 + P4) & M
        else:
            acc = (seed + P5) & M
        acc = (acc + n) & M
        while i + 8 <= n:
            lane = int.from_bytes(data[i : i + 8], "little")
            acc = ((rotl(acc ^ rnd(0, lane), 27) * P1) + P4) & M
            i += 8
        if i + 4 <= n:
            word = int.from_bytes(data[i : i + 4], "little")
            acc = ((rotl(acc ^ (word * P1) & M, 23) * P2) + P3) & M
            i += 4
        while i < n:
            acc = (rotl(acc ^ (data[i] * P5) & M, 11) * P1) & M
            i += 1
        acc ^= acc >> 33
        acc = (acc * P2) & M
        acc ^= acc >> 29
        acc = (acc * P3) & M
        acc ^= acc >> 32
        return acc - (1 << 64) if acc >= (1 << 63) else acc

    strings = ["a" * 31, "b" * 32, "c" * 33, "d" * 64, "e" * 100, "xyz" * 17]
    got = _xx({"s": pa.array(strings)})
    want = [xxh64_py(s.encode()) for s in strings]
    assert got == want

"""Randomized aggregation differential testing vs pandas groupby, plus the
incremental-vs-legacy bit-identity fuzz (docs/agg.md contract: with
exec.agg.incremental.enable flipped, the SAME rows with the SAME exact
values must come out — fingerprint grouping, sorted-state probe/scatter
and merge-path merges are pure execution-strategy changes)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exec.agg_exec import FINAL, PARTIAL, AggExpr, HashAggExec
from auron_tpu.exec.base import ExecutionContext
from auron_tpu.exec.basic import MemoryScanExec
from auron_tpu.exprs.ir import col
from auron_tpu.utils.config import (
    AGG_INCREMENTAL_ENABLE,
    AGG_INCREMENTAL_FINGERPRINT,
    AGG_INCREMENTAL_FP_BITS,
    AGG_INCREMENTAL_MERGEPATH,
    AGG_INCREMENTAL_PROBE,
    BATCH_SIZE,
    Configuration,
    conf_scope,
)


@pytest.mark.parametrize("seed", range(8))
def test_agg_fuzz(seed):
    rng = np.random.default_rng(seed + 500)
    n = int(rng.integers(1, 3000))
    n_keys = int(rng.integers(1, 3))
    key_range = int(rng.integers(1, 60))
    df = pd.DataFrame({
        "k1": rng.integers(0, key_range, n),
        "k2": rng.choice(["a", "b", "c", None], n, p=[0.3, 0.3, 0.3, 0.1]),
        "v": pd.array(
            np.where(rng.random(n) < 0.12, np.nan, rng.normal(size=n).round(4)),
            dtype="Float64",
        ),
    })
    chunk = int(rng.integers(64, 1024))
    batches = [
        Batch.from_arrow(pa.RecordBatch.from_pandas(df.iloc[i:i+chunk], preserve_index=False))
        for i in range(0, n, chunk)
    ]
    gcols = ["k1", "k2"][:n_keys]
    groupings = [(col(i), gcols[i]) for i in range(n_keys)]
    aggs = [
        (AggExpr("sum", col(2)), "s"),
        (AggExpr("count", col(2)), "c"),
        (AggExpr("count_star", None), "cs"),
        (AggExpr("min", col(2)), "mn"),
        (AggExpr("max", col(2)), "mx"),
        (AggExpr("avg", col(2)), "a"),
    ]
    scan = MemoryScanExec.single(batches)
    partial = HashAggExec(scan, groupings, aggs, PARTIAL)
    mid = list(partial.execute(0, ExecutionContext()))
    final = HashAggExec(MemoryScanExec.single(mid), groupings, aggs, FINAL)
    got = final.collect().to_pandas().sort_values(gcols, na_position="last").reset_index(drop=True)

    want = (
        df.groupby(gcols, dropna=False)
        .agg(s=("v", "sum"), c=("v", "count"), cs=("v", "size"),
             mn=("v", "min"), mx=("v", "max"), a=("v", "mean"))
        .reset_index()
        .sort_values(gcols, na_position="last")
        .reset_index(drop=True)
    )
    assert len(got) == len(want), (len(got), len(want))
    assert got["c"].tolist() == want["c"].tolist()
    assert got["cs"].tolist() == want["cs"].tolist()
    for colname in ("s", "mn", "mx", "a"):
        for g, w, c in zip(got[colname], want[colname], want["c"]):
            if c == 0:
                assert pd.isna(g)  # SQL: all-null group -> NULL (pandas: 0.0 for sum)
            else:
                assert g == pytest.approx(w, rel=1e-9), (colname, g, w)


# ---------------------------------------------------------------------------
# incremental-vs-legacy bit-identity (exec.agg.incremental.*)
# ---------------------------------------------------------------------------


def _run_pipeline(batches_fn, groupings, aggs, conf):
    """partial -> final under an explicit Configuration; canonical-sorted
    pandas frame of the result."""
    with conf_scope(conf):
        scan = MemoryScanExec.single(batches_fn())
        partial = HashAggExec(scan, groupings, aggs, PARTIAL)
        ctx = ExecutionContext(conf=conf)
        mid = list(partial.execute(0, ctx)) or [Batch.empty(partial.inter_schema)]
        final = HashAggExec(MemoryScanExec.single(mid), groupings, aggs, FINAL)
        ctx2 = ExecutionContext(conf=conf)
        frames = [b.to_pandas() for b in final.execute(0, ctx2)]
    out = pd.concat(frames)
    keys = [name for _, name in groupings]
    out = out.sort_values(keys, na_position="last").reset_index(drop=True)
    return out, ctx.metrics.values, ctx2.metrics.values


def _assert_bit_identical(inc: pd.DataFrame, leg: pd.DataFrame):
    assert len(inc) == len(leg), (len(inc), len(leg))
    assert list(inc.columns) == list(leg.columns)
    for c in inc.columns:
        for i, (a, b) in enumerate(zip(inc[c], leg[c])):
            if pd.isna(a) and pd.isna(b):
                continue
            assert a == b, (c, i, a, b)


def _inc_conf(enable: bool, fp_bits: int = 64, batch_size: int = 131072):
    # mechanisms pinned "on" explicitly: their auto default is
    # accelerator-only and this suite runs on the CPU backend
    mode = "on" if enable else "off"
    return (
        Configuration()
        .set(AGG_INCREMENTAL_ENABLE, enable)
        .set(AGG_INCREMENTAL_FINGERPRINT, mode)
        .set(AGG_INCREMENTAL_PROBE, mode)
        .set(AGG_INCREMENTAL_MERGEPATH, mode)
        .set(AGG_INCREMENTAL_FP_BITS, fp_bits)
        .set(BATCH_SIZE, batch_size)
    )


_EXACT_AGGS = [
    (AggExpr("sum", col(2)), "s"),
    (AggExpr("count", col(2)), "c"),
    (AggExpr("count_star", None), "cs"),
    (AggExpr("min", col(2)), "mn"),
    (AggExpr("max", col(2)), "mx"),
]


def _dyadic_frame(seed: int, n: int, key_fn):
    """Group keys + a float column of dyadic rationals (k/1024, |k| < 2^20):
    float64 sums of these are EXACT, so the result is independent of
    summation order — the property that makes bit-identity assertable
    across different grouping strategies (and the one the collision test
    leans on: forced collisions legally reorder partial sums)."""
    rng = np.random.default_rng(seed)
    v = rng.integers(-(1 << 20), 1 << 20, n) / 1024.0
    v = np.where(rng.random(n) < 0.1, np.nan, v)
    df = pd.DataFrame({"k1": key_fn(rng, n), "k2": rng.integers(0, 5, n),
                       "v": pd.array(v, dtype="Float64")})
    return df


def _batches_of(df, chunk=1024):
    return lambda: [
        Batch.from_arrow(
            pa.RecordBatch.from_pandas(df.iloc[i:i + chunk], preserve_index=False)
        )
        for i in range(0, len(df), chunk)
    ]


@pytest.mark.parametrize("seed", range(3))
def test_incremental_bit_identical_plain(seed):
    """Production shape (64-bit fingerprints, no collisions): EVERYTHING —
    first included — must be bit-identical between the incremental and
    legacy paths."""
    # big key spread keeps the dense direct-address path out of the way so
    # the fingerprint/probe/merge-path machinery is what actually runs
    df = _dyadic_frame(seed, 20000,
                       lambda rng, n: rng.integers(0, 400, n) * 1_000_003)
    aggs = _EXACT_AGGS + [
        (AggExpr("avg", col(2)), "a"),
        (AggExpr("first_ignores_null", col(2)), "f"),
    ]
    groupings = [(col(0), "k1"), (col(1), "k2")]
    mk = _batches_of(df)
    inc, pm, _ = _run_pipeline(mk, groupings, aggs, _inc_conf(True, batch_size=1024))
    leg, _, _ = _run_pipeline(mk, groupings, aggs, _inc_conf(False, batch_size=1024))
    _assert_bit_identical(inc, leg)
    assert pm.get("fp_collision_batches", 0) == 0


@pytest.mark.parametrize("fp_bits", [1, 3, 8])
def test_incremental_bit_identical_forced_collisions(fp_bits):
    """Seeded-hash collision forcing (exec.agg.incremental.fp.bits): tiny
    fingerprint widths make every batch collide. Deterministic aggregates
    must still be bit-identical — collisions may only change GROUPING
    ORDER internally, never values — and the collisions must be visible
    in the fp_collision_batches counter. (`first` is excluded here: under
    collisions both paths make different-but-equally-valid Spark `first`
    picks; its collision behavior is pinned separately below.)"""
    df = _dyadic_frame(fp_bits, 12000,
                       lambda rng, n: rng.integers(0, 300, n) * 1_000_003)
    groupings = [(col(0), "k1"), (col(1), "k2")]
    mk = _batches_of(df)
    inc, pm, fm = _run_pipeline(
        mk, groupings, _EXACT_AGGS + [(AggExpr("avg", col(2)), "a")],
        _inc_conf(True, fp_bits, batch_size=1024),
    )
    leg, _, _ = _run_pipeline(
        mk, groupings, _EXACT_AGGS + [(AggExpr("avg", col(2)), "a")],
        _inc_conf(False, batch_size=1024),
    )
    _assert_bit_identical(inc, leg)
    assert pm.get("fp_collision_batches", 0) > 0


def test_incremental_first_under_collisions_is_a_valid_pick():
    """`first` is Spark-nondeterministic across merges; under forced
    collisions the incremental path may pick a different row than legacy.
    The contract: the pick is some non-null value OF THAT GROUP."""
    rng = np.random.default_rng(11)
    n = 8000
    df = pd.DataFrame({
        "k1": rng.integers(0, 200, n) * 1_000_003,
        "k2": np.zeros(n, np.int64),
        "v": pd.array(rng.integers(0, 10_000, n).astype(float), dtype="Float64"),
    })
    groupings = [(col(0), "k1")]
    aggs = [(AggExpr("first_ignores_null", col(2)), "f")]
    mk = _batches_of(df)
    inc, _, _ = _run_pipeline(mk, groupings, aggs, _inc_conf(True, 2, batch_size=1024))
    allowed = df.groupby("k1")["v"].agg(lambda s: set(s.dropna()))
    assert len(inc) == len(allowed)
    for k, f in zip(inc["k1"], inc["f"]):
        assert f in allowed[k], (k, f)


def test_incremental_collision_arising_at_final_merge():
    """A collision can FIRST arise inside the final merge: three final
    input parts, each internally collision-free (single key per part), but
    key K lives in parts A and C while a colliding key K2 sits in B — the
    merged fp order interleaves K(A), K2(B), K(C), splitting K. The FINAL
    merge must dedup with the full-word sort: a key must never surface as
    two output rows (review finding: the clean-parts fast path used to let
    these split groups escape)."""
    # FLOAT keys keep the dense direct-address path out (ints would take
    # it and erase the parts' fp provenance); both collide at 1-bit fps
    K, K2 = 7.0 * 10**13, 1.0 * 10**15
    groupings = [(col(0), "k")]
    aggs = [(AggExpr("sum", col(1)), "s"), (AggExpr("count_star", None), "c")]
    for bits in (1,):
        conf = _inc_conf(True, bits)
        with conf_scope(conf):
            # three SEPARATE partial runs -> three clean single-key parts
            parts = []
            for k, vals in ((K, [1.0, 3.0]), (K2, [10.0, 30.0]),
                            (K, [100.0, 300.0])):
                p = HashAggExec(
                    MemoryScanExec.single(
                        [Batch.from_pydict({"k": [k] * 2, "v": vals})]),
                    groupings, aggs, PARTIAL)
                parts.extend(p.execute(0, ExecutionContext(conf=conf)))
            assert all(getattr(x, "_fp_order", False) for x in parts)
            final = HashAggExec(
                MemoryScanExec.single(parts), groupings, aggs, FINAL)
            out = pd.concat(
                b.to_pandas()
                for b in final.execute(0, ExecutionContext(conf=conf))
            ).sort_values("k").reset_index(drop=True)
        assert out["k"].tolist() == [K, K2], out
        assert out["s"].tolist() == [404.0, 40.0]
        assert out["c"].tolist() == [4, 2]


def test_incremental_collision_at_final_merge_host_aggs():
    """Same clean-parts collision interleave as above, but with a HOST
    aggregate (collect_list), which routes _group_reduce through the EAGER
    branch: force_full_sort must thread through it too (review finding:
    the eager branch used to drop it, re-colliding the same fingerprints
    and emitting the split group as two output rows)."""
    K, K2 = 7.0 * 10**13, 1.0 * 10**15
    groupings = [(col(0), "k")]
    aggs = [(AggExpr("collect_list", col(1)), "l"),
            (AggExpr("count_star", None), "c")]
    conf = _inc_conf(True, 1)
    with conf_scope(conf):
        parts = []
        for k, vals in ((K, [1.0, 3.0]), (K2, [10.0, 30.0]),
                        (K, [100.0, 300.0])):
            p = HashAggExec(
                MemoryScanExec.single(
                    [Batch.from_pydict({"k": [k] * 2, "v": vals})]),
                groupings, aggs, PARTIAL)
            parts.extend(p.execute(0, ExecutionContext(conf=conf)))
        assert all(getattr(x, "_fp_order", False) for x in parts)
        final = HashAggExec(
            MemoryScanExec.single(parts), groupings, aggs, FINAL)
        out = pd.concat(
            b.to_pandas()
            for b in final.execute(0, ExecutionContext(conf=conf))
        ).sort_values("k").reset_index(drop=True)
    assert out["k"].tolist() == [K, K2], out
    # one row per key; collect order across merged parts is unspecified
    assert sorted(out["l"][0]) == [1.0, 3.0, 100.0, 300.0]
    assert sorted(out["l"][1]) == [10.0, 30.0]
    assert out["c"].tolist() == [4, 2]


def test_incremental_null_vs_zero_group_keys():
    """NULL and 0 keys are DIFFERENT groups (the packed null-bits word);
    the fingerprint covers that word, so the distinction must survive the
    incremental path bit-for-bit — including at colliding widths."""
    rng = np.random.default_rng(5)
    n = 6000
    k = rng.integers(0, 4, n).astype(float)
    k[rng.random(n) < 0.3] = np.nan  # NULL keys, overlapping value 0 keys
    df = pd.DataFrame({
        "k1": pd.array(np.where(np.isnan(k), np.nan, k * 0), dtype="Int64"),
        "k2": rng.integers(0, 3, n),
        "v": pd.array(rng.integers(-1000, 1000, n) / 4.0, dtype="Float64"),
    })
    groupings = [(col(0), "k1"), (col(1), "k2")]
    mk = _batches_of(df, chunk=512)
    for bits in (64, 2):
        inc, _, _ = _run_pipeline(mk, groupings, _EXACT_AGGS, _inc_conf(True, bits))
        leg, _, _ = _run_pipeline(mk, groupings, _EXACT_AGGS, _inc_conf(False))
        _assert_bit_identical(inc, leg)
        # NULL group present AND 0 group present, separately
        assert inc["k1"].isna().any()
        assert (inc["k1"] == 0).any()


def test_incremental_dict_encoded_keys():
    """String (dict-encoded) group keys: per-batch code vocabularies make
    fingerprints batch-local, so probe/merge-path self-exclude — but the
    fingerprint segmentation still runs per batch and the result must be
    bit-identical to legacy."""
    rng = np.random.default_rng(9)
    n = 6000
    df = pd.DataFrame({
        "k1": rng.choice(["alpha", "beta", "gamma", "delta", None], n,
                         p=[0.3, 0.3, 0.2, 0.1, 0.1]),
        "k2": rng.integers(0, 4, n),
        "v": pd.array(rng.integers(-4000, 4000, n) / 8.0, dtype="Float64"),
    })
    groupings = [(col(0), "k1"), (col(1), "k2")]
    aggs = _EXACT_AGGS + [(AggExpr("first_ignores_null", col(2)), "f")]
    mk = _batches_of(df, chunk=512)
    inc, _, _ = _run_pipeline(mk, groupings, aggs, _inc_conf(True))
    leg, _, _ = _run_pipeline(mk, groupings, aggs, _inc_conf(False))
    _assert_bit_identical(inc, leg)


def test_incremental_wide_decimal_sums():
    """Wide-decimal sums (sum precision > 18, base-1e9 limb accumulators)
    through the incremental path — limb columns scatter-add exactly, so
    the totals are bit-identical at any fingerprint width."""
    import decimal as d

    rng = np.random.default_rng(3)
    n = 4000
    vals = [d.Decimal(int(x)) * d.Decimal("0.01")
            for x in rng.integers(-10**14, 10**14, n)]
    df = pd.DataFrame({
        "k1": rng.integers(0, 50, n) * 1_000_003,
        "k2": rng.integers(0, 3, n),
        "v": vals,
    })
    schema = T.Schema.of(
        T.Field("k1", T.INT64), T.Field("k2", T.INT64),
        T.Field("v", T.decimal(16, 2)),
    )
    chunk = 512
    mk = lambda: [
        Batch.from_pydict(
            {c: df[c].iloc[i:i + chunk].tolist() for c in df.columns},
            schema=schema,
        )
        for i in range(0, n, chunk)
    ]
    groupings = [(col(0), "k1"), (col(1), "k2")]
    aggs = [(AggExpr("sum", col(2)), "s"), (AggExpr("avg", col(2)), "a"),
            (AggExpr("count", col(2)), "c")]
    for bits in (64, 2):
        inc, _, _ = _run_pipeline(mk, groupings, aggs, _inc_conf(True, bits, 512))
        leg, _, _ = _run_pipeline(mk, groupings, aggs, _inc_conf(False, 64, 512))
        _assert_bit_identical(inc, leg)
    # and the totals are truly exact, not just consistent
    want = df.groupby(["k1", "k2"])["v"].sum().reset_index()
    want = want.sort_values(["k1", "k2"]).reset_index(drop=True)
    assert inc["s"].tolist() == want["v"].tolist()


@pytest.mark.parametrize("seed", range(6))
def test_sort_fuzz(seed):
    from auron_tpu.exec.sort_exec import SortExec
    from auron_tpu.ops.sortkeys import SortSpec

    rng = np.random.default_rng(seed + 900)
    n = int(rng.integers(1, 4000))
    df = pd.DataFrame({
        "a": pd.array(
            np.where(rng.random(n) < 0.1, None, rng.integers(-100, 100, n).astype(float)),
            dtype="Int64",
        ),
        "b": rng.normal(size=n).round(3),
        "s": rng.choice(["q", "w", "e", "r"], n),
    })
    chunk = int(rng.integers(64, 700))
    batches = [
        Batch.from_arrow(pa.RecordBatch.from_pandas(df.iloc[i:i+chunk], preserve_index=False))
        for i in range(0, n, chunk)
    ]
    n_sort = int(rng.integers(1, 4))
    cols_ = list(rng.permutation([0, 1, 2]))[:n_sort]
    ascs = [bool(rng.integers(0, 2)) for _ in range(n_sort)]
    spill = int(rng.integers(200, 5000))
    op = SortExec(
        MemoryScanExec.single(batches),
        [col(int(c)) for c in cols_],
        [SortSpec(asc=a, nulls_first=a) for a in ascs],  # Spark default placement
        spill_threshold_rows=spill,
    )
    got = op.collect().to_pandas()
    names = [["a", "b", "s"][c] for c in cols_]
    want = df.sort_values(
        names, ascending=ascs, kind="stable",
        na_position="first" if ascs[0] else "last",
    ).reset_index(drop=True)
    # compare the sort-key columns in order (payload order is stable-equal)
    for name in names:
        gl = [None if pd.isna(x) else x for x in got[name]]
        wl = [None if pd.isna(x) else x for x in want[name]]
        assert gl == wl, name

"""Randomized aggregation differential testing vs pandas groupby."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exec.agg_exec import FINAL, PARTIAL, AggExpr, HashAggExec
from auron_tpu.exec.base import ExecutionContext
from auron_tpu.exec.basic import MemoryScanExec
from auron_tpu.exprs.ir import col


@pytest.mark.parametrize("seed", range(8))
def test_agg_fuzz(seed):
    rng = np.random.default_rng(seed + 500)
    n = int(rng.integers(1, 3000))
    n_keys = int(rng.integers(1, 3))
    key_range = int(rng.integers(1, 60))
    df = pd.DataFrame({
        "k1": rng.integers(0, key_range, n),
        "k2": rng.choice(["a", "b", "c", None], n, p=[0.3, 0.3, 0.3, 0.1]),
        "v": pd.array(
            np.where(rng.random(n) < 0.12, np.nan, rng.normal(size=n).round(4)),
            dtype="Float64",
        ),
    })
    chunk = int(rng.integers(64, 1024))
    batches = [
        Batch.from_arrow(pa.RecordBatch.from_pandas(df.iloc[i:i+chunk], preserve_index=False))
        for i in range(0, n, chunk)
    ]
    gcols = ["k1", "k2"][:n_keys]
    groupings = [(col(i), gcols[i]) for i in range(n_keys)]
    aggs = [
        (AggExpr("sum", col(2)), "s"),
        (AggExpr("count", col(2)), "c"),
        (AggExpr("count_star", None), "cs"),
        (AggExpr("min", col(2)), "mn"),
        (AggExpr("max", col(2)), "mx"),
        (AggExpr("avg", col(2)), "a"),
    ]
    scan = MemoryScanExec.single(batches)
    partial = HashAggExec(scan, groupings, aggs, PARTIAL)
    mid = list(partial.execute(0, ExecutionContext()))
    final = HashAggExec(MemoryScanExec.single(mid), groupings, aggs, FINAL)
    got = final.collect().to_pandas().sort_values(gcols, na_position="last").reset_index(drop=True)

    want = (
        df.groupby(gcols, dropna=False)
        .agg(s=("v", "sum"), c=("v", "count"), cs=("v", "size"),
             mn=("v", "min"), mx=("v", "max"), a=("v", "mean"))
        .reset_index()
        .sort_values(gcols, na_position="last")
        .reset_index(drop=True)
    )
    assert len(got) == len(want), (len(got), len(want))
    assert got["c"].tolist() == want["c"].tolist()
    assert got["cs"].tolist() == want["cs"].tolist()
    for colname in ("s", "mn", "mx", "a"):
        for g, w, c in zip(got[colname], want[colname], want["c"]):
            if c == 0:
                assert pd.isna(g)  # SQL: all-null group -> NULL (pandas: 0.0 for sum)
            else:
                assert g == pytest.approx(w, rel=1e-9), (colname, g, w)


@pytest.mark.parametrize("seed", range(6))
def test_sort_fuzz(seed):
    from auron_tpu.exec.sort_exec import SortExec
    from auron_tpu.ops.sortkeys import SortSpec

    rng = np.random.default_rng(seed + 900)
    n = int(rng.integers(1, 4000))
    df = pd.DataFrame({
        "a": pd.array(
            np.where(rng.random(n) < 0.1, None, rng.integers(-100, 100, n).astype(float)),
            dtype="Int64",
        ),
        "b": rng.normal(size=n).round(3),
        "s": rng.choice(["q", "w", "e", "r"], n),
    })
    chunk = int(rng.integers(64, 700))
    batches = [
        Batch.from_arrow(pa.RecordBatch.from_pandas(df.iloc[i:i+chunk], preserve_index=False))
        for i in range(0, n, chunk)
    ]
    n_sort = int(rng.integers(1, 4))
    cols_ = list(rng.permutation([0, 1, 2]))[:n_sort]
    ascs = [bool(rng.integers(0, 2)) for _ in range(n_sort)]
    spill = int(rng.integers(200, 5000))
    op = SortExec(
        MemoryScanExec.single(batches),
        [col(int(c)) for c in cols_],
        [SortSpec(asc=a, nulls_first=a) for a in ascs],  # Spark default placement
        spill_threshold_rows=spill,
    )
    got = op.collect().to_pandas()
    names = [["a", "b", "s"][c] for c in cols_]
    want = df.sort_values(
        names, ascending=ascs, kind="stable",
        na_position="first" if ascs[0] else "last",
    ).reset_index(drop=True)
    # compare the sort-key columns in order (payload order is stable-equal)
    for name in names:
        gl = [None if pd.isna(x) else x for x in got[name]]
        wl = [None if pd.isna(x) else x for x in want[name]]
        assert gl == wl, name

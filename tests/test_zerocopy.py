"""Zero-copy ingestion (exec.scan.zerocopy, docs/shuffle.md): bit
identity against the copying path, eligibility accounting, aligned
staging, and the Arrow C-FFI bridge handoff."""

import decimal

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.columnar.batch import (
    ZERO_COPY_ALIGN,
    aligned_empty,
    ingest_stats,
    reset_ingest_stats,
)
from auron_tpu.exec.base import ExecutionContext
from auron_tpu.exec.scan import ParquetScanExec
from auron_tpu.utils.config import SCAN_ZEROCOPY, Configuration

RNG = np.random.default_rng(5)


def test_aligned_empty_is_aligned():
    for n in (1, 7, 1000, 131072):
        for dt in (np.int8, np.int32, np.int64, np.float64, bool):
            a = aligned_empty(n, dt)
            assert a.ctypes.data % ZERO_COPY_ALIGN == 0
            assert len(a) == n and a.dtype == np.dtype(dt)
    assert len(aligned_empty(0, np.int64)) == 0  # empty: pointer is moot


def _mixed_record_batch(n=1000, nulls=True):
    mask = (RNG.random(n) < 0.2) if nulls else None
    return pa.RecordBatch.from_arrays([
        pa.array(RNG.integers(-(10**9), 10**9, n), mask=mask),
        pa.array(RNG.random(n), mask=mask),
        pa.array(RNG.integers(0, 2, n).astype(bool), mask=mask),
        pa.array(RNG.integers(0, 10**14, n).astype("datetime64[us]"), mask=mask),
        pa.array(RNG.integers(0, 20000, n).astype(np.int32), mask=mask).cast(pa.date32()),
        pa.array(RNG.choice(["a", "bb", "ccc"], n), mask=mask).dictionary_encode(),
        pa.array([decimal.Decimal(int(v)).scaleb(-2)
                  for v in RNG.integers(-(10**6), 10**6, n)],
                 type=pa.decimal128(10, 2)),
    ], names=["i", "f", "b", "ts", "d", "s", "dec"])


@pytest.mark.parametrize("nulls", [False, True])
def test_from_arrow_bit_identity_on_vs_off(nulls):
    rb = _mixed_record_batch(nulls=nulls)
    off = Batch.from_arrow(rb, conf=Configuration().set(SCAN_ZEROCOPY, "off"))
    on = Batch.from_arrow(rb, conf=Configuration().set(SCAN_ZEROCOPY, "on"))
    assert off.to_arrow().equals(on.to_arrow())
    # device planes identical too
    import jax

    d_off = jax.device_get(off.device)
    d_on = jax.device_get(on.device)
    assert np.array_equal(d_off.sel, d_on.sel)
    for a, b in zip(d_off.values, d_on.values):
        assert np.array_equal(a, b)
    for a, b in zip(d_off.validity, d_on.validity):
        assert np.array_equal(a, b)


def test_from_pandas_bit_identity_on_vs_off():
    df = pd.DataFrame({
        "i": RNG.integers(0, 10**9, 2000),
        "masked": pd.array(
            [None if v % 7 == 0 else int(v) for v in range(2000)],
            dtype="Int64"),
        "f": np.where(RNG.random(2000) < 0.1, np.nan, RNG.random(2000)),
        "s": RNG.choice(["x", "y"], 2000),
    })
    off = Batch.from_pandas(df, conf=Configuration().set(SCAN_ZEROCOPY, "off"))
    on = Batch.from_pandas(df, conf=Configuration().set(SCAN_ZEROCOPY, "on"))
    assert off.to_arrow().equals(on.to_arrow())


def test_clean_full_batch_planes_are_views():
    """Validity-clean fixed-width columns of a FULL batch (rows == cap)
    ride as zero-copy views; nulls/bool force the copy path."""
    import pyarrow.compute as pc

    n = 1024  # a power-of-two bucket: rows == capacity
    # pc.add materializes into Arrow-ALLOCATED buffers (64-aligned, like
    # parquet decode output); numpy-wrapped arrays are only 16-aligned
    rb = pa.RecordBatch.from_arrays(
        [pc.add(pa.array(np.arange(n, dtype=np.int64)), 0),
         pc.add(pa.array(RNG.random(n)), 0.0)],
        names=["a", "b"])
    reset_ingest_stats()
    Batch.from_arrow(rb, conf=Configuration().set(SCAN_ZEROCOPY, "on"))
    st = ingest_stats()
    assert st["zerocopy_planes"] == 2, st
    # a padded (non-full) batch pays the aligned-staging copy instead
    rb2 = pa.RecordBatch.from_arrays(
        [pa.array(np.arange(n - 5, dtype=np.int64))], names=["a"])
    reset_ingest_stats()
    Batch.from_arrow(rb2, conf=Configuration().set(SCAN_ZEROCOPY, "on"))
    st = ingest_stats()
    assert st["copied_planes"] == 1 and st["zerocopy_planes"] == 0, st


def test_parquet_scan_zerocopy_bit_identity(tmp_path):
    """The scan satellite: a predicate-pruned parquet scan produces
    bit-identical batches with exec.scan.zerocopy on/off, and the clean
    fixed-width columns actually take the zero-copy path."""
    n = 4096
    tbl = pa.table({
        "a": pa.array(np.arange(n, dtype=np.int64)),
        "b": pa.array(np.round(RNG.random(n), 3)),
        "c": pa.array([None if i % 11 == 0 else i for i in range(n)],
                      type=pa.int64()),
    })
    path = str(tmp_path / "t.parquet")
    pq.write_table(tbl, path, row_group_size=1024)
    schema = T.Schema.of(T.Field("a", T.INT64), T.Field("b", T.FLOAT64),
                         T.Field("c", T.INT64))
    outs = {}
    for mode in ("off", "on"):
        conf = Configuration().set(SCAN_ZEROCOPY, mode)
        reset_ingest_stats()
        scan = ParquetScanExec(schema, [path])
        outs[mode] = [b.to_arrow()
                      for b in scan.execute(0, ExecutionContext(conf=conf))]
        if mode == "on":
            assert ingest_stats()["zerocopy_planes"] > 0
    assert len(outs["off"]) == len(outs["on"])
    for x, y in zip(outs["off"], outs["on"]):
        assert x.equals(y)


def test_dictionary_pages_pass_through_by_reference(tmp_path):
    """A parquet dictionary-encoded column arriving as DictionaryArray
    keeps its dictionary object by reference (no re-encode)."""
    n = 2048
    tbl = pa.table({"s": pa.array(RNG.choice(["p", "q", "r"], n)).dictionary_encode()})
    path = str(tmp_path / "d.parquet")
    pq.write_table(tbl, path)
    schema = T.Schema.of(T.Field("s", T.STRING))
    scan = ParquetScanExec(schema, [path])
    out = list(scan.execute(0, ExecutionContext(
        conf=Configuration().set(SCAN_ZEROCOPY, "on"))))
    assert out and out[0].dicts[0] is not None
    got = [v for b in out for v in b.to_arrow().column(0).to_pylist()]
    assert got == tbl.column(0).to_pylist()


def test_c_ffi_stream_handoff_roundtrip():
    """Arrow C data interface across the bridge: a stream handed by
    POINTER (no IPC bytes) feeds a task, and results export back through
    C structs — the serde-free JVM-boundary path."""
    ctypes_ffi = pytest.importorskip("pyarrow.cffi")
    ffi = ctypes_ffi.ffi
    from auron_tpu.bridge import api
    from auron_tpu.exprs.ir import BinaryOp, col, lit
    from auron_tpu.plan import builders as B

    rb = pa.record_batch({"x": pa.array(np.arange(64, dtype=np.int64))})
    reader = pa.RecordBatchReader.from_batches(rb.schema, [rb])
    c_stream = ffi.new("struct ArrowArrayStream*")
    reader._export_to_c(int(ffi.cast("uintptr_t", c_stream)))
    api.put_resource_c_stream("cffi_rt", int(ffi.cast("uintptr_t", c_stream)))
    try:
        schema = T.Schema.of(T.Field("x", T.INT64))
        plan = B.filter_(B.ffi_reader(schema, "cffi_rt"),
                         [BinaryOp("lt", col(0), lit(10))])
        h = api.call_native(B.task(plan, partition_id=0).SerializeToString())
        rows = []
        while True:
            c_arr = ffi.new("struct ArrowArray*")
            c_sch = ffi.new("struct ArrowSchema*")
            rc = api.next_batch_c(h, int(ffi.cast("uintptr_t", c_arr)),
                                  int(ffi.cast("uintptr_t", c_sch)))
            assert rc in (0, 1)
            if rc == 0:
                break
            got = pa.RecordBatch._import_from_c(
                int(ffi.cast("uintptr_t", c_arr)),
                int(ffi.cast("uintptr_t", c_sch)))
            rows += got.column(0).to_pylist()
        api.finalize_native(h)
        assert rows == list(range(10))
    finally:
        api.remove_resource("cffi_rt")

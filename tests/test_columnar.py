"""Columnar batch round-trip tests (Arrow <-> device)."""

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch, bucket_capacity
from auron_tpu.columnar.batch import concat_batches, unify_dict


def test_bucket_capacity():
    assert bucket_capacity(0) == 128
    assert bucket_capacity(128) == 128
    assert bucket_capacity(129) == 256
    assert bucket_capacity(8192) == 8192
    assert bucket_capacity(8193) == 16384


def test_roundtrip_numeric():
    rb = pa.record_batch(
        {
            "i8": pa.array([1, None, -3], type=pa.int8()),
            "i32": pa.array([100, 2, None], type=pa.int32()),
            "i64": pa.array([2**40, None, -(2**40)], type=pa.int64()),
            "f32": pa.array([1.5, None, float("nan")], type=pa.float32()),
            "f64": pa.array([2.5, -0.0, None], type=pa.float64()),
            "b": pa.array([True, None, False]),
        }
    )
    b = Batch.from_arrow(rb)
    assert b.capacity == 128
    assert b.num_rows() == 3
    out = b.to_arrow()
    assert out.num_rows == 3
    for name in rb.schema.names:
        got, want = out.column(name), rb.column(name)
        if name == "f32":
            gl, wl = got.to_pylist(), want.to_pylist()
            assert gl[0] == wl[0] and gl[1] is None and np.isnan(gl[2])
        else:
            assert got.equals(want), name


def test_roundtrip_strings():
    rb = pa.record_batch({"s": pa.array(["hello", None, "world", "hello"])})
    b = Batch.from_arrow(rb)
    assert b.dicts[0] is not None
    assert b.to_arrow().column("s").to_pylist() == ["hello", None, "world", "hello"]


def test_roundtrip_decimal_date_ts():
    import decimal as d

    rb = pa.record_batch(
        {
            "dec": pa.array(
                [d.Decimal("123.45"), None, d.Decimal("-0.01")],
                type=pa.decimal128(10, 2),
            ),
            "dt": pa.array([18000, None, 0], type=pa.int32()).cast(pa.date32()),
            "ts": pa.array(
                [np.datetime64("2024-01-01T12:34:56.789", "us"), None,
                 np.datetime64("1970-01-01", "us")]
            ),
        }
    )
    b = Batch.from_arrow(rb)
    out = b.to_arrow()
    assert out.column("dec").to_pylist() == rb.column("dec").to_pylist()
    assert out.column("dt").to_pylist() == rb.column("dt").to_pylist()
    assert out.column("ts").to_pylist() == rb.column("ts").to_pylist()
    # decimal physical repr is scaled int64
    vals = np.asarray(b.col_values(0))
    assert vals[0] == 12345 and vals[2] == -1


def test_from_pydict_and_empty():
    b = Batch.from_pydict({"x": [1, 2, 3], "y": ["a", "b", "a"]})
    assert b.schema.names == ["x", "y"]
    assert b.num_rows() == 3
    e = Batch.empty(b.schema)
    assert e.num_rows() == 0
    assert e.to_arrow().num_rows == 0


def test_concat_batches():
    b1 = Batch.from_pydict({"x": [1, 2], "s": ["a", "b"]})
    b2 = Batch.from_pydict({"x": [3], "s": ["c"]})
    c = concat_batches([b1, b2])
    assert c.to_pydict() == {"x": [1, 2, 3], "s": ["a", "b", "c"]}


def test_unify_dict():
    b1 = Batch.from_pydict({"s": ["a", "b", "a"]})
    b2 = Batch.from_pydict({"s": ["b", "c"]})
    unified, remaps = unify_dict([b1, b2], 0)
    uni = unified.to_pylist()
    # every (batch, code) remaps to the right string
    for b, r in zip([b1, b2], remaps):
        codes = np.asarray(b.col_values(0))
        sel = np.asarray(b.device.sel)
        strings = b.to_arrow().column("s").to_pylist()
        live_codes = codes[sel]
        for s, c in zip(strings, live_codes):
            assert uni[r[c]] == s


def test_large_batch_bucketing():
    n = 10_000
    rb = pa.record_batch({"x": pa.array(np.arange(n))})
    b = Batch.from_arrow(rb)
    assert b.capacity == 16384
    assert b.num_rows() == n
    assert b.to_arrow().column("x").to_pylist() == list(range(n))


def test_from_pandas_edge_ingest():
    """Direct pandas ingest: masked ints, NaN floats, object columns with a
    leading null, and NaT timestamps must all round-trip with exact
    validity (regression for the no-Arrow fast path)."""
    import pandas as pd

    df = pd.DataFrame({
        "s": pd.Series([None, "b", None, "d"], dtype=object),
        "i": pd.Series([1, None, 3, None], dtype="Int64"),
        "f": np.array([1.5, np.nan, 2.5, np.nan]),
        "o": pd.Series([1.0, None, 3.0, np.nan], dtype=object),
        "t": pd.to_datetime(["2020-01-01", None, "2021-06-05", None]),
    })
    out = Batch.from_pandas(df).to_arrow().to_pydict()
    assert out["s"] == [None, "b", None, "d"]
    assert out["i"] == [1, None, 3, None]
    assert out["f"][0] == 1.5 and out["f"][1] is None and out["f"][3] is None
    assert out["o"][0] == 1.0 and out["o"][1] is None and out["o"][3] is None
    assert out["t"][1] is None and str(out["t"][2]).startswith("2021-06-05")

"""Stateless operator tests (project/filter/limit/union/expand/...)."""

import pyarrow as pa

import pytest
from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exec.base import ExecutionContext
from auron_tpu.exec.basic import (
    CoalesceBatchesExec,
    EmptyPartitionsExec,
    ExpandExec,
    FilterExec,
    LimitExec,
    MemoryScanExec,
    ProjectExec,
    RenameColumnsExec,
    UnionExec,
)
from auron_tpu.exprs.ir import BinaryOp, Column, ScalarFunc, col, lit


def _scan(data: dict, nbatches: int = 1):
    b = Batch.from_pydict(data)
    return MemoryScanExec.single([b] * nbatches)


def test_project_filter_pipeline():
    scan = _scan({"x": [1, 2, 3, 4, 5], "y": [10.0, 20.0, 30.0, 40.0, 50.0]})
    filt = FilterExec(scan, [BinaryOp("gt", col(0), lit(2))])
    proj = ProjectExec(
        filt,
        [BinaryOp("mul", col(0), lit(2)), col(1, "y")],
        ["x2", "y"],
    )
    out = proj.collect_pydict()
    assert out == {"x2": [6, 8, 10], "y": [30.0, 40.0, 50.0]}


def test_filter_keeps_capacity_no_compaction():
    scan = _scan({"x": list(range(100))})
    filt = FilterExec(scan, [BinaryOp("lt", col(0), lit(10))])
    ctx = ExecutionContext()
    batches = list(filt.execute(0, ctx))
    assert len(batches) == 1
    assert batches[0].capacity == 128  # same bucket, mask refined
    assert batches[0].num_rows() == 10


def test_limit_across_batches():
    scan = _scan({"x": list(range(10))}, nbatches=3)
    lim = LimitExec(scan, 25)
    out = lim.collect_pydict()
    assert len(out["x"]) == 25
    assert out["x"][:10] == list(range(10))


def test_limit_mid_batch():
    scan = _scan({"x": list(range(10))})
    lim = LimitExec(scan, 4)
    assert lim.collect_pydict() == {"x": [0, 1, 2, 3]}


def test_union():
    u = UnionExec([_scan({"x": [1, 2]}), _scan({"x": [3]})])
    assert u.collect_pydict() == {"x": [1, 2, 3]}


def test_expand():
    scan = _scan({"x": [1, 2]})
    ex = ExpandExec(
        scan,
        [[col(0), lit(0)], [col(0), lit(1)]],
        ["x", "tag"],
    )
    out = ex.collect_pydict()
    assert out == {"x": [1, 2, 1, 2], "tag": [0, 0, 1, 1]}


def test_rename_empty_coalesce():
    scan = _scan({"x": [1, 2]}, nbatches=4)
    ren = RenameColumnsExec(scan, ["renamed"])
    assert list(ren.collect_pydict().keys()) == ["renamed"]
    e = EmptyPartitionsExec(scan.schema, 3)
    assert e.collect_pydict() == {"x": []}
    co = CoalesceBatchesExec(scan, target_rows=8)
    ctx = ExecutionContext()
    bs = list(co.execute(0, ctx))
    assert len(bs) == 1 and bs[0].num_rows() == 8


def test_metrics_tree():
    scan = _scan({"x": [1, 2, 3]})
    filt = FilterExec(scan, [BinaryOp("gteq", col(0), lit(2))])
    ctx = ExecutionContext()
    ctx.metrics.name = filt.name
    list(filt.execute(0, ctx))
    snap = ctx.metrics.snapshot()
    assert snap["values"]["output_rows"] == 2
    assert snap["children"][0]["values"]["output_rows"] == 3
    assert snap["children"][0]["name"] == "MemoryScanExec"


def test_project_string_function():
    scan = _scan({"s": ["a", "bb", None]})
    proj = ProjectExec(scan, [ScalarFunc("upper", (col(0),))], ["u"])
    assert proj.collect_pydict() == {"u": ["A", "BB", None]}


@pytest.fixture(autouse=True)
def _row_metrics_on(enable_row_metrics):
    # these suites assert per-operator output_rows metrics
    pass

"""Kafka wire client against an in-process mini broker.

The mini broker serves the same wire format a real broker does for the
protocol subset the client speaks (Metadata v1 / ListOffsets v1 /
Fetch v4, record batches v2) — both directions of the codec are
exercised: the broker encodes with kafka_wire's producer-side encoder,
the client decodes and CRC-checks.
"""

import json
import socket
import struct
import threading
import time

import pytest

from auron_tpu import types as T
from auron_tpu.exec import kafka_wire as KW


# ---------------------------------------------------------------------------
# mini broker
# ---------------------------------------------------------------------------


class MiniKafkaBroker:
    def __init__(self, topic: str, n_partitions: int = 2, codec: int = KW.CODEC_NONE,
                 fault_hook=None):
        self.topic = topic
        self.codec = codec
        # fault injection seam: fault_hook(api_key) -> None | "drop_before"
        # | "partial_reply" (truncated header then close) | "delay:<s>"
        self.fault_hook = fault_hook
        self.logs: list[list[bytes]] = [[] for _ in range(n_partitions)]
        self.starts = [0] * n_partitions  # log-start offsets (retention)
        self.fetch_chunk = 100  # records per batch in a fetch response
        self.srv = socket.socket()
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(8)
        self.port = self.srv.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def produce(self, partition: int, values: list[bytes]) -> None:
        self.logs[partition].extend(values)

    def trim(self, partition: int, new_start: int) -> None:
        """Retention: delete records below new_start."""
        drop = new_start - self.starts[partition]
        assert 0 <= drop <= len(self.logs[partition])
        self.logs[partition] = self.logs[partition][drop:]
        self.starts[partition] = new_start

    def close(self) -> None:
        self._stop = True
        try:
            self.srv.close()
        except OSError:
            pass

    # -- serving --------------------------------------------------------

    def _serve(self):
        while not self._stop:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn: socket.socket):
        try:
            while True:
                hdr = self._read_exact(conn, 4)
                if hdr is None:
                    return
                (n,) = struct.unpack(">i", hdr)
                frame = self._read_exact(conn, n)
                c = KW.Cursor(frame)
                api, ver, corr = c.i16(), c.i16(), c.i32()
                c.string()  # client id
                if api == KW.API_METADATA:
                    body = self._metadata(c)
                elif api == KW.API_LIST_OFFSETS:
                    body = self._list_offsets(c)
                elif api == KW.API_FETCH:
                    body = self._fetch(c)
                else:
                    return
                resp = struct.pack(">i", corr) + body
                if self.fault_hook is not None:
                    from auron_tpu.utils.netio import apply_fault

                    if apply_fault(conn, self.fault_hook(api), len(resp)):
                        return
                conn.sendall(struct.pack(">i", len(resp)) + resp)
        except (ConnectionError, OSError):
            return

    @staticmethod
    def _read_exact(conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _metadata(self, c: KW.Cursor) -> bytes:
        n = c.i32()
        for _ in range(n):
            c.string()
        out = struct.pack(">i", 1)  # one broker
        out += struct.pack(">i", 0) + KW.enc_str("127.0.0.1")
        out += struct.pack(">i", self.port) + KW.enc_str(None)  # rack
        out += struct.pack(">i", 0)  # controller
        out += struct.pack(">i", 1)  # one topic
        out += struct.pack(">h", 0) + KW.enc_str(self.topic) + struct.pack(">b", 0)
        out += struct.pack(">i", len(self.logs))
        for pid in range(len(self.logs)):
            out += struct.pack(">hii", 0, pid, 0)
            out += struct.pack(">i", 1) + struct.pack(">i", 0)  # replicas
            out += struct.pack(">i", 1) + struct.pack(">i", 0)  # isr
        return out

    def _list_offsets(self, c: KW.Cursor) -> bytes:
        c.i32()  # replica
        reqs = []
        for _ in range(c.i32()):
            c.string()
            for _ in range(c.i32()):
                pid = c.i32()
                ts = c.i64()
                reqs.append((pid, ts))
        out = struct.pack(">i", 1) + KW.enc_str(self.topic)
        out += struct.pack(">i", len(reqs))
        for pid, ts in reqs:
            off = (
                self.starts[pid]
                if ts == KW.TS_EARLIEST
                else self.starts[pid] + len(self.logs[pid])
            )
            out += struct.pack(">ihqq", pid, 0, -1, off)
        return out

    def _fetch(self, c: KW.Cursor) -> bytes:
        c.i32()  # replica
        c.i32()  # max wait
        c.i32()  # min bytes
        c.i32()  # max bytes
        c.i8()  # isolation
        reqs = []
        for _ in range(c.i32()):
            c.string()
            for _ in range(c.i32()):
                pid = c.i32()
                off = c.i64()
                c.i32()  # partition max bytes
                reqs.append((pid, off))
        out = struct.pack(">i", 0)  # throttle
        out += struct.pack(">i", 1) + KW.enc_str(self.topic)
        out += struct.pack(">i", len(reqs))
        for pid, off in reqs:
            log = self.logs[pid]
            start = self.starts[pid]
            hwm = start + len(log)
            if off < start:
                out += struct.pack(">ihqq", pid, 1, hwm, hwm)  # out of range
                out += struct.pack(">i", 0)
                out += KW.enc_bytes(b"")
                continue
            chunk = log[off - start : off - start + self.fetch_chunk]
            rset = (
                KW.encode_record_batch(off, chunk, self.codec) if chunk else b""
            )
            out += struct.pack(">ihqq", pid, 0, hwm, hwm)
            out += struct.pack(">i", 0)  # no aborted txns
            out += KW.enc_bytes(rset)
        return out


# ---------------------------------------------------------------------------
# codec unit tests
# ---------------------------------------------------------------------------


def test_crc32c_vector():
    # the canonical Castagnoli check value
    assert KW.crc32c(b"123456789") == 0xE3069283


@pytest.mark.parametrize("codec", [KW.CODEC_NONE, KW.CODEC_GZIP, KW.CODEC_ZSTD])
def test_record_batch_roundtrip(codec):
    if codec == KW.CODEC_ZSTD:
        # env-dependent: the wire codec needs the zstandard package, which
        # CI images may not ship — skip loudly instead of failing tier-1
        # (a real regression in the zstd path still fails wherever the
        # module exists)
        pytest.importorskip("zstandard")
    values = [f"record-{i}".encode() for i in range(37)]
    buf = KW.encode_record_batch(1000, values, codec)
    got = KW.decode_record_batches(buf)
    assert [(1000 + i, v) for i, v in enumerate(values)] == got


def test_record_batch_crc_detects_corruption():
    buf = bytearray(KW.encode_record_batch(0, [b"abc", b"def"]))
    buf[-1] ^= 0x01
    with pytest.raises(ValueError, match="CRC-32C"):
        KW.decode_record_batches(bytes(buf))


def test_partial_trailing_batch_skipped():
    b1 = KW.encode_record_batch(0, [b"x", b"y"])
    b2 = KW.encode_record_batch(2, [b"z"])
    got = KW.decode_record_batches(b1 + b2[: len(b2) - 3])
    assert got == [(0, b"x"), (1, b"y")]


# ---------------------------------------------------------------------------
# client <-> broker
# ---------------------------------------------------------------------------


@pytest.fixture()
def broker():
    b = MiniKafkaBroker("events", n_partitions=2)
    yield b
    b.close()


def _drain(src, max_records=1000):
    out = []
    while (vals := src.poll(max_records)) is not None:
        out.extend(vals)
    return out


def test_earliest_consumes_all(broker):
    broker.produce(0, [f"p0-{i}".encode() for i in range(250)])
    broker.produce(1, [f"p1-{i}".encode() for i in range(120)])
    src = KW.KafkaWireSource(f"127.0.0.1:{broker.port}", "events", "earliest")
    got = _drain(src)
    assert sorted(got) == sorted(
        [f"p0-{i}".encode() for i in range(250)]
        + [f"p1-{i}".encode() for i in range(120)]
    )
    assert src.offsets() == {0: 250, 1: 120}
    src.close()


def test_latest_skips_existing_then_sees_new(broker):
    broker.produce(0, [b"old-0", b"old-1"])
    src = KW.KafkaWireSource(f"127.0.0.1:{broker.port}", "events", "latest")
    assert src.poll(100) is None  # nothing past the latest offsets
    broker.produce(0, [b"new-0"])
    assert src.poll(100) == [b"new-0"]
    src.close()


def test_offsets_resume_no_dup_no_loss(broker):
    broker.produce(0, [f"a{i}".encode() for i in range(40)])
    broker.produce(1, [f"b{i}".encode() for i in range(40)])
    src = KW.KafkaWireSource(f"127.0.0.1:{broker.port}", "events", "earliest")
    first = src.poll(30)  # partial consumption
    ckpt = src.offsets()
    src.close()

    src2 = KW.KafkaWireSource(
        f"127.0.0.1:{broker.port}", "events", "offsets", start_offsets=ckpt
    )
    rest = _drain(src2)
    src2.close()
    combined = sorted(first + rest)
    assert combined == sorted(
        [f"a{i}".encode() for i in range(40)]
        + [f"b{i}".encode() for i in range(40)]
    )


def test_partition_subset_assignment(broker):
    broker.produce(0, [b"keep-0"])
    broker.produce(1, [b"skip-1"])
    src = KW.KafkaWireSource(
        f"127.0.0.1:{broker.port}", "events", "earliest", partitions=[0]
    )
    assert _drain(src) == [b"keep-0"]
    assert src.offsets() == {0: 1}
    src.close()


def test_control_batch_advances_offset_without_data():
    """Transaction markers (attribute bit 0x20) are not user records, but
    offsets must advance past them."""
    data = KW.encode_record_batch(5, [b"user-record"])
    ctrl = bytearray(KW.encode_record_batch(6, [b"\x00\x00\x00\x00"]))
    # set the isControlBatch bit in attributes and re-CRC
    attr_pos = 8 + 4 + 4 + 1 + 4  # offset+len+epoch+magic+crc
    ctrl[attr_pos + 1] |= 0x20
    crc = KW.crc32c(bytes(ctrl[attr_pos:]))
    ctrl[attr_pos - 4 : attr_pos] = struct.pack(">I", crc)
    got = KW.decode_record_batches(data + bytes(ctrl))
    assert got == [(5, b"user-record"), (6, None)]


def test_offset_out_of_range_resets(broker):
    broker.produce(0, [f"r{i}".encode() for i in range(30)])
    broker.produce(1, [b"other"])
    broker.trim(0, 20)  # retention deleted offsets 0-19
    # checkpoint predates retention -> reset policy kicks in
    src = KW.KafkaWireSource(
        f"127.0.0.1:{broker.port}", "events", "offsets",
        start_offsets={0: 5, 1: 0}, offset_reset="earliest",
    )
    got = _drain(src)
    assert sorted(got) == sorted([f"r{i}".encode() for i in range(20, 30)] + [b"other"])
    assert src.offsets()[0] == 30
    src.close()

    src2 = KW.KafkaWireSource(
        f"127.0.0.1:{broker.port}", "events", "offsets",
        start_offsets={0: 5}, partitions=[0], offset_reset="fail",
    )
    with pytest.raises(RuntimeError, match="out of range"):
        src2.poll(10)
    src2.close()


def test_invalid_startup_mode_rejected(broker):
    with pytest.raises(ValueError, match="startup_mode"):
        KW.KafkaWireSource(f"127.0.0.1:{broker.port}", "events", "earliset")


def test_gzip_broker_batches(broker):
    broker.codec = KW.CODEC_GZIP
    broker.produce(0, [f"z{i}".encode() for i in range(64)])
    src = KW.KafkaWireSource(f"127.0.0.1:{broker.port}", "events", "earliest")
    assert sorted(_drain(src)) == sorted(f"z{i}".encode() for i in range(64))
    src.close()


def test_kafka_scan_exec_with_wire_source(broker):
    """The kafka_scan operator runs against the REAL client (json records
    -> Batch) and surfaces resume offsets, exactly as with the mock."""
    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.exec.streaming import KafkaScanExec

    rows = [{"k": i, "v": f"msg{i}"} for i in range(57)]
    broker.produce(0, [json.dumps(r).encode() for r in rows[:30]])
    broker.produce(1, [json.dumps(r).encode() for r in rows[30:]])

    schema = T.Schema((T.Field("k", T.INT64, False),
                       T.Field("v", T.STRING, True)))

    def provider(topic, startup_mode, start_offsets):
        return KW.KafkaWireSource(
            f"127.0.0.1:{broker.port}", topic, startup_mode, start_offsets
        )

    op = KafkaScanExec(schema, "events", "kafka_src", data_format="json")
    ctx = ExecutionContext(resources={"kafka_src": provider})
    got = []
    for b in op.execute(0, ctx):
        df = b.to_pandas()
        got += list(zip(df["k"].tolist(), df["v"].tolist()))
    assert sorted(got) == sorted((r["k"], r["v"]) for r in rows)
    assert ctx.resources["kafka_src.offsets"] == {0: 30, 1: 27}


# ---------------------------------------------------------------------------
# network fault injection (VERDICT r4 #10: loopback-to-LAN hardening)
# ---------------------------------------------------------------------------


def test_poll_survives_broker_connection_drop():
    """Broker drops the connection before a fetch reply (restart /
    idle-reaping): the source reconnects once and resumes from its
    next_offset — no duplicates, no gaps."""
    topic = "faulty"
    faults = {"n": 0}

    def hook(api):
        if api == KW.API_FETCH and faults["n"] == 0:
            faults["n"] += 1
            return "drop_before"
        return None

    br = MiniKafkaBroker(topic, n_partitions=1, fault_hook=hook)
    try:
        br.produce(0, [f"m{i}".encode() for i in range(10)])
        src = KW.KafkaWireSource(
            f"127.0.0.1:{br.port}", topic, startup_mode="earliest")
        got = []
        while True:
            recs = src.poll(100)
            if not recs:
                break
            got.extend(recs)
        assert got == [f"m{i}".encode() for i in range(10)]
        assert faults["n"] == 1  # the drop DID happen mid-stream
        src.close()
    finally:
        br.close()


def test_poll_survives_partial_frame():
    """A truncated reply header (congestion) fails read_exact cleanly and
    the reconnect retry delivers the full stream."""
    topic = "halfframe"
    faults = {"n": 0}

    def hook(api):
        if api == KW.API_FETCH and faults["n"] == 0:
            faults["n"] += 1
            return "partial_reply"
        return None

    br = MiniKafkaBroker(topic, n_partitions=2, fault_hook=hook)
    try:
        br.produce(0, [b"a0", b"a1"])
        br.produce(1, [b"b0"])
        src = KW.KafkaWireSource(
            f"127.0.0.1:{br.port}", topic, startup_mode="earliest")
        got = []
        while True:
            recs = src.poll(100)
            if not recs:
                break
            got.extend(recs)
        assert sorted(got) == [b"a0", b"a1", b"b0"]
        assert faults["n"] == 1
        src.close()
    finally:
        br.close()


def test_persistent_broker_outage_is_loud():
    """When EVERY retry meets a dead connection the error must propagate
    (reconnect is once, not forever — a dead broker can't spin the task)."""
    topic = "deadbroker"

    def hook(api):
        if api == KW.API_FETCH:
            return "drop_before"
        return None

    br = MiniKafkaBroker(topic, n_partitions=1, fault_hook=hook)
    try:
        br.produce(0, [b"x"])
        src = KW.KafkaWireSource(
            f"127.0.0.1:{br.port}", topic, startup_mode="earliest")
        with pytest.raises((ConnectionError, OSError)):
            src.poll(10)
        src.close()
    finally:
        br.close()

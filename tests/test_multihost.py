"""Multi-host (DCN analog) runtime: REAL two-process jax.distributed run.

VERDICT r1 weak #10: parallel/multihost.py was untested glue. This test
launches two actual processes, each owning 4 virtual CPU devices, joins
them through the AURON_* env contract, builds the 8-device global mesh,
and runs a cross-process psum — the same collective path a multi-host
TPU deployment uses over DCN.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["AURON_TPU_ROOT"])
import jax
from auron_tpu.parallel import multihost

assert multihost.initialize_from_env(), "env contract not detected"
pid, nprocs = multihost.process_info()
assert nprocs == 2
mesh = multihost.global_mesh()

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

n_global = len(jax.devices())
assert n_global == 8, n_global

# every process contributes its local shard; the collective must see all 8
def step(x):
    return jax.lax.psum(x, "p")[None]

fn = jax.jit(shard_map(step, mesh=mesh, in_specs=P("p"), out_specs=P("p")))
local = np.arange(4, dtype=np.int64) + 4 * pid  # this host's shard values
arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("p")), local, (8,)
)
out = fn(arr)
# psum over the partition axis = sum(0..7) = 28 on every shard
local_out = np.asarray([s.data for s in out.addressable_shards])
assert (local_out == 28).all(), local_out
print(f"proc {pid} ok: global devices={n_global} psum=28")
"""


@pytest.mark.timeout(240)
def test_two_process_global_mesh_collective(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)  # skip the axon sitecustomize
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            AURON_COORDINATOR=f"127.0.0.1:{port}",
            AURON_NUM_PROCS="2",
            AURON_PROC_ID=str(pid),
            AURON_TPU_ROOT=root,
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _WORKER],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=210)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"multihost workers hung; partial output: {outs}")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid} ok: global devices=8 psum=28" in out

"""Multi-host (DCN analog) runtime: REAL two-process jax.distributed run.

VERDICT r1 weak #10: parallel/multihost.py was untested glue. This test
launches two actual processes, each owning 4 virtual CPU devices, joins
them through the AURON_* env contract, builds the 8-device global mesh,
and runs a cross-process psum — the same collective path a multi-host
TPU deployment uses over DCN.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["AURON_TPU_ROOT"])
import jax
from auron_tpu.parallel import multihost

assert multihost.initialize_from_env(), "env contract not detected"
pid, nprocs = multihost.process_info()
assert nprocs == 2
mesh = multihost.global_mesh()

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

n_global = len(jax.devices())
assert n_global == 8, n_global

# every process contributes its local shard; the collective must see all 8
def step(x):
    return jax.lax.psum(x, "p")[None]

fn = jax.jit(shard_map(step, mesh=mesh, in_specs=P("p"), out_specs=P("p")))
local = np.arange(4, dtype=np.int64) + 4 * pid  # this host's shard values
arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("p")), local, (8,)
)
out = fn(arr)
# psum over the partition axis = sum(0..7) = 28 on every shard
local_out = np.asarray([s.data for s in out.addressable_shards])
assert (local_out == 28).all(), local_out
print(f"proc {pid} ok: global devices={n_global} psum=28")
"""


_SPMD_WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["AURON_TPU_ROOT"])
from auron_tpu.parallel import multihost

# MUST run before anything touches the XLA backend (jax.devices etc.)
assert multihost.initialize_from_env()

import numpy as np
import pandas as pd
import pyarrow as pa
import jax
from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exprs.ir import col
from auron_tpu.parallel.mesh_driver import MeshQueryDriver
from auron_tpu.plan import builders as B

pid, nprocs = multihost.process_info()
mesh = multihost.global_mesh()
P = len(jax.devices())
assert P == 8 and nprocs == 2

# every process holds the SAME deterministic dataset; the resource map
# carries all 8 map partitions, the SPMD driver executes only local ones
rng = np.random.default_rng(5)
df = pd.DataFrame({
    "k": rng.integers(0, 61, 6000).astype(np.int64),
    "v": rng.integers(-500, 500, 6000).astype(np.int64),
})
per = (len(df) + P - 1) // P
parts = [[Batch.from_arrow(pa.RecordBatch.from_pandas(
    df.iloc[p * per : (p + 1) * per], preserve_index=False))] for p in range(P)]
schema = T.Schema((T.Field("k", T.INT64, False), T.Field("v", T.INT64, False)))

scan = B.memory_scan(schema, "fact")
partial = B.hash_agg(scan, [(col(0), "k")], [("sum", col(1), "s"),
                                             ("count_star", None, "c")], "partial")
ex = B.mesh_exchange(partial, B.hash_partitioning([col(0)], P), "ex0")
final = B.hash_agg(ex, [(col(0), "k")], [("sum", col(1), "s"),
                                         ("count", col(2), "c")], "final")

driver = MeshQueryDriver(mesh, spmd=True)
outs = driver.run(final, {"fact": parts})
rows = []
for p, bs in enumerate(outs):
    for b in bs:
        rows.append(b.to_pandas())
got = (pd.concat(rows) if rows else pd.DataFrame({"k": [], "s": [], "c": []}))
st = driver.stats[0]
assert st.mode == "mesh", st.mode
# emit this process's share for the parent to combine
for _, r in got.iterrows():
    print(f"ROW {int(r['k'])} {int(r['s'])} {int(r['c'])}")
print(f"proc {pid} spmd ok: {len(got)} groups")
"""


_SPMD_FILE_WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["AURON_TPU_ROOT"])
from auron_tpu.parallel import multihost

assert multihost.initialize_from_env()

import numpy as np
import pandas as pd
import pyarrow as pa
import jax
from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exprs.ir import col
from auron_tpu.parallel.mesh_driver import MeshQueryDriver
from auron_tpu.plan import builders as B
from auron_tpu.utils.config import Configuration, EXCHANGE_MODE

pid, nprocs = multihost.process_info()
mesh = multihost.global_mesh()
P = len(jax.devices())
assert P == 8 and nprocs == 2

rng = np.random.default_rng(7)
df = pd.DataFrame({
    "k": rng.integers(0, 37, 5000).astype(np.int64),
    "v": rng.integers(-100, 100, 5000).astype(np.int64),
})
per = (len(df) + P - 1) // P
parts = [[Batch.from_arrow(pa.RecordBatch.from_pandas(
    df.iloc[p * per : (p + 1) * per], preserve_index=False))] for p in range(P)]
schema = T.Schema((T.Field("k", T.INT64, False), T.Field("v", T.INT64, False)))

scan = B.memory_scan(schema, "fact")
partial = B.hash_agg(scan, [(col(0), "k")], [("sum", col(1), "s"),
                                             ("count_star", None, "c")], "partial")
ex = B.mesh_exchange(partial, B.hash_partitioning([col(0)], P), "exf")
final = B.hash_agg(ex, [(col(0), "k")], [("sum", col(1), "s"),
                                         ("count", col(2), "c")], "final")

conf = Configuration().set(EXCHANGE_MODE, "file")
driver = MeshQueryDriver(mesh, conf=conf, work_dir=os.environ["AURON_WORK"],
                         spmd=True)
outs = driver.run(final, {"fact": parts})
rows = []
for p, bs in enumerate(outs):
    for b in bs:
        rows.append(b.to_pandas())
got = (pd.concat(rows) if rows else pd.DataFrame({"k": [], "s": [], "c": []}))
st = driver.stats[0]
assert st.mode == "file", st.mode
for _, r in got.iterrows():
    print(f"ROW {int(r['k'])} {int(r['s'])} {int(r['c'])}")
print(f"proc {pid} spmd-file ok: {len(got)} groups")
"""


@pytest.mark.timeout(240)
def test_two_process_spmd_file_exchange(tmp_path):
    """SPMD exchange over the durable FILE transport: the shared-work_dir
    capability probe passes (same-machine tmp dir), each process writes
    its local map outputs under global shard names, a barrier publishes
    them, and every process's reduce side reads all peers' files
    (closes the VERDICT r4 weak #5 file-transport gap)."""
    import numpy as np
    import pandas as pd

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    work = str(tmp_path / "shared_work")
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            AURON_COORDINATOR=f"127.0.0.1:{port}",
            AURON_NUM_PROCS="2",
            AURON_PROC_ID=str(pid),
            AURON_TPU_ROOT=root,
            AURON_WORK=work,
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _SPMD_FILE_WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=210)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"spmd file workers hung; partial output: {outs}")
    rows = []
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert f"proc {pid} spmd-file ok:" in out
        for ln in out.splitlines():
            if ln.startswith("ROW "):
                k, s, c = ln.split()[1:]
                rows.append((int(k), int(s), int(c)))

    rng = np.random.default_rng(7)
    df = pd.DataFrame({
        "k": rng.integers(0, 37, 5000).astype(np.int64),
        "v": rng.integers(-100, 100, 5000).astype(np.int64),
    })
    want = df.groupby("k").agg(s=("v", "sum"), c=("v", "size")).reset_index()
    got = pd.DataFrame(rows, columns=["k", "s", "c"]).sort_values("k")
    assert len(got) == len(got["k"].unique()), "group split across processes"
    got = got.reset_index(drop=True)
    want = want.sort_values("k").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, want.astype({"c": np.int64}), check_dtype=False)


_SPMD_DICT_WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["AURON_TPU_ROOT"])
from auron_tpu.parallel import multihost

assert multihost.initialize_from_env()

import numpy as np
import pandas as pd
import pyarrow as pa
import jax
from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exprs.ir import col
from auron_tpu.parallel.mesh_driver import MeshQueryDriver
from auron_tpu.plan import builders as B

pid, nprocs = multihost.process_info()
mesh = multihost.global_mesh()
P = len(jax.devices())
assert P == 8 and nprocs == 2

# STRING group-by key: the exchange must unify dictionaries across
# processes (TPC-DS is full of string group-bys — VERDICT r4 weak #5)
rng = np.random.default_rng(11)
cats = np.array(["Books", "Home", "Electronics", "Music", "Sports",
                 "Garden", "Toys"])
df = pd.DataFrame({
    "cat": rng.choice(cats, 4000),
    "v": rng.integers(-300, 300, 4000).astype(np.int64),
})
per = (len(df) + P - 1) // P
# each shard sees a DIFFERENT subset of categories -> local dictionaries
# genuinely differ across shards and processes
parts = [[Batch.from_arrow(pa.RecordBatch.from_pandas(
    df.iloc[p * per : (p + 1) * per], preserve_index=False))] for p in range(P)]
schema = T.Schema((T.Field("cat", T.STRING, False),
                   T.Field("v", T.INT64, False)))

scan = B.memory_scan(schema, "fact")
partial = B.hash_agg(scan, [(col(0), "cat")], [("sum", col(1), "s"),
                                               ("count_star", None, "c")], "partial")
ex = B.mesh_exchange(partial, B.hash_partitioning([col(0)], P), "ex0")
final = B.hash_agg(ex, [(col(0), "cat")], [("sum", col(1), "s"),
                                           ("count", col(2), "c")], "final")

driver = MeshQueryDriver(mesh, spmd=True)
outs = driver.run(final, {"fact": parts})
rows = []
for p, bs in enumerate(outs):
    for b in bs:
        rows.append(b.to_pandas())
got = (pd.concat(rows) if rows else pd.DataFrame({"cat": [], "s": [], "c": []}))
st = driver.stats[0]
assert st.mode == "mesh", st.mode
for _, r in got.iterrows():
    print(f"ROW {r['cat']} {int(r['s'])} {int(r['c'])}")
print(f"proc {pid} spmd-dict ok: {len(got)} groups")
"""


@pytest.mark.timeout(240)
def test_two_process_spmd_dict_group_by(tmp_path):
    """SPMD planned query whose group-by key is a dict-encoded STRING
    column across 2 real processes: the mesh exchange allgathers and
    merges per-process dictionaries so codes agree globally
    (mesh_driver._unify_dicts_global; closes VERDICT r4 weak #5)."""
    import numpy as np
    import pandas as pd

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            AURON_COORDINATOR=f"127.0.0.1:{port}",
            AURON_NUM_PROCS="2",
            AURON_PROC_ID=str(pid),
            AURON_TPU_ROOT=root,
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _SPMD_DICT_WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=210)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"spmd dict workers hung; partial output: {outs}")
    rows = []
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert f"proc {pid} spmd-dict ok:" in out
        for ln in out.splitlines():
            if ln.startswith("ROW "):
                cat, s, c = ln.split()[1:]
                rows.append((cat, int(s), int(c)))

    rng = np.random.default_rng(11)
    cats = np.array(["Books", "Home", "Electronics", "Music", "Sports",
                     "Garden", "Toys"])
    df = pd.DataFrame({
        "cat": rng.choice(cats, 4000),
        "v": rng.integers(-300, 300, 4000).astype(np.int64),
    })
    want = df.groupby("cat").agg(s=("v", "sum"), c=("v", "size")).reset_index()
    got = pd.DataFrame(rows, columns=["cat", "s", "c"]).sort_values("cat")
    assert len(got) == len(got["cat"].unique()), "group split across processes"
    got = got.reset_index(drop=True)
    want = want.sort_values("cat").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


@pytest.mark.timeout(240)
def test_two_process_spmd_planned_query(tmp_path):
    """A REAL planned query (partial agg -> mesh_exchange -> final agg)
    through MeshQueryDriver across 2 jax.distributed processes: each runs
    only its local shards, the exchange rides the global-mesh all_to_all
    (VERDICT r3 weak #6 — beyond psum plumbing)."""
    import numpy as np
    import pandas as pd

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            AURON_COORDINATOR=f"127.0.0.1:{port}",
            AURON_NUM_PROCS="2",
            AURON_PROC_ID=str(pid),
            AURON_TPU_ROOT=root,
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _SPMD_WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=210)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"spmd workers hung; partial output: {outs}")
    rows = []
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert f"proc {pid} spmd ok:" in out
        for ln in out.splitlines():
            if ln.startswith("ROW "):
                k, s, c = ln.split()[1:]
                rows.append((int(k), int(s), int(c)))

    # combined across both processes == pandas oracle, each group once
    rng = np.random.default_rng(5)
    df = pd.DataFrame({
        "k": rng.integers(0, 61, 6000).astype(np.int64),
        "v": rng.integers(-500, 500, 6000).astype(np.int64),
    })
    want = df.groupby("k").agg(s=("v", "sum"), c=("v", "size")).reset_index()
    got = pd.DataFrame(rows, columns=["k", "s", "c"]).sort_values("k")
    assert len(got) == len(got["k"].unique()), "group split across processes"
    got = got.reset_index(drop=True)
    want = want.sort_values("k").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, want.astype({"c": np.int64}), check_dtype=False)


@pytest.mark.timeout(240)
def test_two_process_global_mesh_collective(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)  # skip the axon sitecustomize
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            AURON_COORDINATOR=f"127.0.0.1:{port}",
            AURON_NUM_PROCS="2",
            AURON_PROC_ID=str(pid),
            AURON_TPU_ROOT=root,
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _WORKER],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=210)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"multihost workers hung; partial output: {outs}")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid} ok: global devices=8 psum=28" in out

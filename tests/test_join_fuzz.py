"""Randomized join differential testing across the full matrix.

Random tables (duplicate keys, NULL keys, multiple batches) x random join
type x random exec kind x random build side, against SQL-semantics pandas
oracles — the fuzzing extension of the fixed matrix in test_joins.py.
"""

from collections import Counter

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu.columnar import Batch
from auron_tpu.exec.basic import MemoryScanExec
from auron_tpu.exec.joins import BroadcastHashJoinExec, SortMergeJoinExec
from auron_tpu.exprs.ir import col


def _mk(df, chunk):
    bs = [
        Batch.from_arrow(
            pa.RecordBatch.from_pandas(df.iloc[i : i + chunk], preserve_index=False)
        )
        for i in range(0, max(len(df), 1), chunk)
    ]
    if not bs:
        bs = [Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))]
    return MemoryScanExec.single(bs)


def _table(rng, n, key_range, null_frac):
    k = rng.integers(0, key_range, n).astype(float)
    k[rng.random(n) < null_frac] = np.nan
    return pd.DataFrame({
        "k": pd.array([None if np.isnan(x) else int(x) for x in k], dtype="Int64"),
        "p": rng.integers(0, 1000, n),
    })


def _rows(df, cols):
    out = []
    for _, r in df[cols].iterrows():
        out.append(tuple(None if pd.isna(v) else int(v) for v in r))
    return Counter(out)


@pytest.mark.parametrize("seed", range(12))
def test_join_fuzz(seed):
    rng = np.random.default_rng(seed + 100)
    ldf = _table(rng, int(rng.integers(0, 120)), int(rng.integers(1, 25)), 0.1)
    rdf = _table(rng, int(rng.integers(0, 120)), int(rng.integers(1, 25)), 0.1)
    rdf = rdf.rename(columns={"k": "k2", "p": "q"})
    jt = str(rng.choice(["inner", "left", "right", "full", "left_semi",
                         "left_anti", "existence"]))
    kind = str(rng.choice(["smj", "bhj_left", "bhj_right"]))
    chunk = int(rng.integers(16, 64))

    left = _mk(ldf, chunk)
    right = _mk(rdf, chunk)
    if kind == "smj":
        op = SortMergeJoinExec(left, right, [col(0)], [col(0)], jt)
    else:
        op = BroadcastHashJoinExec(
            left, right, [col(0)], [col(0)], jt,
            build_side="left" if kind == "bhj_left" else "right",
        )
    got = op.collect().to_pandas()

    lnn = ldf[ldf.k.notna()]
    rnn = rdf[rdf.k2.notna()]
    rkeys = set(rnn.k2)
    if jt == "inner":
        want = lnn.merge(rnn, left_on="k", right_on="k2")
        assert _rows(got, ["k", "p", "k2", "q"]) == _rows(want, ["k", "p", "k2", "q"])
    elif jt == "left":
        want = ldf.merge(rnn, left_on="k", right_on="k2", how="left")
        assert _rows(got, ["k", "p", "k2", "q"]) == _rows(want, ["k", "p", "k2", "q"])
    elif jt == "right":
        want = lnn.merge(rdf, left_on="k", right_on="k2", how="right")
        assert _rows(got, ["k", "p", "k2", "q"]) == _rows(want, ["k", "p", "k2", "q"])
    elif jt == "full":
        left_part = ldf.merge(rnn, left_on="k", right_on="k2", how="left")
        matched = set(lnn.k) & rkeys
        right_un = rdf[~rdf.k2.isin(matched) | rdf.k2.isna()]
        pad = pd.DataFrame({"k": [None] * len(right_un), "p": [None] * len(right_un)})
        pad.index = right_un.index
        want = pd.concat([left_part, pd.concat([pad, right_un], axis=1)],
                         ignore_index=True)
        assert _rows(got, ["k", "p", "k2", "q"]) == _rows(want, ["k", "p", "k2", "q"])
    elif jt == "left_semi":
        want = ldf[ldf.k.isin(rkeys)]
        assert _rows(got, ["k", "p"]) == _rows(want, ["k", "p"])
    elif jt == "left_anti":
        want = ldf[~ldf.k.isin(rkeys) | ldf.k.isna()]
        assert _rows(got, ["k", "p"]) == _rows(want, ["k", "p"])
    else:  # existence
        assert len(got) == len(ldf)
        for _, r in got.iterrows():
            expect = (not pd.isna(r.k)) and int(r.k) in rkeys
            assert bool(r["exists"]) == expect

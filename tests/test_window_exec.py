"""Window exec tests vs pandas oracles."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exec.basic import MemoryScanExec
from auron_tpu.exec.window_exec import WindowExec, WindowFunc
from auron_tpu.exprs.ir import col
from auron_tpu.ops.sortkeys import SortSpec


def _win(df, funcs, chunk=None, part_cols=(0,), order_cols=(1,)):
    if chunk:
        bs = [
            Batch.from_arrow(
                pa.RecordBatch.from_pandas(df.iloc[i : i + chunk], preserve_index=False)
            )
            for i in range(0, len(df), chunk)
        ]
    else:
        bs = [Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))]
    scan = MemoryScanExec.single(bs)
    w = WindowExec(
        scan,
        [col(i) for i in part_cols],
        [(col(i), SortSpec()) for i in order_cols],
        funcs,
    )
    return w.collect().to_pandas()


def _df(n=200, seed=21):
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "g": rng.integers(0, 8, n),
            "o": rng.permutation(n),
            "v": rng.normal(size=n).round(3),
        }
    )


def test_row_number_rank_dense():
    df = _df()
    got = _win(
        df,
        [
            (WindowFunc("row_number"), "rn"),
            (WindowFunc("rank"), "rk"),
            (WindowFunc("dense_rank"), "dr"),
        ],
        chunk=64,
    )
    got = got.sort_values(["g", "o"]).reset_index(drop=True)
    want = df.sort_values(["g", "o"]).reset_index(drop=True)
    grp = want.groupby("g")["o"]
    assert got["rn"].tolist() == grp.cumcount().add(1).tolist()
    assert got["rk"].tolist() == grp.rank(method="min").astype(int).tolist()
    assert got["dr"].tolist() == grp.rank(method="dense").astype(int).tolist()


def test_rank_with_ties():
    df = pd.DataFrame({"g": [1] * 6, "o": [10, 10, 20, 20, 20, 30], "v": range(6)})
    got = _win(df, [(WindowFunc("rank"), "rk"), (WindowFunc("dense_rank"), "dr"),
                    (WindowFunc("percent_rank"), "pr"), (WindowFunc("cume_dist"), "cd")])
    assert got["rk"].tolist() == [1, 1, 3, 3, 3, 6]
    assert got["dr"].tolist() == [1, 1, 2, 2, 2, 3]
    assert got["pr"].tolist() == pytest.approx([0, 0, 0.4, 0.4, 0.4, 1.0])
    assert got["cd"].tolist() == pytest.approx([2 / 6, 2 / 6, 5 / 6, 5 / 6, 5 / 6, 1.0])


def test_lead_lag():
    df = _df(100)
    got = _win(
        df,
        [
            (WindowFunc("lead", expr=col(2), offset=1), "ld"),
            (WindowFunc("lag", expr=col(2), offset=2), "lg"),
        ],
    )
    got = got.sort_values(["g", "o"]).reset_index(drop=True)
    want = df.sort_values(["g", "o"]).reset_index(drop=True)
    wld = want.groupby("g")["v"].shift(-1)
    wlg = want.groupby("g")["v"].shift(2)
    assert [None if pd.isna(x) else x for x in got["ld"]] == [
        None if pd.isna(x) else x for x in wld
    ]
    assert [None if pd.isna(x) else x for x in got["lg"]] == [
        None if pd.isna(x) else x for x in wlg
    ]


def test_running_and_whole_aggs():
    df = _df(150, seed=22)
    got = _win(
        df,
        [
            (WindowFunc("agg", agg="sum", expr=col(2)), "rsum"),
            (WindowFunc("agg", agg="count", expr=col(2)), "rcnt"),
            (WindowFunc("agg", agg="min", expr=col(2)), "rmin"),
            (WindowFunc("agg", agg="max", expr=col(2)), "rmax"),
            (WindowFunc("agg", agg="sum", expr=col(2), frame_whole=True), "tsum"),
            (WindowFunc("agg", agg="avg", expr=col(2), frame_whole=True), "tavg"),
        ],
        chunk=50,
    )
    got = got.sort_values(["g", "o"]).reset_index(drop=True)
    want = df.sort_values(["g", "o"]).reset_index(drop=True)
    g = want.groupby("g")["v"]
    assert got["rsum"].tolist() == pytest.approx(g.cumsum().tolist())
    assert got["rcnt"].tolist() == g.cumcount().add(1).tolist()
    assert got["rmin"].tolist() == pytest.approx(g.cummin().tolist())
    assert got["rmax"].tolist() == pytest.approx(g.cummax().tolist())
    assert got["tsum"].tolist() == pytest.approx(g.transform("sum").tolist())
    assert got["tavg"].tolist() == pytest.approx(g.transform("mean").tolist())


def test_running_sum_ties_share_value():
    # RANGE frame: peer rows (same order key) share the running value
    df = pd.DataFrame({"g": [1] * 4, "o": [1, 2, 2, 3], "v": [1.0, 2.0, 3.0, 4.0]})
    got = _win(df, [(WindowFunc("agg", agg="sum", expr=col(2)), "rs")])
    assert got["rs"].tolist() == pytest.approx([1.0, 6.0, 6.0, 10.0])


def test_nulls_in_agg_input():
    df = pd.DataFrame(
        {"g": [1, 1, 1], "o": [1, 2, 3], "v": pd.array([1.0, None, 3.0], dtype="Float64")}
    )
    got = _win(df, [(WindowFunc("agg", agg="sum", expr=col(2)), "rs"),
                    (WindowFunc("agg", agg="count", expr=col(2)), "rc")])
    assert got["rs"].tolist() == pytest.approx([1.0, 1.0, 4.0])
    assert got["rc"].tolist() == [1, 1, 2]


def test_no_partition_by():
    df = pd.DataFrame({"g": [0, 0], "o": [2, 1], "v": [5.0, 7.0]})
    got = _win(df, [(WindowFunc("row_number"), "rn")], part_cols=(), order_cols=(1,))
    assert got.sort_values("o")["rn"].tolist() == [1, 2]


def test_nth_value_ties_share_visibility():
    df = pd.DataFrame({"g": [1] * 4, "o": [1, 1, 2, 3], "v": [10.0, 20.0, 30.0, 40.0]})
    got = _win(df, [(WindowFunc("nth_value", expr=col(2), offset=2), "n2")])
    # rows 0,1 are peers; frame end covers position 1, so BOTH see the 2nd value
    vals = got.sort_values("o")["n2"].tolist()
    assert vals[0] == 20.0 and vals[1] == 20.0
    assert vals[2] == 20.0 and vals[3] == 20.0


def test_window_group_limit():
    from auron_tpu.exec.window_exec import WindowGroupLimitExec

    df = _df(100, seed=33)
    scan = MemoryScanExec.single(
        [Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))]
    )
    op = WindowGroupLimitExec(scan, [col(0)], [(col(1), SortSpec())], limit=3)
    got = op.collect().to_pandas().sort_values(["g", "o"]).reset_index(drop=True)
    want = (
        df.sort_values(["g", "o"]).groupby("g").head(3)
        .sort_values(["g", "o"]).reset_index(drop=True)
    )
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def test_ntile():
    df = pd.DataFrame({"g": [1] * 7, "o": list(range(7)), "v": [0.0] * 7})
    got = _win(df, [(WindowFunc("ntile", offset=3), "nt")])
    # 7 rows, 3 tiles -> sizes 3,2,2
    assert got.sort_values("o")["nt"].tolist() == [1, 1, 1, 2, 2, 3, 3]


def test_ntile_fewer_rows_than_buckets():
    df = pd.DataFrame({"g": [1, 1], "o": [0, 1], "v": [0.0, 0.0]})
    got = _win(df, [(WindowFunc("ntile", offset=4), "nt")])
    assert got.sort_values("o")["nt"].tolist() == [1, 2]


def test_window_min_max_strings_lexicographic():
    # ADVICE r1 (high): dict-code min/max must use lexicographic rank
    df = pd.DataFrame(
        {
            "g": [1, 1, 1, 2, 2],
            "o": [0, 1, 2, 0, 1],
            "s": ["zebra", "apple", "mango", "pear", "fig"],
        }
    )
    got = _win(
        df,
        [
            (WindowFunc("agg", agg="min", expr=col(2), frame_whole=True), "mn"),
            (WindowFunc("agg", agg="max", expr=col(2), frame_whole=True), "mx"),
            (WindowFunc("agg", agg="min", expr=col(2)), "rmn"),
            (WindowFunc("agg", agg="max", expr=col(2)), "rmx"),
        ],
    )
    got = got.sort_values(["g", "o"]).reset_index(drop=True)
    assert list(got["mn"]) == ["apple"] * 3 + ["fig"] * 2
    assert list(got["mx"]) == ["zebra"] * 3 + ["pear"] * 2
    # running frame: prefix min/max in order o
    assert list(got["rmn"]) == ["zebra", "apple", "apple", "pear", "fig"]
    assert list(got["rmx"]) == ["zebra", "zebra", "zebra", "pear", "pear"]

"""Expression engine tests: null semantics, decimal math, casts, functions.

Differential where possible (python/pandas oracle), plus Spark-semantics
edge cases (division by zero -> NULL, Java float->int narrowing, HALF_UP
decimal rounding, Kleene logic).
"""

import datetime as dt
import decimal as pydec

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exprs import eval_exprs
from auron_tpu.exprs.ir import (
    BinaryOp, Case, Cast, Coalesce, Column, If, In, IsNull, Like, Literal,
    Not, ScalarFunc, col, lit,
)


def _run(data, exprs, schema=None):
    b = Batch.from_pydict(data, schema=schema)
    outs = eval_exprs(b, exprs)
    n = b.num_rows()
    res = []
    for o in outs:
        vals = np.asarray(o.values)[:n]
        mask = np.asarray(o.validity)[:n]
        if o.dtype.is_dict_encoded:
            d = o.dict.to_pylist()
            res.append([d[v] if m else None for v, m in zip(vals, mask)])
        elif o.dtype.kind == T.TypeKind.DECIMAL:
            res.append(
                [
                    pydec.Decimal(int(v)).scaleb(-o.dtype.scale) if m else None
                    for v, m in zip(vals.tolist(), mask)
                ]
            )
        else:
            res.append([v if m else None for v, m in zip(vals.tolist(), mask)])
    return res


def test_arithmetic_nulls():
    data = {"a": pa.array([1, None, 3], type=pa.int64()),
            "b": pa.array([10, 20, None], type=pa.int64())}
    (add,), (mul,) = (
        _run(data, [BinaryOp("add", col(0), col(1))]),
        _run(data, [BinaryOp("mul", col(0), col(1))]),
    )
    assert add == [11, None, None]
    assert mul == [10, None, None]


def test_int_div_is_double_and_div_by_zero_null():
    data = {"a": pa.array([7, 1], type=pa.int32()),
            "b": pa.array([2, 0], type=pa.int32())}
    (r,) = _run(data, [BinaryOp("div", col(0), col(1))])
    assert r[0] == pytest.approx(3.5)
    assert r[1] is None  # Spark: x / 0 -> NULL
    (m,) = _run(data, [BinaryOp("mod", col(0), col(1))])
    assert m == [1, None]


def test_mod_sign_follows_dividend():
    data = {"a": pa.array([-7, 7], type=pa.int64()),
            "b": pa.array([3, -3], type=pa.int64())}
    (m,) = _run(data, [BinaryOp("mod", col(0), col(1))])
    assert m == [-1, 1]  # Java % semantics


def test_decimal_arith():
    data = {
        "a": pa.array([pydec.Decimal("12.34"), pydec.Decimal("-0.05"), None],
                      type=pa.decimal128(10, 2)),
        "b": pa.array([pydec.Decimal("1.5"), pydec.Decimal("2.5"), pydec.Decimal("1")],
                      type=pa.decimal128(10, 1)),
    }
    (add,), (mul,), (div,) = (
        _run(data, [BinaryOp("add", col(0), col(1))]),
        _run(data, [BinaryOp("mul", col(0), col(1))]),
        _run(data, [BinaryOp("div", col(0), col(1))]),
    )
    assert add == [pydec.Decimal("13.84"), pydec.Decimal("2.45"), None]
    assert mul == [pydec.Decimal("18.510"), pydec.Decimal("-0.125"), None]
    # div scale: max(6, s1+p2+1) = max(6, 2+10+1) = 13, HALF_UP
    assert div[0] == pydec.Decimal("8.2266666666667")
    assert div[1] == pydec.Decimal("-0.02")


def test_decimal_overflow_null():
    t = T.Schema.of(T.Field("a", T.decimal(18, 0)), T.Field("b", T.decimal(18, 0)))
    data = {"a": [pydec.Decimal(10**17)], "b": [pydec.Decimal(10**17)]}
    (m,) = _run(data, [BinaryOp("mul", col(0), col(1))], schema=t)
    assert m == [None]  # 10^34 exceeds decimal64 domain -> NULL


def test_three_valued_logic():
    data = {"a": pa.array([True, True, True, False, False, None, None]),
            "b": pa.array([True, False, None, False, None, None, True])}
    (a,), (o,) = (
        _run(data, [BinaryOp("and", col(0), col(1))]),
        _run(data, [BinaryOp("or", col(0), col(1))]),
    )
    assert a == [True, False, None, False, False, None, None]
    assert o == [True, True, True, False, None, None, True]


def test_comparisons_and_strings():
    data = {"s": pa.array(["apple", "banana", None, "apple"]),
            "t": pa.array(["apricot", "banana", "x", None])}
    (eq,), (lt,) = (
        _run(data, [BinaryOp("eq", col(0), col(1))]),
        _run(data, [BinaryOp("lt", col(0), col(1))]),
    )
    assert eq == [False, True, None, None]
    assert lt == [True, False, None, None]
    (lit_cmp,) = _run(data, [BinaryOp("gteq", col(0), lit("b"))])
    assert lit_cmp == [False, True, None, False]


def test_case_if_coalesce():
    data = {"x": pa.array([1, 5, None, 10], type=pa.int64())}
    expr = Case(
        branches=(
            (BinaryOp("lt", col(0), lit(3)), lit("small")),
            (BinaryOp("lt", col(0), lit(7)), lit("mid")),
        ),
        orelse=lit("big"),
    )
    (r,) = _run(data, [expr])
    assert r == ["small", "mid", "big", "big"]  # NULL cond -> falls to else
    (c,) = _run(data, [Coalesce((col(0), lit(-1)))])
    assert c == [1, 5, -1, 10]
    (i,) = _run(data, [If(IsNull(col(0)), lit(0), col(0))])
    assert i == [1, 5, 0, 10]


def test_in_and_like():
    data = {"s": pa.array(["foo", "bar", "baz", None])}
    (r,) = _run(data, [In(col(0), ("foo", "baz"))])
    assert r == [True, False, True, None]
    (l,) = _run(data, [Like(col(0), "ba%")])
    assert l == [False, True, True, None]
    (l2,) = _run(data, [Like(col(0), "_a_")])
    assert l2 == [False, True, True, None]


def test_cast_int_wrap_and_float_saturate():
    data = {"x": pa.array([300, -300], type=pa.int64()),
            "f": pa.array([1e20, float("nan")], type=pa.float64())}
    (w,) = _run(data, [Cast(col(0), T.INT8)])
    assert w == [44, -44]  # two's complement wrap like Java
    (s,) = _run(data, [Cast(col(1), T.INT32)])
    assert s == [2**31 - 1, 0]  # saturate; NaN -> 0
    (s64,) = _run(data, [Cast(col(1), T.INT64)])
    assert s64 == [2**63 - 1, 0]


def test_cast_string_to_numeric():
    data = {"s": pa.array(["123", " 45 ", "1.9", "abc", None])}
    (i,) = _run(data, [Cast(col(0), T.INT32)])
    assert i == [123, 45, 1, None, None]
    (f,) = _run(data, [Cast(col(0), T.FLOAT64)])
    assert f == [123.0, 45.0, 1.9, None, None]
    (d,) = _run(data, [Cast(col(0), T.decimal(10, 2))])
    assert d == [pydec.Decimal("123.00"), pydec.Decimal("45.00"),
                 pydec.Decimal("1.90"), None, None]


def test_cast_date_timestamp():
    data = {"d": pa.array([18000, 0], type=pa.int32()).cast(pa.date32())}
    (ts,) = _run(data, [Cast(col(0), T.TIMESTAMP)])
    assert ts == [18000 * 86_400_000_000, 0]
    data2 = {"t": pa.array([np.datetime64("2024-03-05T17:30:00", "us")])}
    (back,) = _run(data2, [Cast(col(0), T.DATE32)])
    want = (dt.date(2024, 3, 5) - dt.date(1970, 1, 1)).days
    assert back == [want]


def test_date_functions_vs_python():
    dates = [dt.date(1969, 12, 31), dt.date(1970, 1, 1), dt.date(2000, 2, 29),
             dt.date(2024, 12, 31), dt.date(1900, 3, 1)]
    days = [(d - dt.date(1970, 1, 1)).days for d in dates]
    data = {"d": pa.array(days, type=pa.int32()).cast(pa.date32())}
    (y,), (m,), (dd,), (doy,), (dow,) = (
        _run(data, [ScalarFunc("year", (col(0),))]),
        _run(data, [ScalarFunc("month", (col(0),))]),
        _run(data, [ScalarFunc("day", (col(0),))]),
        _run(data, [ScalarFunc("dayofyear", (col(0),))]),
        _run(data, [ScalarFunc("dayofweek", (col(0),))]),
    )
    assert y == [d.year for d in dates]
    assert m == [d.month for d in dates]
    assert dd == [d.day for d in dates]
    assert doy == [d.timetuple().tm_yday for d in dates]
    assert dow == [(d.isoweekday() % 7) + 1 for d in dates]


def test_round_half_up():
    data = {"f": pa.array([2.5, -2.5, 1.4], type=pa.float64()),
            "d": pa.array([pydec.Decimal("2.345"), pydec.Decimal("-2.345"),
                           pydec.Decimal("1.004")], type=pa.decimal128(10, 3))}
    (rf,) = _run(data, [ScalarFunc("round", (col(0),))])
    assert rf == [3.0, -3.0, 1.0]  # away from zero, unlike banker's
    (rd,) = _run(data, [ScalarFunc("round", (col(1), lit(2)))])
    assert rd == [pydec.Decimal("2.35"), pydec.Decimal("-2.35"), pydec.Decimal("1.00")]


def test_string_functions():
    data = {"s": pa.array(["Hello", "wORLD", None, ""])}
    (u,), (low,), (ln,), (sub,) = (
        _run(data, [ScalarFunc("upper", (col(0),))]),
        _run(data, [ScalarFunc("lower", (col(0),))]),
        _run(data, [ScalarFunc("length", (col(0),))]),
        _run(data, [ScalarFunc("substring", (col(0), lit(2), lit(3)))]),
    )
    assert u == ["HELLO", "WORLD", None, ""]
    assert low == ["hello", "world", None, ""]
    assert ln == [5, 5, None, 0]
    assert sub == ["ell", "ORL", None, ""]
    (sw,) = _run(data, [ScalarFunc("starts_with", (col(0), lit("He")))])
    assert sw == [True, False, None, False]


def test_common_subexpression_memo():
    # same structural subtree evaluated once: verify via evaluation count
    from auron_tpu.exprs.eval import Evaluator

    data = {"x": pa.array([1.0, 2.0], type=pa.float64())}
    b = Batch.from_pydict(data)
    ev = Evaluator(b.schema)
    sub = BinaryOp("mul", col(0), col(0))
    e1 = BinaryOp("add", sub, sub)
    calls = {"n": 0}
    orig = ev._eval_uncached

    def counting(e, bb, memo):
        calls["n"] += 1
        return orig(e, bb, memo)

    ev._eval_uncached = counting
    ev.evaluate(b, [e1])
    # nodes: e1, sub (once), col (once) => 3, not 5
    assert calls["n"] == 3

"""Sort / TakeOrdered tests, differential against pandas sort_values."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exec.base import ExecutionContext
from auron_tpu.exec.basic import MemoryScanExec
from auron_tpu.exec.sort_exec import SortExec
from auron_tpu.exprs.ir import col
from auron_tpu.ops.sortkeys import SortSpec


def _sort(batches, exprs, specs, fetch=None, spill_rows=1 << 21):
    scan = MemoryScanExec.single(batches)
    s = SortExec(scan, exprs, specs, fetch=fetch, spill_threshold_rows=spill_rows)
    return s.collect().to_pandas()


def test_basic_asc_desc_nulls():
    df = pd.DataFrame({"x": [3, None, 1, 2, None], "y": list("abcde")})
    b = Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))
    asc_nf = _sort([b], [col(0)], [SortSpec(asc=True, nulls_first=True)])
    assert asc_nf["y"].tolist() == ["b", "e", "c", "d", "a"]
    asc_nl = _sort([b], [col(0)], [SortSpec(asc=True, nulls_first=False)])
    assert asc_nl["y"].tolist() == ["c", "d", "a", "b", "e"]
    desc_nl = _sort([b], [col(0)], [SortSpec(asc=False, nulls_first=False)])
    assert desc_nl["y"].tolist() == ["a", "d", "c", "b", "e"]


def test_multikey_random_vs_pandas():
    rng = np.random.default_rng(2)
    n = 3000
    df = pd.DataFrame(
        {
            "a": rng.integers(-5, 5, n),
            "b": rng.normal(size=n),
            "c": rng.choice(["pq", "ab", "zz", "mm"], n),
        }
    )
    batches = [
        Batch.from_arrow(
            pa.RecordBatch.from_pandas(df.iloc[i : i + 700], preserve_index=False)
        )
        for i in range(0, n, 700)
    ]
    got = _sort(
        batches,
        [col(0), col(2), col(1)],
        [SortSpec(asc=True), SortSpec(asc=False), SortSpec(asc=True)],
    )
    want = df.sort_values(
        ["a", "c", "b"], ascending=[True, False, True], kind="stable"
    ).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def test_float_nan_sorts_greatest():
    rb = pa.record_batch(
        {"x": pa.array([1.0, float("nan"), -1.0, float("inf"), -float("inf")],
                       type=pa.float64())}
    )
    b = Batch.from_arrow(rb)
    got = _sort([b], [col(0)], [SortSpec(asc=True)])
    vals = got["x"].tolist()
    assert vals[0] == -float("inf") and vals[-2] == float("inf") and np.isnan(vals[-1])


def test_take_ordered():
    df = pd.DataFrame({"x": [5, 3, 9, 1, 7]})
    b = Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))
    got = _sort([b], [col(0)], [SortSpec()], fetch=3)
    assert got["x"].tolist() == [1, 3, 5]


def test_spilled_runs_merge():
    rng = np.random.default_rng(3)
    n = 4000
    df = pd.DataFrame({"x": rng.integers(0, 10_000, n),
                       "s": rng.choice(["u", "v", "w"], n)})
    batches = [
        Batch.from_arrow(
            pa.RecordBatch.from_pandas(df.iloc[i : i + 500], preserve_index=False)
        )
        for i in range(0, n, 500)
    ]
    # tiny spill threshold forces multiple host runs + merge
    got = _sort([batches_i for batches_i in batches], [col(0)], [SortSpec()], spill_rows=900)
    want = df.sort_values("x", kind="stable").reset_index(drop=True)
    assert got["x"].tolist() == want["x"].tolist()
    # string column survives the merge with unified dictionaries
    assert sorted(set(got["s"])) == ["u", "v", "w"]
    cnt_got = got.groupby("s").size().to_dict()
    cnt_want = want.groupby("s").size().to_dict()
    assert cnt_got == cnt_want


def test_emit_chunks_multiple_batches():
    n = 20000
    df = pd.DataFrame({"x": np.random.default_rng(4).permutation(n)})
    b = Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))
    scan = MemoryScanExec.single([b])
    s = SortExec(scan, [col(0)], [SortSpec()])
    ctx = ExecutionContext()
    # chunked emission is the behavior under test: force a batch size
    # smaller than the input regardless of the engine default
    from auron_tpu.utils.config import BATCH_SIZE

    ctx.conf.set(BATCH_SIZE, 4096)
    out = list(s.execute(0, ctx))
    assert len(out) > 1
    allv = []
    for ob in out:
        allv += ob.to_pydict()["x"]
    assert allv == list(range(n))


def test_spilled_sort_on_string_keys():
    """Per-run dictionary ranks are not globally comparable; sorting BY a
    string column across spilled runs must still produce global order."""
    rng = np.random.default_rng(9)
    words = [f"w{i:04d}" for i in range(400)]
    vals = rng.choice(words, 2000)
    df = pd.DataFrame({"s": vals, "x": np.arange(2000)})
    batches = [
        Batch.from_arrow(
            pa.RecordBatch.from_pandas(df.iloc[i : i + 250], preserve_index=False)
        )
        for i in range(0, 2000, 250)
    ]
    got = _sort(batches, [col(0)], [SortSpec()], spill_rows=500)
    want = df.sort_values("s", kind="stable").reset_index(drop=True)
    assert got["s"].tolist() == want["s"].tolist()


def test_negative_nan_bits_sort_greatest():
    import jax.numpy as jnp

    from auron_tpu.ops.sortkeys import orderable_word
    from auron_tpu.exprs.eval import ColumnVal
    from auron_tpu import types as T

    neg_nan = np.array([0xFFF8000000000000], dtype=np.uint64).view(np.float64)[0]
    vals = jnp.asarray([1.0, neg_nan, -np.inf, np.inf])
    cv = ColumnVal(vals, jnp.ones(4, bool), T.FLOAT64)
    w = np.asarray(orderable_word(cv))
    order = np.argsort(w)
    # ascending: -inf, 1.0, inf, NaN (greatest) — even for negative-bit NaN
    assert order.tolist() == [2, 0, 3, 1]

"""Wide decimal(38,x) columns end-to-end (VERDICT r1 item 10).

p>18 values ride as dictionary codes on device with exact Decimal128
dictionaries host-side, and must survive scan -> shuffle -> join ->
group-by -> aggregation with exact results (reference decimal paths:
ext-commons/src/arrow/cast.rs)."""

import decimal as pydec

# python Decimal arithmetic rounds at the context precision (28 significant
# digits by default) — the ORACLE must be exact, the engine is
pydec.getcontext().prec = 100

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exec.base import ExecutionContext
from auron_tpu.exprs.ir import col
from auron_tpu.plan import builders as B
from auron_tpu.plan.planner import plan_from_proto


def _dec38(rng, n, scale=4):
    """decimal(38, scale) values spanning far beyond int64."""
    out = []
    for _ in range(n):
        # magnitudes sized so per-group EXACT sums stay inside decimal(38)
        # (overflowing sums go NULL per Spark non-ANSI; tested separately)
        mag = int(rng.integers(0, 22))
        u = int(rng.integers(1, 10**9)) * (10**mag) * int(rng.choice([-1, 1]))
        out.append(pydec.Decimal(u).scaleb(-scale))
    return out


@pytest.fixture(scope="module")
def wide_data():
    rng = np.random.default_rng(77)
    n = 500
    fact = {
        "fk": rng.integers(0, 12, n).astype(np.int64).tolist(),
        "amount": _dec38(rng, n),
    }
    dim = {
        "dk": np.arange(12, dtype=np.int64).tolist(),
        "grp": (np.arange(12) % 3).astype(np.int64).tolist(),
    }
    return fact, dim


FACT_SCHEMA = T.Schema.of(T.Field("fk", T.INT64), T.Field("amount", T.decimal(38, 4)))
DIM_SCHEMA = T.Schema.of(T.Field("dk", T.INT64), T.Field("grp", T.INT64))


def _oracle(fact, dim):
    rows = {}
    grp_of = dict(zip(dim["dk"], dim["grp"]))
    for fk, amt in zip(fact["fk"], fact["amount"]):
        g = grp_of[fk]
        s, c, mn, mx = rows.get(g, (pydec.Decimal(0), 0, None, None))
        rows[g] = (
            s + amt, c + 1,
            amt if mn is None or amt < mn else mn,
            amt if mx is None or amt > mx else mx,
        )
    return {
        g: (s, c, mn, mx) for g, (s, c, mn, mx) in sorted(rows.items())
    }


def test_wide_decimal_scan_join_agg_exact(wide_data, tmp_path):
    """parquet scan -> broadcast join -> two-stage group aggregation with
    sum/count/min/max over decimal(38,4), checked EXACTLY vs python
    Decimal arithmetic (no float tolerance)."""
    fact, dim = wide_data
    path = str(tmp_path / "fact.parquet")
    pq.write_table(
        pa.table({
            "fk": pa.array(fact["fk"], pa.int64()),
            "amount": pa.array(fact["amount"], pa.decimal128(38, 4)),
        }),
        path, row_group_size=128,
    )
    dim_b = Batch.from_pydict(dim, schema=DIM_SCHEMA)

    scan = B.parquet_scan(FACT_SCHEMA, [path])
    j = B.hash_join(scan, B.memory_scan(DIM_SCHEMA, "wd_dim"),
                    [col(0)], [col(0)], "inner", build_side="right")
    proj = B.project(j, [(col(3), "grp"), (col(1), "amount")])
    aggs = [("sum", col(1), "s"), ("count", col(1), "c"),
            ("min", col(1), "mn"), ("max", col(1), "mx")]
    partial = B.hash_agg(proj, [(col(0), "grp")], aggs, "partial")
    final = B.hash_agg(partial, [(col(0), "grp")], aggs, "final")

    op = plan_from_proto(final)
    ctx = ExecutionContext(resources={"wd_dim": [[dim_b]]})
    got = op.collect(ctx=ctx).to_arrow().to_pylist()
    got = {r["grp"]: r for r in got}

    want = _oracle(fact, dim)
    assert sorted(got) == sorted(want)
    for g, (s, c, mn, mx) in want.items():
        r = got[g]
        assert r["c"] == c
        assert pydec.Decimal(str(r["s"])) == s, (g, r["s"], s)
        assert pydec.Decimal(str(r["mn"])) == mn
        assert pydec.Decimal(str(r["mx"])) == mx


def test_wide_decimal_shuffle_roundtrip(wide_data, tmp_path):
    """wide decimal columns survive the compacted file shuffle bit-exactly
    (dictionary re-encode at IPC boundaries)."""
    from auron_tpu.bridge import api
    from auron_tpu.exec.shuffle.reader import MultiMapBlockProvider

    fact, _ = wide_data
    b = Batch.from_pydict(fact, schema=FACT_SCHEMA)
    api.put_resource("wd_fact", [[b]])
    try:
        part = B.hash_partitioning([col(0)], 3)
        w = B.shuffle_writer(
            B.memory_scan(FACT_SCHEMA, "wd_fact"), part,
            str(tmp_path / "m.data"), str(tmp_path / "m.index"),
        )
        h = api.call_native(B.task(w).SerializeToString())
        while api.next_batch(h) is not None:
            pass
        api.finalize_native(h)
        api.put_resource(
            "wd_blocks",
            MultiMapBlockProvider([(str(tmp_path / "m.data"), str(tmp_path / "m.index"))]),
        )
        got = []
        for p in range(3):
            h = api.call_native(
                B.task(B.ipc_reader(FACT_SCHEMA, "wd_blocks"), partition_id=p).SerializeToString()
            )
            while (rb := api.next_batch(h)) is not None:
                got += rb.to_pylist()
            api.finalize_native(h)
        want = sorted(zip(fact["fk"], fact["amount"]))
        assert sorted((r["fk"], r["amount"]) for r in got) == want
    finally:
        api.remove_resource("wd_fact")
        api.remove_resource("wd_blocks")


def test_wide_decimal_join_keys(wide_data):
    """joins ON a wide decimal key route and match by exact value."""
    amounts = [pydec.Decimal("123456789012345678901234.5678"),
               pydec.Decimal("-99999999999999999999.0001"),
               pydec.Decimal("0.0001")]
    left = Batch.from_pydict(
        {"a": amounts * 2, "x": list(range(6))},
        schema=T.Schema.of(T.Field("a", T.decimal(38, 4)), T.Field("x", T.INT64)),
    )
    right = Batch.from_pydict(
        {"a2": amounts[:2], "tag": [10, 20]},
        schema=T.Schema.of(T.Field("a2", T.decimal(38, 4)), T.Field("tag", T.INT64)),
    )
    j = B.hash_join(
        B.memory_scan(left.schema, "wd_l"), B.memory_scan(right.schema, "wd_r"),
        [col(0)], [col(0)], "inner", build_side="right",
    )
    op = plan_from_proto(j)
    ctx = ExecutionContext(resources={"wd_l": [[left]], "wd_r": [[right]]})
    got = op.collect(ctx=ctx).to_arrow().to_pylist()
    assert len(got) == 4  # amounts[0], amounts[1] each matched twice
    for r in got:
        assert r["a"] == r["a2"]
        assert r["tag"] == (10 if r["a"] == amounts[0] else 20)


def test_wide_decimal_sort(wide_data):
    """ORDER BY a wide decimal column sorts numerically (not by code)."""
    vals = [pydec.Decimal("1e20"), pydec.Decimal("-3e25"),
            pydec.Decimal("7.5"), None, pydec.Decimal("-0.5")]
    b = Batch.from_pydict(
        {"a": vals}, schema=T.Schema.of(T.Field("a", T.decimal(38, 4)))
    )
    from auron_tpu.ops.sortkeys import SortSpec

    s = B.sort(B.memory_scan(b.schema, "wd_s"), [(col(0), SortSpec())])
    op = plan_from_proto(s)
    ctx = ExecutionContext(resources={"wd_s": [[b]]})
    got = [r["a"] for r in op.collect(ctx=ctx).to_arrow().to_pylist()]
    assert got == [None, pydec.Decimal("-3e25"), pydec.Decimal("-0.5"),
                   pydec.Decimal("7.5"), pydec.Decimal("1e20")]


def test_wide_decimal_sum_overflow_goes_null():
    """a sum whose exact total exceeds 38 digits emits NULL, never a
    wrapped value (Spark non-ANSI overflow semantics)."""
    from auron_tpu.exec.agg_exec import FINAL, PARTIAL, AggExpr, HashAggExec
    from auron_tpu.exec.basic import MemoryScanExec

    vals = [pydec.Decimal(10) ** 33] * 200_000  # exact sum 2e38 > p38
    b = Batch.from_pydict(
        {"k": [1] * len(vals), "v": vals},
        schema=T.Schema.of(T.Field("k", T.INT32), T.Field("v", T.decimal(38, 0))),
    )
    scan = MemoryScanExec.single([b])
    partial = HashAggExec(scan, [(col(0), "k")], [(AggExpr("sum", col(1)), "s")], PARTIAL)
    mid = MemoryScanExec.single(
        list(partial.execute(0, ExecutionContext())) or []
    )
    final = HashAggExec(mid, [(col(0), "k")], [(AggExpr("sum", col(1)), "s")], FINAL)
    got = final.collect().to_arrow().to_pylist()
    assert len(got) == 1 and got[0]["s"] is None


def test_wide_decimal_filter_against_literal():
    """WHERE amount > <literal> compares exact VALUES, not codes."""
    from auron_tpu.exprs.ir import BinaryOp, lit

    vals = [pydec.Decimal("1e25"), pydec.Decimal("-5e20"),
            pydec.Decimal("100.49"), pydec.Decimal("100.51"), None]
    b = Batch.from_pydict(
        {"a": vals}, schema=T.Schema.of(T.Field("a", T.decimal(38, 4)))
    )
    plan = B.filter_(
        B.memory_scan(b.schema, "wf"),
        [BinaryOp("gt", col(0), lit(pydec.Decimal("100.5"), T.decimal(5, 1)))],
    )
    op = plan_from_proto(plan)
    got = [r["a"] for r in op.collect(
        ctx=ExecutionContext(resources={"wf": [[b]]})
    ).to_arrow().to_pylist()]
    assert got == [pydec.Decimal("1e25"), pydec.Decimal("100.51")]


def test_wide_decimal_outer_join_null_side():
    """outer-join null extension builds decimal-typed sentinel dicts."""
    left = Batch.from_pydict(
        {"k": [1, 2, 3]}, schema=T.Schema.of(T.Field("k", T.INT64))
    )
    right = Batch.from_pydict(
        {"k2": [1], "amt": [pydec.Decimal("1e20")]},
        schema=T.Schema.of(T.Field("k2", T.INT64), T.Field("amt", T.decimal(38, 2))),
    )
    j = B.hash_join(B.memory_scan(left.schema, "ol"),
                    B.memory_scan(right.schema, "orr"),
                    [col(0)], [col(0)], "left", build_side="right")
    op = plan_from_proto(j)
    got = op.collect(ctx=ExecutionContext(
        resources={"ol": [[left]], "orr": [[right]]}
    )).to_arrow().to_pylist()
    got = sorted(got, key=lambda r: r["k"])
    assert got[0]["amt"] == pydec.Decimal("1e20")
    assert got[1]["amt"] is None and got[2]["amt"] is None


def test_wide_decimal_scalar_fn_fails_loudly():
    from auron_tpu.exprs.ir import ScalarFunc

    b = Batch.from_pydict(
        {"a": [pydec.Decimal("1e20")]},
        schema=T.Schema.of(T.Field("a", T.decimal(38, 2))),
    )
    plan = B.project(B.memory_scan(b.schema, "wfn"),
                     [(ScalarFunc("abs", (col(0),)), "r")])
    op = plan_from_proto(plan)
    with pytest.raises(NotImplementedError, match="decimal"):
        op.collect(ctx=ExecutionContext(resources={"wfn": [[b]]}))


def test_wide_decimal_vs_int_compare():
    from auron_tpu.exprs.ir import BinaryOp

    b = Batch.from_pydict(
        {"a": [pydec.Decimal("5"), pydec.Decimal("1e20"), pydec.Decimal("-3")],
         "n": [5, 7, -3]},
        schema=T.Schema.of(T.Field("a", T.decimal(38, 0)), T.Field("n", T.INT64)),
    )
    plan = B.project(B.memory_scan(b.schema, "wi"),
                     [(BinaryOp("eq", col(0), col(1)), "e"),
                      (BinaryOp("gt", col(0), col(1)), "g")])
    op = plan_from_proto(plan)
    got = op.collect(ctx=ExecutionContext(resources={"wi": [[b]]})).to_pydict()
    assert got["e"] == [True, False, True]
    assert got["g"] == [False, True, False]


def test_wide_decimal_least_greatest_and_coalesce():
    from auron_tpu.exprs.ir import Coalesce, ScalarFunc

    a = [pydec.Decimal("1e25"), None, pydec.Decimal("-5")]
    c = [pydec.Decimal("3"), pydec.Decimal("2e30"), pydec.Decimal("-1e21")]
    b = Batch.from_pydict(
        {"a": a, "c": c},
        schema=T.Schema.of(T.Field("a", T.decimal(38, 2)), T.Field("c", T.decimal(38, 2))),
    )
    plan = B.project(B.memory_scan(b.schema, "wl"),
                     [(ScalarFunc("least", (col(0), col(1))), "l"),
                      (ScalarFunc("greatest", (col(0), col(1))), "g"),
                      (Coalesce((col(0), col(1))), "co")])
    op = plan_from_proto(plan)
    got = op.collect(ctx=ExecutionContext(resources={"wl": [[b]]})).to_pydict()
    assert got["l"] == [pydec.Decimal("3"), pydec.Decimal("2e30"), pydec.Decimal("-1e21")]
    assert got["g"] == [pydec.Decimal("1e25"), pydec.Decimal("2e30"), pydec.Decimal("-5")]
    assert got["co"] == [pydec.Decimal("1e25"), pydec.Decimal("2e30"), pydec.Decimal("-5")]


def test_wide_decimal_literal_arithmetic_exact():
    """wide-decimal column (+|-|*|/) literal computes exactly as a
    dictionary transform (the q6 'price > 1.2 * avg' shape)."""
    from auron_tpu.exprs.ir import BinaryOp, lit

    vals = [pydec.Decimal("1e24"), pydec.Decimal("-250.5"), None,
            pydec.Decimal("0.0001")]
    b = Batch.from_pydict(
        {"a": vals}, schema=T.Schema.of(T.Field("a", T.decimal(38, 4)))
    )
    plan = B.project(B.memory_scan(b.schema, "wa"), [
        (BinaryOp("mul", col(0), lit(pydec.Decimal("1.2"), T.decimal(2, 1))), "m"),
        (BinaryOp("add", col(0), lit(pydec.Decimal("100"), T.decimal(3, 0))), "p"),
        (BinaryOp("div", col(0), lit(pydec.Decimal("4"), T.decimal(1, 0))), "d"),
    ])
    op = plan_from_proto(plan)
    got = op.collect(ctx=ExecutionContext(resources={"wa": [[b]]})).to_arrow().to_pylist()
    rows = {i: r for i, r in enumerate(got)}
    assert rows[0]["m"] == pydec.Decimal("1.2e24")
    assert rows[1]["m"] == pydec.Decimal("-300.6")
    assert rows[2]["m"] is None
    assert rows[0]["p"] == pydec.Decimal("1e24") + 100
    assert rows[1]["p"] == pydec.Decimal("-150.5")
    assert rows[3]["d"] == pydec.Decimal("0.0001") / 4  # HALF_UP at div scale
    # column-pair wide arithmetic is now exact (pair-table path)
    plan2 = B.project(B.memory_scan(b.schema, "wa"),
                      [(BinaryOp("add", col(0), col(0)), "x")])
    op2 = plan_from_proto(plan2)
    got2 = op2.collect(
        ctx=ExecutionContext(resources={"wa": [[b]]})
    ).to_arrow().to_pylist()
    assert got2[0]["x"] == pydec.Decimal("2e24")
    assert got2[1]["x"] == pydec.Decimal("-501.0")
    assert got2[2]["x"] is None


def test_wide_decimal_filter_with_literal_arith():
    """WHERE amount > 1.2 * <wide threshold>: arithmetic + comparison."""
    from auron_tpu.exprs.ir import BinaryOp, lit

    vals = [pydec.Decimal("100"), pydec.Decimal("130"), pydec.Decimal("1e22")]
    b = Batch.from_pydict(
        {"a": vals}, schema=T.Schema.of(T.Field("a", T.decimal(38, 2)))
    )
    pred = BinaryOp("gt", col(0),
                    BinaryOp("mul", lit(pydec.Decimal("1.2"), T.decimal(2, 1)),
                             lit(pydec.Decimal("100"), T.decimal(38, 2))))
    plan = B.filter_(B.memory_scan(b.schema, "wf2"), [pred])
    op = plan_from_proto(plan)
    got = [r["a"] for r in op.collect(
        ctx=ExecutionContext(resources={"wf2": [[b]]})).to_arrow().to_pylist()]
    assert got == [pydec.Decimal("130"), pydec.Decimal("1e22")]


def test_window_wide_decimal_running_sum_and_avg():
    """windowed sum/avg over decimal(38,x): exact limb-based running and
    whole-frame aggregates (previously a loud NotImplementedError)."""
    from auron_tpu.exec.basic import MemoryScanExec
    from auron_tpu.exec.window_exec import WindowExec, WindowFunc
    from auron_tpu.ops.sortkeys import SortSpec

    vals = [pydec.Decimal("1e22"), pydec.Decimal("2.5"), pydec.Decimal("-1e22"),
            pydec.Decimal("7"), None]
    b = Batch.from_pydict(
        {"g": [1, 1, 1, 2, 2], "o": [0, 1, 2, 0, 1], "a": vals},
        schema=T.Schema.of(T.Field("g", T.INT64), T.Field("o", T.INT64),
                           T.Field("a", T.decimal(38, 2))),
    )
    w = WindowExec(
        MemoryScanExec.single([b]), [col(0)], [(col(1), SortSpec())],
        [(WindowFunc("agg", agg="sum", expr=col(2)), "run"),
         (WindowFunc("agg", agg="sum", expr=col(2), frame_whole=True), "tot"),
         (WindowFunc("agg", agg="avg", expr=col(2), frame_whole=True), "av")],
    )
    got = w.collect().to_arrow().to_pylist()
    got = sorted(got, key=lambda r: (r["g"], r["o"]))
    assert got[0]["run"] == pydec.Decimal("1e22")
    assert got[1]["run"] == pydec.Decimal("1e22") + pydec.Decimal("2.5")
    assert got[2]["run"] == pydec.Decimal("2.5")
    assert all(got[i]["tot"] == pydec.Decimal("2.5") for i in range(3))
    assert got[3]["tot"] == pydec.Decimal("7") and got[4]["tot"] == pydec.Decimal("7")
    # avg over group 2: 7 / 1 (null skipped), group 1: 2.5/3
    with pydec.localcontext() as hp:
        hp.prec = 100
        want_av = (pydec.Decimal("2.5") / 3).quantize(
            pydec.Decimal(1).scaleb(-6), rounding=pydec.ROUND_HALF_UP
        )
    assert got[0]["av"] == want_av
    assert got[3]["av"] == pydec.Decimal("7")


def test_wide_decimal_column_pair_arith_pipeline(tmp_path):
    """col x col wide arithmetic through scan -> join -> agg -> window
    (VERDICT r2 #9): price * qty over two decimal(38,x) columns, grouped
    sums, then a running windowed sum — all EXACT vs python Decimals."""
    from auron_tpu.exprs.ir import BinaryOp
    from auron_tpu.ops.sortkeys import SortSpec

    rng = np.random.default_rng(5)
    n = 300
    price = _dec38(rng, n, scale=4)
    qty = [pydec.Decimal(int(rng.integers(1, 50))).scaleb(-1) for _ in range(n)]
    fk = rng.integers(0, 8, n).astype(np.int64).tolist()
    schema = T.Schema.of(
        T.Field("fk", T.INT64),
        T.Field("price", T.decimal(38, 4)),
        T.Field("qty", T.decimal(20, 1)),
    )
    path = str(tmp_path / "pairs.parquet")
    pq.write_table(
        pa.table({
            "fk": pa.array(fk, pa.int64()),
            "price": pa.array(price, pa.decimal128(38, 4)),
            "qty": pa.array(qty, pa.decimal128(20, 1)),
        }),
        path, row_group_size=64,
    )
    dim = {"dk": np.arange(8, dtype=np.int64).tolist(),
           "grp": (np.arange(8) % 2).astype(np.int64).tolist()}
    dim_b = Batch.from_pydict(dim, schema=DIM_SCHEMA)

    scan = B.parquet_scan(schema, [path])
    j = B.hash_join(scan, B.memory_scan(DIM_SCHEMA, "wp_dim"),
                    [col(0)], [col(0)], "inner", build_side="right")
    ext = B.project(j, [(col(4), "grp"),
                        (BinaryOp("mul", col(1), col(2)), "ext")])
    aggs = [("sum", col(1), "s"), ("count", col(1), "c")]
    partial = B.hash_agg(ext, [(col(0), "grp")], aggs, "partial")
    final = B.hash_agg(partial, [(col(0), "grp")], aggs, "final")
    w = B.window(final, [], [(col(0), SortSpec())],
                 [("agg", "sum", col(1), 1, False, "run")])

    op = plan_from_proto(w)
    ctx = ExecutionContext(resources={"wp_dim": [[dim_b]]})
    got = op.collect(ctx=ctx).to_arrow().to_pylist()
    got = {r["grp"]: r for r in got}

    # exact oracle: Spark result type of decimal(38,4)*decimal(20,1) is
    # decimal(38, 5) after bounding; mirror the engine's declared type
    from auron_tpu.exprs import ir as _ir

    out_t = _ir.arith_result_type("mul", T.decimal(38, 4), T.decimal(20, 1))
    q = pydec.Decimal(1).scaleb(-out_t.scale)
    bound = pydec.Decimal(10) ** (out_t.precision - out_t.scale)
    grp_of = dict(zip(dim["dk"], dim["grp"]))
    want: dict = {}
    with pydec.localcontext() as hp:
        hp.prec = 100
        for k, p, qv in zip(fk, price, qty):
            v = (p * qv).quantize(q, rounding=pydec.ROUND_HALF_UP)
            g = grp_of[k]
            s, c = want.get(g, (pydec.Decimal(0), 0))
            if abs(v) >= bound:
                want[g] = (s, c)  # overflowed product -> NULL, not summed
            else:
                want[g] = (s + v, c + 1)
    run = pydec.Decimal(0)
    for g in sorted(want):
        s, c = want[g]
        r = got[g]
        assert r["c"] == c, (g, r["c"], c)
        assert pydec.Decimal(str(r["s"])) == s, (g, r["s"], s)
        run += s
        assert pydec.Decimal(str(r["run"])) == run, (g, r["run"], run)


def test_wide_decimal_pair_div_mod_and_extreme_scales():
    """wide / wide and wide % wide column pairs, plus the decimal(38,0) vs
    decimal(38,38) comparison that overflowed the fixed word budget
    (ADVICE r2 #3)."""
    from auron_tpu.exprs.ir import BinaryOp

    a = [pydec.Decimal("1e25"), pydec.Decimal("-7.5"), pydec.Decimal("100"), None]
    bvals = [pydec.Decimal("3"), pydec.Decimal("2"), pydec.Decimal("0"),
             pydec.Decimal("4")]
    b = Batch.from_pydict(
        {"a": a, "b": bvals},
        schema=T.Schema.of(T.Field("a", T.decimal(38, 4)),
                           T.Field("b", T.decimal(20, 4))),
    )
    plan = B.project(B.memory_scan(b.schema, "wdm"), [
        (BinaryOp("div", col(0), col(1)), "d"),
        (BinaryOp("mod", col(0), col(1)), "m"),
    ])
    op = plan_from_proto(plan)
    got = op.collect(ctx=ExecutionContext(resources={"wdm": [[b]]})).to_arrow().to_pylist()
    from auron_tpu.exprs import ir as _ir

    dt = _ir.arith_result_type("div", T.decimal(38, 4), T.decimal(20, 4))
    qd = pydec.Decimal(1).scaleb(-dt.scale)
    with pydec.localcontext() as hp:
        hp.prec = 100
        assert got[0]["d"] == (a[0] / bvals[0]).quantize(qd, rounding=pydec.ROUND_HALF_UP)
        assert got[1]["d"] == pydec.Decimal("-3.75")
    assert got[2]["d"] is None  # div by zero -> NULL
    assert got[3]["d"] is None  # NULL operand
    assert got[1]["m"] == pydec.Decimal("-1.5")  # sign of the dividend
    assert got[2]["m"] is None

    # extreme scale-spread comparison no longer overflows
    wide0 = Batch.from_pydict(
        {"x": [pydec.Decimal(10) ** 37, pydec.Decimal(1)],
         "y": [pydec.Decimal("0." + "9" * 38), pydec.Decimal("0.5")]},
        schema=T.Schema.of(T.Field("x", T.decimal(38, 0)),
                           T.Field("y", T.decimal(38, 38))),
    )
    cmp_plan = B.project(B.memory_scan(wide0.schema, "wcmp"), [
        (BinaryOp("gt", col(0), col(1)), "g"),
    ])
    op2 = plan_from_proto(cmp_plan)
    got2 = op2.collect(ctx=ExecutionContext(resources={"wcmp": [[wide0]]})).to_arrow().to_pylist()
    assert got2[0]["g"] is True and got2[1]["g"] is True

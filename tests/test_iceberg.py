"""Real-metadata Iceberg resolution: table dir -> descriptor -> native scan.

The table on disk is built to the PUBLIC Iceberg spec shapes
(metadata/v*.metadata.json, Avro manifest list, Avro manifests over
parquet data files) using utils/avro.py's writer — the same
both-directions approach as the kafka mini-broker. The resolver must
walk snapshot -> manifest list -> manifests -> data files, map partition
values through the spec, and the existing provider must prune + scan.
"""

import json
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from auron_tpu.convert.iceberg import resolve_iceberg_scan
from auron_tpu.utils import avro


MANIFEST_LIST_SCHEMA = {
    "type": "record", "name": "manifest_file",
    "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "partition_spec_id", "type": "int"},
    ],
}

MANIFEST_SCHEMA = {
    "type": "record", "name": "manifest_entry",
    "fields": [
        {"name": "status", "type": "int"},
        {"name": "data_file", "type": {
            "type": "record", "name": "data_file",
            "fields": [
                {"name": "content", "type": "int"},
                {"name": "file_path", "type": "string"},
                {"name": "file_format", "type": "string"},
                {"name": "partition", "type": {
                    "type": "record", "name": "r102",
                    "fields": [{"name": "year", "type": ["null", "long"]}],
                }},
                {"name": "record_count", "type": "long"},
            ],
        }},
    ],
}


def _build_table(root, codec="deflate"):
    """Partitioned iceberg-shaped table: year=2023 and year=2024 files,
    plus one DELETED entry and one delete-content file (both skipped)."""
    data_dir = os.path.join(root, "data")
    meta_dir = os.path.join(root, "metadata")
    os.makedirs(data_dir)
    os.makedirs(meta_dir)
    frames = {}
    rng = np.random.default_rng(4)
    for year in (2023, 2024):
        df = pd.DataFrame({
            "id": rng.integers(0, 1000, 500).astype(np.int64),
            "amount": rng.standard_normal(500),
            "year": np.full(500, year, dtype=np.int64),
        })
        path = os.path.join(data_dir, f"year={year}", "part-0.parquet")
        os.makedirs(os.path.dirname(path))
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False), path)
        frames[year] = df

    def entry(status, content, path, year, count):
        return {"status": status, "data_file": {
            "content": content, "file_path": path, "file_format": "PARQUET",
            "partition": {"year": year}, "record_count": count}}

    m1 = os.path.join(meta_dir, "m1.avro")
    avro.write_container(m1, MANIFEST_SCHEMA, [
        entry(1, 0, os.path.join(data_dir, "year=2023", "part-0.parquet"), 2023, 500),
        entry(2, 0, os.path.join(data_dir, "gone.parquet"), 2023, 10),  # DELETED
    ], codec=codec)
    m2 = os.path.join(meta_dir, "m2.avro")
    avro.write_container(m2, MANIFEST_SCHEMA, [
        entry(1, 0, os.path.join(data_dir, "year=2024", "part-0.parquet"), 2024, 500),
        entry(1, 1, os.path.join(data_dir, "del.parquet"), 2024, 5),  # delete file
    ], codec=codec)
    mlist = os.path.join(meta_dir, "snap-77.avro")
    avro.write_container(mlist, MANIFEST_LIST_SCHEMA, [
        {"manifest_path": m1, "manifest_length": os.path.getsize(m1),
         "partition_spec_id": 0},
        {"manifest_path": m2, "manifest_length": os.path.getsize(m2),
         "partition_spec_id": 0},
    ], codec=codec)

    metadata = {
        "format-version": 2,
        "table-uuid": "0000-test",
        "location": root,
        "current-schema-id": 0,
        "schemas": [{"schema-id": 0, "type": "struct", "fields": [
            {"id": 1, "name": "id", "required": True, "type": "long"},
            {"id": 2, "name": "amount", "required": False, "type": "double"},
            {"id": 3, "name": "year", "required": True, "type": "long"},
        ]}],
        "partition-specs": [{"spec-id": 0, "fields": [
            {"name": "year", "transform": "identity",
             "source-id": 3, "field-id": 1000},
        ]}],
        "current-snapshot-id": 77,
        "snapshots": [{"snapshot-id": 77, "manifest-list": mlist}],
    }
    with open(os.path.join(meta_dir, "v3.metadata.json"), "w") as f:
        json.dump(metadata, f)
    with open(os.path.join(meta_dir, "version-hint.text"), "w") as f:
        f.write("3")
    return frames


def test_avro_codec_roundtrip(tmp_path):
    schema = {"type": "record", "name": "r", "fields": [
        {"name": "s", "type": "string"},
        {"name": "n", "type": ["null", "long"]},
        {"name": "xs", "type": {"type": "array", "items": "int"}},
        {"name": "m", "type": {"type": "map", "values": "double"}},
        {"name": "e", "type": {"type": "enum", "name": "E",
                               "symbols": ["A", "B"]}},
        {"name": "fx", "type": {"type": "fixed", "name": "F", "size": 3}},
    ]}
    records = [
        {"s": "hello", "n": None, "xs": [1, -2, 3], "m": {"a": 1.5}, "e": "B",
         "fx": b"abc"},
        {"s": "", "n": -(2**40), "xs": [], "m": {}, "e": "A", "fx": b"\x00\x01\x02"},
    ]
    for codec in ("null", "deflate"):
        p = str(tmp_path / f"t_{codec}.avro")
        avro.write_container(p, schema, records, codec=codec)
        got_schema, got = avro.read_container(p)
        assert got == records
        assert got_schema["name"] == "r"


def test_resolve_real_metadata_and_scan(tmp_path):
    frames = _build_table(str(tmp_path))
    desc = resolve_iceberg_scan(str(tmp_path))
    assert desc["op"] == "IcebergScanExec"
    assert [s[0] for s in desc["schema"]] == ["id", "amount", "year"]
    files = desc["args"]["files"]
    # deleted entry and delete-content file are gone
    assert sorted(f["partition"]["year"] for f in files) == [2023, 2024]
    assert all(f["record_count"] == 500 for f in files)

    # descriptor -> conversion service -> native scan with partition pruning
    from auron_tpu.bridge import api
    from auron_tpu.convert.service import convert_host_plan_json

    host = dict(desc)
    host["args"] = dict(desc["args"])
    host["args"]["filters"] = [
        {"kind": "call", "name": "equalto", "children": [
            {"kind": "attr", "index": 2, "name": "year"},
            {"kind": "lit", "type": "long", "value": 2024}]},
    ]
    host["children"] = []
    resp = json.loads(convert_host_plan_json(json.dumps(host)))
    assert resp["converted"] is True, resp.get("error")

    import base64

    from auron_tpu.proto import plan_pb2 as pb

    node = pb.PhysicalPlanNode()
    node.ParseFromString(base64.b64decode(resp["root"]["plan_b64"]))
    h = api.call_native(pb.TaskDefinition(plan=node).SerializeToString())
    got = []
    while (rb := api.next_batch(h)) is not None:
        got.append(rb.to_pandas())
    api.finalize_native(h)
    out = pd.concat(got).reset_index(drop=True)
    want = frames[2024][frames[2024].year == 2024].reset_index(drop=True)
    assert len(out) == len(want)
    assert out["amount"].sum() == pytest.approx(want["amount"].sum())
    assert (out["year"] == 2024).all()


def test_snapshot_time_travel(tmp_path):
    _build_table(str(tmp_path))
    # unknown snapshot -> empty scan (no files), not an error
    desc = resolve_iceberg_scan(str(tmp_path), snapshot_id=12345)
    assert desc["args"]["files"] == []


def test_catalog_style_metadata_names(tmp_path):
    frames = _build_table(str(tmp_path))
    meta = str(tmp_path / "metadata")
    os.remove(os.path.join(meta, "version-hint.text"))
    # catalog naming: 00001-uuid < 00004-uuid must win over listdir order
    os.rename(os.path.join(meta, "v3.metadata.json"),
              os.path.join(meta, "00004-aaaa.metadata.json"))
    with open(os.path.join(meta, "00001-zzzz.metadata.json"), "w") as f:
        json.dump({"format-version": 2, "current-schema-id": 0,
                   "schemas": [{"schema-id": 0, "fields": []}],
                   "snapshots": [], "current-snapshot-id": None}, f)
    desc = resolve_iceberg_scan(str(tmp_path))
    assert len(desc["args"]["files"]) == 2  # resolved 00004, not 00001


def test_nested_column_degrades_not_raises(tmp_path):
    _build_table(str(tmp_path))
    meta_path = os.path.join(str(tmp_path), "metadata", "v3.metadata.json")
    with open(meta_path) as f:
        meta = json.load(f)
    meta["schemas"][0]["fields"].append({
        "id": 9, "name": "nested", "required": False,
        "type": {"type": "struct", "fields": []}})
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    desc = resolve_iceberg_scan(str(tmp_path))  # must not raise
    assert desc["schema"][-1][0] == "nested"
    # the unparseable type tag degrades the NODE engine-side with a reason
    from auron_tpu.convert.hostplan import HostNode

    node = HostNode.from_json({"op": "IcebergScanExec",
                               "schema": desc["schema"], "args": desc["args"],
                               "children": []})
    assert node.schema_error is not None


def test_non_parquet_data_file_rejected(tmp_path):
    _build_table(str(tmp_path))
    meta_dir = os.path.join(str(tmp_path), "metadata")
    avro.write_container(os.path.join(meta_dir, "m1.avro"), MANIFEST_SCHEMA, [
        {"status": 1, "data_file": {
            "content": 0, "file_path": "/x/f.orc", "file_format": "ORC",
            "partition": {"year": 2023}, "record_count": 1}},
    ])
    with pytest.raises(ValueError, match="parquet only"):
        resolve_iceberg_scan(str(tmp_path))

"""Regression tests for the R11/R12 engine fixes (auronlint v3).

Each test reproduces the failure path the new static rules surfaced and
pins the fixed behavior: no leaked task runtimes, no leaked memory-
manager registrations, no stranded RSS attempts, no wedged consumers.
"""

import threading

import pytest

from auron_tpu import types as T  # noqa: F401 — parity with sibling suites
from auron_tpu.bridge import api
from auron_tpu.columnar import Batch
from auron_tpu.exprs.ir import ScalarFunc, col
from auron_tpu.memory.memmgr import MemManager
from auron_tpu.plan import builders as B


def _task_bytes(plan, **kw):
    return B.task(plan, **kw).SerializeToString()


def _runtimes_snapshot():
    with api._lock:
        return set(api._runtimes)


# ---------------------------------------------------------------------------
# bridge: call_native unwind + native_task context manager
# ---------------------------------------------------------------------------


def test_call_native_unwinds_runtime_on_post_start_failure(monkeypatch):
    """R11 find: a failure AFTER TaskRuntime construction (the lazy HTTP
    service start) previously leaked the runtime — pump thread running,
    handle never published, finalize never reachable."""
    from auron_tpu.utils import httpsvc

    def boom(conf):
        raise RuntimeError("injected post-start failure")

    monkeypatch.setattr(httpsvc, "maybe_start_from_conf", boom)
    b = Batch.from_pydict({"x": [1, 2, 3]})
    api.put_resource("lc_src", [[b]])
    before = _runtimes_snapshot()
    threads_before = threading.active_count()
    try:
        with pytest.raises(RuntimeError, match="injected post-start"):
            api.call_native(_task_bytes(B.memory_scan(b.schema, "lc_src")))
        assert _runtimes_snapshot() == before
        # the pump thread must be joined by the unwinding finalize, not
        # left alive behind an unreachable handle
        for _ in range(100):
            if threading.active_count() <= threads_before:
                break
            import time

            time.sleep(0.02)
        assert threading.active_count() <= threads_before
    finally:
        api.remove_resource("lc_src")


def test_native_task_finalizes_on_failing_drain():
    """The PR-12 leak class, pinned at the helper level: a drain loop
    that raises must still finalize (handle gone, no error masking)."""
    b = Batch.from_pydict({"x": [1, 0]})
    api.put_resource("lc_src2", [[b]])
    plan = B.project(B.memory_scan(b.schema, "lc_src2"),
                     [(ScalarFunc("nope", (col(0),)), "y")])
    before = _runtimes_snapshot()
    try:
        with pytest.raises(RuntimeError, match="failed"):
            with api.native_task(_task_bytes(plan)) as h:
                while api.next_batch(h) is not None:
                    pass
        assert _runtimes_snapshot() == before
    finally:
        api.remove_resource("lc_src2")


def test_native_task_finalizes_on_consumer_error():
    """An error raised by the CONSUMER (not the task) also finalizes."""
    b = Batch.from_pydict({"x": [1, 2]})
    api.put_resource("lc_src3", [[b]])
    before = _runtimes_snapshot()
    try:
        with pytest.raises(ValueError, match="consumer"):
            with api.native_task(
                _task_bytes(B.memory_scan(b.schema, "lc_src3"))
            ) as h:
                api.next_batch(h)
                raise ValueError("consumer bailed")
        assert _runtimes_snapshot() == before
    finally:
        api.remove_resource("lc_src3")


# ---------------------------------------------------------------------------
# agg setup window: no leaked memory-manager registrations
# ---------------------------------------------------------------------------


def test_agg_setup_failure_leaks_no_consumers(monkeypatch):
    """R11 find: ~300 lines of setup ran between mm.register(table) and
    the protecting try — a failure there (here: TransferWindow
    construction, the deferred-counts arm) leaked registered consumers
    in the process-wide manager for the life of the process."""
    from auron_tpu.runtime import transfer

    def boom(depth):
        raise RuntimeError("injected window failure")

    monkeypatch.setattr(transfer, "TransferWindow", boom)
    b = Batch.from_pydict({"k": [1, 2, 1], "v": [1.0, 2.0, 3.0]})
    api.put_resource("lc_agg", [[b]])
    plan = B.hash_agg(
        B.memory_scan(b.schema, "lc_agg"),
        [(col(0), "k")], [("sum", col(1), "s")], "partial",
    )
    mm = MemManager.get()
    with mm._lock:
        consumers_before = list(mm._consumers)
    try:
        with pytest.raises(RuntimeError, match="failed"):
            with api.native_task(_task_bytes(
                plan, conf={"exec.agg.partial.defer": "on"}
            )) as h:
                while api.next_batch(h) is not None:
                    pass
        with mm._lock:
            leaked = [c for c in mm._consumers if c not in consumers_before]
        assert not leaked, [c.name for c in leaked]
    finally:
        api.remove_resource("lc_agg")


# ---------------------------------------------------------------------------
# spill containers: demote failure releases the disk tier
# ---------------------------------------------------------------------------


def test_hostspill_demote_failure_releases_disk_and_keeps_blocks(
    monkeypatch, tmp_path
):
    """R11 find: a failed demotion write leaked the DiskSpill temp file
    and lost the in-RAM blocks' consistency."""
    import pyarrow as pa

    from auron_tpu.memory import memmgr
    from auron_tpu.utils.config import Configuration

    conf = Configuration()
    sp = memmgr.HostSpill(str(tmp_path), conf=conf)
    sp.write_table(pa.table({"x": [1, 2, 3]}))
    released = []

    class FailingDisk:
        def __init__(self, spill_dir=None, *, conf):
            self.path = str(tmp_path / "no-such-dir" / "spill")

        def release(self):
            released.append(True)

    monkeypatch.setattr(memmgr, "DiskSpill", FailingDisk)
    with pytest.raises(OSError):
        sp._demote()
    assert released == [True]
    # blocks stayed resident and readable
    assert not sp.demoted
    tables = list(sp.read_tables())
    assert sum(t.num_rows for t in tables) == 3
    sp.release()


# ---------------------------------------------------------------------------
# pump boundary: context installation failure relays instead of hanging
# ---------------------------------------------------------------------------


def test_pump_context_failure_relays_not_hangs(monkeypatch):
    """R12 find: set_task_context ran BEFORE the pump's try — a failure
    there killed the pump without enqueueing _END, so next_batch blocked
    forever."""
    from auron_tpu.utils import logging as tlog

    def boom(stage, part):
        raise RuntimeError("injected context failure")

    monkeypatch.setattr(tlog, "set_task_context", boom)
    b = Batch.from_pydict({"x": [1]})
    from auron_tpu.runtime.task import TaskRuntime

    rt = TaskRuntime(
        _task_bytes(B.memory_scan(b.schema, "unused")),
        resources={"unused": [[b]]},
    )
    with pytest.raises(RuntimeError, match="failed"):
        # must raise promptly (the relay), not deadlock on an empty queue
        rt.next_batch()


# ---------------------------------------------------------------------------
# RSS: a failing writer attempt aborts its staged blocks
# ---------------------------------------------------------------------------


def test_rss_writer_aborts_attempt_on_failure():
    """R11/R12 find (the named rss_net suspect): a failing RSS map task
    left its uncommitted attempt's pushed blocks staged in the service
    forever (local RAM, or the remote daemon's)."""
    from auron_tpu.exec.shuffle.rss import (
        LocalRssService, RssPartitionWriterClient,
    )

    svc = LocalRssService()
    inner = RssPartitionWriterClient(svc, "s1", 0)

    class FlakyWriter:
        """First push lands (the attempt has staged bytes to leak — the
        assertion below must not pass vacuously); the second fails."""

        def __init__(self):
            self.pushes = 0

        def write(self, partition, block):
            self.pushes += 1
            if self.pushes >= 2:
                raise RuntimeError("injected push failure")
            inner.write(partition, block)

        def abort(self):
            inner.abort()

    writer = FlakyWriter()
    api.put_resource("lc_rss", writer)
    b = Batch.from_pydict({"x": list(range(16))})
    api.put_resource("lc_rss_src", [[b]])
    plan = B.rss_shuffle_writer(
        B.memory_scan(b.schema, "lc_rss_src"),
        B.hash_partitioning([col(0)], 2), "lc_rss",
    )
    try:
        with pytest.raises(RuntimeError, match="failed"):
            with api.native_task(_task_bytes(plan)) as h:
                while api.next_batch(h) is not None:
                    pass
        assert writer.pushes >= 2, "fixture never pushed — vacuous"
        with svc._lock:
            staged = dict(svc._staging)
        assert not staged, "failed attempt left staged blocks in the service"
    finally:
        api.remove_resource("lc_rss")
        api.remove_resource("lc_rss_src")

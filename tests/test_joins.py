"""Join matrix tests: {SMJ, BHJ-build-left, BHJ-build-right} x 7 join types,
differential against pandas merge (the reference tests the same matrix in
datafusion-ext-plans/src/joins/test.rs)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exec.basic import MemoryScanExec
from auron_tpu.exec.joins import BroadcastHashJoinExec, SortMergeJoinExec
from auron_tpu.exec.joins.core import (
    EXISTENCE, FULL, INNER, LEFT, LEFT_ANTI, LEFT_SEMI, RIGHT,
)
from auron_tpu.exprs.ir import BinaryOp, col, lit


def _mk(df, chunk=None):
    if chunk is None:
        return MemoryScanExec.single(
            [Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))]
        )
    bs = [
        Batch.from_arrow(
            pa.RecordBatch.from_pandas(df.iloc[i : i + chunk], preserve_index=False)
        )
        for i in range(0, len(df), chunk)
    ]
    return MemoryScanExec.single(bs or [Batch.from_arrow(
        pa.RecordBatch.from_pandas(df, preserve_index=False))])


def _join(kind, ldf, rdf, jt, lkeys, rkeys, condition=None, chunk=None):
    left = _mk(ldf, chunk)
    right = _mk(rdf, chunk)
    lk = [col(i) for i in lkeys]
    rk = [col(i) for i in rkeys]
    if kind == "smj":
        op = SortMergeJoinExec(left, right, lk, rk, jt, condition=condition)
    elif kind == "bhj_right":
        op = BroadcastHashJoinExec(left, right, lk, rk, jt, build_side="right",
                                   condition=condition)
    else:
        op = BroadcastHashJoinExec(left, right, lk, rk, jt, build_side="left",
                                   condition=condition)
    return op.collect().to_pandas()


LDF = pd.DataFrame(
    {
        "k": pd.array([1, 2, 2, 3, None, 5], dtype="Int64"),
        "lv": ["a", "b", "c", "d", "e", "f"],
    }
)
RDF = pd.DataFrame(
    {
        "k2": pd.array([2, 2, 3, 4, None], dtype="Int64"),
        "rv": [20.0, 21.0, 30.0, 40.0, 50.0],
    }
)

KINDS = ["smj", "bhj_right", "bhj_left"]


def sql_merge(ldf, rdf, how, lk="k", rk="k2"):
    """pandas merge with SQL NULL semantics (NULL keys never match)."""
    lnn = ldf[ldf[lk].notna()]
    rnn = rdf[rdf[rk].notna()]
    if how == "inner":
        return lnn.merge(rnn, left_on=lk, right_on=rk, how="inner")
    if how == "left":
        return ldf.merge(rnn, left_on=lk, right_on=rk, how="left")
    if how == "right":
        return lnn.merge(rdf, left_on=lk, right_on=rk, how="right")
    if how == "outer":
        left_part = ldf.merge(rnn, left_on=lk, right_on=rk, how="left", indicator=False)
        matched_rkeys = set(lnn[lk].dropna()) & set(rnn[rk].dropna())
        right_unmatched = rdf[~rdf[rk].isin(matched_rkeys) | rdf[rk].isna()]
        pad = pd.DataFrame({c: [None] * len(right_unmatched) for c in ldf.columns})
        pad.index = right_unmatched.index
        right_part = pd.concat([pad, right_unmatched], axis=1)
        return pd.concat([left_part, right_part], ignore_index=True)
    raise ValueError(how)


def _norm(df, cols):
    return (
        df.sort_values(cols, na_position="last")
        .reset_index(drop=True)
        .where(lambda d: d.notna(), None)
    )


@pytest.mark.parametrize("kind", KINDS)
def test_inner(kind):
    got = _join(kind, LDF, RDF, INNER, [0], [0])
    want = sql_merge(LDF, RDF, "inner")
    got = _norm(got, ["k", "lv", "rv"])
    want = _norm(want, ["k", "lv", "rv"])
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


@pytest.mark.parametrize("kind", KINDS)
def test_left(kind):
    got = _join(kind, LDF, RDF, LEFT, [0], [0])
    want = sql_merge(LDF, RDF, "left")
    got = _norm(got, ["k", "lv", "rv"])
    want = _norm(want, ["k", "lv", "rv"])
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


@pytest.mark.parametrize("kind", KINDS)
def test_right(kind):
    got = _join(kind, LDF, RDF, RIGHT, [0], [0])
    want = sql_merge(LDF, RDF, "right")
    got = _norm(got, ["k2", "rv", "lv"])
    want = _norm(want, ["k2", "rv", "lv"])
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def _row_multiset(df, cols):
    from collections import Counter

    rows = []
    for _, r in df[cols].iterrows():
        rows.append(
            tuple(
                None if pd.isna(v) else (float(v) if isinstance(v, (int, float, np.number)) else v)
                for v in r
            )
        )
    return Counter(rows)


@pytest.mark.parametrize("kind", KINDS)
def test_full(kind):
    got = _join(kind, LDF, RDF, FULL, [0], [0])
    want = sql_merge(LDF, RDF, "outer")
    cols = ["k", "lv", "k2", "rv"]
    assert _row_multiset(got, cols) == _row_multiset(want, cols)


@pytest.mark.parametrize("kind", KINDS)
def test_semi_anti_existence(kind):
    got_semi = _join(kind, LDF, RDF, LEFT_SEMI, [0], [0])
    # keys present in right: 2, 3 (null never matches)
    assert sorted(got_semi["lv"].tolist()) == ["b", "c", "d"]
    got_anti = _join(kind, LDF, RDF, LEFT_ANTI, [0], [0])
    assert sorted(got_anti["lv"].tolist()) == ["a", "e", "f"]
    got_ex = _join(kind, LDF, RDF, EXISTENCE, [0], [0])
    ex = dict(zip(got_ex["lv"], got_ex["exists"]))
    assert ex == {"a": False, "b": True, "c": True, "d": True, "e": False, "f": False}


@pytest.mark.parametrize("kind", KINDS)
def test_condition_join(kind):
    # residual predicate: rv > 20 — pairs failing it do not count as matches
    cond = BinaryOp("gt", col(3), lit(20.0))
    got = _join(kind, LDF, RDF, LEFT, [0], [0], condition=cond)
    want_pairs = LDF.merge(RDF, left_on="k", right_on="k2")
    want_pairs = want_pairs[want_pairs.rv > 20]
    matched = set(want_pairs["lv"])
    n_expected = len(want_pairs) + (len(LDF) - len(set(LDF.lv) & matched))
    assert len(got) == n_expected
    # row 'b' (k=2) keeps only the rv=21 pair
    b_rows = got[got.lv == "b"]
    assert b_rows["rv"].dropna().tolist() == [21.0]


@pytest.mark.parametrize("kind", KINDS)
def test_string_keys_multibatch(kind):
    rng = np.random.default_rng(11)
    n, m = 500, 300
    ldf = pd.DataFrame(
        {
            "k": rng.choice(["aa", "bb", "cc", "dd", "ee", "zz"], n),
            "lv": rng.integers(0, 1000, n),
        }
    )
    rdf = pd.DataFrame(
        {
            "k2": rng.choice(["bb", "cc", "dd", "qq"], m),
            "rv": rng.normal(size=m),
        }
    )
    got = _join(kind, ldf, rdf, INNER, [0], [0], chunk=128)
    want = ldf.merge(rdf, left_on="k", right_on="k2", how="inner")
    assert len(got) == len(want)
    gs = got.groupby("k").size().to_dict()
    ws = want.groupby("k").size().to_dict()
    assert gs == ws
    assert got["lv"].sum() == want["lv"].sum()
    assert got["rv"].sum() == pytest.approx(want["rv"].sum())


@pytest.mark.parametrize("kind", ["smj", "bhj_right"])
def test_multi_key_join(kind):
    ldf = pd.DataFrame({"a": [1, 1, 2, 2], "b": ["x", "y", "x", "y"], "lv": [1, 2, 3, 4]})
    rdf = pd.DataFrame({"a2": [1, 2, 2], "b2": ["y", "x", "q"], "rv": [10, 20, 30]})
    got = _join(kind, ldf, rdf, INNER, [0, 1], [0, 1])
    want = ldf.merge(rdf, left_on=["a", "b"], right_on=["a2", "b2"])
    got = _norm(got, ["a", "b"])
    want = _norm(want, ["a", "b"])
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


@pytest.mark.parametrize("kind", KINDS)
def test_empty_sides(kind):
    empty = LDF.iloc[0:0]
    got = _join(kind, empty, RDF, LEFT, [0], [0])
    assert len(got) == 0
    got2 = _join(kind, LDF, RDF.iloc[0:0], LEFT, [0], [0])
    assert len(got2) == len(LDF)
    assert got2["rv"].isna().all()
    got3 = _join(kind, LDF, RDF.iloc[0:0], INNER, [0], [0])
    assert len(got3) == 0


def test_cached_build_lock_evicted_with_resource():
    """Executor-shared broadcast builds mint one lock per cached_build_id;
    the host's resource-removal path must evict the lock with the resource
    or a long-lived executor leaks one Lock per broadcast (ADVICE r3)."""
    from auron_tpu.bridge import api
    from auron_tpu.exec.joins import bhj

    ldf = pd.DataFrame({"k": [1, 2, 3], "lv": [10, 20, 30]})
    rdf = pd.DataFrame({"k2": [1, 2], "rv": [5, 6]})
    left, right = _mk(ldf), _mk(rdf)
    op = BroadcastHashJoinExec(
        left, right, [col(0)], [col(0)], INNER,
        build_side="right", cached_build_id="bcast_evict_test",
    )
    from auron_tpu.exec.base import ExecutionContext

    shared = {}
    ctx = ExecutionContext(shared=shared)
    got = op.collect(0, ctx).to_pandas()
    assert len(got) == 2
    assert "bcast_evict_test" in shared  # build cached executor-wide
    assert "bcast_evict_test" in bhj._key_locks
    # host destroys the broadcast -> resource AND lock must go
    api.put_resource("bcast_evict_test", shared["bcast_evict_test"])
    api.remove_resource("bcast_evict_test")
    assert "bcast_evict_test" not in bhj._key_locks


def test_fused_chain_fallback_memo_cleared_on_completion():
    """On non-unique-build fallback the chain stashes prepared builds in
    ctx.resources; the chain top must clear leftovers when its per-operator
    execution ends so unreached entries can't pin batches (ADVICE r3)."""
    from auron_tpu.exec.base import ExecutionContext

    # duplicate build keys force the fused-chain fallback
    ldf = pd.DataFrame({"k": [1, 1, 2, 3], "lv": [1, 2, 3, 4]})
    mdf = pd.DataFrame({"k2": [1, 1, 2], "mv": [10, 11, 20]})  # dup key 1
    rdf = pd.DataFrame({"k3": [1, 2], "rv": [100, 200]})
    j1 = BroadcastHashJoinExec(
        _mk(ldf), _mk(mdf), [col(0)], [col(0)], INNER, build_side="right"
    )
    top = BroadcastHashJoinExec(
        j1, _mk(rdf), [col(0)], [col(0)], INNER, build_side="right"
    )
    ctx = ExecutionContext()
    got = top.collect(0, ctx).to_pandas()
    want = ldf.merge(mdf, left_on="k", right_on="k2").merge(
        rdf, left_on="k", right_on="k3"
    )
    assert len(got) == len(want)
    leftovers = [
        k for k in ctx.resources
        if isinstance(k, tuple) and k and str(k[0]).startswith("fusion_build_memo")
    ]
    assert leftovers == [], leftovers


def test_condition_with_case_remaps_columns():
    """Residual conditions evaluate over a reduced schema of only their
    referenced columns; Columns nested inside Case.branches (tuple of
    tuples) must be remapped too (regression: they kept combined-schema
    indices and read the wrong column or crashed)."""
    import jax.numpy as jnp

    from auron_tpu import types as T
    from auron_tpu.columnar import Batch
    from auron_tpu.exec.basic import MemoryScanExec
    from auron_tpu.exec.joins.bhj import BroadcastHashJoinExec
    from auron_tpu.exprs import ir

    left = Batch.from_pydict({"k": [1, 1, 2], "a": [10, 20, 30]})
    right = Batch.from_pydict({"k": [1, 1, 2], "b": [5, 25, 40]})
    # CASE WHEN a > 15 THEN b < a ELSE b > a END  (refs a=col1, b=col3)
    cond = ir.Case(
        branches=(
            (ir.BinaryOp("gt", ir.Column(1), ir.Literal(15, T.INT64)),
             ir.BinaryOp("lt", ir.Column(3), ir.Column(1))),
        ),
        orelse=ir.BinaryOp("gt", ir.Column(3), ir.Column(1)),
    )
    j = BroadcastHashJoinExec(
        MemoryScanExec.single([left]), MemoryScanExec.single([right]),
        [ir.col(0)], [ir.col(0)], "inner", condition=cond,
        build_side="right",
    )
    out = j.collect().to_pandas().sort_values(["a", "b"]).reset_index(drop=True)
    rows = set(zip(out["a"], out["b"]))
    # a=10 (else: b>a): (10,25); a=20 (then: b<a): (20,5); a=30: b=40 not <30
    assert rows == {(10, 25), (20, 5)}


def test_unique_build_residual_condition_noncompact_emit():
    """Unique build + residual condition: needs_all_pairs forces the
    NON-compacted unique emit path with proj = full output (regression:
    the _unique_probe_cfg refactor once dropped the local full_n this
    branch sizes its projection with)."""
    import pandas as pd

    from auron_tpu.exec.basic import MemoryScanExec
    from auron_tpu.exec.joins import BroadcastHashJoinExec
    from auron_tpu.exprs.ir import BinaryOp, Column, Literal

    left = pd.DataFrame({"k": np.arange(8, dtype=np.int64),
                         "lv": np.arange(8, dtype=np.int64) * 10})
    right = pd.DataFrame({"rk": np.arange(8, dtype=np.int64),
                          "rv": np.arange(8, dtype=np.int64) * 5})
    j = BroadcastHashJoinExec(
        MemoryScanExec.single([Batch.from_pandas(left)]),
        MemoryScanExec.single([Batch.from_pandas(right)]),
        [Column(0, "k")], [Column(0, "rk")], "inner", build_side="right",
        condition=BinaryOp("gt", Column(3, "rv"), Literal(14, T.INT64)),
    )
    got = j.collect().to_pandas().sort_values("k").reset_index(drop=True)
    want = left.merge(right, left_on="k", right_on="rk")
    want = want[want.rv > 14].sort_values("k").reset_index(drop=True)
    assert got["k"].tolist() == want["k"].tolist()
    assert got["rv"].tolist() == want["rv"].tolist()


def test_compact_join_output_knob_tri_resolution(monkeypatch):
    """Tri-state semantics of spark.auron.join.compact.output pinned
    after the resolve_tri rewrite: on/off force, auto follows the
    backend (tests run on the CPU backend, where syncs are cheap and
    auto resolves to compaction ON)."""
    from auron_tpu.exec import base as exec_base
    from auron_tpu.exec.joins.driver import _compact_join_output_enabled
    from auron_tpu.utils.config import (
        JOIN_COMPACT_OUTPUT, Configuration, conf_scope,
    )

    # drop the last test's lingering operator context so the gate reads
    # the scoped conf, not a stale task's
    monkeypatch.delattr(exec_base._ctx_local, "ctx", raising=False)
    for mode, want in (("on", True), ("off", False), ("auto", True)):
        with conf_scope(Configuration({JOIN_COMPACT_OUTPUT.key: mode})):
            assert _compact_join_output_enabled() is want, mode

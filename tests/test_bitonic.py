"""Bitonic cluster-sort network vs the stable lax.sort it replaces.

Both implementations (jitted jnp network, Pallas kernel via interpreter on
CPU) must be bit-identical to ``lax.sort(operands, num_keys=n-1)`` with an
iota payload — including stability, dead-row clustering, and non-power-of-2
capacities (padding must never leak into the real slots).
"""

import numpy as np
import jax.numpy as jnp
import pytest
from jax import lax

from auron_tpu.ops import bitonic

_pallas_state: list = []


def _skip_unless_pallas(impl):
    """Interpret-mode Pallas needs a jaxlib with TPU lowering registries;
    this CPU-only build raises NotImplementedError (same skip as
    test_native.py's kernel tests). Probe once."""
    if impl != "pallas":
        return
    if not _pallas_state:
        probe = (
            jnp.zeros(8, jnp.uint64),
            jnp.arange(8, dtype=jnp.int32),
        )
        try:
            bitonic.bitonic_sort(probe, impl="pallas", interpret=True)
            _pallas_state.append(None)
        except NotImplementedError as e:
            _pallas_state.append(str(e))
    if _pallas_state[0] is not None:
        pytest.skip(f"pallas unavailable on this jaxlib build: {_pallas_state[0]}")


def _operands(cap, n_words, n_distinct, seed, dead_frac=0.0):
    rng = np.random.default_rng(seed)
    sel = rng.random(cap) >= dead_frac
    dead_first = jnp.where(jnp.asarray(sel), jnp.uint64(0), jnp.uint64(1))
    words = [
        jnp.asarray(rng.integers(0, n_distinct, cap).astype(np.uint64))
        for _ in range(n_words)
    ]
    if n_words:
        # exercise high-plane bits too
        words[0] = words[0] | (words[0] << jnp.uint64(33))
    iota = jnp.arange(cap, dtype=jnp.int32)
    return (dead_first, *words, iota)


@pytest.mark.parametrize("impl", ["jnp", "pallas"])
@pytest.mark.parametrize(
    "cap,n_words,n_distinct,dead_frac",
    [
        (1024, 1, 37, 0.0),
        (1024, 1, 5, 0.3),
        (2048, 2, 400, 0.1),
        (1500, 2, 64, 0.2),  # non-power-of-2 capacity
        (4096, 3, 11, 0.5),  # many duplicates -> stability visible
        (1024, 1, 1, 0.0),  # single group
    ],
)
def test_matches_stable_lax_sort(impl, cap, n_words, n_distinct, dead_frac):
    _skip_unless_pallas(impl)
    ops = _operands(cap, n_words, n_distinct, seed=cap + n_words, dead_frac=dead_frac)
    want = lax.sort(ops, num_keys=len(ops) - 1)
    got = bitonic.bitonic_sort(ops, impl=impl, interpret=True)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(g))


@pytest.mark.parametrize("impl", ["jnp", "pallas"])
def test_signed_operands_match_lax(impl):
    """int64/int32 key operands compare signed (sign-biased planes)."""
    _skip_unless_pallas(impl)
    rng = np.random.default_rng(21)
    cap = 1024
    k = jnp.asarray(rng.integers(-(2**62), 2**62, cap).astype(np.int64))
    v = jnp.asarray(rng.integers(-(2**30), 2**30, cap).astype(np.int32))
    iota = jnp.arange(cap, dtype=jnp.int32)
    ops = (k, v, iota)
    want = lax.sort(ops, num_keys=2)
    got = bitonic.bitonic_sort(ops, impl=impl, interpret=True)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(g))


@pytest.mark.parametrize("impl", ["jnp", "pallas"])
def test_narrow_planes_match(impl):
    """narrow=True operands (statically-zero hi words) sort identically."""
    _skip_unless_pallas(impl)
    ops = _operands(2048, 2, 100, seed=9, dead_frac=0.25)
    # dead key (0/1) and second word masked to 32 bits -> narrowable
    ops = (ops[0], ops[1], ops[2] & jnp.uint64(0xFFFFFFFF), ops[3])
    want = lax.sort(ops, num_keys=len(ops) - 1)
    got = bitonic.bitonic_sort(
        ops, impl=impl, interpret=True, narrow=(True, False, True, False)
    )
    for w, g in zip(want, got):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(g))


@pytest.mark.parametrize("impl", ["jnp", "pallas"])
def test_segment_by_keys_device_impl(impl):
    _skip_unless_pallas(impl)
    from auron_tpu.exprs.eval import ColumnVal
    from auron_tpu import types as T
    from auron_tpu.ops import segments as S

    rng = np.random.default_rng(7)
    cap = 2048
    vals = jnp.asarray(rng.integers(-50, 50, cap).astype(np.int64))
    validity = jnp.asarray(rng.random(cap) > 0.1)
    sel = jnp.asarray(rng.random(cap) > 0.2)
    words = S.key_words([ColumnVal(vals, validity, T.INT64, None)])

    ref = S.segment_by_keys(words, sel, host_sort=False, device_impl="lax")
    got = S.segment_by_keys(words, sel, host_sort=False, device_impl=impl)
    for name in ("order", "seg_ids", "boundary", "group_of_slot", "sel_sorted"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, name)), np.asarray(getattr(got, name)), err_msg=name
        )
    assert int(ref.num_groups) == int(got.num_groups)


@pytest.mark.parametrize("impl", ["jnp", "pallas"])
def test_agg_end_to_end_with_bitonic(impl):
    """A grouped aggregation with the bitonic sort forced stays exact."""
    _skip_unless_pallas(impl)
    import pandas as pd
    import pyarrow as pa

    from auron_tpu.columnar import Batch
    from auron_tpu.exec.agg_exec import AggExpr, HashAggExec
    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.exec.basic import MemoryScanExec
    from auron_tpu.exprs.ir import col
    from auron_tpu.utils.config import (
        DEVICE_SORT_IMPL,
        HOST_SORT_MODE,
        Configuration,
        conf_scope,
    )

    rng = np.random.default_rng(11)
    df = pd.DataFrame({
        "g": rng.integers(0, 40, 6000).astype(np.int64),
        "v": rng.integers(-100, 100, 6000).astype(np.int64),
    })
    scan = MemoryScanExec.single([
        Batch.from_arrow(pa.RecordBatch.from_pandas(
            df.iloc[i : i + 1500], preserve_index=False))
        for i in range(0, len(df), 1500)
    ])
    partial = HashAggExec(
        scan, [(col(0), "g")],
        [(AggExpr("sum", col(1)), "s"), (AggExpr("count", col(1)), "c")],
        "partial",
    )
    agg = HashAggExec(
        partial, [(col(0), "g")],
        [(AggExpr("sum", col(1)), "s"), (AggExpr("count", col(2)), "c")],
        "final",
    )
    # host sort owns CPU by default — force it off so the device impl runs
    conf = Configuration().set(HOST_SORT_MODE, "off").set(DEVICE_SORT_IMPL, impl)
    with conf_scope(conf):
        got = (
            agg.collect(0, ExecutionContext()).to_pandas()
            .sort_values("g").reset_index(drop=True)
        )
    want = (
        df.groupby("g").agg(s=("v", "sum"), c=("v", "count")).reset_index()
        .sort_values("g").reset_index(drop=True)
    )
    import pandas.testing as pdt

    pdt.assert_frame_equal(got, want, check_dtype=False)


@pytest.mark.parametrize("impl", ["jnp", "pallas"])
def test_order_by_and_window_with_bitonic(impl):
    """The ORDER BY and window paths produce identical results with the
    network forced (exec/sort_exec.py + exec/window_exec.py wiring)."""
    _skip_unless_pallas(impl)
    import pandas as pd
    import pyarrow as pa

    from auron_tpu.columnar import Batch
    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.exec.basic import MemoryScanExec
    from auron_tpu.exec.sort_exec import SortExec
    from auron_tpu.exec.window_exec import WindowExec, WindowFunc
    from auron_tpu.exprs.ir import col
    from auron_tpu.ops.sortkeys import SortSpec
    from auron_tpu.utils.config import (
        DEVICE_SORT_IMPL,
        HOST_SORT_MODE,
        Configuration,
        conf_scope,
    )

    rng = np.random.default_rng(31)
    df = pd.DataFrame({
        "g": rng.integers(0, 9, 4000).astype(np.int64),
        "v": rng.standard_normal(4000),
    })
    df.loc[df.index % 11 == 0, "v"] = np.nan
    scan = MemoryScanExec.single([Batch.from_arrow(
        pa.RecordBatch.from_pandas(df.iloc[i:i+1000], preserve_index=False))
        for i in range(0, len(df), 1000)])

    conf = Configuration().set(HOST_SORT_MODE, "off").set(DEVICE_SORT_IMPL, impl)
    ref_conf = Configuration().set(HOST_SORT_MODE, "off").set(DEVICE_SORT_IMPL, "lax")

    def run_sort(c):
        op = SortExec(scan, [col(1), col(0)],
                      [SortSpec(asc=False, nulls_first=False), SortSpec()])
        with conf_scope(c):
            return op.collect(0, ExecutionContext(conf=c)).to_pandas()

    pd.testing.assert_frame_equal(run_sort(conf), run_sort(ref_conf))

    def run_window(c):
        op = WindowExec(scan, [col(0)], [(col(1), SortSpec())],
                        [(WindowFunc("row_number"), "rn")])
        with conf_scope(c):
            out = op.collect(0, ExecutionContext(conf=c)).to_pandas()
        return out.sort_values(["g", "rn"]).reset_index(drop=True)

    pd.testing.assert_frame_equal(run_window(conf), run_window(ref_conf))


def test_sort_impl_for_gates():
    from auron_tpu.utils.config import DEVICE_SORT_IMPL, Configuration, conf_scope

    # explicit override wins regardless of backend
    with conf_scope(Configuration().set(DEVICE_SORT_IMPL, "jnp")):
        assert bitonic.sort_impl_for(2, 1 << 16) == "jnp"
    # auto on the CPU test backend -> lax (hostsort owns CPU)
    with conf_scope(Configuration().set(DEVICE_SORT_IMPL, "auto")):
        assert bitonic.sort_impl_for(2, 1 << 16) == "lax"


# ---------------------------------------------------------------------------
# tiled multi-block path (VERDICT r4 #4)
# ---------------------------------------------------------------------------


def test_tiled_sort_matches_lax_sort_multiblock():
    """Force multi-block tiling (shrunken VMEM gate) and pin the tiled
    network bit-exactly to the stable lax.sort across block-count regimes."""
    from auron_tpu.ops import bitonic as BT

    rng = np.random.default_rng(17)
    old_gate = BT._VMEM_GATE_BYTES
    BT._VMEM_GATE_BYTES = 64 << 10  # tiny: every case below tiles
    try:
        for n in (3000, 8192, 20000, 65536):
            w0 = jnp.asarray(rng.integers(0, 1 << 60, n, dtype=np.uint64))
            w1 = jnp.asarray(rng.integers(0, 50, n, dtype=np.uint64))
            iota = jnp.arange(n, dtype=jnp.int32)
            ops = (w1, w0, iota)  # duplicate-heavy leading key
            want = lax.sort(ops, num_keys=2)
            got = BT.bitonic_sort(ops, impl="jnp")
            for g, w in zip(got, want):
                np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    finally:
        BT._VMEM_GATE_BYTES = old_gate


def test_tiled_sort_block_boundary_values():
    """Adversarial block patterns: presorted, reverse-sorted, constant, and
    alternating runs must all merge-split to global order."""
    from auron_tpu.ops import bitonic as BT

    old_gate = BT._VMEM_GATE_BYTES
    BT._VMEM_GATE_BYTES = 64 << 10
    try:
        n = 16384
        cases = [
            np.arange(n, dtype=np.uint64),
            np.arange(n, dtype=np.uint64)[::-1].copy(),
            np.full(n, 7, dtype=np.uint64),
            np.tile(np.array([5, 1, 9, 3], dtype=np.uint64), n // 4),
        ]
        for arr in cases:
            ops = (jnp.asarray(arr), jnp.arange(n, dtype=jnp.int32))
            want = lax.sort(ops, num_keys=1)
            got = BT.bitonic_sort(ops, impl="jnp")
            for g, w in zip(got, want):
                np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    finally:
        BT._VMEM_GATE_BYTES = old_gate


def test_tiled_sort_pallas_matches_lax_sort():
    from auron_tpu.ops import bitonic as BT

    _skip_unless_pallas("pallas")  # same probe/skip as the other kernel tests
    old_gate = BT._VMEM_GATE_BYTES
    BT._VMEM_GATE_BYTES = 64 << 10
    try:
        rng = np.random.default_rng(5)
        n = 8192
        ops = (jnp.asarray(rng.integers(0, 1 << 40, n, dtype=np.uint64)),
               jnp.arange(n, dtype=jnp.int32))
        want = lax.sort(ops, num_keys=1)
        got = BT.bitonic_sort(ops, impl="pallas")
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    finally:
        BT._VMEM_GATE_BYTES = old_gate

"""RSS network client/server: the engine's shuffle over a real TCP wire.

The write path pushes through RssShuffleWriterExec with a
RemotePartitionWriter resource (drop-in for the in-process client), the
read path fetches through RemoteBlockProvider — the full shuffle rides
the socket protocol while keeping the service semantics the in-process
tests pin (attempt isolation, first-commit-wins, committed-only reads).
"""

import threading

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exec.base import ExecutionContext
from auron_tpu.exec.basic import MemoryScanExec
from auron_tpu.exec.shuffle import rss_net as RN
from auron_tpu.exec.shuffle.partitioning import HashPartitioning
from auron_tpu.exec.shuffle.rss import LocalRssService
from auron_tpu.exec.shuffle.writer import RssShuffleWriterExec
from auron_tpu.exprs.ir import col


@pytest.fixture()
def server():
    srv = RN.RssNetServer(LocalRssService(num_replicas=2))
    yield srv
    srv.close()


def _scan(df, n_parts=2):
    per = (len(df) + n_parts - 1) // n_parts
    return MemoryScanExec([
        [Batch.from_arrow(pa.RecordBatch.from_pandas(
            df.iloc[p * per : (p + 1) * per], preserve_index=False))]
        for p in range(n_parts)
    ], Batch.from_arrow(pa.RecordBatch.from_pandas(
        df.iloc[:1], preserve_index=False)).schema)


def test_shuffle_rides_the_wire(server):
    rng = np.random.default_rng(2)
    df = pd.DataFrame({
        "k": rng.integers(0, 50, 3000).astype(np.int64),
        "v": rng.integers(-10, 10, 3000).astype(np.int64),
    })
    n_red = 4
    client = RN.RssNetClient(server.addr)
    scan = _scan(df)
    w = RssShuffleWriterExec(scan, HashPartitioning([col(0)], n_red), "rss")
    for map_id in range(2):
        writer = RN.RemotePartitionWriter(client, "s1", map_id)
        ctx = ExecutionContext(partition_id=map_id, resources={"rss": writer})
        assert list(w.execute(map_id, ctx)) == []

    provider = RN.RemoteBlockProvider(client, "s1")
    rows = []
    for pid in range(n_red):
        for rb in provider(pid):
            rows.append(rb.to_pandas())
    got = pd.concat(rows)
    assert len(got) == len(df)
    assert got["v"].sum() == df["v"].sum()
    g = got.groupby("k").v.sum().sort_index()
    pd.testing.assert_series_equal(
        g, df.groupby("k").v.sum().sort_index(), check_dtype=False)
    # replica 1 carries the same committed data
    rep1 = RN.RemoteBlockProvider(client, "s1", replica=1)
    n1 = sum(rb.num_rows for pid in range(n_red) for rb in rep1(pid))
    assert n1 == len(df)
    client.close()


def test_speculative_attempt_isolation_over_wire(server):
    client = RN.RssNetClient(server.addr)
    w1 = RN.RemotePartitionWriter(client, "spec", 0)
    w2 = RN.RemotePartitionWriter(client, "spec", 0)  # speculative duplicate
    w1.write(0, b"from-w1")
    w2.write(0, b"from-w2")
    w2.flush()  # w2 commits first -> wins
    w1.flush()  # late commit discarded (first-wins)
    assert client.fetch("spec", 0) == [b"from-w2"]
    client.close()


def test_abort_discards_staged(server):
    client = RN.RssNetClient(server.addr)
    w = RN.RemotePartitionWriter(client, "ab", 0)
    w.write(0, b"staged")
    w.abort()
    w.flush()  # commit after abort is a no-op (staging gone)
    assert client.fetch("ab", 0) == []
    client.close()


def test_large_block_framing(server):
    client = RN.RssNetClient(server.addr)
    big = bytes(np.random.default_rng(0).integers(0, 256, 3 << 20, dtype=np.uint8))
    w = RN.RemotePartitionWriter(client, "big", 0)
    w.write(1, big)
    w.flush()
    assert client.fetch("big", 1) == [big]
    client.close()


def test_concurrent_writers_shared_client(server):
    client = RN.RssNetClient(server.addr)
    errs = []

    def work(map_id):
        try:
            w = RN.RemotePartitionWriter(client, "conc", map_id)
            for p in range(8):
                w.write(p, f"m{map_id}p{p}".encode())
            w.flush()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=work, args=(i,)) for i in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs
    for p in range(8):
        got = sorted(client.fetch("conc", p))
        assert got == sorted(f"m{i}p{p}".encode() for i in range(6))
    client.close()


def test_server_error_relayed(server, monkeypatch):
    client = RN.RssNetClient(server.addr)

    def boom(*a, **k):
        raise RuntimeError("disk full on shuffle node")

    monkeypatch.setattr(server.service, "fetch", boom)
    with pytest.raises(RuntimeError, match="disk full"):
        client.fetch("x", 0)
    client.close()


def test_fetch_pages_through_reply_budget(server, monkeypatch):
    """Partitions larger than the reply budget page across FETCH round
    trips (whole blocks per page; order preserved)."""
    monkeypatch.setattr(RN, "_MAX_REPLY", 64)  # tiny budget -> many pages
    client = RN.RssNetClient(server.addr)
    blocks = [f"block-{i:03d}".encode() * 4 for i in range(23)]
    w = RN.RemotePartitionWriter(client, "page", 0)
    for b in blocks:
        w.write(2, b)
    w.flush()
    assert client.fetch("page", 2) == blocks
    client.close()


# ---------------------------------------------------------------------------
# network fault injection (VERDICT r4 #10: loopback-to-LAN hardening)
# ---------------------------------------------------------------------------


def test_fetch_survives_connection_drop():
    """Server kills the connection before replying to the FIRST fetch; the
    client's reconnect-once path must transparently retry."""
    faults = {"n": 0}

    def hook(op):
        if op == RN.OP_FETCH and faults["n"] == 0:
            faults["n"] += 1
            return "drop_before"
        return None

    srv = RN.RssNetServer(fault_hook=hook)
    try:
        cl = RN.RssNetClient(srv.addr)
        att = cl.new_attempt("s1", 0)
        cl.push("s1", 0, att, 0, b"hello")
        cl.commit("s1", 0, att)
        got = cl.fetch("s1", 0)
        assert got == [b"hello"]
        assert faults["n"] == 1  # the fault DID fire
        cl.close()
    finally:
        srv.close()


def test_fetch_survives_partial_frame():
    """Server sends half a length header then closes (congestion-truncated
    reply): read_exact must fail cleanly and the retry must succeed."""
    faults = {"n": 0}

    def hook(op):
        if op == RN.OP_FETCH and faults["n"] == 0:
            faults["n"] += 1
            return "partial_reply"
        return None

    srv = RN.RssNetServer(fault_hook=hook)
    try:
        cl = RN.RssNetClient(srv.addr)
        att = cl.new_attempt("s2", 0)
        cl.push("s2", 0, att, 1, b"blockA")
        cl.commit("s2", 0, att)
        assert cl.fetch("s2", 1) == [b"blockA"]
        assert faults["n"] == 1
        cl.close()
    finally:
        srv.close()


def test_push_drop_is_loud_and_reattempt_is_clean():
    """A dropped PUSH raises (non-idempotent, never silently retried); the
    writer opens a NEW attempt whose committed data wins, and the broken
    attempt's partial pushes are invisible (attempt isolation)."""
    faults = {"n": 0}

    def hook(op):
        if op == RN.OP_PUSH and faults["n"] == 0:
            faults["n"] += 1
            return "drop_before"
        return None

    srv = RN.RssNetServer(fault_hook=hook)
    try:
        cl = RN.RssNetClient(srv.addr)
        a1 = cl.new_attempt("s3", 0)
        import pytest as _pytest

        with _pytest.raises((ConnectionError, OSError)):
            cl.push("s3", 0, a1, 0, b"broken")
        # fresh attempt over the same (reconnected) client
        a2 = cl.new_attempt("s3", 0)
        cl.push("s3", 0, a2, 0, b"good")
        cl.commit("s3", 0, a2)
        assert cl.fetch("s3", 0) == [b"good"]
        cl.close()
    finally:
        srv.close()


def test_slow_server_times_out_cleanly():
    """A stalled reply must surface as a timeout error, not a hang."""

    def hook(op):
        if op == RN.OP_FETCH:
            return "delay:5"
        return None

    srv = RN.RssNetServer(fault_hook=hook)
    try:
        cl = RN.RssNetClient(srv.addr, timeout_s=0.5)
        att = cl.new_attempt("s4", 0)
        cl.push("s4", 0, att, 0, b"x")
        cl.commit("s4", 0, att)
        import pytest as _pytest

        with _pytest.raises((TimeoutError, OSError)):
            cl.fetch("s4", 0)
        cl.close()
    finally:
        srv.close()

"""Executable spec of the JVM shim's splicer/scheduler (VERDICT r3 #2, #3).

The Scala side (jvm/.../AuronTpuSparkExtension.scala NativeSegmentSplicer +
NativeStagedSegmentExec) cannot be compiled in this image, so this module IS
its contract test: a *mechanical* splicer/scheduler that restricts itself to
exactly what the JVM sees —

  - the conversion-response JSON from ``auron_convert_plan`` (C ABI),
  - byte-level TaskDefinition assembly (manual varints, mirroring
    TaskDefs.assemble — no generated-proto dependency),
  - per-task engine invocations through the C harness as separate OS
    processes (the stand-in executor), with resources registered through
    the same entry points the JVM binds (put_resource /
    put_resource_shuffle),
  - shuffle manifests computed driver-side from the stage templates
    (output_data_template/{work_dir}/{partition} substitution only).

Any behavior change that breaks this test would break the Scala shim the
same way; keep the two in sync.

Reference parity: AuronShuffleManager.scala:14-37 (host-scheduled stages),
NativeShuffleExchangeBase.scala:124-296 (exchange contract),
AuronConverters.scala:436-1186 (multi-input join segments).
"""

import json
import os
import shutil
import subprocess
import sys
import sysconfig

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

pytestmark = pytest.mark.skipif(
    shutil.which("make") is None, reason="no make in this environment"
)

N_PARTS = 2


# ---------------------------------------------------------------------------
# TaskDefs.assemble mirror (Scala wire surgery, manual varints)
# ---------------------------------------------------------------------------


def _varint(v: int) -> bytes:
    out = bytearray()
    while v & ~0x7F:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def taskdef_assemble(plan_proto: bytes, partition_id: int,
                     conf: list[tuple[str, str]]) -> bytes:
    """Mirror of TaskDefs.assemble (AuronTpuSparkExtension.scala): field 1 =
    plan bytes, field 3 = partition_id varint, field 4 = conf map entries
    {1: key, 2: value}. MUST stay in sync with the Scala."""
    out = bytearray()
    out += _varint((1 << 3) | 2) + _varint(len(plan_proto)) + plan_proto
    out += _varint((3 << 3) | 0) + _varint(partition_id)
    for k, v in conf:
        kb, vb = k.encode(), v.encode()
        entry = (
            _varint((1 << 3) | 2) + _varint(len(kb)) + kb
            + _varint((2 << 3) | 2) + _varint(len(vb)) + vb
        )
        out += _varint((4 << 3) | 2) + _varint(len(entry)) + bytes(entry)
    return bytes(out)


def test_taskdef_wire_format_parses():
    """The hand-rolled wire bytes must decode to the exact TaskDefinition
    the engine's generated proto sees (validates the Scala format)."""
    from auron_tpu.plan import builders as B
    from auron_tpu.proto import plan_pb2 as pb
    from auron_tpu import types as T

    schema = T.Schema.of(T.Field("k", T.INT64))
    plan = B.ffi_reader(schema, "x")
    raw = taskdef_assemble(plan.SerializeToString(), 7,
                           [("auron.work_dir", "/tmp/wd"), ("a", "b")])
    t = pb.TaskDefinition()
    t.ParseFromString(raw)
    assert t.partition_id == 7
    assert t.plan.WhichOneof("plan") == "ffi_reader"
    assert dict(t.conf) == {"auron.work_dir": "/tmp/wd", "a": "b"}


# ---------------------------------------------------------------------------
# mechanical splicer/scheduler (NativeStagedSegmentExec mirror)
# ---------------------------------------------------------------------------


def _build_harness():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    native = os.path.join(root, "native")
    r = subprocess.run(
        ["make", "-C", native, "libauron_bridge.so", "bridge_harness"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, f"bridge build failed: {r.stderr[-800:]}"
    return os.path.join(native, "bridge_harness")


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = sysconfig.get_paths()["purelib"]
    env["JAX_PLATFORMS"] = "cpu"
    env["AURON_TPU_ROOT"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return env


def _ipc_bytes(rb: pa.RecordBatch) -> bytes:
    import io

    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as w:
        w.write_batch(rb)
    return sink.getvalue()


def _decode_framed(path) -> list[dict]:
    import io
    import struct

    data = open(path, "rb").read()
    pos, rows = 0, []
    while pos < len(data):
        (n,) = struct.unpack_from("<Q", data, pos)
        pos += 8
        with pa.ipc.open_stream(io.BytesIO(data[pos : pos + n])) as r:
            for rb in r:
                rows += rb.to_pylist()
        pos += n
    return rows


def _convert(harness, tmp_path, hostplan: dict) -> dict:
    req = tmp_path / "hostplan.json"
    req.write_text(json.dumps(hostplan))
    out = tmp_path / "resp.json"
    r = subprocess.run(
        [harness, "--convert", str(req), str(out)],
        env=_env(), capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-1500:]
    return json.loads(out.read_text())


def _fill(template: str, work_dir: str, pid: int) -> str:
    return template.replace("{work_dir}", work_dir).replace(
        "{partition}", str(pid)
    )


class MechanicalScheduler:
    """Stage scheduling exactly as NativeStagedSegmentExec does it: widths
    from input exchanges / task_partitions / ffi children / default; stage
    tasks as separate harness processes; manifests from path templates."""

    def __init__(self, harness, work_dir, tmp_path, default_width=N_PARTS):
        self.harness = harness
        self.work_dir = str(work_dir)
        self.tmp = tmp_path
        self.default_width = default_width
        self._n = 0

    def width_of(self, stage, stages, ffi_parts: dict[str, int]) -> int:
        if stage["input_exchange_ids"]:
            # splicer contract: a stage may not pair an input exchange with
            # FFI children or a pinned scan (widths would conflict -> host
            # fallback)
            assert not stage["ffi_input_ids"], stage
            assert not stage.get("task_partitions"), stage
            widths = {
                s["num_output_partitions"]
                for s in stages
                if s["exchange_id"] in stage["input_exchange_ids"]
            }
            assert len(widths) == 1, widths
            return widths.pop()
        if stage.get("task_partitions"):
            return stage["task_partitions"]
        if stage["ffi_input_ids"]:
            ws = {ffi_parts[r] for r in stage["ffi_input_ids"]}
            assert len(ws) == 1, ws
            return ws.pop()
        return self.default_width

    def manifest_of(self, stage, width) -> bytes:
        return json.dumps(
            [
                {
                    "data": _fill(stage["output_data_template"], self.work_dir, p),
                    "index": _fill(stage["output_index_template"], self.work_dir, p),
                }
                for p in range(width)
            ]
        ).encode()

    def run_task(self, plan_b64: str, pid: int, resources: list[tuple[str, bytes]],
                 manifests: dict[str, bytes]) -> list[dict]:
        import base64

        task = taskdef_assemble(
            base64.b64decode(plan_b64), pid, [("auron.work_dir", self.work_dir)]
        )
        self._n += 1
        task_f = self.tmp / f"t{self._n}.task"
        task_f.write_bytes(task)
        out_f = self.tmp / f"t{self._n}.out"
        args = [self.harness, str(task_f), str(out_f)]
        for key, payload in resources:
            f = self.tmp / f"t{self._n}.{key.replace('/', '_')}.bin"
            f.write_bytes(payload)
            args += [key, str(f)]
        for ex_id, m in manifests.items():
            f = self.tmp / f"t{self._n}.{ex_id}.manifest"
            f.write_bytes(m)
            args += [f"shuffle:{ex_id}", str(f)]
        r = subprocess.run(
            args, env=_env(), capture_output=True, text=True, timeout=600
        )
        assert r.returncode == 0, r.stderr[-1500:]
        return _decode_framed(out_f)

    def run_segment(self, seg: dict,
                    ffi_chunks: dict[str, list[pa.RecordBatch]],
                    scan_resources=None) -> list[dict]:
        """Run all stages producers-first; returns the final stage's rows.
        ``ffi_chunks``: resource id -> per-partition record batches (the
        Spark children's partitions). ``scan_resources``: per-partition
        extra resources (LocalTableScan inputs), pid -> [(key, ipc)]."""
        stages = seg["stages"]
        ffi_parts = {rid: len(chunks) for rid, chunks in ffi_chunks.items()}
        widths = [self.width_of(s, stages, ffi_parts) for s in stages]
        by_ex = {
            s["exchange_id"]: (s, w)
            for s, w in zip(stages, widths)
            if s["exchange_id"]
        }
        rows: list[dict] = []
        for s, width in zip(stages, widths):
            manifests = {
                ex: self.manifest_of(*by_ex[ex]) for ex in s["input_exchange_ids"]
            }
            is_final = s["exchange_id"] is None
            for pid in range(width):
                res = [
                    (f"{rid}.{pid}", _ipc_bytes(ffi_chunks[rid][pid]))
                    for rid in s["ffi_input_ids"]
                ]
                if scan_resources:
                    res += scan_resources(pid)
                out = self.run_task(s["plan_b64"], pid, res, manifests)
                if is_final:
                    rows += out
                else:
                    assert out == [], "shuffle-writer stage emitted rows"
        return rows


# ---------------------------------------------------------------------------
# contract tests
# ---------------------------------------------------------------------------


def _attr(i, name=""):
    return {"kind": "attr", "index": i, "name": name}


def test_two_stage_segment_schedules_under_host(tmp_path):
    """VERDICT r3 #2 done-criterion: a partial-agg -> exchange -> final-agg
    segment splices and runs end-to-end through the host scheduling
    contract (stage templates + manifests), one OS process per task."""
    harness = _build_harness()
    inter = [["k", "long", True], ["s#sum", "long", True]]
    hostplan = {
        "op": "HashAggregateExec", "schema": inter,
        "args": {"mode": "final", "groupings": [{"expr": _attr(0), "name": "k"}],
                 "aggs": [{"fn": "sum", "expr": _attr(1), "name": "s"}]},
        "children": [{
            "op": "ShuffleExchangeExec", "schema": inter,
            "args": {"partitioning": {"kind": "hash", "exprs": [_attr(0)],
                                      "num_partitions": N_PARTS}},
            "children": [{
                "op": "HashAggregateExec", "schema": inter,
                "args": {"mode": "partial",
                         "groupings": [{"expr": _attr(0), "name": "k"}],
                         "aggs": [{"fn": "sum", "expr": _attr(1), "name": "s"}]},
                "children": [{
                    "op": "LocalTableScanExec",
                    "schema": [["k", "long", True], ["v", "long", True]],
                    "args": {"resource_id": "fact"}, "children": [],
                }],
            }],
        }],
    }
    resp = _convert(harness, tmp_path, hostplan)
    assert resp["converted"] is True
    seg = resp["root"]
    assert seg["kind"] == "segment" and seg["inputs"] == []
    stages = seg["stages"]
    assert len(stages) == 2
    s0, s1 = stages
    assert s0["exchange_id"] and s0["num_output_partitions"] == N_PARTS
    assert "{work_dir}" in s0["output_data_template"]
    assert "{partition}" in s0["output_data_template"]
    assert s1["exchange_id"] is None
    assert s1["input_exchange_ids"] == [s0["exchange_id"]]
    # exchange ids are namespaced per conversion (no executor-side clashes)
    resp2 = _convert(harness, tmp_path, hostplan)
    assert resp2["root"]["stages"][0]["exchange_id"] != s0["exchange_id"]

    rng = np.random.default_rng(11)
    df = pd.DataFrame({
        "k": rng.integers(0, 37, 4000).astype(np.int64),
        "v": rng.integers(-100, 100, 4000).astype(np.int64),
    })
    per = (len(df) + N_PARTS - 1) // N_PARTS
    chunks = [
        pa.RecordBatch.from_pandas(df.iloc[p * per : (p + 1) * per],
                                   preserve_index=False)
        for p in range(N_PARTS)
    ]

    sched = MechanicalScheduler(harness, tmp_path / "work", tmp_path)
    (tmp_path / "work").mkdir()
    rows = sched.run_segment(
        seg, {}, scan_resources=lambda pid: [("fact", _ipc_bytes(chunks[pid]))]
    )
    got = pd.DataFrame(rows).sort_values("k").reset_index(drop=True)
    want = (
        df.groupby("k").agg(s=("v", "sum")).reset_index()
        .sort_values("k").reset_index(drop=True)
    )
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def test_multi_input_join_segment(tmp_path):
    """VERDICT r3 #3 done-criterion: a join subtree with TWO unconvertible
    children converts to one segment with two FFI boundaries; the
    mechanical splicer feeds both children's co-partitioned rows and the
    join runs natively."""
    harness = _build_harness()
    lschema = [["k", "long", True], ["a", "long", True]]
    rschema = [["k2", "long", True], ["b", "long", True]]
    out_schema = lschema + rschema
    hostplan = {
        "op": "SortMergeJoinExec", "schema": out_schema,
        "args": {"left_keys": [_attr(0)], "right_keys": [_attr(0)],
                 "join_type": "inner"},
        "children": [
            {"op": "PythonMapExec", "schema": lschema, "args": {},
             "children": [{"op": "LocalTableScanExec", "schema": lschema,
                           "args": {"resource_id": "l"}, "children": []}]},
            {"op": "PythonMapExec", "schema": rschema, "args": {},
             "children": [{"op": "LocalTableScanExec", "schema": rschema,
                           "args": {"resource_id": "r"}, "children": []}]},
        ],
    }
    resp = _convert(harness, tmp_path, hostplan)
    assert resp["converted"] is True
    seg = resp["root"]
    assert seg["kind"] == "segment"
    assert len(seg["inputs"]) == 2  # the r3 splicer bailed at >1
    rids = [i["resource_id"] for i in seg["inputs"]]
    assert [s["ffi_input_ids"] for s in seg["stages"]] == [rids]
    # both children are host subtrees at relative paths 0 and 1
    assert [i["child"]["path"] for i in seg["inputs"]] == [[0], [1]]

    # co-partitioned, sorted inputs (Spark guarantees SMJ child ordering)
    rng = np.random.default_rng(5)
    left = pd.DataFrame({
        "k": np.sort(rng.integers(0, 50, 600)).astype(np.int64),
        "a": rng.integers(0, 10, 600).astype(np.int64),
    })
    right = pd.DataFrame({
        "k2": np.sort(rng.integers(0, 50, 400)).astype(np.int64),
        "b": rng.integers(0, 10, 400).astype(np.int64),
    })
    cut = 25  # co-partition both sides on the same key split
    lchunks = [left[left.k < cut], left[left.k >= cut]]
    rchunks = [right[right.k2 < cut], right[right.k2 >= cut]]
    ffi = {
        rids[0]: [pa.RecordBatch.from_pandas(c, preserve_index=False)
                  for c in lchunks],
        rids[1]: [pa.RecordBatch.from_pandas(c, preserve_index=False)
                  for c in rchunks],
    }

    sched = MechanicalScheduler(harness, tmp_path / "work", tmp_path)
    (tmp_path / "work").mkdir()
    rows = sched.run_segment(seg, ffi)
    got = (
        pd.DataFrame(rows)
        .sort_values(["k", "a", "b"]).reset_index(drop=True)
    )
    want = (
        left.merge(right, left_on="k", right_on="k2")
        .sort_values(["k", "a", "b"]).reset_index(drop=True)
    )
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def test_multi_stage_with_ffi_input(tmp_path):
    """A segment whose MAP stage is fed by an FFI child: partial agg over
    an unconvertible child, exchange, final agg — exercises ffi_input_ids
    placement in stage 0 plus manifest handoff to stage 1.

    (Aggs over non-native children are normally reverted by the
    inefficient-convert rule, so the unconvertible child sits under a
    native project instead.)"""
    harness = _build_harness()
    pschema = [["k", "long", True], ["v", "long", True]]
    inter = [["k", "long", True], ["s#sum", "long", True]]
    hostplan = {
        "op": "HashAggregateExec", "schema": inter,
        "args": {"mode": "final", "groupings": [{"expr": _attr(0), "name": "k"}],
                 "aggs": [{"fn": "sum", "expr": _attr(1), "name": "s"}]},
        "children": [{
            "op": "ShuffleExchangeExec", "schema": inter,
            "args": {"partitioning": {"kind": "hash", "exprs": [_attr(0)],
                                      "num_partitions": N_PARTS}},
            "children": [{
                "op": "HashAggregateExec", "schema": inter,
                "args": {"mode": "partial",
                         "groupings": [{"expr": _attr(0), "name": "k"}],
                         "aggs": [{"fn": "sum", "expr": _attr(1), "name": "s"}]},
                "children": [{
                    "op": "ProjectExec", "schema": pschema,
                    "args": {"projections": [_attr(0, "k"), _attr(1, "v")]},
                    "children": [{
                        "op": "PythonMapExec", "schema": pschema, "args": {},
                        "children": [{
                            "op": "LocalTableScanExec", "schema": pschema,
                            "args": {"resource_id": "t"}, "children": []}],
                    }],
                }],
            }],
        }],
    }
    resp = _convert(harness, tmp_path, hostplan)
    assert resp["converted"] is True
    seg = resp["root"]
    assert seg["kind"] == "segment" and len(seg["inputs"]) == 1
    rid = seg["inputs"][0]["resource_id"]
    stages = seg["stages"]
    assert len(stages) == 2
    assert stages[0]["ffi_input_ids"] == [rid]  # map stage owns the boundary
    assert stages[1]["ffi_input_ids"] == []

    rng = np.random.default_rng(17)
    df = pd.DataFrame({
        "k": rng.integers(0, 23, 3000).astype(np.int64),
        "v": rng.integers(-9, 9, 3000).astype(np.int64),
    })
    per = (len(df) + N_PARTS - 1) // N_PARTS
    ffi = {
        rid: [
            pa.RecordBatch.from_pandas(df.iloc[p * per : (p + 1) * per],
                                       preserve_index=False)
            for p in range(N_PARTS)
        ]
    }
    sched = MechanicalScheduler(harness, tmp_path / "work", tmp_path)
    (tmp_path / "work").mkdir()
    rows = sched.run_segment(seg, ffi)
    got = pd.DataFrame(rows).sort_values("k").reset_index(drop=True)
    want = (
        df.groupby("k").agg(s=("v", "sum")).reset_index()
        .sort_values("k").reset_index(drop=True)
    )
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


# ---------------------------------------------------------------------------
# compile-substitute lint (no JDK in the image — VERDICT r3 weak #3)
# ---------------------------------------------------------------------------


def test_jvm_sources_lint_clean():
    """Structural lint + ABI/wire-contract cross-checks over jvm/ come back
    empty (the compensating gate for the missing scala compiler)."""
    from tools import jvm_lint

    assert jvm_lint.run_all() == []


def test_lint_catches_unbalanced_and_unterminated():
    from tools.jvm_lint import check_balance, strip_and_check

    code, errs = strip_and_check('object A { def f = { 1 }\n', scala=True)
    assert not errs
    assert any("unclosed" in e for e in check_balance(code))

    _, errs = strip_and_check('val s = "never closed\nval t = 1\n', scala=True)
    assert any("unterminated string" in e for e in errs)

    _, errs = strip_and_check("/* outer /* inner */ still open\n", scala=True)
    assert any("unterminated block comment" in e for e in errs)


def test_lint_handles_interpolation_and_comments():
    from tools.jvm_lint import check_balance, strip_and_check

    src = (
        'object A {\n'
        '  // brace in comment: {\n'
        '  /* and here: } /* nested */ still comment { */\n'
        '  val s = s"pre ${x.map { y => y + 1 }} post"\n'
        '  val t = """raw { un } balanced {{{"""\n'
        '  val c = \'{\'\n'
        '}\n'
    )
    code, errs = strip_and_check(src, scala=True)
    assert not errs
    assert check_balance(code) == []


def test_abi_symbols_cross_checked():
    """Every FFM-bound symbol exists in the header AND the built .so."""
    from tools import jvm_lint

    bound = jvm_lint.bound_abi_symbols()
    assert len(bound) >= 9  # call/next/finalize/exit/resources/convert/error
    declared = jvm_lint.declared_abi_symbols()
    assert set(bound) <= declared
    exported = jvm_lint.exported_abi_symbols()
    if exported is not None:
        assert set(bound) <= exported


def test_metric_rollup_twins_agree_on_names():
    """The SQLMetric set NativeMetrics.scala declares must name REAL engine
    metrics (names drift silently otherwise), and MetricNode.flat_totals
    must roll up the snapshot shape the JVM twin parses."""
    import re

    from auron_tpu.exec.metrics import MetricNode

    # engine-side rollup over a synthetic tree
    root = MetricNode("root")
    root.add("output_rows", 5)
    c = root.child(0)
    c.add("output_rows", 7)
    c.add("spill_time", 100)
    c.child(0).add("spill_time", 50)
    flat = MetricNode.flat_totals(root.snapshot())
    assert flat == {"output_rows": 12, "spill_time": 150}

    # every metric the Scala side declares exists somewhere in the engine
    scala = open(
        "jvm/spark-extension/src/main/scala/org/apache/spark/sql/"
        "auron_tpu/NativeMetrics.scala").read()
    declared = re.findall(r'"([a-z_]+)"\s*->\s*SQLMetrics', scala)
    assert len(declared) >= 10
    import subprocess

    for name in declared:
        r = subprocess.run(
            ["grep", "-rlE",
             f'(add|timer|set)\\("{name}"', "auron_tpu/"],
            capture_output=True, text=True)
        assert r.returncode == 0, f"Scala declares unknown engine metric {name!r}"


def test_api_signature_gate_catches_rot():
    """The signature gate (VERDICT r4 #7) must flag the two rot classes an
    unbuilt JVM tree actually ships: a host-API call arity no overload
    accepts (NativeSegmentExec's zipPartitions risk) and an API that
    exists in no host version (the HiveUdfArrowEval ADVICE finding)."""
    import shutil
    import tempfile

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(root, "tools"))
    import jvm_lint

    bad = """
object Bad {
  def f(a: RDD[Int], b: RDD[Int], c: RDD[Int], d: RDD[Int], e: RDD[Int]) = {
    a.zipPartitions(b, c, d, e, true) { (ra, rb, rc, rd, re) => ra }
    val rows = ArrowUtils.fromArrowRecordBatch(root)
  }
}
"""
    tmp = tempfile.mkdtemp()
    try:
        os.makedirs(os.path.join(tmp, "x"))
        with open(os.path.join(tmp, "x", "Bad.scala"), "w") as f:
            f.write(bad)
        orig = jvm_lint.JVM_DIR
        jvm_lint.JVM_DIR = tmp
        try:
            finds = jvm_lint.check_api_signatures()
        finally:
            jvm_lint.JVM_DIR = orig
    finally:
        shutil.rmtree(tmp)
    assert any("zipPartitions" in x for x in finds), finds
    assert any("fromArrowRecordBatch" in x for x in finds), finds


def test_api_signature_gate_clean_on_tree():
    """The real jvm/ tree passes the signature gate."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(root, "tools"))
    import jvm_lint

    assert jvm_lint.check_api_signatures() == []

"""Hash-aggregate tests, differential against pandas groupby."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exec.agg_exec import FINAL, PARTIAL, PARTIAL_MERGE, AggExpr, HashAggExec
from auron_tpu.exec.base import ExecutionContext
from auron_tpu.exec.basic import MemoryScanExec
from auron_tpu.exprs.ir import col
from auron_tpu.utils.config import (
    PARTIAL_AGG_SKIPPING_MIN_ROWS,
    PARTIAL_AGG_SKIPPING_RATIO,
)


def _agg_pipeline(batches, groupings, aggs):
    """partial -> (simulated exchange) -> final, like Spark plans it."""
    scan = MemoryScanExec.single(batches)
    partial = HashAggExec(scan, groupings, aggs, PARTIAL)
    shuffled = MemoryScanExec.single(list(partial.execute(0, ExecutionContext())) or
                                     [Batch.empty(partial.inter_schema)])
    final = HashAggExec(shuffled, groupings, aggs, FINAL)
    return final.collect().to_pandas()


def _sorted(df, by):
    return df.sort_values(by).reset_index(drop=True)


def test_sum_count_avg_min_max_basic():
    data = {
        "k": ["a", "b", "a", "c", "b", "a"],
        "v": [1, 2, 3, None, 5, 6],
    }
    b = Batch.from_pydict(
        data, schema=T.Schema.of(T.Field("k", T.STRING), T.Field("v", T.INT64))
    )
    got = _agg_pipeline(
        [b],
        [(col(0), "k")],
        [
            (AggExpr("sum", col(1)), "s"),
            (AggExpr("count", col(1)), "c"),
            (AggExpr("count_star", None), "cs"),
            (AggExpr("avg", col(1)), "a"),
            (AggExpr("min", col(1)), "mn"),
            (AggExpr("max", col(1)), "mx"),
        ],
    )
    df = pd.DataFrame(data)
    want = df.groupby("k", dropna=False).agg(
        s=("v", "sum"), c=("v", "count"), cs=("v", "size"),
        a=("v", "mean"), mn=("v", "min"), mx=("v", "max"),
    ).reset_index()
    got = _sorted(got, "k")
    want = _sorted(want, "k")
    assert got["k"].tolist() == want["k"].tolist()
    # c group has sum NULL (all inputs null), count 0
    assert got["s"].tolist()[:2] == [10, 7] and pd.isna(got["s"][2])
    assert got["c"].tolist() == [3, 2, 0]
    assert got["cs"].tolist() == [3, 2, 1]
    assert got["a"].tolist()[:2] == [pytest.approx(10 / 3), pytest.approx(3.5)]
    assert pd.isna(got["a"][2])
    assert got["mn"].tolist()[:2] == [1, 2]
    assert got["mx"].tolist()[:2] == [6, 5]


def test_multi_batch_multi_key_random_vs_pandas():
    rng = np.random.default_rng(0)
    n = 5000
    k1 = rng.integers(0, 50, n)
    k2 = rng.choice(["x", "y", "z", "w"], n)
    v = rng.normal(size=n)
    vmask = rng.random(n) < 0.1
    vs = pd.array(v, dtype="Float64")
    vs[vmask] = pd.NA
    df = pd.DataFrame({"k1": k1, "k2": k2, "v": vs})
    batches = []
    for i in range(0, n, 1000):
        chunk = df.iloc[i : i + 1000]
        batches.append(
            Batch.from_arrow(pa.RecordBatch.from_pandas(chunk, preserve_index=False))
        )
    got = _agg_pipeline(
        batches,
        [(col(0), "k1"), (col(1), "k2")],
        [
            (AggExpr("sum", col(2)), "s"),
            (AggExpr("count", col(2)), "c"),
            (AggExpr("min", col(2)), "mn"),
            (AggExpr("max", col(2)), "mx"),
        ],
    )
    want = (
        df.groupby(["k1", "k2"], dropna=False)
        .agg(s=("v", "sum"), c=("v", "count"), mn=("v", "min"), mx=("v", "max"))
        .reset_index()
    )
    got = _sorted(got, ["k1", "k2"])
    want = _sorted(want, ["k1", "k2"])
    assert len(got) == len(want)
    assert got["k1"].tolist() == want["k1"].tolist()
    assert got["k2"].tolist() == want["k2"].tolist()
    assert got["c"].tolist() == want["c"].tolist()
    # pandas sum over all-NA group gives 0.0 with count 0; ours gives NULL
    for g, w, c in zip(got["s"], want["s"], want["c"]):
        if c == 0:
            assert pd.isna(g)
        else:
            assert g == pytest.approx(w, rel=1e-9)
    for colname in ("mn", "mx"):
        for g, w in zip(got[colname], want[colname]):
            assert (pd.isna(g) and pd.isna(w)) or g == pytest.approx(w)


def test_null_group_key():
    data = {"k": [1, None, 1, None], "v": [1.0, 2.0, 3.0, 4.0]}
    b = Batch.from_pydict(
        data, schema=T.Schema.of(T.Field("k", T.INT32), T.Field("v", T.FLOAT64))
    )
    got = _agg_pipeline([b], [(col(0), "k")], [(AggExpr("sum", col(1)), "s")])
    got = got.sort_values("k", na_position="last").reset_index(drop=True)
    assert got["s"].tolist() == [4.0, 6.0]
    assert got["k"][0] == 1 and pd.isna(got["k"][1])


def test_global_agg_and_empty_input():
    b = Batch.from_pydict({"v": [1, 2, 3]},
                          schema=T.Schema.of(T.Field("v", T.INT64)))
    got = _agg_pipeline([b], [], [(AggExpr("sum", col(0)), "s"),
                                  (AggExpr("count", col(0)), "c")])
    assert got["s"].tolist() == [6] and got["c"].tolist() == [3]
    # empty input: global agg still yields one row: sum NULL, count 0
    e = Batch.empty(b.schema)
    got2 = _agg_pipeline([e], [], [(AggExpr("sum", col(0)), "s"),
                                   (AggExpr("count", col(0)), "c")])
    assert len(got2) == 1
    assert pd.isna(got2["s"][0]) and got2["c"].tolist() == [0]


def test_decimal_sum_avg():
    import decimal as d

    data = {"k": [1, 1, 2], "v": [d.Decimal("1.10"), d.Decimal("2.05"), d.Decimal("-0.50")]}
    b = Batch.from_pydict(
        data,
        schema=T.Schema.of(T.Field("k", T.INT32), T.Field("v", T.decimal(7, 2))),
    )
    got = _agg_pipeline([b], [(col(0), "k")],
                        [(AggExpr("sum", col(1)), "s"), (AggExpr("avg", col(1)), "a")])
    got = _sorted(got, "k")
    assert got["s"].tolist() == [d.Decimal("3.15"), d.Decimal("-0.50")]
    # avg type decimal(11,6)
    assert got["a"].tolist() == [d.Decimal("1.575000"), d.Decimal("-0.500000")]


def test_first_and_first_ignores_null():
    data = {"k": [1, 1, 2], "v": [None, 5, None]}
    b = Batch.from_pydict(
        data, schema=T.Schema.of(T.Field("k", T.INT32), T.Field("v", T.INT64))
    )
    got = _agg_pipeline(
        [b], [(col(0), "k")], [(AggExpr("first_ignores_null", col(1)), "f")]
    )
    got = _sorted(got, "k")
    assert got["f"].tolist()[0] == 5
    assert pd.isna(got["f"][1])


def test_partial_merge_mode():
    """partial -> partial_merge -> final three-stage plan."""
    data = {"k": [1, 2, 1], "v": [1.0, 2.0, 3.0]}
    b = Batch.from_pydict(
        data, schema=T.Schema.of(T.Field("k", T.INT32), T.Field("v", T.FLOAT64))
    )
    scan = MemoryScanExec.single([b])
    p = HashAggExec(scan, [(col(0), "k")], [(AggExpr("avg", col(1)), "a")], PARTIAL)
    mid = MemoryScanExec.single(list(p.execute(0, ExecutionContext())))
    pm = HashAggExec(mid, [(col(0), "k")], [(AggExpr("avg", col(1)), "a")], PARTIAL_MERGE)
    fin_in = MemoryScanExec.single(list(pm.execute(0, ExecutionContext())))
    fin = HashAggExec(fin_in, [(col(0), "k")], [(AggExpr("avg", col(1)), "a")], FINAL)
    got = _sorted(fin.collect().to_pandas(), "k")
    assert got["a"].tolist() == [pytest.approx(2.0), pytest.approx(2.0)]


def test_partial_skipping_still_correct():
    """High-cardinality keys trigger pass-through partials; final agg must
    still produce exact results."""
    from auron_tpu.utils.config import Configuration, conf_scope

    n = 4000
    rng = np.random.default_rng(1)
    # all distinct -> ratio 1.0; spread over a huge range so the dense
    # direct-address path (which makes skipping moot) stays ineligible
    k = rng.permutation(n) * 1_000_003
    v = rng.integers(0, 100, n)
    df = pd.DataFrame({"k": k, "v": v})
    batches = [
        Batch.from_arrow(
            pa.RecordBatch.from_pandas(df.iloc[i : i + 500], preserve_index=False)
        )
        for i in range(0, n, 500)
    ]
    conf = Configuration().set(PARTIAL_AGG_SKIPPING_MIN_ROWS, 1000)
    scan = MemoryScanExec.single(batches)
    partial = HashAggExec(scan, [(col(0), "k")], [(AggExpr("sum", col(1)), "s")], PARTIAL)
    ctx = ExecutionContext(conf=conf)
    partial_out = list(partial.execute(0, ctx))
    assert ctx.metrics.values.get("partial_agg_skipped", 0) == 1
    shuffled = MemoryScanExec.single(partial_out)
    final = HashAggExec(shuffled, [(col(0), "k")], [(AggExpr("sum", col(1)), "s")], FINAL)
    got = _sorted(final.collect().to_pandas(), "k")
    want = _sorted(df.groupby("k").agg(s=("v", "sum")).reset_index(), "k")
    assert got["k"].tolist() == want["k"].tolist()
    assert got["s"].tolist() == want["s"].tolist()


def test_collect_list_and_set():
    data = {"k": [1, 1, 2, 1, 2], "v": [5, 3, 7, 3, None]}
    b = Batch.from_pydict(
        data, schema=T.Schema.of(T.Field("k", T.INT32), T.Field("v", T.INT64))
    )
    got = _agg_pipeline(
        [b], [(col(0), "k")],
        [(AggExpr("collect_list", col(1)), "cl"),
         (AggExpr("collect_set", col(1)), "cs")],
    )
    got = _sorted(got, "k")
    assert sorted(got["cl"][0]) == [3, 3, 5]
    assert list(got["cl"][1]) == [7]
    assert list(got["cs"][0]) == [3, 5]
    assert list(got["cs"][1]) == [7]


def test_collect_list_multi_batch():
    b1 = Batch.from_pydict({"k": [1, 2], "v": [1.0, 2.0]})
    b2 = Batch.from_pydict({"k": [1, 1], "v": [3.0, 4.0]})
    got = _agg_pipeline([b1, b2], [(col(0), "k")],
                        [(AggExpr("collect_list", col(1)), "cl")])
    got = _sorted(got, "k")
    assert sorted(got["cl"][0]) == [1.0, 3.0, 4.0]
    assert list(got["cl"][1]) == [2.0]


def test_host_udaf_fallback():
    from auron_tpu.bridge.udf import register_udaf

    # geometric mean — something the native agg set doesn't provide
    register_udaf(
        "geomean",
        lambda vals: float(np.exp(np.mean(np.log([v for v in vals if v is not None]))))
        if any(v is not None for v in vals) else None,
        T.FLOAT64,
    )
    data = {"k": [1, 1, 2, 1], "v": [2.0, 8.0, 5.0, 4.0]}
    b = Batch.from_pydict(
        data, schema=T.Schema.of(T.Field("k", T.INT32), T.Field("v", T.FLOAT64))
    )
    got = _agg_pipeline(
        [b], [(col(0), "k")],
        [(AggExpr("host_udaf", col(1), udaf="geomean"), "g")],
    )
    got = _sorted(got, "k")
    assert got["g"][0] == pytest.approx((2 * 8 * 4) ** (1 / 3))
    assert got["g"][1] == pytest.approx(5.0)


def test_wide_decimal_sum_no_wrap():
    """sum(decimal(18,0)) over values near int64 range: plain int64
    accumulation would silently wrap; limb accumulation stays exact."""
    import decimal as d

    big = d.Decimal(5 * 10**13)  # 200k rows -> sum 1e19 > int64 max (wraps)
    n = 200_000
    data = {"k": [1] * n + [2] * 3,
            "v": [big] * n + [d.Decimal(5)] * 3}
    b = Batch.from_pydict(
        data, schema=T.Schema.of(T.Field("k", T.INT32), T.Field("v", T.decimal(18, 0)))
    )
    got = _agg_pipeline([b], [(col(0), "k")],
                        [(AggExpr("sum", col(1)), "s"), (AggExpr("avg", col(1)), "a")])
    got = _sorted(got, "k")
    # round 2: sums beyond the decimal64 domain emit EXACTLY through the
    # wide-decimal dictionary representation (previously NULL)
    assert got["s"][0] == d.Decimal(10) ** 19
    assert int(got["a"][0]) == 5 * 10**13
    # group 2 small values flow through exactly
    assert got["s"][1] == d.Decimal(15)
    assert int(got["a"][1]) == 5


def test_wide_sum_within_domain_is_exact():
    import decimal as d

    vals = [d.Decimal(10**16 + i) for i in range(50)]  # sum ~5e17, fits
    data = {"k": [1] * 50, "v": vals}
    b = Batch.from_pydict(
        data, schema=T.Schema.of(T.Field("k", T.INT32), T.Field("v", T.decimal(18, 0)))
    )
    got = _agg_pipeline([b], [(col(0), "k")], [(AggExpr("sum", col(1)), "s")])
    assert got["s"][0] == sum(vals)


def test_min_max_over_strings_lexicographic():
    # ADVICE r1 (high): dict codes are first-occurrence ordered; min/max
    # must reduce in lexicographic rank space
    data = {
        "k": [1, 1, 1, 2, 2],
        "s": ["zebra", "apple", "mango", "pear", None],
    }
    b = Batch.from_pydict(
        data, schema=T.Schema.of(T.Field("k", T.INT64), T.Field("s", T.STRING))
    )
    got = _agg_pipeline(
        [b],
        [(col(0), "k")],
        [(AggExpr("min", col(1)), "mn"), (AggExpr("max", col(1)), "mx")],
    )
    got = _sorted(got, ["k"])
    assert list(got["mn"]) == ["apple", "pear"]
    assert list(got["mx"]) == ["zebra", "pear"]


def test_udaf_accumulator_across_shuffle_bounded_state():
    """VERDICT r2 item 5: incremental accumulator UDAF with partial/merge/
    final states across a real exchange, matching a pandas oracle, with the
    serialized per-group state bounded regardless of input size."""
    import pickle

    import pandas as pd

    from auron_tpu.bridge.udf import register_udaf_accumulator
    from auron_tpu.parallel.mesh import make_mesh
    from auron_tpu.parallel.mesh_driver import MeshQueryDriver
    from auron_tpu.plan import builders as B

    # Welford-style mean accumulator: state = (count, total) — constant size
    register_udaf_accumulator(
        "acc_mean",
        init=lambda: (0, 0.0),
        update=lambda st, v: (st[0] + 1, st[1] + v) if v is not None else st,
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
        finish=lambda st: (st[1] / st[0]) if st[0] else None,
        out_dtype=T.FLOAT64,
    )

    rng = np.random.default_rng(11)
    n = 40_000
    df = pd.DataFrame(
        {
            "k": rng.integers(0, 37, n).astype(np.int64),
            "v": rng.normal(10.0, 3.0, n),
        }
    )
    n_dev = 8
    schema = T.Schema.of(T.Field("k", T.INT64), T.Field("v", T.FLOAT64))
    per = (n + n_dev - 1) // n_dev
    parts = [
        [Batch.from_arrow(pa.RecordBatch.from_pandas(
            df.iloc[p * per : (p + 1) * per], preserve_index=False))]
        for p in range(n_dev)
    ]
    scan = B.memory_scan(schema, "udaf_fact")
    partial = B.hash_agg(
        scan, [(col(0), "k")],
        [("host_udaf", col(1), "m", "acc_mean"), ("count_star", None, "c")],
        "partial",
    )
    ex = B.mesh_exchange(partial, B.hash_partitioning([col(0)], n_dev), "udaf_ex")
    final = B.hash_agg(
        ex, [(col(0), "k")],
        [("host_udaf", col(1), "m", "acc_mean"), ("count_star", None, "c")],
        "final",
    )
    driver = MeshQueryDriver(make_mesh(n_dev))
    got = driver.collect(final, {"udaf_fact": parts}).sort_values("k").reset_index(drop=True)

    want = (
        df.groupby("k").agg(m=("v", "mean"), c=("v", "size")).reset_index()
        .sort_values("k").reset_index(drop=True)
    )
    assert got["k"].tolist() == want["k"].tolist()
    assert got["c"].tolist() == want["c"].tolist()
    for g, w in zip(got["m"], want["m"]):
        assert g == pytest.approx(w, rel=1e-9)

    # memory bound: inspect the ENGINE's actual partial-stage state column —
    # every serialized per-group state must be O(1) bytes even though each
    # group folded ~1000 inputs (a collect-based fallback would hold the
    # raw values and grow with the input count)
    scan2 = B.memory_scan(schema, "udaf_fact")
    partial2 = B.hash_agg(
        scan2, [(col(0), "k")],
        [("host_udaf", col(1), "m", "acc_mean")], "partial",
    )
    from auron_tpu.bridge import api as _api

    _api.put_resource("udaf_fact", parts)
    try:
        h = _api.call_native(B.task(partial2, partition_id=0).SerializeToString())
        state_sizes = []
        while (rb := _api.next_batch(h)) is not None:
            for blob in rb.column(1).to_pylist():
                if blob:
                    state_sizes.append(len(blob))
        _api.finalize_native(h)
    finally:
        _api.remove_resource("udaf_fact")
    assert state_sizes, "partial stage produced no states"
    assert max(state_sizes) < 100, max(state_sizes)


def test_udaf_accumulator_state_spills(tmp_path):
    """Accumulator state batches ride the normal spill machinery."""
    from auron_tpu.bridge.udf import register_udaf_accumulator
    from auron_tpu.memory.memmgr import MemManager

    register_udaf_accumulator(
        "acc_sum",
        init=lambda: 0.0,
        update=lambda st, v: st + (v or 0.0),
        merge=lambda a, b: a + b,
        finish=lambda st: st,
        out_dtype=T.FLOAT64,
    )
    rng = np.random.default_rng(5)
    n = 20_000
    ks = rng.integers(0, 50, n).astype(np.int64)
    vs = rng.normal(size=n)
    # many small batches so states accumulate under a tiny budget
    chunk = 512
    batches = [
        Batch.from_pydict({"k": ks[i : i + chunk].tolist(),
                           "v": vs[i : i + chunk].tolist()})
        for i in range(0, n, chunk)
    ]
    MemManager.init(budget_bytes=8192)
    try:
        partial = HashAggExec(
            MemoryScanExec.single(batches),
            [(col(0), "k")],
            [(AggExpr("host_udaf", col(1), udaf="acc_sum"), "s")],
            "partial",
        )
        final = HashAggExec(
            partial, [(col(0), "k")],
            [(AggExpr("host_udaf", col(1), udaf="acc_sum"), "s")],
            "final",
        )
        out = final.collect().to_pandas().sort_values("k").reset_index(drop=True)
        import pandas as pd

        want = (
            pd.DataFrame({"k": ks, "v": vs}).groupby("k")["v"].sum()
            .reset_index().sort_values("k").reset_index(drop=True)
        )
        assert out["k"].tolist() == want["k"].tolist()
        for g, w in zip(out["s"], want["v"]):
            assert g == pytest.approx(w, rel=1e-9)
    finally:
        MemManager.init()


@pytest.fixture(params=["auto", "off"], ids=["hostfold", "devicefold"])
def dense_fold_substrate(request):
    """Run a dense-agg test under BOTH fold substrates. On the CPU CI
    backend AGG_DENSE_HOST_SCATTER=auto resolves to the host numpy
    bincount fold, which would leave the accelerator device-scatter path
    (_dense_update_jit dispatch + its deferred-flag protocol) with zero
    coverage — the 'off' pin keeps that path exercised here."""
    from auron_tpu.utils.config import AGG_DENSE_HOST_SCATTER, active_conf

    conf = active_conf()
    saved = conf.get(AGG_DENSE_HOST_SCATTER)
    conf.set(AGG_DENSE_HOST_SCATTER, request.param)
    try:
        yield request.param
    finally:
        conf.set(AGG_DENSE_HOST_SCATTER, saved)


def test_dense_agg_deferred_restart_no_double_fold(dense_fold_substrate):
    """Dense-table folds are deferred (flag read one batch late). A batch
    whose keys outgrow the anchored range must fold EXACTLY once after the
    drain+re-anchor — both mid-stream and when the growth lands on the
    last batch (resolved at end of stream). Regression: the q88-class last
    band was double-counted."""
    # min/max ride along so BOTH fold substrates (np.minimum/maximum.at
    # on the host, segment_min/max on device) face the restart protocol
    aggs = [
        (AggExpr("count_star", None), "c"), (AggExpr("sum", col(1)), "s"),
        (AggExpr("min", col(1)), "mn"), (AggExpr("max", col(1)), "mx"),
    ]

    def run(key_batches):
        batches = [
            Batch.from_pydict({"k": ks, "v": [float(k % 7) for k in ks]})
            for ks in key_batches
        ]
        agg = HashAggExec(
            MemoryScanExec.single(batches), [(col(0), "k")], aggs, "partial",
        )
        final = HashAggExec(agg, [(col(0), "k")], aggs, "final")
        return (final.collect().to_pandas()
                .sort_values("k").reset_index(drop=True))

    def want(key_batches):
        ks = [k for band in key_batches for k in band]
        return (
            pd.DataFrame({"k": ks, "v": [float(k % 7) for k in ks]})
            .groupby("k")
            .agg(c=("v", "size"), s=("v", "sum"), mn=("v", "min"),
                 mx=("v", "max"))
            .reset_index().sort_values("k").reset_index(drop=True)
        )

    for key_batches in (
        # growth on the LAST batch: its restart resolves at end of stream
        [[0, 0, 1], [1, 1], [100000, 100000]],
        # growth mid-stream: restart then more in-range batches
        [[5, 5], [900000], [5, 6], [900001]],
    ):
        out, exp = run(key_batches), want(key_batches)
        assert out["k"].tolist() == exp["k"].tolist()
        assert out["c"].tolist() == exp["c"].tolist()
        assert out["s"].tolist() == exp["s"].tolist()
        assert out["mn"].tolist() == exp["mn"].tolist()
        assert out["mx"].tolist() == exp["mx"].tolist()


def test_dense_agg_sentinel_key_extremes(dense_fold_substrate):
    """A key near the int64 extremes must trigger the dense table's
    re-anchor (then permanent fallback), never fold into a clamped slot:
    the fused guard compares against host-computed bounds instead of
    doing device int64 arithmetic that wraps."""
    big = (1 << 63) - 1
    agg = HashAggExec(
        MemoryScanExec.single([
            Batch.from_pydict({"k": [0, 1, 2, 2]}),
            Batch.from_pydict({"k": [big, 0]}),
        ]),
        [(col(0), "k")],
        [(AggExpr("count_star", None), "c")],
        "partial",
    )
    final = HashAggExec(
        agg, [(col(0), "k")], [(AggExpr("count_star", None), "c")], "final")
    out = (final.collect().to_pandas()
           .sort_values("k").reset_index(drop=True))
    assert out["k"].tolist() == [0, 1, 2, big]
    assert out["c"].tolist() == [2, 1, 2, 1]


def test_probe_scatter_k_deep_interleaved_misses():
    """Probe/scatter mirror of the dense k-deep test below: once a compact
    has produced an fp-sorted state, hit batches scatter straight into the
    state while miss batches resolve k batches LATE through the async
    window and re-enter the generic path with their selection narrowed to
    the miss rows. Interleaving known-key and new-band batches at several
    window depths, every row must still count exactly once vs pandas."""
    import pandas as pd

    from auron_tpu.utils.config import (
        AGG_INCREMENTAL_FINGERPRINT,
        AGG_INCREMENTAL_MERGEPATH,
        AGG_INCREMENTAL_PROBE,
        BATCH_SIZE,
        PARTIAL_AGG_SKIPPING_ENABLE,
        TRANSFER_WINDOW_DEPTH,
        Configuration,
        conf_scope,
    )

    rng = np.random.default_rng(4)
    key_batches = []
    # phase 1: enough distinct keys to cross the staging threshold (the
    # 1<<15 merge floor) so compact() builds the probe-able state
    pool = np.arange(40_000) * 1_000_003 + 7  # dense-ineligible spread
    for i in range(17):
        key_batches.append(pool[i * 2048:(i + 1) * 2048].tolist())
    # phase 2: interleave state hits with new-band misses so multiple
    # in-flight deferred folds keep resolving against a moving state
    for i in range(12):
        if i % 3 == 2:
            band = 900_000_000_000 + i * 10_000  # brand-new keys: misses
            key_batches.append((band + rng.integers(0, 200, 512)).tolist())
        else:
            key_batches.append(rng.choice(pool[:34_000], 512).tolist())
    all_k = [k for ks in key_batches for k in ks]
    want = (
        pd.DataFrame({"k": all_k, "v": [1.0] * len(all_k)})
        .groupby("k").agg(c=("v", "size"), s=("v", "sum")).reset_index()
        .sort_values("k").reset_index(drop=True)
    )

    aggs = [(AggExpr("count_star", None), "c"), (AggExpr("sum", col(1)), "s")]
    probed_depths = []
    for depth in (1, 3, 6):
        conf = (Configuration().set(TRANSFER_WINDOW_DEPTH, depth)
                .set(BATCH_SIZE, 2048)
                # incremental mechanisms pinned on (auto = accelerator-only)
                .set(AGG_INCREMENTAL_FINGERPRINT, "on")
                .set(AGG_INCREMENTAL_PROBE, "on")
                .set(AGG_INCREMENTAL_MERGEPATH, "on")
                # phase 1 is all-distinct by construction — the pass-through
                # heuristic would drain the state this test probes into
                .set(PARTIAL_AGG_SKIPPING_ENABLE, False))
        with conf_scope(conf):
            batches = [
                Batch.from_pydict({"k": ks, "v": [1.0] * len(ks)})
                for ks in key_batches
            ]
            agg = HashAggExec(
                MemoryScanExec.single(batches), [(col(0), "k")], aggs, "partial")
            ctx = ExecutionContext(conf=conf)
            mid = list(agg.execute(0, ctx))
            final = HashAggExec(
                MemoryScanExec.single(mid), [(col(0), "k")], aggs, "final")
            out = pd.concat(
                b.to_pandas() for b in final.execute(0, ExecutionContext(conf=conf))
            ).sort_values("k").reset_index(drop=True)
        assert out["k"].tolist() == want["k"].tolist(), f"depth={depth}"
        assert out["c"].tolist() == want["c"].tolist(), f"depth={depth}"
        assert out["s"].tolist() == [float(x) for x in want["s"]], f"depth={depth}"
        probed_depths.append(ctx.metrics.values.get("probe_hit_rows", 0))
    # the probe actually engaged (phase-2 hit batches scattered into state)
    assert all(p > 0 for p in probed_depths), probed_depths


def test_probe_scatter_all_agg_kinds_bit_identical():
    """Every probe-foldable aggregate kind through an ACTUALLY-probing
    stream (state built, then repeating-key batches scatter into it):
    sum/count/count_star/avg/min/max/first_ignores_null must come out
    bit-identical to the legacy path. Dyadic values keep float sums exact,
    so the scatter's summation order can't legally differ."""
    import pandas as pd

    from auron_tpu.utils.config import (
        AGG_INCREMENTAL_ENABLE,
        AGG_INCREMENTAL_FINGERPRINT,
        AGG_INCREMENTAL_MERGEPATH,
        AGG_INCREMENTAL_PROBE,
        BATCH_SIZE,
        PARTIAL_AGG_SKIPPING_ENABLE,
        Configuration,
        conf_scope,
    )

    rng = np.random.default_rng(8)
    pool = np.arange(36_000) * 1_000_003 + 13
    key_batches = [pool[i * 2048:(i + 1) * 2048].tolist() for i in range(17)]
    for i in range(8):
        key_batches.append(rng.choice(pool[:30_000], 512).tolist())
    val_batches = [
        (rng.integers(-(1 << 20), 1 << 20, len(ks)) / 1024.0).tolist()
        for ks in key_batches
    ]
    aggs = [
        (AggExpr("sum", col(1)), "s"), (AggExpr("count", col(1)), "c"),
        (AggExpr("count_star", None), "cs"), (AggExpr("avg", col(1)), "a"),
        (AggExpr("min", col(1)), "mn"), (AggExpr("max", col(1)), "mx"),
        (AggExpr("first_ignores_null", col(1)), "f"),
    ]

    def run(enable):
        mode = "on" if enable else "off"
        conf = (Configuration().set(BATCH_SIZE, 2048)
                .set(AGG_INCREMENTAL_ENABLE, enable)
                .set(AGG_INCREMENTAL_FINGERPRINT, mode)
                .set(AGG_INCREMENTAL_PROBE, mode)
                .set(AGG_INCREMENTAL_MERGEPATH, mode)
                .set(PARTIAL_AGG_SKIPPING_ENABLE, False))
        with conf_scope(conf):
            batches = [
                Batch.from_pydict({"k": ks, "v": vs})
                for ks, vs in zip(key_batches, val_batches)
            ]
            agg = HashAggExec(
                MemoryScanExec.single(batches), [(col(0), "k")], aggs, "partial")
            ctx = ExecutionContext(conf=conf)
            mid = list(agg.execute(0, ctx))
            final = HashAggExec(
                MemoryScanExec.single(mid), [(col(0), "k")], aggs, "final")
            out = pd.concat(
                b.to_pandas() for b in final.execute(0, ExecutionContext(conf=conf))
            ).sort_values("k").reset_index(drop=True)
        return out, ctx.metrics.values.get("probe_hit_rows", 0)

    inc, hits = run(True)
    leg, _ = run(False)
    assert hits > 0, "stream never probed — test shape regressed"
    assert len(inc) == len(leg)
    for c in inc.columns:
        for a, b in zip(inc[c], leg[c]):
            assert (pd.isna(a) and pd.isna(b)) or a == b, (c, a, b)


def test_dense_agg_k_deep_window_interleaved_restarts(dense_fold_substrate):
    """The deferred-fold window is now k batches deep (async flag
    harvests, runtime/transfer.py): interleaved out-of-range batches mean
    MULTIPLE in-flight folds can fail and each must re-fold exactly once
    after the drain+re-anchor — totals stay equal to pandas at every
    window depth."""
    import pandas as pd

    from auron_tpu.utils.config import TRANSFER_WINDOW_DEPTH, active_conf

    rng = __import__("numpy").random.default_rng(3)
    key_batches = []
    # alternate between three far-apart ranges so deferred folds keep
    # landing out-of-range mid-window
    for i in range(12):
        base = [0, 500_000, 2_000_000_000][i % 3]
        key_batches.append((base + rng.integers(0, 50, 40)).tolist())
    all_k = [k for ks in key_batches for k in ks]
    want = (
        pd.DataFrame({"k": all_k, "v": [1.0] * len(all_k)})
        .groupby("k").agg(c=("v", "size"), s=("v", "sum")).reset_index()
        .sort_values("k").reset_index(drop=True)
    )

    conf = active_conf()
    saved = conf.get(TRANSFER_WINDOW_DEPTH)
    try:
        for depth in (1, 3, 6):
            conf.set(TRANSFER_WINDOW_DEPTH, depth)
            batches = [
                Batch.from_pydict({"k": ks, "v": [1.0] * len(ks)})
                for ks in key_batches
            ]
            agg = HashAggExec(
                MemoryScanExec.single(batches),
                [(col(0), "k")],
                [(AggExpr("count_star", None), "c"),
                 (AggExpr("sum", col(1)), "s")],
                "partial",
            )
            final = HashAggExec(
                agg, [(col(0), "k")],
                [(AggExpr("count_star", None), "c"),
                 (AggExpr("sum", col(1)), "s")],
                "final",
            )
            out = (final.collect().to_pandas()
                   .sort_values("k").reset_index(drop=True))
            assert out["k"].tolist() == want["k"].tolist(), f"depth={depth}"
            assert out["c"].tolist() == want["c"].tolist(), f"depth={depth}"
            assert out["s"].tolist() == [float(x) for x in want["s"]], \
                f"depth={depth}"
    finally:
        conf.set(TRANSFER_WINDOW_DEPTH, saved)


def test_probe_scatter_spill_park_preserves_first_stream_order(monkeypatch):
    """A spill can park the state mid-window (probe goes un-ready while
    deferred miss batches are still in flight). The NEXT batch then stages
    generically right away — so the probe must drain its window first, or
    a key whose stream-FIRST occurrence sits in a pending miss batch would
    stage after a later batch's rows and `first` would pick the wrong
    value. Simulated by clearing the state's _fp_order right after the
    miss batch's fold (what a real cross-thread spill does to the probe's
    view), then feeding the same keys again with different values."""
    import pandas as pd

    from auron_tpu.exec import agg_exec as agg_mod
    from auron_tpu.utils.config import (
        AGG_INCREMENTAL_FINGERPRINT,
        AGG_INCREMENTAL_MERGEPATH,
        AGG_INCREMENTAL_PROBE,
        BATCH_SIZE,
        PARTIAL_AGG_SKIPPING_ENABLE,
        TRANSFER_WINDOW_DEPTH,
        Configuration,
        conf_scope,
    )

    pool = np.arange(40_000) * 1_000_003 + 7  # dense-ineligible spread
    frames = []
    # phase 1: cross the staging threshold so compact() builds the state
    for i in range(17):
        frames.append((pool[i * 2048:(i + 1) * 2048], 0.0))
    frames.append((pool[:512], 0.0))            # 18: hits — probe engaged
    band = 900_000_000_000 + np.arange(512)
    frames.append((band, 1.0))                  # 19: miss batch, defers
    frames.append((band, 2.0))                  # 20: post-park, same keys
    PARK_AFTER = 19

    calls = {"n": 0, "folded": {}}
    orig_fold = agg_mod._ProbeScatter.fold

    def fold_wrap(self, b):
        res = orig_fold(self, b)
        calls["n"] += 1
        calls["folded"][calls["n"]] = res[0]
        if calls["n"] == PARK_AFTER:
            with self.table._lock:
                st = self.table.state
                assert st is not None and getattr(st, "_fp_order", False), \
                    "test shape regressed: state not probe-able at the park point"
                st._fp_order = False  # what a spill does to the probe's view
        return res

    monkeypatch.setattr(agg_mod._ProbeScatter, "fold", fold_wrap)

    aggs = [(AggExpr("first", col(1)), "f"), (AggExpr("count_star", None), "c")]
    conf = (Configuration().set(TRANSFER_WINDOW_DEPTH, 6)
            .set(BATCH_SIZE, 2048)
            .set(AGG_INCREMENTAL_FINGERPRINT, "on")
            .set(AGG_INCREMENTAL_PROBE, "on")
            .set(AGG_INCREMENTAL_MERGEPATH, "on")
            .set(PARTIAL_AGG_SKIPPING_ENABLE, False))
    with conf_scope(conf):
        batches = [
            Batch.from_pydict({"k": ks.tolist(), "v": [v] * len(ks)})
            for ks, v in frames
        ]
        agg = HashAggExec(
            MemoryScanExec.single(batches), [(col(0), "k")], aggs, "partial")
        mid = list(agg.execute(0, ExecutionContext(conf=conf)))
        final = HashAggExec(
            MemoryScanExec.single(mid), [(col(0), "k")], aggs, "final")
        out = pd.concat(
            b.to_pandas() for b in final.execute(0, ExecutionContext(conf=conf))
        ).sort_values("k").reset_index(drop=True)

    assert calls["folded"][PARK_AFTER], "miss batch did not probe-fold"
    assert not calls["folded"][PARK_AFTER + 1], "park did not disengage probe"
    got_band = out[out["k"] >= 900_000_000_000]
    assert got_band["c"].tolist() == [2] * len(band)   # no row lost or doubled
    # stream-first value is the PENDING miss batch's 1.0, not the
    # post-park batch's 2.0
    assert got_band["f"].tolist() == [1.0] * len(band)


def test_deferred_partial_counts_k_deep_interleaved_mispredicts():
    """exec.agg.partial.defer: the PARTIAL generic path's (live count,
    group count) read rides the k-deep transfer window (mirroring the
    dense-flag deque of PR 2); interleaved selectivity jumps mean MULTIPLE
    in-flight batches can be truncated by an under-sized predicted bucket
    and each must recompute exactly once — counts stay exact vs pandas and
    vs the blocking protocol at every window depth."""
    import pandas as pd

    from auron_tpu.utils.config import (
        AGG_PARTIAL_DEFER, TRANSFER_WINDOW_DEPTH, active_conf,
    )

    rng = np.random.default_rng(17)
    key_batches = []
    for i in range(14):
        if i % 3 == 2:
            # dense batch right after sparse ones: the EWMA's bucket is
            # tiny, so this batch truncates and must repair mid-window
            ks = rng.integers(0, 40, 1200)
        else:
            ks = rng.integers(0, 40, 1200)
            ks[120:] = -1  # dead marker: filtered below
        key_batches.append(ks)
    frames = []
    schema = None
    for ks in key_batches:
        live = [int(k) if k >= 0 else None for k in ks]
        b = Batch.from_pydict({"k": live, "v": [1.0] * len(live)})
        schema = b.schema
        frames.append(b)

    # IsNotNull filter upstream keeps dead rows out; keys 0..39 force the
    # bool/dense-ineligible... (int key IS dense-eligible — widen the range)
    from auron_tpu.exec.basic import FilterExec
    from auron_tpu.exprs.ir import IsNotNull

    conf = active_conf()
    saved_depth = conf.get(TRANSFER_WINDOW_DEPTH)
    saved_defer = conf.get(AGG_PARTIAL_DEFER)

    def run(defer, depth):
        conf.set(TRANSFER_WINDOW_DEPTH, depth)
        conf.set(AGG_PARTIAL_DEFER, defer)
        # spread keys so the dense direct-address table refuses and the
        # GENERIC sort-segmentation path (the deferred read's home) runs
        from auron_tpu.exprs.ir import BinaryOp, Literal

        wide = BinaryOp("mul", col(0), Literal(1_000_003, T.INT64))
        scan = MemoryScanExec.single([Batch(b.schema, b.device, b.dicts) for b in frames])
        flt = FilterExec(scan, [IsNotNull(col(0))])
        p = HashAggExec(flt, [(wide, "k")],
                        [(AggExpr("count_star", None), "c"),
                         (AggExpr("sum", col(1)), "s")], "partial")
        f = HashAggExec(p, [(col(0), "k")],
                        [(AggExpr("count_star", None), "c"),
                         (AggExpr("sum", col(1)), "s")], "final")
        from auron_tpu.exec.base import ExecutionContext

        ctx = ExecutionContext()
        ctx.metrics.name = f.name
        out = f.collect(ctx=ctx).to_pandas().sort_values("k").reset_index(drop=True)
        return out, ctx.metrics.total("sel_mispredicts")

    all_k = [int(k) * 1_000_003 for ks in key_batches for k in ks if k >= 0]
    want = (
        pd.DataFrame({"k": all_k, "v": 1.0})
        .groupby("k").agg(c=("v", "size"), s=("v", "sum")).reset_index()
        .sort_values("k").reset_index(drop=True)
    )
    try:
        mispredicted = 0
        for depth in (1, 3, 6):
            got, mis = run("on", depth)
            mispredicted += mis
            assert got["k"].tolist() == want["k"].tolist(), f"depth={depth}"
            assert got["c"].tolist() == want["c"].tolist(), f"depth={depth}"
            assert got["s"].tolist() == [
                pytest.approx(float(x)) for x in want["s"]], f"depth={depth}"
        # teeth: the sparse->dense jumps actually exercised the repair
        assert mispredicted > 0
        off, _ = run("off", 3)
        assert off["c"].tolist() == want["c"].tolist()
    finally:
        conf.set(TRANSFER_WINDOW_DEPTH, saved_depth)
        conf.set(AGG_PARTIAL_DEFER, saved_defer)
